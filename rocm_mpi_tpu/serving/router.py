"""The fleet router: N independent `SimulationService` replicas behind
one front end (docs/SERVING.md "The fleet"; ROADMAP item 2).

Routing policy — compile state is the scarce resource, so affinity IS
the load-balancing policy:

  1. SESSION affinity: a sessioned request sticks to the replica that
     owns its session directory (resume reads replica-local state; a
     resume that landed elsewhere would silently recompute from
     scratch). Stickiness outranks the saturation bound.
  2. PROGRAM-CLASS affinity: a bin's traffic sticks to the replica
     that already compiled its program classes (`BinKey` → replica).
     First route wins and is journaled; every later request of the
     same bin follows it, so `compiles.steady_state == 0` holds PER
     REPLICA — spreading a bin across replicas would compile it N
     times and then recompile nowhere, which is worse than queueing.
  3. SPILLOVER: when the affine replica is saturated (its depth at
     the per-replica bound), non-sessioned traffic spills to the
     least-loaded healthy replica with room — deterministically, in
     (depth, id) order. When NO replica has room, the router rejects
     fast with the MERGED retry-after hint (the minimum over healthy
     replicas' throughput-derived hints: the earliest any of them
     frees a slot).

What the router NEVER does: hand a wall clock to a replica. Replica
queues run `wall_slo = False`; deadline expiry is decided by the
router's single clock (`RequestQueue.expire_overdue`) before each
drain — the GL08 divergence class, lifted fleet-wide (two replicas
disagreeing about "now" would terminate the same ticket twice, the
exact double-terminal the journal invariant forbids).

Every transition is journaled (serving/journal.py): submit at the
front door, route (and re-route) decisions, and each ticket's ONE
terminal state, harvested from replica queues at drain boundaries by
the router — the single journal writer. A replica killed mid-traffic
(the `replica-kill@step=K,rank=R` fault, a real SIGKILL, rc-75
preemption, or a watchdog/heartbeat verdict) triggers replay-based
reconciliation: the journal names every ticket whose LAST route hit
the dead replica with no terminal, and the router re-routes exactly
those. Side effects stay at-most-once because the only durable side
effect a replica makes — a session step save — is guarded by the
session layer's step manifests (a re-routed session resumes from the
last VALID saved step; a torn save is invisible).

`ElasticPolicy` is promoted to the fleet autoscaler: aggregate queue
depth grows the fleet by whole replicas (`replica_factory` is the
spawn), sustained idleness retires the highest-id replica (rc-75 is
the clean drain signal an out-of-process replica would exit with).
"""

from __future__ import annotations

import dataclasses
import time

from rocm_mpi_tpu.telemetry import tracing as _tracing

from rocm_mpi_tpu.serving import bins as _bins
from rocm_mpi_tpu.serving import journal as _journal
from rocm_mpi_tpu.serving import slo as _slo
from rocm_mpi_tpu.serving.queue import (
    DEFAULT_RETRY_AFTER_S,
    MAX_RETRY_AFTER_S,
    TERMINAL_STATES,
    Ticket,
)

DEFAULT_STALL_GRACE_S = 20.0


class Replica:
    """One fleet member: a `SimulationService` plus the router's view
    of its health. `alive=False` — killed/retired (its queue state is
    presumed lost; the journal is the record). `demoted=True` — up but
    untrusted (progress-stalled): no new routes, pending re-routed."""

    def __init__(self, rid: int, svc):
        self.id = int(rid)
        self.svc = svc
        self.alive = True
        self.demoted = False
        self.retiring = False
        self.verdict: str | None = None
        # The replica queue never owns a wall clock (module docstring).
        svc.queue.wall_slo = False

    @property
    def healthy(self) -> bool:
        return self.alive and not self.demoted and not self.retiring

    def depth(self) -> int:
        return self.svc.queue.depth() if self.alive else 0

    def row(self, steady_state: int) -> dict:
        """The replica's fleet-report row. For an in-process fleet a
        dead replica's counters are still readable (frozen at the
        kill); a real SIGKILL loses them — which is why the MERGED
        accounting comes from the journal, never from these rows."""
        return {
            "id": self.id,
            "alive": self.alive,
            "demoted": self.demoted,
            "verdict": self.verdict,
            "counters": self.svc.queue.counters(),
            "retries": int(self.svc.retries_total),
            "programs": len(self.svc._programs),
            "bins": len(self.svc._stats),
            "steady_state": int(steady_state),
        }


class _TicketRec:
    __slots__ = ("request", "ticket", "replica", "journaled")

    def __init__(self, request, ticket, replica):
        self.request = request
        self.ticket = ticket
        self.replica = replica
        self.journaled = False


class FleetTicket:
    """The caller's handle on a fleet submission. A re-route after a
    replica kill REPLACES the underlying queue ticket (the dead
    replica's ticket object died with its queue); this proxy always
    follows the record's CURRENT ticket, so `state`/`result()` survive
    reconciliation — the caller never learns their request moved."""

    __slots__ = ("_rec",)

    def __init__(self, rec: _TicketRec):
        self._rec = rec

    def __getattr__(self, name):
        return getattr(self._rec.ticket, name)

    def __repr__(self):
        t = self._rec.ticket
        return (f"FleetTicket({t.request.request_id!r}, "
                f"state={t.state!r}, replica={self._rec.replica})")


class FleetRouter:
    """The front end (module docstring). `replica_factory(rid)` builds
    one `SimulationService`; the router owns N of them, the ticket
    journal, and every wall-clock decision."""

    def __init__(self, replica_factory, n_replicas: int, *,
                 journal: _journal.TicketJournal,
                 max_depth_per_replica: int | None = None,
                 policy=None, max_replicas: int | None = None,
                 grow_queue_depth: int = 8, idle_retire_ticks: int = 3,
                 heartbeat_dirs: dict | None = None,
                 stall_grace_s: float = DEFAULT_STALL_GRACE_S):
        if int(n_replicas) < 1:
            raise ValueError(
                f"n_replicas must be >= 1, got {n_replicas}"
            )
        self._factory = replica_factory
        self.journal = journal
        self.max_depth_per_replica = (
            int(max_depth_per_replica)
            if max_depth_per_replica is not None else None
        )
        self.policy = policy
        self.max_replicas = (
            int(max_replicas) if max_replicas is not None
            else int(n_replicas)
        )
        self.grow_queue_depth = int(grow_queue_depth)
        self.idle_retire_ticks = int(idle_retire_ticks)
        self.heartbeat_dirs = dict(heartbeat_dirs or {})
        self.stall_grace_s = float(stall_grace_s)
        self.replicas: list[Replica] = []
        self._affinity: dict[str, int] = {}   # bin key_str -> replica
        self._sessions: dict[str, int] = {}   # session id -> replica
        self._tickets: dict[str, _TicketRec] = {}
        self._tick = 0
        self._idle_ticks = 0
        self._last_scale_tick: int | None = None
        self._hb_progress: dict[int, tuple] = {}  # rid -> (key, mono)
        self.router_rejected = 0
        self.preempted = False
        self.autoscale_events: list[dict] = []
        for rid in range(int(n_replicas)):
            self._spawn(rid)

    # ---- fleet membership ----------------------------------------------

    def _spawn(self, rid: int) -> Replica:
        rep = Replica(rid, self._factory(rid))
        self.replicas.append(rep)
        return rep

    def replica(self, rid: int) -> Replica:
        for rep in self.replicas:
            if rep.id == int(rid):
                return rep
        raise KeyError(f"no replica {rid}")

    def healthy_replicas(self) -> list[Replica]:
        return [r for r in self.replicas if r.healthy]

    def fleet_depth(self) -> int:
        return sum(r.depth() for r in self.healthy_replicas())

    # ---- routing --------------------------------------------------------

    def _bin_of(self, request) -> str | None:
        try:
            return _bins.bin_key(request).key_str()
        except ValueError:
            # The replica will fail the ticket at drain with the real
            # diagnostic; routing just needs SOME deterministic target.
            return None

    def _least_loaded(self, exclude=()) -> Replica | None:
        """Deterministic spill order: (depth, id) over the healthy
        set — same trace, same health history => same choice."""
        candidates = [
            r for r in self.healthy_replicas() if r.id not in exclude
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda r: (r.depth(), r.id))

    def retry_after_hint(self) -> float:
        """The MERGED hint: the earliest any healthy replica expects a
        slot to free — min over their throughput-derived hints,
        bounded exactly like the single-queue hint."""
        hints = [
            r.svc.queue.retry_after_hint()
            for r in self.healthy_replicas()
        ]
        if not hints:
            return DEFAULT_RETRY_AFTER_S
        return min(max(min(hints), 0.01), MAX_RETRY_AFTER_S)

    def submit(self, request) -> FleetTicket:
        """Route one request (module docstring policy). Always returns
        a ticket; a fleet-wide saturation reject is a terminally
        `rejected` ticket carrying the merged retry-after hint."""
        rid_req = request.request_id
        # The fleet front door mints the ROOT trace context (hop 0):
        # every replica-side span of this request descends from it, and
        # it rides Request.trace through the journal so a failover
        # re-route can continue the trace at hop 1.
        if request.trace is None:
            request = dataclasses.replace(
                request,
                trace=_tracing.to_wire(_tracing.mint(request.request_id)),
            )
        ctx = _tracing.from_wire(request.trace)
        bkey = self._bin_of(request)
        self.journal.record_submit(
            rid_req, session=request.session, bin_key=bkey,
        )
        target = None
        sticky = False
        if request.session and request.session in self._sessions:
            pin = self._sessions[request.session]
            try:
                rep = self.replica(pin)
            except KeyError:
                rep = None
            if rep is not None and rep.healthy:
                target, sticky = rep, True
            else:
                # The pinned replica is gone; the session's durable
                # state (step manifests) is what makes the re-route
                # at-most-once, not the pin.
                del self._sessions[request.session]
        if target is None and bkey is not None \
                and bkey in self._affinity:
            try:
                rep = self.replica(self._affinity[bkey])
            except KeyError:
                rep = None
            if rep is not None and rep.healthy:
                target = rep
            else:
                del self._affinity[bkey]
        if target is None:
            target = self._least_loaded()
        if target is None:
            raise RuntimeError("no healthy replica in the fleet")
        bound = self.max_depth_per_replica
        if bound is not None and not sticky \
                and target.depth() >= bound:
            spill = None
            for rep in sorted(self.healthy_replicas(),
                              key=lambda r: (r.depth(), r.id)):
                if rep.depth() < bound:
                    spill = rep
                    break
            if spill is None:
                hint = self.retry_after_hint()
                self.router_rejected += 1
                t = Ticket(request)
                t._terminal_fail(
                    "rejected",
                    f"fleet-full (every replica at max_depth "
                    f"{bound}); retry-after ~{hint:.2f}s",
                )
                self.journal.record_terminal(
                    rid_req, "rejected", replica=None,
                )
                _tracing.emit_tspan("trace.route", ctx,
                                    replica=None, state="rejected")
                rec = _TicketRec(request, t, -1)
                rec.journaled = True
                self._tickets[rid_req] = rec
                return FleetTicket(rec)
            # Spillover deliberately does NOT move the bin affinity:
            # the bin still prefers the replica holding its programs.
            target = spill
            spilled = True
        else:
            spilled = False
        ticket = target.svc.queue.submit(request)
        self.journal.record_route(rid_req, target.id)
        _tracing.emit_tspan(
            "trace.route", ctx, replica=target.id,
            **({"sticky": True} if sticky else {}),
            **({"spill": True} if spilled else {}),
        )
        rec = _TicketRec(request, ticket, target.id)
        self._tickets[rid_req] = rec
        if bkey is not None and bkey not in self._affinity:
            self._affinity[bkey] = target.id
        if request.session:
            self._sessions.setdefault(request.session, target.id)
        return FleetTicket(rec)

    def replica_map(self) -> dict[str, int]:
        """The bin -> replica affinity table (test surface: same trace
        => same map)."""
        return dict(self._affinity)

    # ---- failure, health, reconciliation --------------------------------

    def kill_replica(self, rid: int, verdict: str = "killed") -> None:
        """A replica died (SIGKILL / rc-75 / watchdog): mark it dead
        and reconcile from the journal."""
        rep = self.replica(rid)
        rep.alive = False
        rep.verdict = verdict
        self._reconcile(rid)

    def demote_replica(self, rid: int, verdict: str = "stalled") -> None:
        """A replica is up but not progressing: no new routes, pending
        re-routed. In-process the router simply stops draining it, so
        a demoted replica can never race its re-routed tickets (the
        router IS its drain loop)."""
        rep = self.replica(rid)
        rep.demoted = True
        rep.verdict = verdict
        self._reconcile(rid)

    def _reconcile(self, rid: int) -> None:
        """Replay the journal; every ticket whose LAST route hit `rid`
        with no terminal is re-routed to a healthy replica. Pure
        journal fold — running it again after the re-routes finds
        nothing open on `rid` (the idempotence the drill pins)."""
        for bkey in [k for k, v in self._affinity.items()
                     if v == int(rid)]:
            del self._affinity[bkey]
        for sess in [k for k, v in self._sessions.items()
                     if v == int(rid)]:
            del self._sessions[sess]
        state = _journal.replay(self.journal.segments())
        for rid_req in state.open_on(rid):
            rec = self._tickets.get(rid_req)
            if rec is None:
                continue
            # A session's tickets move TOGETHER: the first re-route
            # re-pins the session and the rest follow it — splitting
            # one tenant's in-order work across replicas would race
            # its own step manifests.
            target = None
            sess = rec.request.session
            if sess and sess in self._sessions:
                try:
                    rep = self.replica(self._sessions[sess])
                except KeyError:
                    rep = None
                if rep is not None and rep.healthy:
                    target = rep
            if target is None:
                target = self._least_loaded(exclude=(int(rid),))
            if target is None:
                raise RuntimeError(
                    "fleet exhausted: no healthy replica to re-route "
                    f"{rid_req!r} to"
                )
            # A re-route is a new HOP: continue the dead hop's trace
            # with hop+1 (parent = the dead hop's span) so the merged
            # timeline shows the failover as one causal chain, and the
            # new replica's queue adopts the bumped context.
            ctx = _tracing.from_wire(rec.request.trace)
            if ctx is None:
                ctx = _tracing.mint(rid_req)
            nctx = _tracing.next_hop(ctx)
            rec.request = dataclasses.replace(
                rec.request, trace=_tracing.to_wire(nctx)
            )
            rec.ticket = target.svc.queue.submit(rec.request)
            rec.replica = target.id
            rec.journaled = False
            self.journal.record_route(rid_req, target.id, reroute=True)
            _tracing.emit_tspan(
                "trace.route", nctx, replica=target.id, reroute=True,
                from_replica=int(rid),
            )
            if rec.request.session:
                self._sessions[rec.request.session] = target.id
            bkey = self._bin_of(rec.request)
            if bkey is not None and bkey not in self._affinity:
                self._affinity[bkey] = target.id

    def poll_health(self, now: float | None = None) -> None:
        """Read the PR-5 heartbeat sidecars for replicas that have
        them (`heartbeat_dirs[rid]`): a replica whose progress key has
        not advanced within `stall_grace_s` while it still owes work
        is demoted — the same stalled-vs-advancing signature the
        launcher watchdog uses, read by the router's single clock."""
        if not self.heartbeat_dirs:
            return
        from rocm_mpi_tpu.telemetry import health as _health

        now = time.monotonic() if now is None else now
        for rep in list(self.replicas):
            if not rep.healthy:
                continue
            directory = self.heartbeat_dirs.get(rep.id)
            if directory is None:
                continue
            beats, _skipped = _health.load_heartbeats(directory)
            if not beats:
                continue
            key = tuple(
                _health._progress_key(doc)
                for _rank, doc in sorted(beats.items())
            )
            prev = self._hb_progress.get(rep.id)
            if prev is None or prev[0] != key:
                self._hb_progress[rep.id] = (key, now)
                continue
            if rep.depth() > 0 and now - prev[1] > self.stall_grace_s:
                self.demote_replica(rep.id, verdict="progress-stalled")

    # ---- the autoscaler (ElasticPolicy, promoted) -----------------------

    def maybe_scale(self) -> bool:
        """Whole-replica elasticity on AGGREGATE queue depth: grow
        when the fleet backlog exceeds grow_queue_depth per live
        replica (and the policy + replica budget agree), retire the
        highest-id replica after sustained fleet idleness. rc-75 is
        the clean drain signal a real retired replica exits with."""
        policy = self.policy
        if policy is None:
            return False
        live = self.healthy_replicas()
        n_live = len(live)
        depth = self.fleet_depth()
        if depth >= self.grow_queue_depth * max(n_live, 1) \
                and policy.wants_grow(
                    n_live, self.max_replicas,
                    step=self._tick,
                    last_change_step=self._last_scale_tick,
                ):
            rid = max(r.id for r in self.replicas) + 1
            self._spawn(rid)
            self._last_scale_tick = self._tick
            self.autoscale_events.append({
                "event": "fleet.grow", "replica": rid,
                "replicas": n_live + 1, "depth": depth,
                "tick": self._tick,
            })
            return True
        min_live = max(1, int(getattr(policy, "min_ranks", 1)))
        if depth == 0 and self._idle_ticks >= self.idle_retire_ticks \
                and n_live > min_live:
            victim = max(live, key=lambda r: r.id)
            victim.retiring = True
            # Idle => its queue is empty; the journal proves it owes
            # nothing (reconcile finds no open tickets).
            self._reconcile(victim.id)
            victim.alive = False
            victim.verdict = "retired"
            self._last_scale_tick = self._tick
            self.autoscale_events.append({
                "event": "fleet.retire", "replica": victim.id,
                "replicas": n_live - 1, "signal": "rc-75",
                "tick": self._tick,
            })
            return True
        return False

    # ---- the drive loop -------------------------------------------------

    def _harvest(self, rep: Replica) -> None:
        """Journal each ticket that reached a terminal state on `rep`
        since the last harvest — the router is the single journal
        writer, and a drain boundary is the only place terminals
        appear (nothing is in flight between drains)."""
        for rid_req, rec in self._tickets.items():
            if rec.journaled or rec.replica != rep.id:
                continue
            state = rec.ticket.state
            if state in TERMINAL_STATES:
                self.journal.record_terminal(
                    rid_req, state, replica=rep.id,
                )
                rec.journaled = True

    def drive_once(self) -> int:
        """One fleet tick: consume due replica faults, poll health,
        autoscale, then expire-and-drain each healthy replica with the
        router's clock and harvest its terminals. Returns requests
        served this tick."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.resilience import faults

        self._tick += 1
        for rep in list(self.replicas):
            if not rep.alive:
                continue
            if faults.replica_fault("replica-kill", step=self._tick,
                                    replica=rep.id):
                self.kill_replica(rep.id, verdict="injected-kill")
                continue
            if faults.replica_fault("replica-stall", step=self._tick,
                                    replica=rep.id):
                self.demote_replica(rep.id, verdict="injected-stall")
        self.poll_health()
        self.maybe_scale()
        served = 0
        now = time.monotonic()
        for rep in self.healthy_replicas():
            # The single-writer clock: the ROUTER expires overdue
            # tickets; the replica's pop never consults wall time.
            rep.svc.queue.expire_overdue(now)
            n, _preempted = rep.svc.drain_once()
            served += n
            self._harvest(rep)
        depth = self.fleet_depth()
        self._idle_ticks = self._idle_ticks + 1 if depth == 0 else 0
        telemetry.gauge("fleet.replicas_live",
                        float(len(self.healthy_replicas())))
        telemetry.gauge("fleet.depth", float(depth))
        telemetry.gauge(
            "fleet.demoted",
            float(sum(1 for r in self.replicas
                      if r.alive and r.demoted)),
        )
        return served

    def drive(self, max_ticks: int = 1000) -> int:
        """Drain the fleet: tick until every healthy replica is empty
        (or a preemption notice stops the loop at a tick boundary —
        queued work stays queued and journaled, nothing is lost).
        Returns total served."""
        from rocm_mpi_tpu.resilience import preempt

        served = 0
        for _ in range(int(max_ticks)):
            if preempt.requested():
                self.preempted = True
                break
            served += self.drive_once()
            if self.fleet_depth() == 0:
                break
            delays = [
                d for d in (
                    r.svc.queue.next_ready_delay()
                    for r in self.healthy_replicas()
                ) if d
            ]
            if delays:
                time.sleep(min(min(delays), 0.25))
        return served

    # ---- accounting and the merged report -------------------------------

    def journal_state(self) -> _journal.JournalState:
        return _journal.replay(self.journal.segments())

    def check_accounting(self) -> list[str]:
        """THE fleet invariant at drain: every journaled ticket has
        exactly one terminal state fleet-wide, and every LIVE
        replica's own books balance. Dead replicas are exactly why
        the journal — not their counters — is the source of truth."""
        state = self.journal_state()
        problems = _journal.exactly_one_terminal(state)
        for rep in self.healthy_replicas():
            problems += [
                f"replica {rep.id}: {p}"
                for p in rep.svc.queue.check_accounting(in_flight=0)
            ]
        return problems

    def merged_counters(self) -> dict:
        """Fleet-wide terminal counters, JOURNAL-derived (a killed
        replica's queue counters died with it); retries are summed
        from the replicas that are still readable."""
        state = self.journal_state()
        term = state.terminal_counts()
        return {
            "submitted": len(state.tickets),
            "completed": term["done"],
            "failed": term["failed"],
            "rejected": term["rejected"],
            "expired": term["expired"],
            "quarantined": term["quarantined"],
            "retries": sum(
                int(r.svc.retries_total) for r in self.replicas
            ),
        }

    def report_doc(self, stream_paths=()) -> dict:
        """The merged fleet report (`rmt-fleet-report` v1): replica
        rows, the journal-derived merged SLO block (latencies from the
        telemetry streams when the run banked any), the journal
        accounting block, and the autoscale trail."""
        from rocm_mpi_tpu.telemetry import compiles

        state = self.journal_state()
        accounting_ok = not self.check_accounting()
        steady = compiles.snapshot()["steady_recompiles"]
        # In-process replicas share one compile tap; the per-replica
        # steady number is the shared window's count (0 stays 0 for
        # every replica — the pin the acceptance drill cares about).
        rows = [rep.row(steady) for rep in self.replicas]
        slo = _slo.slo_block(self.merged_counters(), stream_paths)
        return _journal.fleet_report_doc(
            rows, slo, state.counts(),
            accounting_ok=accounting_ok,
            autoscale=self.autoscale_events,
        )
