"""The serving request plane: requests, tickets, and the async queue
(docs/SERVING.md "Request schema").

Stdlib-at-import by design: the telemetry schema gate
(`telemetry regress --check-schema`) validates archived request sidecars
through `validate_request_record` without importing jax, exactly as
`parallel/wire.py` keeps its mode registry importable for the read side.

A `Request` is everything needed to reproduce one simulation
standalone — workload, exact space shape, dtype, physics constants,
step count, variant/wire knobs — plus the serving-only fields: a
request id, an IC scale (the per-lane variation knob: lane state is
``ic_scale ×`` the workload's standard initial condition), and an
optional `session` id for checkpoint multiplexing (the service saves
the final state under ``sessions/<session>/`` through the PR-6 manifest
machinery; a later request with `resume=True` continues from the latest
valid saved step). Everything that affects the COMPILED program is a
bin-key field (serving/bins.py); everything per-lane is traced data.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

REQUEST_SCHEMA = "rmt-serve-request"
REQUEST_VERSION = 1

WORKLOADS = ("diffusion", "wave", "swe")
REQUEST_DTYPES = ("f32", "f64", "bf16")

# Queued -> running -> done|failed; requeued is the preemption exit
# (docs/SERVING.md "Preemption"): the request never started, the ticket
# is parked for the next service instance.
TICKET_STATES = ("queued", "running", "done", "failed", "requeued")


@dataclasses.dataclass(frozen=True)
class Request:
    """One simulation request (docs/SERVING.md has the field table)."""

    request_id: str
    workload: str = "diffusion"
    global_shape: tuple[int, ...] = (64, 64)
    dtype: str = "f32"
    nt: int = 64
    physics: tuple[tuple[str, float], ...] = ()
    variant: str = "shard"
    wire_mode: str = "f32"
    ic_scale: float = 1.0
    session: str | None = None
    resume: bool = False

    def __post_init__(self):
        if not self.request_id or not isinstance(self.request_id, str):
            raise ValueError("request_id must be a non-empty string")
        if self.workload not in WORKLOADS:
            raise ValueError(
                f"workload must be one of {WORKLOADS}, got {self.workload!r}"
            )
        shape = tuple(int(n) for n in self.global_shape)
        if len(shape) < 1 or any(n < 4 for n in shape):
            raise ValueError(
                f"global_shape must have every axis >= 4, got {shape}"
            )
        object.__setattr__(self, "global_shape", shape)
        if self.dtype not in REQUEST_DTYPES:
            raise ValueError(
                f"dtype must be one of {REQUEST_DTYPES}, got {self.dtype!r}"
            )
        if int(self.nt) < 1:
            raise ValueError(f"nt must be >= 1, got {self.nt}")
        object.__setattr__(self, "nt", int(self.nt))
        phys = tuple(
            (str(k), float(v)) for k, v in tuple(self.physics)
        )
        object.__setattr__(self, "physics", phys)
        if self.resume and not self.session:
            raise ValueError("resume=True needs a session id")

    @property
    def physics_dict(self) -> dict:
        return dict(self.physics)


def request_to_record(req: Request) -> dict:
    """The sidecar line (`serve-requests.jsonl`): schema-stamped, every
    field JSON-plain — `telemetry regress --check-schema` validates the
    archived trace with `validate_request_record`."""
    return {
        "schema": REQUEST_SCHEMA,
        "kind": "serve-request",
        "v": REQUEST_VERSION,
        # Record wall STAMP (the `t` field every telemetry record
        # carries), not an interval measurement — nothing to sync.
        # graftlint: disable-next=GL06
        "t": time.time(),
        "request_id": req.request_id,
        "workload": req.workload,
        "global_shape": list(req.global_shape),
        "dtype": req.dtype,
        "nt": req.nt,
        "physics": {k: v for k, v in req.physics},
        "variant": req.variant,
        "wire_mode": req.wire_mode,
        "ic_scale": req.ic_scale,
        "session": req.session,
        "resume": bool(req.resume),
    }


def request_from_record(doc: dict) -> Request:
    problems = validate_request_record(doc)
    if problems:
        raise ValueError(
            "bad serve-request record: " + "; ".join(problems)
        )
    return Request(
        request_id=doc["request_id"],
        workload=doc["workload"],
        global_shape=tuple(doc["global_shape"]),
        dtype=doc["dtype"],
        nt=doc["nt"],
        physics=tuple(sorted(doc.get("physics", {}).items())),
        variant=doc.get("variant", "shard"),
        wire_mode=doc.get("wire_mode", "f32"),
        ic_scale=float(doc.get("ic_scale", 1.0)),
        session=doc.get("session"),
        resume=bool(doc.get("resume", False)),
    )


def validate_request_record(doc: dict) -> list[str]:
    """Problem strings for a serve-request sidecar record (stdlib —
    shared with telemetry.regress `--check-schema`)."""
    problems: list[str] = []
    if doc.get("schema") != REQUEST_SCHEMA:
        problems.append(f"schema {doc.get('schema')!r} != {REQUEST_SCHEMA}")
    if not isinstance(doc.get("request_id"), str) or not doc.get("request_id"):
        problems.append("missing request_id")
    if doc.get("workload") not in WORKLOADS:
        problems.append(f"unknown workload {doc.get('workload')!r}")
    shape = doc.get("global_shape")
    if not isinstance(shape, list) or not shape or not all(
        isinstance(n, int) and n >= 4 for n in shape
    ):
        problems.append(f"bad global_shape {shape!r}")
    if doc.get("dtype") not in REQUEST_DTYPES:
        problems.append(f"unknown dtype {doc.get('dtype')!r}")
    nt = doc.get("nt")
    if not isinstance(nt, int) or nt < 1:
        problems.append(f"bad nt {nt!r}")
    phys = doc.get("physics", {})
    if not isinstance(phys, dict) or not all(
        isinstance(k, str) and isinstance(v, (int, float))
        and not isinstance(v, bool) for k, v in phys.items()
    ):
        problems.append("physics must be {name: number}")
    if doc.get("resume") and not doc.get("session"):
        problems.append("resume without a session id")
    return problems


def load_trace(path) -> list[Request]:
    """Parse a serve-requests.jsonl trace file into Requests (blank
    lines skipped; a malformed line raises — a trace is an input, not a
    telemetry stream tolerating torn tails)."""
    out: list[Request] = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: bad JSON ({e})") from None
            out.append(request_from_record(doc))
    return out


class Ticket:
    """One queued request's handle: thread-safe state + a waitable
    result. The service resolves it (`_resolve`/`_fail`) when the
    request's batch completes; `result(timeout)` blocks the submitter."""

    def __init__(self, request: Request):
        self.request = request
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._state = "queued"
        self._result = None
        self._error: str | None = None
        self.steps_run = 0  # actually-advanced steps (resume-aware)
        self.start_step = 0  # resume start (session restore)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _mark(self, state: str) -> None:
        if state not in TICKET_STATES:
            raise ValueError(f"unknown ticket state {state!r}")
        with self._lock:
            self._state = state
        if state == "requeued":
            # Wake waiters promptly: a preempted request must not block
            # its submitter until timeout (result() returns None).
            self._event.set()
        elif state == "running":
            # A requeued ticket re-popped by the next drain is live
            # again — re-arm the wait for its real resolution.
            self._event.clear()

    def _resolve(self, result) -> None:
        with self._lock:
            self._state = "done"
            self._result = result
        self._event.set()

    def _fail(self, error: str) -> None:
        with self._lock:
            self._state = "failed"
            self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> str | None:
        with self._lock:
            return self._error

    def result(self, timeout: float | None = None):
        """Block until resolved; raises RuntimeError on a failed
        request, TimeoutError when the wait expires, and returns None
        promptly for a requeued (preempted) request — the caller
        re-submits (or waits for the next service to drain it)."""
        if not self._event.wait(timeout):
            if self.state == "requeued":
                return None
            raise TimeoutError(
                f"request {self.request.request_id} not served in "
                f"{timeout}s (state {self.state})"
            )
        with self._lock:
            if self._state == "failed":
                raise RuntimeError(
                    f"request {self.request.request_id} failed: "
                    f"{self._error}"
                )
            if self._state == "requeued":
                return None
            return self._result


class RequestQueue:
    """Thread-safe FIFO of tickets with counters for the telemetry
    plane (submitted/completed/requeued feed the monitor's SERVE badge,
    docs/TELEMETRY.md). `submit` is the producer side; the service's
    drain loop is the consumer (`pop_pending`); `requeue` parks tickets
    back at the FRONT (preempted work outranks new arrivals)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: list[Ticket] = []
        self._closed = False
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.requeued = 0

    def submit(self, request: Request) -> Ticket:
        t = Ticket(request)
        with self._lock:
            if self._closed:
                raise RuntimeError("queue is closed")
            self._pending.append(t)
            self.submitted += 1
        return t

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def pop_pending(self, max_n: int | None = None) -> list[Ticket]:
        with self._lock:
            n = len(self._pending) if max_n is None else min(
                max_n, len(self._pending)
            )
            out, self._pending = self._pending[:n], self._pending[n:]
        for t in out:
            t._mark("running")
        return out

    def requeue(self, tickets) -> None:
        ts = list(tickets)
        for t in ts:
            t._mark("requeued")
        with self._lock:
            self._pending = ts + self._pending
            self.requeued += len(ts)

    def note_completed(self, n: int = 1, failed: int = 0) -> None:
        with self._lock:
            self.completed += n
            self.failed += failed

    def close(self) -> None:
        with self._lock:
            self._closed = True

    def counters(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "requeued": self.requeued,
                "depth": len(self._pending),
            }
