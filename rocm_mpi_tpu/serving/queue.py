"""The serving request plane: requests, tickets, and the async queue
(docs/SERVING.md "Request schema" and "SLOs and admission").

Stdlib-at-import by design: the telemetry schema gate
(`telemetry regress --check-schema`) validates archived request and
quarantine sidecars through `validate_request_record` /
`validate_quarantine_record` without importing jax, exactly as
`parallel/wire.py` keeps its mode registry importable for the read side.

A `Request` is everything needed to reproduce one simulation
standalone — workload, exact space shape, dtype, physics constants,
step count, variant/wire knobs — plus the serving-only fields: a
request id, an IC scale (the per-lane variation knob: lane state is
``ic_scale ×`` the workload's standard initial condition), an
optional `session` id for checkpoint multiplexing (the service saves
the final state under ``sessions/<session>/`` through the PR-6 manifest
machinery; a later request with `resume=True` continues from the latest
valid saved step), and an optional `deadline_s` TTL (v2): a PENDING
ticket older than its deadline fails with `deadline-exceeded` at pop
time instead of occupying a lane — an in-flight lane always finishes
its batch. Everything that affects the COMPILED program is a bin-key
field (serving/bins.py); everything per-lane is traced data.

Admission control (docs/SERVING.md "SLOs and admission"): a
`RequestQueue(max_depth=)` rejects over-depth submits FAST — the
returned ticket is terminally `rejected` with a retry-after hint
derived from the observed batch throughput — never silently dropped.
Terminal accounting is an invariant: every submitted ticket ends in
exactly one of {done, failed, rejected, expired, quarantined}
(`check_accounting`; the service asserts it at drain time).
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time

from rocm_mpi_tpu.telemetry import tracing as _tracing

REQUEST_SCHEMA = "rmt-serve-request"
# v2: the optional `deadline_s` TTL joined the schema (v1 records
# without it stay valid — the field is optional by construction).
# v3: the optional `trace` context dict joined (telemetry/tracing.py
# wire shape) so a request's trace survives the journal and a fleet
# re-route; v1/v2 records without it stay valid.
REQUEST_VERSION = 3

QUARANTINE_SCHEMA = "rmt-serve-quarantine"
QUARANTINE_VERSION = 1

WORKLOADS = ("diffusion", "wave", "swe")
REQUEST_DTYPES = ("f32", "f64", "bf16")

# Queued -> running -> one of the TERMINAL_STATES; requeued is the
# non-terminal park (preemption, or a retry-budget requeue) — the
# ticket re-enters the queue and is popped again (docs/SERVING.md
# "Preemption" and "SLOs and admission"). Terminal outcomes:
#   done         served; result available
#   failed       a per-request error (bad physics, bad session) — never
#                retried: the request itself is wrong
#   rejected     admission control said no (queue-full, circuit-open) —
#                the submitter retries later
#   expired      the deadline passed while the ticket was still pending
#   quarantined  the retry budget is exhausted (poison request): the
#                full record is banked to quarantine.jsonl and the
#                ticket is never requeued again
TICKET_STATES = ("queued", "running", "done", "failed", "requeued",
                 "rejected", "expired", "quarantined")
TERMINAL_STATES = ("done", "failed", "rejected", "expired", "quarantined")

# Retry-after fallback when no batch has completed yet (no throughput
# observation to derive a hint from).
DEFAULT_RETRY_AFTER_S = 1.0
# Retry-after ceiling: the backlog÷rate derivation over a sparse or
# long-spanning completion window can extrapolate to near-infinity
# ("come back in 4 hours" is a lie about a queue that drains in
# seconds once live) — every hint is clamped here.
MAX_RETRY_AFTER_S = 60.0
# Throughput-window staleness horizon: completion marks older than
# this say nothing about CURRENT throughput (the post-flood idle
# edge) — a stale window falls back to the default, never
# extrapolates.
RETRY_WINDOW_STALE_S = 60.0


@dataclasses.dataclass(frozen=True)
class Request:
    """One simulation request (docs/SERVING.md has the field table)."""

    request_id: str
    workload: str = "diffusion"
    global_shape: tuple[int, ...] = (64, 64)
    dtype: str = "f32"
    nt: int = 64
    physics: tuple[tuple[str, float], ...] = ()
    variant: str = "shard"
    wire_mode: str = "f32"
    ic_scale: float = 1.0
    session: str | None = None
    resume: bool = False
    deadline_s: float | None = None
    # Request-scoped trace context (telemetry/tracing.py wire shape,
    # v3): None = mint a fresh root at submit; a dict = the request is
    # continuing an existing trace (a fleet re-route carries the dead
    # hop's context forward with hop+1).
    trace: dict | None = None

    def __post_init__(self):
        if not self.request_id or not isinstance(self.request_id, str):
            raise ValueError("request_id must be a non-empty string")
        if self.workload not in WORKLOADS:
            raise ValueError(
                f"workload must be one of {WORKLOADS}, got {self.workload!r}"
            )
        shape = tuple(int(n) for n in self.global_shape)
        if len(shape) < 1 or any(n < 4 for n in shape):
            raise ValueError(
                f"global_shape must have every axis >= 4, got {shape}"
            )
        object.__setattr__(self, "global_shape", shape)
        if self.dtype not in REQUEST_DTYPES:
            raise ValueError(
                f"dtype must be one of {REQUEST_DTYPES}, got {self.dtype!r}"
            )
        if int(self.nt) < 1:
            raise ValueError(f"nt must be >= 1, got {self.nt}")
        object.__setattr__(self, "nt", int(self.nt))
        phys = tuple(
            (str(k), float(v)) for k, v in tuple(self.physics)
        )
        object.__setattr__(self, "physics", phys)
        if self.resume and not self.session:
            raise ValueError("resume=True needs a session id")
        if self.deadline_s is not None:
            d = float(self.deadline_s)
            if not math.isfinite(d) or d <= 0:
                raise ValueError(
                    f"deadline_s must be a finite positive number of "
                    f"seconds, got {self.deadline_s!r}"
                )
            object.__setattr__(self, "deadline_s", d)
        if self.trace is not None:
            problems = _tracing.validate_wire(self.trace)
            if problems:
                raise ValueError(
                    "bad trace context: " + "; ".join(problems)
                )
            object.__setattr__(self, "trace", dict(self.trace))

    @property
    def physics_dict(self) -> dict:
        return dict(self.physics)


def request_to_record(req: Request) -> dict:
    """The sidecar line (`serve-requests.jsonl`): schema-stamped, every
    field JSON-plain — `telemetry regress --check-schema` validates the
    archived trace with `validate_request_record`."""
    return {
        "schema": REQUEST_SCHEMA,
        "kind": "serve-request",
        "v": REQUEST_VERSION,
        # Record wall STAMP (the `t` field every telemetry record
        # carries), not an interval measurement — nothing to sync.
        # graftlint: disable-next=GL06
        "t": time.time(),
        "request_id": req.request_id,
        "workload": req.workload,
        "global_shape": list(req.global_shape),
        "dtype": req.dtype,
        "nt": req.nt,
        "physics": {k: v for k, v in req.physics},
        "variant": req.variant,
        "wire_mode": req.wire_mode,
        "ic_scale": req.ic_scale,
        "session": req.session,
        "resume": bool(req.resume),
        "deadline_s": req.deadline_s,
        **({"trace": dict(req.trace)} if req.trace is not None else {}),
    }


def request_from_record(doc: dict) -> Request:
    problems = validate_request_record(doc)
    if problems:
        raise ValueError(
            "bad serve-request record: " + "; ".join(problems)
        )
    return Request(
        request_id=doc["request_id"],
        workload=doc["workload"],
        global_shape=tuple(doc["global_shape"]),
        dtype=doc["dtype"],
        nt=doc["nt"],
        physics=tuple(sorted(doc.get("physics", {}).items())),
        variant=doc.get("variant", "shard"),
        wire_mode=doc.get("wire_mode", "f32"),
        ic_scale=float(doc.get("ic_scale", 1.0)),
        session=doc.get("session"),
        resume=bool(doc.get("resume", False)),
        deadline_s=doc.get("deadline_s"),
        trace=doc.get("trace"),
    )


def validate_request_record(doc: dict) -> list[str]:
    """Problem strings for a serve-request sidecar record (stdlib —
    shared with telemetry.regress `--check-schema`)."""
    problems: list[str] = []
    if doc.get("schema") != REQUEST_SCHEMA:
        problems.append(f"schema {doc.get('schema')!r} != {REQUEST_SCHEMA}")
    if not isinstance(doc.get("request_id"), str) or not doc.get("request_id"):
        problems.append("missing request_id")
    if doc.get("workload") not in WORKLOADS:
        problems.append(f"unknown workload {doc.get('workload')!r}")
    shape = doc.get("global_shape")
    if not isinstance(shape, list) or not shape or not all(
        isinstance(n, int) and n >= 4 for n in shape
    ):
        problems.append(f"bad global_shape {shape!r}")
    if doc.get("dtype") not in REQUEST_DTYPES:
        problems.append(f"unknown dtype {doc.get('dtype')!r}")
    nt = doc.get("nt")
    if not isinstance(nt, int) or nt < 1:
        problems.append(f"bad nt {nt!r}")
    phys = doc.get("physics", {})
    if not isinstance(phys, dict) or not all(
        isinstance(k, str) and isinstance(v, (int, float))
        and not isinstance(v, bool) for k, v in phys.items()
    ):
        problems.append("physics must be {name: number}")
    if doc.get("resume") and not doc.get("session"):
        problems.append("resume without a session id")
    ddl = doc.get("deadline_s")
    if ddl is not None and (
        not isinstance(ddl, (int, float)) or isinstance(ddl, bool)
        or not math.isfinite(ddl) or ddl <= 0
    ):
        problems.append(f"bad deadline_s {ddl!r} (want a positive number)")
    if doc.get("trace") is not None:
        problems += _tracing.validate_wire(doc["trace"])
    return problems


def load_trace(path) -> list[Request]:
    """Parse a serve-requests.jsonl trace file into Requests (blank
    lines skipped; a malformed line raises — a trace is an input, not a
    telemetry stream tolerating torn tails)."""
    out: list[Request] = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: bad JSON ({e})") from None
            out.append(request_from_record(doc))
    return out


# ---------------------------------------------------------------------------
# Quarantine sidecar (docs/SERVING.md "SLOs and admission")
# ---------------------------------------------------------------------------


def quarantine_record(req: Request, error: str, retries: int) -> dict:
    """One quarantine.jsonl line: the FULL request record rides inside
    so the poison request can be reproduced offline exactly as
    submitted, plus the failure it kept hitting and the retries it
    burned. Schema-checked by `telemetry regress --check-schema`."""
    return {
        "schema": QUARANTINE_SCHEMA,
        "kind": "quarantine",
        "v": QUARANTINE_VERSION,
        # Record wall STAMP (the `t` field every telemetry record
        # carries), not an interval measurement — nothing to sync.
        # graftlint: disable-next=GL06
        "t": time.time(),
        "request_id": req.request_id,
        "error": str(error),
        "retries": int(retries),
        "request": request_to_record(req),
    }


def validate_quarantine_record(doc: dict) -> list[str]:
    """Problem strings for a quarantine.jsonl record (stdlib; shared
    with telemetry.regress --check-schema)."""
    problems: list[str] = []
    if doc.get("schema") != QUARANTINE_SCHEMA:
        problems.append(
            f"schema {doc.get('schema')!r} != {QUARANTINE_SCHEMA}"
        )
    if not isinstance(doc.get("error"), str) or not doc.get("error"):
        problems.append("quarantine record missing error")
    retries = doc.get("retries")
    if not isinstance(retries, int) or retries < 0:
        problems.append(f"bad retries {retries!r}")
    req = doc.get("request")
    if not isinstance(req, dict):
        problems.append("quarantine record missing the full request")
    else:
        problems += [f"request.{p}" for p in validate_request_record(req)]
    return problems


def append_quarantine(path, doc: dict) -> None:
    """Append one quarantine record. APPEND-ONLY on purpose (GL09's
    other blessed discipline): the sidecar is an incident ledger an
    out-of-process reader may tail while the service is live — every
    complete line is valid, a torn final line is droppable, and nothing
    already banked is ever rewritten."""
    problems = validate_quarantine_record(doc)
    if problems:
        raise ValueError("bad quarantine record: " + "; ".join(problems))
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(doc, sort_keys=True) + "\n")


def load_quarantine(path) -> list[dict]:
    """Read a quarantine.jsonl ledger (torn final line tolerated — it
    is a live-appended telemetry stream, unlike a request trace)."""
    out: list[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail
    return out


# ---------------------------------------------------------------------------
# Tickets
# ---------------------------------------------------------------------------


class Ticket:
    """One queued request's handle: thread-safe state + a waitable
    result. The service resolves it (`_resolve`/`_fail`/...) when the
    request's batch completes; `result(timeout)` blocks the submitter.

    Serving-plane bookkeeping (docs/SERVING.md "SLOs and admission"):
    `ordinal` is the 1-based submission number (the fault grammar's
    `lane-nan@request=N` key), `submitted_mono` anchors the deadline
    and the latency SLO, `retries`/`not_before` drive the bounded
    exponential-backoff retry budget."""

    def __init__(self, request: Request):
        self.request = request
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._state = "queued"
        self._result = None
        self._error: str | None = None
        self.steps_run = 0  # actually-advanced steps (resume-aware)
        self.start_step = 0  # resume start (session restore)
        self.ordinal = 0  # 1-based submission number (queue-assigned)
        self.submitted_mono = time.monotonic()
        self.retries = 0  # batch-level/numerical retry count
        self.not_before = 0.0  # backoff eligibility (monotonic)
        # True while parked by a RETRY requeue (wake=False): the live
        # service still owns the ticket, so result() must keep the
        # submitter waiting — None is the PREEMPTION contract only.
        self._retry_park = False
        # Request-scoped tracing (telemetry/tracing.py): the context
        # this ticket runs under (adopted from Request.trace or minted
        # at submit) and the telescoping latency-decomposition state —
        # `decomp` accumulates per-stage seconds, `_t_mark` is the last
        # charged instant, `backoff_pending` is scheduled retry delay
        # not yet charged (split out of the next queue_wait interval).
        self.trace: _tracing.TraceContext | None = None
        self.decomp: dict[str, float] = {}
        self.backoff_pending = 0.0
        self._t_mark = self.submitted_mono

    def trace_mark(self, stage: str, now: float) -> None:
        """Charge the interval since the previous mark to `stage`
        (telemetry/tracing.py DECOMP_STAGES). The marks telescope —
        every interval of the ticket's life is charged to exactly one
        stage — so the stages sum to the terminal latency by
        construction, across any number of retries. A queue_wait
        interval is split against scheduled retry backoff first: the
        backoff window is deliberate delay, not queue pressure."""
        d = now - self._t_mark
        if d < 0.0:
            d = 0.0
        if stage == "queue_wait" and self.backoff_pending > 0.0:
            b = min(d, self.backoff_pending)
            self.decomp["backoff"] = self.decomp.get("backoff", 0.0) + b
            self.backoff_pending = 0.0
            d -= b
        self.decomp[stage] = self.decomp.get(stage, 0.0) + d
        self._t_mark = now

    def decomp_doc(self) -> dict:
        """The per-request decomposition block the done event carries
        (rounded like latency_s; validated by
        tracing.validate_decomposition)."""
        return {k: round(v, 6) for k, v in self.decomp.items()}

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _mark(self, state: str, wake: bool = True) -> None:
        if state not in TICKET_STATES:
            raise ValueError(f"unknown ticket state {state!r}")
        with self._lock:
            self._state = state
        if state == "requeued":
            # Wake waiters promptly on a PREEMPTION requeue: the
            # request must not block its submitter until timeout
            # (result() returns None). A retry-budget requeue parks
            # with wake=False — the submitter keeps waiting for the
            # retried batch's real resolution.
            self._retry_park = not wake
            if wake:
                self._event.set()
        elif state == "running":
            # A requeued ticket re-popped by the next drain is live
            # again — re-arm the wait for its real resolution.
            self._retry_park = False
            self._event.clear()

    def _resolve(self, result) -> None:
        with self._lock:
            self._state = "done"
            self._result = result
        self._event.set()

    def _terminal_fail(self, state: str, error: str) -> None:
        if state not in TERMINAL_STATES or state == "done":
            raise ValueError(f"not a failure terminal state: {state!r}")
        with self._lock:
            self._state = state
            self._error = error
        self._event.set()

    def _fail(self, error: str) -> None:
        self._terminal_fail("failed", error)

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> str | None:
        with self._lock:
            return self._error

    def age_s(self, now: float | None = None) -> float:
        """Seconds since submission (monotonic)."""
        return (time.monotonic() if now is None else now) \
            - self.submitted_mono

    def result(self, timeout: float | None = None):
        """Block until resolved; raises RuntimeError on any failure
        terminal state (failed / rejected / expired / quarantined —
        `state` and `error` say which), TimeoutError when the wait
        expires, and returns None promptly for a requeued (preempted)
        request — the caller re-submits (or waits for the next service
        to drain it). A RETRY-parked ticket is still owned by the live
        service: a timeout during its backoff window raises
        TimeoutError like any other in-progress wait — returning the
        preemption None here would invite a duplicate re-submit of a
        request that is about to be retried."""
        if not self._event.wait(timeout):
            if self.state == "requeued" and not self._retry_park:
                return None
            raise TimeoutError(
                f"request {self.request.request_id} not served in "
                f"{timeout}s (state {self.state})"
            )
        with self._lock:
            if self._state in TERMINAL_STATES and self._state != "done":
                raise RuntimeError(
                    f"request {self.request.request_id} "
                    f"{self._state}: {self._error}"
                )
            if self._state == "requeued":
                return None
            return self._result


class RequestQueue:
    """Thread-safe FIFO of tickets with counters for the telemetry
    plane (submitted/completed/… feed the monitor's SERVE badge,
    docs/TELEMETRY.md). `submit` is the producer side; the service's
    drain loop is the consumer (`pop_pending`); `requeue` parks tickets
    back at the FRONT (preempted/retried work outranks new arrivals),
    order-pinned by submission ordinal so any sequence of requeues
    preserves the tickets' original relative order.

    `max_depth` is the admission bound (docs/SERVING.md "SLOs and
    admission"): an over-depth submit is rejected FAST — the returned
    ticket is terminally `rejected` with a retry-after hint derived
    from the observed batch throughput — never silently dropped.

    `wall_slo` gates the wall-clock-dependent decisions (deadline
    expiry, retry backoff). A multi-controller service turns it off:
    rank-local clocks diverge, and a ticket expiring on one rank but
    not another would plan divergent batches — exactly the GL08
    collective-divergence hazard. Depth-based admission stays on
    everywhere (depth is deterministic)."""

    def __init__(self, max_depth: int | None = None):
        if max_depth is not None and int(max_depth) < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self._lock = threading.Lock()
        self._front: list[Ticket] = []  # requeued; popped before _pending
        self._pending: list[Ticket] = []
        self._closed = False
        self.max_depth = int(max_depth) if max_depth is not None else None
        self.wall_slo = True
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.requeued = 0
        self.rejected = 0
        # Submit-time slice of `rejected` (queue-full): the service's
        # flight-counter sync reads it apart from the circuit-open
        # rejections it already counted itself.
        self.rejected_at_submit = 0
        self.expired = 0
        self.quarantined = 0
        # Completion history (monotonic stamp, count) — the retry-after
        # hint's throughput observation window.
        self._done_marks: list[tuple[float, int]] = []
        self._expired_log: list[Ticket] = []

    def submit(self, request: Request) -> Ticket:
        t = Ticket(request)
        # Adopt the request's wire context (a fleet re-route continues
        # the dead hop's trace) or mint a fresh root: trace_id IS the
        # request_id, so a trace needs no id-mapping layer.
        ctx = _tracing.from_wire(request.trace)
        t.trace = ctx if ctx is not None \
            else _tracing.mint(request.request_id)
        with self._lock:
            if self._closed:
                raise RuntimeError("queue is closed")
            self.submitted += 1
            t.ordinal = self.submitted
            depth = len(self._front) + len(self._pending)
            if self.max_depth is not None and depth >= self.max_depth:
                self.rejected += 1
                self.rejected_at_submit += 1
                hint = self._retry_after_locked(depth)
                error = (
                    f"queue-full (depth {depth} >= max_depth "
                    f"{self.max_depth}); retry-after ~{hint:.2f}s"
                )
            else:
                error = None
                self._pending.append(t)
        if error is not None:
            t._terminal_fail("rejected", error)
        _tracing.emit_tspan("trace.submit", t.trace,
                            ordinal=t.ordinal, state=t.state)
        return t

    def _retry_after_locked(self, depth: int) -> float:
        """Retry-after hint: backlog ÷ observed completion throughput
        over the recent history window. Every edge is BOUNDED into
        [0.01, MAX_RETRY_AFTER_S]: zero/one completion marks (cold
        start) fall back to the default constant; a window whose
        newest mark is RETRY_WINDOW_STALE_S old (post-flood idle)
        falls back too, because extrapolating a dead window produces
        a near-infinite hint; a same-instant burst (span 0) likewise.
        A hint, not a promise."""
        marks = self._done_marks
        if len(marks) >= 2:
            span = marks[-1][0] - marks[0][0]
            n = sum(c for _, c in marks)
            stale = (
                time.monotonic() - marks[-1][0] > RETRY_WINDOW_STALE_S
            )
            if span > 0 and n > 0 and not stale:
                return min(
                    max(depth * span / n, 0.01), MAX_RETRY_AFTER_S
                )
        return DEFAULT_RETRY_AFTER_S

    def retry_after_hint(self) -> float:
        with self._lock:
            return self._retry_after_locked(
                len(self._front) + len(self._pending)
            )

    def depth(self) -> int:
        with self._lock:
            return len(self._front) + len(self._pending)

    def pop_pending(self, max_n: int | None = None) -> list[Ticket]:
        """Pop the eligible pending tickets (requeued front first, both
        halves in submission order). Pop time is where SLO decisions
        land: a ticket past its deadline fails with `deadline-exceeded`
        HERE — it never occupies a lane — and a retry-backoff ticket
        whose `not_before` hasn't arrived stays parked in place. With
        `wall_slo` off both checks are skipped (multi-controller
        determinism; class docstring)."""
        now = time.monotonic()
        expired: list[Ticket] = []
        popped: list[Ticket] = []
        with self._lock:
            # Order pin: the requeued block replays in original
            # submission order no matter how many requeue calls built it.
            self._front.sort(key=lambda t: t.ordinal)
            budget = (len(self._front) + len(self._pending)) \
                if max_n is None else int(max_n)
            for lst in (self._front, self._pending):
                keep: list[Ticket] = []
                for t in lst:
                    d = t.request.deadline_s
                    if self.wall_slo and d is not None \
                            and now - t.submitted_mono >= d:
                        expired.append(t)
                    elif len(popped) < budget and (
                        not self.wall_slo or t.not_before <= now
                    ):
                        popped.append(t)
                    else:
                        keep.append(t)
                lst[:] = keep
            self.expired += len(expired)
            self._expired_log.extend(expired)
        for t in expired:
            t._terminal_fail(
                "expired",
                f"deadline-exceeded: pending {t.age_s(now):.2f}s > "
                f"deadline_s {t.request.deadline_s}",
            )
        for t in popped:
            t._mark("running")
        return popped

    def pop_matching(self, pred, max_n: int | None = None,
                     ) -> list[Ticket]:
        """Pop up to `max_n` eligible pending tickets whose REQUEST
        satisfies `pred` — the continuous drain's swap-in feed
        (docs/SERVING.md "Continuous batching"): at a segment boundary
        the service pulls queued requests of the batch's own program
        class into freed lanes, leaving everything else parked in
        place. Same SLO semantics as `pop_pending` (deadline expiry and
        retry backoff land here, skipped with `wall_slo` off), same
        order pin (requeued front first, submission order), and popped
        tickets are marked running."""
        now = time.monotonic()
        expired: list[Ticket] = []
        popped: list[Ticket] = []
        with self._lock:
            self._front.sort(key=lambda t: t.ordinal)
            budget = (len(self._front) + len(self._pending)) \
                if max_n is None else int(max_n)
            for lst in (self._front, self._pending):
                keep: list[Ticket] = []
                for t in lst:
                    d = t.request.deadline_s
                    if self.wall_slo and d is not None \
                            and now - t.submitted_mono >= d:
                        expired.append(t)
                    elif len(popped) < budget and pred(t.request) and (
                        not self.wall_slo or t.not_before <= now
                    ):
                        popped.append(t)
                    else:
                        keep.append(t)
                lst[:] = keep
            self.expired += len(expired)
            self._expired_log.extend(expired)
        for t in expired:
            t._terminal_fail(
                "expired",
                f"deadline-exceeded: pending {t.age_s(now):.2f}s > "
                f"deadline_s {t.request.deadline_s}",
            )
        for t in popped:
            t._mark("running")
        return popped

    def take_expired(self) -> list[Ticket]:
        """Drain the newly-expired tickets (the service emits their
        telemetry events and flight counters from here)."""
        with self._lock:
            out, self._expired_log = self._expired_log, []
        return out

    def expire_overdue(self, now: float | None = None) -> list[Ticket]:
        """Expire pending tickets past their deadline with the
        CALLER'S clock — the fleet router's single-writer wall-clock
        authority (docs/SERVING.md "The fleet"): replica queues run
        with `wall_slo` off, so no replica-local clock ever makes an
        SLO decision; the router makes every one of them through this
        hook before draining a replica. Returns the tickets after
        terminally failing them; `take_expired` still feeds their
        telemetry as usual."""
        now = time.monotonic() if now is None else now
        expired: list[Ticket] = []
        with self._lock:
            for lst in (self._front, self._pending):
                keep: list[Ticket] = []
                for t in lst:
                    d = t.request.deadline_s
                    if d is not None and now - t.submitted_mono >= d:
                        expired.append(t)
                    else:
                        keep.append(t)
                lst[:] = keep
            self.expired += len(expired)
            self._expired_log.extend(expired)
        for t in expired:
            t._terminal_fail(
                "expired",
                f"deadline-exceeded: pending {t.age_s(now):.2f}s > "
                f"deadline_s {t.request.deadline_s} (router clock)",
            )
        return expired

    def next_ready_delay(self) -> float | None:
        """Seconds until the earliest backoff-parked ticket becomes
        eligible; 0.0 when something is already eligible; None when the
        queue is empty."""
        now = time.monotonic()
        with self._lock:
            tickets = self._front + self._pending
            if not tickets:
                return None
            if not self.wall_slo:
                return 0.0
            return max(min(t.not_before for t in tickets) - now, 0.0)

    def requeue(self, tickets, wake: bool = True) -> None:
        """Park tickets back at the front. `wake=True` (preemption) lets
        blocked submitters observe the park promptly; `wake=False`
        (a retry-budget requeue) keeps them waiting for the retried
        batch's real resolution."""
        ts = list(tickets)
        for t in ts:
            t._mark("requeued", wake=wake)
        with self._lock:
            self._front.extend(ts)
            self.requeued += len(ts)

    def note_completed(self, n: int = 1, failed: int = 0) -> None:
        with self._lock:
            self.completed += n
            self.failed += failed
            if n:
                self._done_marks.append((time.monotonic(), n))
                del self._done_marks[:-32]

    def note_rejected(self, n: int = 1) -> None:
        """Admission rejections decided OUTSIDE submit (the service's
        circuit breaker rejects popped tickets of an open class)."""
        with self._lock:
            self.rejected += n

    def note_quarantined(self, n: int = 1) -> None:
        with self._lock:
            self.quarantined += n

    def close(self) -> None:
        with self._lock:
            self._closed = True

    def counters(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "requeued": self.requeued,
                "rejected": self.rejected,
                "expired": self.expired,
                "quarantined": self.quarantined,
                "depth": len(self._front) + len(self._pending),
            }

    def check_accounting(self, in_flight: int = 0) -> list[str]:
        """The terminal accounting invariant (docs/SERVING.md "SLOs and
        admission"): every submitted ticket is terminally accounted —
        done + failed + rejected + expired + quarantined + still-queued
        (+ `in_flight` popped-but-unresolved) == submitted. The service
        asserts this at drain time with in_flight=0; problem strings
        returned, [] when the books balance."""
        c = self.counters()
        accounted = (
            c["completed"] + c["failed"] + c["rejected"] + c["expired"]
            + c["quarantined"] + c["depth"] + int(in_flight)
        )
        if accounted != c["submitted"]:
            return [
                f"terminal accounting violated: done {c['completed']} + "
                f"failed {c['failed']} + rejected {c['rejected']} + "
                f"expired {c['expired']} + quarantined "
                f"{c['quarantined']} + depth {c['depth']} + in-flight "
                f"{in_flight} = {accounted} != submitted "
                f"{c['submitted']}"
            ]
        return []
