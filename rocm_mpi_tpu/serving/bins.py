"""The bin scheduler: pack heterogeneous requests onto shared compiled
programs (docs/SERVING.md "Bins").

Since the persistent compile cache is unsound on this stack, bin-packed
program reuse is the ONLY compile amortizer: a compiled batched advance
is specialized on everything in the `BinKey` — workload, exact space
shape class, dtype, physics constants, step variant, wire mode — plus
the lane width W. Requests that agree on the key share programs;
heterogeneity INSIDE a bin rides traced data instead of trace identity:

  * per-lane step counts — the batch executes max(nt_i) steps and each
    lane freezes bitwise at its own count (`lane_steps`, a traced
    operand; models.*.batched_advance_fn), so mixed step counts never
    split a program. The `steps_bucket` key field (next power of two)
    only bounds the WASTE of that padding — lanes in one bucket differ
    by at most 2× in length;
  * lane-width padding — arrivals rarely match a power-of-two width, so
    `plan_batches` packs pending requests into pow2 widths and pads the
    tail batch with idle lanes (steps 0: frozen from step 0, pure
    machine padding). The `occupancy_floor` (perf/budgets.json
    "serving") is the traffic-gate feed: a batch whose idle-lane
    padding would inflate bytes/useful-lane past budget is SPLIT into a
    narrower width class (its own program) instead of shipped padded.

Stdlib-at-import (the schema gate reads the bin-manifest format without
jax). Everything here is deterministic — in a multi-controller service
every rank must plan the identical batches, or the batched collectives
diverge (graftlint GL08's whole hazard class).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

from rocm_mpi_tpu.serving.queue import Request

BIN_MANIFEST_SCHEMA = "rmt-bin-manifest"
BIN_MANIFEST_VERSION = 1

DEFAULT_MAX_WIDTH = 8
DEFAULT_OCCUPANCY_FLOOR = 0.5
# The shape-padding ladder (docs/SERVING.md "Continuous batching"):
# rung quantum = pow2_floor(n) / LADDER_QUANTUM_FRACTION per axis (min
# LADDER_MIN_QUANTUM cells), so rungs get coarser as shapes grow — the
# space edition of steps_bucket's pow2 coarsening, but with a bounded
# per-axis inflation of at most one quantum. The committed FLOPs bound
# lives in perf/budgets.json "serving"/"padded_flops_tolerance".
LADDER_QUANTUM_FRACTION = 4
LADDER_MIN_QUANTUM = 4
DEFAULT_LADDER_TOLERANCE = 0.25


def steps_bucket(nt: int) -> int:
    """Canonical step bucket: the next power of two >= nt. Lanes in one
    bucket differ by at most 2x in length, bounding the padded-steps
    waste of the batch's max(nt) execution."""
    if nt < 1:
        raise ValueError(f"nt must be >= 1, got {nt}")
    b = 1
    while b < nt:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True, order=True)
class BinKey:
    """Compile identity of a batched program, minus the lane width
    (docs/SERVING.md has the field table). `key_str` round-trips
    through `parse` — the spelling the manifest and telemetry use."""

    workload: str
    shape: tuple[int, ...]
    dtype: str
    physics: tuple[tuple[str, float], ...]
    variant: str
    wire_mode: str
    steps_bucket: int

    def key_str(self) -> str:
        shape = "x".join(str(n) for n in self.shape)
        phys = ",".join(f"{k}={v!r}" for k, v in self.physics) or "-"
        return (
            f"{self.workload}|{shape}|{self.dtype}|{phys}|"
            f"{self.variant}|{self.wire_mode}|{self.steps_bucket}"
        )

    @classmethod
    def parse(cls, s: str) -> "BinKey":
        parts = s.split("|")
        if len(parts) != 7:
            raise ValueError(f"bad bin key {s!r} (want 7 '|' fields)")
        wl, shape_s, dtype, phys_s, variant, wire, bucket = parts
        shape = tuple(int(n) for n in shape_s.split("x"))
        phys: tuple = ()
        if phys_s != "-":
            pairs = []
            for item in phys_s.split(","):
                k, _, v = item.partition("=")
                if not _ or not k:
                    raise ValueError(f"bad physics field {item!r} in {s!r}")
                pairs.append((k, float(v)))
            phys = tuple(pairs)
        return cls(
            workload=wl, shape=shape, dtype=dtype, physics=phys,
            variant=variant, wire_mode=wire, steps_bucket=int(bucket),
        )


def bin_key(req: Request,
            ladder_tolerance: float | None = None) -> BinKey:
    """The request's bin: every trace-identity field, physics sorted so
    spelling order can't split a bin. With `ladder_tolerance` set, the
    shape field is laddered up a rung (`ladder_shape`) so near-rung
    shape classes MERGE into one program class — the caller (the
    service) decides eligibility; this stays the pure shape mapper."""
    key = BinKey(
        workload=req.workload,
        shape=tuple(req.global_shape),
        dtype=req.dtype,
        physics=tuple(sorted(req.physics)),
        variant=req.variant,
        wire_mode=req.wire_mode,
        steps_bucket=steps_bucket(req.nt),
    )
    if ladder_tolerance is not None:
        padded = ladder_shape(key.shape, ladder_tolerance)
        if padded != key.shape:
            key = dataclasses.replace(key, shape=padded)
    return key


def ladder_rung(n: int) -> int:
    """The smallest ladder rung >= n: the next multiple of the rung
    quantum `max(LADDER_MIN_QUANTUM, pow2_floor(n) //
    LADDER_QUANTUM_FRACTION)`. Like `steps_bucket`, rungs coarsen with
    size, but the per-axis inflation is bounded by ONE quantum (at most
    ~1/LADDER_QUANTUM_FRACTION of the axis), so the FLOPs cost of a
    merge stays small enough for the tolerance gate to accept most of
    the traffic it consolidates."""
    if n < 1:
        raise ValueError(f"axis size must be >= 1, got {n}")
    q = max(LADDER_MIN_QUANTUM, pow2_floor(n) // LADDER_QUANTUM_FRACTION)
    return ((n + q - 1) // q) * q


def ladder_inflation(shape, padded) -> float:
    """Fractional padded-FLOPs cost of serving `shape` embedded in
    `padded`: cells(padded)/cells(shape) - 1 (a per-step stencil's work
    is proportional to cells)."""
    orig = 1
    pad = 1
    for a, b in zip(shape, padded):
        orig *= int(a)
        pad *= int(b)
    return pad / orig - 1.0


def ladder_shape(shape, tolerance: float = DEFAULT_LADDER_TOLERANCE,
                 ) -> tuple[int, ...]:
    """Pad every space axis up to its ladder rung — IF the total
    padded-FLOPs inflation stays within `tolerance`; otherwise return
    the shape unchanged (the bin keeps its exact shape class: the
    split-instead-of-pad rule, the shape edition of the occupancy
    floor's split). Deterministic — every controller maps a shape to
    the same rung."""
    if tolerance < 0.0:
        raise ValueError(
            f"padded_flops_tolerance must be >= 0, got {tolerance}"
        )
    padded = tuple(ladder_rung(int(n)) for n in shape)
    if padded == tuple(int(n) for n in shape):
        return tuple(int(n) for n in shape)
    if ladder_inflation(shape, padded) > tolerance:
        return tuple(int(n) for n in shape)
    return padded


def pow2_width(n: int, max_width: int) -> int:
    """Smallest power of two >= n, capped at max_width."""
    w = 1
    while w < n and w < max_width:
        w *= 2
    return min(w, max_width)


def pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1) — the shared rounding the
    width planner and the service's grow target both use."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def plan_batches(n_pending: int, max_width: int = DEFAULT_MAX_WIDTH,
                 occupancy_floor: float = DEFAULT_OCCUPANCY_FLOOR,
                 ) -> list[int]:
    """Deterministic width plan for `n_pending` same-key requests: a
    list of batch widths (each a power of two <= max_width) covering all
    requests in FIFO order. Greedy: take the widest batch whose
    occupancy (live/width) clears the floor; the split rule is built in
    — a remainder that would ride a wide batch under-occupied gets a
    narrower width class of its own (its own program) instead
    (docs/SERVING.md "Padding policy")."""
    if max_width < 1:
        raise ValueError(f"max_width must be >= 1, got {max_width}")
    if not 0.0 < occupancy_floor <= 1.0:
        raise ValueError(
            f"occupancy_floor must be in (0, 1], got {occupancy_floor}"
        )
    cap = pow2_floor(max_width)
    out: list[int] = []
    n = int(n_pending)
    while n > 0:
        # The narrowest pow2 covering what's left (programs are the
        # scarce resource — one wide batch beats two narrow ones), then
        # the split rule: shrink while the batch would ride under the
        # occupancy floor.
        w = pow2_width(n, cap)
        while w > 1 and (min(n, w) / w) < occupancy_floor:
            w //= 2
        out.append(w)
        n -= min(n, w)
    return out


@dataclasses.dataclass
class BinStats:
    """One bin's serving accounting (the occupancy / padding-waste
    gauges, docs/TELEMETRY.md "Serving"). `lanes` counts compiled lane
    slots across executed batches; `live_lanes` the slots that carried a
    request; `useful_steps` the sum of per-lane requested steps;
    `machine_steps` width x executed-steps summed over batches — the
    denominator padding waste is measured against."""

    key: BinKey
    requests: int = 0
    batches: int = 0
    widths: tuple[int, ...] = ()
    lanes: int = 0
    live_lanes: int = 0
    useful_steps: int = 0
    machine_steps: int = 0
    splits: int = 0
    # Continuous-drain extras (docs/SERVING.md "Continuous batching"):
    # lanes swapped in at segment boundaries, segments executed, and the
    # ladder's cell accounting — cells are steps-weighted so a short
    # laddered lane can't dominate the waste of a long exact one.
    swaps_in: int = 0
    segments: int = 0
    cells_useful: int = 0
    cells_machine: int = 0

    @property
    def occupancy(self) -> float:
        return self.live_lanes / self.lanes if self.lanes else 0.0

    @property
    def padding_waste(self) -> float:
        """1 − useful/machine steps: the fraction of executed lane-steps
        that served no request (idle lanes + frozen tail steps)."""
        if not self.machine_steps:
            return 0.0
        return 1.0 - self.useful_steps / self.machine_steps

    @property
    def ladder_waste(self) -> float:
        """1 − useful/machine CELLS (steps-weighted): the fraction of
        executed stencil work spent on ladder shape padding. Distinct
        from `padding_waste`, which counts idle-lane and frozen-tail
        STEP padding — a bin can have ladder waste with zero width
        waste and vice versa."""
        if not self.cells_machine:
            return 0.0
        return 1.0 - self.cells_useful / self.cells_machine

    def _note_cells(self, lane_nts, lane_cells) -> None:
        for nt, (orig_cells, padded_cells) in zip(lane_nts, lane_cells):
            self.cells_useful += int(orig_cells) * int(nt)
            self.cells_machine += int(padded_cells) * int(nt)

    def note_batch(self, width: int, lane_nts: list[int],
                   executed_steps: int, split: bool = False,
                   lane_cells: list[tuple[int, int]] | None = None,
                   ) -> None:
        self.batches += 1
        self.widths = tuple(sorted(set(self.widths) | {width}))
        self.lanes += width
        self.live_lanes += len(lane_nts)
        self.requests += len(lane_nts)
        self.useful_steps += sum(lane_nts)
        self.machine_steps += width * executed_steps
        if split:
            self.splits += 1
        if lane_cells is not None:
            self._note_cells(lane_nts, lane_cells)

    def note_continuous(self, width: int, lane_nts: list[int],
                        executed_steps: int, swaps_in: int,
                        segments: int, split: bool = False,
                        lane_cells: list[tuple[int, int]] | None = None,
                        ) -> None:
        """Accounting for one segmented (continuous) batch: `lane_nts`
        lists every tenant that rode the batch — possibly MORE than
        `width`, since slots are re-seated at segment boundaries — so
        slot occupancy caps `live_lanes` at the compiled width (the
        manifest bounds occupancy to [0, 1]); the machine denominator
        is still width x executed machine steps."""
        self.batches += 1
        self.widths = tuple(sorted(set(self.widths) | {width}))
        self.lanes += width
        self.live_lanes += min(len(lane_nts), width)
        self.requests += len(lane_nts)
        self.useful_steps += sum(lane_nts)
        self.machine_steps += width * executed_steps
        self.swaps_in += int(swaps_in)
        self.segments += int(segments)
        if split:
            self.splits += 1
        if lane_cells is not None:
            self._note_cells(lane_nts, lane_cells)


def manifest_doc(stats: dict, programs: list[str],
                 queue_counters: dict | None = None,
                 extra: dict | None = None) -> dict:
    """The bin manifest (`serve-manifest.json`, schema-checked by
    `telemetry regress --check-schema`): one row per bin with its
    occupancy/padding-waste accounting, plus the compiled program
    classes — `len(programs)` IS the trace's compile count under the
    steady-state contract."""
    rows = []
    for key, st in sorted(stats.items(), key=lambda kv: kv[0]):
        row = {
            "key": key.key_str() if isinstance(key, BinKey) else str(key),
            "requests": st.requests,
            "batches": st.batches,
            "widths": list(st.widths),
            "occupancy": round(st.occupancy, 4),
            "padding_waste": round(st.padding_waste, 4),
            "splits": st.splits,
        }
        if st.swaps_in or st.segments:
            row["swaps_in"] = st.swaps_in
            row["segments"] = st.segments
        if st.cells_machine:
            row["ladder_waste"] = round(st.ladder_waste, 4)
        rows.append(row)
    doc = {
        "schema": BIN_MANIFEST_SCHEMA,
        "v": BIN_MANIFEST_VERSION,
        # Record wall STAMP (the `t` field every telemetry record
        # carries), not an interval measurement — nothing to sync.
        # graftlint: disable-next=GL06
        "t": time.time(),
        "bins": rows,
        "programs": sorted(programs),
    }
    if queue_counters:
        doc["queue"] = dict(queue_counters)
    if extra:
        doc.update(extra)
    return doc


def validate_manifest_doc(doc: dict) -> list[str]:
    """Problem strings for a bin manifest (stdlib; shared with
    telemetry.regress --check-schema)."""
    problems: list[str] = []
    if doc.get("schema") != BIN_MANIFEST_SCHEMA:
        problems.append(
            f"schema {doc.get('schema')!r} != {BIN_MANIFEST_SCHEMA}"
        )
    if not isinstance(doc.get("v"), int):
        problems.append("missing int v")
    bins = doc.get("bins")
    if not isinstance(bins, list):
        return problems + ["missing bins list"]
    for i, row in enumerate(bins):
        if not isinstance(row, dict):
            problems.append(f"bins[{i}] not an object")
            continue
        key = row.get("key")
        if not isinstance(key, str):
            problems.append(f"bins[{i}] missing key")
        else:
            try:
                BinKey.parse(key)
            except ValueError as e:
                problems.append(f"bins[{i}].key: {e}")
        for field in ("requests", "batches"):
            if not isinstance(row.get(field), int) or row.get(field) < 0:
                problems.append(f"bins[{i}].{field} not a count")
        for field in ("occupancy", "padding_waste"):
            v = row.get(field)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not 0.0 <= v <= 1.0:
                problems.append(f"bins[{i}].{field} outside [0, 1]")
        # Continuous/ladder row extras are optional (archived manifests
        # predate them) but must be well-formed when present.
        for field in ("swaps_in", "segments"):
            v = row.get(field)
            if v is not None and (
                not isinstance(v, int) or isinstance(v, bool) or v < 0
            ):
                problems.append(f"bins[{i}].{field} not a count")
        lw = row.get("ladder_waste")
        if lw is not None and (
            not isinstance(lw, (int, float)) or isinstance(lw, bool)
            or not 0.0 <= lw <= 1.0
        ):
            problems.append(f"bins[{i}].ladder_waste outside [0, 1]")
    progs = doc.get("programs")
    if not isinstance(progs, list) or not all(
        isinstance(p, str) for p in progs
    ):
        problems.append("missing programs list")
    pipe = doc.get("pipeline")
    if pipe is not None and pipe != {}:
        # The drain-pipeline block (docs/SERVING.md "The pipeline"):
        # depth, resolved batches, the device-bubble fraction, and the
        # per-stage host walls — a hand-edited bubble outside [0, 1]
        # or a non-count depth must fail here, not silently corrupt
        # the next pipeline-efficiency audit of an archived manifest.
        if not isinstance(pipe, dict):
            problems.append("'pipeline' block is not an object")
        else:
            depth = pipe.get("depth")
            if not isinstance(depth, int) or isinstance(depth, bool) \
                    or depth < 1:
                problems.append(f"pipeline.depth {depth!r} not >= 1")
            batches = pipe.get("batches")
            if not isinstance(batches, int) or isinstance(batches, bool) \
                    or batches < 0:
                problems.append(
                    f"pipeline.batches {batches!r} not a count"
                )
            bubble = pipe.get("bubble")
            if not isinstance(bubble, (int, float)) \
                    or isinstance(bubble, bool) \
                    or not 0.0 <= bubble <= 1.0:
                problems.append(
                    f"pipeline.bubble {bubble!r} outside [0, 1]"
                )
            for field in ("assemble_s", "dispatch_s", "fetch_s",
                          "resolve_s", "busy_s", "wall_s"):
                v = pipe.get(field)
                if v is not None and (
                    not isinstance(v, (int, float))
                    or isinstance(v, bool) or v < 0
                ):
                    problems.append(
                        f"pipeline.{field} {v!r} not a non-negative "
                        "wall"
                    )
    cont = doc.get("continuous")
    if cont is not None:
        # The continuous-drain block (docs/SERVING.md "Continuous
        # batching"): segment count knob, executed segments, the swap
        # counters, and the step-weighted occupancy the regress gate
        # floors — a doctored occupancy outside [0, 1] or a zero
        # segments knob must fail the schema check.
        if not isinstance(cont, dict):
            problems.append("'continuous' block is not an object")
        else:
            segs = cont.get("segments")
            if not isinstance(segs, int) or isinstance(segs, bool) \
                    or segs < 1:
                problems.append(
                    f"continuous.segments {segs!r} not >= 1"
                )
            for field in ("batches", "segments_run", "swaps_in",
                          "swaps_out"):
                v = cont.get(field)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    problems.append(
                        f"continuous.{field} {v!r} not a count"
                    )
            occ = cont.get("occupancy")
            if not isinstance(occ, (int, float)) \
                    or isinstance(occ, bool) or not 0.0 <= occ <= 1.0:
                problems.append(
                    f"continuous.occupancy {occ!r} outside [0, 1]"
                )
    queue = doc.get("queue")
    if queue is not None:
        if not isinstance(queue, dict):
            problems.append("'queue' block is not an object")
        else:
            for field, v in queue.items():
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    problems.append(
                        f"queue.{field} {v!r} is not a count"
                    )
            # The terminal accounting invariant (docs/SERVING.md "SLOs
            # and admission"), enforced on the ARCHIVED manifest too:
            # manifests are written at drain boundaries (nothing in
            # flight), so every submitted ticket must be terminally
            # accounted or still queued — requeued is a cumulative
            # event count, not an outcome, and stays out of the sum.
            terminal = ("completed", "failed", "rejected", "expired",
                        "quarantined", "depth")
            if "submitted" in queue and all(
                isinstance(queue.get(k), int) for k in terminal
            ):
                total = sum(queue[k] for k in terminal)
                if total != queue["submitted"]:
                    problems.append(
                        f"queue counters do not sum to submissions "
                        f"({total} != {queue['submitted']}): every "
                        f"submitted ticket must end done/failed/"
                        f"rejected/expired/quarantined or still queued"
                    )
    return problems


def write_manifest(path, doc: dict) -> None:
    """Atomic tmp+rename write (GL09: this is a schema-versioned
    sidecar; a torn manifest must never be readable)."""
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
