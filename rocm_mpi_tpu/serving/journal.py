"""The durable fleet ticket journal (docs/SERVING.md "The fleet").

A fleet router (serving/router.py) owns tickets that outlive any one
replica: a `SimulationService` killed mid-traffic (SIGKILL, rc-75
preemption, or a watchdog verdict) takes its queue counters with it,
so the fleet-wide terminal-accounting invariant — every submitted
ticket reaches EXACTLY ONE terminal state — needs a source of truth
that survives the replica. That is this journal: an append-only JSONL
ledger (`rmt-fleet-journal` v1) recording every ticket's
submit → route → terminal transitions, written by exactly one router
(single-writer per journal; replicas never write it — the same
single-writer discipline that keeps wall clocks router-side, the GL08
divergence class).

Durability discipline (GL09): the live segment is append-only — every
completed line is a valid record, and a torn tail (the router died
mid-write) is tolerated by replay, never parsed as data. Sealed
segments move out of the live path via an atomic rename
(`TicketJournal.seal_segment`), so a reader never observes a
half-sealed file.

Replay (`replay`) is a pure fold from record lines to per-ticket
state: running it twice — or re-running it over an already-reconciled
fleet — changes nothing (the reconciliation idempotence the
replica-kill drill pins). `exactly_one_terminal` turns the folded
state into the fleet accounting verdict.

Stdlib-at-import on purpose: `telemetry regress --check-schema` and
lint.sh validate archived `fleet-journal*.jsonl` / `fleet-report*.json`
sidecars through the validators here without importing jax.
"""

from __future__ import annotations

import json
import os
import pathlib
import time

JOURNAL_SCHEMA = "rmt-fleet-journal"
JOURNAL_VERSION = 1
JOURNAL_KINDS = ("submit", "route", "terminal")

FLEET_REPORT_SCHEMA = "rmt-fleet-report"
FLEET_REPORT_VERSION = 1

# serving/queue.py TERMINAL_STATES, spelled flat for the stdlib read
# side (tests/test_fleet.py pins the spellings against the queue).
TERMINAL_STATES = ("done", "failed", "rejected", "expired", "quarantined")


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------


def _base_record(kind: str, seq: int, request_id: str) -> dict:
    return {
        "schema": JOURNAL_SCHEMA,
        "v": JOURNAL_VERSION,
        "kind": kind,
        "seq": int(seq),
        "request_id": request_id,
    }


def validate_journal_record(doc: dict) -> list[str]:
    """Problem strings for one fleet-journal line (stdlib; shared with
    `telemetry regress --check-schema`)."""
    problems: list[str] = []
    if doc.get("schema") != JOURNAL_SCHEMA:
        problems.append(
            f"schema {doc.get('schema')!r} != {JOURNAL_SCHEMA}"
        )
    if not isinstance(doc.get("v"), int):
        problems.append("missing int v")
    kind = doc.get("kind")
    if kind not in JOURNAL_KINDS:
        problems.append(
            f"kind {kind!r} not one of {list(JOURNAL_KINDS)}"
        )
    seq = doc.get("seq")
    if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
        problems.append(f"seq {seq!r} is not a non-negative int")
    rid = doc.get("request_id")
    if not isinstance(rid, str) or not rid:
        problems.append("missing request_id")
    if kind == "route":
        rep = doc.get("replica")
        if not isinstance(rep, int) or isinstance(rep, bool) or rep < 0:
            problems.append(f"route record replica {rep!r} is not an id")
    if kind == "terminal":
        state = doc.get("state")
        if state not in TERMINAL_STATES:
            problems.append(
                f"terminal state {state!r} not one of "
                f"{list(TERMINAL_STATES)}"
            )
    return problems


# ---------------------------------------------------------------------------
# the single-writer journal
# ---------------------------------------------------------------------------


class TicketJournal:
    """Append-only single-writer journal. One instance per router; the
    live segment is `<path>`, sealed segments are
    `<stem>-segNNN<suffix>` siblings (atomic rename — see
    `seal_segment`). Every append is flushed line-atomically, so a
    replica kill between appends never tears a record."""

    def __init__(self, path):
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._seq = 0
        self._sealed = 0
        # Resume the seq counter over an existing live segment (a
        # router restart keeps appending to the same ledger).
        if self.path.is_file():
            state = replay([self.path])
            self._seq = state.seq_max + 1
        self._fh = open(self.path, "a", encoding="utf-8")

    # -- writers ----------------------------------------------------------

    def _append(self, doc: dict) -> dict:
        self._fh.write(json.dumps(doc, sort_keys=True) + "\n")
        self._fh.flush()
        self._seq += 1
        return doc

    def record_submit(self, request_id: str, *, session=None,
                      bin_key=None) -> dict:
        doc = _base_record("submit", self._seq, request_id)
        doc["session"] = session
        doc["bin"] = bin_key
        return self._append(doc)

    def record_route(self, request_id: str, replica: int, *,
                     reroute: bool = False) -> dict:
        doc = _base_record("route", self._seq, request_id)
        doc["replica"] = int(replica)
        doc["reroute"] = bool(reroute)
        return self._append(doc)

    def record_terminal(self, request_id: str, state: str, *,
                        replica=None) -> dict:
        if state not in TERMINAL_STATES:
            raise ValueError(
                f"terminal state must be one of {TERMINAL_STATES}, "
                f"got {state!r}"
            )
        doc = _base_record("terminal", self._seq, request_id)
        doc["state"] = state
        doc["replica"] = replica
        return self._append(doc)

    # -- segments ---------------------------------------------------------

    def seal_segment(self) -> pathlib.Path | None:
        """Atomically move the live segment aside (`os.replace` — a
        reader sees either the live file or the sealed one, never a
        torn copy) and start a fresh live segment. Returns the sealed
        path, or None when the live segment is empty."""
        self._fh.close()
        sealed = None
        if self.path.is_file() and self.path.stat().st_size > 0:
            sealed = self.path.with_name(
                f"{self.path.stem}-seg{self._sealed:03d}"
                f"{self.path.suffix}"
            )
            os.replace(self.path, sealed)
            self._sealed += 1
        self._fh = open(self.path, "a", encoding="utf-8")
        return sealed

    def segments(self) -> list[pathlib.Path]:
        """Every segment in replay order: sealed (oldest first) then
        the live tail."""
        sealed = sorted(
            self.path.parent.glob(
                f"{self.path.stem}-seg*{self.path.suffix}"
            )
        )
        live = [self.path] if self.path.is_file() else []
        return sealed + live

    def close(self) -> None:
        self._fh.close()


# ---------------------------------------------------------------------------
# replay: the pure fold
# ---------------------------------------------------------------------------


class JournalState:
    """Folded per-ticket view of a journal replay. `tickets` maps
    request_id -> {"submitted", "session", "bin", "routes",
    "terminals", "reroutes"}; a complete fleet run leaves every ticket
    with exactly one terminal."""

    def __init__(self):
        self.tickets: dict[str, dict] = {}
        self.seq_max = -1
        self.torn_lines = 0
        self.malformed: list[str] = []

    def _ticket(self, rid: str) -> dict:
        return self.tickets.setdefault(rid, {
            "submitted": False, "session": None, "bin": None,
            "routes": [], "reroutes": 0, "terminals": [],
        })

    def apply(self, doc: dict) -> None:
        problems = validate_journal_record(doc)
        if problems:
            self.malformed.append("; ".join(problems))
            return
        self.seq_max = max(self.seq_max, int(doc["seq"]))
        t = self._ticket(doc["request_id"])
        kind = doc["kind"]
        if kind == "submit":
            t["submitted"] = True
            t["session"] = doc.get("session")
            t["bin"] = doc.get("bin")
        elif kind == "route":
            t["routes"].append(int(doc["replica"]))
            if doc.get("reroute"):
                t["reroutes"] += 1
        elif kind == "terminal":
            t["terminals"].append(
                (doc["state"], doc.get("replica"))
            )

    # -- derived views ----------------------------------------------------

    def open_on(self, replica: int) -> list[str]:
        """Tickets whose LAST route landed on `replica` and that never
        reached a terminal — the re-route set when `replica` dies."""
        out = []
        for rid, t in self.tickets.items():
            if t["terminals"] or not t["routes"]:
                continue
            if t["routes"][-1] == int(replica):
                out.append(rid)
        return sorted(out)

    def terminal_counts(self) -> dict:
        counts = {s: 0 for s in TERMINAL_STATES}
        for t in self.tickets.values():
            for state, _rep in t["terminals"]:
                counts[state] += 1
        return counts

    def counts(self) -> dict:
        """The journal block of the fleet report."""
        term = self.terminal_counts()
        n_term = sum(
            1 for t in self.tickets.values() if t["terminals"]
        )
        return {
            "tickets": len(self.tickets),
            "terminal": term,
            "open": len(self.tickets) - n_term,
            "rerouted": sum(
                t["reroutes"] for t in self.tickets.values()
            ),
            "torn_lines": self.torn_lines,
        }


def replay(paths) -> JournalState:
    """Fold journal segments into a `JournalState`. Pure and
    idempotent: same segments -> same state, and a state rebuilt after
    reconciliation already contains the reconciliation's own records —
    there is nothing to 'apply twice'. A torn tail line (the router
    died mid-append) is counted, never parsed."""
    state = JournalState()
    for raw in paths:
        path = pathlib.Path(raw)
        if not path.is_file():
            continue
        try:
            text = path.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                state.torn_lines += 1
                continue
            if isinstance(doc, dict):
                state.apply(doc)
            else:
                state.torn_lines += 1
    return state


def exactly_one_terminal(state: JournalState) -> list[str]:
    """THE fleet accounting invariant (docs/SERVING.md "The fleet"):
    at fleet drain, every journaled ticket has exactly one terminal
    record — zero means a ticket vanished with a replica (the exact
    loss the journal exists to catch), two means a re-routed ticket's
    side effects ran twice. Problem strings; [] when the books
    balance."""
    problems = []
    for rid in sorted(state.tickets):
        t = state.tickets[rid]
        n = len(t["terminals"])
        if not t["submitted"]:
            problems.append(f"{rid}: routed/terminated, never submitted")
        if n == 0:
            problems.append(f"{rid}: no terminal state (lost ticket)")
        elif n > 1:
            states = [s for s, _ in t["terminals"]]
            problems.append(
                f"{rid}: {n} terminal states {states} (want exactly 1)"
            )
    if state.malformed:
        problems.append(
            f"{len(state.malformed)} malformed record(s): "
            + state.malformed[0]
        )
    return problems


# ---------------------------------------------------------------------------
# the merged fleet report
# ---------------------------------------------------------------------------


def fleet_report_doc(replicas, slo: dict, journal_counts: dict, *,
                     accounting_ok: bool, autoscale=()) -> dict:
    """The schema-versioned merged fleet report
    (`rmt-fleet-report` v1): one row per replica (alive or not — a
    killed replica's frozen view stays in the record), the merged SLO
    block (journal-derived terminal counts: replica counters die with
    the replica, the journal does not), the journal accounting block,
    and the autoscale event trail."""
    return {
        "schema": FLEET_REPORT_SCHEMA,
        "v": FLEET_REPORT_VERSION,
        # Record wall STAMP (the `t` field every telemetry record
        # carries), not an interval measurement — nothing to sync.
        # graftlint: disable-next=GL06
        "t": time.time(),
        "replicas": list(replicas),
        "slo": dict(slo),
        "journal": dict(journal_counts),
        "autoscale": list(autoscale),
        "accounting_ok": bool(accounting_ok),
    }


def validate_fleet_report(doc: dict) -> list[str]:
    """Problem strings for a fleet-report.json document (stdlib;
    shared with `telemetry regress --check-schema`)."""
    problems: list[str] = []
    if doc.get("schema") != FLEET_REPORT_SCHEMA:
        problems.append(
            f"schema {doc.get('schema')!r} != {FLEET_REPORT_SCHEMA}"
        )
    if not isinstance(doc.get("v"), int):
        problems.append("missing int v")
    if not isinstance(doc.get("accounting_ok"), bool):
        problems.append("missing bool accounting_ok")
    reps = doc.get("replicas")
    if not isinstance(reps, list) or not reps:
        problems.append("missing non-empty replicas list")
    else:
        for i, rep in enumerate(reps):
            if not isinstance(rep, dict):
                problems.append(f"replicas[{i}] not an object")
                continue
            if not isinstance(rep.get("id"), int):
                problems.append(f"replicas[{i}] missing int id")
            if not isinstance(rep.get("alive"), bool):
                problems.append(f"replicas[{i}] missing bool alive")
            steady = rep.get("steady_state")
            if not isinstance(steady, int) or isinstance(steady, bool):
                problems.append(
                    f"replicas[{i}] missing int steady_state"
                )
    slo = doc.get("slo")
    if not isinstance(slo, dict):
        problems.append("missing slo block")
    else:
        for field in ("submitted", "done", "failed", "rejected",
                      "expired", "quarantined", "retries"):
            v = slo.get(field)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"slo.{field} {v!r} is not a count")
    jc = doc.get("journal")
    if not isinstance(jc, dict):
        problems.append("missing journal block")
    else:
        for field in ("tickets", "open", "rerouted", "torn_lines"):
            v = jc.get(field)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"journal.{field} {v!r} is not a count")
        term = jc.get("terminal")
        if not isinstance(term, dict) or set(term) != set(
            TERMINAL_STATES
        ):
            problems.append(
                "journal.terminal must map every terminal state"
            )
    if not isinstance(doc.get("autoscale"), list):
        problems.append("missing autoscale event list")
    return problems


def write_fleet_report(path, doc: dict) -> None:
    """Atomic tmp+rename write (GL09: the merged report is the one
    artifact a killed fleet leaves for triage — a torn report after
    the kill it exists to explain would be absurd)."""
    problems = validate_fleet_report(doc)
    if problems:
        raise ValueError("bad fleet report: " + "; ".join(problems))
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
