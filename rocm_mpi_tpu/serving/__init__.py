"""Multi-tenant batched simulation serving (docs/SERVING.md).

The layer that turns the single-run platform into a service (ROADMAP
item 1): an async request queue (`queue.py`), a bin scheduler that packs
heterogeneous requests onto shared compiled programs (`bins.py` — since
the persistent compile cache is unsound on this stack, bin-packed
program reuse is the ONLY compile amortizer), and a service driver
(`service.py`) that executes batches on a space×batch mesh
(parallel.mesh.BatchedGrid) through a PIPELINED drain — explicit
assemble → dispatch → fetch → resolve stages, double-buffered by
default so host work overlaps device compute, bitwise-equal to the
serial drain at any depth (docs/SERVING.md "The pipeline") —
multiplexes per-session checkpoints, streams per-request telemetry,
and consumes the resilience layer's ElasticPolicy (grow when the
queue is deep, shrink when idle, requeue rc-75 preemptions).

The request plane is hardened (docs/SERVING.md "SLOs and admission"):
per-request deadlines expire stale pending tickets at pop time, a
bounded queue rejects over-depth submits fast with a retry-after hint,
transient batch/numerical failures ride a bounded exponential-backoff
retry budget, poison requests are quarantined to an append-only
`quarantine.jsonl` ledger, and a per-BinKey circuit breaker stops one
failing shape class from starving every other tenant. `slo.py` carries
the SLO accounting and the `soak-report.json` schema the chaos soak
driver (apps/soak.py) banks.

`queue`, `bins`, and `slo` are stdlib-at-import (the telemetry/regress
schema side reads their formats without jax); `service` imports jax
lazily.
"""

from rocm_mpi_tpu.serving.bins import (  # noqa: F401
    BIN_MANIFEST_SCHEMA,
    BinKey,
    bin_key,
    plan_batches,
    steps_bucket,
)
from rocm_mpi_tpu.serving.queue import (  # noqa: F401
    QUARANTINE_SCHEMA,
    REQUEST_SCHEMA,
    Request,
    RequestQueue,
    Ticket,
)
from rocm_mpi_tpu.serving.slo import (  # noqa: F401
    SOAK_SCHEMA,
    validate_soak_report,
    write_soak_report,
)
