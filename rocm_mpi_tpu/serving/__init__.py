"""Multi-tenant batched simulation serving (docs/SERVING.md).

The layer that turns the single-run platform into a service (ROADMAP
item 1): an async request queue (`queue.py`), a bin scheduler that packs
heterogeneous requests onto shared compiled programs (`bins.py` — since
the persistent compile cache is unsound on this stack, bin-packed
program reuse is the ONLY compile amortizer), and a service driver
(`service.py`) that executes batches on a space×batch mesh
(parallel.mesh.BatchedGrid), multiplexes per-session checkpoints,
streams per-request telemetry, and consumes the resilience layer's
ElasticPolicy (grow when the queue is deep, shrink when idle, requeue
rc-75 preemptions).

`queue` and `bins` are stdlib-at-import (the telemetry/regress schema
side reads their formats without jax); `service` imports jax lazily.
"""

from rocm_mpi_tpu.serving.bins import (  # noqa: F401
    BIN_MANIFEST_SCHEMA,
    BinKey,
    bin_key,
    plan_batches,
    steps_bucket,
)
from rocm_mpi_tpu.serving.queue import (  # noqa: F401
    REQUEST_SCHEMA,
    Request,
    RequestQueue,
    Ticket,
)
