"""The autotuner CLI (docs/PERF.md "Autotuning").

    python -m rocm_mpi_tpu.tuning search   [--ops A,B] [--shape N[,M…]]
                                           [--dtype f32] [--repeats R]
                                           [--cache PATH] [--force]
    python -m rocm_mpi_tpu.tuning show     [--cache PATH]
    python -m rocm_mpi_tpu.tuning validate PATH [PATH…]

* `search` — offline tuning for the default op set (the diffusion and
  wave VMEM-resident loops) or --ops, at the per-shard --shape. Keys
  whose fingerprint-valid entry already exists are pure cache hits: no
  candidate runs, no compile — the end-of-run line reports
  `compiles.steady_state=0` on a warm cache (steady state is marked
  after the hit scan, so any compile a warm run still pays is a gated
  recompile). Exit 0 on success (including all-hit), 1 when a key ends
  all-rejected (every candidate over the traffic budget), 2 on usage.
* `show` — the cache's entries as a table, stale fingerprints marked.
* `validate` — strict schema + traffic-gate check of committed cache
  files (scripts/lint.sh runs this): exit 1 on schema drift or any
  entry whose config models over its A_eff budget, 2 on unreadable
  paths. Unlike the runtime's tolerant read, a torn committed file
  FAILS here.
"""

from __future__ import annotations

import argparse
import json
import sys

from rocm_mpi_tpu.tuning import cache as _cache
from rocm_mpi_tpu.tuning import gate as _gate
from rocm_mpi_tpu.tuning.keys import parse_dims, parse_key


def _log(*parts) -> None:
    print(*parts, file=sys.stderr)


DEFAULT_SEARCH_OPS = ("diffusion.vmem_loop", "wave.vmem_loop")


def cmd_search(args) -> int:
    from rocm_mpi_tpu.telemetry import compiles

    from rocm_mpi_tpu.tuning import search as _search

    ops = (
        tuple(o for o in args.ops.split(",") if o)
        if args.ops else DEFAULT_SEARCH_OPS
    )
    shape = parse_dims(args.shape)
    path = args.cache or _cache.default_cache_path()
    compiles.install()

    # Hit scan first: a fully warm cache must do NO work — the line
    # every compile after this mark crosses is the steady-state gauge
    # the acceptance drill pins at 0.
    results = []
    pending = []
    for op in ops:
        r = _search.search_op(op, shape, args.dtype, cache_path=path,
                              force=args.force, log=_log)
        if r["status"] == "hit":
            results.append(r)
        else:
            pending.append((op, r))
    if not pending:
        compiles.mark_steady()
    statuses = [r["status"] for r in results] + [
        r["status"] for _, r in pending
    ]
    hits = statuses.count("hit")
    tuned = statuses.count("tuned")
    bad = statuses.count("all-rejected")
    _log(
        f"tuning search: {hits} hit(s), {tuned} tuned, {bad} rejected-out, "
        f"{statuses.count('empty')} empty — cache {path}; "
        f"compiles.steady_state={compiles.steady_state()}"
    )
    from rocm_mpi_tpu.tuning import resolve as _resolve

    _resolve.emit_gauges()
    return 1 if bad else 0


def cmd_show(args) -> int:
    path = args.cache or _cache.default_cache_path()
    doc = _cache.load(path)
    entries = doc.get("entries", {})
    if not entries:
        print(f"tuning cache {path}: empty")
        return 0
    try:
        from rocm_mpi_tpu.tuning.keys import fingerprint

        live = fingerprint()
    except Exception:  # noqa: BLE001 — show must work without a backend
        live = None
    print(f"tuning cache {path}: {len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'}")
    for raw_key, entry in sorted(entries.items()):
        fp = entry.get("fingerprint", {})
        stale = ""
        if live is not None and (
            fp.get("jax") != live["jax"]
        ):
            stale = "  [STALE: jax " + str(fp.get("jax")) + "]"
        print(
            f"  {raw_key}\n"
            f"    config={json.dumps(entry.get('config'), sort_keys=True)} "
            f"median_us={entry.get('median_us')} "
            f"gate={entry.get('gate_ratio')}x{stale}"
        )
    return 0


def cmd_validate(args) -> int:
    if not args.paths:
        _log("tuning validate: no paths given")
        return 2
    problems = []
    for path in args.paths:
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except OSError as e:
            _log(f"tuning validate: cannot read {path}: {e}")
            return 2
        except ValueError as e:
            problems.append(f"{path}: not valid JSON ({e})")
            continue
        problems.extend(_cache.validate_doc(doc, path))
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            continue
        for raw_key, entry in sorted(entries.items()):
            try:
                key = parse_key(raw_key)
            except ValueError:
                continue  # already reported by validate_doc
            if not isinstance(entry, dict) or not isinstance(
                entry.get("config"), dict
            ):
                continue
            g = _gate.validate_entry(key, entry)
            if not g.ok:
                problems.append(f"{path}: entry {raw_key!r}: {g.reason}")
        if not problems:
            _log(f"tuning validate: {path} ok "
                 f"({len(entries)} entr"
                 f"{'y' if len(entries) == 1 else 'ies'})")
    for p in problems:
        _log(f"tuning validate: PROBLEM: {p}")
    return 1 if problems else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m rocm_mpi_tpu.tuning",
        description=__doc__.splitlines()[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("search", help="measure + gate + persist winners")
    ps.add_argument("--ops", default=None,
                    help="comma-separated tunable ops (default: "
                    + ",".join(DEFAULT_SEARCH_OPS) + ")")
    ps.add_argument("--shape", default="32x32",
                    help="per-shard field shape, e.g. 252x252 "
                    "(default %(default)s — CPU-feasible)")
    ps.add_argument("--dtype", default="f32",
                    choices=["f32", "f64", "bf16"])
    ps.add_argument("--repeats", type=int, default=3,
                    help="timing repeats per candidate (median wins)")
    ps.add_argument("--cache", default=None, metavar="PATH")
    ps.add_argument("--force", action="store_true",
                    help="re-measure keys that already have valid entries")

    pw = sub.add_parser("show", help="print the cache's entries")
    pw.add_argument("--cache", default=None, metavar="PATH")

    pv = sub.add_parser("validate",
                        help="strict schema + traffic-gate check")
    pv.add_argument("paths", nargs="*", metavar="PATH")

    args = p.parse_args(argv)
    if args.cmd == "search":
        # argparse-level shape errors are usage errors (exit 2), and the
        # repeats knob must be sane before any measurement starts.
        try:
            parse_dims(args.shape)
        except ValueError as e:
            _log(f"tuning search: {e}")
            return 2
        if args.repeats < 1:
            _log("tuning search: --repeats must be >= 1")
            return 2
        return cmd_search(args)
    if args.cmd == "show":
        return cmd_show(args)
    return cmd_validate(args)


if __name__ == "__main__":
    sys.exit(main())
