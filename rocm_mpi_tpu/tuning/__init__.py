"""Kernel autotuning: a persistent, traffic-gated tuning cache
(docs/PERF.md "Autotuning"; ROADMAP item 2).

Every kernel configuration the stack used to hand-pick — body_form,
pad_pow2, the VMEM chunk, the masked-step stripe height tm, the
deep-halo depth k, the scan chunk q — is tunable here:

* `tuning.search` / `python -m rocm_mpi_tpu.tuning search` measures the
  admission-filtered space per key and persists traffic-gated winners;
* `tuning.resolve.resolve` is the ONE trace-time consumer every
  `config="auto"` entry point funnels through (miss = hand-picked
  defaults; resolved values travel as explicit trace-time kwargs);
* `tuning.cache` owns the versioned, atomically-written, fingerprinted
  on-disk document; `tuning.gate` rejects configs over the A_eff byte
  budget no matter how fast they timed.
"""

from rocm_mpi_tpu.tuning.keys import (  # noqa: F401
    CACHE_KIND,
    CACHE_VERSION,
    KNOWN_OPS,
    TuningKey,
    fingerprint,
    key_str,
    parse_key,
    tuning_key,
)

__all__ = [
    "CACHE_KIND",
    "CACHE_VERSION",
    "KNOWN_OPS",
    "TuningKey",
    "fingerprint",
    "key_str",
    "parse_key",
    "tuning_key",
]
