"""The autotuner search driver: measure the legal space, gate the
winner, persist it.

Per tuning key the driver enumerates the admission-filtered candidates
(tuning/space.py), **gates each through the traffic model first**
(tuning/gate.py — closed-form and free, where a doomed measurement
costs real compiles inside a bounded chip window): a config that
models over the A_eff byte budget can never win, it cannot even run.
Surviving candidates are measured with the framework's own timing
protocol — the model runners' warmup-excluded windows (compiles land in
the untimed warmup advance; metrics.Timer feeds telemetry spans),
median over `repeats` repeat runs — with compile wall attributed
separately via the PR-5 compile tracker (telemetry/compiles.py). The
fastest in-budget candidate persists into the atomic cache
(tuning/cache.py) with the jax/backend fingerprint of the measuring
process.

Measurable ops: the three VMEM-resident loops and the diffusion
deep-halo depth — the single-process-runnable subset. The other spaces
(masked_step tm, scan q) are consumable (resolve) and validatable
(gate) but need a chip/mesh harness to measure honestly; searching them
rides the chip window, not this driver.
"""

from __future__ import annotations

import statistics

from rocm_mpi_tpu.tuning import cache as _cache
from rocm_mpi_tpu.tuning import gate as _gate
from rocm_mpi_tpu.tuning import space as _space
from rocm_mpi_tpu.tuning.keys import TuningKey, fingerprint, tuning_key

MEASURABLE_OPS = (
    "diffusion.vmem_loop",
    "wave.vmem_loop",
    "swe.vmem_loop",
    "diffusion.deep",
)


def _compile_wall_s() -> float:
    from rocm_mpi_tpu.telemetry import compiles

    return sum(
        row["wall_s"] for row in compiles.snapshot()["programs"].values()
    )


def _make_runner(op: str, shape, dtype: str):
    """run(config) -> per-step seconds for one candidate invocation
    (warmup-excluded, the models' own protocol). Each runner sizes its
    windows off the candidate (chunk/k granularity divides both), so a
    256-chunk candidate is measured as a 256-chunk program, not a
    silently degraded one."""
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import (
        AcousticWave,
        HeatDiffusion,
        ShallowWater,
        SWEConfig,
        WaveConfig,
    )

    ndim = len(shape)
    common = dict(
        global_shape=tuple(shape), lengths=(10.0,) * ndim,
        dtype=dtype, dims=(1,) * ndim,
    )

    if op == "diffusion.vmem_loop":
        programs: dict = {}

        def run(config):
            c = int(config["chunk"])
            model = HeatDiffusion(
                DiffusionConfig(nt=2 * c, warmup=c, **common)
            )
            r = model.run_vmem_resident(
                chunk=c, body_form=config["body_form"],
                pad_pow2=config["pad_pow2"], program_cache=programs,
            )
            return r.wtime_it

    elif op == "wave.vmem_loop":

        def run(config):
            c = int(config["chunk"])
            model = AcousticWave(WaveConfig(nt=2 * c, warmup=c, **common))
            return model.run_vmem_resident(chunk=c).wtime_it

    elif op == "swe.vmem_loop":

        def run(config):
            c = int(config["chunk"])
            model = ShallowWater(SWEConfig(nt=2 * c, warmup=c, **common))
            return model.run_vmem_resident(chunk=c).wtime_it

    elif op == "diffusion.deep":

        def run(config):
            k = int(config["k"])
            model = HeatDiffusion(
                DiffusionConfig(nt=2 * k, warmup=k, **common)
            )
            # The wire axis: a candidate IS its (k, wire_mode) pair —
            # measuring a bf16 candidate through the f32 exchange would
            # crown winners on numbers they never produced.
            return model.run_deep(
                block_steps=k, wire_mode=config.get("wire_mode")
            ).wtime_it

    else:
        raise ValueError(
            f"op {op!r} has no single-process measurement runner "
            f"(measurable: {MEASURABLE_OPS})"
        )
    return run


def search_op(op: str, shape, dtype: str = "f32", repeats: int = 3,
              cache_path=None, force: bool = False, log=None,
              candidates=None) -> dict:
    """Search one key; returns a status dict:

        {"key": TuningKey, "status": "hit"|"empty"|"tuned"|"all-rejected",
         "entry": {...} | None, "rejected": [(config, reason), ...]}

    "hit" = a fingerprint-valid entry already exists (no measurement at
    all — the warm-cache contract); --force re-measures.
    """
    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.telemetry import compiles

    log = log or (lambda *_: None)
    key = tuning_key(op, shape, dtype)
    path = cache_path or _cache.default_cache_path()
    if not force:
        existing = _cache.lookup(
            _cache.load(path), key, fingerprint(key.backend)
        )
        if existing is not None:
            log(f"tune: {op} {key.shape_class} {key.dtype} — cache hit, "
                f"config {existing}")
            return {"key": key, "status": "hit",
                    "entry": {"config": existing}, "rejected": []}

    if candidates is None:
        candidates = _space.enumerate_space(op, shape, dtype,
                                            backend=key.backend)
    if not candidates:
        log(f"tune: {op} {key.shape_class} — nothing tunable (empty "
            "admitted space)")
        return {"key": key, "status": "empty", "entry": None,
                "rejected": []}

    # Gate FIRST: the traffic model is closed-form and free, while a
    # rejected candidate's measurement costs real compiles inside a
    # bounded chip window — a config the gate will always refuse is
    # never worth timing. Rejections are still logged/annotated loudly
    # (the teeth: a doctored fast-but-wasteful config cannot win, it
    # cannot even run).
    rejected = []
    admitted = []  # (index, config, GateResult)
    for i, config in enumerate(candidates):
        g = _gate.validate_config(op, shape, dtype, config)
        if g.ok:
            admitted.append((i, config, g))
            continue
        rejected.append((config, g.reason))
        log(f"tune: {op} REJECTED {config}: {g.reason}")
        if telemetry.enabled():
            telemetry.annotate("tune.gate_reject", op=op,
                               config=str(sorted(config.items())),
                               ratio=round(g.ratio, 4))
    if not admitted:
        log(f"tune: {op} — every candidate over the traffic budget; "
            "nothing cached")
        return {"key": key, "status": "all-rejected", "entry": None,
                "rejected": rejected}

    compiles.install()
    run = _make_runner(op, shape, dtype)
    measured = []  # (median_s, index, config, compile_s, gate)
    for i, config, g in admitted:
        wall0 = _compile_wall_s()
        with telemetry.span("tune.measure", op=op, candidate=i):
            times = [run(config) for _ in range(max(1, repeats))]
        compile_s = _compile_wall_s() - wall0
        med = statistics.median(times)
        measured.append((med, i, config, compile_s, g))
        log(f"tune: {op} {config}: {med * 1e6:.3f} us/step "
            f"(median of {max(1, repeats)}, compile {compile_s:.1f} s)")

    med, _i, config, compile_s, g = min(measured,
                                        key=lambda t: (t[0], t[1]))
    entry = {
        "config": config,
        "median_us": round(med * 1e6, 4),
        "compile_s": round(compile_s, 3),
        "gate_ratio": round(g.ratio, 4),
        "fingerprint": fingerprint(key.backend),
    }
    _cache.store(path, key, entry)
    log(f"tune: {op} winner {config} "
        f"({med * 1e6:.3f} us/step, gate {g.ratio:.2f}x) -> {path}")
    return {"key": key, "status": "tuned", "entry": entry,
            "rejected": rejected}
