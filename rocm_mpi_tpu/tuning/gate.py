"""The tuning traffic gate: a "fast" config that blows the A_eff byte
budget is rejected, no matter what it measured.

Wall-clock on a loaded box can crown a winner whose speed is an
artifact (cache luck, a straggling rival) while its compiled program
moves more HBM bytes per step than the schedule needs — exactly the
drift class the perf traffic gate (docs/PERF.md, perf/traffic.py)
polices on the distributed drivers. The tuned knobs here change traffic
*analytically* — padding inflates every pass by the padded/unpadded
ratio, a short stripe re-reads its ghost rows more often per output
row, a deep sweep trades exchange count for padded-block passes — so
the gate models each config's bytes-per-step against the (2+1)
A_eff traversal ideal in closed form and holds the ratio to a per-family
budget. Same ideals as perf/traffic.py (ideal_deep_sweep_bytes is
imported, not re-derived); no compilation, no accelerator, so the
validate CLI can run it over a committed cache from the key alone.

Budgets (measured/ideal ceilings per family):

* vmem_loop 1.5 — pad_pow2 may inflate passes by (prod padded)/(prod
  shape); 252²→256² is 1.03×, fine; a doctored 140²→256² (3.3×) fails.
* masked_step 1.5 — ratio (2 + (tm+2g)/tm)/3: the slab re-read cost of
  short stripes (tm=8 audits 1.67× and is rejected; tm>=16 passes).
* deep 6.0 — per-sweep analytic vs k·(2+1)·N; deep sweeps legitimately
  pay padded-block passes (the perf gate budgets deep at 4.4 on its CPU
  lowering for the same reason).
* scan 1.05 — the scan chunk is traffic-neutral by construction.
"""

from __future__ import annotations

from typing import NamedTuple

from rocm_mpi_tpu.tuning import space as _space
from rocm_mpi_tpu.tuning.keys import TuningKey, parse_dims

BUDGETS = {
    "vmem_loop": 1.5,
    "masked_step": 1.5,
    "deep": 6.0,
    "scan": 1.05,
}


class GateResult(NamedTuple):
    ok: bool
    ratio: float
    measured_bytes: int  # modeled bytes per step (per shard)
    ideal_bytes: int  # (2+1)-traversal bound per step
    budget: float
    reason: str  # "" when ok


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _wire_ladder() -> dict:
    """The committed wire-bytes ladder rows (perf/budgets.json "wire"),
    falling back to the code defaults when a budgets file predates the
    ladder. Cached: the gate may run per cache entry."""
    ladder = _WIRE_LADDER_CACHE.get("ladder")
    if ladder is None:
        from rocm_mpi_tpu.parallel import wire as _wire

        try:
            from rocm_mpi_tpu.perf.traffic import load_budgets

            ladder = dict(_wire.DEFAULT_LADDER)
            ladder.update(load_budgets().get("wire", {}).get("ladder", {}))
        except (OSError, ValueError):
            ladder = dict(_wire.DEFAULT_LADDER)
        _WIRE_LADDER_CACHE["ladder"] = ladder
    return ladder


_WIRE_LADDER_CACHE: dict = {}


def _validate_wire_mode(op: str, family: str, shape, config: dict,
                        budget: float, ideal: int) -> GateResult | None:
    """The wire-precision double gate on a config's `wire_mode` field
    (None = no wire field = nothing to check). A non-f32 mode is
    accepted ONLY when (a) its closed-form wire bytes land under the
    committed ladder row — fast-but-fat rejected — AND (b) the mode
    passes the tolerance contract vs the f64 host-staged oracle
    (parallel/wire.certify) — fast-but-out-of-tolerance rejected."""
    wm = config.get("wire_mode")
    if wm is None:
        return None
    from rocm_mpi_tpu.parallel import wire as _wire

    bad = lambda reason: GateResult(  # noqa: E731 — local shorthand
        False, float("inf"), 0, ideal, budget, reason
    )
    if wm not in _wire.WIRE_MODES:
        return bad(f"wire_mode={wm!r} is not one of {_wire.WIRE_MODES}")
    if family not in ("deep", "scan"):
        return bad(
            f"wire_mode is not a knob for op family {family!r} (the "
            "exchangeful families are deep/scan)"
        )
    if _wire.is_stateful(wm) and family != "deep":
        return bad(
            f"wire_mode={wm!r} carries error-feedback state; only the "
            "deep-halo schedule threads it (per-step programs are "
            "stateless)"
        )
    if wm == "f32":
        return None
    width = int(config.get("k", 1) or 1) if family == "deep" else 1
    frac = _wire.ladder_fraction(shape, width, wm)
    row = _wire_ladder().get(wm)
    if row is not None and frac > row:
        return bad(
            f"wire_mode={wm} models {frac:.3f} of the full-precision "
            f"wire vs its ladder row {row:.2f} (perf/budgets.json) — "
            "over the wire-bytes ladder, rejected"
        )
    cert = _wire.certify(wm)
    if not cert.ok:
        return bad(
            f"wire_mode={wm} fails the tolerance contract vs the f64 "
            f"host-staged oracle (rel err {cert.rel_err:.2e} > bound "
            f"{cert.bound:.2e} over {cert.steps} steps) — fast-but-"
            "out-of-tolerance, rejected"
        )
    return None


def validate_config(op: str, shape, dtype: str, config: dict,
                    budget: float | None = None) -> GateResult:
    """Model one config's per-step HBM traffic against the A_eff ideal
    and gate the ratio. `shape` is the per-shard field shape; `dtype`
    the storage dtype name from the tuning key. A `wire_mode` field is
    double-gated (_validate_wire_mode): the wire-bytes ladder AND the
    f64-oracle tolerance contract must both hold."""
    family = op.split(".", 1)[1] if "." in op else op
    if budget is None:
        budget = BUDGETS[family]
    shape = tuple(int(n) for n in shape)
    itemsize = _space.compute_itemsize(dtype)
    n = _prod(shape) * itemsize
    ideal = 3 * n  # the (2+1)-traversal bound per step

    wire_verdict = _validate_wire_mode(op, family, shape, config,
                                       budget, ideal)
    if wire_verdict is not None:
        return wire_verdict

    if family == "vmem_loop":
        # Knob validity is part of the gate's contract: the runtime
        # sanitizer (tuning/resolve.py) silently DROPS these, so the
        # validate CLI must be the loud half — a committed entry whose
        # knobs would never steer anything is a broken entry.
        c = config.get("chunk")
        if c is not None and not (
            isinstance(c, int) and not isinstance(c, bool)
            and c >= 4 and (c & (c - 1)) == 0
        ):
            return GateResult(False, float("inf"), 0, ideal, budget,
                              f"chunk={c!r} is not a power of two >= 4 "
                              "(below 4 the kernel switches body form)")
        bf = config.get("body_form")
        if bf is not None and bf not in ("eqc", "conly"):
            return GateResult(False, float("inf"), 0, ideal, budget,
                              f"body_form={bf!r} is not eqc/conly")
        if not isinstance(config.get("pad_pow2", False), bool):
            return GateResult(False, float("inf"), 0, ideal, budget,
                              "pad_pow2 is not a bool")
        # Per chunk launch: read state (+coefficients), write state —
        # each pass inflated to the padded layout when pad_pow2 is on.
        if config.get("pad_pow2"):
            np_ = _prod(_space.next_pow2_shape(shape)) * itemsize
        else:
            np_ = n
        measured = 3 * np_
    elif family == "masked_step":
        g = 8
        tm = int(config.get("tm", 0) or 0)
        if tm <= 0 or tm % g:
            return GateResult(False, float("inf"), 0, ideal, budget,
                              f"tm={config.get('tm')!r} is not a positive "
                              f"multiple of {g}")
        # Per step: slab read ((tm+2g)/tm of the field), core Cm read,
        # core write.
        measured = int(n * (tm + 2 * g) / tm) + 2 * n
    elif family == "deep":
        from rocm_mpi_tpu.perf.traffic import ideal_deep_sweep_bytes

        k = int(config.get("k", 0) or 0)
        if k < 1 or k > min(shape):
            return GateResult(False, float("inf"), 0, ideal, budget,
                              f"k={config.get('k')!r} outside [1, "
                              f"{min(shape)}]")
        measured = ideal_deep_sweep_bytes(shape, itemsize, k) // k
        ideal = 3 * n
    elif family == "scan":
        measured = 3 * n
    else:
        return GateResult(False, float("inf"), 0, ideal, budget,
                          f"no traffic model for op {op!r}")

    ratio = measured / ideal
    ok = ratio <= budget
    reason = "" if ok else (
        f"{op} config {config} models {ratio:.2f}x the A_eff ideal "
        f"(budget {budget:.2f}) — fast-but-wasteful, rejected"
    )
    return GateResult(ok, ratio, int(measured), int(ideal), budget, reason)


def validate_entry(key: TuningKey, entry: dict) -> GateResult:
    """Gate one CACHE entry from its key alone (the validate CLI / lint
    path: no side channel beyond the file)."""
    return validate_config(
        key.op, parse_dims(key.shape_class), key.dtype,
        entry.get("config", {}),
    )
