"""The tuning key — what a tuned kernel configuration is keyed by.

A winning kernel config is only transferable between runs that lower the
same program to the same hardware: the stencil-tuning literature the
autotuner is grounded in (arXiv:2406.08923 across AMD/Nvidia,
arXiv:2404.04441 across programming models) shows the winner shifts with
shape, dtype, topology and backend — so all four are part of the key,
alongside the op itself and (as a cache-entry fingerprint, not a key
field) the jax version the measurement was taken under.

    TuningKey(op, shape_class, dtype, topology, backend)

* `op`         — the tunable entry point, "workload.family" spelled
                 ("diffusion.vmem_loop", "wave.vmem_loop",
                 "diffusion.masked_step", "diffusion.deep",
                 "diffusion.scan", …).
* `shape_class`— the per-shard field shape, "252x252" spelled. Exact
                 shapes, not buckets: the admission rules (VMEM budgets,
                 stripe divisibility) are shape-exact, so a config legal
                 at one shape can crash at a neighboring one.
* `dtype`      — the STORAGE dtype short name ("f32"/"bf16"/"f64"); the
                 kernels budget at compute width internally, but storage
                 width changes admission and traffic.
* `topology`   — the mesh dims, "2x1" spelled ("1x1" = unsharded).
* `backend`    — jax.default_backend() ("tpu"/"cpu"): a CPU-searched
                 cache must never steer a chip run and vice versa.

`key_str` is the canonical on-disk spelling (the cache's entry key):
"op|shape|dtype|topology|backend" — parseable back by `parse_key`, so
the validate CLI can re-derive admission and traffic facts from the key
alone, with no side channel.

stdlib-only: the read side (CLI validate, lint schema gate) must not
need jax.
"""

from __future__ import annotations

from typing import NamedTuple

CACHE_VERSION = 1
CACHE_KIND = "rmt-tuning-cache"

# The tunable ops the space/gate/search modules know. Order is the
# canonical search order (determinism: the CLI iterates this, never a
# set).
KNOWN_OPS = (
    "diffusion.vmem_loop",
    "wave.vmem_loop",
    "swe.vmem_loop",
    "diffusion.masked_step",
    "diffusion.deep",
    "diffusion.scan",
    "wave.scan",
    "swe.scan",
)

_DTYPE_NAMES = {
    "float32": "f32", "float64": "f64", "bfloat16": "bf16",
    "f32": "f32", "f64": "f64", "bf16": "bf16",
}


class TuningKey(NamedTuple):
    op: str
    shape_class: str
    dtype: str
    topology: str
    backend: str


def dtype_name(dtype) -> str:
    """Canonical short dtype spelling from a dtype name, a numpy/jax
    dtype instance, or a scalar type class (config.jax_dtype is
    `jnp.float32` the CLASS — np.dtype normalizes all of them)."""
    if isinstance(dtype, str):
        name = dtype
    else:
        import numpy as np

        name = np.dtype(dtype).name
    try:
        return _DTYPE_NAMES[name]
    except KeyError:
        raise ValueError(f"unsupported tuning dtype {name!r}") from None


def shape_class(shape) -> str:
    return "x".join(str(int(n)) for n in shape)


def topology_class(dims) -> str:
    if isinstance(dims, str):
        return dims
    return "x".join(str(int(d)) for d in dims)


def parse_dims(cls: str) -> tuple[int, ...]:
    """Inverse of shape_class/topology_class ("252x252" -> (252, 252))."""
    try:
        dims = tuple(int(p) for p in cls.split("x"))
    except ValueError:
        raise ValueError(f"malformed shape/topology class {cls!r}") from None
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"malformed shape/topology class {cls!r}")
    return dims


def tuning_key(op: str, shape, dtype, topology=None,
               backend: str | None = None) -> TuningKey:
    """Build the key for one tunable call site. `topology=None` means
    unsharded — (1,)*ndim, matching the shape's rank so 2D and 3D
    single-shard keys cannot collide. `backend=None` reads the live jax
    backend (the one place this module touches jax — read-side callers
    always pass it)."""
    if op not in KNOWN_OPS:
        raise ValueError(f"unknown tunable op {op!r}; known: {KNOWN_OPS}")
    shape = tuple(int(n) for n in shape)
    if topology is None:
        topology = (1,) * len(shape)
    if backend is None:
        import jax

        backend = jax.default_backend()
    return TuningKey(
        op=op,
        shape_class=shape_class(shape),
        dtype=dtype_name(dtype),
        topology=topology_class(topology),
        backend=str(backend),
    )


def key_str(key: TuningKey) -> str:
    return "|".join(key)


def parse_key(s: str) -> TuningKey:
    """Parse the on-disk spelling; raises ValueError on malformation
    (the schema gate's contract — a drifted key must fail loudly)."""
    parts = s.split("|")
    if len(parts) != 5 or not all(parts):
        raise ValueError(f"malformed tuning key {s!r} (want 5 '|' fields)")
    key = TuningKey(*parts)
    if key.op not in KNOWN_OPS:
        raise ValueError(f"unknown tunable op in key {s!r}")
    parse_dims(key.shape_class)
    parse_dims(key.topology)
    return key


def fingerprint(backend: str | None = None) -> dict:
    """The cache-entry fingerprint: which jax lowered the measured
    programs. Backend rides along explicitly (redundant with the key,
    but an entry must be self-describing for the stale check)."""
    import jax

    return {
        "jax": jax.__version__,
        "backend": str(backend if backend is not None
                       else jax.default_backend()),
    }
