"""The one trace-time consumer of the tuning cache.

Every `config="auto"` path in the stack funnels through `resolve()`:
build the tuning key for the call site, look it up in the process's
cache snapshot, and return the winning config dict — or None, meaning
"use the hand-picked defaults" (the miss contract: auto is never worse
than the defaults, only sometimes better). The resolved values are then
passed onward as the explicit trace-time kwargs PR 1 established; this
module never mutates kernel-module state (GL02's whole point), and its
own bookkeeping is a read-once document snapshot plus hit/miss
counters, both held in one module dict (no `global` writes — resolve()
runs inside jit traces).

The cache document is read ONCE per process (first resolve) and cached:
a trace-time file read per program is tolerable, one per *call* is not,
and a mid-run cache rewrite changing live programs silently would be
exactly the stale-global hazard GL02 exists for. Tests and the search
CLI use `refresh()` / `configure(path=…)` to swap snapshots explicitly.
"""

from __future__ import annotations

import json

from rocm_mpi_tpu.tuning import cache as _cache
from rocm_mpi_tpu.tuning import keys as _keys

# The one mutable cell: doc snapshot + explicit path override + counters.
# Dict item writes need no `global` statement and resolve() may legally
# run inside a traced body (it only reads the snapshot).
_STATE: dict = {
    "doc": None,  # loaded cache document (None = not loaded yet)
    "path": None,  # explicit override (configure/tests); None = default
    "hits": 0,
    "misses": 0,
}


def configure(path) -> None:
    """Point this process at an explicit cache file (tests, the search
    CLI's --cache); drops the current snapshot."""
    _STATE["path"] = str(path) if path is not None else None
    _STATE["doc"] = None


def refresh() -> None:
    """Drop the snapshot; the next resolve() re-reads the file."""
    _STATE["doc"] = None


def cache_path() -> str:
    return _STATE["path"] or _cache.default_cache_path()


def _doc() -> dict:
    doc = _STATE["doc"]
    if doc is None:
        doc = _cache.load(cache_path())
        _STATE["doc"] = doc
    return doc


def _valid_wire_mode(v) -> bool:
    from rocm_mpi_tpu.parallel.wire import WIRE_MODES

    return v in WIRE_MODES


# Per-knob validity at the consumption seam: a cache entry is UNTRUSTED
# input (hand-edited, written by a future version, doctored) and the
# miss contract says auto is never worse than the defaults — so a field
# that would crash a kernel (chunk=-8, body_form="bogus") is DROPPED
# here, not propagated to a trace-time ValueError. These are the
# crash-safety bounds only; op-family rules with numerics consequences
# (e.g. vmem chunks must stay >= 4 to keep one kernel body form) live
# with the consumers and the traffic gate.
_FIELD_VALID = {
    "chunk": lambda v: isinstance(v, int) and not isinstance(v, bool)
    and v >= 1,
    "body_form": lambda v: v in ("eqc", "conly"),
    "pad_pow2": lambda v: isinstance(v, bool),
    "tm": lambda v: isinstance(v, int) and not isinstance(v, bool)
    and v >= 8 and v % 8 == 0,
    "k": lambda v: isinstance(v, int) and not isinstance(v, bool)
    and v >= 1,
    # Crash-safety only, like every row here: an unknown mode would be a
    # trace-time ValueError out of the exchange; the gate/validate CLI
    # is the loud half that rejects an uncertified or over-ladder one.
    "wire_mode": _valid_wire_mode,
}


def _sanitize(config: dict) -> dict:
    """Drop unknown/invalid fields from a looked-up config (an all-
    invalid entry degrades to {} — falsy, i.e. a miss to every consumer)."""
    return {
        k: v for k, v in config.items()
        if k in _FIELD_VALID and _FIELD_VALID[k](v)
    }


def resolve(op: str, shape, dtype, topology=None,
            backend: str | None = None) -> dict | None:
    """The chokepoint: winning config for this call site, or None on any
    miss (unknown key, stale jax/backend fingerprint, unreadable cache).
    Looked-up configs are sanitized field-by-field (_FIELD_VALID) so a
    malformed entry can never crash an auto run. Emits one
    `tune.resolve` trace annotation per distinct outcome and counts
    hits/misses for the run gauges (stats())."""
    key = _keys.tuning_key(op, shape, dtype, topology, backend)
    config = _cache.lookup(_doc(), key, _keys.fingerprint(key.backend))
    if config is not None:
        config = _sanitize(config)
    hit = bool(config)
    if not hit:
        config = None
    _STATE["hits" if hit else "misses"] += 1

    from rocm_mpi_tpu import telemetry

    if telemetry.enabled():
        telemetry.annotate(
            "tune.resolve",
            key=_keys.key_str(key),
            hit=hit,
            config=json.dumps(config, sort_keys=True) if hit else "",
        )
    return config


def stats() -> dict:
    """Process-cumulative resolve outcomes: {"hits": n, "misses": n}."""
    return {"hits": _STATE["hits"], "misses": _STATE["misses"]}


def reset_stats() -> None:
    _STATE["hits"] = 0
    _STATE["misses"] = 0


def emit_gauges() -> None:
    """Bank the resolve outcomes as `tune.hits` / `tune.misses` run
    gauges (no-op when telemetry is off or nothing was resolved) — the
    hook bench.py --suite and weak_scaling --autotune call at run end so
    `telemetry regress` can gate tuned-vs-default summaries."""
    from rocm_mpi_tpu import telemetry

    if not telemetry.enabled():
        return
    s = stats()
    if not (s["hits"] or s["misses"]):
        return
    telemetry.gauge("tune.hits", s["hits"])
    telemetry.gauge("tune.misses", s["misses"])
