"""Legal config-space enumeration, admission-filtered.

One function, `enumerate_space(op, shape, dtype)`, returns the ordered
candidate list the search driver measures. Admission reuses the SAME
footprint rules the kernels enforce at trace time
(ops/pallas_kernels.py): the VMEM-resident budget at the f32 compute
width, the 256 KB unroll-friendly chunk cap, the striped slab compile
envelope, the wave/SWE operand-count multipliers — so a candidate the
search would measure can never be one the kernel would refuse to trace.
Order is canonical (defaults first, then ascending knob values): the
search's tie-break is "earlier candidate wins", which keeps a re-search
deterministic when two configs measure within noise of each other.

The knobs per op family (ISSUE 7 / ROADMAP item 2):

* `*.vmem_loop`    — scan chunk `q` per kernel launch; diffusion adds
                     `body_form` (eqc/conly) and `pad_pow2`. Chunks stay
                     >= 4 on purpose: 1..3 switch the kernel to the
                     direct (non-A/c) body, a DIFFERENT fp expression —
                     the tuned space must stay bitwise-equal to the
                     defaults (the config="auto" contract).
* `diffusion.masked_step` — the stripe height `tm` (the threads=(32,8)
                     analog) for HBM-class fields.
* `diffusion.deep` — the sweep depth `k` (exchange every k steps), and
                     the state exchange's `wire_mode` (the PR-12 wire-
                     precision plane, parallel/wire.py) — the deep sweep
                     is the one schedule every mode supports, stateful
                     int8/delta included. Default-precision candidates
                     enumerate first so the tie-break keeps f32 when a
                     cheaper wire buys nothing.
* `*.scan`         — the scan drivers' static chunk `q`.
"""

from __future__ import annotations

_CHUNKS = (16, 64, 256)
_SCAN_CHUNKS = (16, 64, 256)
_DEEP_KS = (4, 8, 16, 32)


def _kernel_budgets():
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _PS_SLAB_BUDGET_BYTES,
        _VMEM_BLOCK_BUDGET_BYTES,
    )

    return _VMEM_BLOCK_BUDGET_BYTES, _PS_SLAB_BUDGET_BYTES


def compute_itemsize(dtype_name: str) -> int:
    """Storage-only-bf16 compute width from the key's dtype spelling —
    the stdlib twin of ops.pallas_kernels._compute_itemsize (one rule:
    budget at >= f32 width)."""
    storage = {"f32": 4, "f64": 8, "bf16": 2}
    try:
        return max(storage[dtype_name], 4)
    except KeyError:
        raise ValueError(f"unsupported tuning dtype {dtype_name!r}") from None


def next_pow2_shape(shape) -> tuple[int, ...]:
    return tuple(1 << (int(n) - 1).bit_length() for n in shape)


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def enumerate_space(op: str, shape, dtype: str,
                    backend: str | None = None) -> list[dict]:
    """Ordered legal candidates for `op` at per-shard `shape` /
    storage-dtype name. Empty list = nothing tunable at this point
    (e.g. a masked_step shape the VMEM loop serves anyway).

    `backend` tightens admission where the compile envelope is
    backend-specific: on "cpu" the multi-step kernels run in the Pallas
    interpreter, whose trace cost scales with the unroll — a chunk-256
    candidate takes minutes to TRACE there, so CPU spaces cap the chunk
    at 16 (the chip search measures the real chunk ladder; a CPU-keyed
    entry never steers a chip run anyway)."""
    vmem_budget, slab_budget = _kernel_budgets()
    shape = tuple(int(n) for n in shape)
    itemsize = compute_itemsize(dtype)
    nbytes = _prod(shape) * itemsize
    family = op.split(".", 1)[1] if "." in op else op

    if family == "vmem_loop":
        admitted_bytes = {
            "diffusion.vmem_loop": vmem_budget,
            # The wave kernel holds the state pair + M + Cw; SWE holds
            # 2(ndim+1) state + ndim masks (the kernels' own admission).
            "wave.vmem_loop": vmem_budget // 2,
            "swe.vmem_loop": vmem_budget // (3 * len(shape) + 2),
        }[op]
        if nbytes > admitted_bytes:
            return []
        chunks = [c for c in _CHUNKS if nbytes <= 256 * 1024 or c <= 16]
        if backend == "cpu":
            chunks = [c for c in chunks if c <= 16]
        if op != "diffusion.vmem_loop":
            return [{"chunk": c} for c in chunks]
        out = []
        padded = next_pow2_shape(shape)
        pad_ok = (
            padded != shape
            and _prod(padded) * itemsize <= vmem_budget
        )
        for form in ("eqc", "conly"):
            for pad in (False, True) if pad_ok else (False,):
                for c in chunks:
                    out.append(
                        {"body_form": form, "pad_pow2": pad, "chunk": c}
                    )
        return out

    if family == "masked_step":
        if nbytes <= vmem_budget:
            return []  # the VMEM loop serves it; tm never dispatches
        g = 8
        n0 = shape[0]
        row = _prod(shape[1:]) * itemsize
        out = []
        for tm in range(g, 129, g):
            if n0 % tm or (n0 // tm) < 2:
                continue
            if (tm + 2 * g) * row > slab_budget:
                continue
            out.append({"tm": tm})
        return out

    if family == "deep":
        from rocm_mpi_tpu.parallel.wire import WIRE_MODES

        # wire_mode outer, k inner, f32 first: the search's "earlier
        # candidate wins" tie-break must prefer full precision at equal
        # speed, and within a mode the shallower sweep.
        return [
            {"k": k, "wire_mode": wm}
            for wm in WIRE_MODES
            for k in _DEEP_KS if k <= min(shape)
        ]

    if family == "scan":
        return [{"chunk": q} for q in _SCAN_CHUNKS]

    raise ValueError(f"no config space for op {op!r}")
