"""The persistent tuning cache: versioned JSON, atomically written.

One document per cache file:

    {"v": 1,
     "kind": "rmt-tuning-cache",
     "entries": {
       "diffusion.vmem_loop|252x252|f32|1x1|tpu": {
         "config":      {"body_form": "conly", "pad_pow2": true,
                         "chunk": 256},
         "median_us":   0.39,        # per-step, warmup excluded
         "compile_s":   12.1,        # attributed separately, never timed
         "gate_ratio":  1.03,        # modeled/ideal A_eff at admission
         "fingerprint": {"jax": "0.4.37", "backend": "tpu"}
       }, …}}

Contracts (tests/test_tuning.py pins each):

* **Atomic writes** — tmp + os.replace, so a killed search can never
  leave a torn file that bricks every later trace-time lookup.
* **Torn/alien files read as empty** — a cache is an accelerator, not a
  dependency: any parse problem degrades to "miss everywhere" with one
  warning, never an exception out of a trace.
* **Stale fingerprints are ignored, never deleted** — an entry measured
  under a different jax (or recorded for a different backend than its
  key says) is a miss; the bytes stay on disk so a rollback to the old
  pin finds its winners again.

stdlib-only on purpose: the validate CLI and lint schema gate run
without jax.
"""

from __future__ import annotations

import json
import os
import pathlib
import warnings

from rocm_mpi_tpu.tuning.keys import (
    CACHE_KIND,
    CACHE_VERSION,
    TuningKey,
    key_str,
    parse_key,
)

ENV_CACHE_PATH = "RMT_TUNING_CACHE"

# Cache-entry value fields and their types (schema closed on purpose:
# the validate gate must reject drifted writers loudly).
_ENTRY_FIELDS = {
    "config": dict,
    "median_us": (int, float),
    "compile_s": (int, float),
    "gate_ratio": (int, float),
    "fingerprint": dict,
}


def default_cache_path() -> str:
    """RMT_TUNING_CACHE, else <repo>/output/tuning/cache.json — next to
    the other runtime artifacts the lint gate schema-checks."""
    env = os.environ.get(ENV_CACHE_PATH)
    if env:
        return env
    root = pathlib.Path(__file__).resolve().parents[2]
    return str(root / "output" / "tuning" / "cache.json")


def empty_doc() -> dict:
    return {"v": CACHE_VERSION, "kind": CACHE_KIND, "entries": {}}


def load(path=None) -> dict:
    """Read a cache document, degrading every failure mode to an empty
    cache: missing file (the normal cold start), torn/garbage JSON, or a
    well-formed file of the wrong kind/version. Never raises."""
    path = path or default_cache_path()
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return empty_doc()
    except (OSError, ValueError) as e:
        warnings.warn(
            f"tuning cache {path} unreadable ({e}); treating as empty — "
            "every lookup is a miss until it is rewritten",
            stacklevel=2,
        )
        return empty_doc()
    if (
        not isinstance(doc, dict)
        or doc.get("kind") != CACHE_KIND
        or doc.get("v") != CACHE_VERSION
        or not isinstance(doc.get("entries"), dict)
    ):
        warnings.warn(
            f"tuning cache {path} is not a v{CACHE_VERSION} {CACHE_KIND} "
            "document; treating as empty",
            stacklevel=2,
        )
        return empty_doc()
    return doc


def lookup(doc: dict, key: TuningKey, fingerprint: dict) -> dict | None:
    """The entry's config for `key`, or None — on a missing key, a
    malformed entry, or a stale fingerprint (jax/backend drift). Stale
    entries are left in place by design."""
    entry = doc.get("entries", {}).get(key_str(key))
    if not isinstance(entry, dict):
        return None
    config = entry.get("config")
    fp = entry.get("fingerprint")
    if not isinstance(config, dict) or not isinstance(fp, dict):
        return None
    if fp.get("jax") != fingerprint.get("jax"):
        return None
    if fp.get("backend") != fingerprint.get("backend"):
        return None
    return dict(config)


def store(path, key: TuningKey, entry: dict) -> None:
    """Insert/replace one entry and rewrite the file atomically
    (read-modify-write; sorted keys and stable formatting so identical
    content is byte-identical — the determinism the acceptance drill
    diffs)."""
    path = str(path or default_cache_path())
    doc = load(path)
    doc["entries"][key_str(key)] = entry
    write_doc(path, doc)


def write_doc(path, doc: dict) -> None:
    path = str(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def validate_doc(doc, path: str = "<doc>") -> list[str]:
    """Schema problems of one cache document (empty list = valid). The
    shared checker of the validate CLI verb and scripts/lint.sh — a
    drifted writer must fail the gate, not silently miss forever."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"{path}: not a JSON object"]
    if doc.get("kind") != CACHE_KIND:
        problems.append(f"{path}: kind != {CACHE_KIND!r}")
    if doc.get("v") != CACHE_VERSION:
        problems.append(f"{path}: v != {CACHE_VERSION}")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return problems + [f"{path}: entries is not an object"]
    for raw_key, entry in sorted(entries.items()):
        where = f"{path}: entry {raw_key!r}"
        try:
            parse_key(raw_key)
        except ValueError as e:
            problems.append(f"{where}: {e}")
            continue
        if not isinstance(entry, dict):
            problems.append(f"{where}: not an object")
            continue
        for field, types in _ENTRY_FIELDS.items():
            if field not in entry:
                problems.append(f"{where}: missing {field!r}")
            elif not isinstance(entry[field], types):
                problems.append(f"{where}: {field!r} has wrong type")
        fp = entry.get("fingerprint")
        if isinstance(fp, dict) and not (
            isinstance(fp.get("jax"), str)
            and isinstance(fp.get("backend"), str)
        ):
            problems.append(f"{where}: fingerprint needs jax+backend strings")
        cfg = entry.get("config")
        if isinstance(cfg, dict):
            for ck, cv in cfg.items():
                if not isinstance(ck, str) or not isinstance(
                    cv, (str, int, float, bool, type(None))
                ):
                    problems.append(
                        f"{where}: config field {ck!r} is not a scalar"
                    )
    return problems
