"""rocm_mpi_tpu — a TPU-native distributed stencil framework.

A brand-new framework (JAX / XLA / Pallas / shard_map) with the capabilities
of the reference ROCm-aware-MPI diffusion suite (williamfgc/ROCm-MPI):
cartesian domain decomposition over a device mesh, halo exchange via XLA
collectives riding the ICI, Pallas stencil kernels, and a
communication/computation-overlap step — demonstrated on 2D/3D transient heat
diffusion at four escalating performance levels, plus a second workload
(models.wave: leapfrog acoustic wave) proving the layers are
workload-agnostic.

Layer map (TPU-native analog of reference SURVEY.md §1):
  L1 launch/env     -> scripts/run.sh + jax.distributed      (ref: runme.sh/setenv.sh)
  L2 device compute -> jax.numpy + Pallas kernels            (ref: AMDGPU.jl @roc)
  L3 communication  -> XLA collectives (ppermute) over ICI   (ref: ROCm-aware MPI)
  L4 global grid    -> rocm_mpi_tpu.parallel.mesh/halo       (ref: ImplicitGlobalGrid.jl)
  L5 visualization  -> rocm_mpi_tpu.utils.viz (matplotlib)   (ref: Plots.jl/GR)
  L6 apps           -> apps/diffusion_2d_*.py                (ref: scripts/diffusion_2D_*.jl)

Cross-cutting: rocm_mpi_tpu.telemetry (spans/events/trace/regress —
docs/TELEMETRY.md, the reference's tic/toc+T_eff printout grown into a
subsystem) and rocm_mpi_tpu.analysis (graftlint, docs/ANALYSIS.md).
"""

__version__ = "0.1.0"

from rocm_mpi_tpu import parallel, ops, models, telemetry, utils  # noqa: F401
