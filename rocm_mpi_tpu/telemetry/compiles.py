"""Compile observability: count compilations and cache misses per
program, and pin "zero recompiles after warmup".

Why this is a health-plane concern: the framework's perf story (PR 4's
donation-aware scan drivers, the fused halo/interior step) assumes each
steady-state program compiles ONCE. A shape change, a dtype drift, or a
non-hashable static arg quietly re-triggers XLA per step instead — a
recompile storm that looks like "the run got slow" and, on the flapping
chip tunnel, like "the run hung". Nothing in the PR-3 stream recorded
compiles at all, so the storm was invisible.

The hook rides the `utils/compat.install_compile_listener` chokepoint
(jax-version drift owned there, not here): every completed
trace/lower/backend-compile interval lands in `record_interval`, every
persistent-cache hit/miss point event in `record_cache_event`. Backend
compiles are the ones that cost real wall time, so they are what the
per-program table, the `compile.backend` telemetry spans, and the
steady-state gauge count.

Steady state: `mark_steady()` draws the line after an app's warmup (and
after any deliberately-compiled probe/heartbeat programs). Every backend
compile after the mark is a RECOMPILE — `steady_state()` returns the
count, `emit_gauges()` banks it as the `compiles.steady_state` gauge,
and the regress gate treats `compiles.*` gauges as lower-is-better with
a meaningful zero (telemetry/regress.py), so a committed baseline of 0
makes any steady-state recompile a gated regression.

jax is imported only inside `install()`; everything else is stdlib, so
the read-side CLI can import this module's constants freely.
"""

from __future__ import annotations

import threading
import time

from rocm_mpi_tpu.telemetry import events
from rocm_mpi_tpu.telemetry.spans import span_record

_LOCK = threading.Lock()
_MODE: str | None = None
_PROGRAMS: dict[str, dict] = {}   # name -> {"count", "wall_s", "steady"}
_TOTALS = {"backend_compiles": 0, "cache_hits": 0, "cache_misses": 0}
_STEADY_MARKED = False
_STEADY_EVER = False
_STEADY_RECOMPILES = 0


def install() -> str | None:
    """Install the compile listener (idempotent; returns the mode —
    "named" per-program, "events" totals-only, None unavailable). Safe
    to call whether or not telemetry collection is on: recording is a
    counter bump; the telemetry span is emitted only when enabled."""
    global _MODE
    if _MODE is not None:
        return _MODE
    from rocm_mpi_tpu.utils.compat import install_compile_listener

    _MODE = install_compile_listener(record_interval, record_cache_event)
    return _MODE


def record_interval(event: str, name: str | None, dur_s: float) -> None:
    """One completed compile-pipeline interval (the compat hook's
    callback; also the test seam — no jax needed to drive it)."""
    if not isinstance(event, str) or not event.endswith(
        "backend_compile_duration"
    ):
        return
    global _STEADY_RECOMPILES
    prog = name or "<unnamed>"
    with _LOCK:
        row = _PROGRAMS.setdefault(
            prog, {"count": 0, "wall_s": 0.0, "steady": 0}
        )
        row["count"] += 1
        row["wall_s"] += float(dur_s)
        _TOTALS["backend_compiles"] += 1
        steady = _STEADY_MARKED
        if steady:
            row["steady"] += 1
            _STEADY_RECOMPILES += 1
    if events.enabled():
        span_record(
            "compile.backend", time.time() - dur_s, dur_s,
            phase="compile", program=prog, steady=steady,
        )


def record_cache_event(event: str) -> None:
    if not isinstance(event, str):
        return
    with _LOCK:
        if event.endswith("/cache_hits"):
            _TOTALS["cache_hits"] += 1
        elif event.endswith("/cache_misses"):
            _TOTALS["cache_misses"] += 1


def mark_steady() -> None:
    """Open a steady-state window: every backend compile until
    `unmark_steady()` is a recompile the steady-state gauge (and the
    regress gate) counts. A weak-scaling ladder opens one window per
    rung's timed loop — each rung's warmup/mesh compiles are legitimate
    and happen OUTSIDE the windows; the count accumulates across them."""
    global _STEADY_MARKED, _STEADY_EVER
    with _LOCK:
        _STEADY_MARKED = True
        _STEADY_EVER = True


def unmark_steady() -> None:
    """Close the current steady-state window (rung boundary)."""
    global _STEADY_MARKED
    with _LOCK:
        _STEADY_MARKED = False


def steady_marked() -> bool:
    return _STEADY_MARKED


def steady_state() -> int:
    """Backend compiles since mark_steady() — the "recompiles after
    warmup" number; 0 is the healthy steady state."""
    return _STEADY_RECOMPILES


def snapshot() -> dict:
    """The full compile accounting (monitor/test surface)."""
    with _LOCK:
        return {
            "mode": _MODE,
            "programs": {k: dict(v) for k, v in _PROGRAMS.items()},
            "totals": dict(_TOTALS),
            "steady_marked": _STEADY_MARKED,
            "steady_ever_marked": _STEADY_EVER,
            "steady_recompiles": _STEADY_RECOMPILES,
        }


def emit_gauges() -> None:
    """Bank the compile accounting into the telemetry stream. Call at
    the end of the measured window, BEFORE any deliberately-compiled
    epilogue (phase probes): their compiles are paid-for tooling, not
    steady-state recompiles. `compiles.steady_state` is only emitted
    once mark_steady() ran — an unmarked run has no warmup line and a
    fake 0 would green-gate it."""
    if not events.enabled():
        return
    with _LOCK:
        total = _TOTALS["backend_compiles"]
        misses = _TOTALS["cache_misses"]
        ever_marked = _STEADY_EVER
        steady = _STEADY_RECOMPILES
        per_program = {k: v["count"] for k, v in _PROGRAMS.items()}
    if _MODE is None and not total and not misses:
        # No listener ever installed and nothing recorded: these zeros
        # would be fabrication, not measurement — a recompile storm in
        # such a run would read as a green steady_state baseline.
        return
    events.gauge("compiles.total", total)
    events.gauge("compiles.cache_misses", misses)
    if ever_marked:
        events.gauge("compiles.steady_state", steady)
    for prog, count in sorted(per_program.items()):
        events.annotate("compiles.program", program=prog, count=count)


def reset() -> None:
    """Test isolation: drop the accounting (the installed hook stays —
    uninstalling a process-wide tap mid-run would lose compiles)."""
    global _STEADY_MARKED, _STEADY_EVER, _STEADY_RECOMPILES
    with _LOCK:
        _PROGRAMS.clear()
        _TOTALS.update(backend_compiles=0, cache_hits=0, cache_misses=0)
        _STEADY_MARKED = False
        _STEADY_EVER = False
        _STEADY_RECOMPILES = 0
