"""Chrome trace-event export: one track per rank, openable in Perfetto.

Replaces the raw `docs/prof_trace_hide8192_r3`-style dumps with the
standard trace-event JSON every Chrome/Perfetto build renders
(https://ui.perfetto.dev, chrome://tracing). Mapping:

* span   -> complete slice  (ph "X"): pid = rank, tid = recording thread,
            ts/dur in microseconds; Perfetto nests slices on a track by
            containment, which the per-thread span stack guarantees.
* counter-> counter sample  (ph "C") on the rank's track.
* gauge  -> counter sample  (ph "C") — a gauge is a one-point counter.
* event  -> instant         (ph "i", scope "p"): retries/restores show as
            pins on the rank that emitted them.
* trace  -> process metadata: static per-program facts (bytes per halo
            exchange) land in the rank's metadata args, not on the
            timeline (they have no duration).

Cross-rank alignment uses the records' WALL timestamps (`t`): each
process's monotonic origin is arbitrary, so `t_mono` orders within a
rank but cannot place ranks against each other. The trace origin is the
earliest wall stamp across all ranks; NTP-grade skew between ranks on
one host (the launcher case) is microseconds — fine for eyeballing halo
waits. Durations come from `dur_s` (monotonic-derived), so slice widths
never inherit wall-clock jumps. stdlib-only, like the whole read side.
"""

from __future__ import annotations

import pathlib

TRACE_REQUIRED_KEYS = ("name", "ph", "ts", "pid")


def to_chrome_trace(streams: dict[int, list[dict]]) -> dict:
    """Build the trace-event document from per-rank record streams
    (aggregate.load_rank_streams shape)."""
    all_recs = [r for recs in streams.values() for r in recs]
    wall_stamps = [r["t"] for r in all_recs if isinstance(r.get("t"),
                                                          (int, float))]
    origin = min(wall_stamps) if wall_stamps else 0.0

    events: list[dict] = []
    for rk in sorted(streams):
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": rk,
            "ts": 0,
            "args": {"name": f"rank {rk}"},
        })
        for rec in streams[rk]:
            kind = rec.get("kind")
            t = rec.get("t")
            if not isinstance(t, (int, float)):
                continue
            ts = (t - origin) * 1e6
            attrs = rec.get("attrs") or {}
            if kind == "span":
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "X",
                    "ts": ts,
                    "dur": max(float(rec.get("dur_s", 0.0)) * 1e6, 0.0),
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": attrs,
                })
            elif kind in ("counter", "gauge"):
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "C",
                    "ts": ts,
                    "pid": rk,
                    "args": {rec.get("name", "?"): rec.get("value", 0)},
                })
            elif kind == "event":
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "i",
                    "s": "p",
                    "ts": ts,
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": {
                        k: v for k, v in rec.items()
                        if k in ("attempt", "step", "wait_s", "error")
                    },
                })
            elif kind == "trace":
                events.append({
                    "name": f"traced:{rec.get('name', '?')}",
                    "ph": "M",
                    "pid": rk,
                    "ts": 0,
                    "args": attrs,
                })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "rocm_mpi_tpu.telemetry"},
    }


def write_chrome_trace(streams: dict[int, list[dict]], path) -> dict:
    """Export `streams` as trace-event JSON at `path`; returns the doc."""
    from rocm_mpi_tpu.telemetry.aggregate import write_json_atomic

    doc = to_chrome_trace(streams)
    write_json_atomic(pathlib.Path(path), doc, indent=None)
    return doc
