"""Chrome trace-event export: one track per rank, openable in Perfetto.

Replaces the raw `docs/prof_trace_hide8192_r3`-style dumps with the
standard trace-event JSON every Chrome/Perfetto build renders
(https://ui.perfetto.dev, chrome://tracing). Mapping:

* span   -> complete slice  (ph "X"): pid = rank, tid = recording thread,
            ts/dur in microseconds; Perfetto nests slices on a track by
            containment, which the per-thread span stack guarantees.
* counter-> counter sample  (ph "C") on the rank's track.
* gauge  -> counter sample  (ph "C") — a gauge is a one-point counter.
* event  -> instant         (ph "i", scope "p"): retries/restores show as
            pins on the rank that emitted them.
* trace  -> process metadata: static per-program facts (bytes per halo
            exchange) land in the rank's metadata args, not on the
            timeline (they have no duration).

Health-plane inputs (optional — the post-mortem bundle's merged
timeline, telemetry/health.py):

* heartbeat sidecars -> one counter track per rank: each heartbeat's
  progress counters become a "progress" counter sample (ph "C") at the
  heartbeat's wall stamp, so the stalled rank's flat-lining step counter
  is visible right on its track.
* watchdog verdicts  -> one global instant (ph "i", scope "g") each,
  pinned to the flagged rank's track and carrying the verdict args —
  the first thing an operator should see when the trace opens.

Events are emitted sorted by ts (metadata first): Perfetto tolerates
unsorted input, but the post-mortem reader (and the tests) treat the
file as a timeline and must not have to re-sort it.

Cross-rank alignment (the PR-20 fix): a stream that carries a
`clock.anchor` record (telemetry/tracing.py — every `configure()`d rank
does) is positioned on the anchor-mapped clock, `anchor_t + (t_mono -
anchor_t_mono)`: tear-free WITHIN the rank (monotonic) and comparable
ACROSS fleet replicas (one wall read per process, not one per record).
Anchor-less legacy streams fall back to per-record wall stamps — their
records may misalign against anchored ranks, so the export WARNS about
them (`otherData.warnings`) instead of silently interleaving two clock
disciplines. The trace origin is the earliest aligned stamp across all
ranks. Durations come from `dur_s` (monotonic-derived), so slice widths
never inherit wall-clock jumps. stdlib-only, like the whole read side.
"""

from __future__ import annotations

import pathlib

from rocm_mpi_tpu.telemetry import tracing as _tracing

TRACE_REQUIRED_KEYS = ("name", "ph", "ts", "pid")


def to_chrome_trace(streams: dict[int, list[dict]],
                    heartbeats: dict[int, dict] | None = None,
                    verdicts: list[dict] | None = None) -> dict:
    """Build the trace-event document from per-rank record streams
    (aggregate.load_rank_streams shape), optionally merged with health
    sidecars and watchdog verdicts (module docstring)."""
    anchors = {rk: _tracing.anchor_of(recs)
               for rk, recs in streams.items()}
    warnings: list[str] = []
    if any(a is not None for a in anchors.values()):
        for rk in sorted(streams):
            if anchors[rk] is None and streams[rk]:
                warnings.append(
                    f"rank {rk} stream has no clock.anchor record "
                    "(legacy): its events are placed by per-record "
                    "wall stamps and may misalign against anchored "
                    "ranks"
                )
    elif len(streams) > 1:
        warnings.append(
            "no stream carries a clock.anchor record: cross-rank "
            "alignment falls back to per-record wall stamps"
        )
    wall_stamps = [
        w
        for rk, recs in streams.items()
        for w in (_tracing.aligned_wall(r, anchors[rk]) for r in recs)
        if w is not None
    ]
    for doc in (heartbeats or {}).values():
        if isinstance(doc.get("t"), (int, float)):
            wall_stamps.append(doc["t"])
    origin = min(wall_stamps) if wall_stamps else 0.0

    events: list[dict] = []
    ranks = sorted(set(streams) | set(heartbeats or {}))
    for rk in ranks:
        if rk in streams:
            continue
        events.append({
            "name": "process_name", "ph": "M", "pid": rk, "ts": 0,
            "args": {"name": f"rank {rk}"},
        })
    for rk in sorted(streams):
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": rk,
            "ts": 0,
            "args": {"name": f"rank {rk}"},
        })
        for rec in streams[rk]:
            kind = rec.get("kind")
            if kind == _tracing.ANCHOR_KIND:
                continue  # alignment machinery, not a timeline event
            t = _tracing.aligned_wall(rec, anchors.get(rk))
            if t is None:
                continue
            ts = (t - origin) * 1e6
            attrs = rec.get("attrs") or {}
            if kind == "span":
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "X",
                    "ts": ts,
                    "dur": max(float(rec.get("dur_s", 0.0)) * 1e6, 0.0),
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": attrs,
                })
            elif kind in ("counter", "gauge"):
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "C",
                    "ts": ts,
                    "pid": rk,
                    "args": {rec.get("name", "?"): rec.get("value", 0)},
                })
            elif kind == "event":
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "i",
                    "s": "p",
                    "ts": ts,
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": {
                        k: v for k, v in rec.items()
                        if k in ("attempt", "step", "wait_s", "error")
                    },
                })
            elif kind == _tracing.TRACE_KIND:
                # Request-trace transitions (telemetry/tracing.py):
                # instants carrying the trace context, so a request's
                # path is searchable by trace_id in the merged view.
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "i",
                    "s": "p",
                    "ts": ts,
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": {
                        k: v for k, v in rec.items()
                        if k in ("trace_id", "span_id", "parent_id",
                                 "hop", "seq", "seg", "bin", "width",
                                 "replica", "reroute", "members")
                        and v is not None
                    },
                })
            elif kind == "trace":
                events.append({
                    "name": f"traced:{rec.get('name', '?')}",
                    "ph": "M",
                    "pid": rk,
                    "ts": 0,
                    "args": attrs,
                })
    for rk in sorted(heartbeats or {}):
        doc = heartbeats[rk]
        t = doc.get("t")
        counters = doc.get("counters") or {}
        if not isinstance(t, (int, float)) or not counters:
            continue
        events.append({
            "name": "progress",
            "ph": "C",
            "ts": (t - origin) * 1e6,
            "pid": rk,
            "args": {
                k: v for k, v in sorted(counters.items())
                if isinstance(v, (int, float))
            },
        })
    for v in verdicts or []:
        rk = v.get("rank", 0)
        t = v.get("t")
        ts = (t - origin) * 1e6 if isinstance(t, (int, float)) else 0.0
        events.append({
            "name": "watchdog.verdict",
            "ph": "i",
            "s": "g",  # global scope: a verdict indicts the whole run
            "ts": max(ts, 0.0),
            "pid": rk,
            "args": {
                k: val for k, val in v.items()
                if k in ("rank", "step", "median_step", "stalled_for_s",
                         "last_phase", "last_phase_name")
            },
        })
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    other: dict = {"source": "rocm_mpi_tpu.telemetry"}
    if warnings:
        other["warnings"] = warnings
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def write_chrome_trace(streams: dict[int, list[dict]], path,
                       heartbeats: dict[int, dict] | None = None,
                       verdicts: list[dict] | None = None) -> dict:
    """Export `streams` as trace-event JSON at `path`; returns the doc."""
    from rocm_mpi_tpu.telemetry.aggregate import write_json_atomic

    doc = to_chrome_trace(streams, heartbeats=heartbeats, verdicts=verdicts)
    write_json_atomic(pathlib.Path(path), doc, indent=None)
    return doc
