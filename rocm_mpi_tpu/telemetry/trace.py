"""Chrome trace-event export: one track per rank, openable in Perfetto.

Replaces the raw `docs/prof_trace_hide8192_r3`-style dumps with the
standard trace-event JSON every Chrome/Perfetto build renders
(https://ui.perfetto.dev, chrome://tracing). Mapping:

* span   -> complete slice  (ph "X"): pid = rank, tid = recording thread,
            ts/dur in microseconds; Perfetto nests slices on a track by
            containment, which the per-thread span stack guarantees.
* counter-> counter sample  (ph "C") on the rank's track.
* gauge  -> counter sample  (ph "C") — a gauge is a one-point counter.
* event  -> instant         (ph "i", scope "p"): retries/restores show as
            pins on the rank that emitted them.
* trace  -> process metadata: static per-program facts (bytes per halo
            exchange) land in the rank's metadata args, not on the
            timeline (they have no duration).

Health-plane inputs (optional — the post-mortem bundle's merged
timeline, telemetry/health.py):

* heartbeat sidecars -> one counter track per rank: each heartbeat's
  progress counters become a "progress" counter sample (ph "C") at the
  heartbeat's wall stamp, so the stalled rank's flat-lining step counter
  is visible right on its track.
* watchdog verdicts  -> one global instant (ph "i", scope "g") each,
  pinned to the flagged rank's track and carrying the verdict args —
  the first thing an operator should see when the trace opens.

Events are emitted sorted by ts (metadata first): Perfetto tolerates
unsorted input, but the post-mortem reader (and the tests) treat the
file as a timeline and must not have to re-sort it.

Cross-rank alignment uses the records' WALL timestamps (`t`): each
process's monotonic origin is arbitrary, so `t_mono` orders within a
rank but cannot place ranks against each other. The trace origin is the
earliest wall stamp across all ranks; NTP-grade skew between ranks on
one host (the launcher case) is microseconds — fine for eyeballing halo
waits. Durations come from `dur_s` (monotonic-derived), so slice widths
never inherit wall-clock jumps. stdlib-only, like the whole read side.
"""

from __future__ import annotations

import pathlib

TRACE_REQUIRED_KEYS = ("name", "ph", "ts", "pid")


def to_chrome_trace(streams: dict[int, list[dict]],
                    heartbeats: dict[int, dict] | None = None,
                    verdicts: list[dict] | None = None) -> dict:
    """Build the trace-event document from per-rank record streams
    (aggregate.load_rank_streams shape), optionally merged with health
    sidecars and watchdog verdicts (module docstring)."""
    all_recs = [r for recs in streams.values() for r in recs]
    wall_stamps = [r["t"] for r in all_recs if isinstance(r.get("t"),
                                                          (int, float))]
    for doc in (heartbeats or {}).values():
        if isinstance(doc.get("t"), (int, float)):
            wall_stamps.append(doc["t"])
    origin = min(wall_stamps) if wall_stamps else 0.0

    events: list[dict] = []
    ranks = sorted(set(streams) | set(heartbeats or {}))
    for rk in ranks:
        if rk in streams:
            continue
        events.append({
            "name": "process_name", "ph": "M", "pid": rk, "ts": 0,
            "args": {"name": f"rank {rk}"},
        })
    for rk in sorted(streams):
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": rk,
            "ts": 0,
            "args": {"name": f"rank {rk}"},
        })
        for rec in streams[rk]:
            kind = rec.get("kind")
            t = rec.get("t")
            if not isinstance(t, (int, float)):
                continue
            ts = (t - origin) * 1e6
            attrs = rec.get("attrs") or {}
            if kind == "span":
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "X",
                    "ts": ts,
                    "dur": max(float(rec.get("dur_s", 0.0)) * 1e6, 0.0),
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": attrs,
                })
            elif kind in ("counter", "gauge"):
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "C",
                    "ts": ts,
                    "pid": rk,
                    "args": {rec.get("name", "?"): rec.get("value", 0)},
                })
            elif kind == "event":
                events.append({
                    "name": rec.get("name", "?"),
                    "ph": "i",
                    "s": "p",
                    "ts": ts,
                    "pid": rk,
                    "tid": rec.get("tid", 0),
                    "args": {
                        k: v for k, v in rec.items()
                        if k in ("attempt", "step", "wait_s", "error")
                    },
                })
            elif kind == "trace":
                events.append({
                    "name": f"traced:{rec.get('name', '?')}",
                    "ph": "M",
                    "pid": rk,
                    "ts": 0,
                    "args": attrs,
                })
    for rk in sorted(heartbeats or {}):
        doc = heartbeats[rk]
        t = doc.get("t")
        counters = doc.get("counters") or {}
        if not isinstance(t, (int, float)) or not counters:
            continue
        events.append({
            "name": "progress",
            "ph": "C",
            "ts": (t - origin) * 1e6,
            "pid": rk,
            "args": {
                k: v for k, v in sorted(counters.items())
                if isinstance(v, (int, float))
            },
        })
    for v in verdicts or []:
        rk = v.get("rank", 0)
        t = v.get("t")
        ts = (t - origin) * 1e6 if isinstance(t, (int, float)) else 0.0
        events.append({
            "name": "watchdog.verdict",
            "ph": "i",
            "s": "g",  # global scope: a verdict indicts the whole run
            "ts": max(ts, 0.0),
            "pid": rk,
            "args": {
                k: val for k, val in v.items()
                if k in ("rank", "step", "median_step", "stalled_for_s",
                         "last_phase", "last_phase_name")
            },
        })
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "rocm_mpi_tpu.telemetry"},
    }


def write_chrome_trace(streams: dict[int, list[dict]], path,
                       heartbeats: dict[int, dict] | None = None,
                       verdicts: list[dict] | None = None) -> dict:
    """Export `streams` as trace-event JSON at `path`; returns the doc."""
    from rocm_mpi_tpu.telemetry.aggregate import write_json_atomic

    doc = to_chrome_trace(streams, heartbeats=heartbeats, verdicts=verdicts)
    write_json_atomic(pathlib.Path(path), doc, indent=None)
    return doc
