"""Runtime health plane, read side: sidecar tailing, the progress-aware
stall verdict, post-mortem composition, the live monitor, and the
OpenMetrics export.

The write side (telemetry/flight.py) publishes one `heartbeat-rank{k}.json`
per rank — counters, last phase entered, the flight ring — via atomic
rename. Everything here only READS those sidecars (plus the rank JSONL
streams for the merged timeline), so it runs out-of-process: in the
launcher's watchdog thread, or on a box with no jax at all (the monitor
and export CLI verbs). stdlib-only, like the rest of the read side.

The stalled-collective signature
--------------------------------
Wall clock alone cannot name a wedged rank: when one rank dies or spins
mid-collective, EVERY peer eventually blocks and all of them look
equally idle. Progress counters can: the victim's step counter stopped
first, so the cross-rank median of step counters (the same interpolating
median aggregate.py's straggler detector uses) advances PAST it — peers
bump their counter on entering the window the victim never reached, then
block. `ProgressWatch` flags a rank when

* its sidecar's progress content (counters + last phase) has not changed
  for `stall_grace_s`, AND
* the cross-rank median step counter is strictly ahead of its own.

Only ranks that have PUBLISHED a step counter participate in the median
and in verdicts (and at least two must have): a rank with no `step` yet
has not entered an instrumented loop — it may be sitting out a
weak-scaling rung it owns no devices in, or still compiling — and
comparing its absence-of-progress against working ranks would get a
healthy rank killed. The step counters of participating ranks are
comparable by the writers' contract: apps bump one GLOBAL step count
per process (weak_scaling banks skipped/completed rungs into the
offset), never a per-phase restart that the recorder's monotonic guard
would mask.

A coordinated slow phase (everyone compiling, everyone in one long
window) leaves every participating rank at the same counter — nobody is
strictly behind the median, no verdict. That is the "by progress, not
wall clock" contract the watchdog drill pins.
"""

from __future__ import annotations

import json
import math
import pathlib
import re
import shutil
import statistics
import time

from rocm_mpi_tpu.telemetry import aggregate
from rocm_mpi_tpu.telemetry.flight import (
    BUNDLE_SCHEMA,
    BUNDLE_VERSION,
    HEARTBEAT_SCHEMA,
    POSTMORTEM_SCHEMA,
    POSTMORTEM_VERSION,
)

DEFAULT_STALL_GRACE_S = 5.0

_HEARTBEAT_RE = re.compile(r"heartbeat-rank(\d+)\.json$")
_POSTMORTEM_RE = re.compile(r"postmortem-rank(\d+)\.json$")


def heartbeat_paths(directory) -> dict[int, pathlib.Path]:
    """{rank: sidecar path} under `directory`."""
    out: dict[int, pathlib.Path] = {}
    root = pathlib.Path(directory)
    if not root.is_dir():
        return out
    for path in sorted(root.glob("heartbeat-rank*.json")):
        m = _HEARTBEAT_RE.search(path.name)
        if m:
            out[int(m.group(1))] = path
    return out


def load_heartbeats(directory) -> tuple[dict[int, dict], int]:
    """Parse every heartbeat sidecar. Returns ({rank: doc}, skipped).
    A rank killed mid-write (or a reader racing the writer's rename on a
    filesystem without atomic replace) leaves a torn file: counted and
    skipped, never fatal — the surviving sidecars are the point."""
    beats: dict[int, dict] = {}
    skipped = 0
    for rk, path in heartbeat_paths(directory).items():
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError):
            skipped += 1
            continue
        if isinstance(doc, dict) and doc.get("schema") == HEARTBEAT_SCHEMA:
            doc.setdefault("rank", rk)
            beats[rk] = doc
        else:
            skipped += 1
    return beats, skipped


def _progress_key(doc: dict):
    """What counts as progress: the counters and the phase — NOT the
    wall stamp (a stalled rank's flusher may rewrite identical content
    forever; that is liveness, not progress)."""
    counters = doc.get("counters") or {}
    return (tuple(sorted(counters.items())), doc.get("last_phase"),
            doc.get("last_phase_name"))


class ProgressWatch:
    """Tracks per-rank progress across repeated sidecar observations and
    issues stall verdicts (module docstring has the signature). Feed it
    `observe(beats, now)` each poll; `now` is any monotonic clock."""

    def __init__(self, stall_grace_s: float = DEFAULT_STALL_GRACE_S):
        self.stall_grace_s = float(stall_grace_s)
        self._state: dict[int, dict] = {}

    def observe(self, beats: dict[int, dict], now: float) -> None:
        for rk, doc in beats.items():
            key = _progress_key(doc)
            st = self._state.get(rk)
            if st is None or st["key"] != key:
                self._state[rk] = {"key": key, "changed_at": now, "doc": doc}
            else:
                st["doc"] = doc

    def ages(self, now: float) -> dict[int, float]:
        """Seconds since each rank's progress content last changed — the
        per-rank ages the launcher's health heartbeat line reports."""
        return {
            rk: max(now - st["changed_at"], 0.0)
            for rk, st in sorted(self._state.items())
        }

    def steps(self) -> dict[int, int]:
        """Step counters of the PARTICIPATING ranks only (those that
        have published a `step` at all — module docstring)."""
        out = {}
        for rk, st in self._state.items():
            step = (st["doc"].get("counters") or {}).get("step")
            if isinstance(step, (int, float)):
                out[rk] = int(step)
        return out

    def verdicts(self, now: float) -> list[dict]:
        """Ranks currently matching the stalled-collective signature,
        worst (most-behind) first. Needs >= 2 ranks with published step
        counters — there is no cross-rank median of one, and a rank
        that never published progress cannot have stalled it."""
        steps = self.steps()
        if len(steps) < 2:
            return []
        median = statistics.median(steps.values())
        out = []
        for rk, st in sorted(self._state.items()):
            if rk not in steps:
                continue
            stalled_for = now - st["changed_at"]
            if stalled_for < self.stall_grace_s:
                continue
            if not steps[rk] < median:
                continue
            out.append({
                "rank": rk,
                "step": steps[rk],
                "median_step": median,
                "stalled_for_s": round(stalled_for, 3),
                "last_phase": st["doc"].get("last_phase"),
                "last_phase_name": st["doc"].get("last_phase_name"),
            })
        out.sort(key=lambda v: v["step"])
        return out


# ---------------------------------------------------------------------------
# Elastic supervisor events (docs/RESILIENCE.md "Elastic recovery")
# ---------------------------------------------------------------------------
#
# The elastic supervisor (resilience.elastic.run_elastic) outlives every
# rank — its decisions (launch on this mesh, shrink to that one, give up)
# cannot ride a rank's telemetry stream. They land in one append-only
# `elastic.jsonl` sidecar next to the heartbeat sidecars, written here
# (telemetry owns the clock reads — GL06) and read back by the monitor
# verb, which shows the current mesh shape plus SHRUNK / GROWN badges
# for runs that changed topology (and a PREEMPTED marker for a whole-job
# eviction). scripts/lint.sh schema-checks the records
# (regress.check_schema) wherever they get archived.

ELASTIC_SCHEMA = "rocm_mpi_tpu.resilience.elastic"
ELASTIC_VERSION = 1
ELASTIC_FILE = "elastic.jsonl"


def append_elastic_event(directory, name: str, **attrs) -> dict:
    """Append one supervisor event (`elastic.launch` / `elastic.shrink` /
    `elastic.complete` / `elastic.gave-up`) to `<directory>/elastic.jsonl`,
    wall-stamped here. Returns the record."""
    rec = {
        "schema": ELASTIC_SCHEMA,
        "v": ELASTIC_VERSION,
        "kind": "event",
        "name": name,
        "t": time.time(),
        **attrs,
    }
    root = pathlib.Path(directory)
    root.mkdir(parents=True, exist_ok=True)
    with open(root / ELASTIC_FILE, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    return rec


def load_elastic_events(directory) -> tuple[list[dict], int]:
    """Parse `<directory>/elastic.jsonl`. Returns (records, skipped) —
    torn/foreign lines are counted and skipped, never fatal (the same
    tolerance every sidecar reader here has)."""
    path = pathlib.Path(directory) / ELASTIC_FILE
    records: list[dict] = []
    skipped = 0
    try:
        text = path.read_text()
    except OSError:
        return records, skipped
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(doc, dict) and doc.get("schema") == ELASTIC_SCHEMA:
            records.append(doc)
        else:
            skipped += 1
    return records, skipped


def elastic_status(events: list[dict]) -> dict | None:
    """The monitor's one-line view of the elastic record: current mesh
    dims, rank count, whether the run ever SHRANK (and from what) or
    GREW (and onto what), and whether the whole job was preempted. None
    when there are no elastic events (non-elastic run: no badge)."""
    mesh = None
    nprocs = None
    first_mesh = None
    grow_mesh = None
    shrinks = 0
    grows = 0
    preempted = False
    for e in events:
        name = e.get("name")
        if name == "elastic.launch":
            mesh = e.get("mesh") or mesh
            nprocs = e.get("nprocs", nprocs)
            if first_mesh is None:
                first_mesh = e.get("mesh")
        elif name == "elastic.shrink":
            shrinks += 1
            mesh = e.get("new_mesh") or mesh
            nprocs = e.get("new_nprocs", nprocs)
            if first_mesh is None:
                first_mesh = e.get("old_mesh")
        elif name == "elastic.grow":
            grows += 1
            mesh = e.get("new_mesh") or mesh
            grow_mesh = e.get("new_mesh") or grow_mesh
            nprocs = e.get("new_nprocs", nprocs)
            if first_mesh is None:
                first_mesh = e.get("old_mesh")
        elif name == "elastic.preempted":
            preempted = True
    if mesh is None and nprocs is None:
        return None
    return {
        "mesh": mesh,
        "nprocs": nprocs,
        "shrunk": shrinks > 0,
        "shrinks": shrinks,
        "grown": grows > 0,
        "grows": grows,
        "grow_mesh": grow_mesh,
        "preempted": preempted,
        "first_mesh": first_mesh,
    }


def _mesh_str(mesh) -> str | None:
    """Render mesh dims for the monitor header; None when the elastic
    run never recorded dims (run_elastic without a global shape plans
    plain rank counts — the header then shows ranks only, never the
    literal string 'None')."""
    if isinstance(mesh, list):
        return "(" + ", ".join(str(d) for d in mesh) + ")"
    return None


def format_elastic_status(status: dict | None) -> str | None:
    """`mesh (2, 1)  2 rank(s)` — plus the SHRUNK badge once a shrink
    happened: `mesh (1, 1)  1 rank(s)  [SHRUNK from (2, 1), 1
    shrink(s)]`, the mirror GROWN badge once a grow happened
    (`[GROWN to (2, 1), 1 grow(s)]` — both can show: a run that shrank
    and grew back carries its whole topology history), and
    `[PREEMPTED — resumable]` when the supervisor recorded a whole-job
    eviction. Mesh fragments are omitted when the events carry no
    dims."""
    if not status:
        return None
    parts = []
    mesh_s = _mesh_str(status.get("mesh"))
    if mesh_s is not None:
        parts.append(f"mesh {mesh_s}")
    if status.get("nprocs") is not None:
        parts.append(f"{status['nprocs']} rank(s)")
    if status.get("shrunk"):
        first_s = _mesh_str(status.get("first_mesh"))
        origin = (
            f"from {first_s}" if first_s is not None
            else "from more ranks"
        )
        parts.append(
            f"[SHRUNK {origin}, {status['shrinks']} shrink(s)]"
        )
    if status.get("grown"):
        grow_s = _mesh_str(status.get("grow_mesh"))
        target = (
            f"to {grow_s}" if grow_s is not None
            else "to more ranks"
        )
        parts.append(
            f"[GROWN {target}, {status['grows']} grow(s)]"
        )
    if status.get("preempted"):
        parts.append("[PREEMPTED — resumable]")
    return "  ".join(parts) if parts else None


def storage_status(beats: dict[int, dict]) -> dict | None:
    """The degraded-storage view the monitor renders next to the elastic
    badges, computed from the heartbeat progress counters the segmented
    loop bumps alongside its `ckpt.degraded`/`ckpt.recovered` telemetry
    events (utils.checkpoint._guarded_save): a rank is degraded NOW when
    it entered degraded mode more times than it recovered. None when no
    rank ever degraded (the common case: no indicator at all)."""
    degraded_ranks = []
    skipped = 0
    for rank, doc in sorted(beats.items()):
        counters = doc.get("counters") or {}
        skipped += int(counters.get("ckpt_skipped", 0) or 0)
        entered = int(counters.get("ckpt_degraded", 0) or 0)
        recovered = int(counters.get("ckpt_recovered", 0) or 0)
        if entered > recovered:
            degraded_ranks.append(rank)
    if not degraded_ranks and not skipped:
        return None
    return {
        "degraded": bool(degraded_ranks),
        "degraded_ranks": degraded_ranks,
        "skipped": skipped,
    }


def format_storage_status(status: dict | None) -> str | None:
    """`[STORAGE DEGRADED rank(s) 0,1 — 3 skipped save(s)]` while an
    outage is live; once every rank recovered, the quieter
    `storage recovered (3 skipped save(s))` keeps the loss window
    visible. None when checkpointing never degraded."""
    if not status:
        return None
    if status["degraded"]:
        ranks = ",".join(str(r) for r in status["degraded_ranks"])
        return (
            f"[STORAGE DEGRADED rank(s) {ranks} — "
            f"{status['skipped']} skipped save(s)]"
        )
    return f"storage recovered ({status['skipped']} skipped save(s))"


def serve_status(beats: dict[int, dict]) -> dict | None:
    """The serving-plane view next to the elastic/storage badges
    (docs/SERVING.md; docs/TELEMETRY.md "Serving"), computed from the
    heartbeat progress counters the service's drain loop bumps
    (serve_submitted / serve_completed / serve_requeued /
    serve_resizes / serve_rejected / serve_expired / serve_quarantined
    are ADDITIVE counters — depth is their difference; serve_retries
    rides for visibility but is an event count, not an outcome).
    None when no rank ever served (the common case: no badge)."""
    submitted = completed = requeued = resizes = failed = 0
    rejected = expired = quarantined = retries = 0
    seen = False
    for _rank, doc in sorted(beats.items()):
        counters = doc.get("counters") or {}
        if not any(k.startswith("serve_") for k in counters):
            continue
        seen = True
        submitted += int(counters.get("serve_submitted", 0) or 0)
        completed += int(counters.get("serve_completed", 0) or 0)
        requeued += int(counters.get("serve_requeued", 0) or 0)
        resizes += int(counters.get("serve_resizes", 0) or 0)
        failed += int(counters.get("serve_failed", 0) or 0)
        rejected += int(counters.get("serve_rejected", 0) or 0)
        expired += int(counters.get("serve_expired", 0) or 0)
        quarantined += int(counters.get("serve_quarantined", 0) or 0)
        retries += int(counters.get("serve_retries", 0) or 0)
    if not seen:
        return None
    return {
        # Every outcome leaves the backlog — a failed/rejected/expired/
        # quarantined request must not read as depth forever, and a
        # retry-requeue hands the ticket back to the queue (it will be
        # re-counted when re-popped), so retries subtract too.
        "depth": max(
            submitted - completed - requeued - failed - rejected
            - expired - quarantined - retries, 0
        ),
        "submitted": submitted,
        "completed": completed,
        "requeued": requeued,
        "resizes": resizes,
        "failed": failed,
        "rejected": rejected,
        "expired": expired,
        "quarantined": quarantined,
        "retries": retries,
    }


def format_serve_status(status: dict | None) -> str | None:
    """`[SERVE depth=3 — 17 done]` while requests are in flight; the
    quieter `serve idle (17 done)` once drained; requeued work
    (preemption), elastic resizes, and the SLO outcomes — deadline
    misses (expired), quarantined poison, admission rejections — ride
    along, so a poisoned or overloaded service is visible from the
    sidecar alone (docs/SERVING.md "SLOs and admission"). None when
    the run never served."""
    if not status:
        return None
    tail = f"{status['completed']} done"
    if status.get("failed"):
        tail += f", {status['failed']} failed"
    if status.get("expired"):
        tail += f", {status['expired']} deadline-missed"
    if status.get("quarantined"):
        tail += f", {status['quarantined']} quarantined"
    if status.get("rejected"):
        tail += f", {status['rejected']} rejected"
    if status.get("retries"):
        tail += f", {status['retries']} retried"
    if status["requeued"]:
        tail += f", {status['requeued']} requeued"
    if status["resizes"]:
        tail += f", {status['resizes']} resize(s)"
    if status["depth"]:
        return f"[SERVE depth={status['depth']} — {tail}]"
    return f"serve idle ({tail})"


def fleet_status(report: dict | None) -> dict | None:
    """The fleet-plane view next to the SERVE badge (docs/SERVING.md
    "The fleet"), computed from a merged fleet report
    (serving/journal.py `rmt-fleet-report`): live/total replicas, the
    journal-derived merged SLO counts, the re-route count, and the
    accounting verdict. None when the doc isn't a fleet report."""
    if not report or report.get("schema") != "rmt-fleet-report":
        return None
    replicas = report.get("replicas") or []
    slo = report.get("slo") or {}
    journal = report.get("journal") or {}
    live = sum(
        1 for r in replicas
        if r.get("alive") and not r.get("demoted")
    )
    return {
        "live": live,
        "total": len(replicas),
        "demoted": sum(
            1 for r in replicas
            if r.get("alive") and r.get("demoted")
        ),
        "depth": int(journal.get("open", 0) or 0),
        "done": int(slo.get("done", 0) or 0),
        "failed": int(slo.get("failed", 0) or 0),
        "rejected": int(slo.get("rejected", 0) or 0),
        "expired": int(slo.get("expired", 0) or 0),
        "quarantined": int(slo.get("quarantined", 0) or 0),
        "rerouted": int(journal.get("rerouted", 0) or 0),
        "accounting_ok": bool(report.get("accounting_ok")),
    }


def format_fleet_status(status: dict | None) -> str | None:
    """`[FLEET 2/3 up — depth=4, 17 done, 3 rerouted]` while the fleet
    owes work; the quieter `fleet idle (3/3 up — 17 done)` once the
    journal shows every ticket terminal. A broken accounting invariant
    is the loudest thing on the line — a lost or double-terminal
    ticket must not hide behind healthy-looking counts. None when
    there is no fleet report."""
    if not status:
        return None
    up = f"{status['live']}/{status['total']} up"
    tail = f"{status['done']} done"
    if status.get("failed"):
        tail += f", {status['failed']} failed"
    if status.get("expired"):
        tail += f", {status['expired']} deadline-missed"
    if status.get("quarantined"):
        tail += f", {status['quarantined']} quarantined"
    if status.get("rejected"):
        tail += f", {status['rejected']} rejected"
    if status.get("rerouted"):
        tail += f", {status['rerouted']} rerouted"
    if status.get("demoted"):
        tail += f", {status['demoted']} demoted"
    if not status.get("accounting_ok"):
        tail += ", ACCOUNTING BROKEN"
    if status["depth"]:
        return f"[FLEET {up} — depth={status['depth']}, {tail}]"
    return f"fleet idle ({up} — {tail})"


def wire_status(directory) -> list[str]:
    """The run's active wire-precision mode(s) (docs/PERF.md "Wire
    precision"), annotation-sourced from the telemetry rank streams in
    `directory` (the halo.exchange / deep.sweep / overlap.step trace
    records stamp `wire` per compiled program). Sorted, [] when the
    streams carry no wire-stamped annotations (pre-wire-plane runs)."""
    from rocm_mpi_tpu.telemetry import aggregate

    modes: set[str] = set()
    streams, _skipped = aggregate.load_rank_streams(directory)
    for recs in streams.values():
        for rec in recs:
            w = aggregate.record_wire_mode(rec)
            if w:
                modes.add(w)
    return sorted(modes)


def format_wire_status(modes: list[str]) -> str | None:
    """`[WIRE bf16]` for a reduced-precision (or mixed-mode) run — like
    the GROWN/DEGRADED badges, the operator must see at a glance that
    this run's halo bytes are not comparable to an f32 run's. None for
    f32-only or unstamped streams (no badge — the common case)."""
    if not modes or modes == ["f32"]:
        return None
    return "[WIRE " + ", ".join(m for m in modes) + "]"


# ---------------------------------------------------------------------------
# Post-mortem composition and bundling (the watchdog's out-of-process half)
# ---------------------------------------------------------------------------


def write_postmortem(directory, rank: int, verdict: dict,
                     traceback_text: str | None = None) -> pathlib.Path:
    """Compose `postmortem-rank{k}.json` from the rank's last heartbeat,
    the watchdog verdict, and the faulthandler dump (read from the
    `.traceback` sidecar when not passed). Runs OUT of process — the
    wedged rank only had to have flushed a heartbeat once and own a
    registered faulthandler; everything else is the reader's job."""
    root = pathlib.Path(directory)
    # Wall-stamp the verdict IN PLACE (telemetry owns the clock reads —
    # GL06): the caller's verdict list and the bundle's trace instants
    # see the same stamp.
    verdict.setdefault("t", time.time())
    beats, _ = load_heartbeats(root)
    if traceback_text is None:
        tb_path = root / f"postmortem-rank{rank}.traceback"
        try:
            traceback_text = tb_path.read_text()
        except OSError:
            traceback_text = None
    doc = {
        "schema": POSTMORTEM_SCHEMA,
        "v": POSTMORTEM_VERSION,
        "rank": int(rank),
        "t": time.time(),
        "verdict": verdict,
        "heartbeat": beats.get(rank),
        "traceback": traceback_text,
    }
    path = root / f"postmortem-rank{rank}.json"
    aggregate.write_json_atomic(path, doc)
    return path


def bundle_postmortem(directory, verdicts: list[dict]) -> pathlib.Path:
    """Collect a run's wreckage into `<directory>/postmortem/`: the
    per-rank post-mortems and heartbeats, a `bundle.json` naming the
    verdicts, and a merged `timeline-trace.json` (the rank streams plus
    progress counter tracks and one instant per verdict — the Chrome
    trace an operator opens FIRST). Returns the bundle directory."""
    from rocm_mpi_tpu.telemetry import trace

    root = pathlib.Path(directory)
    out = root / "postmortem"
    if out.is_dir():
        # The bundle describes THIS run's incident: a leftover bundle in
        # a reused directory would mix last incident's per-rank files
        # with the new verdicts and misattribute the wreckage.
        shutil.rmtree(out, ignore_errors=True)
    out.mkdir(parents=True, exist_ok=True)
    copied = []
    for pattern in ("postmortem-rank*.json", "postmortem-rank*.traceback",
                    "heartbeat-rank*.json"):
        for path in sorted(root.glob(pattern)):
            try:
                shutil.copy2(path, out / path.name)
                copied.append(path.name)
            except OSError:
                continue
    beats, _ = load_heartbeats(root)
    streams, _ = aggregate.load_rank_streams(root)
    try:
        trace.write_chrome_trace(
            streams, out / "timeline-trace.json",
            heartbeats=beats, verdicts=verdicts,
        )
        copied.append("timeline-trace.json")
    except Exception:  # noqa: BLE001 — the bundle must survive a bad stream
        pass
    bundle = {
        "schema": BUNDLE_SCHEMA,
        "v": BUNDLE_VERSION,
        "t": time.time(),
        "verdicts": verdicts,
        "ranks": sorted(beats),
        "files": sorted(set(copied)),
    }
    aggregate.write_json_atomic(out / "bundle.json", bundle)
    return out


# ---------------------------------------------------------------------------
# Live monitor (the `monitor` CLI verb)
# ---------------------------------------------------------------------------


def monitor_rows(beats: dict[int, dict],
                 prev: dict[int, dict] | None = None,
                 now_wall: float | None = None) -> list[dict]:
    """Per-rank monitor rows from one sidecar snapshot (plus the previous
    snapshot for step rates). Stateless — the CLI loop owns the cadence."""
    now_wall = time.time() if now_wall is None else now_wall
    steps = {
        rk: int((doc.get("counters") or {}).get("step", 0))
        for rk, doc in beats.items()
    }
    median = statistics.median(steps.values()) if steps else 0.0
    rows = []
    for rk in sorted(beats):
        doc = beats[rk]
        rate = None
        if prev and rk in prev:
            d_step = steps[rk] - int(
                (prev[rk].get("counters") or {}).get("step", 0)
            )
            d_t = (doc.get("t") or 0.0) - (prev[rk].get("t") or 0.0)
            if d_t > 0:
                rate = d_step / d_t
        phase_t = doc.get("last_phase_t") or doc.get("t") or now_wall
        rows.append({
            "rank": rk,
            "step": steps[rk],
            "phase": doc.get("last_phase") or "-",
            "age_s": max(now_wall - (doc.get("t") or now_wall), 0.0),
            "phase_age_s": max(now_wall - phase_t, 0.0),
            "rate": rate,
            "delta_vs_median": steps[rk] - median,
        })
    return rows


def format_monitor(rows: list[dict], skipped: int = 0) -> str:
    lines = [
        "rank  step      rate/s   phase         phase-age  Δmedian",
    ]
    for r in rows:
        rate = f"{r['rate']:8.2f}" if r["rate"] is not None else "       ?"
        lines.append(
            f"{r['rank']:<5d} {r['step']:<9d} {rate} "
            f"{r['phase']:<13s} {r['phase_age_s']:8.1f}s  "
            f"{r['delta_vs_median']:+g}"
        )
    if skipped:
        lines.append(f"({skipped} torn sidecar(s) skipped)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# OpenMetrics export (the `export-openmetrics` CLI verb)
# ---------------------------------------------------------------------------


def _om_escape(value: str) -> str:
    return (
        str(value).replace("\\", r"\\").replace('"', r'\"')
        .replace("\n", r"\n")
    )


def _om_number(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if isinstance(v, float) else str(v)


def export_openmetrics(directory) -> str | None:
    """A Prometheus/OpenMetrics text snapshot of the run's gauges,
    counters, and per-rank progress. The run's own metric keys (e.g.
    `run.gpts@4dev:scan`) contain characters OpenMetrics metric names
    forbid, so every key rides VERBATIM in a `key` label under three
    fixed metric families — the snapshot round-trips exactly, no lossy
    renaming. Returns None when `directory` holds neither rank streams
    nor heartbeat sidecars (the caller's exit-2 case)."""
    streams, _ = aggregate.load_rank_streams(directory)
    beats, _ = load_heartbeats(directory)
    if not streams and not beats:
        return None
    summary = aggregate.summarize(streams) if streams else None
    lines = []
    if summary:
        lines.append("# TYPE rmt_gauge gauge")
        lines.append("# HELP rmt_gauge telemetry gauges, key verbatim "
                     "(rank-median where multiple ranks emitted)")
        for key in sorted(summary["gauges"]):
            value = summary["gauges"][key]
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                lines.append(
                    f'rmt_gauge{{key="{_om_escape(key)}"}} '
                    f"{_om_number(value)}"
                )
        lines.append("# TYPE rmt_counter counter")
        lines.append("# HELP rmt_counter telemetry counters, key verbatim")
        for key in sorted(summary["counters"]):
            lines.append(
                f'rmt_counter_total{{key="{_om_escape(key)}"}} '
                f"{_om_number(summary['counters'][key])}"
            )
    if beats:
        lines.append("# TYPE rmt_progress gauge")
        lines.append("# HELP rmt_progress flight-recorder progress "
                     "counters per rank (heartbeat sidecars)")
        for rk in sorted(beats):
            counters = beats[rk].get("counters") or {}
            for name in sorted(counters):
                value = counters[name]
                if isinstance(value, (int, float)):
                    lines.append(
                        f'rmt_progress{{rank="{rk}",'
                        f'counter="{_om_escape(name)}"}} '
                        f"{_om_number(value)}"
                    )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> dict[str, dict]:
    """Parse an export back into {family: {label-tuple or key: value}} —
    the round-trip half the export test pins; also handy for scrapers
    that want the values without a Prometheus client."""
    out: dict[str, dict] = {}
    sample_re = re.compile(
        r'^(\w+)\{(.*)\}\s+(\S+)$'
    )
    label_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = sample_re.match(line)
        if not m:
            continue
        family, labelstr, value = m.groups()
        # Single-pass unescape (\\ \" \n): ordered str.replace would
        # consume the second character of an escaped backslash as a
        # fresh escape and corrupt values like 'a\\nb'.
        unescape = {"n": "\n", '"': '"', "\\": "\\"}
        labels = {
            k: re.sub(
                r"\\(.)", lambda m: unescape.get(m.group(1), m.group(1)), v
            )
            for k, v in label_re.findall(labelstr)
        }
        key = labels.get("key") or tuple(sorted(labels.items()))
        out.setdefault(family, {})[key] = float(value)
    return out
