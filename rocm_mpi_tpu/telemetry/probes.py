"""Phase-attribution probes: measure halo / interior / checkpoint cost
when the hot loop itself exposes no runtime seams.

The framework's whole design keeps the step fused — halo ppermutes and
interior compute live inside ONE compiled program precisely so XLA can
overlap them (parallel/overlap.py), and the time loop never leaves the
device (models/*.py fori_loop). That is the right execution schedule and
the wrong measurement surface: there is no host-visible boundary to
span. The standard answer (both tuning surveys the ROADMAP cites —
arxiv 2406.08923, 2404.04441 — lean on it) is differential probing: run
each phase as its OWN compiled program over the same state and time it
under a span. The probe programs are built from the very building blocks
the fused step composes (exchange_halo, the padded stencil update,
save/restore), so the attribution measures the real kernels, not a
model of them.

Caveat stamped into every probe span (`attrs["probe"] = True`): probe
phases run serially, so their sum exceeds a fused step that overlaps
them — the summary's `step` phase is the ground truth for total time;
probes attribute, they do not re-measure.

This module needs jax; the telemetry package imports it lazily so the
stdlib-only read side (aggregate/trace/regress CLI) stays jax-free.
"""

from __future__ import annotations

from rocm_mpi_tpu.telemetry import events
from rocm_mpi_tpu.telemetry.spans import span


def run_diffusion_phase_probes(model, iters: int = 10,
                               checkpoint_dir=None,
                               driver: str | None = None) -> None:
    """Measure halo / interior (and optionally checkpoint) phases for a
    HeatDiffusion model, emitting one span per phase.

    `iters` iterations run inside one jitted fori_loop per probe (one
    dispatch, no per-iteration host round-trips), after a warmup call
    that eats the compile. With `checkpoint_dir`, one save/restore cycle
    runs through utils.checkpoint — whose own spans provide the
    checkpoint attribution (every process must call this on multi-host
    runs: orbax saves are collective). `driver` stamps the loop form the
    probed run used (apps --driver) on every probe span: phase
    attributions banked from a scan-driver run and a step-driver run are
    different measurements and must say so.
    """
    if not events.enabled():
        return
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from rocm_mpi_tpu.ops.diffusion import step_fused_padded
    from rocm_mpi_tpu.parallel.halo import exchange_halo, exchange_nbytes
    from rocm_mpi_tpu.utils.compat import shard_map

    cfg, grid = model.config, model.grid
    T, Cp = model.init_state()
    dt = cfg.jax_dtype(cfg.dt)
    core = tuple(slice(1, -1) for _ in range(grid.ndim))
    n_local_devices = sum(
        1 for d in grid.mesh.devices.flat
        if d.process_index == jax.process_index()
    )
    per_exchange = exchange_nbytes(
        grid.local_shape, jnp.dtype(cfg.jax_dtype).itemsize
    )

    @functools.partial(jax.jit, static_argnums=1)
    def halo_probe(x, n):
        def local(xl):
            def body(_, cur):
                return exchange_halo(cur, grid)[core]

            return lax.fori_loop(0, n, body, xl)

        return shard_map(
            local, mesh=grid.mesh, in_specs=(grid.spec,),
            out_specs=grid.spec, check_vma=False,
        )(x)

    @functools.partial(jax.jit, static_argnums=2)
    def interior_probe(x, c, n):
        def local(xl, cl):
            def body(_, cur):
                # Zero-padded block: the same stencil update the fused
                # step applies, with no communication to hide behind.
                return step_fused_padded(
                    jnp.pad(cur, 1), cl, cfg.lam, dt, cfg.spacing
                )

            return lax.fori_loop(0, n, body, xl)

        return shard_map(
            local, mesh=grid.mesh, in_specs=(grid.spec, grid.spec),
            out_specs=grid.spec, check_vma=False,
        )(x, c)

    from rocm_mpi_tpu.utils.metrics import force

    # Warm with the SAME static iteration count the span will use:
    # `n` is a static argument, so a warmup at a different n compiles a
    # different program and the span would time the compile, not the
    # kernels — poisoning every baseline banked from the run.
    stamp = {} if driver is None else {"driver": driver}
    force(halo_probe(T, iters))
    with span(
        "halo.probe", phase="halo", probe=True, iters=iters,
        bytes=per_exchange * n_local_devices * iters, **stamp,
    ) as sp:
        sp.sync(halo_probe(T, iters))

    force(interior_probe(T, Cp, iters))
    with span(
        "interior.probe", phase="interior", probe=True, iters=iters,
        **stamp,
    ) as sp:
        sp.sync(interior_probe(T, Cp, iters))

    if checkpoint_dir is not None:
        from rocm_mpi_tpu.utils import checkpoint as ckpt

        try:
            # The spans come from checkpoint.py's own instrumentation;
            # the probe just drives one full save/validate/restore cycle.
            ckpt.save_state(checkpoint_dir, 0, (T,))
            ckpt.restore_state(checkpoint_dir, 0, (T,))
        except Exception as e:  # noqa: BLE001 — a probe must not kill the run
            events.record_event("probe-failed", error=f"checkpoint: {e!r}")


def make_halo_heartbeat(model):
    """Build the per-window halo heartbeat for the health plane: one
    compiled single-exchange program over `model`'s grid, returned as
    `beat(x) -> x` which runs the exchange under a
    `halo.heartbeat` span (phase=halo, probe=True, real wire bytes).

    Purpose (docs/TELEMETRY.md "Health plane"): the fused windowed run
    gives the flight recorder nothing halo-shaped at runtime — the
    exchanges live inside the compiled window. One real cross-rank
    exchange per window boundary is a live probe of the collective
    fabric: its span feeds the flight ring (so a rank wedged at a
    boundary reads "last phase: halo", which is what it is blocked on),
    its latency lands in the halo phase attribution marked probe:true,
    and its cost is one exchange per WINDOW, not per step. Compile the
    returned callable once (call it during warmup, before
    compiles.mark_steady) — it is jitted and reused.
    """
    import jax
    import jax.numpy as jnp

    from rocm_mpi_tpu.parallel.halo import exchange_halo, exchange_nbytes
    from rocm_mpi_tpu.utils.compat import shard_map

    grid = model.grid
    cfg = model.config
    core = tuple(slice(1, -1) for _ in range(grid.ndim))
    n_local_devices = sum(
        1 for d in grid.mesh.devices.flat
        if d.process_index == jax.process_index()
    )
    nbytes = exchange_nbytes(
        grid.local_shape, jnp.dtype(cfg.jax_dtype).itemsize
    ) * n_local_devices

    @jax.jit
    def one_exchange(x):
        def local(xl):
            return exchange_halo(xl, grid)[core]

        return shard_map(
            local, mesh=grid.mesh, in_specs=(grid.spec,),
            out_specs=grid.spec, check_vma=False,
        )(x)

    def beat(x):
        with span("halo.heartbeat", phase="halo", probe=True,
                  bytes=nbytes) as sp:
            return sp.sync(one_exchange(x))

    return beat
