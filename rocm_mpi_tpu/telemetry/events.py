"""Telemetry event stream: versioned records, one JSONL writer per rank.

The reference's observability is one tic()/toc() pair and a printed T_eff
(SURVEY.md §5.5); PR 1 added an ad-hoc `record_event` for resilience
decisions. This module is the unification: every observation — span,
counter, gauge, resilience event, trace annotation — is one dict record
with a common stamped header, collected in-process and (when a sink
directory is configured) appended to `telemetry-rank{k}.jsonl`, one
writer per rank so concurrent ranks never interleave within a line.

Record header (every kind):

    {"v": SCHEMA_VERSION,      # event-schema version (v1 = the PR-1
                               #   unversioned RunEvent lines)
     "kind": "span" | "counter" | "gauge" | "event" | "trace"
             | "tspan" | "anchor",
     "name": str,              # dotted, phase-prefixed ("halo.exchange")
     "t": float,               # time.time() — comparable ACROSS ranks
     "t_mono": float,          # time.perf_counter() — orders WITHIN a rank
     "rank": int}

Kind-specific fields: spans add `dur_s`/`depth`/`tid`, counters and
gauges add `value`, events carry the resilience payload
(attempt/step/wait_s/error), trace annotations carry static metadata
recorded at trace time (bytes per halo exchange etc. — see spans.annotate),
tspans carry a request's trace context (telemetry/tracing.py).
Everything else rides in `attrs` so the header schema stays closed.

Two timestamps by design: wall time aligns ranks in the merged Chrome
trace (each process's monotonic origin is arbitrary), while `t_mono`
gives the tear-free ordering within a rank that the PR-1 events lacked —
the satellite fix for "events are unordered across ranks". The
"anchor"-kind `clock.anchor` record (one per sink, emitted by
configure()) binds the two clocks: its header stamps t and t_mono back
to back, so the fleet merger can map any record's t_mono into
comparable wall time (telemetry/tracing.py `aligned_wall`).

Configuration (env first, so launcher-spawned ranks need no code):

    RMT_TELEMETRY=1          enable collection (0/off/false disables)
    RMT_TELEMETRY_DIR=DIR    sink directory (implies enabled)
    RMT_PROCESS_ID           rank stamp fallback (the launcher contract)

or `configure(enabled=…, directory=…, rank=…)` from an app (--telemetry).

Cost discipline: `enabled()` is one module-global bool read — the hot
guard every span/annotation checks first. "event"-kind records are the
exception: they buffer in-process even when disabled, because the
resilience layer's `metrics.events()` API predates telemetry and its
callers (tests, supervisor post-mortems) must see events without opting
into collection. stdlib-only on purpose: the aggregate/trace/regress CLI
must run on a box with no jax at all.
"""

from __future__ import annotations

import json
import os
import threading
import time

SCHEMA_VERSION = 2

_FALSY = ("0", "off", "false", "no", "")


def _env_enabled() -> bool:
    flag = os.environ.get("RMT_TELEMETRY")
    if flag is not None:
        return flag.lower() not in _FALSY
    return bool(os.environ.get("RMT_TELEMETRY_DIR"))


_LOCK = threading.Lock()
_ENABLED: bool = _env_enabled()
_DIR: str | None = os.environ.get("RMT_TELEMETRY_DIR") or None
_RANK: int | None = None
_RECORDS: list[dict] = []
_ANNOTATED: set = set()  # (name, sorted attrs) — trace-annotation dedup
_ANCHORED: set = set()   # (dir, rank) — one clock anchor per sink

# In-process buffer cap for hot kinds (spans/counters/gauges/trace): the
# JSONL file is the real sink; the buffer exists for tests and
# single-process introspection and must not grow without bound over a
# production-length run (a per-step host-staged oracle emits 2 spans per
# step). Beyond the cap, hot records still hit the file but skip the
# buffer (counted in dropped_records()). "event"-kind records are exempt:
# they are rare and the metrics.events() contract depends on them.
_MAX_HOT_RECORDS = 100_000
_DROPPED = 0

# Optional observer of every emitted record (the flight recorder's ring,
# telemetry/flight.py). One slot, set/cleared whole — not a listener
# list: the hot path pays one global read when no tap is installed.
_TAP = None


def set_tap(fn) -> None:
    """Install (or with None clear) the single record tap. The tap runs
    outside the emit lock and must never raise into the caller."""
    global _TAP
    _TAP = fn


def enabled() -> bool:
    """The one hot-path guard: a plain module-global read."""
    return _ENABLED


def configure(enabled: bool | None = None, directory=None,
              rank: int | None = None) -> None:
    """Override the env-derived telemetry config (an app's --telemetry
    flag). `directory` is created on the spot — a misconfigured sink must
    fail at configure time, not silently drop every record later."""
    global _ENABLED, _DIR, _RANK
    with _LOCK:
        if directory is not None:
            _DIR = str(directory)
            os.makedirs(_DIR, exist_ok=True)
            if enabled is None:
                enabled = True
        if enabled is not None:
            _ENABLED = bool(enabled)
        if rank is not None:
            _RANK = int(rank)
    if _ENABLED and _DIR is not None:
        _emit_clock_anchor()


def _emit_clock_anchor() -> None:
    """One wall<->monotonic clock anchor per (sink, rank): the record's
    own header stamps `t` and `t_mono` back to back, and that pair is
    what the fleet trace merger aligns replica streams with
    (telemetry/tracing.py). Emitted outside configure()'s lock — emit()
    takes it. Streams that never pass through configure() (legacy
    env-only ranks) simply have no anchor; the merger warns on them."""
    key = (_DIR, rank())
    with _LOCK:
        if key in _ANCHORED:
            return
        _ANCHORED.add(key)
    emit("anchor", "clock.anchor", pid=os.getpid())


def rank() -> int:
    """The stamped rank: configure(rank=…) wins, else the launcher's
    RMT_PROCESS_ID contract, else 0 (single-process runs)."""
    if _RANK is not None:
        return _RANK
    try:
        return int(os.environ.get("RMT_PROCESS_ID", "0"))
    except ValueError:
        return 0


def directory() -> str | None:
    """The configured sink directory (None = in-process buffering only)."""
    return _DIR


def stream_path() -> str | None:
    """This rank's JSONL sink path, or None when no directory is set."""
    if _DIR is None:
        return None
    return os.path.join(_DIR, f"telemetry-rank{rank()}.jsonl")


def _write_line(line: str) -> None:
    path = stream_path()
    if path is None:
        return
    try:
        # Env-configured ranks (RMT_TELEMETRY_DIR, the launcher contract)
        # never call configure(), so the sink directory may not exist on
        # the first write — create it here, not just in configure().
        os.makedirs(_DIR, exist_ok=True)
        with open(path, "a") as fh:
            fh.write(line + "\n")
    except OSError:
        pass  # telemetry must never be what kills a run


def emit(kind: str, name: str, *, buffer_always: bool = False,
         **fields) -> dict:
    """Stamp and record one event. Caller checks `enabled()` first for
    hot kinds; `buffer_always` is the "event"-kind back-compat carve-out
    (see module docstring)."""
    rec = {
        "v": SCHEMA_VERSION,
        "kind": kind,
        "name": name,
        "t": time.time(),
        "t_mono": time.perf_counter(),
        "rank": rank(),
    }
    rec.update(fields)
    global _DROPPED
    with _LOCK:
        if buffer_always:
            _RECORDS.append(rec)
        elif _ENABLED:
            if len(_RECORDS) < _MAX_HOT_RECORDS:
                _RECORDS.append(rec)
            else:
                _DROPPED += 1
        write = _ENABLED
    if write:
        # Outside the lock: each record is ONE write() of one line to an
        # O_APPEND stream, which the kernel appends atomically — holding
        # the lock over disk I/O would serialize every emitting thread
        # (launcher drains, supervisor events) behind each append and
        # skew the very intervals being recorded on a slow sink.
        _write_line(json.dumps(rec))
    tap = _TAP
    if tap is not None:
        try:
            tap(rec)
        except Exception:  # noqa: BLE001 — observability never kills a run
            pass
    return rec


def dropped_records() -> int:
    """Hot records that skipped the bounded in-process buffer (they were
    still written to the rank stream when a sink is configured)."""
    return _DROPPED


def counter(name: str, value, **attrs) -> dict | None:
    """Record a cumulative count (e.g. bytes moved, retries)."""
    if not _ENABLED:
        return None
    return emit("counter", name, value=value,
                **({"attrs": attrs} if attrs else {}))


def gauge(name: str, value, **attrs) -> dict | None:
    """Record a point-in-time measurement (e.g. Gpts/s of a finished run)."""
    if not _ENABLED:
        return None
    return emit("gauge", name, value=value,
                **({"attrs": attrs} if attrs else {}))


def record_event(name: str, *, attempt=None, step=None, wait_s=None,
                 error=None, **attrs) -> dict:
    """One structured run event (retry, restore, give-up…) — the PR-1
    resilience schema, now versioned and monotonic-stamped.

    Always buffered in-process (the `metrics.events()` contract); written
    to the rank stream when telemetry is enabled; best-effort teed to
    RMT_EVENT_LOG in the legacy line shape for existing tooling
    (docs/RESILIENCE.md §2). Extra keyword attrs (the storage-fault and
    preemption records carry reasons, deadlines, pruned-step lists —
    docs/RESILIENCE.md §7) ride flat in the record, None-valued ones
    dropped like the named fields.
    """
    payload = {
        k: v
        for k, v in (("attempt", attempt), ("step", step),
                     ("wait_s", wait_s), ("error", error))
        if v is not None
    }
    payload.update({k: v for k, v in attrs.items() if v is not None})
    rec = emit("event", name, buffer_always=True, **payload)
    legacy_path = os.environ.get("RMT_EVENT_LOG")
    if legacy_path:
        legacy = {"kind": name, "t": rec["t"], "t_mono": rec["t_mono"],
                  "v": SCHEMA_VERSION, **payload}
        try:
            with open(legacy_path, "a") as fh:
                fh.write(json.dumps(legacy) + "\n")
        except OSError:
            pass
    return rec


def annotate(name: str, **attrs) -> dict | None:
    """Trace-time annotation: static metadata observed while jax traces a
    program (shapes are concrete there) — e.g. bytes per halo exchange.

    Deduplicated per (name, attrs): jax may retrace the same program
    (abstract eval + lowering, or per-variant compiles), and "this
    compiled program exchanges N bytes per invocation" is one fact, not
    one per trace. Values must be hashable scalars for the same reason.
    """
    if not _ENABLED:
        return None
    key = (name, tuple(sorted(attrs.items())))
    with _LOCK:
        if key in _ANNOTATED:
            return None
        _ANNOTATED.add(key)
    return emit("trace", name, **({"attrs": attrs} if attrs else {}))


def records(kind: str | None = None, name: str | None = None) -> list[dict]:
    """The in-process record buffer (optionally filtered)."""
    with _LOCK:
        out = list(_RECORDS)
    if kind is not None:
        out = [r for r in out if r["kind"] == kind]
    if name is not None:
        out = [r for r in out if r["name"] == name]
    return out


def clear(kind: str | None = None) -> None:
    """Drop the in-process buffer (tests; already-written JSONL files
    are untouched). With `kind`, only that kind's records are dropped —
    `metrics.clear_events()` clears kind="event" without losing buffered
    spans/gauges or the annotation dedup set (a cleared dedup set would
    re-emit "once per compiled program" annotations on the next
    retrace). A full clear() also resets the dedup set and drop count."""
    global _DROPPED
    with _LOCK:
        if kind is None:
            _RECORDS.clear()
            _ANNOTATED.clear()
            _ANCHORED.clear()
            _DROPPED = 0
        else:
            _RECORDS[:] = [r for r in _RECORDS if r["kind"] != kind]


def clear_events() -> None:
    """THE public reset for the structured event trail: drops buffered
    "event"-kind records only — buffered spans/gauges and the
    trace-annotation dedup set survive (a cleared dedup set would
    re-emit once-per-program annotations on the next retrace). This is
    the one behavior behind `metrics.clear_events()` (a deprecated
    alias) and the flight recorder's reset path (flight.reset)."""
    clear(kind="event")
