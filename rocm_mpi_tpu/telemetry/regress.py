"""Perf-regression gate: compare a run summary against a committed baseline.

The round-3/4/5 measurement campaigns banked numbers as one-off JSON
files (BASELINE.json, MULTICHIP_r0*.json, docs/*_mechanics_*.jsonl) with
no machine that ever re-reads them — a regression was whatever a human
happened to notice. This module closes the loop:

    python -m rocm_mpi_tpu.telemetry regress SUMMARY --baseline BASE
        exit 0  within tolerance (or better)
        exit 1  regression: a metric moved the WRONG way by > tolerance
        exit 2  missing/unreadable baseline or summary (never silently
                passes — an absent baseline is a broken gate, not a green
                one)

Comparable metrics are extracted from the summary schema
(aggregate.SUMMARY_SCHEMA) with an explicit direction each:

    lower is better    steps.per_step_us.{mean,p50,p90,p99},
                       phases.{halo,interior,checkpoint}.wall_s,
                       gauges.compiles.* (compile/recompile counts —
                       included even at 0: "zero recompiles after
                       warmup" is a real measurement, and a zero
                       baseline makes ANY steady-state recompile a
                       gated regression)
    higher is better   phases.halo.bytes_per_s, every other numeric
                       gauge (gauges are rates: gpts, t_eff — the
                       driver metric)

A baseline may be (a) a summary from a previous run — the normal flow:
bank today's summary, gate tomorrow's run against it — or (b) a hand-flat
``{"metrics": {name: {"value": v, "direction": "lower"|"higher"}}}``
file for curated budgets. Improvements never fail the gate; only
directional regressions beyond `tolerance` (default 20% — CPU-mechanics
runs jitter; chip baselines can gate tighter) do.

``--check-schema`` mode validates that committed measurement artifacts
still parse and look like a format this repo knows (summary, BASELINE,
MULTICHIP probe, mechanics/telemetry JSONL) — the cheap CI guard
(scripts/lint.sh) against a hand-edit quietly bricking the gate's inputs.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

DEFAULT_TOLERANCE = 0.20

LOWER, HIGHER = "lower", "higher"


@dataclasses.dataclass(frozen=True)
class Delta:
    """One compared metric; `regressed` when it moved the wrong way by
    more than the tolerance."""

    name: str
    direction: str
    baseline: float
    current: float
    change: float  # signed relative change, + = current larger
    regressed: bool

    def describe(self) -> str:
        verdict = "REGRESSED" if self.regressed else "ok"
        return (
            f"{self.name} [{self.direction} is better]: "
            f"{self.baseline:g} -> {self.current:g} "
            f"({self.change:+.1%}) {verdict}"
        )


def extract_metrics(doc: dict) -> dict[str, tuple[float, str]]:
    """{metric name: (value, direction)} from a summary or a flat
    metrics file. Zero-valued summary entries are skipped: an unobserved
    phase is absence of evidence, not a 0-second budget."""
    out: dict[str, tuple[float, str]] = {}
    if "metrics" in doc and isinstance(doc["metrics"], dict):
        for name, spec in doc["metrics"].items():
            if isinstance(spec, dict) and "value" in spec:
                direction = spec.get("direction", LOWER)
                if direction in (LOWER, HIGHER):
                    try:
                        out[name] = (float(spec["value"]), direction)
                    except (TypeError, ValueError):
                        pass
        return out

    steps = doc.get("steps", {})
    for q, v in (steps.get("per_step_us") or {}).items():
        if isinstance(v, (int, float)) and v > 0:
            out[f"steps.per_step_us.{q}"] = (float(v), LOWER)
    for ph, row in (doc.get("phases") or {}).items():
        wall = row.get("wall_s")
        if isinstance(wall, (int, float)) and wall > 0:
            out[f"phases.{ph}.wall_s"] = (float(wall), LOWER)
        bps = row.get("bytes_per_s")
        if ph == "halo" and isinstance(bps, (int, float)) and bps > 0:
            out["phases.halo.bytes_per_s"] = (float(bps), HIGHER)
    for name, v in (doc.get("gauges") or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if name.startswith("compiles.") or name == "serve.device_bubble":
            # Compile counts AND the serving pipeline's device-bubble
            # fraction: fewer/less is better and ZERO is evidence (the
            # steady-state / fully-overlapped contracts), unlike the
            # rate gauges where an absent/zero value means "not
            # measured".
            out[f"gauges.{name}"] = (float(v), LOWER)
        elif name.startswith("serve.pipeline_"):
            # Config echoes (serve.pipeline_depth): recorded for the
            # summary reader, but a depth change is a deliberate knob,
            # not a directional health metric — never regress-gated.
            continue
        elif v > 0:
            out[f"gauges.{name}"] = (float(v), HIGHER)
    return out


def compare(summary: dict, baseline: dict,
            tolerance: float = DEFAULT_TOLERANCE) -> list[Delta]:
    """Compare every metric present in BOTH documents. The baseline's
    direction wins on disagreement (the committed gate is authoritative)."""
    if tolerance < 0:
        raise ValueError(f"tolerance must be >= 0, got {tolerance}")
    cur = extract_metrics(summary)
    base = extract_metrics(baseline)
    deltas: list[Delta] = []
    for name in sorted(set(cur) & set(base)):
        b_val, direction = base[name]
        c_val, _ = cur[name]
        if b_val == 0:
            if direction == HIGHER:
                continue  # no meaningful relative change off a 0 rate
            # A lower-is-better zero baseline is a hard pin (the
            # compiles.steady_state == 0 contract): any rise regresses.
            change = float("inf") if c_val > 0 else 0.0
            worse = c_val > 0
            deltas.append(Delta(
                name=name, direction=direction, baseline=b_val,
                current=c_val, change=change, regressed=worse,
            ))
            continue
        change = (c_val - b_val) / abs(b_val)
        worse = change > tolerance if direction == LOWER \
            else change < -tolerance
        deltas.append(Delta(
            name=name, direction=direction, baseline=b_val,
            current=c_val, change=change, regressed=worse,
        ))
    return deltas


def regressions(deltas: list[Delta]) -> list[Delta]:
    return [d for d in deltas if d.regressed]


def load_json(path) -> dict | None:
    """Parse a JSON file; None on any failure (callers turn that into
    exit 2 — a gate input that cannot be read must fail loudly)."""
    try:
        doc = json.loads(pathlib.Path(path).read_text())
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


# ---------------------------------------------------------------------------
# --check-schema: recognize the repo's committed measurement formats
# ---------------------------------------------------------------------------


def _classify_json(doc: dict) -> str | None:
    from rocm_mpi_tpu.analysis.baseline import BASELINE_SCHEMA
    from rocm_mpi_tpu.analysis.report import FINDINGS_SCHEMA
    from rocm_mpi_tpu.telemetry.aggregate import SUMMARY_SCHEMA
    from rocm_mpi_tpu.telemetry.flight import (
        BUNDLE_SCHEMA,
        HEARTBEAT_SCHEMA,
        POSTMORTEM_SCHEMA,
    )

    from rocm_mpi_tpu.serving.bins import BIN_MANIFEST_SCHEMA
    from rocm_mpi_tpu.serving.journal import FLEET_REPORT_SCHEMA
    from rocm_mpi_tpu.serving.slo import SOAK_SCHEMA
    from rocm_mpi_tpu.telemetry.tracing import TRACE_REPORT_SCHEMA

    named = {
        SUMMARY_SCHEMA: "telemetry summary",
        HEARTBEAT_SCHEMA: "health heartbeat sidecar",
        POSTMORTEM_SCHEMA: "health post-mortem",
        BUNDLE_SCHEMA: "health post-mortem bundle",
        FINDINGS_SCHEMA: "graftlint findings artifact",
        BASELINE_SCHEMA: "graftlint baseline",
        BIN_MANIFEST_SCHEMA: "serving bin manifest",
        SOAK_SCHEMA: "soak report",
        FLEET_REPORT_SCHEMA: "fleet report",
        TRACE_REPORT_SCHEMA: "trace report",
    }
    if doc.get("schema") in named:
        return named[doc["schema"]]
    if "step" in doc and "leaves" in doc and "files" in doc:
        return "checkpoint manifest"
    if "budgets" in doc and isinstance(doc.get("budgets"), dict) \
            and "v" in doc:
        return "perf budgets"
    if "metrics" in doc and isinstance(doc["metrics"], dict):
        return "flat metrics baseline"
    if "metric" in doc and "north_star" in doc:
        return "BASELINE.json north-star record"
    if "n_devices" in doc and "rc" in doc:
        return "multichip probe record"
    if "metric" in doc:
        return "bench/mechanics row"
    return None


def _validate_classified(doc: dict, kind: str) -> list[str]:
    """Deep checks for families with committed inner structure. The
    checkpoint-manifest topology metadata is the load-bearing one: a
    drifted/hand-edited meta block would brick every template-less
    elastic resume that reads it (utils.checkpoint — its
    validate_manifest_meta is stdlib-only, shared here on purpose)."""
    if kind == "checkpoint manifest":
        from rocm_mpi_tpu.utils.checkpoint import validate_manifest_meta

        return [f"manifest {p}" for p in validate_manifest_meta(doc)]
    if kind == "graftlint findings artifact":
        from rocm_mpi_tpu.analysis.report import validate_findings_doc

        return validate_findings_doc(doc)
    if kind == "graftlint baseline":
        from rocm_mpi_tpu.analysis.baseline import validate_baseline_doc

        return validate_baseline_doc(doc)
    if kind == "perf budgets":
        return _validate_perf_budgets(doc)
    if kind == "serving bin manifest":
        from rocm_mpi_tpu.serving.bins import validate_manifest_doc

        return validate_manifest_doc(doc)
    if kind == "soak report":
        from rocm_mpi_tpu.serving.slo import validate_soak_report

        return validate_soak_report(doc)
    if kind == "fleet report":
        from rocm_mpi_tpu.serving.journal import validate_fleet_report

        return validate_fleet_report(doc)
    if kind == "trace report":
        from rocm_mpi_tpu.telemetry.tracing import validate_trace_report

        return validate_trace_report(doc)
    return []


# The wire-mode registry, spelled here so the telemetry read side stays
# importable without jax (parallel.wire's tables are behind the
# parallel package's jax-importing __init__). tests/test_wire.py pins
# this tuple equal to parallel.wire.WIRE_MODES — drift fails loudly.
_WIRE_MODES = ("f32", "bf16", "int8", "int8_delta")

# Serving sidecar schema markers (rocm_mpi_tpu/serving/{queue,bins}.py
# are stdlib-at-import on purpose — the validators import directly).
# tests/test_serving.py pins these spellings against serving.queue.
_SERVE_REQUEST_SCHEMA = "rmt-serve-request"
_QUARANTINE_SCHEMA = "rmt-serve-quarantine"
# tests/test_fleet.py pins this spelling against serving.journal.
_FLEET_JOURNAL_SCHEMA = "rmt-fleet-journal"


def _validate_perf_budgets(doc: dict) -> list[str]:
    """perf/budgets.json (docs/PERF.md): per-variant A_eff ratio budgets
    plus the PR-12 wire-bytes ladder block. A hand-edited row (negative
    budget, unknown wire mode, fraction over 1.02) must fail HERE, not
    silently loosen — or brick — the traffic gate that reads it."""
    problems = []
    for name, v in doc["budgets"].items():
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
            problems.append(f"budget {name!r} is not a positive number")
    serving = doc.get("serving")
    if serving is not None:
        if not isinstance(serving, dict):
            problems.append("'serving' block is not an object")
        else:
            tol = serving.get("batch_tolerance")
            if not isinstance(tol, (int, float)) or isinstance(tol, bool) \
                    or tol < 1.0:
                problems.append(
                    f"serving batch_tolerance {tol!r} must be >= 1.0 "
                    "(a B-lane program can never move fewer bytes than "
                    "B x one lane)"
                )
            hide = serving.get("hide_tolerance")
            if hide is not None and (
                not isinstance(hide, (int, float))
                or isinstance(hide, bool) or hide < 1.0
            ):
                problems.append(
                    f"serving hide_tolerance {hide!r} must be >= 1.0 "
                    "(the batched-hide program is gated per lane "
                    "against the single-lane exchanged-step ideal)"
                )
            floor = serving.get("occupancy_floor")
            if not isinstance(floor, (int, float)) \
                    or isinstance(floor, bool) or not 0.0 < floor <= 1.0:
                problems.append(
                    f"serving occupancy_floor {floor!r} outside (0, 1]"
                )
            ptol = serving.get("padded_flops_tolerance")
            if ptol is not None and (
                not isinstance(ptol, (int, float))
                or isinstance(ptol, bool) or ptol < 0.0
            ):
                problems.append(
                    f"serving padded_flops_tolerance {ptol!r} must be "
                    ">= 0 (the ladder's padded-FLOPs inflation cap; 0 "
                    "admits only exact-rung shapes)"
                )
            occ = serving.get("occupancy")
            if occ is not None and (
                not isinstance(occ, (int, float))
                or isinstance(occ, bool) or not 0.0 < occ <= 1.0
            ):
                problems.append(
                    f"serving occupancy {occ!r} outside (0, 1] (the "
                    "continuous drain's step-weighted occupancy floor)"
                )
    wire = doc.get("wire")
    if wire is None:
        return problems
    if not isinstance(wire, dict):
        return problems + ["'wire' block is not an object"]
    ladder = wire.get("ladder")
    if not isinstance(ladder, dict) or not ladder:
        problems.append("wire block missing its 'ladder' rows")
        return problems
    for mode, frac in ladder.items():
        if mode not in _WIRE_MODES:
            problems.append(
                f"wire ladder names unknown mode {mode!r} "
                f"(known: {list(_WIRE_MODES)})"
            )
        if not isinstance(frac, (int, float)) or isinstance(frac, bool) \
                or not 0 < frac <= 1.02:
            problems.append(
                f"wire ladder row {mode!r}={frac!r} outside (0, 1.02]"
            )
    return problems


def _validate_elastic_record(doc: dict) -> list[str]:
    """elastic.jsonl record validation (telemetry.health owns the
    format; resilience.elastic writes it): every record names its event
    and is wall-stamped; a shrink or grow must carry the old→new rank
    counts the monitor's SHRUNK / GROWN badges are computed from."""
    problems = []
    name = doc.get("name")
    if not isinstance(name, str) or not name.startswith("elastic."):
        problems.append(f"elastic record name {name!r} (want elastic.*)")
    if not isinstance(doc.get("t"), (int, float)):
        problems.append("elastic record missing wall stamp t")
    if name in ("elastic.shrink", "elastic.grow"):
        for key in ("old_nprocs", "new_nprocs"):
            if not isinstance(doc.get(key), int):
                problems.append(f"{name} missing {key}")
    return problems


# Event families whose archived records carry committed inner structure
# (docs/RESILIENCE.md §7): the preemption decision trail and the
# storage-fault plane. Validated wherever a telemetry JSONL stream gets
# banked (chip_watcher archives rank streams per burst) — a drifted
# writer must fail here, not as an unreadable loss-window audit after
# the next real eviction/outage.
_GUARDED_EVENT_PREFIXES = ("preempt.", "ckpt.")


def _validate_event_record(doc: dict) -> list[str]:
    """Telemetry "event"-kind records for the preempt.* / ckpt.*
    families: every one is anchored to the segment boundary that decided
    it (an int `step`); a `ckpt.degraded` additionally names its reason
    — the field the loss-window audit groups on."""
    name = doc.get("name")
    if not isinstance(name, str):
        return []
    if name == "serve.request.done" and doc.get("decomp") is not None:
        # The per-request latency decomposition (PR-20 request
        # tracing): stage keys and non-negative times, validated by
        # the tracing module's shared stdlib checker.
        from rocm_mpi_tpu.telemetry.tracing import validate_decomposition

        return validate_decomposition(doc["decomp"])
    if not name.startswith(_GUARDED_EVENT_PREFIXES):
        return []
    problems = []
    if not isinstance(doc.get("step"), int):
        problems.append(f"{name} event missing int step")
    if name == "ckpt.degraded" and not isinstance(doc.get("reason"), str):
        problems.append("ckpt.degraded event missing reason")
    return problems


def check_schema(paths) -> list[str]:
    """Validate committed measurement artifacts. Returns problem strings
    (empty = all recognized). `.jsonl` files are checked line-by-line;
    `.json` files as one document."""
    from rocm_mpi_tpu.telemetry.health import ELASTIC_SCHEMA

    problems: list[str] = []
    for raw in paths:
        path = pathlib.Path(raw)
        if not path.is_file():
            problems.append(f"{raw}: missing")
            continue
        try:
            text = path.read_text()
        except OSError as e:
            problems.append(f"{raw}: unreadable ({e})")
            continue
        if path.suffix == ".jsonl":
            for i, line in enumerate(text.splitlines(), 1):
                if not line.strip():
                    continue
                try:
                    doc = json.loads(line)
                except ValueError as e:
                    problems.append(f"{raw}:{i}: bad JSON line ({e})")
                    continue
                if not isinstance(doc, dict) or not (
                    "metric" in doc or ("kind" in doc and "v" in doc)
                ):
                    problems.append(
                        f"{raw}:{i}: unrecognized JSONL record "
                        "(want a mechanics row or a telemetry event)"
                    )
                    continue
                if doc.get("schema") == ELASTIC_SCHEMA:
                    for p in _validate_elastic_record(doc):
                        problems.append(f"{raw}:{i}: {p}")
                elif doc.get("schema") == _SERVE_REQUEST_SCHEMA:
                    from rocm_mpi_tpu.serving.queue import (
                        validate_request_record,
                    )

                    for p in validate_request_record(doc):
                        problems.append(f"{raw}:{i}: {p}")
                elif doc.get("schema") == _QUARANTINE_SCHEMA:
                    from rocm_mpi_tpu.serving.queue import (
                        validate_quarantine_record,
                    )

                    for p in validate_quarantine_record(doc):
                        problems.append(f"{raw}:{i}: {p}")
                elif doc.get("schema") == _FLEET_JOURNAL_SCHEMA:
                    from rocm_mpi_tpu.serving.journal import (
                        validate_journal_record,
                    )

                    for p in validate_journal_record(doc):
                        problems.append(f"{raw}:{i}: {p}")
                elif doc.get("kind") == "event":
                    for p in _validate_event_record(doc):
                        problems.append(f"{raw}:{i}: {p}")
        else:
            try:
                doc = json.loads(text)
            except ValueError as e:
                problems.append(f"{raw}: bad JSON ({e})")
                continue
            kind = _classify_json(doc) if isinstance(doc, dict) else None
            if kind is None:
                problems.append(
                    f"{raw}: unrecognized schema (known: telemetry "
                    "summary, flat metrics, BASELINE, multichip probe, "
                    "bench row, checkpoint manifest)"
                )
            else:
                for p in _validate_classified(doc, kind):
                    problems.append(f"{raw}: {p}")
    return problems
