"""Structured observability for the framework (docs/TELEMETRY.md).

The write side (this package's hot half) is stdlib-only and gated on one
bool so instrumented code costs nothing when telemetry is off:

    from rocm_mpi_tpu import telemetry

    telemetry.configure(directory="out/telemetry", rank=jax.process_index())
    with telemetry.span("step_window", phase="step", steps=50) as sp:
        T = advance(T, Cp, 50)
        sp.sync(T)                      # device-fetch sync, not block_until_ready
    telemetry.gauge("run.gpts", r.gpts)
    telemetry.record_event("restored", step=120)   # resilience kinds

Every rank appends to its own `telemetry-rank{k}.jsonl` (versioned
schema: telemetry.events). The read side merges them:

    python -m rocm_mpi_tpu.telemetry summarize DIR        # + Chrome trace
    python -m rocm_mpi_tpu.telemetry regress S --baseline B

Layer map: spans/events collect (write side); aggregate merges and
attributes (halo / interior / checkpoint / step, stragglers); trace
exports to Perfetto; regress gates PRs on committed baselines; probes
(jax-needing, imported lazily) measure phase attribution for fused step
programs that expose no seams at runtime.

The runtime health plane rides on top (docs/TELEMETRY.md "Health
plane"): flight (write side — per-rank flight recorder, heartbeat
sidecars, SIGUSR2 post-mortems), health (read side — sidecar tailing,
the progress-aware stall verdict, monitor/OpenMetrics), compiles
(per-program compile + recompile accounting through utils/compat):

    python -m rocm_mpi_tpu.telemetry monitor DIR
    python -m rocm_mpi_tpu.telemetry export-openmetrics DIR
"""

from rocm_mpi_tpu.telemetry.events import (
    SCHEMA_VERSION,
    annotate,
    clear,
    clear_events,
    configure,
    counter,
    enabled,
    gauge,
    rank,
    record_event,
    records,
    stream_path,
)
from rocm_mpi_tpu.telemetry.spans import span, span_record

__all__ = [
    "SCHEMA_VERSION",
    "annotate",
    "clear",
    "clear_events",
    "configure",
    "counter",
    "enabled",
    "gauge",
    "rank",
    "record_event",
    "records",
    "span",
    "span_record",
    "stream_path",
]
