"""Request-scoped distributed tracing (docs/TELEMETRY.md "Request
tracing"): causal timelines for a request's whole life across the fleet.

A request's path in the fleet era is router -> replica queue -> bin ->
batched drain -> (segment swaps) -> terminal, and may re-route to a
second replica when its first one dies mid-batch. Spans (PR 3) see
phases and the health plane (PR 5) sees ranks, but neither connects one
request's transitions causally. This module does, with three pieces:

* A `TraceContext` — trace_id (ALWAYS the request_id: one request is
  one trace, no id mapping layer), a per-process minted span_id, the
  parent span_id, and a hop counter (0 = first route; +1 per re-route
  after a replica kill). Contexts ride `serving.queue.Request.trace` as
  a plain dict (the v3 request schema's optional field) so they survive
  the wire and the journal untouched.

* A new `tspan`-kind record on the existing v2 JSONL streams
  (`emit_tspan`): trace.submit / trace.route / trace.batch /
  trace.segment, each stamped with the context. Batch records carry a
  `members` roster ({trace_id, lane}), so per-request device spans are
  DERIVED from batch spans plus lane occupancy — the stream stays
  O(batches), not O(requests x stages). Swapped-in lanes (PR 19) appear
  in the `trace.segment` record of the boundary they joined at.

* A per-process wall<->monotonic clock anchor (`anchor`-kind record,
  emitted once per sink by `events.configure()`): the record's own
  header stamps `t` (wall) and `t_mono` (monotonic) back to back, and
  that pair IS the anchor — the fleet merger maps any record's t_mono
  into comparable wall time via `anchor_t + (t_mono - anchor_t_mono)`.
  Streams without an anchor (legacy, or env-configured ranks that never
  called configure()) fall back to per-record wall stamps and are
  WARNED about, never silently misaligned (telemetry/trace.py).

Latency decomposition: the serving layer attributes every terminal
ticket's life to the stages in `DECOMP_STAGES` by telescoping marks
(`serving.queue.Ticket.trace_mark`) — each transition charges the time
since the previous mark to one stage, so the stages sum EXACTLY to the
done-event latency by construction. The per-request block rides the
`serve.request.done` event (`decomp`, `hop`) and aggregates into the
SLO reports (serving/slo.py).

stdlib-only end to end, like the whole telemetry read side: the `trace`
CLI verb must run on a box with no jax at all.
"""

from __future__ import annotations

import itertools
import json
import os
import pathlib
import time

from rocm_mpi_tpu.telemetry import events

# Record kinds this module owns on the v2 streams.
TRACE_KIND = "tspan"
ANCHOR_KIND = "anchor"
ANCHOR_NAME = "clock.anchor"

TRACE_REPORT_SCHEMA = "rmt-trace-report"
TRACE_REPORT_VERSION = 1

# The latency-decomposition stages, in causal order (docs/TELEMETRY.md
# "Request tracing" documents each boundary). Pinned by tests — the SLO
# aggregation, the report validator, and the serving marks must agree.
DECOMP_STAGES = (
    "queue_wait",  # submit -> popped into a drain (minus backoff)
    "backoff",     # retry-parked and ineligible (not_before in force)
    "compile",     # program-class acquisition for the request's batch
    "device",      # dispatched: assembly/upload through device compute
    "swap_wait",   # continuous drain: waiting for a free lane/seat
    "fetch",       # the blocking device->host fetch of its batch
    "resolve",     # per-lane resolution (finiteness, session save)
)

_SPAN_COUNTER = itertools.count(1)


class TraceContext:
    """One request's position in its trace (module docstring). Treated
    as immutable — transitions mint new contexts (`child`, `next_hop`)
    so a journaled wire dict never mutates under its reader."""

    __slots__ = ("trace_id", "span_id", "parent_id", "hop")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str | None = None, hop: int = 0):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_id = parent_id if parent_id is None else str(parent_id)
        self.hop = int(hop)

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, {self.span_id!r}, "
                f"parent={self.parent_id!r}, hop={self.hop})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and to_wire(self) == to_wire(other))


def _next_span_id() -> str:
    """Process-unique span id: rank-prefixed so two replicas' spans of
    one trace never collide even when minted at the same count."""
    return f"s{events.rank()}.{next(_SPAN_COUNTER)}"


def mint(trace_id: str) -> TraceContext:
    """Root context for a request entering the system (hop 0)."""
    return TraceContext(trace_id, _next_span_id())


def child(ctx: TraceContext) -> TraceContext:
    """A new span under `ctx`, same hop (a stage within one replica)."""
    return TraceContext(ctx.trace_id, _next_span_id(),
                        parent_id=ctx.span_id, hop=ctx.hop)


def next_hop(ctx: TraceContext) -> TraceContext:
    """The failover transition: a re-route after a replica kill is a
    new hop — new span, parent = the dead hop's span, hop + 1."""
    return TraceContext(ctx.trace_id, _next_span_id(),
                        parent_id=ctx.span_id, hop=ctx.hop + 1)


def to_wire(ctx: TraceContext | None) -> dict | None:
    """The context as the plain dict that rides Request.trace (v3)."""
    if ctx is None:
        return None
    doc = {"trace_id": ctx.trace_id, "span_id": ctx.span_id,
           "hop": ctx.hop}
    if ctx.parent_id is not None:
        doc["parent_id"] = ctx.parent_id
    return doc


def from_wire(doc) -> TraceContext | None:
    """Parse a wire dict back into a context; None on anything that is
    not one (tolerant: a legacy v2 request simply has no trace)."""
    if not isinstance(doc, dict):
        return None
    tid = doc.get("trace_id")
    sid = doc.get("span_id")
    if not isinstance(tid, str) or not isinstance(sid, str):
        return None
    pid = doc.get("parent_id")
    hop = doc.get("hop", 0)
    return TraceContext(
        tid, sid,
        parent_id=pid if isinstance(pid, str) else None,
        hop=hop if isinstance(hop, int) and not isinstance(hop, bool)
        else 0,
    )


def validate_wire(doc) -> list[str]:
    """Problem strings for a Request.trace wire dict (the v3 request
    record validator defers here)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"trace {doc!r} is not an object"]
    for key in ("trace_id", "span_id"):
        if not isinstance(doc.get(key), str) or not doc.get(key):
            problems.append(f"trace.{key} {doc.get(key)!r} not a string")
    hop = doc.get("hop")
    if not isinstance(hop, int) or isinstance(hop, bool) or hop < 0:
        problems.append(f"trace.hop {hop!r} not a non-negative int")
    pid = doc.get("parent_id")
    if pid is not None and not isinstance(pid, str):
        problems.append(f"trace.parent_id {pid!r} not a string")
    return problems


def emit_tspan(name: str, ctx: TraceContext | None, **fields):
    """One tspan record under `ctx` on this rank's stream. The hot-path
    guard is the same one every span pays (`events.enabled()`); with no
    context (tracing disabled at the serving layer) it is a no-op."""
    if ctx is None or not events.enabled():
        return None
    return events.emit(
        TRACE_KIND, name,
        trace_id=ctx.trace_id, span_id=ctx.span_id,
        parent_id=ctx.parent_id, hop=ctx.hop, **fields,
    )


# ---------------------------------------------------------------------------
# read side: anchors, timelines, the trace report
# ---------------------------------------------------------------------------


def anchor_of(records) -> tuple[float, float] | None:
    """The stream's (t_wall, t_mono) clock anchor, or None (legacy)."""
    for rec in records:
        if rec.get("kind") != ANCHOR_KIND:
            continue
        t, tm = rec.get("t"), rec.get("t_mono")
        if isinstance(t, (int, float)) and isinstance(tm, (int, float)):
            return (float(t), float(tm))
    return None


def aligned_wall(rec: dict, anchor: tuple[float, float] | None):
    """A record's wall time on the fleet-comparable clock: anchored
    streams map the record's monotonic stamp through the anchor (tear-
    free within the rank, comparable across replicas); anchor-less
    streams fall back to the record's own wall stamp."""
    tm = rec.get("t_mono")
    if anchor is not None and isinstance(tm, (int, float)):
        return anchor[0] + (float(tm) - anchor[1])
    t = rec.get("t")
    return float(t) if isinstance(t, (int, float)) else None


def _mentions(rec: dict, request_id: str) -> bool:
    """Does this record belong to `request_id`'s trace? Direct stamps
    (trace_id on tspans, request_id on serve events) or roster
    membership (batch/segment records carry {trace_id, lane} rows)."""
    if rec.get("trace_id") == request_id \
            or rec.get("request_id") == request_id:
        return True
    for row in rec.get("members") or ():
        if isinstance(row, dict) and row.get("trace_id") == request_id:
            return True
    return False


# Terminal serve events, keyed by the event name's outcome suffix.
_TERMINAL_EVENTS = {
    "serve.request.done": "done",
    "serve.request.quarantined": "quarantined",
    "serve.request.rejected": "rejected",
    "serve.request.expired": "expired",
}


def request_timeline(streams: dict[int, list[dict]],
                     request_id: str) -> dict | None:
    """The causal timeline of one request across every rank stream:
    its tspan records, its serve.* events, and the batch/segment
    records whose roster names it — sorted on the anchor-aligned wall
    clock. Returns None when no stream mentions the request."""
    rows: list[dict] = []
    warnings: list[str] = []
    terminal = None
    decomp = None
    latency = None
    hops: set[int] = set()
    for rk in sorted(streams):
        recs = streams[rk]
        anchor = anchor_of(recs)
        if anchor is None and recs:
            warnings.append(
                f"rank {rk}: no clock anchor (legacy stream) — its "
                "events use per-record wall stamps and may misalign "
                "against anchored ranks"
            )
        for rec in recs:
            if rec.get("kind") not in (TRACE_KIND, "event"):
                continue
            if not _mentions(rec, request_id):
                continue
            wall = aligned_wall(rec, anchor)
            if wall is None:
                continue
            name = rec.get("name", "?")
            hop = rec.get("hop")
            if isinstance(hop, int) and not isinstance(hop, bool):
                hops.add(hop)
            row = {"t": wall, "rank": rk, "kind": rec.get("kind"),
                   "name": name}
            for key in ("span_id", "parent_id", "hop", "seq", "seg",
                        "bin", "width", "lane", "replica", "reroute",
                        "error", "state", "latency_s", "retries"):
                if rec.get(key) is not None:
                    row[key] = rec[key]
            rows.append(row)
            if name in _TERMINAL_EVENTS:
                terminal = _TERMINAL_EVENTS[name]
            if name == "serve.request.done":
                if isinstance(rec.get("latency_s"), (int, float)):
                    latency = float(rec["latency_s"])
                if isinstance(rec.get("decomp"), dict):
                    decomp = dict(rec["decomp"])
    if not rows:
        return None
    rows.sort(key=lambda r: r["t"])
    return {
        "request_id": request_id,
        "hops": sorted(hops),
        "terminal": terminal,
        "latency_s": latency,
        "decomposition": decomp,
        "events": rows,
        "warnings": warnings,
    }


def trace_report_doc(timeline: dict) -> dict:
    """The schema-versioned trace report (`rmt-trace-report` v1) for
    one request — the artifact `telemetry trace --out` banks and
    `regress --check-schema` gates."""
    return {
        "schema": TRACE_REPORT_SCHEMA,
        "v": TRACE_REPORT_VERSION,
        # Record wall STAMP (the header convention) — not an interval.
        "t": time.time(),
        **{k: timeline.get(k) for k in (
            "request_id", "hops", "terminal", "latency_s",
            "decomposition", "events", "warnings",
        )},
    }


def validate_trace_report(doc: dict) -> list[str]:
    """Problem strings for a trace-report document (stdlib; shared with
    `telemetry regress --check-schema`)."""
    problems: list[str] = []
    if doc.get("schema") != TRACE_REPORT_SCHEMA:
        problems.append(
            f"schema {doc.get('schema')!r} != {TRACE_REPORT_SCHEMA}"
        )
    if not isinstance(doc.get("v"), int):
        problems.append("missing int v")
    if not isinstance(doc.get("request_id"), str) \
            or not doc.get("request_id"):
        problems.append("missing request_id")
    hops = doc.get("hops")
    if not isinstance(hops, list) or not all(
        isinstance(h, int) and not isinstance(h, bool) for h in hops
    ):
        problems.append("hops is not a list of ints")
    evs = doc.get("events")
    if not isinstance(evs, list) or not evs:
        problems.append("missing non-empty events list")
    else:
        for i, ev in enumerate(evs):
            if not isinstance(ev, dict) \
                    or not isinstance(ev.get("name"), str) \
                    or not isinstance(ev.get("t"), (int, float)):
                problems.append(f"events[{i}] missing name/t")
    problems += validate_decomposition(doc.get("decomposition"))
    return problems


def validate_decomposition(decomp) -> list[str]:
    """Problem strings for a per-request decomposition dict (None is
    fine: a non-terminal or tracing-off request has none). Stage keys
    must come from DECOMP_STAGES and values must be non-negative
    seconds — the telescoping-marks contract."""
    if decomp is None:
        return []
    if not isinstance(decomp, dict):
        return [f"decomposition {decomp!r} is not an object"]
    problems = []
    for stage, v in decomp.items():
        if stage not in DECOMP_STAGES:
            problems.append(
                f"decomposition stage {stage!r} unknown "
                f"(known: {list(DECOMP_STAGES)})"
            )
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v < 0:
            problems.append(
                f"decomposition.{stage} {v!r} not a non-negative time"
            )
    return problems


def write_trace_report(path, doc: dict) -> None:
    """Atomic tmp+rename write (GL09 discipline), validated first."""
    problems = validate_trace_report(doc)
    if problems:
        raise ValueError("bad trace report: " + "; ".join(problems))
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def format_timeline(timeline: dict) -> str:
    """The human causal timeline: one line per event, indented by hop,
    timed relative to the request's first observation."""
    rows = timeline["events"]
    t0 = rows[0]["t"] if rows else 0.0
    lines = [
        f"trace {timeline['request_id']}: "
        f"{len(rows)} event(s), hops {timeline['hops'] or [0]}, "
        f"terminal={timeline['terminal'] or '(none)'}"
    ]
    for w in timeline.get("warnings") or ():
        lines.append(f"  warning: {w}")
    for row in rows:
        hop = row.get("hop")
        indent = "  " * (1 + (hop if isinstance(hop, int) else 0))
        extra = []
        for key in ("replica", "seq", "seg", "bin", "width", "lane",
                    "state", "retries", "error"):
            if row.get(key) is not None:
                extra.append(f"{key}={row[key]}")
        if row.get("reroute"):
            extra.append("REROUTE")
        lines.append(
            f"{indent}+{row['t'] - t0:9.4f}s r{row['rank']} "
            f"{row['name']}" + (f"  [{', '.join(extra)}]" if extra else "")
        )
    decomp = timeline.get("decomposition")
    if decomp:
        total = sum(decomp.values())
        lines.append(f"  decomposition (sum {total:.4f}s"
                     + (f", done latency {timeline['latency_s']:.4f}s"
                        if timeline.get("latency_s") is not None else "")
                     + "):")
        for stage in DECOMP_STAGES:
            if stage in decomp:
                lines.append(f"    {stage:<10} {decomp[stage]:9.4f}s")
    return "\n".join(lines)


def to_request_chrome(timeline: dict) -> dict:
    """A Chrome-trace document for ONE request: a track (pid) per hop,
    instants for every causal event, and — when the request terminated
    with a decomposition — the stage ladder as slices on its terminal
    hop, chained back from the done stamp (the stages telescope, so
    end-to-end they tile the measured latency exactly)."""
    rows = timeline["events"]
    t0 = rows[0]["t"] if rows else 0.0
    events_out: list[dict] = []
    hops = timeline["hops"] or [0]
    for hop in hops:
        events_out.append({
            "name": "process_name", "ph": "M", "pid": hop, "ts": 0,
            "args": {"name": f"hop {hop}"},
        })
    for row in rows:
        hop = row.get("hop") if isinstance(row.get("hop"), int) else 0
        events_out.append({
            "name": row["name"], "ph": "i", "s": "p",
            "ts": (row["t"] - t0) * 1e6, "pid": hop, "tid": 0,
            "args": {k: v for k, v in row.items()
                     if k not in ("t", "name", "kind")},
        })
    decomp = timeline.get("decomposition")
    done_t = None
    for row in rows:
        if row["name"] == "serve.request.done":
            done_t = row["t"]
    if decomp and done_t is not None:
        hop = max(hops)
        end = done_t
        for stage in reversed(DECOMP_STAGES):
            dur = float(decomp.get(stage, 0.0))
            if dur <= 0:
                continue
            events_out.append({
                "name": stage, "ph": "X",
                "ts": (end - dur - t0) * 1e6, "dur": dur * 1e6,
                "pid": hop, "tid": 1, "args": {"stage": stage},
            })
            end -= dur
    events_out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {
        "traceEvents": events_out,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "rocm_mpi_tpu.telemetry.tracing",
            "request_id": timeline["request_id"],
        },
    }


def write_request_chrome(timeline: dict, path) -> dict:
    """Export the per-request per-hop Chrome trace at `path`."""
    from rocm_mpi_tpu.telemetry.aggregate import write_json_atomic

    doc = to_request_chrome(timeline)
    write_json_atomic(pathlib.Path(path), doc, indent=None)
    return doc
