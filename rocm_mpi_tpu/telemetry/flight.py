"""Per-rank flight recorder: the write side of the runtime health plane.

The PR-3 telemetry stream is append-only JSONL — great for post-run
attribution, useless for diagnosing a run that is WEDGED: a rank blocked
inside a collective stops appending, and nothing on disk says which rank
stopped making progress or why (the launcher's old heartbeat only knew
"ranks alive" by wall clock). This module is the black box that survives
the crash:

* a bounded **ring** of the last N span/event records (fed by a tap on
  `telemetry.events.emit` plus span-ENTRY notes from `telemetry.spans` —
  exits alone would miss the phase a rank is currently stuck in);
* monotonically increasing **progress counters** — step index, halo
  exchanges completed, halo bytes moved — plus the last phase entered;
* a **heartbeat sidecar** `heartbeat-rank{k}.json`, flushed via atomic
  tmp+rename at low frequency, so an *out-of-process* reader (the
  launcher's watchdog, the `monitor` CLI) sees this rank's last recorded
  progress even while the rank itself is blocked inside a collective and
  cannot run another line of Python;
* a **post-mortem hook**: `install_postmortem_handler()` registers
  SIGUSR2 with `faulthandler` — the C-level dumper, chosen precisely
  because a Python-level `signal.signal` handler never runs while the
  interpreter is wedged inside a C collective — appending an all-thread
  traceback to `postmortem-rank{k}.traceback`. The watchdog composes
  that text with the last heartbeat into `postmortem-rank{k}.json`
  (telemetry.health.write_postmortem): out-of-process composition is the
  only kind a wedged rank can be relied on to cooperate with.

Flush ordering contract: `progress()` flushes BEFORE the caller enters
the next potentially-blocking region whenever the step counter changed.
The watchdog's stalled-collective signature (a rank's step counter
behind the advancing cross-rank median) only works if a rank that is
about to block has already published the bump it reached — see
parallel/launcher.py.

Config mirrors telemetry.events: env first (`RMT_HEALTH=1`, sidecar dir
from `RMT_HEALTH_DIR` falling back to `RMT_TELEMETRY_DIR` — the
sidecars live next to the rank streams), or `enable()` from an app's
`--health` flag. stdlib-only; `enabled()` is one module-global read.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import signal
import threading
import time

from rocm_mpi_tpu.telemetry import events

HEARTBEAT_SCHEMA = "rocm_mpi_tpu.telemetry.heartbeat"
HEARTBEAT_VERSION = 1
POSTMORTEM_SCHEMA = "rocm_mpi_tpu.telemetry.postmortem"
POSTMORTEM_VERSION = 1
BUNDLE_SCHEMA = "rocm_mpi_tpu.telemetry.postmortem_bundle"
BUNDLE_VERSION = 1

DEFAULT_RING_SIZE = 64
DEFAULT_FLUSH_INTERVAL_S = 0.25

_FALSY = ("0", "off", "false", "no", "")

_LOCK = threading.Lock()
_ENABLED = False
_DIR: str | None = None
_RANK: int | None = None
_RING: collections.deque = collections.deque(maxlen=DEFAULT_RING_SIZE)
_COUNTERS: dict[str, int] = {}
_LAST_PHASE: str | None = None
_LAST_PHASE_NAME: str | None = None
_LAST_PHASE_T: float | None = None
_FLUSH_INTERVAL_S = DEFAULT_FLUSH_INTERVAL_S
_LAST_FLUSH_MONO = 0.0
_STARTED_T = None
_TRACEBACK_FH = None  # keeps the faulthandler sink open for the process
# Trace ids of the requests currently riding a dispatched batch on this
# rank (serving/service.py marks them at dispatch, clears at resolve):
# a wedged rank's heartbeat then names WHICH requests are stuck in
# flight, not just that a batch is — the post-mortem's causal handle
# into the request-trace plane (telemetry/tracing.py).
_INFLIGHT_TRACES: set = set()


def enabled() -> bool:
    """The hot-path guard: one module-global read."""
    return _ENABLED


def _rank() -> int:
    if _RANK is not None:
        return _RANK
    return events.rank()


def _phase_of(name: str, attrs: dict | None) -> str:
    """A span's phase, by the same rule aggregate.phase_of applies on the
    read side (explicit attr wins, else the dotted name's head)."""
    if attrs and "phase" in attrs:
        return str(attrs["phase"])
    head = str(name).split(".", 1)[0]
    return "step" if head == "step_window" else head


def enable(directory=None, rank: int | None = None,
           ring_size: int | None = None,
           flush_interval_s: float | None = None) -> None:
    """Turn the flight recorder on. `directory` (default: the telemetry
    sink, then RMT_HEALTH_DIR/RMT_TELEMETRY_DIR) is where the heartbeat
    sidecar lands; created on the spot so a misconfigured sink fails
    here, not silently at every flush."""
    global _ENABLED, _DIR, _RANK, _RING, _FLUSH_INTERVAL_S, _STARTED_T
    with _LOCK:
        directory = (
            directory
            or os.environ.get("RMT_HEALTH_DIR")
            or events.directory()
            or os.environ.get("RMT_TELEMETRY_DIR")
        )
        if directory is None:
            raise ValueError(
                "flight recorder needs a sidecar directory: pass one, or "
                "configure telemetry (--telemetry DIR / RMT_TELEMETRY_DIR)"
            )
        _DIR = str(directory)
        os.makedirs(_DIR, exist_ok=True)
        if rank is not None:
            _RANK = int(rank)
        if ring_size is not None:
            _RING = collections.deque(_RING, maxlen=int(ring_size))
        if flush_interval_s is not None:
            _FLUSH_INTERVAL_S = float(flush_interval_s)
        if _STARTED_T is None:
            _STARTED_T = time.time()
        _ENABLED = True
    if not events.enabled():
        # The recorder rides the span/event stream: ring entries and the
        # "last phase entered" come from spans, which short-circuit to
        # no-ops while collection is off. Health WITHOUT telemetry would
        # flush structurally-valid but empty sidecars — last_phase null,
        # ring [] — and the watchdog's post-mortem would say nothing. So
        # arming the recorder arms collection too, into the same dir.
        events.configure(enabled=True, directory=_DIR, rank=rank)
    events.set_tap(_on_record)
    flush()


def enable_from_env() -> bool:
    """Enable when the launcher contract says so (RMT_HEALTH truthy);
    returns whether the recorder is on afterwards. Cheap when unset."""
    flag = os.environ.get("RMT_HEALTH")
    if flag is None or flag.lower() in _FALSY:
        return _ENABLED
    if not _ENABLED:
        enable()
    return True


def disable() -> None:
    """Stop recording and detach the events tap (tests)."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False
    events.set_tap(None)


def reset() -> None:
    """The one reset behavior (the satellite-6 contract): the flight ring
    and counters are cleared AND the buffered event trail is dropped via
    `events.clear_events()` — which preserves buffered spans/gauges and
    the trace-annotation dedup set. Exactly one semantics, shared with
    every other caller of clear_events."""
    global _LAST_PHASE, _LAST_PHASE_NAME, _LAST_PHASE_T, _STARTED_T
    with _LOCK:
        _RING.clear()
        _COUNTERS.clear()
        _INFLIGHT_TRACES.clear()
        _LAST_PHASE = _LAST_PHASE_NAME = _LAST_PHASE_T = None
        _STARTED_T = None
    events.clear_events()


def sidecar_path() -> str | None:
    """This rank's heartbeat sidecar path (None while disabled)."""
    if _DIR is None:
        return None
    return os.path.join(_DIR, f"heartbeat-rank{_rank()}.json")


def traceback_path() -> str | None:
    """Where the SIGUSR2 faulthandler dump lands (None while disabled)."""
    if _DIR is None:
        return None
    return os.path.join(_DIR, f"postmortem-rank{_rank()}.traceback")


def _compact(rec: dict) -> dict:
    """Ring entries keep the fields the post-mortem reader needs and drop
    the rest — the ring rides inside every heartbeat flush."""
    out = {k: rec[k] for k in ("kind", "name", "t", "t_mono") if k in rec}
    for k in ("dur_s", "error", "step", "phase"):
        if k in rec:
            out[k] = rec[k]
    attrs = rec.get("attrs")
    if isinstance(attrs, dict):
        kept = {
            k: attrs[k]
            for k in ("phase", "steps", "bytes", "probe", "variant")
            if k in attrs
        }
        if kept:
            out["attrs"] = kept
    return out


def _on_record(rec: dict) -> None:
    """events.emit tap: every emitted record lands in the ring; halo
    spans also advance the exchange/byte counters (the fused paths
    annotate bytes at trace time, but the spans that DO run at runtime —
    host-staged oracle, probes, heartbeat probes — are counted here)."""
    if not _ENABLED:
        return
    with _LOCK:
        _RING.append(_compact(rec))
        if rec.get("kind") == "span" and \
                _phase_of(rec.get("name", ""), rec.get("attrs")) == "halo":
            _COUNTERS["halo_exchanges"] = _COUNTERS.get("halo_exchanges", 0) + 1
            attrs = rec.get("attrs") or {}
            nbytes = attrs.get("bytes", 0)
            if isinstance(nbytes, int):
                _COUNTERS["halo_bytes"] = (
                    _COUNTERS.get("halo_bytes", 0) + nbytes
                )
    _maybe_flush()


def enter_phase(name: str, attrs: dict | None = None) -> None:
    """Span-ENTRY note (telemetry.spans calls this): records the phase
    the rank is in RIGHT NOW — a rank wedged inside a halo collective
    never reaches the span's exit record, and "last phase entered" is
    exactly what its post-mortem must say. A phase CHANGE bypasses the
    flush rate limit: the sidecar must say "halo" before the rank blocks
    there, not after."""
    global _LAST_PHASE, _LAST_PHASE_NAME, _LAST_PHASE_T
    if not _ENABLED:
        return
    phase = _phase_of(name, attrs)
    with _LOCK:
        changed = phase != _LAST_PHASE
        _LAST_PHASE = phase
        _LAST_PHASE_NAME = name
        _LAST_PHASE_T = time.time()
        _RING.append({
            "kind": "phase", "name": name, "phase": phase,
            "t": _LAST_PHASE_T, "t_mono": time.perf_counter(),
        })
    _maybe_flush(force=changed)


def progress(step: int | None = None, step_inc: int | None = None,
             **counts) -> None:
    """Advance the progress counters. `step` sets the absolute step
    index (monotonic — a lower value is ignored; use a process-GLOBAL
    count, the cross-rank comparability contract in telemetry.health);
    `step_inc` adds to it (per-step loops that don't track a global
    index); keyword counts are ADDED (`progress(halo_exchanges=1,
    halo_bytes=n)`). A step advance flushes immediately: the bump must
    be on disk before the caller enters the next potentially-blocking
    collective (module docstring)."""
    if not _ENABLED:
        return
    stepped = False
    with _LOCK:
        if step is not None:
            step = int(step)
            if step > _COUNTERS.get("step", -1):
                _COUNTERS["step"] = step
                stepped = True
        if step_inc:
            _COUNTERS["step"] = _COUNTERS.get("step", 0) + int(step_inc)
            stepped = True
        for key, delta in counts.items():
            try:
                _COUNTERS[key] = _COUNTERS.get(key, 0) + int(delta)
            except (TypeError, ValueError):
                continue
    _maybe_flush(force=stepped)


def trace_inflight_add(trace_ids) -> None:
    """Mark request trace ids as riding a dispatched batch. No-op while
    disabled; no flush of its own (the dispatch path's progress() bump
    already forces one, and the ids must be in THAT flush)."""
    if not _ENABLED:
        return
    with _LOCK:
        _INFLIGHT_TRACES.update(str(t) for t in trace_ids)


def trace_inflight_drop(trace_ids) -> None:
    """Clear request trace ids whose batch resolved (or failed)."""
    if not _ENABLED:
        return
    with _LOCK:
        _INFLIGHT_TRACES.difference_update(str(t) for t in trace_ids)


def inflight_traces() -> list[str]:
    """The currently in-flight request trace ids (sorted; tests)."""
    with _LOCK:
        return sorted(_INFLIGHT_TRACES)


def snapshot() -> dict:
    """The heartbeat document (also what flush writes)."""
    with _LOCK:
        return {
            "schema": HEARTBEAT_SCHEMA,
            "v": HEARTBEAT_VERSION,
            "rank": _rank(),
            "t": time.time(),
            "t_mono": time.perf_counter(),
            "started_t": _STARTED_T,
            "counters": dict(_COUNTERS),
            "last_phase": _LAST_PHASE,
            "last_phase_name": _LAST_PHASE_NAME,
            "last_phase_t": _LAST_PHASE_T,
            "inflight_traces": sorted(_INFLIGHT_TRACES),
            "ring": list(_RING),
        }


def flush() -> str | None:
    """Write the sidecar NOW (atomic tmp+rename — a reader must never
    see a half-written heartbeat; a rank killed mid-write leaves at worst
    a stale-but-complete sidecar plus tmp litter). Returns the path."""
    global _LAST_FLUSH_MONO
    path = sidecar_path()
    if path is None or not _ENABLED:
        return None
    doc = snapshot()
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
    except OSError:
        return None  # observability must never be what kills a run
    _LAST_FLUSH_MONO = time.monotonic()
    return path


def _maybe_flush(force: bool = False) -> None:
    if not _ENABLED or _DIR is None:
        return
    if force or time.monotonic() - _LAST_FLUSH_MONO >= _FLUSH_INTERVAL_S:
        flush()


def install_postmortem_handler() -> str | None:
    """Register SIGUSR2 → faulthandler all-thread traceback appended to
    `postmortem-rank{k}.traceback`. faulthandler (not `signal.signal`)
    on purpose: its dumper runs at the C level, so it fires even while
    the main thread is wedged inside a collective that never returns to
    the interpreter — the exact state the watchdog probes. Returns the
    traceback path (None when the platform has no SIGUSR2 or the
    recorder is disabled). Repo rule GL07 pins this module (plus
    resilience/) as the only legitimate home of signal/faulthandler use.
    """
    global _TRACEBACK_FH
    path = traceback_path()
    if path is None or not hasattr(signal, "SIGUSR2"):
        return None
    try:
        # Append mode: repeated SIGUSR2s accumulate dumps; the fh stays
        # open for the process lifetime (faulthandler writes to the fd).
        fh = open(path, "a")
        faulthandler.register(signal.SIGUSR2, file=fh, all_threads=True,
                              chain=False)
    except (OSError, ValueError, AttributeError):
        return None
    if _TRACEBACK_FH is not None:
        try:
            _TRACEBACK_FH.close()
        except OSError:
            pass
    _TRACEBACK_FH = fh
    flush()
    return path
