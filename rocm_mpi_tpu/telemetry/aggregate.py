"""Merge per-rank telemetry streams into one run summary.

Input: a directory of `telemetry-rank{k}.jsonl` files (events.py's
writers — each rank wrote its own, so merging is a read-side concern).
Output: one summary dict (schema below) answering the questions the
ROADMAP's scale work keeps asking:

* where did the wall time go, per phase (halo / interior / checkpoint /
  step) and per rank — the attribution without which stencil perf work
  "devolves into guesswork" (arxiv 2406.08923 §1, 2404.04441 §2);
* how fast were the steps (percentiles across step windows, not just the
  mean the reference prints — a straggling window is invisible in wtime/nt);
* how much halo traffic moved, and at what bytes/s;
* did any rank straggle (its phase wall vs the cross-rank median — the
  multi-chip failure mode weak scaling hides inside an aggregate number);
* what did the resilience layer do (event counts by kind).

Summary schema (``SUMMARY_SCHEMA``/``SUMMARY_VERSION``):

    {"schema": "rocm_mpi_tpu.telemetry.summary", "v": 1,
     "ranks": [int], "records": int, "skipped_lines": int,
     "phases": {phase: {"wall_s", "count", "bytes", "bytes_per_s",
                        "by_rank": {str(rank): wall_s}}},
     "steps": {"count", "windows", "wall_s",
               "per_step_us": {"mean","p50","p90","p99"}},
     "gauges": {key: value}, "counters": {name: sum},
     "gauge_series": [{"name","value","rank","attrs"}],
     "events": {name: count}, "traced": {name: attrs},
     "stragglers": [{"rank","phase","wall_s","median_s","ratio"}]}

Gauge keys carry the `devices` attr when present (`run.gpts@4dev`), so
a weak-scaling sweep's per-rung rates stay distinct — flat last-wins
would let a mid-ladder regression hide behind the final rung — and the
regress gate compares rung against like rung. Numeric samples that share
a key (every rank emits its own jittering copy of a rung's rate) reduce
to the cross-rank MEDIAN — an arbitrary single rank's sample would make
the regress gate fire on one straggler and miss a slowdown confined to
the others. `gauge_series` keeps every emission (rank, full attrs) for
anything the keyed view collapses.

The canonical phases (halo, interior, checkpoint, step) are always
present — a zero row says "observed nothing", which is itself
attribution; absence would just be ambiguity. stdlib-only: summarize
runs where jax never will (CI boxes, laptops reading a pod's stream).
"""

from __future__ import annotations

import json
import pathlib
import re
import statistics

SUMMARY_SCHEMA = "rocm_mpi_tpu.telemetry.summary"
SUMMARY_VERSION = 1

CANONICAL_PHASES = ("halo", "interior", "checkpoint", "step")

# A rank is a straggler when its phase wall exceeds the cross-rank median
# by this factor (and the phase saw real time — see _MIN_STRAGGLER_WALL_S).
DEFAULT_STRAGGLER_FACTOR = 1.5
_MIN_STRAGGLER_WALL_S = 1e-4

_RANK_FILE_RE = re.compile(r"telemetry-rank(\d+)\.jsonl$")


def rank_stream_paths(directory) -> dict[int, pathlib.Path]:
    """{rank: path} of the per-rank streams under `directory`."""
    out: dict[int, pathlib.Path] = {}
    root = pathlib.Path(directory)
    if not root.is_dir():
        return out
    for path in sorted(root.glob("telemetry-rank*.jsonl")):
        m = _RANK_FILE_RE.search(path.name)
        if m:
            out[int(m.group(1))] = path
    return out


def load_rank_streams(directory) -> tuple[dict[int, list[dict]], int]:
    """Parse every rank stream. Returns ({rank: [records]}, skipped_lines).
    Unparseable lines are counted and skipped — a rank killed mid-write
    leaves a torn last line, and the surviving records are the point."""
    streams: dict[int, list[dict]] = {}
    skipped = 0
    for rk, path in rank_stream_paths(directory).items():
        recs: list[dict] = []
        try:
            text = path.read_text()
        except OSError:
            skipped += 1
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict) and "kind" in rec:
                rec.setdefault("rank", rk)
                recs.append(rec)
            else:
                skipped += 1
        streams[rk] = recs
    return streams, skipped


def record_wire_mode(rec: dict) -> str | None:
    """The wire-precision mode a telemetry record annotates, or None.
    THE one definition of "this record stamps a wire mode" — the
    summary's `wire_modes` list and the monitor's WIRE badge
    (telemetry.health.wire_status) both consume it, so the annotation
    shape can never drift between the two read sides."""
    if rec.get("kind") != "trace":
        return None
    w = (rec.get("attrs") or {}).get("wire")
    return str(w) if w else None


def phase_of(rec: dict) -> str:
    """A record's phase: the explicit `phase` attr wins, else the dotted
    name's first component, with the step-window spelling folded in."""
    attrs = rec.get("attrs") or {}
    if "phase" in attrs:
        return str(attrs["phase"])
    head = str(rec.get("name", "")).split(".", 1)[0]
    return "step" if head == "step_window" else head


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(streams: dict[int, list[dict]], skipped_lines: int = 0,
              straggler_factor: float = DEFAULT_STRAGGLER_FACTOR) -> dict:
    """Merge per-rank record streams into the summary dict (module
    docstring has the schema)."""
    phases: dict[str, dict] = {
        p: {"wall_s": 0.0, "count": 0, "bytes": 0, "by_rank": {}}
        for p in CANONICAL_PHASES
    }
    per_step_us: list[float] = []
    step_count = 0
    step_windows = 0
    gauge_samples: dict[str, list] = {}
    gauge_series: list[dict] = []
    counters: dict[str, float] = {}
    event_counts: dict[str, int] = {}
    traced: dict[str, dict] = {}
    wire_modes: set[str] = set()
    tspan_counts: dict[str, int] = {}
    trace_ids: set[str] = set()
    n_records = 0

    for rk, recs in sorted(streams.items()):
        for rec in recs:
            n_records += 1
            kind = rec.get("kind")
            attrs = rec.get("attrs") or {}
            if kind == "span":
                ph = phase_of(rec)
                row = phases.setdefault(
                    ph, {"wall_s": 0.0, "count": 0, "bytes": 0, "by_rank": {}}
                )
                dur = float(rec.get("dur_s", 0.0))
                row["wall_s"] += dur
                row["count"] += 1
                row["bytes"] += int(attrs.get("bytes", 0) or 0)
                row["by_rank"][str(rk)] = (
                    row["by_rank"].get(str(rk), 0.0) + dur
                )
                steps = attrs.get("steps")
                if ph == "step" and steps:
                    step_windows += 1
                    step_count += int(steps)
                    per_step_us.append(dur / int(steps) * 1e6)
            elif kind == "gauge":
                key = rec["name"]
                if "devices" in attrs:
                    key = f"{key}@{attrs['devices']}dev"
                if "driver" in attrs:
                    # The loop form is part of the measurement's identity:
                    # a scan-driver rate and a step-driver rate must land
                    # under distinct keys so the regress gate can never
                    # compare them silently (apps/_common.py --driver).
                    key = f"{key}:{attrs['driver']}"
                if attrs.get("wire") and attrs["wire"] != "f32":
                    # Same identity rule for the wire-precision plane:
                    # an f32 rate and a bf16-wire rate are different
                    # measurements (the default spelling is unchanged
                    # so committed baselines keep gating f32 runs).
                    key = f"{key}:{attrs['wire']}"
                gauge_samples.setdefault(key, []).append(rec.get("value"))
                gauge_series.append({
                    "name": rec["name"], "value": rec.get("value"),
                    "rank": rk, "attrs": attrs,
                })
            elif kind == "counter":
                try:
                    counters[rec["name"]] = (
                        counters.get(rec["name"], 0) + rec.get("value", 0)
                    )
                except TypeError:
                    pass  # non-numeric counter: drop, never crash the merge
            elif kind == "event":
                event_counts[rec["name"]] = (
                    event_counts.get(rec["name"], 0) + 1
                )
            elif kind == "tspan":
                # Request-trace transitions (telemetry/tracing.py):
                # the summary counts them per name and the distinct
                # traces observed — the cheap "is tracing on, and how
                # much is it writing" view; the per-request read side
                # is the `telemetry trace` verb, not the summary.
                tspan_counts[rec.get("name", "?")] = (
                    tspan_counts.get(rec.get("name", "?"), 0) + 1
                )
                tid = rec.get("trace_id")
                if isinstance(tid, str):
                    trace_ids.add(tid)
            elif kind == "trace":
                traced[rec["name"]] = attrs
                # The active wire-precision mode(s), annotation-sourced
                # (halo.exchange / deep.sweep / overlap.step stamp it at
                # trace time): collected ACROSS records, because `traced`
                # keeps only the last attrs per name and a mixed-mode
                # run would otherwise report just one mode.
                w = record_wire_mode(rec)
                if w:
                    wire_modes.add(w)

    gauges: dict[str, object] = {}
    for key, samples in gauge_samples.items():
        numeric = [v for v in samples if isinstance(v, (int, float))]
        if numeric and len(numeric) == len(samples):
            gauges[key] = statistics.median(numeric)
        else:
            gauges[key] = samples[-1]

    for row in phases.values():
        row["wall_s"] = round(row["wall_s"], 9)
        row["bytes_per_s"] = (
            round(row["bytes"] / row["wall_s"], 3)
            if row["bytes"] and row["wall_s"] > 0 else 0.0
        )

    per_step_us.sort()
    steps = {
        "count": step_count,
        "windows": step_windows,
        "wall_s": round(phases["step"]["wall_s"], 9),
        "per_step_us": {
            "mean": round(sum(per_step_us) / len(per_step_us), 3)
            if per_step_us else 0.0,
            "p50": round(_percentile(per_step_us, 0.50), 3),
            "p90": round(_percentile(per_step_us, 0.90), 3),
            "p99": round(_percentile(per_step_us, 0.99), 3),
        },
    }

    stragglers = []
    if len(streams) >= 2:
        for ph, row in phases.items():
            walls = sorted(row["by_rank"].items(), key=lambda kv: kv[1])
            if len(walls) < 2:
                continue
            vals = [w for _, w in walls]
            # True median (interpolating for even counts): nearest-rank
            # would return the FASTEST rank's wall in the 2-rank case and
            # over-flag the other one.
            median = statistics.median(vals)
            if median < _MIN_STRAGGLER_WALL_S:
                continue
            for rk_s, wall in walls:
                if wall > straggler_factor * median:
                    stragglers.append({
                        "rank": int(rk_s),
                        "phase": ph,
                        "wall_s": round(wall, 6),
                        "median_s": round(median, 6),
                        "ratio": round(wall / median, 3),
                    })

    return {
        "schema": SUMMARY_SCHEMA,
        "v": SUMMARY_VERSION,
        "ranks": sorted(streams),
        "records": n_records,
        "skipped_lines": skipped_lines,
        "phases": phases,
        "steps": steps,
        "gauges": gauges,
        "gauge_series": gauge_series,
        "counters": counters,
        "events": event_counts,
        "traced": traced,
        "tspans": tspan_counts,
        "trace_requests": len(trace_ids),
        "wire_modes": sorted(wire_modes),
        "stragglers": stragglers,
    }


def summarize_dir(directory,
                  straggler_factor: float = DEFAULT_STRAGGLER_FACTOR) -> dict:
    streams, skipped = load_rank_streams(directory)
    return summarize(streams, skipped, straggler_factor)


def write_json_atomic(path, doc: dict, indent: int | None = 1) -> None:
    """Publish a JSON artifact via tmp + rename: a process killed
    mid-write (the watcher's operating reality) must never leave a
    half-written summary/trace for the regress gate or the archive to
    trust."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps(doc, indent=indent))
    tmp.replace(path)


def write_summary(directory, out_path=None,
                  straggler_factor: float = DEFAULT_STRAGGLER_FACTOR) -> dict:
    """Summarize `directory`'s rank streams and write the summary next to
    them (default: <directory>/telemetry-summary.json). Returns the dict."""
    summary = summarize_dir(directory, straggler_factor)
    path = (pathlib.Path(out_path) if out_path
            else pathlib.Path(directory) / "telemetry-summary.json")
    write_json_atomic(path, summary)
    return summary


def format_summary(summary: dict) -> str:
    """Human-readable report of a summary (the CLI's default output)."""
    lines = [
        f"telemetry summary: ranks={summary['ranks']} "
        f"records={summary['records']} "
        f"(skipped_lines={summary['skipped_lines']})",
        "phase        wall_s      count   bytes        bytes/s",
    ]
    for ph in sorted(summary["phases"],
                     key=lambda p: (p not in CANONICAL_PHASES, p)):
        row = summary["phases"][ph]
        lines.append(
            f"{ph:12s} {row['wall_s']:<11.6f} {row['count']:<7d} "
            f"{row['bytes']:<12d} {row['bytes_per_s']:.3g}"
        )
    st = summary["steps"]
    if st["windows"]:
        p = st["per_step_us"]
        lines.append(
            f"steps: {st['count']} over {st['windows']} window(s), "
            f"per-step us mean={p['mean']} p50={p['p50']} "
            f"p90={p['p90']} p99={p['p99']}"
        )
    wire_modes = summary.get("wire_modes") or []
    if wire_modes and wire_modes != ["f32"]:
        # The badge: a reduced-precision (or mixed) wire must be
        # impossible to miss next to an f32 summary — the f32-only case
        # stays silent so existing reports are byte-identical.
        lines.append("WIRE MODE: " + ", ".join(wire_modes))
    for name, value in sorted(summary["gauges"].items()):
        lines.append(f"gauge {name} = {value}")
    for name, n in sorted(summary["events"].items()):
        lines.append(f"event {name} × {n}")
    if summary["stragglers"]:
        for s in summary["stragglers"]:
            lines.append(
                f"STRAGGLER rank {s['rank']} in phase {s['phase']}: "
                f"{s['wall_s']}s vs median {s['median_s']}s "
                f"({s['ratio']}x)"
            )
    else:
        lines.append("no stragglers detected")
    return "\n".join(lines)
