"""Nestable walltime spans with device-fetch-correct sync semantics.

    with span("halo.probe", phase="halo", bytes=n) as sp:
        out = probe(state)
        sp.sync(out)        # truly wait before the span closes

Sync discipline: `sp.sync(x)` routes through `utils.metrics.force` —
block_until_ready THEN a one-scalar fetch — because on the tunneled-chip
transport this framework targets, `block_until_ready` alone returns
before remote execution finishes (measured: a 2.5 s computation "synced"
at 0.000 s; utils/metrics.py has the full story). A span that closes
without syncing times only the async dispatch, which is exactly the
mistake the reference's `wait(signal)`-before-toc exists to avoid.

Overhead discipline: when telemetry is disabled, `span()` returns one
module-level no-op singleton — no allocation, no clock read, no lock;
`sp.sync(x)` then returns `x` without forcing (the run's correctness
never depends on the fetch, only timing fidelity does). The disabled
cost is a function call and one global read, safe inside per-step loops.

Nesting is tracked per thread (a depth counter in threading.local), so
spans opened on the launcher's drain threads or inside a supervised
retry don't corrupt each other's stacks; the emitted record carries
`depth` and `tid`, which is all the Chrome-trace exporter needs to nest
slices on a rank's track.
"""

from __future__ import annotations

import threading
import time

from rocm_mpi_tpu.telemetry import events, flight

_stack = threading.local()


def _depth() -> int:
    return getattr(_stack, "depth", 0)


class Span:
    """One open span; emitted as a single record at __exit__."""

    __slots__ = ("name", "attrs", "_t_wall", "_t_mono", "_depth", "_tid")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._depth = _depth()
        _stack.depth = self._depth + 1
        self._tid = threading.get_ident()
        if flight.enabled():
            # Entry note BEFORE the clock reads: a rank that wedges
            # inside this span never reaches __exit__'s record, and the
            # flight recorder's "last phase entered" must already say so
            # (heartbeat sidecar, telemetry/flight.py).
            flight.enter_phase(self.name, self.attrs)
        self._t_wall = time.time()
        self._t_mono = time.perf_counter()
        return self

    def set(self, **attrs):
        """Attach attributes discovered mid-span (byte counts, step ids)."""
        self.attrs.update(attrs)
        return self

    def sync(self, x):
        """Truly wait for `x` (device-fetch sync) and return it."""
        from rocm_mpi_tpu.utils.metrics import force  # lazy: needs jax

        return force(x)

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t_mono
        _stack.depth = self._depth
        fields = {
            "t": self._t_wall,
            "dur_s": dur,
            "depth": self._depth,
            "tid": self._tid,
        }
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        if self.attrs:
            fields["attrs"] = self.attrs
        events.emit("span", self.name, **fields)
        return False


class _NoopSpan:
    """The disabled-mode singleton: every operation is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self

    def sync(self, x):
        return x


_NOOP = _NoopSpan()


def span(name: str, **attrs):
    """Open a span named `name` (dotted, phase-prefixed: "halo.probe",
    "checkpoint.save", "step_window"). Returns a context manager; the
    record is emitted when the span closes. A `phase=` attr overrides the
    name-prefix phase mapping (telemetry.aggregate.phase_of)."""
    if not events.enabled():
        return _NOOP
    return Span(name, attrs)


def span_record(name: str, t_wall: float, dur_s: float,
                error: str | None = None, **attrs) -> None:
    """Emit a span record for an interval timed by OTHER machinery
    (utils.metrics.Timer's labeled mode): the interval is already over,
    so it never passes through the nesting stack. `error` lands at the
    record's top level, matching Span.__exit__'s failed-body shape."""
    if not events.enabled():
        return
    fields = {"t": t_wall, "dur_s": dur_s, "depth": _depth(),
              "tid": threading.get_ident()}
    if error is not None:
        fields["error"] = error
    if attrs:
        fields["attrs"] = attrs
    events.emit("span", name, **fields)
