"""CLI: python -m rocm_mpi_tpu.telemetry
           {summarize,regress,monitor,export-openmetrics,trace} …

    summarize DIR [--json] [--out FILE] [--trace FILE]
                  [--straggler-factor F]
        Merge DIR's telemetry-rank*.jsonl streams; write the summary
        (default DIR/telemetry-summary.json) and a Chrome trace (default
        DIR/telemetry-trace.json, openable at ui.perfetto.dev — health
        heartbeat sidecars in DIR merge in as progress counter tracks);
        print a human report (--json prints the summary document
        instead). Exit 0 on success, 2 when DIR has no rank streams.

    regress SUMMARY --baseline FILE [--tolerance F]
        Gate SUMMARY (a summary file, or a run directory to summarize on
        the fly) against a committed baseline. Exit 0 pass, 1 regression,
        2 missing/unreadable inputs.

    regress --check-schema FILE [FILE…]
        Validate committed measurement artifacts (BASELINE.json,
        MULTICHIP_r0*.json, mechanics/telemetry JSONLs, summaries,
        heartbeat/post-mortem sidecars) still parse as a known format.
        Exit 0 ok, 1 problems.

    monitor DIR [--interval S] [--iterations N]
        Live per-rank view from the health-plane heartbeat sidecars
        (docs/TELEMETRY.md "Health plane"): step counter, step rate,
        current phase, phase age, delta vs the cross-rank median. When
        the elastic supervisor left an elastic.jsonl sidecar in DIR
        (docs/RESILIENCE.md "Elastic recovery" and §7), the header shows
        the CURRENT mesh shape plus SHRUNK / GROWN badges for runs that
        changed topology, a STORAGE DEGRADED indicator when the
        ckpt_* heartbeat counters say a rank is skipping saves through a
        storage outage, and a WIRE badge when the run's telemetry
        streams carry reduced-precision exchange annotations
        (docs/PERF.md "Wire precision"). Curses-free — redraws in place
        on a TTY, appends
        snapshots otherwise. Exit 0 after N iterations (default: run
        until ^C), 2 when DIR has no heartbeat sidecars to watch.

    export-openmetrics DIR [--out FILE]
        One Prometheus/OpenMetrics text snapshot of the run's gauges,
        counters, and per-rank progress, metric keys verbatim in a
        `key` label (scrape-ready; round-trips `run.gpts@4dev:scan`
        keys exactly). Exit 0, 2 when DIR has neither rank streams nor
        heartbeat sidecars.

    trace DIR --request ID [--out FILE] [--chrome FILE]
        One request's causal timeline across every rank stream under
        DIR (fleet layouts with replica subdirectories included):
        hop-indented human lines plus the latency decomposition
        (docs/TELEMETRY.md "Request tracing"). --out banks the
        schema-versioned trace report (rmt-trace-report, gated by
        regress --check-schema); --chrome exports a per-hop Chrome
        trace for the request. Exit 0, 2 when DIR has no streams or
        no stream mentions the request.

stdlib-only end to end: the read side of telemetry must run on machines
that will never import jax (CI, a laptop holding a pod's stream).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from rocm_mpi_tpu.telemetry import aggregate, health, regress, trace, tracing


def _cmd_summarize(args) -> int:
    streams, skipped = aggregate.load_rank_streams(args.dir)
    if not streams:
        print(
            f"error: no telemetry-rank*.jsonl under {args.dir} "
            "(run with --telemetry DIR, or RMT_TELEMETRY_DIR=DIR)",
            file=sys.stderr,
        )
        return 2
    summary = aggregate.summarize(streams, skipped, args.straggler_factor)
    out = pathlib.Path(
        args.out or pathlib.Path(args.dir) / "telemetry-summary.json"
    )
    aggregate.write_json_atomic(out, summary)
    trace_path = pathlib.Path(
        args.trace or pathlib.Path(args.dir) / "telemetry-trace.json"
    )
    # Health sidecars, when the run left any, ride into the trace as
    # progress counter tracks — same merge the post-mortem bundle gets.
    beats, _ = health.load_heartbeats(args.dir)
    trace.write_chrome_trace(streams, trace_path, heartbeats=beats or None)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(aggregate.format_summary(summary))
        print(f"summary: {out}")
        print(f"chrome trace: {trace_path} (open at ui.perfetto.dev)")
    return 0


def _cmd_regress(args) -> int:
    if args.check_schema:
        targets = [args.summary] if args.summary else []
        targets += args.extra
        if not targets:
            print("error: --check-schema needs at least one file",
                  file=sys.stderr)
            return 2
        problems = regress.check_schema(targets)
        for p in problems:
            print(p, file=sys.stderr)
        if not problems:
            print(f"schema check ok: {len(targets)} file(s)")
        return 1 if problems else 0

    if not args.summary or not args.baseline:
        print("error: regress needs SUMMARY and --baseline FILE",
              file=sys.stderr)
        return 2
    summary_path = pathlib.Path(args.summary)
    if summary_path.is_dir():
        summary = aggregate.summarize_dir(summary_path)
        if not summary["ranks"]:
            print(f"error: no telemetry streams under {summary_path}",
                  file=sys.stderr)
            return 2
    else:
        summary = regress.load_json(summary_path)
        if summary is None:
            print(f"error: cannot read summary {summary_path}",
                  file=sys.stderr)
            return 2
    baseline = regress.load_json(args.baseline)
    if baseline is None:
        print(f"error: cannot read baseline {args.baseline}",
              file=sys.stderr)
        return 2
    deltas = regress.compare(summary, baseline, args.tolerance)
    if not deltas:
        print(
            "error: no comparable metrics between summary and baseline "
            "(a gate that compares nothing must not pass)",
            file=sys.stderr,
        )
        return 2
    # Key drift must be VISIBLE: a baseline metric with no counterpart in
    # the summary simply drops out of the comparison (e.g. gauge keys
    # grew a ':driver' suffix, or a phase stopped being observed) — that
    # family is then ungated, which the operator must be told about even
    # while the remaining metrics still gate.
    dropped = sorted(
        set(regress.extract_metrics(baseline))
        - set(regress.extract_metrics(summary))
    )
    if dropped:
        shown = ", ".join(dropped[:5]) + ("…" if len(dropped) > 5 else "")
        print(
            f"warning: {len(dropped)} baseline metric(s) have no "
            f"counterpart in the summary and are NOT gated: {shown} "
            "(renamed keys? re-bank the baseline)",
            file=sys.stderr,
        )
    for d in deltas:
        print(d.describe())
    bad = regress.regressions(deltas)
    if bad:
        print(f"REGRESSION: {len(bad)}/{len(deltas)} metric(s) beyond "
              f"{args.tolerance:.0%} tolerance", file=sys.stderr)
        return 1
    print(f"pass: {len(deltas)} metric(s) within "
          f"{args.tolerance:.0%} tolerance")
    return 0


def _cmd_monitor(args) -> int:
    import time

    beats, skipped = health.load_heartbeats(args.dir)
    if not beats:
        print(
            f"error: no heartbeat-rank*.json under {args.dir} — is a "
            "--health run writing sidecars there? (docs/TELEMETRY.md)",
            file=sys.stderr,
        )
        return 2
    prev: dict[int, dict] | None = None
    i = 0
    clear_screen = sys.stdout.isatty()
    # Reduced-precision wire badge (docs/PERF.md "Wire precision"):
    # annotation-sourced from the rank streams — an f32 run and a
    # bf16-wire run must never be eyeballed (or regress-compared) as
    # the same measurement. Wire modes are trace-time facts, fixed per
    # compiled program: read the streams ONCE here, not per poll (they
    # grow with the run; the heartbeat sidecars the loop re-reads stay
    # small by construction).
    wire_line = health.format_wire_status(health.wire_status(args.dir))
    try:
        while True:
            rows = health.monitor_rows(beats, prev)
            if clear_screen:
                print("\x1b[H\x1b[2J", end="")
            print(f"health monitor: {args.dir}  "
                  f"({len(beats)} rank(s), poll {args.interval:g}s)")
            # Elastic runs (resilience.elastic) leave an elastic.jsonl
            # next to the sidecars: surface the current mesh and the
            # SHRUNK / GROWN badges — an operator must see at a glance
            # that this run is no longer on the mesh it started with.
            elastic_events, _ = health.load_elastic_events(args.dir)
            elastic_line = health.format_elastic_status(
                health.elastic_status(elastic_events)
            )
            if elastic_line:
                print(elastic_line)
            # Degraded checkpoint storage (docs/RESILIENCE.md §7): the
            # segmented loop keeps computing through an outage, so the
            # ONLY place an operator sees the widening loss window is
            # here — the ckpt_* heartbeat counters each boundary bumps.
            storage_line = health.format_storage_status(
                health.storage_status(beats)
            )
            if storage_line:
                print(storage_line)
            # Serving runs (docs/SERVING.md): queue depth + served /
            # requeued counts from the serve_* heartbeat counters — the
            # operator's at-a-glance backlog view.
            serve_line = health.format_serve_status(
                health.serve_status(beats)
            )
            if serve_line:
                print(serve_line)
            if wire_line:
                print(wire_line)
            print(health.format_monitor(rows, skipped))
            sys.stdout.flush()
            i += 1
            if args.iterations is not None and i >= args.iterations:
                return 0
            time.sleep(args.interval)
            prev = beats
            beats, skipped = health.load_heartbeats(args.dir)
            if not beats:
                print(f"error: heartbeat sidecars vanished from {args.dir}",
                      file=sys.stderr)
                return 2
    except KeyboardInterrupt:
        return 0


def _cmd_export_openmetrics(args) -> int:
    text = health.export_openmetrics(args.dir)
    if text is None:
        print(
            f"error: nothing to export under {args.dir} (neither "
            "telemetry-rank*.jsonl nor heartbeat-rank*.json)",
            file=sys.stderr,
        )
        return 2
    if args.out:
        out = pathlib.Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        tmp = out.with_suffix(out.suffix + ".tmp")
        tmp.write_text(text)
        tmp.replace(out)
        print(f"wrote {out}", file=sys.stderr)
    else:
        print(text, end="")
    return 0


def _cmd_trace(args) -> int:
    streams, _ = aggregate.load_rank_streams(args.dir)
    if not streams:
        print(
            f"error: no telemetry-rank*.jsonl under {args.dir} "
            "(run with --telemetry DIR, or RMT_TELEMETRY_DIR=DIR)",
            file=sys.stderr,
        )
        return 2
    timeline = tracing.request_timeline(streams, args.request)
    if timeline is None:
        print(
            f"error: no stream under {args.dir} mentions request "
            f"{args.request!r} (tracing off, or wrong id?)",
            file=sys.stderr,
        )
        return 2
    print(tracing.format_timeline(timeline))
    if args.out:
        doc = tracing.trace_report_doc(timeline)
        tracing.write_trace_report(args.out, doc)
        print(f"trace report: {args.out}")
    if args.chrome:
        tracing.write_request_chrome(timeline, args.chrome)
        print(f"per-hop chrome trace: {args.chrome} "
              "(open at ui.perfetto.dev)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rocm_mpi_tpu.telemetry",
        description="telemetry read side: merge rank streams, export "
                    "Chrome traces, gate on perf baselines "
                    "(docs/TELEMETRY.md)",
    )
    sub = parser.add_subparsers(dest="command")

    p_sum = sub.add_parser("summarize", help="merge per-rank streams")
    p_sum.add_argument("dir", help="directory of telemetry-rank*.jsonl")
    p_sum.add_argument("--json", action="store_true",
                       help="print the summary document instead of the "
                            "human report")
    p_sum.add_argument("--out", default=None, metavar="FILE",
                       help="summary path (default DIR/telemetry-summary.json)")
    p_sum.add_argument("--trace", default=None, metavar="FILE",
                       help="Chrome trace path (default "
                            "DIR/telemetry-trace.json)")
    p_sum.add_argument("--straggler-factor", type=float,
                       default=aggregate.DEFAULT_STRAGGLER_FACTOR,
                       help="rank flagged when phase wall exceeds the "
                            "median by this factor (default %(default)s)")

    p_reg = sub.add_parser("regress", help="gate a summary vs a baseline")
    p_reg.add_argument("summary", nargs="?", default=None,
                       help="summary JSON (or run directory)")
    p_reg.add_argument("extra", nargs="*", default=[],
                       help="more files (--check-schema mode)")
    p_reg.add_argument("--baseline", default=None, metavar="FILE")
    p_reg.add_argument("--tolerance", type=float,
                       default=regress.DEFAULT_TOLERANCE,
                       help="allowed relative slip (default %(default)s)")
    p_reg.add_argument("--check-schema", action="store_true",
                       help="only validate the files parse as known "
                            "measurement formats")

    p_mon = sub.add_parser(
        "monitor", help="live per-rank progress from heartbeat sidecars"
    )
    p_mon.add_argument("dir", help="directory of heartbeat-rank*.json")
    p_mon.add_argument("--interval", type=float, default=1.0, metavar="S",
                       help="poll interval in seconds (default %(default)s)")
    p_mon.add_argument("--iterations", type=int, default=None, metavar="N",
                       help="exit 0 after N redraws (default: run until ^C)")

    p_om = sub.add_parser(
        "export-openmetrics",
        help="Prometheus text snapshot of gauges/counters/progress",
    )
    p_om.add_argument("dir", help="telemetry/health run directory")
    p_om.add_argument("--out", default=None, metavar="FILE",
                      help="write the snapshot here instead of stdout")

    p_tr = sub.add_parser(
        "trace",
        help="one request's causal timeline + latency decomposition",
    )
    p_tr.add_argument("dir", help="directory of telemetry-rank*.jsonl")
    p_tr.add_argument("--request", required=True, metavar="ID",
                      help="request id (== trace id) to reconstruct")
    p_tr.add_argument("--out", default=None, metavar="FILE",
                      help="bank the rmt-trace-report artifact here")
    p_tr.add_argument("--chrome", default=None, metavar="FILE",
                      help="export the per-hop Chrome trace here")

    args = parser.parse_args(argv)
    if args.command == "summarize":
        return _cmd_summarize(args)
    if args.command == "regress":
        return _cmd_regress(args)
    if args.command == "monitor":
        return _cmd_monitor(args)
    if args.command == "export-openmetrics":
        return _cmd_export_openmetrics(args)
    if args.command == "trace":
        return _cmd_trace(args)
    parser.print_usage(sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
