"""Configuration dataclasses — the framework's flag system.

The reference has no CLI flags or config files: physics/numerics are
hardcoded constants at the top of each `diffusion2D()`
(/root/reference/scripts/diffusion_2D_ap.jl:10-16,
 scripts/diffusion_2D_perf.jl:16-25), variants are chosen by editing
runme.sh, and environment variables are the real config system
(IGG_ROCMAWARE_MPI etc., scripts/setenv.sh:11-18; SURVEY.md §5.6). Here every
knob the reference treats as tunable (grid size/fact, tile shape, boundary
width b_width, step count nt, do_vis, dtype, halo transport) is an explicit
dataclass field, with env-var overrides only for the transport toggle.
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp

DTYPES = {
    "f32": jnp.float32,
    "f64": jnp.float64,
    "bf16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "bfloat16": jnp.bfloat16,
}

# Halo transport selector — analog of the reference's IGG_ROCMAWARE_MPI env
# toggle (scripts/setenv.sh:13,18; README.md:25-35): "ici" passes
# device-resident shards straight to the collective (ROCm-aware / GPU-direct
# analog), "host" stages the exchange through host memory (the =0 fallback,
# kept as a correctness oracle).
HALO_TRANSPORT_ENV = "RMT_HALO_TRANSPORT"


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    """All knobs of a diffusion run (any variant, 2D or 3D)."""

    global_shape: tuple[int, ...] = (128, 128)
    lengths: tuple[float, ...] = (10.0, 10.0)  # lx, ly (ap.jl:11)
    lam: float = 1.0  # thermal conductivity λ (ap.jl:12)
    cp0: float = 1.0  # heat capacity (ap.jl:13)
    nt: int = 1000  # time steps (ap.jl:16)
    warmup: int = 10  # steps excluded from timing (perf.jl:48,56)
    dtype: str = "f64"
    dims: tuple[int, ...] | None = None  # process grid; None = auto
    b_width: tuple[int, ...] = (32, 4)  # boundary frame width (hide.jl:42)
    do_vis: bool = False  # (perf.jl:15)
    halo_transport: str = dataclasses.field(
        default_factory=lambda: os.environ.get(HALO_TRANSPORT_ENV, "ici")
    )
    # On-wire halo slab precision (parallel/wire.py): "f32" (default,
    # bitwise-identical to the pre-wire-plane exchange), "bf16", or the
    # stateful "int8"/"int8_delta" modes (deep-halo schedules only —
    # per-step variants are stateless programs).
    wire_mode: str = "f32"

    def __post_init__(self):
        if len(self.lengths) != len(self.global_shape):
            raise ValueError("lengths rank must match global_shape rank")
        if self.dtype not in DTYPES:
            raise ValueError(f"dtype must be one of {sorted(DTYPES)}")
        if self.halo_transport not in ("ici", "host"):
            raise ValueError("halo_transport must be 'ici' or 'host'")
        from rocm_mpi_tpu.parallel import wire

        wire.validate_mode(self.wire_mode)

    @property
    def ndim(self) -> int:
        return len(self.global_shape)

    @property
    def jax_dtype(self):
        return DTYPES[self.dtype]

    @property
    def spacing(self) -> tuple[float, ...]:
        return tuple(
            l / n for l, n in zip(self.lengths, self.global_shape)
        )

    @property
    def dt(self) -> float:
        """Stable explicit time step.

        2D: min(dx²,dy²)·Cp0/λ/4.1 (diffusion_2D_ap.jl:20). Generalized to
        N dimensions as /(2·ndim + 0.1) — the reference's 4.1 is the 2D case
        of the 2·ndim CFL bound with the same 0.1 safety margin.
        """
        h2 = min(d * d for d in self.spacing)
        return h2 * self.cp0 / self.lam / (2 * self.ndim + 0.1)


def with_fact(cfg: DiffusionConfig, fact: int) -> DiffusionConfig:
    """Scale the grid as the reference's `fact` knob: nx = fact·1024
    (diffusion_2D_perf.jl:21-22)."""
    shape = tuple(fact * 1024 for _ in cfg.global_shape)
    return dataclasses.replace(cfg, global_shape=shape)
