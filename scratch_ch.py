import time, functools
import jax, jax.numpy as jnp
import rocm_mpi_tpu.ops.pallas_kernels as pk
from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.utils.metrics import force

for chunk in (256, 512, 1024):
    cfg = DiffusionConfig(global_shape=(252, 252), lengths=(10.0, 10.0),
                          nt=chunk * 8 + chunk * 4096, warmup=chunk * 8,
                          dtype="f32", dims=(1, 1))
    m = HeatDiffusion(cfg)
    t0 = time.perf_counter()
    r = m._run_single_shard(None, None, pk.fused_multi_step, chunk, "chunk")
    total = time.perf_counter() - t0
    print(f"chunk={chunk:5d}: {r.wtime_it*1e6:7.4f} us/step  {r.gpts:8.2f} Gpts/s  (total {total:.0f}s)")
