"""2D heat diffusion — kernel-programming variant (C2 analog).

The hand-written-kernels rung of the ladder
(/root/reference/scripts/diffusion_2D_kp.jl): the step is three separate
Pallas kernels (Flux → Residual → Update) with the reference's staggered
flux-grid shapes, instead of C1's array ops or C3's single fused kernel.
Reference defaults: 128², 1000 steps, heatmap artifact.

  python apps/diffusion_2d_kp.py --cpu-devices 4
  python apps/diffusion_2d_kp.py --dtype f32          # single real chip
"""

import sys

from _common import make_parser, run_app

if __name__ == "__main__":
    args = make_parser("kp", nx=128, ny=128, nt=1000, do_vis=True).parse_args()
    sys.exit(run_app("kp", args))
