"""Multi-tenant batched simulation service — the serving-layer driver
(docs/SERVING.md; ROADMAP item 1).

One-shot trace mode (default): load a request trace (--trace FILE.jsonl,
rmt-serve-request records) or generate a deterministic synthetic mix
(--synthetic N --seed S), serve it through serving.SimulationService,
print the bin report, and bank the sidecars under --out:

    serve-requests.jsonl   the served trace (schema-checked by lint.sh)
    serve-manifest.json    bins/programs/occupancy/waste accounting

Daemon mode (--serve): drain the queue until idle for --idle-exit-s
(a SIGTERM preemption notice requeues pending work and exits rc 75 —
the scheduler's requeue signal, resilience/preempt.py).

Exit codes: 0 served clean (rejected/expired are the SLO machinery
working, not app failures); 1 any request failed or was quarantined;
75 preempted (EX_TEMPFAIL, pending work requeued in the manifest);
2 usage.
"""

from __future__ import annotations

import argparse
import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from apps._common import (  # noqa: E402
    add_health_flag,
    add_telemetry_flag,
    positive_int,
    setup_health,
    setup_telemetry,
)

SYNTH_SHAPES = ((16, 16), (24, 24), (32, 32))
SYNTH_WORKLOADS = ("diffusion", "wave", "swe")

# The heavy-tailed mix rides shapes a rung apart on purpose: with
# --ladder, (30, 30) embeds into the (32, 32) rung and the two classes
# consolidate into one compiled program; (16, 16) stays its own rung.
HEAVY_SHAPES = ((30, 30), (32, 32), (16, 16))


def synthetic_trace(n: int, seed: int, nt_max: int = 64,
                    dtype: str = "f32", sessions: bool = False,
                    deadline_s: float | None = None):
    """Deterministic heterogeneous request mix: >=3 shape classes,
    mixed workloads/physics/step counts — the acceptance-trace shape
    (ISSUE: 50 requests through apps/serve.py compile exactly
    len(bins) programs). `deadline_s` stamps every request with a TTL
    (docs/SERVING.md "SLOs and admission")."""
    from rocm_mpi_tpu.serving.queue import Request

    rng = random.Random(seed)
    reqs = []
    for i in range(n):
        wl = SYNTH_WORKLOADS[i % len(SYNTH_WORKLOADS)]
        shape = SYNTH_SHAPES[rng.randrange(len(SYNTH_SHAPES))]
        nt = rng.randrange(max(nt_max // 2, 1), nt_max + 1)
        physics = ()
        if wl == "diffusion" and rng.random() < 0.3:
            physics = (("lam", rng.choice([0.5, 1.0])),)
        reqs.append(Request(
            request_id=f"synth-{seed}-{i:04d}",
            workload=wl,
            global_shape=shape,
            dtype=dtype,
            nt=nt,
            physics=physics,
            ic_scale=1.0 + 0.01 * (i % 17),
            session=f"sess-{i:04d}" if sessions else None,
            deadline_s=deadline_s,
        ))
    return reqs


def heavy_tailed_trace(n: int, seed: int, nt_max: int = 64,
                       dtype: str = "f32",
                       deadline_s: float | None = None):
    """Heavy-tailed mixed-shape synthetic mix — the continuous-batching
    acceptance trace (docs/SERVING.md "Continuous batching"): most
    requests finish in a handful of steps while a Pareto tail runs to
    `nt_max`, so a batch-synchronous drain strands resolved lanes
    behind the longest tenant where the segmented drain swaps queued
    work into their slots at segment boundaries. Shapes mix off-rung
    domains with their rung (HEAVY_SHAPES) so `--ladder` can
    consolidate program classes on the same trace; the occasional SWE
    request exercises the ladder's eligibility exclusion."""
    from rocm_mpi_tpu.serving.queue import Request

    rng = random.Random(seed)
    reqs = []
    for i in range(n):
        # Diffusion-heavy (the ladder-eligible class), wave for the
        # second eligible physics, SWE rarely (never laddered).
        r = rng.random()
        wl = "swe" if r < 0.1 else ("wave" if r < 0.35 else "diffusion")
        shape = HEAVY_SHAPES[rng.randrange(len(HEAVY_SHAPES))]
        nt = min(nt_max, 2 + int(2.0 * rng.paretovariate(1.2)))
        reqs.append(Request(
            request_id=f"heavy-{seed}-{i:04d}",
            workload=wl,
            global_shape=shape,
            dtype=dtype,
            nt=nt,
            physics=(),
            ic_scale=1.0 + 0.01 * (i % 17),
            session=None,
            deadline_s=deadline_s,
        ))
    return reqs


def make_parser():
    p = argparse.ArgumentParser(
        description="multi-tenant batched simulation service "
        "(docs/SERVING.md)"
    )
    src = p.add_mutually_exclusive_group()
    src.add_argument("--trace", default=None, metavar="FILE.jsonl",
                     help="serve this request trace "
                     "(rmt-serve-request records, one per line)")
    src.add_argument("--synthetic", type=positive_int, default=None,
                     metavar="N", help="serve N deterministic synthetic "
                     "requests (default 12)")
    p.add_argument("--seed", type=int, default=1,
                   help="synthetic-trace seed (determinism contract)")
    p.add_argument("--nt-max", type=positive_int, default=64,
                   help="synthetic per-request step-count cap")
    p.add_argument("--dtype", default="f32", choices=["f32", "f64", "bf16"],
                   help="synthetic-trace dtype")
    p.add_argument("--max-width", type=positive_int, default=8,
                   help="widest batch lane count (pow2-capped)")
    p.add_argument("--occupancy-floor", type=float, default=None,
                   help="min live/width per batch (default: "
                   "perf/budgets.json 'serving' row)")
    p.add_argument("--batch-dims", type=positive_int, default=1,
                   help="device rows along the batch mesh axis")
    p.add_argument("--cpu-devices", type=int, default=0, metavar="N",
                   help="simulate N virtual CPU devices")
    p.add_argument("--sessions", default=None, metavar="DIR",
                   help="checkpoint-multiplex root: requests with a "
                   "session id save their final state under DIR/<id>/")
    p.add_argument("--synthetic-sessions", action="store_true",
                   help="give every synthetic request a session id "
                   "(needs --sessions)")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="bank serve-requests.jsonl + serve-manifest.json "
                   "under DIR")
    p.add_argument("--elastic", action="store_true",
                   help="consume the ElasticPolicy: grow batch rows when "
                   "the queue is deep, shrink when idle")
    p.add_argument("--grow-depth", type=positive_int, default=8,
                   help="queue depth that makes the policy consider a "
                   "grow (--elastic)")
    p.add_argument("--serve", action="store_true",
                   help="daemon mode: keep draining until idle for "
                   "--idle-exit-s")
    p.add_argument("--idle-exit-s", type=float, default=2.0,
                   help="daemon idle exit (seconds; --serve)")
    p.add_argument("--max-depth", type=positive_int, default=None,
                   help="admission bound: over-depth submits are "
                   "rejected fast with a retry-after hint "
                   "(default: unbounded)")
    p.add_argument("--retry-budget", type=int, default=None,
                   help="retries per request before quarantine "
                   "(default: the RequestRetryPolicy default)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="stamp every synthetic request with this TTL "
                   "(pending past it fails deadline-exceeded at pop)")
    p.add_argument("--quarantine", default=None, metavar="FILE.jsonl",
                   help="append-only poison-request ledger (default: "
                   "<--out>/quarantine.jsonl when --out is given)")
    p.add_argument("--heavy-tailed", action="store_true",
                   help="heavy-tailed mixed-shape synthetic mix: Pareto "
                   "step counts + rung-apart shapes (the continuous-"
                   "batching acceptance trace; needs --synthetic)")
    p.add_argument("--segments", type=positive_int, default=None,
                   help="continuous batching (docs/SERVING.md): run "
                   "each batch as this many fixed-size step segments "
                   "of ONE compiled program, swapping resolved lanes "
                   "for queued same-class requests at the boundaries "
                   "(default 1 = batch-synchronous)")
    p.add_argument("--no-request-trace", action="store_true",
                   help="disable request-scoped tracing (trace contexts, "
                   "tspan records, per-request latency decomposition — "
                   "docs/TELEMETRY.md 'Request tracing'); the bench "
                   "overhead rung's tracing-off arm")
    p.add_argument("--ladder", action="store_true",
                   help="shape-padding ladder: pad eligible lanes up "
                   "to their rung so rung-sharing shapes consolidate "
                   "into one compiled program class")
    p.add_argument("--pipeline-depth", type=positive_int, default=None,
                   help="drain pipeline depth (docs/SERVING.md 'The "
                   "pipeline'): 1 = serial drain, 2 (default) = "
                   "double-buffered — batch N+1 assembles/dispatches "
                   "while batch N computes; results bitwise-equal at "
                   "any depth")
    add_telemetry_flag(p)
    add_health_flag(p)
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)

    import jax

    from rocm_mpi_tpu.parallel.distributed import maybe_initialize_distributed

    maybe_initialize_distributed()
    if args.cpu_devices:
        from rocm_mpi_tpu.utils.backend import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)
    setup_telemetry(args, jax)
    setup_health(args, jax)
    # Compile accounting is the steady-state contract's instrument —
    # install it even without telemetry so the report's
    # compiles.steady_state is real, not a fabricated zero.
    from rocm_mpi_tpu.telemetry import compiles

    compiles.install()
    # Preemption awareness: SIGTERM → grace-deadline notice → the drain
    # loop requeues pending work and exits 75 (resilience/preempt.py).
    from rocm_mpi_tpu.resilience import preempt

    preempt.install_from_env()

    from rocm_mpi_tpu.serving.queue import load_trace, request_to_record
    from rocm_mpi_tpu.serving.service import ServeConfig, SimulationService
    from rocm_mpi_tpu.utils.logging import log0

    if args.trace:
        requests = load_trace(args.trace)
    else:
        n = args.synthetic or 12
        if args.synthetic_sessions and not args.sessions:
            print("--synthetic-sessions needs --sessions DIR",
                  file=sys.stderr)
            return 2
        if args.heavy_tailed:
            if args.synthetic_sessions:
                print("--heavy-tailed is sessionless "
                      "(drop --synthetic-sessions)", file=sys.stderr)
                return 2
            requests = heavy_tailed_trace(
                n, args.seed, nt_max=args.nt_max, dtype=args.dtype,
                deadline_s=args.deadline_s,
            )
        else:
            requests = synthetic_trace(
                n, args.seed, nt_max=args.nt_max, dtype=args.dtype,
                sessions=args.synthetic_sessions,
                deadline_s=args.deadline_s,
            )
    if any(r.dtype == "f64" for r in requests):
        # x64 follows the TRACE, not just the synthetic --dtype knob: a
        # recorded f64 request served at canonicalized f32 would
        # silently break the bitwise-equal-to-standalone contract while
        # the bin key still claims f64.
        jax.config.update("jax_enable_x64", True)

    policy = None
    if args.elastic:
        from rocm_mpi_tpu.resilience.policy import ElasticPolicy

        policy = ElasticPolicy()

    retry = None
    if args.retry_budget is not None:
        from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy

        retry = RequestRetryPolicy(budget=max(args.retry_budget, 0))
    quarantine = args.quarantine
    if quarantine is None and args.out and jax.process_index() == 0:
        out_dir = pathlib.Path(args.out)
        out_dir.mkdir(parents=True, exist_ok=True)
        quarantine = str(out_dir / "quarantine.jsonl")

    cfg_kw = {}
    if args.pipeline_depth is not None:
        cfg_kw["pipeline_depth"] = args.pipeline_depth
    if args.segments is not None:
        cfg_kw["segments"] = args.segments
    if args.ladder:
        cfg_kw["ladder"] = True
    if args.no_request_trace:
        cfg_kw["trace_requests"] = False
    svc = SimulationService(config=ServeConfig(
        max_width=args.max_width,
        occupancy_floor=args.occupancy_floor,
        batch_dims=args.batch_dims,
        sessions_dir=args.sessions,
        policy=policy,
        grow_queue_depth=args.grow_depth,
        max_depth=args.max_depth,
        retry=retry,
        quarantine_path=quarantine,
        **cfg_kw,
    ))

    log0(f"serving {len(requests)} request(s) "
         f"(max_width={args.max_width}, batch_dims={args.batch_dims}, "
         f"pipeline_depth={svc.config.pipeline_depth}, "
         f"devices={len(jax.devices())})")

    pre_served = 0

    def submit_paced(reqs):
        # This driver is its own submitter: with --max-depth it paces
        # submission against the backlog (drain, then submit) instead
        # of bulk-submitting the whole fixed trace into its own
        # admission bound — rejecting input we cannot re-submit would
        # silently drop most of the trace while still exiting 0. The
        # fast-reject path is for EXTERNAL submitters who can honor
        # the retry-after hint.
        nonlocal_served = 0
        for r in reqs:
            while svc.config.max_depth is not None \
                    and svc.queue.depth() >= svc.config.max_depth:
                served, _ = svc.drain_once()
                nonlocal_served += served
            svc.queue.submit(r)
        return nonlocal_served

    if args.serve:
        pre_served = submit_paced(requests)
        report = svc.serve_forever(idle_exit_s=args.idle_exit_s)
    else:
        pre_served = submit_paced(requests)
        report = svc._drain_all()
    report.served += pre_served

    log0(
        f"served {report.served}/{len(requests)} "
        f"({report.failed} failed, {report.requeued} requeued, "
        f"{report.rejected} rejected, {report.expired} expired, "
        f"{report.quarantined} quarantined) — "
        f"{report.n_bins} bin(s), {report.n_programs} program(s), "
        f"compiles.steady_state={report.compiles.get('steady_state')}"
    )
    pipe = report.pipeline
    if pipe.get("batches"):
        log0(
            f"  pipeline depth={pipe['depth']} "
            f"batches={pipe['batches']} bubble={pipe['bubble']:.2f} "
            f"(assemble {pipe['assemble_s']:.3f}s / dispatch "
            f"{pipe['dispatch_s']:.3f}s / fetch {pipe['fetch_s']:.3f}s "
            f"/ resolve {pipe['resolve_s']:.3f}s)"
        )
    cont = report.continuous
    if cont:
        log0(
            f"  continuous segments={cont['segments']} "
            f"batches={cont['batches']} "
            f"segments_run={cont['segments_run']} "
            f"swaps_in={cont['swaps_in']} "
            f"swaps_out={cont['swaps_out']} "
            f"occupancy={cont['occupancy']:.3f}"
        )
    for key, st in sorted(report.bins.items()):
        log0(
            f"  bin {key.key_str():48s} req={st.requests:3d} "
            f"batches={st.batches} widths={list(st.widths)} "
            f"occ={st.occupancy:.2f} waste={st.padding_waste:.2f}"
            + (f" splits={st.splits}" if st.splits else "")
        )
    for ev in report.elastic:
        log0(f"  elastic: {ev}")

    if args.out and jax.process_index() == 0:
        out = pathlib.Path(args.out)
        out.mkdir(parents=True, exist_ok=True)
        trace_path = out / "serve-requests.jsonl"
        import json

        with open(trace_path, "w", encoding="utf-8") as fh:
            for r in requests:
                fh.write(json.dumps(request_to_record(r)) + "\n")
        doc = svc.write_manifest(out / "serve-manifest.json")
        log0(f"banked {trace_path} and serve-manifest.json "
             f"({len(doc['bins'])} bin row(s))")

    if report.preempted:
        log0("preempted: pending work requeued; rc 75 (EX_TEMPFAIL)")
        return 75
    # Quarantined requests are failures the service survived — the run
    # still reports them (a poisoned trace must not exit 0). Rejected/
    # expired are the SLO machinery doing its job, not an app failure.
    return 1 if (report.failed or report.quarantined) else 0


if __name__ == "__main__":
    sys.exit(main())
