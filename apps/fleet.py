"""The fleet driver: N simulation-service replicas behind one router
(docs/SERVING.md "The fleet"; serving/router.py has the policy).

Builds an in-process fleet — N independent `SimulationService`
replicas, one `FleetRouter` front end, one durable ticket journal —
serves a deterministic synthetic trace through it, and banks the
fleet sidecars under --out:

    fleet-journal.jsonl    the append-only ticket journal
                           (rmt-fleet-journal v1, schema-checked)
    fleet-report.json      the merged fleet report (rmt-fleet-report
                           v1: replica rows, journal-derived SLO
                           block, accounting verdict, autoscale trail)

Fault drills ride the standard grammar (--inject-fault
"replica-kill@step=2,rank=1" kills replica 1 at fleet tick 2; the
router reconciles from the journal and the run still has to balance).

Exit codes: 0 fleet drained clean and every journaled ticket reached
exactly one terminal state; 1 accounting broke or a request
failed/was quarantined; 75 preempted (queued work journaled, rc 75 is
the scheduler's requeue signal); 2 usage.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from apps._common import (  # noqa: E402
    add_health_flag,
    add_telemetry_flag,
    positive_int,
    setup_health,
    setup_telemetry,
)
from apps.serve import synthetic_trace  # noqa: E402


def make_parser():
    p = argparse.ArgumentParser(
        description="multi-replica serving fleet: router + journal + "
        "N SimulationService replicas (docs/SERVING.md 'The fleet')"
    )
    p.add_argument("--replicas", type=positive_int, default=3,
                   help="fleet size at launch (default 3)")
    p.add_argument("--synthetic", type=positive_int, default=None,
                   metavar="N", help="serve N deterministic synthetic "
                   "requests (default 12)")
    p.add_argument("--seed", type=int, default=1,
                   help="synthetic-trace seed (determinism contract)")
    p.add_argument("--nt-max", type=positive_int, default=64,
                   help="synthetic per-request step-count cap")
    p.add_argument("--dtype", default="f32",
                   choices=["f32", "f64", "bf16"],
                   help="synthetic-trace dtype")
    p.add_argument("--max-width", type=positive_int, default=8,
                   help="widest batch lane count per replica")
    p.add_argument("--max-depth", type=positive_int, default=None,
                   help="per-replica admission bound: the router "
                   "spills over it and fleet-full rejects carry the "
                   "MERGED retry-after hint (default: unbounded)")
    p.add_argument("--cpu-devices", type=int, default=0, metavar="N",
                   help="simulate N virtual CPU devices")
    p.add_argument("--sessions", default=None, metavar="DIR",
                   help="session root: each replica checkpoints its "
                   "sessions under DIR/replica-<id>/")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="stamp every synthetic request with this TTL "
                   "(expired by the ROUTER's clock — replicas never "
                   "own wall time)")
    p.add_argument("--elastic", action="store_true",
                   help="promote ElasticPolicy to the fleet "
                   "autoscaler: grow/retire whole replicas on "
                   "aggregate queue depth")
    p.add_argument("--max-replicas", type=positive_int, default=None,
                   help="autoscale ceiling (default: --replicas)")
    p.add_argument("--grow-depth", type=positive_int, default=8,
                   help="aggregate backlog per live replica that "
                   "makes the autoscaler consider a grow (--elastic)")
    p.add_argument("--ticks", type=positive_int, default=1000,
                   help="fleet drive-tick budget (bounded drills)")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="bank fleet-journal.jsonl + fleet-report.json "
                   "under DIR")
    p.add_argument("--inject-fault", default=None, metavar="SPEC",
                   help="deterministic fault plan, e.g. "
                   "'replica-kill@step=2,rank=1' (rank = REPLICA id; "
                   "resilience/faults.py has the grammar)")
    add_telemetry_flag(p)
    add_health_flag(p)
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)

    if args.inject_fault:
        from rocm_mpi_tpu.resilience import faults

        faults.install(args.inject_fault)

    import jax

    from rocm_mpi_tpu.parallel.distributed import maybe_initialize_distributed

    maybe_initialize_distributed()
    if args.cpu_devices:
        from rocm_mpi_tpu.utils.backend import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)
    setup_telemetry(args, jax)
    setup_health(args, jax)
    from rocm_mpi_tpu.telemetry import compiles

    compiles.install()
    from rocm_mpi_tpu.resilience import preempt

    preempt.install_from_env()

    from rocm_mpi_tpu.serving import journal as fleet_journal
    from rocm_mpi_tpu.serving.router import FleetRouter
    from rocm_mpi_tpu.serving.service import ServeConfig, SimulationService
    from rocm_mpi_tpu.telemetry import health
    from rocm_mpi_tpu.utils.logging import log0

    n = args.synthetic or 12
    requests = synthetic_trace(
        n, args.seed, nt_max=args.nt_max, dtype=args.dtype,
        deadline_s=args.deadline_s,
    )
    if any(r.dtype == "f64" for r in requests):
        jax.config.update("jax_enable_x64", True)

    out = pathlib.Path(args.out) if args.out else None
    if out is not None:
        out.mkdir(parents=True, exist_ok=True)
        journal_path = out / "fleet-journal.jsonl"
    else:
        journal_path = (
            pathlib.Path(tempfile.mkdtemp(prefix="rmt-fleet-"))
            / "fleet-journal.jsonl"
        )
    journal = fleet_journal.TicketJournal(journal_path)

    policy = None
    if args.elastic:
        from rocm_mpi_tpu.resilience.policy import ElasticPolicy

        policy = ElasticPolicy()

    def factory(rid: int) -> SimulationService:
        sessions_dir = None
        if args.sessions:
            sessions_dir = str(
                pathlib.Path(args.sessions) / f"replica-{rid}"
            )
        return SimulationService(config=ServeConfig(
            max_width=args.max_width,
            sessions_dir=sessions_dir,
        ))

    router = FleetRouter(
        factory, args.replicas,
        journal=journal,
        max_depth_per_replica=args.max_depth,
        policy=policy,
        max_replicas=args.max_replicas,
        grow_queue_depth=args.grow_depth,
    )
    log0(f"fleet up: {args.replicas} replica(s), journal "
         f"{journal_path} (max_width={args.max_width}, "
         f"max_depth={args.max_depth}, devices={len(jax.devices())})")

    # This driver is its own submitter: the trace is paced into the
    # fleet in waves with one drive tick between them — a drain pass
    # empties a replica's whole backlog, so up-front submission would
    # finish in one tick and a fault plan keyed to fleet ticks
    # (replica-kill@step=K) could never fire MID-traffic. With
    # --max-depth it also paces against the fleet backlog (drive,
    # then submit) so the fixed trace is never fast-rejected into the
    # void — the fleet-full reject path is for external submitters
    # who can honor the merged retry-after hint.
    served = 0
    wave = max(1, len(requests) // 4)
    for i in range(0, len(requests), wave):
        for r in requests[i:i + wave]:
            if args.max_depth is not None:
                while router.healthy_replicas() and all(
                    rep.depth() >= args.max_depth
                    for rep in router.healthy_replicas()
                ):
                    served += router.drive_once()
            router.submit(r)
        if i + wave < len(requests):
            served += router.drive_once()
    served += router.drive(max_ticks=args.ticks)

    problems = router.check_accounting()
    merged = router.merged_counters()
    stream_paths = ()
    if args.telemetry:
        stream_paths = tuple(sorted(
            pathlib.Path(args.telemetry).glob("telemetry-rank*.jsonl")
        ))
    doc = router.report_doc(stream_paths=stream_paths)

    log0(
        f"fleet served {served} batch-request(s): "
        f"{merged['completed']}/{merged['submitted']} done, "
        f"{merged['failed']} failed, {merged['rejected']} rejected, "
        f"{merged['expired']} expired, "
        f"{merged['quarantined']} quarantined, "
        f"{merged['retries']} retries"
    )
    for rep in router.replicas:
        state = ("up" if rep.healthy
                 else (rep.verdict or "down"))
        log0(f"  replica {rep.id}: {state} "
             f"counters={rep.svc.queue.counters()}")
    for ev in router.autoscale_events:
        log0(f"  autoscale: {ev}")
    jc = doc["journal"]
    log0(f"  journal: {jc['tickets']} ticket(s), {jc['open']} open, "
         f"{jc['rerouted']} rerouted, {jc['torn_lines']} torn")
    for p in problems:
        log0(f"  ACCOUNTING: {p}")
    log0(health.format_fleet_status(health.fleet_status(doc)))

    if out is not None and jax.process_index() == 0:
        report_path = out / "fleet-report.json"
        fleet_journal.write_fleet_report(report_path, doc)
        log0(f"banked {journal_path.name} and {report_path.name} "
             f"({len(doc['replicas'])} replica row(s))")
    journal.close()

    if router.preempted:
        log0("preempted: queued work journaled; rc 75 (EX_TEMPFAIL)")
        return 75
    if problems or merged["failed"] or merged["quarantined"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
