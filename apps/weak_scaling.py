"""Weak-scaling harness — the driver-baseline north-star measurement.

Target (BASELINE.md): the overlap variant at 252²/device on a pod slice at
≥90% weak-scaling efficiency vs single chip. This harness holds the local
shard size fixed, grows the global grid with the device count (the same
weak-scaling protocol as the reference's per-rank-constant grids,
/root/reference/scripts/diffusion_2D_perf.jl:21-22 — 12288² *per rank*),
and reports per-device throughput and efficiency vs the 1-device run.

On real multi-chip hardware this measures the target directly. On one chip
(or `--cpu-devices N` virtual devices) it exercises the full sharded code
path — mesh construction, ppermute halo, overlap scheduling — so the
scaling *mechanics* are testable anywhere, as with everything else in this
framework.

  python apps/weak_scaling.py --cpu-devices 8        # 1,2,4,8 virtual devs
  python apps/weak_scaling.py --local 252 --variant hide   # real hardware
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def telemetry_windowed_run(model, variant: str, nt: int, warmup: int,
                           windows: int, driver: str = "step",
                           step_base: int = 0, config: str | None = None):
    """The --telemetry run path (diffusion): the same warmup/timed
    protocol as model.run, but the timed loop split into `windows`
    spanned windows — per-step PERCENTILES need more than the single
    sample model.run's one timed window gives (aggregate's p50/p90/p99
    over windows is what catches a straggling stretch the mean hides).
    Each window boundary costs one device-fetch sync (the span's
    correctness requirement); windows of many steps amortize it, exactly
    as tic/toc always did.

    `driver` picks the loop form (step/scan — models run the same step
    program either way); the scan driver's static chunk q quantizes the
    windows (every window a multiple of q, guaranteed non-degenerate by
    q | gcd(warmup, timed)), and every span carries the driver stamp so
    summaries from different drivers can't be compared silently.

    Under the health plane (--health / RMT_HEALTH, flight recorder on)
    each window boundary additionally runs, in this order: (1) the halo
    heartbeat probe — one REAL cross-rank exchange under a
    `halo.heartbeat` span, a live probe of the collective fabric whose
    entry is the last thing a rank wedging at the boundary records;
    (2) the "window" fault point (deterministic drills: the `stall`
    kind wedges a rank right here); (3) the flight-recorder step bump,
    flushed to the sidecar BEFORE this rank enters the window's
    compiled collectives. The bump-after-fault-point order is what
    makes the watchdog's stalled-collective signature deterministic: a
    rank stalled at boundary K never publishes step K, while its peer
    publishes K and then blocks inside window K — the cross-rank median
    moves past the victim and names it (telemetry.health). `step_base`
    offsets the published steps by this process's earlier ladder rungs
    (run or sat out), keeping flight step counters COMPARABLE across
    ranks — the watchdog's contract; a rung-local restart would be
    masked by the recorder's monotonic guard and skew every later
    comparison.
    After warmup (and the heartbeat's own compile) the run calls
    `compiles.mark_steady()`: every later XLA compile counts as a
    steady-state recompile, banked as the `compiles.steady_state` gauge
    the regress gate pins at 0."""
    from rocm_mpi_tpu.models.diffusion import RunResult
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.telemetry import compiles, flight
    from rocm_mpi_tpu.utils import metrics

    if not 0 <= warmup < nt:
        # Same contract as model.run: a degenerate window must fail
        # loudly here, not as a later divide-by-zero or a negative rate.
        raise ValueError(f"need 0 <= warmup < nt, got {warmup}, {nt}")
    if driver == "scan":
        advance, unit = model.scan_advance_fn(variant, nt=nt, warmup=warmup,
                                              config=config)
    else:
        advance, unit = model.advance_fn(variant), 1
    T, Cp = model.init_state()
    from rocm_mpi_tpu import telemetry

    with telemetry.span("warmup", steps=warmup, variant=variant,
                        driver=driver) as sp:
        if warmup:
            T = advance(T, Cp, warmup)
        sp.sync(T)
    heartbeat = None
    if flight.enabled():
        from rocm_mpi_tpu.telemetry import probes

        heartbeat = probes.make_halo_heartbeat(model)
        T = heartbeat(T)  # eat the heartbeat's compile inside warmup
    if warmup:
        # The warmup line: the window program and the heartbeat are
        # compiled; anything XLA compiles after this is a recompile.
        # A --warmup 0 run has no warmup line to draw — the window
        # program's FIRST compile would land inside the steady window
        # and fail the zero-pin gate with no actual recompile storm —
        # so such runs simply don't pin steady state (the gauge is
        # only emitted once a window was ever opened).
        compiles.mark_steady()
    timed = nt - warmup
    n_windows = max(1, min(windows, timed // unit))
    base, extra = divmod(timed // unit, n_windows)
    wtime = 0.0
    done = warmup
    for i in range(n_windows):
        w = (base + (1 if i < extra else 0)) * unit
        if w == 0:
            continue
        if heartbeat is not None:
            T = heartbeat(T)
        faults.fault_point("window", step=done)
        flight.progress(step=step_base + done, windows=1)
        timer = metrics.Timer(label="step_window", phase="step", steps=w,
                              variant=variant, window=i, driver=driver,
                              workload="diffusion")
        timer.tic(T)
        T = advance(T, Cp, w)
        timer.toc(T)
        wtime += timer.elapsed
        done += w
    flight.progress(step=step_base + done)
    # Close this rung's steady window: the NEXT rung's mesh/shape
    # compiles are legitimate warmup, not steady-state recompiles.
    compiles.unmark_steady()
    return RunResult(T=T, wtime=wtime, nt=nt, warmup=warmup,
                     config=model.config)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--local", type=int, default=252,
                   help="per-device shard edge (target geometry: 252)")
    p.add_argument("--nt", type=int, default=2000)
    p.add_argument("--warmup", type=int, default=200)
    p.add_argument("--variant", default=None,
                   choices=["ap", "fused", "shard", "perf", "kp", "hide",
                            "deep"],
                   help="step schedule; 'deep' = deep-halo sweeps "
                   "(run_deep, the flagship multi-chip schedule). "
                   "Default: hide (both workloads)")
    p.add_argument("--workload", default="diffusion",
                   choices=["diffusion", "wave", "swe"],
                   help="physics model: the diffusion flagship, the "
                   "acoustic-wave second workload, or the shallow-water "
                   "coupled workload (non-diffusion variants "
                   "ap/perf/hide/deep)")
    p.add_argument("--deep-k", type=int, default=None, metavar="K",
                   help="deep-halo sweep depth (default: run_deep's auto)")
    p.add_argument("--dtype", default="f32")
    p.add_argument("--cpu-devices", type=int, default=0, metavar="N")
    p.add_argument("--counts", default=None,
                   help="comma-separated device counts (default: powers of 2 "
                   "up to all available)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line per count as well")
    from _common import (
        add_driver_flag,
        add_health_flag,
        add_telemetry_flag,
        setup_jax,
    )

    add_driver_flag(p)
    add_telemetry_flag(p)
    add_health_flag(p)
    p.add_argument("--telemetry-windows", type=int, default=8, metavar="W",
                   help="with --telemetry: split the timed loop into W "
                   "spanned windows (per-step percentiles need more than "
                   "one sample; default %(default)s)")
    p.add_argument("--no-probes", dest="probes", action="store_false",
                   default=True,
                   help="with --telemetry: skip the halo/interior/"
                   "checkpoint phase-attribution probes "
                   "(telemetry.probes)")
    p.add_argument("--autotune", action="store_true",
                   help="consult the persistent tuning cache "
                   "(config='auto', docs/PERF.md 'Autotuning'): the scan "
                   "chunk and deep-halo depth resolve per "
                   "(shape, dtype, topology, backend) key, falling back "
                   "to the hand defaults on a miss; cache hit/miss and "
                   "the chosen configs land in the run gauges "
                   "(tune.hits/tune.misses) so `telemetry regress` can "
                   "gate tuned-vs-default ladders")
    args = p.parse_args(argv)

    jax = setup_jax(args)  # distributed init + --cpu-devices + x64, shared
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.utils.logging import log0
    from rocm_mpi_tpu.models import (
        AcousticWave,
        HeatDiffusion,
        ShallowWater,
        SWEConfig,
        WaveConfig,
    )
    from rocm_mpi_tpu.parallel.mesh import suggest_dims

    if args.variant is None:
        args.variant = "hide"
    if args.workload != "diffusion" and args.variant not in (
        "ap", "perf", "hide", "deep"
    ):
        log0(f"--workload {args.workload} supports variants "
             f"ap/perf/hide/deep, not {args.variant!r}")
        return 2

    n_avail = len(jax.devices())
    if args.counts:
        # Ascending, deduplicated: the first row run IS the efficiency
        # baseline, so the smallest count must come first.
        counts = sorted({int(c) for c in args.counts.split(",")})
    else:
        counts, c = [], 1
        while c <= n_avail:
            counts.append(c)
            c *= 2
    base_per_dev = base_n = None
    probe_model = None
    # Global flight-step offset across the ladder (health plane): every
    # rung this process ran — or sat out before its first participation
    # — banks its nt, so participating ranks' step counters stay
    # comparable rung after rung (the watchdog's contract; a sat-out
    # rung never publishes, which the read side treats as
    # "not participating", never as "stalled").
    steps_banked = 0
    # The loop-form stamp every gauge/probe carries (the deep schedule is
    # its own form; --driver only selects among the per-step loop forms).
    run_driver = "deep" if args.variant == "deep" else args.driver
    # Process-0-gated output: on a multi-host slice every process runs this
    # script, but only one may report (rank-0 printing, SURVEY.md §5.5).
    log0(
        f"weak scaling: variant={args.variant}, {args.local}²/device, "
        f"nt={args.nt}, dtype={args.dtype}, {n_avail} device(s) available"
    )
    for n in counts:
        if n > n_avail:
            log0(f"n={n}: skipped (only {n_avail} devices)")
            continue
        if not any(
            d.process_index == jax.process_index()
            for d in jax.devices()[:n]
        ):
            # A rung whose submesh holds none of this process's devices:
            # this process cannot allocate on it (jax 0.4.x refuses a
            # device assignment with no local devices) and the compute is
            # entirely local to the owning process(es) — sit the rung out
            # (banking its steps for the health plane's global counter).
            steps_banked += args.nt
            continue
        dims = suggest_dims(n, 2)
        shape = (args.local * dims[0], args.local * dims[1])
        common = dict(
            global_shape=shape,
            lengths=(10.0 * dims[0], 10.0 * dims[1]),
            nt=args.nt,
            warmup=args.warmup,
            dtype=args.dtype,
            dims=dims,
        )
        model_cls, cfg_cls = {
            "wave": (AcousticWave, WaveConfig),
            "swe": (ShallowWater, SWEConfig),
            "diffusion": (HeatDiffusion, DiffusionConfig),
        }[args.workload]
        model = model_cls(cfg_cls(**common), devices=jax.devices()[:n])
        from rocm_mpi_tpu import telemetry

        run_config = "auto" if args.autotune else None
        if args.variant == "deep":
            # Both models default None to their own depth policy and
            # reject explicit invalid depths loudly. --autotune lets an
            # unset depth consult the tuning cache (diffusion only — the
            # other models keep their own policies).
            if args.workload == "diffusion":
                r = model.run_deep(block_steps=args.deep_k,
                                   config=run_config)
            else:
                r = model.run_deep(block_steps=args.deep_k)
        elif (telemetry.enabled() and args.workload == "diffusion"
              and model.config.halo_transport != "host"):
            # The windowed path drives the advance directly; under
            # halo_transport='host' that would silently measure the
            # device-collective path while labeling it a host run —
            # model.run owns the host-staged dispatch and its warning.
            # GL08: this run IS reachable under the rank-dependent rung
            # sit-out above — by design: a sitting-out process owns no
            # device of the rung's submesh, so the rung's collectives
            # span only the participating processes' devices and every
            # participant still issues the identical sequence.
            # graftlint: disable-next=GL08
            r = telemetry_windowed_run(
                model, args.variant, args.nt, args.warmup,
                args.telemetry_windows, driver=args.driver,
                step_base=steps_banked, config=run_config,
            )
        else:
            r = model.run(variant=args.variant, driver=args.driver,
                          config=run_config)
        steps_banked += args.nt
        probe_model = model  # the last rung this process participated in
        per_dev = r.gpts / n
        if base_per_dev is None:
            # The efficiency baseline is the smallest count actually run;
            # the north-star "vs single chip" number requires n=1 in the
            # list, so label the baseline explicitly.
            base_per_dev, base_n = per_dev, n
        eff = per_dev / base_per_dev
        # The driver stamp rides every gauge: a "scan"-driver summary and
        # a "step"-driver summary are different measurements and must not
        # regress-gate against each other silently.
        if telemetry.enabled():
            telemetry.gauge("run.gpts", round(r.gpts, 6), devices=n,
                            variant=args.variant, workload=args.workload,
                            driver=run_driver)
            telemetry.gauge("run.gpts_per_device", round(per_dev, 6),
                            devices=n, driver=run_driver)
            telemetry.gauge("run.efficiency", round(eff, 6), devices=n,
                            driver=run_driver)
        log0(
            f"n={n:4d} mesh={dims} global={shape}: "
            f"{r.wtime_it * 1e6:9.3f} us/step  {r.gpts:9.4f} Gpts/s "
            f"({per_dev:7.4f}/dev)  efficiency={eff:6.1%} vs n={base_n}"
        )
        if args.json and jax.process_index() == 0:
            wl = "" if args.workload == "diffusion" else f"{args.workload} "
            row = {
                "metric": f"weak-scaling {wl}{args.variant} "
                          f"{args.local}²/dev",
                "devices": n, "dims": dims, "gpts": round(r.gpts, 4),
                "gpts_per_device": round(per_dev, 4),
                "efficiency": round(eff, 4),
            }
            if jax.devices()[0].platform == "cpu":
                # Interpret-mode rates are meaningless; without this stamp
                # a committed jsonl row's bare `efficiency` reads as a
                # performance claim (VERDICT r4 weak #6). Real-hardware
                # rows omit the key and ARE the claim.
                row["mechanics_only"] = True
            print(json.dumps(row))

    from rocm_mpi_tpu import telemetry

    if telemetry.enabled():
        # Compile accounting, banked BEFORE the phase probes below: the
        # probes compile their own halo/interior programs on purpose,
        # and those deliberate epilogue compiles must not show up as
        # steady-state recompiles in the gauge the regress gate pins.
        from rocm_mpi_tpu.telemetry import compiles

        compiles.emit_gauges()
        # Autotuner resolve outcomes (tune.hits/tune.misses + per-key
        # tune.resolve annotations): a tuned ladder and a hand-default
        # ladder are different measurements — the gauges say which this
        # was, so regress never compares them silently.
        from rocm_mpi_tpu.tuning import resolve as tuning_resolve

        tuning_resolve.emit_gauges()

    if (telemetry.enabled() and args.probes and probe_model is not None
            and args.workload == "diffusion"):
        # Phase attribution for the fused step (telemetry/probes.py):
        # halo-only and interior-only programs over the final rung's
        # state, plus one save/restore cycle for the checkpoint phase.
        # Participation bookkeeping: process sets grow monotonically with
        # the rung's device count, so every process with a probe_model
        # participated in the final rung and holds that rung's model —
        # the halo/interior probes are mesh-scoped collectives among
        # exactly those processes (the same shape every rung already
        # runs). The ORBAX save is different: its completion barrier is
        # GLOBAL across all jax processes, so the checkpoint probe only
        # runs when the probe mesh spans every process — a host whose
        # devices sat out the whole ladder must not be waited on.
        from rocm_mpi_tpu.telemetry import events as tel_events
        from rocm_mpi_tpu.telemetry import probes

        tel_dir = tel_events.directory()
        mesh_procs = {
            d.process_index
            for d in probe_model.grid.mesh.devices.flat
        }
        spans_all = mesh_procs == set(range(jax.process_count()))
        ckpt_dir = (
            pathlib.Path(tel_dir) / "ckpt-probe"
            if tel_dir and spans_all else None
        )
        log0("telemetry: running halo/interior"
             + ("/checkpoint" if ckpt_dir else "")
             + " phase probes")
        probes.run_diffusion_phase_probes(
            probe_model, checkpoint_dir=ckpt_dir, driver=run_driver,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
