"""The long-horizon chaos soak — ROADMAP item 5's driver
(docs/RESILIENCE.md §8 "The soak").

Nine PRs built fault machinery one plane at a time (supervised retries,
elastic shrink/grow, preemption, storage faults, tenant isolation, the
request-plane SLOs); this driver is where they COMPOSE: a live serving
session runs under a deterministic rolling fault schedule that strikes
all three layers —

  * the queue (queue-flood admission storms, deadline expiry),
  * the lanes (lane-nan numerical poison, batch-error/slow-batch,
    the per-BinKey circuit breaker's open → half-open → recover arc),
  * the infrastructure (SIGTERM eviction, injected storage outages
    through the session-save path, and gloo-real ≥2-rank episodes where
    a rank is killed / vanishes / stalls mid-batch and the launcher's
    supervision — peer-grace kill, vanish detection, the progress
    watchdog — must name the victim),

with SLO accounting (request latency p50/p99 from real telemetry
events, deadline-miss rate, rejected/expired/quarantined totals) banked
in a schema-versioned, atomically-written `soak-report.json`
(serving/slo.py) plus the append-only `quarantine.jsonl` poison ledger.

`--bounded` is the chip_watcher.sh edition (minutes, not hours): one
episode per fault family, the gloo kill drill included. The full
schedule adds the die (vanish) and stall (watchdog) episodes. Exit 0
iff every episode met its expectation AND the terminal accounting
invariant held everywhere — a soak that "mostly worked" is a failed
soak.

    python apps/soak.py --bounded --out output/soak
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from apps._common import positive_int  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent

# Shapes small enough that every episode compiles in seconds on any
# backend; two classes so the bin scheduler has real work.
SHAPE_A = (16, 16)
SHAPE_B = (24, 24)


def _req(rid, shape=SHAPE_A, nt=4, workload="diffusion", dtype="f32",
         **kw):
    from rocm_mpi_tpu.serving.queue import Request

    return Request(request_id=rid, workload=workload,
                   global_shape=shape, dtype=dtype, nt=nt, **kw)


def _drive(svc, flood_shape=SHAPE_A, max_drains=200):
    """Drain the service to empty, consulting the `queue-flood` fault
    at each drain boundary (the driver owns submission, so the flood
    lives here, not in the service). Returns the number of flooded
    submissions."""
    from rocm_mpi_tpu.resilience import faults

    flooded = 0
    drain = 0
    while True:
        drain += 1
        clause = faults.serving_fault("queue-flood", step=drain)
        if clause is not None:
            n = max(int(clause.delay_s), 1)
            for i in range(n):
                svc.queue.submit(_req(
                    f"flood-{drain}-{i:03d}", shape=flood_shape, nt=2,
                    ic_scale=1.0 + 0.001 * i,
                ))
            flooded += n
        svc.maybe_resize()
        _, preempted = svc.drain_once()
        if preempted or svc.queue.depth() == 0:
            return flooded, preempted
        delay = svc.queue.next_ready_delay()
        if delay:
            time.sleep(min(delay, 0.25))
        if drain >= max_drains:
            raise RuntimeError(
                f"soak drive did not drain in {max_drains} drains "
                f"(depth {svc.queue.depth()})"
            )


def _episode(name, mode, fault_spec, fn):
    """Run one episode; never let an exception escape the schedule —
    a failed episode is a row with ok=False and the error, and the
    soak exits 1 (a crashed soak banks no report at all)."""
    from rocm_mpi_tpu.resilience import faults

    t0 = time.monotonic()
    row = {"name": name, "mode": mode, "faults": fault_spec or ""}
    print(f"[soak] episode {name} ({mode})", flush=True)
    try:
        faults.install(fault_spec)
        details = fn()
        row.update(ok=True, **(details or {}))
    except Exception as e:  # noqa: BLE001 — the report is the verdict
        row.update(ok=False, error=f"{type(e).__name__}: {e}")
    finally:
        faults.install(None)
    row["wall_s"] = round(time.monotonic() - t0, 3)
    status = "ok" if row["ok"] else f"FAILED ({row.get('error')})"
    print(f"[soak] episode {name}: {status} in {row['wall_s']}s",
          flush=True)
    return row


class Soak:
    def __init__(self, out: pathlib.Path, ranks: int, seed: int):
        self.out = out
        self.ranks = ranks
        self.seed = seed
        self.quarantine = out / "quarantine.jsonl"
        self.counters: dict[str, int] = {}
        self.stream_dirs = [out / "telemetry"]

    # ---- shared plumbing ------------------------------------------------

    def _service(self, **cfg):
        from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy
        from rocm_mpi_tpu.serving.service import (
            ServeConfig,
            SimulationService,
        )

        cfg.setdefault("max_width", 4)
        cfg.setdefault("quarantine_path", str(self.quarantine))
        cfg.setdefault(
            "retry", RequestRetryPolicy(budget=2, backoff_base_s=0.01)
        )
        return SimulationService(config=ServeConfig(**cfg))

    def _bank(self, svc, name: str) -> dict:
        """Close one in-process episode: accounting invariant asserted,
        counters folded into the soak totals, manifest banked."""
        svc._assert_accounting()
        c = svc.queue.counters()
        for k, v in c.items():
            if k != "depth":
                self.counters[k] = self.counters.get(k, 0) + int(v)
        self.counters["retries"] = (
            self.counters.get("retries", 0) + svc.retries_total
        )
        svc.write_manifest(self.out / f"serve-manifest-{name}.json")
        return c

    # ---- in-process episodes -------------------------------------------

    def ep_serve_chaos(self):
        """The request-plane storm: flood + deadline expiry + NaN
        poison + a transient batch error + a slow batch, on an elastic
        service — admission rejects the overflow fast, the poison lane
        ends quarantined, everything else serves."""
        import jax

        from rocm_mpi_tpu.resilience.policy import ElasticPolicy

        svc = self._service(
            max_depth=8,
            policy=ElasticPolicy(min_grow_interval_steps=0),
            device_budget=lambda: len(jax.devices()),
            grow_queue_depth=6,
            idle_shrink_drains=2,
        )
        for i in range(8):
            svc.queue.submit(_req(
                f"chaos-{i:03d}",
                shape=SHAPE_A if i % 3 else SHAPE_B,
                nt=3 + (i % 4),
                ic_scale=1.0 + 0.02 * i,
                # Two tickets with an already-hopeless TTL: pinned
                # deterministic deadline-exceeded at pop time.
                deadline_s=1e-6 if i in (5, 6) else None,
            ))
        flooded, _ = _drive(svc)
        c = self._bank(svc, "serve-chaos")
        assert c["quarantined"] >= 1, f"no quarantine: {c}"
        assert c["rejected"] >= 2, f"flood not rejected: {c}"
        assert c["expired"] >= 2, f"deadlines not expired: {c}"
        return {"counters": c, "flooded": flooded,
                "grew": bool(svc._elastic)}

    def ep_pipeline(self):
        """The pipelined drain under a slow-batch fault
        (docs/SERVING.md "The pipeline"): the SAME trace through the
        double-buffered drain and its serial twin — the overlapped
        fetch/resolve stage must not reorder terminal accounting
        (identical queue counters, invariant asserted on both) and
        every co-served result stays bitwise-equal across modes."""
        import numpy as np

        def trace():
            return [
                _req(
                    f"pipe-{i:02d}",
                    shape=SHAPE_A if i % 3 else SHAPE_B,
                    nt=3 + (i % 3),
                    ic_scale=1.0 + 0.015 * i,
                )
                for i in range(8)
            ]

        outs = {}
        counters = {}
        for depth in (2, 1):
            svc = self._service(max_width=2, pipeline_depth=depth)
            tickets = [svc.queue.submit(r) for r in trace()]
            _drive(svc)
            svc._assert_accounting()
            counters[depth] = {
                k: v for k, v in svc.queue.counters().items()
                if k != "depth"
            }
            outs[depth] = [t.result(timeout=5) for t in tickets]
            if depth == 2:
                pipe = svc.pipeline_stats()
                assert pipe["depth"] == 2 and pipe["batches"] >= 1, pipe
                self._bank(svc, "pipeline")
        assert counters[2] == counters[1], (
            "pipelined drain reordered terminal accounting: "
            f"{counters[2]} != {counters[1]}"
        )
        for i, (a, b) in enumerate(zip(outs[2], outs[1])):
            for la, lb in zip(a, b):
                assert np.array_equal(np.asarray(la), np.asarray(lb)), (
                    f"request {i}: pipelined != serial"
                )
        return {"counters": counters[2], "bubble": pipe["bubble"]}

    def ep_swap(self):
        """The continuous-batching swap drill (docs/SERVING.md
        "Continuous batching"): a same-class backlog deeper than the
        batch width runs through the step-segmented drain, so resolved
        lanes swap out at segment boundaries and queued tenants swap
        into their slots — and the swapped-in poison lane (lane-nan on
        every attempt) exhausts its retry budget mid-trace. The
        exactly-one-terminal invariant must hold across the swap churn,
        and every surviving co-batched tenant stays bitwise-equal to
        its standalone batch-synchronous twin."""
        import numpy as np

        def trace(tag):
            # One bin class: nts 4/3 share the 4-step bucket, so the
            # 2-step segments see both mid-flight freezes and
            # finishers whose slots the backlog refills.
            return [
                _req(f"{tag}-{i:02d}", shape=SHAPE_A,
                     nt=4 if i % 2 == 0 else 3,
                     ic_scale=1.0 + 0.02 * i)
                for i in range(6)
            ]

        svc = self._service(max_width=2, segments=2)
        tickets = [svc.queue.submit(r) for r in trace("swap")]
        _drive(svc)
        cont = svc._continuous
        assert cont["batches"] >= 1, cont
        assert cont["swaps_in"] >= 1, (
            f"segmented drain never swapped a lane in: {cont}"
        )
        # The poisoned swap-in (ordinal 3) burned its whole retry
        # budget; everyone else reached done — exactly one terminal
        # state each, certified by _bank's accounting assert.
        bad = tickets[2]
        assert bad.state == "quarantined", (bad.state, bad.error)
        for t in tickets:
            if t is not bad:
                assert t.state == "done", (
                    t.request.request_id, t.state, t.error
                )
        c = self._bank(svc, "swap")
        assert c["completed"] == 5 and c["quarantined"] == 1, c
        # Bitwise pin: each survivor against a solo batch-synchronous
        # run (the injected lane-nan clause is exhausted by now).
        twin = self._service(max_width=1)
        twin_tickets = [twin.queue.submit(r) for r in trace("swap")]
        _drive(twin)
        for i, (t, ref) in enumerate(zip(tickets, twin_tickets)):
            if t is bad:
                continue
            for a, b in zip(t.result(timeout=5), ref.result(timeout=5)):
                assert np.array_equal(np.asarray(a), np.asarray(b)), (
                    f"request {i}: swapped lane != standalone twin"
                )
        return {"counters": c, "swaps_in": cont["swaps_in"],
                "segments_run": cont["segments_run"]}

    def ep_breaker(self):
        """The circuit-breaker arc: three consecutive injected batch
        errors open SHAPE_A's class (its pending requests reject fast
        with circuit-open while SHAPE_B keeps serving), the cooled-down
        breaker re-admits one half-open probe, and recovery closes it."""
        from rocm_mpi_tpu.resilience.policy import (
            CircuitPolicy,
            RequestRetryPolicy,
        )

        svc = self._service(
            max_width=2,
            retry=RequestRetryPolicy(budget=1, backoff_base_s=0.0),
            circuit=CircuitPolicy(k=3, cooldown_drains=2),
        )
        from rocm_mpi_tpu.resilience import faults

        # Drain 1 executes SHAPE_A's three width-2 batches first
        # (sorted bin keys), then SHAPE_B's: the three errors strike
        # exactly class A.
        faults.install(
            "batch-error@step=1;batch-error@step=2;batch-error@step=3"
        )
        healthy = []
        for i in range(6):
            svc.queue.submit(_req(f"brk-a-{i}", shape=SHAPE_A, nt=3))
        for i in range(2):
            healthy.append(svc.queue.submit(
                _req(f"brk-b-{i}", shape=SHAPE_B, nt=3)
            ))
        _drive(svc)
        from rocm_mpi_tpu.serving.bins import bin_key

        key_a = bin_key(_req("probe0", shape=SHAPE_A, nt=3))
        br = svc._breakers[key_a]
        assert br.state == "open", f"breaker never opened ({br.state})"
        for t in healthy:
            assert t.state == "done", (
                "an open class starved a healthy tenant: "
                f"{t.request.request_id} {t.state}"
            )
        # Cool down (empty drains), then the half-open probe recovers
        # (the injected errors are exhausted by now).
        svc.drain_once()
        svc.drain_once()
        probe = svc.queue.submit(_req("probe-recover", shape=SHAPE_A,
                                      nt=3))
        _drive(svc)
        assert probe.state == "done", f"probe {probe.state}: {probe.error}"
        assert br.state == "closed", f"breaker stuck {br.state}"
        c = self._bank(svc, "breaker")
        assert c["rejected"] >= 1, f"open breaker rejected nothing: {c}"
        return {"counters": c}

    def ep_storage(self):
        """Storage outages strike the session-save path: an io-error
        burst outlasting the checkpoint retry ladder fails the lane,
        the request-plane retry re-runs it to a clean save; enospc and
        io-slow are absorbed by the StoragePolicy ladder itself."""
        sessions = self.out / "sessions"
        svc = self._service(sessions_dir=str(sessions))
        from rocm_mpi_tpu.resilience import faults

        faults.install(
            "io-error@step=6,times=3;io-slow=0.1@step=8;"
            "enospc@step=10"
        )
        a = svc.queue.submit(_req("store-a", nt=6, session="soak-a"))
        b = svc.queue.submit(_req("store-b", nt=8, session="soak-b"))
        d = svc.queue.submit(_req("store-c", nt=10, session="soak-c"))
        _drive(svc)
        for t in (a, b, d):
            assert t.state == "done", (t.request.request_id, t.error)
        assert a.retries >= 1, "outage never forced a request retry"
        from rocm_mpi_tpu.utils import checkpoint as ckpt

        for sid, nt in (("soak-a", 6), ("soak-b", 8), ("soak-c", 10)):
            step = ckpt.latest_valid_step(sessions / sid)
            assert step == nt, f"session {sid}: {step} != {nt}"
        c = self._bank(svc, "storage")
        return {"counters": c, "request_retries": a.retries}

    def ep_evict(self):
        """A real SIGTERM eviction mid-trace: the notice stops dispatch
        at the batch boundary, every unserved ticket is requeued (the
        rc-75 contract), and the relaunched drain serves them all."""
        from rocm_mpi_tpu.resilience import preempt

        preempt.install(grace_s=30.0)
        svc = self._service(max_width=1)
        for i in range(6):
            svc.queue.submit(_req(f"evict-{i}", nt=3,
                                  ic_scale=1.0 + 0.01 * i))
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not preempt.requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert preempt.requested(), "SIGTERM notice never landed"
        report = svc._drain_all()
        assert report.preempted, "drain ignored the eviction notice"
        requeued = svc.queue.depth()
        assert requeued >= 1, "nothing requeued at the eviction"
        # The next service instance (same queue here) drains the parked
        # work after the eviction passes.
        preempt.reset()
        report2 = svc._drain_all()
        assert not report2.preempted
        assert svc.queue.depth() == 0
        c = self._bank(svc, "evict")
        assert c["completed"] == 6, c
        return {"counters": c, "requeued_at_eviction": requeued}

    def ep_fleet(self):
        """The fleet kill drill (docs/SERVING.md "The fleet"): three
        in-process replicas behind the router + ticket journal,
        replica 1 killed MID-traffic by the fault grammar at fleet
        tick 2 — every journaled ticket reaches exactly one terminal
        state fleet-wide (journal replay is idempotent and balances),
        the surviving tenants' results stay bitwise-equal to a
        standalone twin, and the merged fleet report banks
        schema-valid with compiles.steady_state 0 per replica."""
        import numpy as np

        from rocm_mpi_tpu.serving import journal as fleet_journal
        from rocm_mpi_tpu.serving.router import FleetRouter
        from rocm_mpi_tpu.telemetry import compiles

        # The report rows carry the process-global steady-recompile
        # count; isolate this episode's window from earlier episodes'
        # compile traffic (the installed tap stays).
        compiles.reset()

        def trace(prefix="fleet"):
            # Three bins over two shapes: wave pacing below guarantees
            # at least one ticket is OPEN on replica 1 at the tick-2
            # kill (bin affinity spreads the three bins one per
            # replica on first route).
            return [
                _req(
                    f"{prefix}-{i:02d}",
                    shape=SHAPE_A if i % 3 else SHAPE_B,
                    nt=3 + (i % 3),
                    ic_scale=1.0 + 0.015 * i,
                )
                for i in range(9)
            ]

        jpath = self.out / "fleet-journal.jsonl"
        if jpath.exists():
            jpath.unlink()
        journal = fleet_journal.TicketJournal(jpath)
        router = FleetRouter(
            lambda rid: self._service(max_width=2), 3, journal=journal,
        )
        reqs = trace()
        tickets = []
        for i in range(0, len(reqs), 3):
            tickets += [router.submit(r) for r in reqs[i:i + 3]]
            router.drive_once()
        router.drive()
        problems = router.check_accounting()
        assert not problems, problems
        dead = [r for r in router.replicas if not r.alive]
        assert [r.id for r in dead] == [1], (
            f"replica-kill@step=2,rank=1 did not kill replica 1: "
            f"{[(r.id, r.alive, r.verdict) for r in router.replicas]}"
        )
        state = router.journal_state()
        counts = state.counts()
        assert counts["open"] == 0 and counts["rerouted"] >= 1, counts
        # Replay idempotence: the journal is a pure fold — replaying
        # the complete journal changes no counter.
        assert fleet_journal.replay(journal.segments()).counts() \
            == counts, "journal replay is not idempotent"
        # Bitwise twin: the same trace through ONE standalone service.
        # Distinct twin ids: the twin's done events land in the SAME
        # rank stream, and the trace-continuity check below pins "one
        # terminal span per fleet request" — identical ids would read
        # as duplicate terminals (results only depend on shape/nt/
        # ic_scale, so renaming changes nothing bitwise).
        twin = self._service(max_width=2)
        twin_tickets = [twin.queue.submit(r) for r in trace("twin")]
        _drive(twin)
        for t, ref in zip(tickets, twin_tickets):
            assert t.state == "done", (t.request.request_id, t.error)
            for a, b in zip(t.result(timeout=5),
                            ref.result(timeout=5)):
                assert np.array_equal(np.asarray(a), np.asarray(b)), (
                    f"{t.request.request_id}: fleet != standalone twin"
                )
        streams = sorted(
            pathlib.Path(self.stream_dirs[0]).glob(
                "telemetry-rank*.jsonl"
            )
        )
        doc = router.report_doc(stream_paths=streams)
        assert doc["accounting_ok"], doc
        for row in doc["replicas"]:
            assert row["steady_state"] == 0, row
        fleet_journal.write_fleet_report(
            self.out / "fleet-report.json", doc
        )
        # Trace continuity across the failover (docs/TELEMETRY.md
        # "Request tracing"): every ticket's causal timeline must end
        # in exactly ONE terminal span, the journal-recovered tickets
        # must show BOTH hops (minted at the front door, hop+1 at
        # reconcile), and the done event's latency decomposition must
        # telescope — stages summing to the measured latency — under a
        # real mid-batch kill, not a unit fixture.
        from rocm_mpi_tpu.telemetry import aggregate, tracing

        loaded, _ = aggregate.load_rank_streams(self.stream_dirs[0])
        rerouted_ids = []
        for t in tickets:
            rid = t.request.request_id
            tl = tracing.request_timeline(loaded, rid)
            assert tl is not None, f"{rid}: no trace in rank streams"
            assert not tl["warnings"], (rid, tl["warnings"])
            terms = [r for r in tl["events"]
                     if r["name"].startswith("serve.request.")
                     and r["name"].split(".")[-1] in
                     ("done", "quarantined", "rejected", "expired")]
            assert len(terms) == 1 and tl["terminal"] == "done", (
                f"{rid}: expected one terminal done span, got "
                f"{[(r['name'], r['rank']) for r in terms]}"
            )
            decomp = tl["decomposition"]
            assert decomp is not None \
                and not tracing.validate_decomposition(decomp), (
                    rid, decomp,
                    tracing.validate_decomposition(decomp or {}),
                )
            assert abs(sum(decomp.values()) - tl["latency_s"]) < 0.05, (
                f"{rid}: decomposition {decomp} does not sum to "
                f"latency {tl['latency_s']}"
            )
            if max(tl["hops"], default=0) >= 1:
                assert tl["hops"] == [0, 1], (rid, tl["hops"])
                rerouted_ids.append(rid)
                tracing.write_trace_report(
                    self.out / f"trace-report-{rid}.json",
                    tracing.trace_report_doc(tl),
                )
        assert len(rerouted_ids) >= 1, (
            "replica kill produced no two-hop trace "
            f"(journal rerouted={counts['rerouted']})"
        )
        journal.close()
        merged = router.merged_counters()
        for k, v in merged.items():
            self.counters[k] = self.counters.get(k, 0) + int(v)
        return {"counters": merged, "rerouted": counts["rerouted"],
                "killed": [r.id for r in dead]}

    # ---- gloo-real episodes --------------------------------------------

    def _serve_argv(self, n: int, extra=()):
        return [
            str(REPO / "apps" / "serve.py"),
            "--synthetic", str(n), "--seed", str(self.seed),
            "--nt-max", "16", "--max-width", "4", "--cpu-devices", "1",
            *extra,
        ]

    def ep_gloo_serve(self):
        """The clean ≥2-rank serving session: a space mesh over gloo
        ranks, every request served, per-request latency telemetry
        banked (the SLO block's primary real-telemetry source)."""
        from rocm_mpi_tpu.parallel.launcher import spawn_ranks

        tdir = self.out / "telemetry-gloo"
        out_dir = self.out / "gloo-serve"
        results = spawn_ranks(
            self._serve_argv(8, extra=["--out", str(out_dir)]),
            nprocs=self.ranks, timeout=300, telemetry_dir=tdir,
        )
        self.stream_dirs.append(tdir)
        for rank, (proc, (out, err)) in enumerate(results):
            assert proc.returncode == 0, (
                rank, out[-500:], err[-2000:]
            )
        manifest = json.loads(
            (out_dir / "serve-manifest.json").read_text()
        )
        for k, v in manifest.get("queue", {}).items():
            if k != "depth":
                self.counters[k] = self.counters.get(k, 0) + int(v)
        assert manifest["queue"]["completed"] == 8, manifest["queue"]
        return {"ranks": self.ranks,
                "programs": len(manifest["programs"])}

    def ep_gloo_kill(self):
        """Infrastructure kill mid-batch on a 2-rank serving session:
        rank 1 exits rc 43 at the serve-batch fault site; the
        launcher's first-failure scan names it and the peer-grace kill
        reaps the wedged survivor."""
        from rocm_mpi_tpu.parallel.launcher import spawn_ranks
        from rocm_mpi_tpu.resilience.faults import RC_INJECTED_KILL

        results = spawn_ranks(
            self._serve_argv(8),
            nprocs=self.ranks, timeout=240, peer_grace_s=5,
            inject_fault="kill@step=2,rank=1,at=serve-batch",
        )
        ff = results.report.first_failure
        assert ff is not None, "launcher saw no failure"
        assert ff[0] == 1 and ff[1] == RC_INJECTED_KILL, ff
        return {"first_failure": list(ff[:2])}

    def ep_gloo_die(self):
        """The vanished rank: rank 1 exits CLEAN (rc 0) mid-batch; only
        vanish detection can tell the death from completion skew."""
        from rocm_mpi_tpu.parallel.launcher import spawn_ranks

        results = spawn_ranks(
            self._serve_argv(8),
            nprocs=self.ranks, timeout=240, peer_grace_s=5,
            vanish_grace_s=4.0,
            inject_fault="die@step=2,rank=1,at=serve-batch",
        )
        report = results.report
        assert report.vanished == 1, (report.vanished, report.events)
        return {"vanished": report.vanished}

    def ep_gloo_stall(self):
        """The wedged rank: rank 1 busy-waits forever BEFORE its batch
        progress bump; its peer bumps past it into the batch collective
        and the progress watchdog names the victim BY PROGRESS."""
        from rocm_mpi_tpu.parallel.launcher import spawn_ranks

        hdir = self.out / "health-stall"
        results = spawn_ranks(
            self._serve_argv(12),
            nprocs=self.ranks, timeout=300, peer_grace_s=5,
            health_dir=hdir, stall_grace_s=5.0,
            inject_fault="stall@step=3,rank=1,at=serve-batch",
        )
        verdicts = results.report.watchdog_verdicts
        assert verdicts and verdicts[0]["rank"] == 1, (
            verdicts, results.report.events
        )
        return {"watchdog_rank": verdicts[0]["rank"]}

    # ---- the schedule ---------------------------------------------------

    def schedule(self, bounded: bool, gloo: bool):
        eps = [
            ("serve-chaos", "in-process",
             "queue-flood=10@step=2;lane-nan@request=3,times=9;"
             "slow-batch=0.05@step=3;batch-error@step=4",
             self.ep_serve_chaos),
            # times=2: the pipelined run and its serial twin each
            # consume one firing of every slow-batch clause.
            ("pipeline", "in-process",
             "slow-batch=0.05@step=2,times=2;"
             "slow-batch=0.05@step=4,times=2",
             self.ep_pipeline),
            # times=3: the swapped-in poison lane burns its full retry
            # budget (attempt + 2 retries), then the clause is spent so
            # the bitwise twin runs clean.
            ("swap", "in-process", "lane-nan@request=3,times=3",
             self.ep_swap),
            # breaker/storage install their own specs (multiple phases).
            ("breaker", "in-process", None, self.ep_breaker),
            ("storage", "in-process", None, self.ep_storage),
            ("evict", "in-process", None, self.ep_evict),
            ("fleet", "in-process", "replica-kill@step=2,rank=1",
             self.ep_fleet),
        ]
        if gloo:
            eps += [
                ("gloo-serve", "gloo", None, self.ep_gloo_serve),
                ("gloo-kill", "gloo",
                 "kill@step=2,rank=1,at=serve-batch", self.ep_gloo_kill),
            ]
            if not bounded:
                eps += [
                    ("gloo-die", "gloo",
                     "die@step=2,rank=1,at=serve-batch",
                     self.ep_gloo_die),
                    ("gloo-stall", "gloo",
                     "stall@step=3,rank=1,at=serve-batch",
                     self.ep_gloo_stall),
                ]
        return eps


def fault_kinds_in(episodes) -> list[str]:
    """The fault kinds this soak actually composed (report evidence)."""
    kinds = set()
    for ep in episodes:
        for clause in (ep.get("faults") or "").split(";"):
            head = clause.split("@")[0].split("=")[0].strip()
            if head:
                kinds.add(head)
    # Episodes that install specs internally (breaker/storage) + the
    # eviction's real SIGTERM:
    names = {ep["name"] for ep in episodes}
    if "breaker" in names:
        kinds.add("batch-error")
    if "storage" in names:
        kinds.update({"io-error", "io-slow", "enospc"})
    if "evict" in names:
        kinds.add("sigterm")
    return sorted(kinds)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="long-horizon chaos soak (docs/RESILIENCE.md §8)"
    )
    p.add_argument("--bounded", action="store_true",
                   help="the chip_watcher edition: one episode per "
                   "fault family, minutes not hours")
    p.add_argument("--out", default="output/soak", metavar="DIR")
    p.add_argument("--ranks", type=positive_int, default=2,
                   help="ranks for the gloo episodes")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--cpu-devices", type=int, default=0, metavar="N",
                   help="simulate N virtual CPU devices for the "
                   "in-process episodes")
    p.add_argument("--no-gloo", action="store_true",
                   help="skip the multi-rank episodes (debug only — "
                   "the acceptance soak is gloo-real)")
    args = p.parse_args(argv)

    out = pathlib.Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    # A fresh soak owns its ledger: stale quarantine lines from a
    # previous run must not inflate this run's poison count.
    q = out / "quarantine.jsonl"
    if q.exists():
        q.unlink()

    import jax

    if args.cpu_devices:
        from rocm_mpi_tpu.utils.backend import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)

    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.serving import slo
    from rocm_mpi_tpu.telemetry import compiles

    tdir = out / "telemetry"
    telemetry.configure(enabled=True, directory=str(tdir))
    compiles.install()

    soak = Soak(out, ranks=args.ranks, seed=args.seed)
    episodes = []
    for name, mode, spec, fn in soak.schedule(
        bounded=args.bounded, gloo=not args.no_gloo
    ):
        episodes.append(_episode(name, mode, spec, fn))

    # SLO block from REAL telemetry: every serve.request.done event's
    # latency across the in-process stream and the gloo rank streams.
    streams = []
    for d in soak.stream_dirs:
        streams += sorted(pathlib.Path(d).glob("telemetry-rank*.jsonl"))
    counters = dict(soak.counters)
    counters.setdefault("retries", 0)
    # accounting_ok certifies ONLY the terminal-accounting invariant
    # (every episode banks through _bank's _assert_accounting, whose
    # violation surfaces in the episode error) — a failed SLO
    # expectation must not read as a phantom ticket leak.
    accounting_ok = not any(
        "accounting invariant" in (ep.get("error") or "")
        for ep in episodes
    )
    doc = slo.soak_report_doc(
        episodes,
        slo.slo_block(counters, streams),
        bounded=args.bounded,
        accounting_ok=accounting_ok,
        fault_kinds=fault_kinds_in(episodes),
    )
    report_path = out / "soak-report.json"
    try:
        slo.write_soak_report(report_path, doc)
    except ValueError as e:
        # A soak whose serving episodes banked no telemetry cannot
        # produce a valid (populated) report — say so and fail, don't
        # crash without a verdict.
        print(f"[soak] report not bankable: {e}", file=sys.stderr,
              flush=True)
        return 1
    ok = all(ep["ok"] for ep in episodes)
    print(
        f"[soak] {'OK' if ok else 'FAILED'}: "
        f"{sum(ep['ok'] for ep in episodes)}/{len(episodes)} episodes, "
        f"slo p50={doc['slo']['latency_s']['p50']} "
        f"p99={doc['slo']['latency_s']['p99']} "
        f"miss_rate={doc['slo']['deadline_miss_rate']} "
        f"quarantined={doc['slo']['quarantined']} — {report_path}",
        flush=True,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
