"""2D heat diffusion — communication/computation overlap variant (C4 analog).

The top rung of the ladder
(/root/reference/scripts/diffusion_2D_perf_hide.jl): boundary-frame strips
computed first, halo exchange overlapped with interior compute. This app
implements the reference's *intended* variant (3) semantics — full-frame
coverage plus halo exchange between boundary and interior completion — which
the reference shipped commented-out as "not ready yet" (hide.jl:94-101).
There are no user-managed queues/priorities/signals: the `ppermute` and the
interior update are dataflow-independent inside one shard_map program, so
XLA's latency-hiding scheduler overlaps them (the HSA-priority-queue analog,
SURVEY.md §2.2 D8). Reference defaults: fact=12 → 12288², nt=100,
b_width=(32,4).

The profiling twin (C5, …_perf_hide_prof.jl) is the --profile flag, not a
file fork: `--profile DIR` wraps the timed loop in jax.profiler.trace
(warmup excluded), viewable in TensorBoard/Perfetto.

  python apps/diffusion_2d_perf_hide.py --cpu-devices 8 --fact 0 --nx 512 --ny 512
  python apps/diffusion_2d_perf_hide.py --profile /tmp/trace
"""

import sys

from _common import make_parser, run_app

if __name__ == "__main__":
    parser = make_parser("hide", nx=12288, ny=12288, nt=100, do_vis=False)
    parser.set_defaults(dtype="f32")
    parser.add_argument(
        "--b-width",
        default="32,4",
        help="boundary frame width bx,by (hide.jl:42; clamped to shard/2)",
    )
    args = parser.parse_args()
    sys.exit(run_app("hide", args))
