"""2D heat diffusion — fused-kernel performance variant (C3 analog).

The memory-bound rung of the ladder
(/root/reference/scripts/diffusion_2D_perf.jl): a single fused Pallas
stencil kernel per step (row-striped through VMEM for large grids),
double-buffered via XLA buffer donation instead of an explicit T/T2 swap,
explicit ppermute halo exchange when sharded, and the T_eff/Gpts printout on
warmup-excluded timing. Reference defaults: fact=12 → 12288² grid, 1000
steps. dtype defaults to f32 (the TPU fast path; Mosaic has no f64 — use
--dtype f64 on CPU meshes for parity runs).

  python apps/diffusion_2d_perf.py                      # 12288², real chip
  python apps/diffusion_2d_perf.py --fact 2 --cpu-devices 4 --dtype f64
"""

import sys

from _common import make_parser, run_app

if __name__ == "__main__":
    parser = make_parser("perf", nx=12288, ny=12288, nt=1000, do_vis=False)
    parser.set_defaults(dtype="f32")
    args = parser.parse_args()
    sys.exit(run_app("perf", args))
