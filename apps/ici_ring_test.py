"""ICI ring-exchange smoke test — run this first on new hardware.

TPU-native analog of the reference's ROCm-aware MPI capability proof
(/root/reference/scripts/rocmaware_test_selectdevice.jl): every device fills
a device-resident buffer with its own rank and passes it around a ring
directly over the interconnect (lax.ppermute -> ICI collective-permute; the
reference passes ROCArray pointers straight into MPI.Sendrecv!). Success =
each device holds its left neighbor's rank, printed per device exactly as
each reference rank prints its received message (…selectdevice.jl:23).

Usage:
  python apps/ici_ring_test.py                 # real devices (TPU)
  python apps/ici_ring_test.py --cpu-devices 8 # 8 virtual CPU devices
"""

from __future__ import annotations

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--cpu-devices",
        type=int,
        default=0,
        metavar="N",
        help="simulate N virtual CPU devices instead of real hardware "
        "(the TPU answer to 'no cluster handy'; reference needed Slurm)",
    )
    parser.add_argument(
        "--width", type=int, default=4, help="elements per device buffer (ref: 4)"
    )
    args = parser.parse_args(argv)

    import jax

    if args.cpu_devices:
        from rocm_mpi_tpu.utils.backend import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)

    import numpy as np

    from rocm_mpi_tpu.parallel import init_global_grid
    from rocm_mpi_tpu.parallel.ring import ring_exchange_demo

    devices = jax.devices()
    n = len(devices)
    print(f"ring over {n} device(s): {[d.device_kind for d in devices]}")

    grid = init_global_grid(n * args.width, lengths=(1.0,), dims=(n,))
    sent, received = ring_exchange_demo(grid.mesh, width=args.width)
    sent = np.asarray(sent).reshape(n, args.width)
    received = np.asarray(received).reshape(n, args.width)

    ok = True
    for i in range(n):
        expect = (i - 1) % n
        good = (received[i] == expect).all()
        ok &= bool(good)
        status = "ok" if good else "MISMATCH"
        print(
            f"device {i}: sent {sent[i].tolist()} "
            f"recv {received[i].tolist()} (expect {float(expect)}) {status}"
        )
    print("ring exchange: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
