"""3D heat diffusion — communication/computation overlap variant.

The 3D weak-scaling target (driver BASELINE.json: 128³ per chip, 6-neighbor
halo, v4-32). The reference suite is 2D-only; this is its natural extension
on the same machinery: the N-D halo exchange (6 face ppermutes with edge/
corner ghosts via the sequential-axis trick), the N-D overlap step (boundary
shell slabs + ghost-free interior, exchange hidden behind interior compute),
and the same fused Pallas stencil (7-point in 3D, plane-striped through VMEM
for blocks over budget).

  python apps/diffusion_3d_perf_hide.py --cpu-devices 8     # 2x2x2 mesh
  python apps/diffusion_3d_perf_hide.py --nx 256 --ny 256 --nz 256
"""

import sys

from _common import make_parser, run_app

if __name__ == "__main__":
    parser = make_parser(
        "hide", nx=128, ny=128, nz=128, nt=100, do_vis=False
    )
    parser.set_defaults(dtype="f32")
    parser.add_argument(
        "--b-width",
        default="8,8,128",
        help="boundary shell width bx,by,bz (clamped to shard/2)",
    )
    args = parser.parse_args()
    sys.exit(run_app("hide", args))
