"""2D/3D shallow-water app — the coupled-multi-field workload driver.

Runs models.swe.ShallowWater on the same launch/report skeleton as the
diffusion and wave apps. No reference analog (the reference ships one
physics model); alongside the wave app this is the worked example of
docs/ADDING_A_MODEL.md at the app layer. Reports the closed-basin mass
drift — the workload's exact invariant — the way the diffusion apps report
the max(T) decay invariant.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

from _common import OUTPUT_DIR, setup_jax  # noqa: E402


def make_parser():
    import argparse

    from _common import nonneg_int, positive_int

    p = argparse.ArgumentParser(
        description="2D/3D linear shallow water — forward-backward C-grid"
    )
    p.add_argument("--nx", type=int, default=252)
    p.add_argument("--ny", type=int, default=252)
    p.add_argument(
        "--nz", type=nonneg_int, default=0,
        help="z grid points (0 or 1 = 2D, matching init_global_grid's "
        "squeeze of trailing size-1 axes)",
    )
    p.add_argument("--nt", type=int, default=1000)
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--dtype", default="f64", choices=["f32", "f64", "bf16"])
    p.add_argument("--dims", default=None, help="process grid, e.g. 2,2")
    p.add_argument("--cpu-devices", type=int, default=0, metavar="N")
    p.add_argument(
        "--variant", default="perf", choices=["ap", "perf", "hide"]
    )
    sched = p.add_mutually_exclusive_group()
    sched.add_argument(
        "--deep", type=positive_int, default=0, metavar="K",
        help="deep-halo sweeps: exchange the width-K ghosts of the whole "
        "coupled state once per K steps instead of width-1 every step",
    )
    sched.add_argument(
        "--vmem", action="store_true",
        help="whole-loop-in-VMEM fast path (single device only)",
    )
    p.add_argument("--vis", action="store_true")
    p.add_argument(
        "--vis-shards", action="store_true",
        help="also render one panel per device shard (the "
        "poc_rocmaware.png-style halo-exchange proof; 2D + --vis only)",
    )
    p.add_argument(
        "--profile", default=None, metavar="DIR",
        help="trace the timed loop with jax.profiler into DIR (the "
        "--profile convention of the diffusion apps, SURVEY.md §5.1)",
    )
    p.add_argument(
        "--save-field", default=None, metavar="PATH.npy",
        help="dump the final gathered surface height as .npy on process 0 "
        "(the machine-readable artifact, SURVEY.md §5.4)",
    )
    from _common import (
        add_checkpoint_flags,
        add_driver_flag,
        add_telemetry_flag,
        add_wire_mode_flag,
    )

    add_wire_mode_flag(p)
    add_driver_flag(p)
    add_telemetry_flag(p)
    add_checkpoint_flags(p)
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    jax = setup_jax(args)

    import jax.numpy as jnp

    from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater
    from rocm_mpi_tpu.parallel import gather_to_host0
    from rocm_mpi_tpu.utils import viz
    from rocm_mpi_tpu.utils.logging import log0

    dims = tuple(int(d) for d in args.dims.split(",")) if args.dims else None
    shape = (args.nx, args.ny) + ((args.nz,) if args.nz > 1 else ())
    cfg = SWEConfig(
        global_shape=shape,
        lengths=(10.0,) * len(shape),
        nt=args.nt,
        warmup=args.warmup,
        dtype=args.dtype,
        dims=dims,
        wire_mode=args.wire_mode,
    )
    model = ShallowWater(cfg)
    grid = model.grid
    log0(
        f"Process {grid.me} grid {grid.global_shape} over mesh {grid.dims} "
        f"({grid.nprocs} device(s): {jax.devices()[0].device_kind} …)"
    )
    h0, _ = model.init_state()
    mass0 = float(jnp.sum(h0, dtype=jnp.float64))
    # One chain decides label AND runner together (the _common.py
    # convention: artifacts must identify the schedule that actually ran).
    if args.checkpoint:
        if args.vmem:
            log0("--checkpoint supports the per-step and deep schedules; "
                 "drop --vmem")
            return 2
        from _common import checkpoint_schedule, make_checkpoint_runner

        from rocm_mpi_tpu.models.swe import SWERunResult

        make_advance, quantum, label = checkpoint_schedule(
            args, model, args.variant,
            lambda: model.advance_fn(args.variant),
        )

        def advance_state():
            advance = make_advance()
            h1, us1 = model.init_state()
            Mus = model.face_masks()
            return (
                lambda s, n: tuple(advance(s[0], s[1], Mus, n)),
                (h1, us1),
            )

        runner = make_checkpoint_runner(
            args, log0, advance_state,
            lambda s, ran, wtime: SWERunResult(
                h=s[0], us=s[1], wtime=wtime, nt=ran, warmup=0, config=cfg
            ),
            quantum=quantum,
        )
    elif args.deep:
        k_eff = model.effective_deep_depth(block_steps=args.deep, warn=False)
        label = f"deep{k_eff}"
        log0(f"--deep: running deep-halo sweeps (k={k_eff}) instead of "
             "the per-step variant")
        runner = lambda: model.run_deep(block_steps=k_eff)
    elif args.vmem:
        if grid.nprocs != 1:
            log0("--vmem requires a single-device grid (the whole-loop-in-"
                 f"VMEM path is unsharded); mesh is {grid.dims}")
            return 2
        label = "vmem"
        log0("--vmem: running the whole-loop-in-VMEM fast path instead of "
             "the per-step variant")
        runner = model.run_vmem_resident
    else:
        label = args.variant
        runner = lambda: model.run(variant=args.variant, driver=args.driver)
    from _common import profile_context

    profile_ctx = profile_context(jax, args)
    log0("Starting the time loop 🚀...", end="")
    with profile_ctx:
        result = runner()
    log0("done")
    from _common import report_checkpointed_line

    report_checkpointed_line(result, args, log0)
    mass = float(jnp.sum(result.h, dtype=jnp.float64))
    log0(
        f"mass drift = {abs(mass - mass0) / abs(mass0):.3e} "
        "(closed basin: conserved up to storage-dtype rounding)"
    )
    if args.vis and len(shape) != 2:
        log0("--vis is 2D-only (heatmap); skipping the artifact")
        args.vis = False
    h_v = (
        gather_to_host0(result.h)
        if (args.vis or args.save_field)
        else None
    )
    if args.save_field and h_v is not None:
        import numpy as np

        out = pathlib.Path(args.save_field)
        out.parent.mkdir(parents=True, exist_ok=True)
        np.save(out, h_v)
        log0(f"wrote {out}")
    if args.vis:
        if h_v is not None:
            path = OUTPUT_DIR / viz.artifact_name(
                f"swe_{label}", grid.nprocs, grid.global_shape
            )
            viz.save_heatmap(
                h_v, path,
                title=f"swe {label} nt={result.nt} mesh={grid.dims}",
            )
            log0(f"wrote {path}")
            if args.vis_shards and grid.ndim == 2:
                # signed: h oscillates around 0 — symmetric limits, or the
                # troughs clip to flat colormap-bottom and hide seams.
                ppath = viz.save_shard_panels_artifact(
                    h_v, grid, f"swe_{label}", OUTPUT_DIR, signed=True
                )
                log0(f"wrote {ppath}")
    else:
        log0(f"maximum(|h|) = {float(jnp.abs(result.h).max())}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
