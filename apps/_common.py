"""Shared CLI driver for the diffusion apps (reference L6/L1 analog).

The reference's five apps share an identical skeleton — init grid, IC, hot
loop, T_eff printout, gather + heatmap (SURVEY.md §3). Here that skeleton is
one driver parameterized by variant; each app file pins its variant and
defaults, exactly as runme.sh selects which .jl to run
(/root/reference/scripts/runme.sh:5-9).
"""

from __future__ import annotations

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

OUTPUT_DIR = pathlib.Path(__file__).resolve().parent.parent / "output"


def positive_int(v):
    """argparse type: int >= 1 (shared by the workload app parsers)."""
    import argparse

    i = int(v)
    if i < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return i


def nonneg_int(v):
    """argparse type: int >= 0 (shared by the workload app parsers)."""
    import argparse

    i = int(v)
    if i < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
    return i


def make_parser(
    variant: str, *, nx: int, ny: int, nt: int, do_vis: bool, nz: int = 0
):
    ndim = "3D" if nz else "2D"
    p = argparse.ArgumentParser(
        description=f"{ndim} heat diffusion — {variant} variant"
    )
    p.add_argument("--nx", type=int, default=nx, help="global grid points, x")
    p.add_argument("--ny", type=int, default=ny, help="global grid points, y")
    p.add_argument(
        "--nz", type=int, default=nz, help="global grid points, z (0 = 2D)"
    )
    p.add_argument(
        "--fact",
        type=int,
        default=0,
        help="if set, every grid axis becomes fact*1024 "
        "(perf.jl:21 'fact' knob; in 3D this includes nz)",
    )
    p.add_argument("--nt", type=int, default=nt, help="time steps")
    p.add_argument("--warmup", type=int, default=10, help="untimed steps")
    p.add_argument(
        "--dtype", default="f64", choices=["f32", "f64", "bf16"],
        help="f64 matches the reference; f32 is the TPU fast path",
    )
    p.add_argument(
        "--dims", default=None,
        help="process grid, e.g. 2,2 (default: auto near-square)",
    )
    p.add_argument(
        "--cpu-devices", type=int, default=0, metavar="N",
        help="simulate N virtual CPU devices instead of real hardware",
    )
    vis = p.add_mutually_exclusive_group()
    vis.add_argument("--vis", dest="do_vis", action="store_true", default=do_vis)
    vis.add_argument("--no-vis", dest="do_vis", action="store_false")
    p.add_argument(
        "--vis-shards", action="store_true",
        help="also render one panel per shard (the poc_rocmaware.png-style "
        "halo-exchange proof; 2D + --vis only)",
    )
    p.add_argument(
        "--transport", default=None, choices=["ici", "host"],
        help="halo transport: device-direct collectives vs host staging "
        "(IGG_ROCMAWARE_MPI=1/0 analog)",
    )
    p.add_argument(
        "--profile", default=None, metavar="DIR",
        help="trace the timed loop with jax.profiler into DIR",
    )
    p.add_argument(
        "--deep", type=int, default=0, metavar="K",
        help="use deep-halo sweeps: exchange width-K ghosts every K steps "
        "instead of width-1 every step (parallel.deep_halo; f32/bf16)",
    )
    add_wire_mode_flag(p)
    add_driver_flag(p)
    p.add_argument(
        "--save-field", default=None, metavar="PATH.npy",
        help="dump the final gathered field as .npy (process 0)",
    )
    add_telemetry_flag(p)
    add_health_flag(p)
    add_checkpoint_flags(p)
    return p


def add_wire_mode_flag(p) -> None:
    """The shared --wire-mode knob (docs/PERF.md "Wire precision"): the
    halo exchange's on-wire slab precision. The stateful int8 modes are
    deep-halo-only (the per-step programs are stateless); telemetry
    stamps the mode on every exchange annotation and run gauge so
    reduced-wire summaries can't be regress-compared to f32 ones."""
    from rocm_mpi_tpu.parallel.wire import WIRE_MODES

    p.add_argument(
        "--wire-mode", default="f32", choices=list(WIRE_MODES),
        help="on-wire halo slab precision (default f32 — bitwise-"
        "identical exchange; bf16 halves the wire; int8/int8_delta "
        "quantize with error feedback and need --deep)",
    )


def add_driver_flag(p) -> None:
    """The shared --driver knob: which multi-step loop form runs the
    per-step variants. "scan" (default) is the donation-aware lax.scan
    driver (models.*.scan_advance_fn — allocation-free steady state);
    "step" the classic per-step fori_loop. Results are bitwise identical;
    telemetry stamps the form so summaries from different drivers can't
    be compared silently."""
    p.add_argument(
        "--driver", default="scan", choices=["step", "scan"],
        help="multi-step loop form for per-step variants (default: scan, "
        "the donation-aware lax.scan driver); --deep and --checkpoint "
        "schedules have their own loop forms and ignore this",
    )


def add_telemetry_flag(p) -> None:
    """The shared --telemetry block (docs/TELEMETRY.md): every workload
    app and the weak-scaling harness expose the same knob."""
    p.add_argument(
        "--telemetry", default=None, metavar="DIR",
        help="collect structured telemetry (spans/counters/events) into "
        "DIR as telemetry-rank{k}.jsonl; merge and inspect with "
        "`python -m rocm_mpi_tpu.telemetry summarize DIR` "
        "(RMT_TELEMETRY_DIR is the env spelling the launcher forwards)",
    )


def setup_telemetry(args, jax) -> None:
    """Enable telemetry when --telemetry DIR was given (env-configured
    collection — the launcher's RMT_TELEMETRY_DIR — needs no call here;
    events reads the env at import). Called after distributed init so
    the rank stamp is the real process index. A telemetry-enabled run
    also installs the compile tracker (telemetry/compiles.py): compile
    spans and the recompile accounting ride the same stream."""
    from rocm_mpi_tpu import telemetry

    if getattr(args, "telemetry", None):
        telemetry.configure(
            directory=args.telemetry, enabled=True,
            rank=jax.process_index(),
        )
    if telemetry.enabled():
        from rocm_mpi_tpu.telemetry import compiles

        compiles.install()


def add_health_flag(p) -> None:
    """The shared --health knob (docs/TELEMETRY.md "Health plane")."""
    p.add_argument(
        "--health", action="store_true",
        help="run the per-rank flight recorder: progress counters + a "
        "heartbeat-rank{k}.json sidecar (atomic, watchdog/monitor-"
        "readable even while this rank is blocked in a collective) and "
        "a SIGUSR2 faulthandler post-mortem hook; needs a telemetry "
        "directory (--telemetry DIR or the launcher env) for the "
        "sidecars (RMT_HEALTH=1 is the env spelling spawn_ranks "
        "forwards)",
    )


def setup_health(args, jax) -> None:
    """Arm the flight recorder when --health was given or the launcher
    contract says so (RMT_HEALTH, forwarded by spawn_ranks health_dir).
    Called after distributed init + setup_telemetry: the sidecar rank
    stamp must be the real process index, and the default sidecar home
    is the telemetry sink."""
    from rocm_mpi_tpu.telemetry import flight

    try:
        if getattr(args, "health", False):
            flight.enable(rank=jax.process_index())
        elif not flight.enable_from_env():
            return
    except ValueError as e:
        # Both spellings (--health flag, RMT_HEALTH env) fail the same
        # clean way when no sidecar directory is configured.
        raise SystemExit(f"--health / RMT_HEALTH: {e}") from None
    flight.install_postmortem_handler()
    # flight.enable may have just armed telemetry collection (health
    # implies it) AFTER setup_telemetry's install gate ran — re-check,
    # or a health-only run would mark/emit compile gauges with no
    # tracker listening and bank fabricated zeros.
    from rocm_mpi_tpu import telemetry

    if telemetry.enabled():
        from rocm_mpi_tpu.telemetry import compiles

        compiles.install()


def add_checkpoint_flags(p) -> None:
    """The shared --checkpoint/--ckpt-every/--resume/--retries/
    --inject-fault block (SURVEY.md §5.4 upgraded + the resilience layer
    — utils/checkpoint.py and rocm_mpi_tpu/resilience/ have the design)."""
    p.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="periodically checkpoint the run state into DIR (orbax, "
        "sharded save); the run becomes durable against preemption",
    )
    p.add_argument(
        "--ckpt-every", type=positive_int, default=None, metavar="N",
        help="checkpoint interval in steps (default: nt/4)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="with --checkpoint: continue from the latest VALID saved "
        "step in DIR (corrupt/truncated checkpoints are skipped) instead "
        "of the initial condition",
    )
    p.add_argument(
        "--retries", type=nonneg_int, default=0, metavar="N",
        help="with --checkpoint: supervise the run — on a crash/backend "
        "error, restore the latest valid checkpoint and retry with "
        "exponential backoff, up to N restarts (resilience.run_supervised)",
    )
    p.add_argument(
        "--inject-fault", default=None, metavar="SPEC",
        help="deterministic fault injection for drills/tests, e.g. "
        "'crash@step=12' or 'truncate-latest@segment=2' "
        "(rocm_mpi_tpu/resilience/faults.py has the grammar)",
    )


def checkpointed_run(args, advance, init_state, log0, quantum: int = 1):
    """--checkpoint mode: segmented advance with orbax saves between
    segments; --resume restores the latest step first. `advance(state, n)
    -> state` is the framework's standard traced-step-count contract, so
    all segments share one compiled program. Returns
    (final_state, steps_run_here, wtime) — wtime spans the segmented loop
    INCLUDING save time (this is the durability mode, not the benchmark
    protocol; the reported rate says so).

    `quantum` is the schedule's step granularity (the deep schedule
    advances k steps per sweep): the save interval is rounded UP to a
    multiple of it, so segment lengths never truncate a sweep."""
    from rocm_mpi_tpu.utils import checkpoint as ckpt
    from rocm_mpi_tpu.utils.metrics import Timer

    every = args.ckpt_every or max(args.nt // 4, 1)
    if every % quantum:
        rounded = ((every // quantum) + 1) * quantum
        log0(f"--ckpt-every {every} rounded to {rounded} (the schedule "
             f"advances {quantum} steps at a time)")
        every = rounded
    supervised = getattr(args, "retries", 0) > 0
    start = 0
    state = init_state
    if args.resume:
        # The latest VALID step (integrity manifest checked): a corrupt
        # or truncated checkpoint falls back to the previous kept step
        # instead of being restored — or worse, trusted.
        latest = ckpt.latest_valid_step(args.checkpoint, log=log0)
        # `is not None`, not truthiness: the contract is int | None, and
        # a (hypothetical) step-0 checkpoint must restore, not silently
        # fall through to the initial condition.
        if latest is not None:
            start = latest
            if not supervised:
                log0(f"--resume: restoring step {latest} from "
                     f"{args.checkpoint}")
                state = ckpt.restore_state(args.checkpoint, latest,
                                           init_state)
        else:
            log0(f"--resume: no checkpoint under {args.checkpoint}; "
                 "starting from the initial condition")
    # A checkpoint written by a different schedule/nt can land on a step
    # the current schedule cannot reach exactly (the deep advance moves k
    # steps per sweep and its trip count floors — a misaligned window
    # would silently drop up to k-1 trailing steps). Refuse loudly.
    if start % quantum or (args.nt - start) % quantum:
        log0(
            f"--resume: checkpoint step {start} / window {args.nt - start} "
            f"is not a multiple of the schedule's step quantum {quantum} "
            "(was this checkpoint written by a different schedule or nt?); "
            "resume with the schedule that wrote it or adjust --nt"
        )
        raise SystemExit(2)
    if start >= args.nt and not supervised:
        log0(f"--resume: checkpoint already at step {start} >= nt={args.nt};"
             " nothing to run")
        return state, 0, 0.0
    # Labeled Timer: the interval lands in the telemetry stream as a
    # "run.checkpointed" span (durability window: advance + saves — the
    # per-save attribution comes from checkpoint.py's own spans). The
    # bare perf_counter() this replaces is now lint-gated (GL06).
    with Timer(label="run.checkpointed", steps=args.nt - start) as timer:
        if supervised:
            # Crash supervision (resilience.run_supervised): restore, the
            # nothing-to-run case, and retry restarts are all owned by the
            # supervisor — the app only pre-resolved `start` for the
            # quantum guard above and the steps-run accounting below.
            from rocm_mpi_tpu.resilience import run_supervised

            log0(f"supervised run: up to {args.retries} restart(s), "
                 f"resume={'on' if args.resume else 'off'}")
            state = run_supervised(
                advance, init_state, args.nt, args.checkpoint, every,
                max_retries=args.retries, resume=args.resume, log=log0,
            )
        else:
            state = ckpt.run_segmented(
                advance, state, args.nt, args.checkpoint, every,
                start_step=start,
            )
    wtime = timer.elapsed
    ran = max(args.nt - start, 0)
    if ran:
        log0(f"checkpointed {start}→{args.nt} every {every} steps into "
             f"{args.checkpoint}")
    return state, ran, wtime


def checkpoint_schedule(args, model, per_step_label, make_per_step):
    """The one chooser for checkpoint mode's schedule: returns
    (make_advance, quantum, label). With --deep it builds the model's
    deep advance ONCE and uses the k that deep_advance_fn itself returns
    (single source — label, quantum, and executed depth cannot diverge);
    otherwise the per-step variant with quantum 1."""
    if getattr(args, "deep", 0):
        advance, k = model.deep_advance_fn(
            block_steps=args.deep, nt=args.nt, warmup=0
        )
        return (lambda: advance), k, f"ckpt_deep{k}"
    return make_per_step, 1, f"ckpt_{per_step_label}"


def make_checkpoint_runner(args, log0, advance_state, make_result,
                           quantum: int = 1):
    """The one checkpoint-mode runner shared by the workload apps:
    `advance_state() -> (adv, init_state)` builds the model's segmented
    advance (the standard `adv(state, n) -> state` contract) and
    `make_result(state, ran, wtime)` wraps the outcome in the workload's
    RunResult type with `nt=ran, warmup=0` — nt of 0 signals the
    nothing-to-run case (resume already complete), which
    report_checkpointed_line then reports WITHOUT touching the rate
    properties (t_eff would divide by the zero wall time)."""

    def runner():
        adv, init_state = advance_state()
        state, ran, wtime = checkpointed_run(
            args, adv, init_state, log0, quantum=quantum
        )
        return make_result(state, ran, wtime)

    return runner


def report_checkpointed_line(result, args, log0) -> None:
    """The checkpoint-aware 'Executed …' report: rates only when steps
    actually ran (a fully-resumed run has nt=0 and zero wall time)."""
    if getattr(args, "checkpoint", None) and result.nt == 0:
        log0("0 steps run (checkpoint already complete); state restored")
        return
    log0(
        f"Executed {result.nt} steps in = {result.wtime:.3e} sec "
        f"(@ T_eff = {result.t_eff:.2f} GB/s aggregate, "
        f"{result.gpts:.4f} Gpts/s)"
    )
    if getattr(args, "checkpoint", None):
        log0("(durability mode: wall time includes checkpoint saves — "
             "not the benchmark protocol)")


def setup_jax(args):
    import jax

    from rocm_mpi_tpu.parallel.distributed import maybe_initialize_distributed

    if getattr(args, "inject_fault", None):
        # Before distributed init: the "init" fault point (delay-rank
        # drills) fires inside maybe_initialize_distributed.
        from rocm_mpi_tpu.resilience import faults

        faults.install(args.inject_fault)
    maybe_initialize_distributed()
    if args.cpu_devices:
        from rocm_mpi_tpu.utils.backend import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)
    if args.dtype == "f64":
        jax.config.update("jax_enable_x64", True)
    # Persistent compile cache: on the flapping chip tunnel an app re-run
    # skips the Mosaic compiles a killed run already paid; on CPU it is a
    # no-op unless the test harness opts in (RMT_CPU_CACHE=1 — see
    # utils.backend), where it stops the suite's subprocess app tests
    # re-paying identical XLA:CPU compiles every run.
    from rocm_mpi_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache()
    setup_telemetry(args, jax)
    setup_health(args, jax)
    # Preemption awareness (resilience.preempt, docs/RESILIENCE.md §7):
    # arm the SIGTERM grace-deadline handler when the launcher contract
    # says so (RMT_PREEMPT_GRACE_S, forwarded by spawn_ranks
    # preempt_grace_s) — cheap no-op otherwise. Installation lives in
    # resilience/ (a GL07 signal-hygiene owner); this is only the call.
    from rocm_mpi_tpu.resilience import preempt

    preempt.install_from_env()
    return jax


def profile_context(jax, args):
    """The one --profile idiom (SURVEY.md §5.1): a jax.profiler trace over
    the timed loop when --profile DIR was given, a no-op otherwise. Shared
    by run_app and the wave app so the profiling convention cannot
    diverge between workloads."""
    import contextlib

    if getattr(args, "profile", None):
        return jax.profiler.trace(args.profile)
    return contextlib.nullcontext()


def build_config(args):
    from rocm_mpi_tpu.config import DiffusionConfig, with_fact

    dims = None
    if args.dims:
        dims = tuple(int(d) for d in args.dims.split(","))
    kwargs = {}
    if args.transport:
        kwargs["halo_transport"] = args.transport
    if getattr(args, "wire_mode", None):
        kwargs["wire_mode"] = args.wire_mode
    if getattr(args, "b_width", None):
        kwargs["b_width"] = tuple(int(b) for b in args.b_width.split(","))
    shape = (args.nx, args.ny)
    if getattr(args, "nz", 0):
        shape += (args.nz,)
    cfg = DiffusionConfig(
        global_shape=shape,
        lengths=(10.0,) * len(shape),
        nt=args.nt,
        warmup=args.warmup,
        dtype=args.dtype,
        dims=dims,
        do_vis=args.do_vis,
        **kwargs,
    )
    if args.fact:
        cfg = with_fact(cfg, args.fact)
    return cfg


def emit_run_gauges(result, variant: str, driver: str | None = None,
                    wire: str | None = None) -> None:
    """Bank the run's headline rates into the telemetry stream (no-op
    when collection is off; rate properties divide by the timed window,
    so a fully-resumed nt=0 run emits nothing). `driver` stamps the loop
    form (step/scan) and `wire` the on-wire halo precision on the
    gauges, so summaries from different drivers or wire modes can't be
    compared silently (aggregate folds non-f32 wire into the gauge key,
    like the driver suffix)."""
    from rocm_mpi_tpu import telemetry

    if not telemetry.enabled() or not result.nt or not result.wtime:
        return
    attrs = {"variant": variant}
    if driver is not None:
        attrs["driver"] = driver
    if wire is not None:
        attrs["wire"] = wire
    telemetry.gauge("run.gpts", result.gpts, **attrs)
    telemetry.gauge("run.t_eff_gbs", result.t_eff, **attrs)


def run_app(variant: str, args) -> int:
    """The shared skeleton: init → run → report → (gather + heatmap)."""
    jax = setup_jax(args)
    import numpy as np

    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.parallel import gather_to_host0
    from rocm_mpi_tpu.utils import viz
    from rocm_mpi_tpu.utils.logging import log0

    cfg = build_config(args)
    model = HeatDiffusion(cfg)
    grid = model.grid
    log0(
        f"Process {grid.me} grid {grid.global_shape} over mesh {grid.dims} "
        f"({grid.nprocs} device(s): {jax.devices()[0].device_kind} …)"
    )

    profile_ctx = profile_context(jax, args)
    ckpt_mode = bool(getattr(args, "checkpoint", None))
    if getattr(args, "deep", 0):
        # The deep-halo schedule replaces the variant's own step entirely
        # (variant-specific knobs like --b-width are unused); label the
        # run and its artifacts with the depth that will actually execute
        # — the model's own accounting, so label and executed k cannot
        # drift (run_deep degrades k when the step counts aren't
        # divisible). Checkpoint mode has no warmup window, so its k is
        # gcd'd against nt alone — computed here so label and executed
        # depth agree in that mode too.
        k_eff = model.effective_deep_depth(
            warmup=0 if ckpt_mode else None,
            block_steps=args.deep, warn=False,
        )
        variant = f"deep{k_eff}"
        log0(f"--deep: running deep-halo sweeps (k={k_eff}"
             + (f", degraded from {args.deep}" if k_eff != args.deep else "")
             + ") instead of the per-step variant")
    if ckpt_mode:
        from rocm_mpi_tpu.models.diffusion import RunResult

        per_step = variant  # bind before the label rebinding below
        make_advance, quantum, variant = checkpoint_schedule(
            args, model, per_step, lambda: model.advance_fn(per_step)
        )

        def advance_state():
            advance = make_advance()
            return (
                lambda s, n: (advance(s[0], s[1], n), s[1]),
                model.init_state(),
            )

        runner = make_checkpoint_runner(
            args, log0, advance_state,
            lambda s, ran, wtime: RunResult(
                T=s[0], wtime=wtime, nt=ran, warmup=0, config=cfg
            ),
            quantum=quantum,
        )
        with profile_ctx:
            result = runner()
        report_checkpointed_line(result, args, log0)
        emit_run_gauges(result, variant,
                        wire=getattr(args, "wire_mode", None))
    else:
        log0("Starting the time loop 🚀...", end="")
        driver = getattr(args, "driver", "step")
        with profile_ctx:
            if getattr(args, "deep", 0):
                # The deep schedule is its own loop form (k-step sweeps);
                # --driver selects among the per-step loop forms only.
                # Stamp "deep" — the same spelling weak_scaling uses — so
                # the two harnesses' gauges land under one key.
                result = model.run_deep(
                    block_steps=args.deep,
                    wire_mode=getattr(args, "wire_mode", None),
                )
                driver = "deep"
            else:
                result = model.run(variant=variant, driver=driver)
        log0("done")

        per_chip = result.t_eff / grid.nprocs
        log0(
            f"Executed {result.nt} steps in = {result.wtime:.3e} sec "
            f"(@ T_eff = {result.t_eff:.2f} GB/s aggregate, "
            f"{per_chip:.2f} GB/s/chip, {result.gpts:.4f} Gpts/s)"
        )
        emit_run_gauges(result, variant, driver=driver,
                        wire=getattr(args, "wire_mode", None))

    T_v = (
        gather_to_host0(result.T)
        if (cfg.do_vis or getattr(args, "save_field", None))
        else None
    )
    if cfg.do_vis:
        if T_v is not None:
            log0(f"maximum(T_v) = {T_v.max()}")  # decay invariant (hide.jl:115)
            path = OUTPUT_DIR / viz.artifact_name(
                variant, grid.nprocs, grid.global_shape
            )
            viz.save_heatmap(
                T_v, path, title=f"{variant} nt={result.nt} mesh={grid.dims}"
            )
            log0(f"wrote {path}")
            if getattr(args, "vis_shards", False) and grid.ndim == 2:
                ppath = viz.save_shard_panels_artifact(
                    T_v, grid, variant, OUTPUT_DIR
                )
                log0(f"wrote {ppath}")
    else:
        # Cheap scalar invariant even without vis: peak must decay.
        log0(f"maximum(T) = {float(result.T.max())}")

    if getattr(args, "save_field", None) and T_v is not None:
        # The persistence artifact (SURVEY.md §5.4: the reference's only
        # persisted outputs are the PNG and prof.txt; the .npy dump is the
        # machine-readable equivalent).
        out = pathlib.Path(args.save_field)
        out.parent.mkdir(parents=True, exist_ok=True)
        np.save(out, T_v)
        log0(f"wrote {out}")
    return 0
