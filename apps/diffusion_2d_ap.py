"""2D heat diffusion — array-programming variant (C1 analog).

The baseline level of the performance ladder: the step is plain jnp
array ops in staggered flux form on the *global* sharded field; XLA/GSPMD
auto-partitions over the device mesh and inserts the halo communication that
the reference performs explicitly with `update_halo!`
(/root/reference/scripts/diffusion_2D_ap.jl). Defaults match the reference:
128² grid (global here; per-rank there), 1000 steps, Float64, heatmap
artifact written to output/.

  python apps/diffusion_2d_ap.py --cpu-devices 4      # 2x2 virtual mesh
  python apps/diffusion_2d_ap.py --nx 252 --ny 252    # single real chip
"""

import sys

from _common import make_parser, run_app

if __name__ == "__main__":
    args = make_parser("ap", nx=128, ny=128, nt=1000, do_vis=True).parse_args()
    sys.exit(run_app("ap", args))
