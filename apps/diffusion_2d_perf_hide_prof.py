"""2D heat diffusion — profiling variant of the overlap app (C5 analog).

The reference forks its overlap app into a separate profiling file
(/root/reference/scripts/diffusion_2D_perf_hide_prof.jl): the time loop is
extracted into a named `compute_step` so the statistical profiler can
attribute samples, a 12-step warmup runs first, `Profile.clear()` resets,
a 300-step profiled run follows, and a text report lands in ./prof.txt
(maxdepth=30, wide displaysize — prof.jl:110-121). GC is disabled around
the measurement so collector pauses don't pollute the profile.

TPU-native re-design: the profiler is `jax.profiler.trace` (XLA op-level
timeline, viewable in TensorBoard/Perfetto — SURVEY.md §5.1), warmup runs
*outside* the trace window (the Profile.clear() analog), and the text
report is written from the compiled program's own metadata: XLA cost
analysis (FLOPs, bytes accessed) plus wall-time phases. There is no GC to
disable — nothing allocates inside the jitted loop.

Reference defaults: 8192² grid, nt=300, 12-step warmup, b_width=(32,8)
(prof.jl:71-77).

  python apps/diffusion_2d_perf_hide_prof.py                 # real chip
  python apps/diffusion_2d_perf_hide_prof.py --cpu-devices 8 --nx 512 --ny 512
"""

import pathlib
import sys

from _common import build_config, make_parser, setup_jax


def main() -> int:
    parser = make_parser("hide", nx=8192, ny=8192, nt=300, do_vis=False)
    parser.set_defaults(dtype="f32", warmup=12, profile="prof_trace")
    parser.add_argument(
        "--b-width",
        default="32,8",
        help="boundary frame width bx,by (prof.jl:77; clamped to shard/2)",
    )
    parser.add_argument(
        "--report",
        default="prof.txt",
        help="text report path (the reference's ./prof.txt analog)",
    )
    args = parser.parse_args()
    if args.checkpoint or args.resume:
        # The profiling driver times a trace window, not a durable run;
        # silently accepting the flags would let a user believe a
        # multi-hour profiled run was checkpointed when it was not.
        print("--checkpoint/--resume are not supported by the profiling "
              "app; use the perf/hide apps for durable runs")
        return 2
    if not 0 <= args.warmup < args.nt:
        parser.error(
            f"need 0 <= warmup < nt, got warmup={args.warmup} nt={args.nt} "
            "(the default warmup is 12 — raise --nt or lower --warmup)"
        )

    jax = setup_jax(args)
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.utils import metrics
    from rocm_mpi_tpu.utils.logging import log0

    cfg = build_config(args)
    if cfg.halo_transport == "host":
        from rocm_mpi_tpu.models.diffusion import warn_host_transport_ignored

        warn_host_transport_ignored("hide", stacklevel=2)
    model = HeatDiffusion(cfg)
    T, Cp = model.init_state()
    advance = model.advance_fn("hide")

    # AOT-compile ONCE, outside every measured window, and drive both the
    # warmup and the timed run through the same executable (the step count
    # is a traced argument, so one compilation serves both). The compiled
    # handle also feeds the report (the named-frame analog: one compiled
    # program IS the profile's attribution unit on TPU).
    compiled = advance.lower(T, Cp, cfg.nt - cfg.warmup).compile()
    timer = metrics.Timer()

    # Warmup (12 steps) before the trace starts = Profile.clear() analog.
    T = compiled(T, Cp, cfg.warmup)
    jax.block_until_ready(T)

    with jax.profiler.trace(args.profile):
        timer.tic(T)
        T = compiled(T, Cp, cfg.nt - cfg.warmup)
        wtime = timer.toc(T)

    wtime_it = metrics.wtime_per_it(wtime, cfg.nt, cfg.warmup)
    t_eff = metrics.t_eff_gbs(T.shape, T.dtype.itemsize, wtime_it)
    gpts = metrics.gpts_per_s(T.shape, wtime_it)
    log0(
        f"Executed {cfg.nt} steps in = {wtime:.3e} sec "
        f"(@ T_eff = {t_eff:.2f} GB/s, {gpts:.4f} Gpts/s)"
    )

    # prof.txt analog: phase walltimes + the compiled program's XLA cost
    # analysis, written by process 0 only.
    if jax.process_index() == 0:
        lines = [
            f"profile report — diffusion_2D_perf_hide_prof "
            f"(grid {cfg.global_shape}, nt={cfg.nt}, warmup={cfg.warmup}, "
            f"b_width={cfg.b_width}, dtype={cfg.dtype}, "
            f"mesh {model.grid.dims}, {model.grid.nprocs} device(s))",
            "",
            f"timed walltime        : {wtime:.6e} s "
            f"({cfg.nt - cfg.warmup} steps)",
            f"per-step walltime     : {wtime_it:.6e} s",
            f"T_eff                 : {t_eff:.3f} GB/s",
            f"throughput            : {gpts:.4f} Gpts/s",
            f"trace (TensorBoard)   : {args.profile}",
            "",
            "XLA cost analysis of the timed program (per invocation):",
        ]
        from rocm_mpi_tpu.utils.compat import cost_analysis_dict

        cost = cost_analysis_dict(compiled)
        for key in sorted(cost):
            val = cost[key]
            if isinstance(val, (int, float)) and val:
                lines.append(f"  {key:30s} {val:.6g}")
        mem = compiled.memory_analysis()
        if mem is not None:
            for attr in (
                "temp_size_in_bytes",
                "argument_size_in_bytes",
                "output_size_in_bytes",
                "generated_code_size_in_bytes",
            ):
                v = getattr(mem, attr, None)
                if v is not None:
                    lines.append(f"  {attr:30s} {v}")
        report = pathlib.Path(args.report)
        report.write_text("\n".join(lines) + "\n")
        log0(f"wrote {report} and trace dir {args.profile}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
