#!/usr/bin/env bash
# One-time bootstrap — the analog of the reference's startup.sh
# (/root/reference/startup.sh: run once under `srun -n 1` to Pkg-add the
# pinned Julia dependencies, bind the system MPI, and Pkg.build). In this
# framework the Python dependencies (jax/flax/optax/numpy/pytest) ship with
# the image, so bootstrap means: verify the stack is importable and sane,
# build the native C++ host-staging engine, and run the capability smoke
# test (the ROCm-aware ring-exchange PoC was the reference's first runnable
# proof too — README.md:5-7).
#
# Usage:  ./startup.sh            # verify + build native + ring smoke test
#         ./startup.sh --no-test  # skip the smoke test (e.g. no devices yet)
set -euo pipefail
cd "$(dirname "$0")"

echo "== dependency check =="
python - <<'EOF'
import importlib
for mod in ("jax", "jax.experimental.pallas", "numpy"):
    importlib.import_module(mod)
    print(f"  {mod}: ok")
import jax
print(f"  jax {jax.__version__}")
EOF
# Identifying the default backend initializes it, which hangs indefinitely
# when the chip tunnel is stalled (observed) — probe in a bounded
# subprocess so bootstrap always completes; the CPU paths (tests, apps
# with --cpu-devices, the smoke test below) need no accelerator.
if ! timeout -k 5 45 python -c \
    "import jax; print('  default backend:', jax.default_backend())"; then
  echo "  default backend: unreachable within 45s (chip tunnel down?);" \
       "CPU paths remain usable"
fi

echo "== native host-staging engine =="
bash scripts/build_native.sh

# Prime the persistent XLA compilation cache (.jax_cache/) with the bench
# programs so a driver `bench.py` run skips the multi-ten-second Mosaic
# compiles (VERDICT r3 #1: one cold compile burned the whole bench budget).
# Bounded + non-fatal: a stalled chip tunnel must not wedge bootstrap.
echo "== bench compilation cache =="
# 5 programs now (floor + the flagship kernel-form ladder) at ~20-40 s
# cold compile each; the budget covers a cold cache end to end.
rc=0; timeout -k 5 360 python bench.py --prime-cache || rc=$?
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
  echo "  cache priming timed out after 360s (chip tunnel down or slow);" \
       "bench.py still works — its floor measurement self-primes the cache"
elif [ "$rc" -ne 0 ]; then
  echo "  cache priming CRASHED (rc=$rc) — investigate above before" \
       "benching; bench.py itself still shields failures"
fi

if [ "${1:-}" != "--no-test" ]; then
  echo "== capability smoke test (ring exchange on 8 virtual devices) =="
  python apps/ici_ring_test.py --cpu-devices 8
fi

echo "bootstrap complete"
