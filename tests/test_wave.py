"""Acoustic-wave workload (models.wave): numpy oracle, exact time
reversal, cross-variant and sharding equivalence — the same correctness
strategy as the diffusion flagship, applied to the second workload to pin
down that the framework layers (mesh/halo/kernels/metrics) are
workload-agnostic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.models.wave import (
    AcousticWave,
    WaveConfig,
    wave_step_fused,
)


def _cfg(shape=(24, 20), dims=(1, 1), dtype="f64", nt=40, warmup=8):
    return WaveConfig(
        global_shape=shape,
        lengths=tuple(10.0 for _ in shape),
        nt=nt,
        warmup=warmup,
        dtype=dtype,
        dims=dims,
    )


def _numpy_leapfrog(U, Uprev, C2, dt, spacing, n):
    """Transparent numpy oracle of the leapfrog update."""
    U, Uprev = np.array(U, np.float64), np.array(Uprev, np.float64)
    C2 = np.array(C2, np.float64)
    ndim = U.ndim
    core = tuple(slice(1, -1) for _ in range(ndim))
    for _ in range(n):
        lap = np.zeros_like(U[core])
        for ax in range(ndim):
            hi = tuple(
                slice(2, None) if a == ax else slice(1, -1)
                for a in range(ndim)
            )
            lo = tuple(
                slice(None, -2) if a == ax else slice(1, -1)
                for a in range(ndim)
            )
            lap += (U[hi] - 2.0 * U[core] + U[lo]) / (
                spacing[ax] * spacing[ax]
            )
        new = U.copy()
        new[core] = (
            2.0 * U[core] - Uprev[core] + dt * dt * C2[core] * lap
        )
        U, Uprev = new, U
    return U


def test_wave_matches_numpy_oracle():
    cfg = _cfg()
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U, Uprev, C2 = model.init_state()
    ref = _numpy_leapfrog(U, Uprev, C2, cfg.dt, cfg.spacing, 25)
    got, _ = model.advance_fn("ap")(U, Uprev, C2, 25)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-12)


def test_wave_boundary_cells_held():
    cfg = _cfg()
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U0, Uprev, C2 = model.init_state()
    edge0 = np.asarray(U0)[0].copy()
    got, _ = model.advance_fn("ap")(jnp.copy(U0), Uprev, C2, 30)
    np.testing.assert_array_equal(np.asarray(got)[0], edge0)


def test_wave_time_reversal_exact():
    # Leapfrog is time-symmetric: running the pair backward returns the
    # initial state at rounding level — an exactness check the dissipative
    # diffusion model has no analog of.
    cfg = _cfg(nt=60)
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U0, Uprev0, C2 = model.init_state()
    U0_np = np.asarray(U0).copy()
    adv = model.advance_fn("ap")
    n = 60
    U, Uprev = adv(jnp.copy(U0), jnp.copy(Uprev0), C2, n)
    # Swap the pair to flip time's arrow, take n-1 reversed steps: the
    # trailing state of the reversed trajectory is u_0 again.
    Ub, _ = adv(Uprev, U, C2, n - 1)
    np.testing.assert_allclose(np.asarray(Ub), U0_np, rtol=0, atol=1e-10)


@pytest.mark.parametrize("dtype", ["f64", "f32"])
def test_wave_perf_matches_ap(dtype):
    cfg = _cfg(dtype=dtype)
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U, Uprev, C2 = model.init_state()
    a, _ = model.advance_fn("ap")(jnp.copy(U), jnp.copy(Uprev), C2, 20)
    p, _ = model.advance_fn("perf")(jnp.copy(U), jnp.copy(Uprev), C2, 20)
    rtol = 1e-12 if dtype == "f64" else 2e-5
    np.testing.assert_allclose(np.asarray(p), np.asarray(a), rtol=rtol,
                               atol=1e-7 if dtype == "f32" else 0)


def test_wave_sharded_matches_single_device():
    # The halo-correctness oracle, wave edition: 2x2 mesh vs 1 device.
    single = AcousticWave(_cfg(), devices=jax.devices()[:1])
    U, Uprev, C2 = single.init_state()
    ref, _ = single.advance_fn("perf")(U, Uprev, C2, 24)

    sharded = AcousticWave(_cfg(dims=(2, 2)))
    Us, Uprevs, C2s = sharded.init_state()
    got, _ = sharded.advance_fn("perf")(Us, Uprevs, C2s, 24)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-12
    )


@pytest.mark.parametrize("dtype", ["f64", "f32"])
def test_wave_hide_matches_ap_sharded(dtype):
    # The overlap rung, wave edition (VERDICT r3 #5): boundary-slab /
    # interior decomposition with only U exchanged must reproduce the ap
    # (GSPMD) trajectory on a real 2x2 mesh. b_width (32,4) clamps to the
    # small shards, exercising partial-interior strip assembly.
    cfg = _cfg(dims=(2, 2), dtype=dtype)
    model = AcousticWave(cfg)
    U, Uprev, C2 = model.init_state()
    a, a_prev = model.advance_fn("ap")(jnp.copy(U), jnp.copy(Uprev), C2, 20)
    h, h_prev = model.advance_fn("hide")(
        jnp.copy(U), jnp.copy(Uprev), C2, 20
    )
    rtol = 1e-12 if dtype == "f64" else 2e-5
    atol = 0 if dtype == "f64" else 1e-7
    np.testing.assert_allclose(np.asarray(h), np.asarray(a), rtol=rtol,
                               atol=atol)
    np.testing.assert_allclose(
        np.asarray(h_prev), np.asarray(a_prev), rtol=rtol, atol=atol
    )


def test_wave_hide_3d_matches_perf():
    # N-D claim of the overlap decomposition, wave edition: 3D shell.
    cfg = _cfg(shape=(12, 10, 8), dims=(2, 2, 1), nt=16, warmup=4)
    model = AcousticWave(cfg)
    U, Uprev, C2 = model.init_state()
    p, _ = model.advance_fn("perf")(jnp.copy(U), jnp.copy(Uprev), C2, 10)
    h, _ = model.advance_fn("hide")(jnp.copy(U), jnp.copy(Uprev), C2, 10)
    np.testing.assert_allclose(np.asarray(h), np.asarray(p), rtol=1e-12)


def test_wave_hide_single_device_routes_to_perf():
    # No neighbors → nothing to hide; the single-device hide must be the
    # perf program (the diffusion model's policy, bit-identical result).
    cfg = _cfg()
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U, Uprev, C2 = model.init_state()
    p, _ = model.advance_fn("perf")(jnp.copy(U), jnp.copy(Uprev), C2, 12)
    h, _ = model.advance_fn("hide")(jnp.copy(U), jnp.copy(Uprev), C2, 12)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(p))


def test_wave_3d_runs_and_matches_oracle():
    cfg = _cfg(shape=(12, 10, 8), dims=(2, 1, 1), nt=16, warmup=4)
    model = AcousticWave(cfg)
    U, Uprev, C2 = model.init_state()
    ref = _numpy_leapfrog(U, Uprev, C2, cfg.dt, cfg.spacing, 10)
    got, _ = model.advance_fn("perf")(U, Uprev, C2, 10)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-12)


def test_wave_vmem_multi_step_matches_ap():
    # The whole-loop-in-VMEM leapfrog (ops.wave_kernels.wave_multi_step)
    # against the per-step ap path: same trajectory, chunked schedule.
    from rocm_mpi_tpu.ops.wave_kernels import wave_multi_step

    cfg = _cfg()
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U, Uprev, C2 = model.init_state()
    ref, ref_prev = model.advance_fn("ap")(
        jnp.copy(U), jnp.copy(Uprev), C2, 24
    )
    got, got_prev = wave_multi_step(
        U, Uprev, C2, cfg.dt, cfg.spacing, 24, chunk=8
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(got_prev), np.asarray(ref_prev), rtol=1e-12
    )


def test_wave_vmem_equal_spacing_a_form_matches_ap():
    # The r4 A-form branch of _wave_multi_step_kernel fires only for
    # equal spacing + chunk >= 4 (the default _cfg is deliberately
    # unequal, exercising the direct form): a square grid with equal
    # lengths takes the prologue-hoisted form, which must reproduce the
    # ap trajectory and hold Dirichlet edges bitwise.
    from rocm_mpi_tpu.ops.wave_kernels import wave_multi_step

    cfg = _cfg(shape=(24, 24))  # lengths (10, 10) → equal spacing
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U, Uprev, C2 = model.init_state()
    edge0 = np.asarray(U)[0].copy()
    ref, ref_prev = model.advance_fn("ap")(
        jnp.copy(U), jnp.copy(Uprev), C2, 24
    )
    got, got_prev = wave_multi_step(
        U, Uprev, C2, cfg.dt, cfg.spacing, 24, chunk=8
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(got_prev), np.asarray(ref_prev), rtol=1e-12
    )
    np.testing.assert_array_equal(np.asarray(got)[0], edge0)  # bitwise hold


def test_wave_run_vmem_resident():
    cfg = _cfg(nt=48, warmup=16)
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    r = model.run_vmem_resident()
    # Same end state as the per-step run (fresh model: run() re-inits).
    r_ref = AcousticWave(cfg, devices=jax.devices()[:1]).run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(r.U), np.asarray(r_ref.U), rtol=1e-12
    )


def test_wave_deep_sweep_matches_ap_sharded():
    # The deep-halo schedule, wave edition: 2 sweeps of k=4 on a 2x2 mesh
    # must land on the same state pair as 8 per-step ap steps.
    from rocm_mpi_tpu.parallel.deep_halo import make_wave_deep_sweep

    cfg = _cfg(dims=(2, 2))
    model = AcousticWave(cfg)
    U, Uprev, C2 = model.init_state()
    ref, ref_prev = model.advance_fn("ap")(
        jnp.copy(U), jnp.copy(Uprev), C2, 8
    )
    sched = make_wave_deep_sweep(model.grid, 4, cfg.dt, cfg.spacing)
    P = jax.jit(sched.prepare)(C2)  # the ONE C2 exchange of the schedule
    sweep = jax.jit(sched.sweep)
    got, got_prev = sweep(*sweep(U, Uprev, P), P)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(got_prev), np.asarray(ref_prev), rtol=1e-12
    )


def test_wave_run_deep_matches_per_step_run():
    cfg = _cfg(dims=(2, 2), nt=48, warmup=16)
    r = AcousticWave(cfg).run_deep(block_steps=8)
    r_ref = AcousticWave(cfg).run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(r.U), np.asarray(r_ref.U), rtol=1e-12
    )


def test_wave_explicit_oversized_deep_depth_raises():
    # ADVICE r3: explicit depths exceeding the shard extent must raise
    # (matching HeatDiffusion), not silently clamp past the strict
    # validation; the DEFAULT still clamps.
    cfg = _cfg(dims=(2, 2), nt=48, warmup=16)  # shard (12, 10)
    model = AcousticWave(cfg)
    # gcd(16, 32, 16) = 16 > 10: stays oversized after the window gcd.
    with pytest.raises(ValueError, match="exceeds a local shard extent"):
        model.effective_deep_depth(block_steps=16, warn=False)
    assert model.effective_deep_depth(block_steps=8, warn=False) == 8
    # An oversized depth the window gcd REDUCES below the shard extent
    # degrades and runs (diffusion's policy): gcd(16, 32, 24) = 8 <= 10.
    assert model.effective_deep_depth(block_steps=24, warn=False) == 8
    # Default clamps to the shard extent without raising.
    assert model.effective_deep_depth(warn=False) >= 1


def test_wave_run_reports_metrics():
    cfg = _cfg(nt=24, warmup=8)
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    r = model.run(variant="ap")
    assert r.wtime > 0 and r.gpts > 0 and r.t_eff > 0
    assert r.U.shape == cfg.global_shape
    # Peak displacement stays bounded (CFL-stable run).
    assert float(jnp.abs(r.U).max()) < 2.0


def test_wave_app_runs():
    import importlib
    import pathlib
    import sys

    apps_dir = str(pathlib.Path(__file__).resolve().parent.parent / "apps")
    sys.path.insert(0, apps_dir)
    try:
        app = importlib.import_module("wave_2d")
    finally:
        sys.path.remove(apps_dir)
    rc = app.main(
        ["--nx", "24", "--ny", "20", "--nt", "12", "--warmup", "4",
         "--dims", "2,2", "--variant", "perf"]
    )
    assert rc == 0
    rc = app.main(
        ["--nx", "24", "--ny", "20", "--nt", "12", "--warmup", "4",
         "--dims", "2,2", "--deep", "4"]
    )
    assert rc == 0
    rc = app.main(
        ["--nx", "24", "--ny", "20", "--nt", "12", "--warmup", "4",
         "--dims", "1,1", "--vmem"]
    )
    assert rc == 0
    # --profile writes a trace directory (the §5.1 convention) and
    # --save-field the .npy artifact (§5.4), together in one run.
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        field = pathlib.Path(td) / "field.npy"
        rc = app.main(
            ["--nx", "24", "--ny", "20", "--nt", "12", "--warmup", "4",
             "--dims", "2,2", "--variant", "hide", "--profile", td,
             "--save-field", str(field)]
        )
        assert rc == 0
        assert any(pathlib.Path(td).iterdir()), "profile trace not written"
        import numpy as np

        assert np.load(field).shape == (24, 20)
    rc = app.main(
        ["--nx", "12", "--ny", "10", "--nz", "8", "--nt", "12",
         "--warmup", "4", "--dims", "2,2,2"]
    )
    assert rc == 0
    # argparse rejects the combination before any backend work
    with pytest.raises(SystemExit) as exc:
        app.main(["--deep", "4", "--vmem"])
    assert exc.value.code == 2
    # --vmem on a sharded mesh: clean diagnostic, not a traceback
    rc = app.main(
        ["--nx", "24", "--ny", "20", "--nt", "12", "--warmup", "4",
         "--dims", "2,2", "--vmem"]
    )
    assert rc == 2
