"""Gloo-real rank worker for the multi-tenant serving drill
(tests/test_serving.py drives it via parallel.launcher.spawn_ranks).

Each rank joins the jax.distributed cluster (one virtual CPU device per
rank — the space mesh spans the ranks), builds the IDENTICAL
deterministic heterogeneous request trace, and runs it through
SimulationService. Scheduling is a pure function of the trace
(serving/bins.py's determinism contract), so every rank plans the same
batches and the batched collectives never diverge (the GL08 hazard
class). The drill's pins: the trace compiles exactly len(bins)
programs, and `compiles.steady_state == 0` after the program classes
exist (a second identical trace compiles NOTHING).
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

from rocm_mpi_tpu.utils.backend import set_cpu_device_count

jax.config.update("jax_platforms", "cpu")
set_cpu_device_count(1)  # one device per rank: the space mesh IS the ranks
jax.config.update("jax_enable_x64", True)


def trace(seed_tag: str):
    from rocm_mpi_tpu.serving.queue import Request

    reqs = []
    # >= 3 bins: two diffusion shape classes + one wave class; shapes
    # divide the 2-rank (2, 1) space mesh. Mixed step counts exercise
    # the per-lane masking inside shared batches.
    mix = [
        ("diffusion", (16, 16), 5), ("diffusion", (16, 16), 7),
        ("diffusion", (24, 24), 6), ("wave", (16, 16), 5),
        ("diffusion", (16, 16), 3), ("wave", (16, 16), 6),
    ]
    for i, (wl, shape, nt) in enumerate(mix):
        reqs.append(Request(
            request_id=f"{seed_tag}-{i:03d}", workload=wl,
            global_shape=shape, dtype="f64", nt=nt,
            ic_scale=1.0 + 0.05 * i,
        ))
    return reqs


def fleet_main() -> int:
    """The 2-replica fleet smoke, gloo-real: every rank mirrors the
    SAME router (routing is a pure fold over the trace — no wall time,
    no randomness), so the per-rank replica maps must come out
    identical and the interleaved replica drains plan the same batched
    collectives on every rank. The driving test compares the printed
    map across ranks."""
    import pathlib
    import tempfile

    from rocm_mpi_tpu.parallel.distributed import process_id
    from rocm_mpi_tpu.serving import journal as fleet_journal
    from rocm_mpi_tpu.serving.router import FleetRouter
    from rocm_mpi_tpu.serving.service import ServeConfig, SimulationService

    journal = fleet_journal.TicketJournal(
        pathlib.Path(tempfile.mkdtemp(prefix="rmt-fleet-worker-"))
        / "fleet-journal.jsonl"
    )
    router = FleetRouter(
        lambda rid: SimulationService(config=ServeConfig(max_width=4)),
        2, journal=journal,
    )
    tickets = [router.submit(r) for r in trace("fleet")]
    router.drive()
    problems = router.check_accounting()
    assert not problems, problems
    for t in tickets:
        assert t.state == "done", (t.request.request_id, t.state,
                                   t.error)
    merged = router.merged_counters()
    fmap = ",".join(
        f"{k}->{v}" for k, v in sorted(router.replica_map().items())
    )
    journal.close()
    print(
        f"FLEET_WORKER_DONE rank={process_id()} "
        f"done={merged['completed']} map={fmap}",
        flush=True,
    )
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--fleet", action="store_true",
                   help="run the 2-replica in-process fleet smoke "
                   "instead of the single-service drill")
    args = p.parse_args()

    from rocm_mpi_tpu.parallel.distributed import (
        maybe_initialize_distributed,
        process_id,
    )

    maybe_initialize_distributed()
    from rocm_mpi_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache()

    from rocm_mpi_tpu.telemetry import compiles

    compiles.install()

    if args.fleet:
        return fleet_main()

    from rocm_mpi_tpu.serving.service import ServeConfig, SimulationService

    svc = SimulationService(config=ServeConfig(max_width=4))
    report = svc.run_trace(trace("gloo"))
    assert report.served == 6, report.served
    assert report.failed == 0, report.failed
    # exactly len(bins) program classes compiled for the trace
    n_bins = report.n_bins
    n_programs = report.n_programs

    # Steady state: the identical mix again (fresh ids) compiles ZERO
    # new programs — the bin cache is the compile amortizer.
    before = compiles.snapshot()["totals"]["backend_compiles"]
    report2 = svc.run_trace(trace("gloo2"))
    after = compiles.snapshot()["totals"]["backend_compiles"]
    assert report2.served == 6, report2.served
    steady = report2.compiles["steady_state"]

    print(
        f"SERVING_WORKER_DONE rank={process_id()} bins={n_bins} "
        f"programs={n_programs} steady={steady} "
        f"second_trace_compiles={after - before}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
