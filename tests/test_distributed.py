"""Multi-host process model (D9) — executed, not just written.

The reference's process model is `srun -n N --mpi=pmix` + PMIx wiring
(/root/reference/README.md:18). Here the test plays the launcher: it spawns
2 real Python processes, hands each its rank via the framework's launcher
env contract (RMT_COORDINATOR/RMT_NUM_PROCS/RMT_PROCESS_ID), and the
workers (tests/distributed_worker.py) form a jax.distributed cluster over
gloo, run a sharded step whose halo exchange crosses the process boundary,
and gather to process 0 — exercising maybe_initialize_distributed,
gather_to_host0's process_allgather branch, and metrics.force's
non-addressable branch.
"""

import os
import pathlib
import socket
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_step_and_gather():
    port = _free_port()
    base = os.environ.copy()
    # The workers size their own device count (2 cpu devices per process);
    # an inherited XLA_FLAGS device-count force would conflict with it.
    base.pop("XLA_FLAGS", None)
    procs = []
    for pid in range(2):
        env = dict(
            base,
            JAX_PLATFORMS="cpu",
            RMT_DISTRIBUTED="1",
            RMT_COORDINATOR=f"127.0.0.1:{port}",
            RMT_NUM_PROCS="2",
            RMT_PROCESS_ID=str(pid),
            RMT_INIT_TIMEOUT_S="60",
            # The worker imports the package from the repo root (the spawned
            # interpreter only gets the script's own dir on sys.path).
            PYTHONPATH=os.pathsep.join(
                [str(ROOT)] + ([base["PYTHONPATH"]] if "PYTHONPATH" in base else [])
            ),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(ROOT / "tests" / "distributed_worker.py")],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=ROOT,
            )
        )
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=240))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n--- stdout ---\n{out}"
            f"\n--- stderr ---\n{err[-3000:]}"
        )
    assert "DISTRIBUTED_OK" in outs[0][0], outs[0][0]
