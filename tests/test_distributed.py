"""Multi-host process model (D9) — executed, not just written.

The reference's process model is `srun -n N --mpi=pmix` + PMIx wiring
(/root/reference/README.md:18). Here the test plays the launcher: it spawns
2 real Python processes, hands each its rank via the framework's launcher
env contract (RMT_COORDINATOR/RMT_NUM_PROCS/RMT_PROCESS_ID), and the
workers (tests/distributed_worker.py) form a jax.distributed cluster over
gloo, run a sharded step whose halo exchange crosses the process boundary,
and gather to process 0 — exercising maybe_initialize_distributed,
gather_to_host0's process_allgather branch, and metrics.force's
non-addressable branch.
"""

import pathlib

from rocm_mpi_tpu.parallel.launcher import spawn_ranks

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _spawn_two_process(argv, timeout=240):
    """Play the launcher (srun/PMIx analog) via the shared N-rank
    implementation (parallel.launcher.spawn_ranks), asserting every rank
    exits cleanly."""
    results = spawn_ranks(argv, nprocs=2, timeout=timeout)
    for pid, (p, (out, err)) in enumerate(results):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n--- stdout ---\n{out}"
            f"\n--- stderr ---\n{err[-3000:]}"
        )
    return results


def test_two_process_distributed_step_and_gather():
    results = _spawn_two_process([str(ROOT / "tests" / "distributed_worker.py")])
    assert "DISTRIBUTED_OK" in results[0][1][0], results[0][1][0]


def test_two_process_weak_scaling_loop():
    """VERDICT r3 #7: the weak-scaling harness itself (apps/weak_scaling.py)
    run under the 2-process gloo launcher, so the scaling loop — mesh
    construction per count, the timed run, the efficiency accounting —
    crosses a real process boundary (n=4 spans both processes' device
    pairs; n=2 is the proc-0-only submesh rung, which the other process
    must still participate in dispatching)."""
    import json

    results = _spawn_two_process(
        [
            str(ROOT / "apps" / "weak_scaling.py"),
            "--cpu-devices", "2", "--local", "16", "--nt", "32",
            "--warmup", "8", "--counts", "2,4", "--variant", "hide",
            "--json",
        ]
    )
    out0 = results[0][1][0]
    rows = [
        json.loads(ln) for ln in out0.splitlines()
        if ln.strip().startswith("{")
    ]
    assert [r["devices"] for r in rows] == [2, 4], out0
    assert rows[1]["dims"] == [2, 2]  # really spans both processes
    assert all(r["gpts"] > 0 for r in rows)
    # Process 0 reports; process 1 stays silent on stdout (log0-gated).
    assert "weak scaling:" not in results[1][1][0]
