"""End-to-end app integration tests (L6): every entry point runs as a real
subprocess on virtual CPU devices, exactly as a user would invoke it.

The reference's acceptance procedure is "run the app under srun and check
the output" (README.md:14-19); these tests automate that for the whole app
ladder — exit code, key printout lines, and the artifacts (heatmap PNG for
the vis path, prof.txt report for the profiling app).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
APPS = REPO / "apps"


def run_app(script, *args, timeout=240):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # app must pick cpu via --cpu-devices
    proc = subprocess.run(
        [sys.executable, str(APPS / script), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, (
        f"{script} failed ({proc.returncode}):\n{proc.stdout}\n{proc.stderr}"
    )
    return proc.stdout + proc.stderr


@pytest.mark.parametrize("n", [1, 3, 8])
def test_ring_app(n):
    out = run_app("ici_ring_test.py", "--cpu-devices", str(n))
    assert "ring exchange: PASS" in out


@pytest.mark.parametrize(
    "script,extra",
    [
        ("diffusion_2d_ap.py", []),
        ("diffusion_2d_kp.py", []),
        ("diffusion_2d_perf.py", ["--fact", "0"]),
        ("diffusion_2d_perf_hide.py", ["--fact", "0", "--b-width", "8,8"]),
    ],
)
def test_2d_apps_run(script, extra):
    out = run_app(
        script,
        "--cpu-devices", "4", "--nx", "64", "--ny", "64", "--nt", "20",
        "--warmup", "4", "--no-vis", *extra,
    )
    assert "Executed 20 steps" in out
    assert "maximum(T)" in out


def test_ap_app_writes_heatmap():
    png = REPO / "output" / "Temp_ap_4_64_64.png"
    png.unlink(missing_ok=True)  # a stale artifact must not mask a regression
    out = run_app(
        "diffusion_2d_ap.py",
        "--cpu-devices", "4", "--nx", "64", "--ny", "64", "--nt", "10",
        "--warmup", "2", "--vis",
    )
    assert "wrote" in out
    assert png.exists() and png.stat().st_size > 0


def test_3d_app_runs():
    out = run_app(
        "diffusion_3d_perf_hide.py",
        "--cpu-devices", "8", "--nx", "32", "--ny", "32", "--nz", "32",
        "--nt", "10", "--warmup", "2", "--b-width", "4,4,32", "--no-vis",
    )
    assert "Executed 10 steps" in out


def test_weak_scaling_app():
    out = run_app(
        "weak_scaling.py",
        "--cpu-devices", "4", "--local", "32", "--nt", "20", "--warmup", "4",
        "--variant", "shard", "--json",
    )
    assert "efficiency=100.0%" in out  # n=1 row defines the baseline
    assert '"devices": 4' in out


def test_weak_scaling_app_wave_workload():
    out = run_app(
        "weak_scaling.py",
        "--cpu-devices", "4", "--local", "16", "--nt", "16", "--warmup", "4",
        "--workload", "wave", "--variant", "deep", "--deep-k", "4", "--json",
    )
    assert "efficiency=100.0%" in out
    assert '"metric": "weak-scaling wave deep' in out
    assert '"devices": 4' in out


def test_prof_app_writes_report(tmp_path):
    report = tmp_path / "prof.txt"
    trace = tmp_path / "trace"
    out = run_app(
        "diffusion_2d_perf_hide_prof.py",
        "--cpu-devices", "4", "--nx", "64", "--ny", "64", "--nt", "20",
        "--b-width", "8,8",
        "--report", str(report), "--profile", str(trace),
    )
    assert "Executed 20 steps" in out
    text = report.read_text()
    assert "XLA cost analysis" in text
    assert trace.is_dir()


def test_deep_flag_and_save_field(tmp_path):
    import numpy as np

    field = tmp_path / "final.npy"
    out = run_app(
        "diffusion_2d_perf.py",
        "--cpu-devices", "4", "--fact", "0", "--nx", "64", "--ny", "64",
        "--nt", "24", "--warmup", "8", "--deep", "8", "--no-vis",
        "--save-field", str(field),
    )
    assert "Executed 24 steps" in out
    arr = np.load(field)
    assert arr.shape == (64, 64)
    assert 0 < arr.max() < 1.0
