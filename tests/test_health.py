"""Runtime health plane tests (docs/TELEMETRY.md "Health plane"):
flight-recorder ring/counters/sidecar semantics, the progress-aware
stall verdict, post-mortem composition and the merged timeline trace
(torn sidecars included), monitor / export-openmetrics CLI exit codes
and the OpenMetrics round-trip, compile accounting with the regress
zero-pin, the unified clear_events reset, and the ISSUE-5 acceptance
drills: a real 2-rank weak-scaling launch with an injected `stall`
fault (watchdog names the rank BY PROGRESS, post-mortem carries a
faulthandler traceback and a flight ring ending in a halo span, peers
reaped by the existing grace kill, wreckage bundled) and its clean twin
(zero verdicts, compiles.steady_state == 0, regress-pinned)."""

from __future__ import annotations

import json
import pathlib

import pytest

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.telemetry import compiles, events, flight, health, trace
from rocm_mpi_tpu.telemetry.__main__ import main as cli_main

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_health(monkeypatch):
    """Every test starts with telemetry and the flight recorder off and
    empty; compile accounting reset (the installed process-wide hook, if
    any, stays — uninstalling it mid-suite would be its own bug)."""
    monkeypatch.setattr(events, "_ENABLED", False)
    monkeypatch.setattr(events, "_DIR", None)
    monkeypatch.setattr(events, "_RANK", None)
    monkeypatch.setattr(flight, "_ENABLED", False)
    monkeypatch.setattr(flight, "_DIR", None)
    monkeypatch.setattr(flight, "_RANK", None)
    events.clear()
    flight.reset()
    compiles.reset()
    yield
    flight.disable()
    events.clear()
    flight.reset()
    compiles.reset()


def _beat(rank, step, phase="step", t=1000.0, extra=None):
    doc = {
        "schema": flight.HEARTBEAT_SCHEMA, "v": 1, "rank": rank, "t": t,
        "counters": {"step": step}, "last_phase": phase,
        "last_phase_name": f"{phase}.x", "last_phase_t": t, "ring": [],
    }
    if extra:
        doc.update(extra)
    return doc


# ---------------------------------------------------------------------------
# Flight recorder: ring, counters, sidecar, reset
# ---------------------------------------------------------------------------


def test_flight_sidecar_counters_and_ring(tmp_path):
    events.configure(directory=tmp_path, rank=2)
    flight.enable(rank=2)
    with telemetry.span("halo.heartbeat", phase="halo", bytes=4096):
        pass
    flight.progress(step=7, windows=1)
    flight.flush()
    doc = json.loads((tmp_path / "heartbeat-rank2.json").read_text())
    assert doc["schema"] == flight.HEARTBEAT_SCHEMA
    assert doc["rank"] == 2
    assert doc["counters"]["step"] == 7
    assert doc["counters"]["windows"] == 1
    # the events tap counted the halo span and its bytes
    assert doc["counters"]["halo_exchanges"] == 1
    assert doc["counters"]["halo_bytes"] == 4096
    # span ENTRY set the phase (a wedged rank never reaches the exit)
    assert doc["last_phase"] == "halo"
    assert doc["last_phase_name"] == "halo.heartbeat"
    # ring holds the entry note and the exit record, in order
    kinds = [(r["kind"], r["name"]) for r in doc["ring"]]
    assert ("phase", "halo.heartbeat") in kinds
    assert ("span", "halo.heartbeat") in kinds
    assert doc["ring"][-1]["kind"] == "span"


def test_flight_step_counter_is_monotonic_and_bounded_ring(tmp_path):
    events.configure(directory=tmp_path, rank=0)
    flight.enable(rank=0, ring_size=4)
    flight.progress(step=9)
    flight.progress(step=3)  # lower: ignored
    for i in range(10):
        telemetry.counter("x", i)
    doc = flight.snapshot()
    assert doc["counters"]["step"] == 9
    assert len(doc["ring"]) == 4, "ring is bounded"


def test_flight_progress_flushes_before_blocking(tmp_path):
    """The watchdog contract: a step bump is on disk synchronously —
    the caller may block in a collective immediately after."""
    events.configure(directory=tmp_path, rank=0)
    flight.enable(rank=0)
    flight.progress(step=41)
    doc = json.loads((tmp_path / "heartbeat-rank0.json").read_text())
    assert doc["counters"]["step"] == 41


def test_flight_reset_is_the_unified_clear_events(tmp_path):
    """Satellite 6: exactly one reset behavior — events dropped,
    annotation dedup preserved — shared by telemetry.clear_events, the
    deprecated metrics.clear_events alias, and flight.reset."""
    from rocm_mpi_tpu.utils import metrics

    events.configure(directory=tmp_path, rank=0)
    flight.enable(rank=0)
    telemetry.annotate("halo.exchange", bytes=128)
    telemetry.record_event("retry", attempt=1)
    with telemetry.span("s"):
        pass
    flight.reset()
    assert events.records(kind="event") == [], "events dropped"
    assert events.records(kind="span"), "spans survive the reset"
    assert telemetry.annotate("halo.exchange", bytes=128) is None, \
        "annotation dedup preserved: no re-emit after reset"
    assert flight.snapshot()["counters"] == {}
    # the deprecated alias forwards (and says so)
    telemetry.record_event("retry", attempt=2)
    with pytest.deprecated_call():
        metrics.clear_events()
    assert events.records(kind="event") == []
    # the public spelling needs no warning
    telemetry.record_event("retry", attempt=3)
    telemetry.clear_events()
    assert events.records(kind="event") == []


def test_flight_enable_needs_a_directory(monkeypatch):
    monkeypatch.delenv("RMT_HEALTH_DIR", raising=False)
    monkeypatch.delenv("RMT_TELEMETRY_DIR", raising=False)
    with pytest.raises(ValueError, match="directory"):
        flight.enable()


# ---------------------------------------------------------------------------
# Read side: sidecar loading (torn-tolerant) and the stall verdict
# ---------------------------------------------------------------------------


def test_load_heartbeats_skips_torn_sidecar(tmp_path):
    (tmp_path / "heartbeat-rank0.json").write_text(json.dumps(_beat(0, 5)))
    (tmp_path / "heartbeat-rank1.json").write_text(
        '{"schema": "rocm_mpi_tpu.telemetry.heartbeat", "counters": {"st'
    )  # killed mid-write
    beats, skipped = health.load_heartbeats(tmp_path)
    assert list(beats) == [0] and skipped == 1


def test_progress_watch_stalled_collective_signature():
    w = health.ProgressWatch(stall_grace_s=2.0)
    w.observe({0: _beat(0, 10), 1: _beat(1, 10)}, now=0.0)
    # rank 0 advances to 15 and blocks; rank 1 never changes
    w.observe({0: _beat(0, 15), 1: _beat(1, 10)}, now=1.0)
    assert w.verdicts(1.5) == [], "grace not elapsed for rank 1"
    v = w.verdicts(3.5)
    assert [x["rank"] for x in v] == [1]
    assert v[0]["step"] == 10 and v[0]["median_step"] == 12.5
    assert v[0]["stalled_for_s"] >= 2.0
    # rank 0 is NOT flagged even when it also stops changing: its
    # counter is at/above the median (it is the wedged survivor)
    assert all(x["rank"] != 0 for x in w.verdicts(30.0))


def test_progress_watch_needs_median_ahead_not_wall_clock():
    """Everyone equally slow (one long window, a coordinated compile):
    nobody's median moves past anybody — no verdict, ever."""
    w = health.ProgressWatch(stall_grace_s=1.0)
    w.observe({0: _beat(0, 10), 1: _beat(1, 10)}, now=0.0)
    assert w.verdicts(100.0) == []
    # single rank: no cross-rank median, no verdict
    w2 = health.ProgressWatch(stall_grace_s=1.0)
    w2.observe({0: _beat(0, 10)}, now=0.0)
    assert w2.verdicts(100.0) == []


def test_progress_watch_ignores_ranks_without_step_counters():
    """A rank that never published a step counter is NOT participating
    (sitting out a weak-scaling rung, still compiling) — it must be
    excluded from the median and never flagged, and a lone publishing
    rank has no cross-rank median to be judged against."""
    parked = _beat(1, 0)
    del parked["counters"]["step"]  # no step ever published
    w = health.ProgressWatch(stall_grace_s=1.0)
    w.observe({0: _beat(0, 5), 1: parked}, now=0.0)
    w.observe({0: _beat(0, 50), 1: parked}, now=2.0)
    assert w.verdicts(50.0) == [], \
        "neither the parked rank (no counter) nor the lone worker fires"
    assert list(w.steps()) == [0]


def test_progress_watch_liveness_is_not_progress():
    """A stalled rank's flusher rewrites identical counters forever
    (fresh wall stamps): content, not mtime, defines progress."""
    w = health.ProgressWatch(stall_grace_s=1.0)
    w.observe({0: _beat(0, 5, t=1.0), 1: _beat(1, 9, t=1.0)}, now=0.0)
    w.observe({0: _beat(0, 5, t=2.0), 1: _beat(1, 9, t=2.0)}, now=2.0)
    v = w.verdicts(2.5)
    assert [x["rank"] for x in v] == [0]
    assert w.ages(2.5)[0] == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# Post-mortem composition + merged timeline trace (satellite 3)
# ---------------------------------------------------------------------------


def test_postmortem_compose_and_bundle_with_torn_sidecar(tmp_path):
    (tmp_path / "heartbeat-rank0.json").write_text(
        json.dumps(_beat(0, 14, phase="halo", t=1000.5))
    )
    (tmp_path / "heartbeat-rank1.json").write_text('{"torn')  # died mid-write
    (tmp_path / "postmortem-rank0.traceback").write_text(
        "Current thread 0x1 (most recent call first):\n  fault_point\n"
    )
    (tmp_path / "telemetry-rank0.jsonl").write_text(json.dumps({
        "v": 2, "kind": "span", "name": "step_window", "t": 1000.0,
        "t_mono": 1.0, "rank": 0, "dur_s": 0.25, "depth": 0, "tid": 1,
        "attrs": {"phase": "step", "steps": 5},
    }) + "\n" + '{"kind": "span", "name": "torn')
    verdict = {"rank": 0, "step": 14, "median_step": 16.5,
               "stalled_for_s": 3.0, "last_phase": "halo"}
    pm = health.write_postmortem(tmp_path, 0, verdict)
    doc = json.loads(pm.read_text())
    assert doc["schema"] == flight.POSTMORTEM_SCHEMA
    assert "fault_point" in doc["traceback"]
    assert doc["heartbeat"]["counters"]["step"] == 14
    assert isinstance(verdict.get("t"), float), "verdict wall-stamped"

    bundle_dir = health.bundle_postmortem(tmp_path, [verdict])
    bundle = json.loads((bundle_dir / "bundle.json").read_text())
    assert bundle["schema"] == flight.BUNDLE_SCHEMA
    assert bundle["verdicts"][0]["rank"] == 0
    # the merged timeline still opens with the torn sidecar in the mix:
    # JSON-valid, ts sorted, one verdict instant, a progress counter track
    tl = json.loads((bundle_dir / "timeline-trace.json").read_text())
    evs = tl["traceEvents"]
    for ev in evs:
        for key in trace.TRACE_REQUIRED_KEYS:
            assert key in ev, (key, ev)
    ts = [e["ts"] for e in evs if e["ph"] != "M"]
    assert ts == sorted(ts), "timeline must be ts-sorted"
    instants = [e for e in evs if e["name"] == "watchdog.verdict"]
    assert len(instants) == 1 and instants[0]["pid"] == 0
    assert instants[0]["args"]["median_step"] == 16.5
    counters = [e for e in evs if e["ph"] == "C" and e["name"] == "progress"]
    assert counters and counters[0]["args"]["step"] == 14
    # schema gate recognizes every bundled JSON artifact
    assert cli_main([
        "regress", "--check-schema",
        str(tmp_path / "heartbeat-rank0.json"), str(pm),
        str(bundle_dir / "bundle.json"),
    ]) == 0


def test_trace_verdict_instants_per_verdict_and_heartbeat_tracks():
    beats = {k: _beat(k, 10 + k, t=1000.0 + k) for k in (0, 1, 2)}
    verdicts = [
        {"rank": 1, "step": 3, "median_step": 5, "stalled_for_s": 2.0,
         "t": 1002.5},
        {"rank": 2, "step": 4, "median_step": 5, "stalled_for_s": 2.0,
         "t": 1003.0},
    ]
    doc = trace.to_chrome_trace({}, heartbeats=beats, verdicts=verdicts)
    evs = doc["traceEvents"]
    assert len([e for e in evs if e["name"] == "watchdog.verdict"]) == 2
    assert len([e for e in evs if e["ph"] == "C"]) == 3, \
        "one progress counter track per rank"
    assert {e["pid"] for e in evs} == {0, 1, 2}
    ts = [e["ts"] for e in evs if e["ph"] != "M"]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# monitor / export-openmetrics CLI (exit codes + round-trip)
# ---------------------------------------------------------------------------


def test_monitor_cli_exit_codes(tmp_path, capsys):
    assert cli_main(["monitor", str(tmp_path), "--iterations", "1"]) == 2
    (tmp_path / "heartbeat-rank0.json").write_text(json.dumps(_beat(0, 7)))
    (tmp_path / "heartbeat-rank1.json").write_text(json.dumps(_beat(1, 9)))
    assert cli_main(["monitor", str(tmp_path), "--iterations", "2",
                     "--interval", "0.05"]) == 0
    out = capsys.readouterr().out
    assert "rank" in out and "Δmedian" in out
    assert "+1" in out and "-1" in out, "straggler delta vs median shown"


def test_export_openmetrics_round_trips_run_gauges(tmp_path, capsys):
    assert cli_main(["export-openmetrics", str(tmp_path)]) == 2
    events.configure(directory=tmp_path, rank=0)
    # the exact key shapes the aggregator produces for rung gauges
    telemetry.gauge("run.gpts", 1.25, devices=4, driver="scan")
    telemetry.gauge("run.t_eff_gbs", 3.5, variant="hide")
    telemetry.counter("halo.exchange_nbytes", 2048)
    telemetry.counter("halo.exchange_nbytes", 2048)
    flight.enable(rank=0)
    flight.progress(step=12)
    assert cli_main(["export-openmetrics", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert text.rstrip().endswith("# EOF")
    parsed = health.parse_openmetrics(text)
    assert parsed["rmt_gauge"]["run.gpts@4dev:scan"] == 1.25, \
        "rung gauge keys round-trip verbatim"
    assert parsed["rmt_gauge"]["run.t_eff_gbs"] == 3.5
    assert parsed["rmt_counter_total"]["halo.exchange_nbytes"] == 4096
    assert parsed["rmt_progress"][
        (("counter", "step"), ("rank", "0"))
    ] == 12
    # --out writes the same snapshot atomically
    out_file = tmp_path / "snap.om"
    assert cli_main(["export-openmetrics", str(tmp_path),
                     "--out", str(out_file)]) == 0
    capsys.readouterr()
    assert health.parse_openmetrics(out_file.read_text()) == parsed


# ---------------------------------------------------------------------------
# Compile accounting (telemetry/compiles.py + the regress zero-pin)
# ---------------------------------------------------------------------------

BACKEND = "/jax/core/compile/backend_compile_duration"


def test_compiles_tracker_counts_and_steady_window(tmp_path):
    events.configure(directory=tmp_path, rank=0)
    compiles.record_interval(BACKEND, "jit_step", 0.2)
    compiles.record_interval(BACKEND, "jit_step", 0.1)
    compiles.record_interval(BACKEND, "jit_probe", 0.1)
    compiles.record_interval("/jax/core/compile/jaxpr_trace_duration",
                             "jit_step", 0.1)  # not a backend compile
    compiles.record_cache_event("/jax/compilation_cache/cache_misses")
    compiles.record_cache_event("/jax/compilation_cache/cache_hits")
    assert compiles.steady_state() == 0
    compiles.mark_steady()
    compiles.record_interval(BACKEND, "jit_step", 0.3)  # a RECOMPILE
    compiles.unmark_steady()
    compiles.record_interval(BACKEND, "jit_next_rung", 0.3)  # legitimate
    snap = compiles.snapshot()
    assert snap["programs"]["jit_step"]["count"] == 3
    assert snap["programs"]["jit_step"]["steady"] == 1
    assert snap["totals"] == {"backend_compiles": 5, "cache_hits": 1,
                              "cache_misses": 1}
    assert compiles.steady_state() == 1
    compiles.emit_gauges()
    gauges = {r["name"]: r["value"] for r in events.records(kind="gauge")}
    assert gauges["compiles.total"] == 5
    assert gauges["compiles.steady_state"] == 1
    assert gauges["compiles.cache_misses"] == 1
    spans = events.records(kind="span", name="compile.backend")
    assert len(spans) == 5
    assert spans[0]["attrs"]["program"] == "jit_step"


def test_compiles_steady_gauge_only_after_mark(tmp_path):
    events.configure(directory=tmp_path, rank=0)
    compiles.record_interval(BACKEND, "jit_x", 0.1)
    compiles.emit_gauges()
    gauges = {r["name"] for r in events.records(kind="gauge")}
    assert "compiles.steady_state" not in gauges, \
        "an unmarked run must not fake a zero"


def test_regress_pins_zero_steady_state_recompiles():
    from rocm_mpi_tpu.telemetry import regress

    base = {"gauges": {"compiles.steady_state": 0, "run.gpts": 2.0}}
    clean = {"gauges": {"compiles.steady_state": 0, "run.gpts": 2.1}}
    stormy = {"gauges": {"compiles.steady_state": 4, "run.gpts": 2.1}}
    assert not regress.regressions(regress.compare(clean, base))
    bad = regress.regressions(regress.compare(stormy, base))
    assert [d.name for d in bad] == ["gauges.compiles.steady_state"]
    # direction pins: a compile count going DOWN never regresses
    fewer = {"gauges": {"compiles.steady_state": 0, "compiles.total": 2}}
    more = {"gauges": {"compiles.steady_state": 0, "compiles.total": 9}}
    assert not regress.regressions(regress.compare(fewer, more))
    assert regress.regressions(regress.compare(more, fewer))


def test_compiles_install_smoke():
    """The real hook on the installed jax: a fresh jit compile is
    counted with its program name (mode 'named' on this pin; 'events'
    would still count, nameless)."""
    mode = compiles.install()
    if mode is None:
        pytest.skip("no compile listener available on this jax")
    import jax
    import jax.numpy as jnp

    before = compiles.snapshot()["totals"]["backend_compiles"]

    def never_seen_before_fn(x):
        return x * 3.0 + 1.5

    jax.jit(never_seen_before_fn)(jnp.arange(7.0)).block_until_ready()
    snap = compiles.snapshot()
    assert snap["totals"]["backend_compiles"] >= before + 1
    if mode == "named":
        assert any("never_seen_before_fn" in name
                   for name in snap["programs"]), snap["programs"]


# ---------------------------------------------------------------------------
# Acceptance drills: 2-rank weak_scaling via spawn_ranks (CPU/gloo)
# ---------------------------------------------------------------------------


def _spawn_health_run(tmp_path, inject=None, **kw):
    from rocm_mpi_tpu.parallel.launcher import spawn_ranks

    tel = tmp_path / "tel"
    return tel, spawn_ranks(
        [
            REPO / "apps" / "weak_scaling.py",
            "--cpu-devices", "1", "--local", "16", "--nt", "24",
            "--warmup", "4", "--counts", "2", "--dtype", "f32",
            "--telemetry-windows", "4", "--driver", "step", "--no-probes",
        ],
        nprocs=2,
        timeout=240,
        inject_fault=inject,
        telemetry_dir=tel,
        health_dir=tel,
        **kw,
    )


def test_watchdog_drill_stall_fault_names_rank1_by_progress(tmp_path):
    """THE acceptance drill: rank 1 wedges in a `stall` fault at a
    window boundary (step-driver boundaries: 4, 9, 14, 19); the
    watchdog must name it by PROGRESS — its published step counter
    behind the advancing cross-rank median — dump a faulthandler
    traceback via SIGUSR2, write postmortem-rank1.json whose flight
    ring ends in a halo span, kill it, reap rank 0 with the existing
    peer grace, and bundle a merged timeline naming rank 1."""
    tel, results = _spawn_health_run(
        tmp_path, inject="stall@step=14,rank=1",
        heartbeat_s=2.0, peer_grace_s=6.0, stall_grace_s=3.0,
    )
    report = results.report
    # the watchdog — not the launch timeout — ended both ranks
    assert len(report.watchdog_verdicts) == 1, report.events
    verdict = report.watchdog_verdicts[0]
    assert verdict["rank"] == 1
    # detection is by progress: rank 1 never published boundary 14,
    # rank 0 did — so the median sits strictly ahead of the victim
    assert verdict["step"] == 9
    assert verdict["median_step"] > verdict["step"]
    assert verdict["last_phase"] == "halo"
    (p0, (_, _)), (p1, (out1, _)) = results
    assert p0.returncode != 0, "rank 0 was wedged and peer-grace killed"
    assert p1.returncode != 0, "rank 1 was killed by the watchdog"
    assert report.first_failure is not None and report.first_failure[0] == 1
    assert report.killed_after_failure == [0], \
        "the EXISTING peer-grace kill reaped the wedged survivor"
    # the health heartbeat line replaced the legacy wall-clock-only line
    assert any("last progress age" in e for e in report.events)
    # post-mortem: faulthandler traceback + flight ring ending in halo
    pm = json.loads((tel / "postmortem-rank1.json").read_text())
    assert pm["schema"] == flight.POSTMORTEM_SCHEMA
    assert "fault_point" in pm["traceback"], \
        "the all-thread dump must show where rank 1 is wedged"
    hb = pm["heartbeat"]
    assert hb["counters"]["step"] == 9
    assert hb["last_phase"] == "halo"
    span_like = [r for r in hb["ring"] if r["kind"] in ("span", "phase")]
    assert span_like and span_like[-1]["name"] == "halo.heartbeat", \
        "the ring's last phase is a halo span"
    # the merged bundle names rank 1 as the verdict
    bundle = json.loads((tel / "postmortem" / "bundle.json").read_text())
    assert [v["rank"] for v in bundle["verdicts"]] == [1]
    tl = json.loads((tel / "postmortem" / "timeline-trace.json").read_text())
    assert any(e["name"] == "watchdog.verdict" and e["pid"] == 1
               for e in tl["traceEvents"])
    ts = [e["ts"] for e in tl["traceEvents"] if e["ph"] != "M"]
    assert ts == sorted(ts)


def test_clean_health_run_zero_verdicts_and_zero_recompiles(tmp_path,
                                                            capsys):
    """The clean twin: same launch, no fault — zero watchdog verdicts,
    no postmortem/ bundle, and compiles.steady_state == 0 after warmup,
    pinned through `telemetry regress` against a zero baseline."""
    tel, results = _spawn_health_run(
        tmp_path, heartbeat_s=2.0, stall_grace_s=3.0,
    )
    for i, (p, (out, err)) in enumerate(results):
        assert p.returncode == 0, f"rank {i} rc={p.returncode}:\n{err}"
    assert results.report.watchdog_verdicts == []
    assert not (tel / "postmortem").is_dir(), \
        "a clean run must not leave an (empty) incident bundle"
    summary = json.loads((tel / "telemetry-summary.json").read_text())
    assert summary["gauges"]["compiles.steady_state"] == 0
    assert summary["gauges"]["compiles.total"] > 0
    # the halo heartbeat probes ran per window boundary on both ranks
    assert summary["phases"]["halo"]["count"] >= 8
    assert summary["traced"], "trace-time annotations intact"
    # regress-pinned: the summary gates against itself (zero baseline
    # zero current), and a doctored recompile storm fails the gate
    summary_path = tel / "telemetry-summary.json"
    assert cli_main(["regress", str(summary_path),
                     "--baseline", str(summary_path)]) == 0
    stormy = json.loads(summary_path.read_text())
    stormy["gauges"]["compiles.steady_state"] = 7
    stormy_path = tmp_path / "stormy.json"
    stormy_path.write_text(json.dumps(stormy))
    assert cli_main(["regress", str(stormy_path),
                     "--baseline", str(summary_path)]) == 1
    capsys.readouterr()
    # the sidecars the run left behind pass the schema gate lint.sh runs
    sidecars = sorted(str(p) for p in tel.glob("heartbeat-rank*.json"))
    assert len(sidecars) == 2
    assert cli_main(["regress", "--check-schema", *sidecars]) == 0
    capsys.readouterr()


def test_flight_enable_arms_collection(tmp_path):
    """Health without telemetry would flush structurally-valid but empty
    sidecars (last_phase null, ring []) — so arming the recorder arms
    the span/event stream too, into the same directory."""
    assert not events.enabled()
    flight.enable(directory=tmp_path, rank=0)
    assert events.enabled(), "--health implies collection"
    with telemetry.span("halo.x", phase="halo"):
        pass
    doc = flight.snapshot()
    assert doc["last_phase"] == "halo" and doc["ring"]


def test_spawn_ranks_clears_stale_sidecars_from_reused_health_dir(tmp_path):
    """A reused health_dir must not feed the watchdog last run's
    counters: fresh ranks spend longer than the stall grace in startup
    before their first flush, and stale uneven steps would get a healthy
    rank flagged and killed for the previous incident."""
    from rocm_mpi_tpu.parallel.launcher import spawn_ranks

    (tmp_path / "heartbeat-rank0.json").write_text(
        json.dumps(_beat(0, 2, phase="halo"))
    )
    (tmp_path / "heartbeat-rank1.json").write_text(json.dumps(_beat(1, 50)))
    (tmp_path / "postmortem-rank0.json").write_text(json.dumps(
        {"schema": flight.POSTMORTEM_SCHEMA, "v": 1, "rank": 0}
    ))
    (tmp_path / "postmortem-rank0.traceback").write_text("old dump")
    (tmp_path / "postmortem").mkdir()
    (tmp_path / "postmortem" / "bundle.json").write_text(json.dumps(
        {"schema": flight.BUNDLE_SCHEMA, "v": 1, "verdicts": [{"rank": 0}]}
    ))
    results = spawn_ranks(
        ["-c", "import time; time.sleep(6); print('ok')"],
        nprocs=2, timeout=60, health_dir=tmp_path, stall_grace_s=2.0,
    )
    assert all(p.returncode == 0 for p, _ in results)
    assert results.report.watchdog_verdicts == [], results.report.events
    assert not (tmp_path / "heartbeat-rank0.json").exists()
    assert not (tmp_path / "postmortem-rank0.json").exists()
    assert not (tmp_path / "postmortem-rank0.traceback").exists()
    assert not (tmp_path / "postmortem").exists(), \
        "clean reruns leave no bundle — last incident's dir is cleared"


# ---------------------------------------------------------------------------
# stall fault parsing (satellite 2)
# ---------------------------------------------------------------------------


def test_stall_fault_spec_parses_and_requires_trigger():
    from rocm_mpi_tpu.resilience import faults

    plan = faults.FaultPlan.parse("stall@step=14,rank=1")
    (clause,) = plan.clauses
    assert clause.kind == "stall" and clause.step == 14 and clause.rank == 1
    with pytest.raises(ValueError, match="step=K or segment=N"):
        faults.FaultPlan.parse("stall")
