"""Property-based test of the SWE workload's sharded paths (hypothesis):
for arbitrary shapes, mesh dims, and step counts, the shard_map + pytree
halo 'perf' path must reproduce the transparent numpy forward-backward
oracle, and mass must stay exactly conserved — the machine-checked
generalization of test_swe.py's hand-picked cases (the same §5.2-analog
strategy as tests/test_halo_properties.py)."""

from __future__ import annotations

import os

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from rocm_mpi_tpu.models.swe import ShallowWater  # noqa: E402

# Sibling test module (tests/ has no __init__; pytest's default
# prepend-import puts this directory on sys.path during collection).
from test_swe import _cfg, _numpy_fb  # noqa: E402


@st.composite
def swe_cases(draw):
    ndim = draw(st.integers(2, 3))
    dims, shape = [], []
    budget = 8  # device budget (conftest provides 8)
    for _ in range(ndim):
        d = draw(st.sampled_from([1, 2, 4]))
        while d > 1 and d * int(np.prod(dims or [1])) > budget:
            d //= 2
        local = draw(st.integers(3, 6))
        dims.append(d)
        shape.append(d * local)
    n_steps = draw(st.integers(1, 12))
    return tuple(shape), tuple(dims), n_steps


@given(swe_cases())
@settings(max_examples=int(os.environ.get("RMT_PROP_EXAMPLES", "20")),
          deadline=None)
def test_swe_perf_matches_oracle_property(case):
    shape, dims, n_steps = case
    cfg = _cfg(shape=shape, dims=dims, nt=max(n_steps, 2) + 1, warmup=0)
    model = ShallowWater(cfg)
    h0, us0 = model.init_state()
    mass0 = float(np.sum(np.asarray(h0, dtype=np.float64)))
    ref_h, ref_us = _numpy_fb(
        h0, us0, cfg.dt, cfg.spacing, cfg.H0, cfg.g, n_steps
    )
    got_h, got_us = model.advance_fn("perf")(
        h0, us0, model.face_masks(), n_steps
    )
    np.testing.assert_allclose(
        np.asarray(got_h), ref_h, rtol=1e-11, atol=1e-13
    )
    for gu, ru in zip(got_us, ref_us):
        np.testing.assert_allclose(np.asarray(gu), ru, atol=1e-12)
    mass = float(np.sum(np.asarray(got_h, dtype=np.float64)))
    assert abs(mass - mass0) <= 1e-12 * max(abs(mass0), 1.0)
