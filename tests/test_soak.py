"""The chaos soak (docs/RESILIENCE.md §8; ROADMAP item 5).

Covers the soak-report schema (rmt-soak-report v1: validator, atomic
writer, regress --check-schema recognition, doctored gates), the SLO
aggregation from real telemetry streams (latency dedup across ranks,
deadline-miss accounting, interpolating percentiles), and THE
acceptance drill: a bounded `apps/soak.py` run — the rolling fault
schedule composing the queue, lane, and infrastructure planes,
gloo-real on 2 ranks — exits 0 with a schema-valid report whose SLO
block is populated from real telemetry.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

from rocm_mpi_tpu.serving import slo  # noqa: E402


# ---------------------------------------------------------------------------
# SLO aggregation
# ---------------------------------------------------------------------------


def test_percentile_interpolates():
    assert slo.percentile([], 50) is None
    assert slo.percentile([3.0], 99) == 3.0
    assert slo.percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    assert slo.percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0
    assert slo.percentile([1.0, 2.0, 3.0, 4.0], 0) == 1.0


def _event_line(rid, latency, miss=False):
    return json.dumps({
        "kind": "event", "v": 2, "name": "serve.request.done",
        "t": 1.0, "request_id": rid, "latency_s": latency,
        "deadline_miss": miss,
    })


def test_latencies_dedupe_across_rank_streams(tmp_path):
    """In a multi-controller service every rank emits the same done
    event: one request is ONE observation, and torn tails are
    tolerated (live JSONL streams)."""
    r0 = tmp_path / "telemetry-rank0.jsonl"
    r1 = tmp_path / "telemetry-rank1.jsonl"
    r0.write_text(
        _event_line("a", 0.5) + "\n" + _event_line("b", 1.5, miss=True)
        + "\n"
    )
    r1.write_text(
        _event_line("a", 0.5) + "\n" + _event_line("b", 1.5, miss=True)
        + "\n" + '{"torn'
    )
    facts = slo.latencies_from_streams([r0, r1])
    assert facts["latencies"] == {"a": 0.5, "b": 1.5}
    assert facts["deadline_missed_done"] == ["b"]

    block = slo.slo_block(
        {"submitted": 4, "completed": 2, "failed": 0, "rejected": 1,
         "expired": 1, "quarantined": 0, "retries": 0},
        [r0, r1],
    )
    assert block["latency_s"]["n"] == 2
    assert block["latency_s"]["p50"] == 1.0
    # misses = 1 expired pending + 1 late completion, over 4 submitted
    assert block["deadline_misses"] == 2
    assert block["deadline_miss_rate"] == 0.5


# ---------------------------------------------------------------------------
# Report schema
# ---------------------------------------------------------------------------


def _valid_doc(tmp_path):
    streams = tmp_path / "telemetry-rank0.jsonl"
    streams.write_text(_event_line("a", 0.25) + "\n")
    block = slo.slo_block(
        {"submitted": 1, "completed": 1, "failed": 0, "rejected": 0,
         "expired": 0, "quarantined": 0, "retries": 0},
        [streams],
    )
    return slo.soak_report_doc(
        [{"name": "serve-chaos", "mode": "in-process", "ok": True}],
        block, bounded=True, accounting_ok=True,
        fault_kinds=["lane-nan", "kill"],
    )


def test_soak_report_roundtrip_and_gate(tmp_path):
    doc = _valid_doc(tmp_path)
    assert slo.validate_soak_report(doc) == []
    path = tmp_path / "soak-report.json"
    slo.write_soak_report(path, doc)
    assert path.is_file() and not (tmp_path / "soak-report.json.tmp").exists()

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([path]) == []

    # an UNPOPULATED SLO block (no latency observations) fails — a
    # soak that banked no telemetry proves nothing
    empty = _valid_doc(tmp_path)
    empty["slo"]["latency_s"] = {"n": 0, "p50": None, "p99": None}
    assert any("populated" in p for p in slo.validate_soak_report(empty))
    with pytest.raises(ValueError, match="populated"):
        slo.write_soak_report(tmp_path / "never.json", empty)

    # doctored rate / missing episode verdict fail the gate
    bad = _valid_doc(tmp_path)
    bad["slo"]["deadline_miss_rate"] = 1.7
    bad_path = tmp_path / "bad-soak-report.json"
    bad_path.write_text(json.dumps(bad))
    assert any("deadline_miss_rate" in p for p in check_schema([bad_path]))
    bad2 = _valid_doc(tmp_path)
    del bad2["episodes"][0]["ok"]
    bad2_path = tmp_path / "bad2-soak-report.json"
    bad2_path.write_text(json.dumps(bad2))
    assert any("ok" in p for p in check_schema([bad2_path]))


def test_slo_fields_pinned_against_queue_terminals():
    """The SLO block's terminal outcomes are the queue's terminal
    states (plus the submitted/retries bookkeeping) — spelled flat in
    slo.py for the stdlib read side; drift fails here."""
    from rocm_mpi_tpu.serving.queue import TERMINAL_STATES

    # done <-> completed is the one deliberate rename
    assert set(slo.SLO_COUNT_FIELDS) == {
        "submitted", "retries", "done", "failed", "rejected", "expired",
        "quarantined",
    }
    assert set(TERMINAL_STATES) == {
        "done", "failed", "rejected", "expired", "quarantined",
    }


# ---------------------------------------------------------------------------
# THE acceptance drill
# ---------------------------------------------------------------------------


def test_bounded_soak_acceptance(tmp_path):
    """THE ISSUE-14 acceptance: a bounded apps/soak.py run — the
    rolling fault schedule composing the queue plane (flood, deadline
    expiry, NaN quarantine, breaker recovery), the storage plane
    (io-error/io-slow/enospc through session saves), a real SIGTERM
    eviction, and gloo-real 2-rank serve + kill episodes — exits 0
    with a schema-valid soak-report.json whose SLO block is populated
    from real telemetry."""
    out = tmp_path / "soak"
    proc = subprocess.run(
        [sys.executable, str(REPO / "apps" / "soak.py"),
         "--bounded", "--cpu-devices", "2", "--out", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (proc.stdout[-3000:],
                                  proc.stderr[-3000:])
    doc = json.loads((out / "soak-report.json").read_text())
    assert slo.validate_soak_report(doc) == []

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([out / "soak-report.json",
                         out / "quarantine.jsonl"]) == []

    names = {ep["name"]: ep for ep in doc["episodes"]}
    assert set(names) == {"serve-chaos", "pipeline", "swap", "breaker",
                          "storage", "evict", "fleet", "gloo-serve",
                          "gloo-kill"}
    # the swap episode re-seated lanes at segment boundaries and
    # quarantined exactly the poisoned swapped-in lane
    assert names["swap"]["swaps_in"] >= 1, names["swap"]
    assert names["swap"]["counters"]["quarantined"] == 1, names["swap"]
    # the pipeline episode proved overlap does not reorder accounting
    assert "bubble" in names["pipeline"], names["pipeline"]
    # the fleet episode killed replica 1 mid-traffic and re-routed
    assert names["fleet"]["killed"] == [1], names["fleet"]
    assert names["fleet"]["rerouted"] >= 1, names["fleet"]
    assert all(ep["ok"] for ep in doc["episodes"]), doc["episodes"]
    assert doc["accounting_ok"] is True

    # the fleet sidecars banked schema-valid (the merged report's
    # inner structure is validated by check_schema's dispatch)
    assert check_schema([out / "fleet-report.json",
                         out / "fleet-journal.jsonl"]) == []

    # request tracing composed with the failover: the fleet episode
    # banked a schema-valid two-hop trace report for the rerouted
    # request, and the SLO block decomposes tail latency per stage
    # (docs/TELEMETRY.md "Request tracing")
    trace_reports = sorted(out.glob("trace-report-*.json"))
    assert trace_reports, "fleet episode banked no trace report"
    assert check_schema(trace_reports) == []
    tr = json.loads(trace_reports[0].read_text())
    assert tr["hops"] == [0, 1] and tr["terminal"] == "done", tr
    dec = doc["slo"]["decomposition"]
    assert dec["n"] >= 8 and dec["hops"]["rerouted"] >= 1, dec
    assert {"queue_wait", "device"} <= set(dec["stages"]), dec

    # the SLO block is populated from REAL telemetry
    assert doc["slo"]["latency_s"]["n"] >= 8
    assert doc["slo"]["latency_s"]["p50"] > 0
    assert doc["slo"]["quarantined"] >= 1
    assert doc["slo"]["rejected"] >= 2
    assert doc["slo"]["expired"] >= 2
    assert doc["slo"]["retries"] >= 1
    assert 0.0 < doc["slo"]["deadline_miss_rate"] < 1.0

    # every plane actually composed — the fleet plane included
    assert {"lane-nan", "batch-error", "slow-batch", "queue-flood",
            "io-error", "io-slow", "enospc", "sigterm",
            "kill", "replica-kill"} <= set(doc["fault_kinds"])

    # the poison ledger carries a reproducible full record
    from rocm_mpi_tpu.serving.queue import (
        load_quarantine,
        request_from_record,
    )

    records = load_quarantine(out / "quarantine.jsonl")
    assert records
    assert request_from_record(records[0]["request"]).workload
