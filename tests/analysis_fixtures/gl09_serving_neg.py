"""GL09 true negatives for the request-plane sidecars (ISSUE 14): the
two committed disciplines as the real writers spell them —
serving/queue.append_quarantine (append-only JSONL) and
serving/slo.write_soak_report (tmp+rename).

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json
import os


def append_quarantine_record(path, doc):
    # Append-only: the incident ledger's discipline — a torn final line
    # is droppable, every complete line stays valid, nothing banked is
    # ever rewritten.
    record = {"schema": "rmt-serve-quarantine", "v": 1, **doc}
    with open(path, "a") as fh:
        fh.write(json.dumps(record) + "\n")


def write_soak_report_atomic(path, doc):
    # tmp + os.replace: the reference shape (serving/slo.py).
    record = {"schema": "rmt-soak-report", "v": 1, **doc}
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(record, fh)
    os.replace(tmp, path)
