"""GL02 true negatives, serving-pipeline edition: the SHIPPED
chokepoint shapes. The drain pipeline's stage accounting and stage
hooks mutate INSTANCE state from plain host-side methods — after the
dispatch returns, outside every traced region — which is the legal
form (serving/service.SimulationService._prepare_batch /
_resolve_batch / _stage_hook)."""

import time

import jax


class PipelineAccounting:
    """The _pipe/_note_dispatched shape: instance-attr mutation from
    untraced host methods."""

    def __init__(self):
        self.busy_s = 0.0
        self.inflight = 0
        self.since = None

    def note_dispatched(self):
        if self.inflight == 0:
            self.since = time.monotonic()
        self.inflight += 1

    def note_fetched(self):
        self.inflight -= 1
        if self.inflight == 0 and self.since is not None:
            self.busy_s += time.monotonic() - self.since
            self.since = None


def resolve_hook(stage, info):
    """The stage-callback contract: a HOST-side callable fired after
    the stage — free to sleep, log, or mutate its own closure."""
    time.sleep(0.0)
    return (stage, dict(info))


@jax.jit
def pure_batched_step(x, *, lane_steps=None):
    # the pipeline's traced half stays pure: per-lane variation is
    # traced DATA (lane_steps), never a host-state read-back
    return x * 2 if lane_steps is None else x + 1
