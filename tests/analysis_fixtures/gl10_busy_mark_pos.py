"""Twin of the PR-15 busy-mark bug, pre-fix shape (must fire GL10).

The shipped bug: the drain pipeline marked `_inflight_n` busy BEFORE
invoking the raising stage hook — one hook exception and the device-
bubble gauge read 1.0 forever. Re-staged here with the mark under an
explicit lock acquire: the raising hook now leaks the LOCK too, which
is the same ordering mistake with a worse blast radius.
"""

import threading


class DrainPipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight_n = 0

    def _note_fetched(self):
        with self._lock:
            self._inflight_n -= 1

    def _note_aborted(self):
        with self._lock:
            self._inflight_n = 0

    def _prepare_batch(self, stage_hook, tickets):
        self._lock.acquire()
        self._inflight_n += len(tickets)  # busy-mark FIRST
        stage_hook("dispatch", n=len(tickets))  # raising hook: lock leaks
        self._lock.release()
