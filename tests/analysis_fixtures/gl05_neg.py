"""GL05 true negatives: matching literals, and variables (not judged)."""

import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rocm_mpi_tpu.utils.compat import shard_map


def build(devices, x, axis_names):
    mesh = Mesh(np.array(devices), ("gx", "gy"))

    def body(block):
        total = lax.psum(block, "gy")  # literal, in the mesh
        rolled = lax.ppermute(
            block, axis_names[0], [(0, 1)]
        )  # variable axis: skipped
        return total + rolled

    return shard_map(
        body, mesh, in_specs=(P("gx", "gy"),), out_specs=P("gx", "gy"),
        check_vma=False,
    )(x)
