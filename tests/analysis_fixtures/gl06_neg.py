"""GL06 true negative: the sanctioned idioms — telemetry spans, a
labeled Timer, monotonic deadlines, and sleeps — plus non-time lookalikes."""

import time

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.utils import metrics


def timed_run(advance, state, n):
    with telemetry.span("step_window", phase="step", steps=n) as sp:
        state = advance(state, n)
        sp.sync(state)
    return state


def timed_run_timer(advance, state, n):
    timer = metrics.Timer(label="step_window", steps=n)
    timer.tic(state)
    state = advance(state, n)
    timer.toc(state)
    return state, timer.elapsed


def budget_loop(work, budget_s):
    # Deadline control flow, not measurement: monotonic is the right tool.
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        work()
        time.sleep(0.1)


class Clock:
    def time(self):
        return 0.0


def not_the_time_module(clock: Clock):
    return clock.time()  # attribute named `time` on a non-module object
