"""GL09 true positives for the request-plane sidecars (ISSUE 14): the
doctored in-place twins of the REAL quarantine and soak-report writers
(serving/queue.append_quarantine is append-only; serving/slo.
write_soak_report is tmp+rename — these twins drop the discipline and
must fire).

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json


def write_quarantine_in_place(directory, records):
    # The doctored twin of append_quarantine: REWRITES the whole poison
    # ledger in "w" mode — a reader tailing the incident trail mid-write
    # sees a torn file, and every previously-banked line is at risk.
    path = f"{directory}/quarantine.jsonl"
    with open(path, "w") as fh:  # GL09
        for doc in records:
            fh.write(json.dumps(doc) + "\n")


def write_soak_report_in_place(path, episodes, slo):
    # The doctored twin of slo.write_soak_report: dumps the
    # schema-versioned report straight onto the final path — the one
    # artifact a multi-hour soak leaves behind, torn by a mid-write flap.
    doc = {"schema": "rmt-soak-report", "v": 1, "episodes": episodes,
           "slo": slo}
    with open(path, "w") as fh:  # GL09
        json.dump(doc, fh)


def write_quarantine_by_name(directory, line):
    # Even with an opaque payload, the path names the quarantine family:
    # evidence enough (write_text form).
    target = directory / "quarantine.jsonl"
    target.write_text(json.dumps(line))  # GL09
