"""GL04 wire-seam true positives: arithmetic on a received
reduced-precision slab without the f32 upcast at the seam
(docs/ANALYSIS.md#gl04; parallel/wire.py owns the codec)."""

import jax.numpy as jnp

from rocm_mpi_tpu.parallel.halo import neighbor_shift


def bad_direct_downcast(u, name):
    # Payload downcast at the ship call; the received slab is consumed
    # raw by seam arithmetic — GL04 fires.
    ghost = neighbor_shift(u.astype(jnp.bfloat16), name, +1)
    return ghost + u


def bad_named_payload(u, name):
    # The downcast marker propagates through the payload name.
    payload = u.astype(jnp.bfloat16)
    ghost = neighbor_shift(payload, name, -1)
    return u - ghost * 2.0
