"""GL04 true negatives: the repo's kernel conventions, followed."""

import functools

import jax
import jax.numpy as jnp
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu


def _upcast_for_compute(*arrays):
    if arrays[0].dtype == jnp.bfloat16:
        return tuple(a.astype(jnp.float32) for a in arrays)
    return arrays


def _good_kernel(a_ref, b_ref, o_ref, *, scale):
    a, b = _upcast_for_compute(a_ref[:], b_ref[:])
    zg = jnp.zeros_like(a)  # helper built from the upcast value
    ndim = len(a_ref.shape)  # .shape on a bare ref is metadata, fine
    combined = jnp.concatenate([a, zg], axis=0)
    o_ref[:] = (combined[: a.shape[0]] + scale * b * ndim).astype(
        o_ref.dtype
    )


def launch(a, b):
    return pl.pallas_call(
        functools.partial(_good_kernel, scale=2.0),
        grid=(4,),
        in_specs=[
            pl.BlockSpec((8,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((8,), lambda i: (i,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8,), lambda i: (i,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32,), "float32"),
    )(a, b)
