"""GL05 true negatives (batch-axis vocabulary, docs/SERVING.md):
reductions over the 'batch' lane axis are legitimate cross-lane
diagnostics, and permutes over a SPACE axis are the halo exchange
working as designed."""

import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rocm_mpi_tpu.utils.compat import shard_map


def build(devices, x):
    mesh = Mesh(np.array(devices).reshape(2, -1), ("batch", "gx"))

    def body(block):
        lane_sum = lax.psum(block, "batch")  # cross-lane reduction: fine
        ghost = lax.ppermute(block, "gx", [(0, 1)])  # space halo: fine
        return lane_sum + ghost

    return shard_map(
        body, mesh, in_specs=(P("batch", "gx"),),
        out_specs=P("batch", "gx"), check_vma=False,
    )(x)
