"""GL04 true positives: bare refs, skipped upcast, arity/coverage bugs."""

import functools

import jax.numpy as jnp
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu


def _upcast_for_compute(*arrays):
    return tuple(a.astype(jnp.float32) for a in arrays)


def _bad_bare_ref_kernel(x_ref, o_ref):
    o_ref[:] = jnp.tanh(x_ref)  # GL04: ref passed bare to a jnp op


def _bad_raw_precision_kernel(a_ref, b_ref, o_ref):
    # GL04: arithmetic straight off the refs, no f32 upcast (bf16 inputs
    # would quantize per step — the r4 frozen-trajectory bug)
    o_ref[:] = (a_ref[:] + b_ref[:]).astype(o_ref.dtype)


def _ok_kernel(a_ref, o_ref):
    (a,) = _upcast_for_compute(a_ref[:])
    o_ref[:] = (a * 2.0).astype(o_ref.dtype)


def launch(x, a, b):
    one = pl.pallas_call(
        _bad_bare_ref_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
    two = pl.pallas_call(
        _bad_raw_precision_kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
    )(a, b)
    # GL04: index_map arity 1 vs grid rank 2
    three = pl.pallas_call(
        functools.partial(_ok_kernel),
        grid=(4, 4),
        in_specs=[
            pl.BlockSpec((8, 8), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, 8), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32, 32), "float32"),
    )(a)
    # GL04: grid (2,) x block (8,) covers 16 of 32 rows
    four = pl.pallas_call(
        functools.partial(_ok_kernel),
        grid=(2,),
        in_specs=[pl.BlockSpec((8,), lambda i: (i,),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8,), lambda i: (i,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32,), "float32"),
    )(a)
    return one, two, three, four


import jax  # noqa: E402  (fixture: parsed, never imported)
