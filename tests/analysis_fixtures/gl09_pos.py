"""GL09 true positives: schema-versioned artifacts written in place.

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json


def write_status_torn(path, step):
    # In-place dump of a schema-carrying document: a reader observing
    # mid-write sees torn JSON.
    doc = {"schema": "rmt-status", "v": 1, "step": step}
    with open(path, "w") as fh:  # GL09
        json.dump(doc, fh)


def write_heartbeat_torn(directory, rank, payload):
    # Path names a committed artifact family — evidence enough even
    # though the payload dict is opaque here.
    path = f"{directory}/heartbeat-rank{rank}.json"
    with open(path, "w") as fh:  # GL09
        fh.write(json.dumps(payload))


def write_manifest_torn(path, manifest_doc):
    # write_text straight onto the final path: same torn window.
    target = path / "manifest-000100.json"
    target.write_text(json.dumps(manifest_doc))  # GL09


def write_heartbeat_pathlib_torn(directory, rank, payload):
    # The method form (`Path.open("w")`) is the same torn window as
    # builtin open — the receiver is the path, the mode is args[0].
    target = directory / f"heartbeat-rank{rank}.json"
    with target.open("w") as fh:  # GL09
        json.dump(payload, fh)


def write_tmp_never_published(path, doc):
    # Half the discipline is none of it: the tmp file is written but
    # never renamed over the final path — the artifact never publishes
    # (and a stale old version keeps vouching for the wrong state).
    record = {"kind": "rmt-tuning-cache", "v": 1, "entries": doc}
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:  # GL09: no rename anywhere in scope
        json.dump(record, fh)
