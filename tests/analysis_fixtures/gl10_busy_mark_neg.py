"""Twin of the PR-15 busy-mark bug, shipped-fix shape (GL10-clean).

The fix ordering: the raising stage hook runs BEFORE the busy-mark,
and the mark itself sits in a plain `with` region (no explicit
acquire/release to leak).
"""

import threading


class DrainPipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight_n = 0

    def _note_fetched(self):
        with self._lock:
            self._inflight_n -= 1

    def _note_aborted(self):
        with self._lock:
            self._inflight_n = 0

    def _prepare_batch(self, stage_hook, tickets):
        stage_hook("dispatch", n=len(tickets))  # hook first: a raise
        with self._lock:                        # leaves nothing marked
            self._inflight_n += len(tickets)
