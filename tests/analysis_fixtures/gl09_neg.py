"""GL09 true negatives: the committed disciplines (tmp+rename and
append-only), plus writes that are not schema-versioned artifacts.

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json
import os


def write_doc_atomic(path, doc):
    # The reference shape (tuning/cache.write_doc): tmp + os.replace.
    record = {"kind": "rmt-tuning-cache", "v": 1, "entries": doc}
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(record, fh)
    os.replace(tmp, path)


def write_manifest_atomic(path, manifest_doc):
    # The pathlib shape (utils/checkpoint.write_manifest).
    target = path / "manifest-000100.json"
    tmp = target.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(manifest_doc))
    tmp.replace(target)


def write_heartbeat_pathlib_atomic(directory, rank, payload):
    # The Path.open("w") form of the discipline: write the tmp-named
    # sibling, then rename over the final path.
    target = directory / f"heartbeat-rank{rank}.json"
    tmp = directory / f"heartbeat-rank{rank}.json.tmp"
    with tmp.open("w") as fh:
        json.dump(payload, fh)
    tmp.replace(target)


def append_elastic_event(root, rec):
    # Append-only JSONL: a torn final line is droppable; every complete
    # line stays valid (telemetry/health.py's elastic.jsonl).
    record = {"schema": "rmt-elastic-event", "v": 1, **rec}
    with open(root / "elastic.jsonl", "a") as fh:
        fh.write(json.dumps(record) + "\n")


def write_scratch_notes(path, rows):
    # Not a schema-versioned artifact (no schema/kind/version marker, no
    # artifact-family name): out of GL09's scope by design.
    with open(path, "w") as fh:
        json.dump({"rows": rows}, fh)


def read_cache(path):
    # Reads are never writes.
    with open(path) as fh:
        return json.load(fh)
