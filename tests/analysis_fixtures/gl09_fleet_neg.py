"""GL09 true negatives for the fleet sidecars (ISSUE 16): the two
committed disciplines as the real writers spell them —
serving/journal.TicketJournal (append-only JSONL segments) and
serving/journal.write_fleet_report (tmp+rename).

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json
import os


def append_journal_record(path, doc):
    # Append-only: the ticket journal's discipline — a torn final line
    # is droppable at replay, every complete line stays valid, nothing
    # banked is ever rewritten (single writer: the router).
    record = {"schema": "rmt-fleet-journal", "v": 1, **doc}
    with open(path, "a") as fh:
        fh.write(json.dumps(record) + "\n")


def write_fleet_report_atomic(path, doc):
    # tmp + os.replace: the reference shape (serving/journal.py).
    record = {"schema": "rmt-fleet-report", "v": 1, **doc}
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(record, fh)
    os.replace(tmp, path)
