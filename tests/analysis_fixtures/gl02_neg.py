"""GL02 true negatives: module-own globals in plain (untraced) functions,
instance attribute writes, and explicit trace-time kwargs."""

import jax

_PLAN = None
_ENV_CONSUMED = False


def configure(plan):  # plain host-side function: module-own global is fine
    global _PLAN, _ENV_CONSUMED
    _PLAN = plan
    _ENV_CONSUMED = True


class Holder:
    def __init__(self):
        self.knob = "eqc"

    def set_knob(self, value):
        self.knob = value  # instance attr, not a module


@jax.jit
def pure_step(x, *, body_form="eqc"):
    # the PR-1 fix idiom: the switch is a trace-time kwarg, no global
    return x * (2 if body_form == "eqc" else 3)
