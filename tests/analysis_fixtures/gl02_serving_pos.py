"""GL02 true positives, serving-pipeline edition (ISSUE 15 satellite):
host-side service state mutated from INSIDE a traced/async region. The
drain pipeline's stage callbacks (ServeConfig.stage_hooks) run on the
host AFTER each stage by contract — a "hook" that instead pokes service
or module state from a jitted body runs once at trace time and is
silently skipped by every cached-program reuse, exactly the stale-global
class GL02 exists for."""

import jax
import rocm_mpi_tpu.serving.service as serving_service

_BUBBLE_MARKS = 0


@jax.jit
def fetch_stage_with_state_write(x):
    global _BUBBLE_MARKS  # GL02: bubble accounting in a traced body
    _BUBBLE_MARKS = _BUBBLE_MARKS + 1
    return x * 2


@jax.jit
def resolve_stage_with_cross_module_write(x):
    # GL02 (cross-module mutation): stamping the service module's
    # pipeline state from a traced body — the next reuse of this
    # compiled program never re-runs the write, so the "accounting"
    # freezes at trace time.
    serving_service._PIPELINE_STAGE = "resolve"
    return x + 1
