"""Twin of the PR-14 N-writer quarantine bug, pre-fix shape (must
fire GL10).

The shipped bug: every rank of a multi-controller service appended its
own copy of each poison record to the same quarantine.jsonl — N
identical writers interleaving a ledger that is only a ledger with one
writer. The append here lives in an ordinary service method, outside
any owning `append_*` helper or *Journal/*Ledger/*Writer class.
"""

import json


class ServiceRank:
    def __init__(self, out_dir):
        self.out_dir = out_dir

    def quarantine(self, doc):
        # every rank executes this — N appenders on one sidecar
        with open(self.out_dir + "/quarantine.jsonl", "a") as fh:
            fh.write(json.dumps(doc, sort_keys=True) + "\n")
