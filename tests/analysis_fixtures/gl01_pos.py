"""GL01 true positives: read-after-donate and save/advance overlap.

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=0)
def advance(state, n):
    return state + n


def reads_after_donate(state):
    out = advance(state, 4)
    return out + state.sum()  # GL01: state was donated


def rebinds_during_async_save(advance_fn, state, directory):
    mgr = make_manager(directory)
    for step in range(10):
        state = advance_fn(state, 1)  # GL01 (pass 2): save still in flight
        mgr.save(step, args=state)
    return state


def reshards_after_donate(restored, new_grid):
    # The elastic-resume hazard: a restored state stepped with the
    # donating advance, then handed to the reshard gather — which READS
    # every leaf of the already-donated buffer.
    stepped = advance(restored, 1)  # donates `restored`
    slabs = gather_slabs(restored)  # GL01: restored was donated
    return stepped, scatter_slabs(slabs, new_grid)


def gather_slabs(state):
    return list(state)


def scatter_slabs(slabs, grid):
    return tuple(slabs)


def make_manager(directory):
    return CheckpointManager(directory)


class CheckpointManager:
    def __init__(self, directory):
        self.directory = directory

    def save(self, step, args=None):
        pass

    def wait_until_finished(self):
        pass
