"""GL05 true positive (batch-axis vocabulary, docs/SERVING.md): a
halo/permutation collective issued over the multi-tenant 'batch' lane
axis — the axis IS in the mesh vocabulary, but permuting over it moves
one tenant's state into another's lane."""

import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rocm_mpi_tpu.utils.compat import shard_map


def build(devices, x):
    mesh = Mesh(np.array(devices).reshape(2, -1), ("batch", "gx"))

    def body(block):
        # GL05: ppermute over the lane axis = cross-tenant leak.
        leaked = lax.ppermute(block, "batch", [(0, 1)])
        return leaked

    return shard_map(
        body, mesh, in_specs=(P("batch", "gx"),),
        out_specs=P("batch", "gx"), check_vma=False,
    )(x)
