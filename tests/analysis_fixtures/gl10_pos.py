"""GL10 true positives: every concurrency-discipline facet violated.

Each class below breaks exactly one of the conventions the serving
control plane hand-enforces (docs/ANALYSIS.md#gl10). Nothing here may
trip another rule family — the fixture harness asserts GL10 fires
alone (time.monotonic/sleep are GL06-clean on purpose).
"""

import json
import threading
import time


class LeakyGauge:
    """(a) guarded-attribute read outside the lock, (b1) *_locked
    helper called without the lock, (d) blocking under the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def inc(self):
        with self._lock:
            self._n += 1

    def dec(self):
        with self._lock:
            self._n -= 1

    def peek(self):
        # _n is mutated under _lock in two regions above: it is
        # lock-guarded, and this unlocked read races both writers.
        return self._n

    def _drain_locked(self):
        return self._n

    def snapshot(self):
        # the *_locked convention says the caller holds the lock; no
        # lock is held on this path.
        return self._drain_locked()

    def slow_inc(self):
        with self._lock:
            time.sleep(0.01)  # blocking while every inc()/dec() waits
            self._n += 1


class OrderedWrong:
    """(c) lock-order cycle: ab() takes _a then _b, ba() takes _b
    then _a — two threads deadlock."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass


class BusyMark:
    """(b2) explicit acquire with a call site before the release and
    no try/finally — a raising hook leaks the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0
        self._spill = 0

    def mark(self, hook):
        self._lock.acquire()
        self._inflight += 1
        hook("dispatch")
        self._lock.release()

    def unmark(self):
        with self._lock:
            self._inflight -= 1

    def spill(self):
        with self._lock:
            self._spill += 1

    def respill(self):
        with self._lock:
            self._spill += 1


class FleetFrontend:
    """(f) append-mode open of a quarantine sidecar outside any owning
    writer — N of these interleave records (the PR-14 shape)."""

    def bank_poison(self, root, doc):
        with open(root + "/quarantine.jsonl", "a") as fh:
            fh.write(json.dumps(doc) + "\n")
