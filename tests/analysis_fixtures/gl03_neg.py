"""GL03 true negatives: everything routed through the chokepoints."""

import jax
from rocm_mpi_tpu.utils.backend import set_cpu_device_count
from rocm_mpi_tpu.utils.compat import (
    axis_size,
    cost_analysis_dict,
    out_struct_like,
    pallas as pl,
    shard_map,
)


def clean(compiled, mesh, specs, exemplar):
    cost = cost_analysis_dict(compiled)
    set_cpu_device_count(8)
    n = axis_size("gx")
    struct = out_struct_like((8, 8), exemplar)
    jax.config.update("jax_platforms", "cpu")  # a knob compat does not own
    return cost, n, struct
