"""GL03 true positives: every raw spelling the compat chokepoints own."""

import jax
from jax import lax
from jax import shard_map  # GL03: version-specific home
from jax.experimental import pallas as pl  # GL03
from jax.experimental.shard_map import shard_map as sm  # GL03


def drifted(compiled, mesh, specs):
    cost = compiled.cost_analysis()  # GL03: list on 0.4.x, dict on newer
    jax.config.update("jax_num_cpu_devices", 8)  # GL03: no knob on 0.4.x
    n = lax.axis_size("gx")  # GL03: missing on 0.4.x
    struct = jax.ShapeDtypeStruct((8, 8), "float32", vma={"gx"})  # GL03
    f = jax.experimental.pjit  # GL03: attribute chain
    return cost, n, struct, f
