"""GL01 true negatives: the safe rebinding and save-then-wait idioms."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0, 1))
def advance(u_prev, u_curr, n):
    return u_curr, u_prev + n


def safe_rebinding(u_prev, u_curr):
    u_prev, u_curr = advance(u_prev, u_curr, 4)  # rebound: donation is fine
    u_prev, u_curr = advance(u_prev, u_curr, 8)
    return u_curr


def safe_segmented(advance_fn, state, directory):
    mgr = make_manager(directory)
    for step in range(10):
        state = advance_fn(state, 1)
        mgr.save(step, args=state)
        mgr.wait_until_finished()  # guard: save completes before reuse
    return state


def safe_reshard_order(restored, new_grid):
    # The safe elastic-resume shape: gather the slabs BEFORE any
    # donating step consumes the restored buffers, then step the
    # freshly-scattered copy (which is rebound every call).
    slabs = gather_slabs(restored)
    state = scatter_slabs(slabs, new_grid)
    state = advance(state, state, 1)
    return state


def gather_slabs(state):
    return list(state)


def scatter_slabs(slabs, grid):
    return tuple(slabs)


def branches_do_not_leak(state, flag):
    if flag:
        out = advance(state, state, 2)
    else:
        out = (state, state)
    return out


def make_manager(directory):
    return object()
