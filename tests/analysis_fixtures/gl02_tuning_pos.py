"""GL02 true positive, tuning edition (ISSUE 7 satellite): a tuning-cache
WRITE from inside a traced body. The cache is consumed at trace time
(read-only resolve — legal); mutating its module state from a traced
step is the stale-global hazard GL02 exists for — the write runs once at
trace time and every cached program reuse silently skips it."""

import jax
import rocm_mpi_tpu.tuning.resolve as tuning_resolve

_TUNED = None


@jax.jit
def step_with_cache_write(x):
    # GL02 (cross-module mutation): poking the resolve chokepoint's
    # snapshot from a traced body — the next reuse of this compiled
    # program never re-runs the write.
    tuning_resolve._STATE = {"doc": None}
    return x * 2


@jax.jit
def step_with_global_write(x):
    global _TUNED  # GL02: a "record the winner" global in a traced body
    _TUNED = {"chunk": 16}
    return x + 1
