"""GL07 true negative: the sanctioned signal idioms — constants,
delivery, and non-signal lookalikes; no handler installs."""

import os
import signal
import subprocess


def nudge_rank(proc: subprocess.Popen):
    # DELIVERING a signal is fine anywhere; only handler installation
    # is owned by telemetry/flight.py + resilience/.
    if hasattr(signal, "SIGUSR2"):
        proc.send_signal(signal.SIGUSR2)


def kill_by_pid(pid: int):
    os.kill(pid, signal.SIGTERM)


class Radio:
    def signal(self, strength):
        return strength * 2


def not_the_signal_module(radio: Radio):
    return radio.signal(3)  # attribute named `signal` on a non-module
