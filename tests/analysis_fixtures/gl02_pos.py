"""GL02 true positives: cross-module mutation + global write in traced body."""

import functools

import jax
import rocm_mpi_tpu.ops.pallas_kernels as pk

_CALLS = 0

pk.EQC_BODY_FORM = "conly"  # GL02: the old bench.py ladder hazard


def flip_knob(form):
    pk.VMEM_PAD_POW2 = form  # GL02: cross-module mutation in a helper too
    setattr(pk, "EQC_BODY_FORM", form)  # GL02: same via setattr


@jax.jit
def traced_counter(x):
    global _CALLS  # GL02: runs once at trace time, not per call
    _CALLS = _CALLS + 1
    return x * 2


def make_step():
    @functools.partial(jax.jit, donate_argnums=0)
    def step(x):
        global _CALLS  # GL02: traced body via partial-jit decorator
        _CALLS += 1
        return x + 1

    return step
