"""GL06 true positive: raw timing in non-owner code, all four spellings."""

import time
from time import perf_counter
from time import time as walltime


def timed_run(advance, state, n):
    t0 = time.perf_counter()        # GL06: module-attribute spelling
    state = advance(state, n)
    wtime = time.perf_counter() - t0
    stamp = time.time()             # GL06: wall-clock spelling
    return state, wtime, stamp


def timed_run_from_imports(advance, state, n):
    t0 = perf_counter()             # GL06: from-import alias
    state = advance(state, n)
    return state, perf_counter() - t0, walltime()  # GL06 ×2
