"""GL04 wire-seam true negatives: the decoded-slab discipline
(upcast/decode before any seam arithmetic), and full-precision ships
that never taint."""

import jax.numpy as jnp

from rocm_mpi_tpu.parallel.halo import neighbor_shift


def ok_upcast_at_seam(u, name):
    # The received slab is upcast BEFORE arithmetic — the contract.
    ghost = neighbor_shift(u.astype(jnp.bfloat16), name, +1)
    decoded = ghost.astype(jnp.float32)
    return decoded + u


def ok_inline_upcast(u, name):
    ghost = neighbor_shift(u.astype(jnp.bfloat16), name, -1)
    return u - ghost.astype(u.dtype) * 2.0


def ok_full_precision_ship(u, name):
    # Full-precision wire: nothing to decode, arithmetic is fine.
    ghost = neighbor_shift(u, name, +1)
    return ghost + u
