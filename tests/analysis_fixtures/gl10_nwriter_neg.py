"""Twin of the PR-14 N-writer quarantine bug, shipped-fix shape
(GL10-clean).

The fix: ONE owning `append_*` helper does the append, and callers
route through it behind a single-writer guard (rank 0 in the shipped
code) — the ledger keeps exactly one writer.
"""

import json


def append_quarantine(path, doc):
    """The owning writer: the only place the sidecar is appended."""
    with open(path, "a") as fh:
        fh.write(json.dumps(doc, sort_keys=True) + "\n")


class ServiceRank:
    def __init__(self, out_dir, rank):
        self.out_dir = out_dir
        self.rank = rank

    def quarantine(self, doc):
        if self.rank == 0:  # single-writer guard
            append_quarantine(self.out_dir + "/quarantine.jsonl", doc)
