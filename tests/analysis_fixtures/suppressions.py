"""Suppression-directive fixture: each violation is silenced a different way."""

from jax.experimental import pallas as pl  # graftlint: disable=GL03

# graftlint: disable-next=GL03
from jax.experimental import multihost_utils

from jax import shard_map  # this one stays a live GL03 finding
