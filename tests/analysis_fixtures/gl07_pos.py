"""GL07 true positive: handler installs and faulthandler outside the
health-plane owners, all flagged spellings."""

import faulthandler  # GL07: importing the capability
import signal
from signal import signal as install_handler


def hijack_sigusr2():
    signal.signal(signal.SIGUSR2, lambda *_: None)   # GL07: steals the hook
    faulthandler.enable()


def hijack_from_import():
    install_handler(signal.SIGTERM, lambda *_: None)  # GL07: alias spelling
