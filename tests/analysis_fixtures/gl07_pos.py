"""GL07 true positive: handler installs and faulthandler outside the
health-plane owners, all flagged spellings."""

import faulthandler  # GL07: importing the capability
import signal
from signal import signal as install_handler


def hijack_sigusr2():
    signal.signal(signal.SIGUSR2, lambda *_: None)   # GL07: steals the hook
    faulthandler.enable()


def hijack_from_import():
    install_handler(signal.SIGTERM, lambda *_: None)  # GL07: alias spelling


_DEADLINE = None


def stray_preemption_handler(grace_s: float):
    # The resilience.preempt SIGTERM grace-deadline pattern, copied
    # OUTSIDE the resilience/ owner dir: exactly the stray install the
    # GL07 seam must keep firing on — last install wins, so this copy
    # would silently disarm the real preemption plane (and the SIGUSR2
    # post-mortem hook keeps its own reasons to care).
    def _handler(signum, frame):
        global _DEADLINE
        _DEADLINE = grace_s

    signal.signal(signal.SIGTERM, _handler)  # GL07: preempt-shaped stray
