"""GL08 true positives: the PR-7 multi-controller cache-divergence
hazard and the PR-6 elastic rebuild-vs-reuse hazard, reconstructed.

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json

import jax


def cache_path():
    return "output/tuning/cache.json"


def load_tuned_chunk():
    # Under multi-controller jax every process reads ITS OWN filesystem:
    # the returned value is per-rank.
    with open(cache_path()) as fh:
        doc = json.load(fh)
    return doc.get("chunk")


def exchange(T):
    return jax.lax.ppermute(T, "x", [(0, 1)])


def scan_whole(T, n):
    for _ in range(n):
        T = exchange(T)
    return T


def scan_chunked(T, n, q):
    # A different chunking builds a different per-invocation collective
    # count — divergently traced programs across ranks.
    for _ in range(n):
        T = exchange(exchange(T))
    return T


def advance_auto(T, n):
    # PR-7 reconstruction (the shape models/diffusion.auto_scan_chunk
    # guards against): the resolved per-rank cache content picks the
    # program structure, and the two arms' collective sequences differ.
    chunk = load_tuned_chunk()
    if chunk:  # GL08: per-rank-file-content-dependent, arms differ
        return scan_chunked(T, n, chunk)
    return scan_whole(T, n)


def restore_elastic(state, new_dims):
    # PR-6 reconstruction: rank 0 re-gathers the slabs for the new mesh
    # while every other rank reuses its local shard — the rebuild arm's
    # collective never completes because the peers never enter it.
    if jax.process_index() == 0:  # GL08: rank-dependent, arms differ
        state = jax.lax.psum(state, "x")
    return state
