"""GL10 true negatives: the same shapes as gl10_pos.py, disciplined.

Locked accesses everywhere, *_locked called under the lock, one global
lock order, blocking moved outside lock regions, explicit acquire
released in a finally, Condition.wait on the held Condition (the one
blessed blocking call), and sidecar appends routed through owners.
"""

import json
import threading
import time


class Gauge:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def inc(self):
        with self._lock:
            self._n += 1

    def dec(self):
        with self._lock:
            self._n -= 1

    def peek(self):
        with self._lock:
            return self._n

    def _drain_locked(self):
        return self._n

    def snapshot(self):
        with self._lock:
            return self._drain_locked()

    def slow_inc(self):
        time.sleep(0.01)  # blocking OUTSIDE the lock region
        with self._lock:
            self._n += 1

    def marked(self, hook):
        hook("dispatch")  # the raising call runs before the lock
        self._lock.acquire()
        try:
            self._n += 1
        finally:
            self._lock.release()


class OrderedRight:
    """One global acquisition order: _a before _b, everywhere."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def also_ab(self):
        with self._a:
            with self._b:
                pass


class Waiter:
    """Condition.wait on the HELD Condition is what a Condition is
    for — never a blocking-under-lock finding."""

    def __init__(self):
        self._cond = threading.Condition()
        self._ready = False

    def wait_ready(self):
        with self._cond:
            while not self._ready:
                self._cond.wait()
            return True

    def set_ready(self):
        with self._cond:
            self._ready = True
            self._cond.notify_all()


def append_ticket_line(path, doc):
    """An `append_*` function owns its sidecar append."""
    with open(path, "a") as fh:
        fh.write(json.dumps(doc) + "\n")


class PoisonLedgerWriter:
    """A *Writer class owns its append; the path is data, not a second
    hardcoded writer."""

    def __init__(self, path):
        self.path = path

    def bank(self, doc):
        with open(self.path, "a") as fh:
            fh.write(json.dumps(doc) + "\n")
