"""GL05 true positive: collective over an axis name missing from the mesh."""

import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rocm_mpi_tpu.utils.compat import shard_map


def build(devices, x):
    mesh = Mesh(np.array(devices), ("gx",))

    def body(block):
        total = lax.psum(block, "gy")  # GL05: mesh only has 'gx'
        idx = lax.axis_index("gx")  # fine
        return total + idx

    return shard_map(
        body, mesh, in_specs=(P("gx"),), out_specs=P("gx"), check_vma=False
    )(x)
