"""File-wide suppression fixture."""
# graftlint: disable-file=GL03

from jax.experimental import pallas as pl
from jax.experimental import multihost_utils
from jax import shard_map  # GL02/GL01 etc would still fire; GL03 cannot
