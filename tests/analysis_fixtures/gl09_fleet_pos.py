"""GL09 true positives for the fleet sidecars (ISSUE 16): the
doctored in-place twins of the REAL journal and report writers
(serving/journal.TicketJournal appends; write_fleet_report is
tmp+rename — these twins drop the discipline and must fire).

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json


def write_journal_in_place(directory, records):
    # The doctored twin of TicketJournal._append: REWRITES the whole
    # ticket journal in "w" mode — the one artifact reconciliation
    # replays after a replica kill, torn exactly when it matters.
    path = f"{directory}/fleet-journal.jsonl"
    with open(path, "w") as fh:  # GL09
        for doc in records:
            fh.write(json.dumps(doc) + "\n")


def write_fleet_report_in_place(path, replicas, slo):
    # The doctored twin of journal.write_fleet_report: dumps the merged
    # report straight onto the final path — a mid-write flap leaves a
    # torn accounting verdict.
    doc = {"schema": "rmt-fleet-report", "v": 1, "replicas": replicas,
           "slo": slo}
    with open(path, "w") as fh:  # GL09
        json.dump(doc, fh)


def write_journal_by_name(directory, line):
    # Even with an opaque payload, the path names the fleet family:
    # evidence enough (write_text form).
    target = directory / "fleet-journal.jsonl"
    target.write_text(json.dumps(line))  # GL09
