"""GL08 true negatives: the SHIPPED fixes for the same hazards, plus
the legitimate rank-guarded host-only patterns.

Never imported — parsed only (tests/test_analysis_rules.py).
"""

import json

import jax


def cache_path():
    return "output/tuning/cache.json"


def load_tuned_chunk():
    with open(cache_path()) as fh:
        doc = json.load(fh)
    return doc.get("chunk")


def exchange(T):
    return jax.lax.ppermute(T, "x", [(0, 1)])


def scan_whole(T, n):
    for _ in range(n):
        T = exchange(T)
    return T


def scan_chunked(T, n, q):
    for _ in range(n):
        T = exchange(exchange(T))
    return T


def advance_auto_fixed(T, n):
    # The PR-7 fix shape: multi-controller processes never consult their
    # per-rank cache — the early return proves the continuation
    # single-controller, where file content cannot skew ranks.
    if jax.process_count() > 1:
        return scan_whole(T, n)
    chunk = load_tuned_chunk()
    if chunk:  # single-controller: legal
        return scan_chunked(T, n, chunk)
    return scan_whole(T, n)


def advance_auto_broadcast(T, n, chunk_local):
    # The other blessed fix: launder the per-rank decision through a
    # collective — broadcast results are uniform by construction.
    from rocm_mpi_tpu.utils.compat import multihost_utils

    chunk = multihost_utils.broadcast_one_to_all(chunk_local)
    if chunk:  # uniform: legal
        return scan_chunked(T, n, chunk)
    return scan_whole(T, n)


def write_manifest_rank0(state, directory):
    # Rank-guarded HOST-ONLY work (the write_manifest shape): no
    # collective under the branch, nothing to diverge.
    if jax.process_index() != 0:
        return None
    doc = {"leaves": len(state)}
    return directory, doc


def symmetric_early_exit(T):
    # Both paths issue the SAME collective sequence: rank-dependent
    # control flow without divergence.
    if jax.process_index() == 0:
        return exchange(T)
    return exchange(T)


def uniform_variant_branch(T, n, variant):
    # A branch on plain config: every rank takes the same arm, however
    # different the arms' collectives are.
    if variant == "deep":
        return scan_chunked(T, n, 8)
    return scan_whole(T, n)
