"""Driver entry-point self-tests: the compile-check and multi-chip dry run
the external driver performs, exercised in-repo so regressions surface in
CI rather than at judging time. Subprocesses, because dryrun_multichip must
own jax backend initialization (the in-process test backend is pinned to
the 8-device conftest configuration).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_py(code, env_extra=None, timeout=240):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, (
        f"exit {proc.returncode}:\n{proc.stdout}\n{proc.stderr}"
    )
    return proc.stdout


# n=8 is the driver's actual invocation and stays in the per-commit lane;
# the smaller meshes re-prove the same legs at different dims and move to
# the soak lane (VERDICT r4 #4 — keep coverage, cut the default gate).
@pytest.mark.parametrize(
    "n",
    [
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
        8,
    ],
)
def test_dryrun_multichip_self_provisioned(n):
    out = run_py(
        f"import __graft_entry__ as g; g.dryrun_multichip({n})"
    )
    assert "dryrun_multichip ok" in out


def test_dryrun_multichip_driver_flags():
    # The documented driver invocation: devices provided via XLA_FLAGS.
    out = run_py(
        "import __graft_entry__ as g; g.dryrun_multichip(8)",
        env_extra={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "JAX_PLATFORMS": "cpu",
        },
    )
    assert "dryrun_multichip ok" in out


def test_entry_compiles_and_runs():
    out = run_py(
        """
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
assert out.shape == args[0].shape
print("entry ok", out.shape)
"""
    )
    assert "entry ok" in out
