"""Pallas kernel parity (D7): whole-block, striped, multi-step, and the
'perf' model variant vs the jnp oracle (interpret mode on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import rocm_mpi_tpu.ops.pallas_kernels as pk
from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.ops.diffusion import step_fused, step_fused_padded
from rocm_mpi_tpu.ops.pallas_kernels import fused_multi_step, fused_step_padded


def _rand(shape, seed=0, dtype=jnp.float64):
    return jax.random.uniform(jax.random.PRNGKey(seed), shape, dtype=dtype)


def test_whole_block_matches_jnp():
    Tp = _rand((34, 30))
    Cp = 1.0 + _rand((32, 28), seed=1)
    args = (1.3, 1e-4, (0.1, 0.07))
    ref = step_fused_padded(Tp, Cp, *args)
    got = fused_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_striped_matches_jnp(monkeypatch):
    # Shrink the VMEM budget to force the row-striped path on a small grid.
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    Tp = _rand((66, 50))
    Cp = 1.0 + _rand((64, 48), seed=1)
    args = (1.0, 2e-4, (0.1, 0.1))
    ref = step_fused_padded(Tp, Cp, *args)
    got = fused_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_multi_step_matches_stepwise():
    T = _rand((32, 32))
    Cp = jnp.full((32, 32), 1.5, jnp.float64)
    args = (1.0, 1e-5, (0.1, 0.1))
    got = fused_multi_step(T, Cp, *args, n_steps=50)
    ref = T
    for _ in range(50):
        ref = step_fused(ref, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


def test_perf_variant_matches_ap_on_mesh():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=40, warmup=0, dims=(4, 2))
    model = HeatDiffusion(cfg)
    res_perf = model.run(variant="perf")
    res_ap = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_perf.T), np.asarray(res_ap.T), rtol=1e-13, atol=1e-15
    )


def test_vmem_resident_run_matches_ap():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=60, warmup=10, dims=(1, 1))
    model = HeatDiffusion(cfg)
    res_v = model.run_vmem_resident()
    res_ap = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_v.T), np.asarray(res_ap.T), rtol=1e-12, atol=1e-14
    )


def test_vmem_resident_rejects_sharded_grid():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=20, warmup=0, dims=(2, 2))
    with pytest.raises(ValueError, match="unsharded"):
        HeatDiffusion(cfg).run_vmem_resident()


def test_oversized_multi_step_rejected():
    T = jnp.zeros((2048, 2048), jnp.float64)  # 32 MB > budget
    with pytest.raises(ValueError, match="VMEM"):
        fused_multi_step(T, T, 1.0, 1e-5, (0.1, 0.1), 10)


def test_kp_padded_matches_jnp():
    from rocm_mpi_tpu.ops.pallas_kernels import kp_step_padded

    Tp = _rand((34, 30))
    Cp = 1.0 + _rand((32, 28), seed=1)
    args = (1.3, 1e-4, (0.1, 0.07))
    ref = step_fused_padded(Tp, Cp, *args)
    got = kp_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


def test_kp_variant_matches_ap_on_mesh():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=30, warmup=0, dims=(4, 2))
    model = HeatDiffusion(cfg)
    res_kp = model.run(variant="kp")
    res_ap = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_kp.T), np.asarray(res_ap.T), rtol=1e-13, atol=1e-15
    )


def test_temporal_blocked_matches_stepwise():
    """fused_multi_step_hbm (k steps per sweep) == k individual steps."""
    n = 48  # 3 stripes of 16
    T = _rand((n, n), dtype=jnp.float32)
    Cp = (1.0 + _rand((n, n), seed=1, dtype=jnp.float32))
    lam, dt, spacing = 1.0, 1e-4, (0.5, 0.5)
    # oracle: 16 steps through the VMEM-resident kernel (itself tested
    # against the jnp stepper above)
    ref = fused_multi_step(T, Cp, lam, dt, spacing, 16, chunk=16)
    got = pk.fused_multi_step_hbm(T, Cp, lam, dt, spacing, 16, block_steps=8)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=1e-6
    )


def test_temporal_blocked_3d():
    T = _rand((32, 12, 10), dtype=jnp.float32)
    Cp = 1.0 + _rand((32, 12, 10), seed=2, dtype=jnp.float32)
    lam, dt, spacing = 0.8, 5e-5, (0.3, 0.4, 0.5)
    ref = fused_multi_step(T, Cp, lam, dt, spacing, 8, chunk=8)
    got = pk.fused_multi_step_hbm(T, Cp, lam, dt, spacing, 8, block_steps=4)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=1e-6
    )


def test_temporal_blocked_validation():
    T = _rand((48, 48), dtype=jnp.float32)
    Cp = jnp.ones((48, 48), jnp.float32)
    with pytest.raises(ValueError, match="multiple"):
        pk.fused_multi_step_hbm(T, Cp, 1.0, 1e-4, (0.5, 0.5), 12, block_steps=8)
    with pytest.raises(ValueError, match="block_steps"):
        pk.fused_multi_step_hbm(T, Cp, 1.0, 1e-4, (0.5, 0.5), 16, block_steps=9)
    with pytest.raises(ValueError, match="axis-0"):
        pk.fused_multi_step_hbm(
            T[:20], Cp[:20], 1.0, 1e-4, (0.5, 0.5), 8, block_steps=8
        )


def test_run_hbm_blocked_model_runner():
    cfg = DiffusionConfig(
        global_shape=(64, 40),
        lengths=(10.0, 8.0),
        nt=32,
        warmup=8,
        dtype="f32",
        dims=(1, 1),
    )
    model = HeatDiffusion(cfg)
    res_tb = model.run_hbm_blocked()
    res_ps = model.run(variant="perf")
    np.testing.assert_allclose(
        np.asarray(res_tb.T), np.asarray(res_ps.T), rtol=2e-5, atol=1e-6
    )
