"""Pallas kernel parity (D7): whole-block, striped, multi-step, and the
'perf' model variant vs the jnp oracle (interpret mode on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import rocm_mpi_tpu.ops.pallas_kernels as pk
from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.ops.diffusion import step_fused, step_fused_padded
from rocm_mpi_tpu.ops.pallas_kernels import fused_multi_step, fused_step_padded


def _rand(shape, seed=0, dtype=jnp.float64):
    return jax.random.uniform(jax.random.PRNGKey(seed), shape, dtype=dtype)


def test_whole_block_matches_jnp():
    Tp = _rand((34, 30))
    Cp = 1.0 + _rand((32, 28), seed=1)
    args = (1.3, 1e-4, (0.1, 0.07))
    ref = step_fused_padded(Tp, Cp, *args)
    got = fused_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_striped_matches_jnp(monkeypatch):
    # Shrink the VMEM budget to force the row-striped path on a small grid.
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    Tp = _rand((66, 50))
    Cp = 1.0 + _rand((64, 48), seed=1)
    args = (1.0, 2e-4, (0.1, 0.1))
    ref = step_fused_padded(Tp, Cp, *args)
    got = fused_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_multi_step_matches_stepwise():
    T = _rand((32, 32))
    Cp = jnp.full((32, 32), 1.5, jnp.float64)
    args = (1.0, 1e-5, (0.1, 0.1))
    got = fused_multi_step(T, Cp, *args, n_steps=50)
    ref = T
    for _ in range(50):
        ref = step_fused(ref, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


@pytest.mark.parametrize("spacing", [(0.1, 0.1), (0.1, 0.07)])
def test_multi_step_chunk4_ac_forms_match_stepwise(spacing):
    # n_steps=50 above gets chunk gcd(50,256)=2, i.e. the direct form only.
    # chunk=8 enters the prologue-hoisted A/c branch — the form the scored
    # benchmark geometry executes: equal spacing takes the single-c (eqc)
    # specialization, unequal spacing the per-axis general form. Tight
    # tolerance against the per-step jnp oracle.
    T = _rand((32, 32))
    Cp = 1.0 + _rand((32, 32), seed=1)
    args = (1.0, 1e-5, spacing)
    got = fused_multi_step(T, Cp, *args, n_steps=16, chunk=8)
    ref = T
    for _ in range(16):
        ref = step_fused(ref, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


def test_multi_step_pow2_pad_bitwise_equals_unpadded(monkeypatch):
    """The padded-layout opt-in (VMEM_PAD_POW2, the chip A/B's pad_* rows)
    must be BITWISE the unpadded program on the interior: the pad ring
    carries Cm==0, so pad cells never update and wraparound only reaches
    frozen cells. Non-pow2 shape (20, 24) pads to (32, 32)."""
    T = _rand((20, 24), dtype=jnp.float32)
    Cp = (1.0 + _rand((20, 24), seed=1)).astype(jnp.float32)
    args = (1.0, 1e-5, (0.1, 0.1))
    ref = fused_multi_step(T, Cp, *args, n_steps=16, chunk=8)
    monkeypatch.setattr(pk, "VMEM_PAD_POW2", True)
    got = fused_multi_step(T, Cp, *args, n_steps=16, chunk=8)
    assert got.shape == ref.shape
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_multi_step_conly_form_matches_stepwise(monkeypatch):
    """The A-free equal-spacing body (EQC_BODY_FORM='conly') is the same
    update to rounding: pinned against the per-step jnp oracle BEFORE the
    chip A/B so flipping the default (scripts/bench_kernel_forms.py,
    VERDICT r4 next #2) is a measured one-line change, not a correctness
    event. Also pins the Dirichlet hold the form's algebra promises:
    Cm==0 on the rim ⇒ rim cells bitwise frozen."""
    monkeypatch.setattr(pk, "EQC_BODY_FORM", "conly")
    T = _rand((32, 32))
    Cp = 1.0 + _rand((32, 32), seed=1)
    args = (1.0, 1e-5, (0.1, 0.1))
    got = fused_multi_step(T, Cp, *args, n_steps=16, chunk=8)
    ref = T
    for _ in range(16):
        ref = step_fused(ref, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)
    rim = np.ones((32, 32), bool)
    rim[1:-1, 1:-1] = False
    np.testing.assert_array_equal(np.asarray(got)[rim], np.asarray(T)[rim])


def _cm_oracle(Tp, Cm, spacing):
    """jnp oracle of the Cm contract: new core = Tp[core] + Cm·lap(Tp)."""
    ndim = Cm.ndim
    core = tuple(slice(1, -1) for _ in range(ndim))
    lap = jnp.zeros_like(Cm)
    for ax in range(ndim):
        hi = tuple(
            slice(2, None) if a == ax else slice(1, -1) for a in range(ndim)
        )
        lo = tuple(
            slice(None, -2) if a == ax else slice(1, -1) for a in range(ndim)
        )
        lap = lap + (Tp[hi] - 2.0 * Tp[core] + Tp[lo]) / (
            spacing[ax] * spacing[ax]
        )
    return Tp[core] + Cm * lap


def test_fused_step_cm_whole_matches_oracle():
    Tp = _rand((34, 30))
    Cm = _rand((32, 28), seed=1) * 1e-4
    got = pk.fused_step_cm(Tp, Cm, (0.1, 0.07))
    ref = _cm_oracle(Tp, Cm, (0.1, 0.07))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_fused_step_cm_striped_matches_oracle(monkeypatch):
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    # 61 rows: NOT a multiple of the stripe height — exercises the ceil
    # grid + Pallas-masked partial trailing blocks (no divisor hunting).
    Tp = _rand((63, 50))
    Cm = _rand((61, 48), seed=1) * 1e-4
    got = pk.fused_step_cm(Tp, Cm, (0.1, 0.1))
    ref = _cm_oracle(Tp, Cm, (0.1, 0.1))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_striped_nondivisible_rows(monkeypatch):
    # The unmasked striped kernel on a prime row count: previously fell
    # back to whole-block; now runs striped with a partial trailing stripe.
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    Tp = _rand((69, 50))
    Cp = 1.0 + _rand((67, 48), seed=1)
    args = (1.0, 2e-4, (0.1, 0.1))
    ref = step_fused_padded(Tp, Cp, *args)
    got = fused_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_masked_step_small_matches_step_fused():
    # VMEM-resident dispatch: masked_step(T, edge_masked_cm) == step_fused
    # (edge cells bit-identically held: old + 0.0·lap).
    T = _rand((32, 28))
    Cp = 1.0 + _rand((32, 28), seed=1)
    lam, dt, spacing = 1.3, 1e-4, (0.1, 0.07)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    got = pk.masked_step(T, Cm, spacing)
    ref = step_fused(T, Cp, lam, dt, spacing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)
    np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(T)[0])


def test_masked_step_striped_matches_step_fused(monkeypatch):
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    T = _rand((64, 48))
    Cp = 1.0 + _rand((64, 48), seed=1)
    lam, dt, spacing = 1.0, 2e-4, (0.1, 0.1)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    got = pk.masked_step(T, Cm, spacing)
    ref = step_fused(T, Cp, lam, dt, spacing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_masked_step_pad_fallback_matches_step_fused(monkeypatch):
    # 60 rows, stripe height 8: 60 % 8 != 0, so the garbage-safe route is
    # the zero-ghost pad + padded-contract striped kernel.
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    T = _rand((60, 48))
    Cp = 1.0 + _rand((60, 48), seed=1)
    lam, dt, spacing = 1.0, 2e-4, (0.1, 0.1)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    got = pk.masked_step(T, Cm, spacing)
    ref = step_fused(T, Cp, lam, dt, spacing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


@pytest.mark.parametrize("budget", [512, 2048, 2 * 1024 * 1024])
def test_masked_step_dispatch_sweep(budget, monkeypatch):
    # The dispatcher's three branches (VMEM-resident roll kernel, ghost-
    # block striped, pad + padded-contract fallback) are shape- and
    # budget-dependent; sweep awkward shapes at several budgets and demand
    # every route agrees with step_fused. Covers: divisible and
    # non-divisible row counts, single-stripe fields, odd widths, 3D.
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", budget)
    lam, dt = 1.1, 5e-5
    shapes = [
        (8, 8), (9, 13), (16, 24), (24, 17), (31, 8), (40, 48),
        (57, 50), (64, 8), (12, 10, 8),
    ]
    for shape in shapes:
        spacing = (0.2,) * len(shape)
        T = _rand(shape, seed=sum(shape))
        Cp = 1.0 + _rand(shape, seed=sum(shape) + 1)
        Cm = pk.edge_masked_cm(T, Cp, lam, dt)
        got = pk.masked_step(T, Cm, spacing)
        ref = step_fused(T, Cp, lam, dt, spacing)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-13, atol=1e-15,
            err_msg=f"shape={shape} budget={budget}",
        )


def test_masked_step_3d_striped(monkeypatch):
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    T = _rand((16, 10, 8))
    Cp = 1.0 + _rand((16, 10, 8), seed=1)
    lam, dt, spacing = 0.8, 5e-5, (0.3, 0.4, 0.5)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    got = pk.masked_step(T, Cm, spacing)
    ref = step_fused(T, Cp, lam, dt, spacing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-13)


def test_perf_variant_matches_ap_on_mesh():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=40, warmup=0, dims=(4, 2))
    model = HeatDiffusion(cfg)
    res_perf = model.run(variant="perf")
    res_ap = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_perf.T), np.asarray(res_ap.T), rtol=1e-13, atol=1e-15
    )


def test_vmem_resident_run_matches_ap():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=60, warmup=10, dims=(1, 1))
    model = HeatDiffusion(cfg)
    res_v = model.run_vmem_resident()
    res_ap = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_v.T), np.asarray(res_ap.T), rtol=1e-12, atol=1e-14
    )


def test_vmem_resident_rejects_sharded_grid():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=20, warmup=0, dims=(2, 2))
    with pytest.raises(ValueError, match="unsharded"):
        HeatDiffusion(cfg).run_vmem_resident()


def test_oversized_multi_step_rejected():
    T = jnp.zeros((2048, 2048), jnp.float64)  # 32 MB > budget
    with pytest.raises(ValueError, match="VMEM"):
        fused_multi_step(T, T, 1.0, 1e-5, (0.1, 0.1), 10)


def test_kp_padded_matches_jnp():
    from rocm_mpi_tpu.ops.pallas_kernels import kp_step_padded

    Tp = _rand((34, 30))
    Cp = 1.0 + _rand((32, 28), seed=1)
    args = (1.3, 1e-4, (0.1, 0.07))
    ref = step_fused_padded(Tp, Cp, *args)
    got = kp_step_padded(Tp, Cp, *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


def test_kp_variant_matches_ap_on_mesh():
    cfg = DiffusionConfig(global_shape=(64, 64), nt=30, warmup=0, dims=(4, 2))
    model = HeatDiffusion(cfg)
    res_kp = model.run(variant="kp")
    res_ap = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_kp.T), np.asarray(res_ap.T), rtol=1e-13, atol=1e-15
    )


def test_temporal_blocked_matches_stepwise():
    """fused_multi_step_hbm (k steps per sweep) == k individual steps."""
    n = 48  # 3 stripes of 16
    T = _rand((n, n), dtype=jnp.float32)
    Cp = (1.0 + _rand((n, n), seed=1, dtype=jnp.float32))
    lam, dt, spacing = 1.0, 1e-4, (0.5, 0.5)
    # oracle: 16 steps through the VMEM-resident kernel (itself tested
    # against the jnp stepper above)
    ref = fused_multi_step(T, Cp, lam, dt, spacing, 16, chunk=16)
    got = pk.fused_multi_step_hbm(T, Cp, lam, dt, spacing, 16, block_steps=8)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=1e-6
    )


def test_temporal_blocked_3d():
    T = _rand((32, 12, 10), dtype=jnp.float32)
    Cp = 1.0 + _rand((32, 12, 10), seed=2, dtype=jnp.float32)
    lam, dt, spacing = 0.8, 5e-5, (0.3, 0.4, 0.5)
    ref = fused_multi_step(T, Cp, lam, dt, spacing, 8, chunk=8)
    got = pk.fused_multi_step_hbm(T, Cp, lam, dt, spacing, 8, block_steps=4)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=1e-6
    )


def test_temporal_blocked_validation():
    T = _rand((48, 48), dtype=jnp.float32)
    Cp = jnp.ones((48, 48), jnp.float32)
    with pytest.raises(ValueError, match="multiple"):
        pk.fused_multi_step_hbm(T, Cp, 1.0, 1e-4, (0.5, 0.5), 12, block_steps=8)
    with pytest.raises(ValueError, match="block_steps"):
        pk.fused_multi_step_hbm(
            T, Cp, 1.0, 1e-4, (0.5, 0.5), 34, block_steps=17
        )
    # 8 < k <= 16 is valid since r4 (the (16, 32) geometry) — but its
    # taller stripes impose their own row-divisibility constraint.
    with pytest.raises(ValueError, match="axis-0"):
        pk.fused_multi_step_hbm(T, Cp, 1.0, 1e-4, (0.5, 0.5), 18, block_steps=9)
    with pytest.raises(ValueError, match="axis-0"):
        pk.fused_multi_step_hbm(
            T[:20], Cp[:20], 1.0, 1e-4, (0.5, 0.5), 8, block_steps=8
        )


def test_run_hbm_blocked_model_runner():
    cfg = DiffusionConfig(
        global_shape=(64, 40),
        lengths=(10.0, 8.0),
        nt=32,
        warmup=8,
        dtype="f32",
        dims=(1, 1),
    )
    model = HeatDiffusion(cfg)
    res_tb = model.run_hbm_blocked()
    res_ps = model.run(variant="perf")
    np.testing.assert_allclose(
        np.asarray(res_tb.T), np.asarray(res_ps.T), rtol=2e-5, atol=1e-6
    )


def test_interpret_default_raises_on_unknown_accelerator(monkeypatch):
    # VERDICT r3 hygiene: a GPU backend must error loudly, not silently
    # run the interpreter (≈hours) — compiled Mosaic is TPU-only.
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
    with pytest.raises(RuntimeError, match="TPU-only"):
        pk._interpret_default()
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert pk._interpret_default() is True
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert pk._interpret_default() is False


def test_tb_geometry_and_deep_sweep_k16():
    # The deeper (16, 32) temporal-blocking geometry (r4): k=16 per HBM
    # sweep — half the passes per step of the (8, 16) geometry — must
    # reproduce 16 per-step updates exactly (light cone k <= g).
    assert pk.tb_geometry(8) == (8, 16)
    assert pk.tb_geometry(16) == (16, 32)
    with pytest.raises(ValueError):
        pk.tb_geometry(17)

    T = _rand((64, 48), dtype=jnp.float32)
    Cp = 1.0 + _rand((64, 48), seed=1, dtype=jnp.float32)
    lam, dt, spacing = 1.0, 1e-4, (0.1, 0.1)
    ref = T
    for _ in range(16):
        ref = step_fused(ref, Cp, lam, dt, spacing)
    got = pk.fused_multi_step_hbm(
        T, Cp, lam, dt, spacing, 16, block_steps=16
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=1e-6
    )


def test_deep_sweep_routes_hbm_at_k16(monkeypatch):
    # Deep sweeps beyond the old k<=8 HBM bound: a k=16 sweep on a
    # (shrunk-budget) HBM-class shard must route to the temporal-blocked
    # kernel via the (16, 32) geometry and agree with per-step perf.
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    calls = []
    orig = pk.multi_step_cm_hbm
    monkeypatch.setattr(
        pk, "multi_step_cm_hbm",
        lambda *a, **k: (calls.append(1), orig(*a, **k))[1],
    )
    cfg = DiffusionConfig(
        global_shape=(64, 64), lengths=(10.0, 10.0), nt=16, warmup=0,
        dtype="f32", dims=(2, 1),
    )
    m = HeatDiffusion(cfg)
    # shard (32, 64) + 2·16 ghosts → padded (64, 96): 64 % tm(32) == 0.
    r_deep = m.run_deep(block_steps=16)
    assert calls, "k=16 deep sweep did not route to multi_step_cm_hbm"
    r_ref = HeatDiffusion(cfg).run(variant="perf")
    np.testing.assert_allclose(
        np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
    )


def test_tb_slab_envelope_guard():
    # tb_geometry rejects non-positive and too-deep k (the full contract).
    for bad in (0, -3, 17):
        with pytest.raises(ValueError):
            pk.tb_geometry(bad)
    # The deep (16, 32) geometry's 64-row slab exceeds the Mosaic compile
    # envelope at flagship-wide f32 rows; the kernels must refuse loudly
    # (and the deep router falls back to jnp instead — tested below).
    assert pk.tb_slab_fits(8, (12288, 12288), jnp.float32)
    assert not pk.tb_slab_fits(16, (12288, 12288), jnp.float32)
    assert pk.tb_slab_fits(16, (12288, 4096), jnp.float32)
    T = jnp.zeros((12320, 12288), jnp.float32)
    with pytest.raises(ValueError, match="compile envelope"):
        pk.multi_step_cm_hbm(T, T, (0.1, 0.1), 16)
    # hbm_class_edge stays stripe-divisible for both supported depths.
    for k in (8, 16):
        n = pk.hbm_class_edge(k=k)
        tm = pk.tb_geometry(k)[1]
        assert (n + 2 * k) % tm == 0
        assert (n + 2 * k) ** 2 * 4 > pk._VMEM_BLOCK_BUDGET_BYTES
    with pytest.raises(ValueError, match="divisible"):
        pk.hbm_class_edge(k=5)


def test_deep_sweep_wide_rows_k16_falls_back_to_jnp(monkeypatch):
    # A k=16 sweep whose slab would blow the compile envelope must route
    # to the jnp fallback (the pre-r4 behavior), not crash: shrink the
    # envelope so a small test shard counts as "too wide".
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    monkeypatch.setattr(pk, "_PS_SLAB_BUDGET_BYTES", 1024)
    calls = []
    orig = pk.multi_step_cm_hbm
    monkeypatch.setattr(
        pk, "multi_step_cm_hbm",
        lambda *a, **k: (calls.append(1), orig(*a, **k))[1],
    )
    cfg = DiffusionConfig(
        global_shape=(64, 64), lengths=(10.0, 10.0), nt=16, warmup=0,
        dtype="f32", dims=(2, 1),
    )
    m = HeatDiffusion(cfg)
    r_deep = m.run_deep(block_steps=16)
    assert not calls, "router ignored the compile-envelope gate"
    r_ref = HeatDiffusion(cfg).run(variant="perf")
    np.testing.assert_allclose(
        np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
    )
