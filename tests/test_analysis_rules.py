"""graftlint rule-engine tests: one true-positive and one true-negative
fixture per rule family (tests/analysis_fixtures/), suppression
directives, the JSON reporter schema, and CLI exit codes.

The fixtures are PARSED, never imported — some deliberately contain the
bugs the rules exist to catch.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from rocm_mpi_tpu.analysis import (
    PARSE_RULE,
    all_rules,
    gate_exit_code,
    lint_paths,
    lint_source,
    to_json,
)
from rocm_mpi_tpu.analysis.__main__ import main as cli_main

FIXTURES = pathlib.Path(__file__).parent / "analysis_fixtures"


def lint_fixture(name: str):
    path = FIXTURES / name
    return lint_source(path.read_text(), str(path))


def live_rules(findings) -> set[str]:
    return {f.rule for f in findings if not f.suppressed}


# ---------------------------------------------------------------------------
# Per-rule true positive / true negative
# ---------------------------------------------------------------------------


ALL_RULE_IDS = [
    "GL01", "GL02", "GL03", "GL04", "GL05", "GL06", "GL07", "GL08",
    "GL09", "GL10",
]


@pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
def test_rule_true_positive(rule_id):
    findings = lint_fixture(f"{rule_id.lower()}_pos.py")
    assert rule_id in live_rules(findings), (
        f"{rule_id} did not fire on its positive fixture; "
        f"got {[(f.rule, f.line) for f in findings]}"
    )
    # positives are findings of the rule under test, not collateral noise
    assert live_rules(findings) == {rule_id}


@pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
def test_rule_true_negative(rule_id):
    findings = lint_fixture(f"{rule_id.lower()}_neg.py")
    assert rule_id not in live_rules(findings), (
        f"{rule_id} false-positive on its negative fixture: "
        f"{[(f.line, f.message) for f in findings if f.rule == rule_id]}"
    )


def test_gl01_flags_both_patterns():
    """Read-after-donate AND save/advance overlap each produce a finding."""
    findings = [f for f in lint_fixture("gl01_pos.py") if f.rule == "GL01"]
    messages = " | ".join(f.message for f in findings)
    assert "donated" in messages
    assert "async save" in messages


def test_gl01_flags_reshard_gather_after_donate():
    """The elastic-resume hazard (resilience.reshard module docstring):
    the reshard gather READS every leaf, so gathering a state that a
    donating advance already consumed is a read-after-donate — the
    fixture's reshards_after_donate shape must fire, and the safe
    gather-before-donate ordering in the negative fixture must not
    (covered by test_rule_true_negative)."""
    findings = [f for f in lint_fixture("gl01_pos.py") if f.rule == "GL01"]
    assert any(
        "restored" in f.message and f.line > 0 for f in findings
    ), [(f.line, f.message) for f in findings]


def test_gl06_owners_are_exempt():
    """The measurement chokepoints may read the raw clocks; the same
    source is a finding anywhere else."""
    src = "import time\nt0 = time.perf_counter()\n"
    for owner in (
        "repo/rocm_mpi_tpu/utils/metrics.py",
        "repo/rocm_mpi_tpu/telemetry/spans.py",
    ):
        assert "GL06" not in live_rules(lint_source(src, owner)), owner
    assert "GL06" in live_rules(lint_source(src, "repo/apps/foo.py"))


def test_gl06_monotonic_and_sleep_stay_clean():
    src = (
        "import time\n"
        "deadline = time.monotonic() + 5\n"
        "time.sleep(0.1)\n"
    )
    assert lint_source(src, "repo/apps/foo.py") == []


def test_gl07_owners_are_exempt():
    """telemetry/flight.py and resilience/ own signal handlers; the same
    source is a finding anywhere else — including the launcher, which
    may SEND signals but never install handlers."""
    src = (
        "import faulthandler\nimport signal\n"
        "faulthandler.register(signal.SIGUSR2)\n"
        "signal.signal(signal.SIGTERM, None)\n"
    )
    for owner in (
        "repo/rocm_mpi_tpu/telemetry/flight.py",
        "repo/rocm_mpi_tpu/resilience/faults.py",
        "repo/rocm_mpi_tpu/resilience/supervisor.py",
        # The preemption plane (ISSUE 9): SIGTERM grace-deadline handler
        # + the launcher's forwarder both install HERE, inside the owner
        # dir — the launcher only ever calls the returned seam.
        "repo/rocm_mpi_tpu/resilience/preempt.py",
    ):
        assert "GL07" not in live_rules(lint_source(src, owner)), owner
    for elsewhere in (
        "repo/rocm_mpi_tpu/parallel/launcher.py",
        "repo/rocm_mpi_tpu/telemetry/events.py",
        "repo/apps/foo.py",
    ):
        assert "GL07" in live_rules(lint_source(src, elsewhere)), elsewhere


def test_gl07_preempt_shaped_stray_still_fires():
    """Admitting resilience/preempt.py must not have widened the seam:
    the exact SIGTERM grace-deadline install preempt.py performs is
    still a finding anywhere OUTSIDE the owners (the fixture carries the
    preempt-shaped stray), and the real preempt module itself lints
    clean under its owner path."""
    fixture_src = (FIXTURES / "gl07_pos.py").read_text()
    stray_line = next(
        i for i, raw in enumerate(fixture_src.splitlines(), 1)
        if "preempt-shaped stray" in raw
    )
    findings = [
        f for f in lint_fixture("gl07_pos.py") if f.rule == "GL07"
    ]
    assert any(f.line == stray_line for f in findings), [
        (f.line, f.message) for f in findings
    ]
    real = (
        pathlib.Path(__file__).parent.parent
        / "rocm_mpi_tpu" / "resilience" / "preempt.py"
    ).read_text()
    assert "GL07" not in live_rules(lint_source(
        real, "repo/rocm_mpi_tpu/resilience/preempt.py"
    ))
    # The same source under a non-owner path fires: the exemption is the
    # path, not the code.
    assert "GL07" in live_rules(lint_source(real, "repo/apps/preempt.py"))


def test_gl07_sending_signals_stays_clean():
    src = (
        "import os\nimport signal\n"
        "def f(p):\n"
        "    p.send_signal(signal.SIGUSR2)\n"
        "    os.kill(1234, signal.SIGTERM)\n"
    )
    assert lint_source(src, "repo/rocm_mpi_tpu/parallel/launcher.py") == []


def test_gl02_flags_cross_module_and_traced_global():
    findings = [f for f in lint_fixture("gl02_pos.py") if f.rule == "GL02"]
    messages = " | ".join(f.message for f in findings)
    assert "mutates module" in messages
    assert "trace time" in messages


def test_gl02_flags_tuning_cache_write_in_traced_body():
    """ISSUE 7's hazard fixture: the tuning cache is READ at trace time
    (resolve — legal); a cache WRITE from a traced body is the
    stale-global class GL02 polices, both as a cross-module mutation of
    the resolve chokepoint and as a winner-recording `global`."""
    findings = [
        f for f in lint_fixture("gl02_tuning_pos.py") if f.rule == "GL02"
    ]
    assert len(findings) >= 2
    messages = " | ".join(f.message for f in findings)
    assert "tuning_resolve._STATE" in messages
    assert "_TUNED" in messages


def test_gl02_flags_stage_callback_state_write_in_traced_body():
    """ISSUE 15's hazard fixture: the drain pipeline's stage callbacks
    are HOST-side by contract — a "hook" that mutates service/module
    state from inside a traced body (the fetch/resolve stage's async
    region) runs once at trace time and is skipped by every cached
    program reuse; both shipped shapes (a bubble-accounting `global`
    and a cross-module write into the service module) must fire."""
    findings = [
        f for f in lint_fixture("gl02_serving_pos.py") if f.rule == "GL02"
    ]
    assert len(findings) >= 2, findings
    messages = " | ".join(f.message for f in findings)
    assert "_BUBBLE_MARKS" in messages
    assert "serving_service._PIPELINE_STAGE" in messages


def test_gl02_serving_chokepoint_shapes_stay_clean():
    """The SHIPPED pipeline shapes — instance-attr stage accounting
    from plain host methods, a host-side stage hook, a pure traced
    batched step — must not fire (the real chokepoint is pinned clean
    repo-wide by test_self_lint)."""
    assert "GL02" not in live_rules(lint_fixture("gl02_serving_neg.py"))


# ---------------------------------------------------------------------------
# GL08 / GL09 — the interprocedural rule families (ISSUE 8)
# ---------------------------------------------------------------------------


def test_gl08_flags_pr7_multicontroller_cache_reconstruction():
    """The PR-7 hazard shape: per-rank cache content selects between
    branch arms whose collective sequences differ."""
    findings = [f for f in lint_fixture("gl08_pos.py") if f.rule == "GL08"]
    assert any(
        "per-rank-file-content-dependent" in f.message for f in findings
    ), [(f.line, f.message) for f in findings]


def test_gl08_flags_pr6_rank_rebuild_reconstruction():
    """The PR-6 hazard shape: a rank-guarded rebuild arm issuing a
    collective the reuse arm never does."""
    findings = [f for f in lint_fixture("gl08_pos.py") if f.rule == "GL08"]
    assert any(
        "rank-dependent" in f.message and "psum" in f.message
        for f in findings
    ), [(f.line, f.message) for f in findings]


def test_gl08_fixed_forms_pass():
    """The SHIPPED fixes must be clean: the process_count() > 1 early
    return (PR 7) and the broadcast_one_to_all laundering — plus
    rank-guarded host-only work and same-sequence-on-both-paths."""
    findings = lint_fixture("gl08_neg.py")
    assert "GL08" not in live_rules(findings), [
        (f.line, f.message) for f in findings if f.rule == "GL08"
    ]


def test_gl08_interprocedural_across_modules(tmp_path):
    """The divergence is only visible with BOTH modules in the program:
    the collective lives in a helper module, the rank branch in the
    caller. Per-file lint of the caller alone must stay silent (the
    callee is unresolvable); the whole-program pass must fire."""
    (tmp_path / "helpers.py").write_text(
        "import jax\n"
        "def exchange(T):\n"
        "    return jax.lax.ppermute(T, 'x', [(0, 1)])\n"
    )
    caller = tmp_path / "caller.py"
    caller.write_text(
        "import jax\n"
        "from helpers import exchange\n"
        "def f(T):\n"
        "    if jax.process_index() == 0:\n"
        "        return exchange(T)\n"
        "    return T\n"
    )
    from rocm_mpi_tpu.analysis.core import lint_file

    assert "GL08" not in live_rules(lint_file(caller))
    findings, _ = lint_paths([str(tmp_path)])
    gl08 = [f for f in findings if f.rule == "GL08"]
    assert gl08 and "caller.py" in gl08[0].file, [
        (f.file, f.line) for f in findings
    ]


def test_gl01_interprocedural_donating_helper(tmp_path):
    """Donate in a HELPER, read in the caller: the helper donates its
    parameter into a jitted donate_argnums callable, so the caller's
    binding is poisoned by the helper call — only the whole-program
    summaries can see it."""
    (tmp_path / "lib.py").write_text(
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, donate_argnums=0)\n"
        "def advance(state, n):\n"
        "    return state + n\n"
        "def advance_twice(state):\n"
        "    out = advance(state, 1)\n"
        "    return advance(out, 1)\n"
    )
    caller = tmp_path / "driver.py"
    caller.write_text(
        "from lib import advance_twice\n"
        "def run(state):\n"
        "    out = advance_twice(state)\n"
        "    return out + state.sum()\n"  # read after helper donated it
    )
    from rocm_mpi_tpu.analysis.core import lint_file

    assert "GL01" not in live_rules(lint_file(caller))
    findings, _ = lint_paths([str(tmp_path)])
    gl01 = [
        f for f in findings
        if f.rule == "GL01" and "driver.py" in f.file and not f.suppressed
    ]
    assert gl01, [(f.file, f.line, f.rule) for f in findings]


def test_gl09_flags_every_torn_writer_shape():
    """dump-to-final, write-through-artifact-path, write_text-in-place,
    Path.open('w')-in-place, and tmp-without-rename each fire."""
    findings = [f for f in lint_fixture("gl09_pos.py") if f.rule == "GL09"]
    assert len(findings) == 5, [(f.line, f.message) for f in findings]


def test_gl09_emergency_save_writers_are_atomic():
    """The preemption emergency-save path (ISSUE 9) publishes its
    manifest through the same tmp+rename writer as every other sidecar:
    the REAL utils/checkpoint.py — _save_once, the retry loop, and the
    preempt branch included — lints clean under GL09, while an
    in-place manifest write of the same shape still fires (the rule
    did not get a checkpoint-module carve-out)."""
    real = (
        pathlib.Path(__file__).parent.parent
        / "rocm_mpi_tpu" / "utils" / "checkpoint.py"
    ).read_text()
    findings = lint_source(real, "repo/rocm_mpi_tpu/utils/checkpoint.py")
    assert "GL09" not in live_rules(findings), [
        (f.line, f.message) for f in findings if f.rule == "GL09"
    ]
    torn = (
        "import json\n"
        "def emergency_save(path, step, leaves):\n"
        "    doc = {'schema': 'rmt-manifest', 'v': 2, 'step': step,\n"
        "           'leaves': leaves}\n"
        "    with open(path, 'w') as fh:\n"
        "        json.dump(doc, fh)\n"
    )
    assert "GL09" in live_rules(
        lint_source(torn, "repo/rocm_mpi_tpu/utils/checkpoint.py")
    )


def test_gl09_accepts_both_disciplines():
    """tmp+os.replace, pathlib tmp+.replace, and append-only JSONL are
    the committed disciplines; scratch JSON without a schema marker is
    out of scope."""
    findings = lint_fixture("gl09_neg.py")
    assert "GL09" not in live_rules(findings), [
        (f.line, f.message) for f in findings if f.rule == "GL09"
    ]


def test_gl09_serving_sidecar_twins():
    """The request-plane hardening's sidecars (ISSUE 14): the REAL
    writers lint clean — serving/queue.append_quarantine is
    append-only, serving/slo.write_soak_report is tmp+rename — while
    their doctored in-place twins fire (payload-schema evidence for
    both, plus the quarantine family name alone as path evidence)."""
    findings = [
        f for f in lint_fixture("gl09_serving_pos.py")
        if f.rule == "GL09" and not f.suppressed
    ]
    assert len(findings) == 3, [(f.line, f.message) for f in findings]
    neg = lint_fixture("gl09_serving_neg.py")
    assert "GL09" not in live_rules(neg), [
        (f.line, f.message) for f in neg if f.rule == "GL09"
    ]
    repo = pathlib.Path(__file__).parent.parent
    for mod in ("serving/queue.py", "serving/slo.py"):
        real = (repo / "rocm_mpi_tpu" / mod).read_text()
        real_findings = lint_source(real, f"rocm_mpi_tpu/{mod}")
        assert "GL09" not in live_rules(real_findings), (
            mod,
            [(f.line, f.message) for f in real_findings
             if f.rule == "GL09"],
        )


def test_gl09_fleet_sidecar_twins():
    """The fleet's sidecars (ISSUE 16): the REAL writers lint clean —
    serving/journal.TicketJournal appends, write_fleet_report is
    tmp+rename — while their doctored in-place twins fire
    (payload-schema evidence for both, plus the fleet family name
    alone as path evidence)."""
    findings = [
        f for f in lint_fixture("gl09_fleet_pos.py")
        if f.rule == "GL09" and not f.suppressed
    ]
    assert len(findings) == 3, [(f.line, f.message) for f in findings]
    neg = lint_fixture("gl09_fleet_neg.py")
    assert "GL09" not in live_rules(neg), [
        (f.line, f.message) for f in neg if f.rule == "GL09"
    ]
    repo = pathlib.Path(__file__).parent.parent
    for mod in ("serving/journal.py", "serving/router.py"):
        real = (repo / "rocm_mpi_tpu" / mod).read_text()
        real_findings = lint_source(real, f"rocm_mpi_tpu/{mod}")
        assert "GL09" not in live_rules(real_findings), (
            mod,
            [(f.line, f.message) for f in real_findings
             if f.rule == "GL09"],
        )


# ---------------------------------------------------------------------------
# GL10 — concurrency discipline (ISSUE 17)
# ---------------------------------------------------------------------------


def test_gl10_all_facets_fire():
    """One finding per facet on the positive fixture: guarded-attr
    read, *_locked without the lock, blocking under the lock, the
    lock-order cycle, the acquire/release balance, and the non-owner
    sidecar append."""
    findings = [f for f in lint_fixture("gl10_pos.py")
                if f.rule == "GL10"]
    messages = " | ".join(f.message for f in findings)
    assert "lock-guarded" in messages
    assert "*_locked convention" in messages
    assert "blocking call `time.sleep`" in messages
    assert "lock-order cycle" in messages
    assert "outside try/finally" in messages
    assert "append-mode open" in messages
    assert len(findings) == 6, [(f.line, f.message) for f in findings]


def test_gl10_busy_mark_twins():
    """The PR-15 busy-mark ordering bug: the pre-fix twin (mark under
    an explicit acquire, raising hook before the release) fires; the
    shipped ordering (hook first, mark in a `with` region) is clean —
    and so are the REAL pipelined-drain files under their real
    serving paths (GL10e included)."""
    pos = [f for f in lint_fixture("gl10_busy_mark_pos.py")
           if f.rule == "GL10"]
    assert len(pos) == 1 and "busy-mark" in pos[0].message, [
        (f.line, f.message) for f in pos
    ]
    neg = lint_fixture("gl10_busy_mark_neg.py")
    assert "GL10" not in live_rules(neg), [
        (f.line, f.message) for f in neg if f.rule == "GL10"
    ]
    repo = pathlib.Path(__file__).parent.parent
    for mod in ("serving/service.py", "serving/queue.py"):
        real = (repo / "rocm_mpi_tpu" / mod).read_text()
        real_findings = lint_source(real, f"rocm_mpi_tpu/{mod}")
        assert "GL10" not in live_rules(real_findings), (
            mod,
            [(f.line, f.message) for f in real_findings
             if f.rule == "GL10"],
        )


def test_gl10_nwriter_twins():
    """The PR-14 N-writer quarantine bug: the pre-fix twin (every rank
    appends the sidecar from an ordinary method) fires; the shipped
    single-writer shape (one `append_*` owner behind a rank guard) is
    clean — and so are the REAL journal/quarantine writers."""
    pos = [f for f in lint_fixture("gl10_nwriter_pos.py")
           if f.rule == "GL10"]
    assert len(pos) == 1 and "N appenders" in pos[0].message, [
        (f.line, f.message) for f in pos
    ]
    neg = lint_fixture("gl10_nwriter_neg.py")
    assert "GL10" not in live_rules(neg), [
        (f.line, f.message) for f in neg if f.rule == "GL10"
    ]
    repo = pathlib.Path(__file__).parent.parent
    for mod in ("serving/journal.py", "serving/router.py"):
        real = (repo / "rocm_mpi_tpu" / mod).read_text()
        real_findings = lint_source(real, f"rocm_mpi_tpu/{mod}")
        assert "GL10" not in live_rules(real_findings), (
            mod,
            [(f.line, f.message) for f in real_findings
             if f.rule == "GL10"],
        )


def test_gl10_serving_clock_chokepoints():
    """GL10e single-clock-writer: a raw wall-clock read in serving/*
    fires; the injection idiom (`x if now is None else now`), direct
    dict-literal stamps, the owner files (queue/router), and
    non-serving paths are all exempt — and every REAL serving module
    is clean under its real path (the dogfood fix)."""
    raw = "import time\n\ndef age():\n    return time.monotonic()\n"
    fs = lint_source(raw, "rocm_mpi_tpu/serving/widget.py")
    assert "GL10" in live_rules(fs)
    assert "clock chokepoints" in [
        f for f in fs if f.rule == "GL10"
    ][0].message
    # the injection seam is the blessed shape
    seam = ("import time\n\ndef age(now=None):\n"
            "    now = time.monotonic() if now is None else now\n"
            "    return now\n")
    assert "GL10" not in live_rules(
        lint_source(seam, "rocm_mpi_tpu/serving/widget.py")
    )
    # a dict-literal stamp is a record field, not a control-flow clock
    stamp = ("import time\n\ndef doc():\n"
             "    return {\"t\": time.time()}\n")
    assert "GL10" not in live_rules(
        lint_source(stamp, "rocm_mpi_tpu/serving/widget.py")
    )
    # the owners and everything outside serving/* stay unflagged
    assert "GL10" not in live_rules(
        lint_source(raw, "rocm_mpi_tpu/serving/queue.py")
    )
    assert "GL10" not in live_rules(
        lint_source(raw, "rocm_mpi_tpu/telemetry/widget.py")
    )
    repo = pathlib.Path(__file__).parent.parent
    for mod in ("serving/service.py", "serving/bins.py",
                "serving/slo.py", "serving/journal.py",
                "serving/sessions.py", "serving/scheduler.py"):
        path = repo / "rocm_mpi_tpu" / mod
        if not path.is_file():
            continue
        real_findings = lint_source(
            path.read_text(), f"rocm_mpi_tpu/{mod}"
        )
        assert "GL10" not in live_rules(real_findings), (
            mod,
            [(f.line, f.message) for f in real_findings
             if f.rule == "GL10"],
        )


def test_gl10_interprocedural_lock_effects():
    """The engine-summary facets: a lock-order cycle closed through a
    self-call (the callee's acquire effect), and transitive blocking
    (a helper summarized as file I/O called under the lock)."""
    cycle = (
        "import threading\n\n\n"
        "class Pair:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n\n"
        "    def _grab_b(self):\n"
        "        with self._b:\n"
        "            pass\n\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            self._grab_b()\n\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    fs = [f for f in lint_source(cycle, "cycle.py") if f.rule == "GL10"]
    assert any("lock-order cycle" in f.message for f in fs), [
        (f.line, f.message) for f in fs
    ]
    blocking = (
        "import threading\n\n\n"
        "class Spiller:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.rows = []\n\n"
        "    def _flush(self, path):\n"
        "        with open(path, \"w\") as fh:\n"
        "            fh.write(\"x\")\n\n"
        "    def spill(self, path):\n"
        "        with self._lock:\n"
        "            self._flush(path)\n"
    )
    fs = [f for f in lint_source(blocking, "spill.py")
          if f.rule == "GL10"]
    assert any("summarized as blocking" in f.message for f in fs), [
        (f.line, f.message) for f in fs
    ]
    # re-acquiring a held non-reentrant Lock is the degenerate cycle;
    # the same shape on an RLock is legal reentrancy
    reacquire = (
        "import threading\n\n\n"
        "class Nest:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.{kind}()\n\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self.inner()\n\n"
        "    def inner(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    fs = [f for f in lint_source(reacquire.format(kind="Lock"),
                                 "nest.py") if f.rule == "GL10"]
    assert any("self-deadlock" in f.message for f in fs), [
        (f.line, f.message) for f in fs
    ]
    fs = [f for f in lint_source(reacquire.format(kind="RLock"),
                                 "nest.py") if f.rule == "GL10"]
    assert fs == [], [(f.line, f.message) for f in fs]


def test_serving_fault_kinds_parse_and_consume():
    """The serving-plane fault grammar (docs/SERVING.md "SLOs and
    admission"): the four kinds parse with their triggers, serving
    clauses are invisible to the raising fault_point (their step
    numbering is batches, not simulation steps), and serving_fault
    consumes fires exactly like every other clause."""
    from rocm_mpi_tpu.resilience import faults

    plan = faults.FaultPlan.parse(
        "lane-nan@request=3,times=2;batch-error@step=2;"
        "slow-batch=0.25@step=4;queue-flood=20@step=1"
    )
    kinds = [c.kind for c in plan.clauses]
    assert kinds == ["lane-nan", "batch-error", "slow-batch",
                     "queue-flood"]
    assert plan.clauses[0].request == 3 and plan.clauses[0].times == 2
    assert plan.clauses[2].delay_s == 0.25
    assert plan.clauses[3].delay_s == 20.0

    faults.install(
        "batch-error@step=2;lane-nan@request=1"
    )
    try:
        # Invisible to the generic fault point — even at a matching
        # step count on a legacy site.
        faults.fault_point("step", step=2)
        faults.fault_point("segment", step=2)
        # serving_fault matches, consumes, and re-arms per times=.
        assert faults.serving_fault("batch-error", step=1) is None
        clause = faults.serving_fault("batch-error", step=2)
        assert clause is not None and clause.kind == "batch-error"
        assert faults.serving_fault("batch-error", step=2) is None
        assert faults.serving_fault("lane-nan", request=2) is None
        assert faults.serving_fault("lane-nan", request=1) is not None
    finally:
        faults.install(None)

    with pytest.raises(ValueError, match="request=N"):
        faults.FaultPlan.parse("lane-nan@step=3")
    with pytest.raises(ValueError, match="step=N"):
        faults.FaultPlan.parse("batch-error")
    with pytest.raises(ValueError, match="request"):
        faults.FaultPlan.parse("kill@request=3")


def test_gl08_fires_inside_shadowed_defs():
    """index_functions' last-wins-by-bare-name dedup is a
    call-RESOLUTION heuristic only: every def body — shadowed defs and
    same-named methods included — gets its own GL08 flow walk (the gate
    scope has modules with five same-named `step` methods)."""
    src = (
        "import jax\n"
        "class A:\n"
        "    def step(self, T):\n"
        "        if jax.process_index() == 0:\n"
        "            return jax.lax.psum(T, 'x')\n"
        "        return T\n"
        "class B:\n"
        "    def step(self, T):\n"
        "        return T\n"
    )
    findings = [f for f in lint_source(src, "shadow.py")
                if f.rule == "GL08"]
    assert findings and findings[0].line == 5, [
        (f.line, f.message) for f in findings
    ]


def test_gl08_suppression_works():
    src = (
        "import jax\n"
        "def exchange(T):\n"
        "    return jax.lax.ppermute(T, 'x', [(0, 1)])\n"
        "def f(T):\n"
        "    if jax.process_index() == 0:\n"
        "        # graftlint: disable-next=GL08\n"
        "        return exchange(T)\n"
        "    return T\n"
    )
    findings = lint_source(src, "sup.py")
    gl08 = [f for f in findings if f.rule == "GL08"]
    assert gl08 and all(f.suppressed for f in gl08)
    assert gate_exit_code(findings) == 0


# ---------------------------------------------------------------------------
# Baseline (--baseline / --baseline-write) + the content-hash cache
# ---------------------------------------------------------------------------


def test_baseline_roundtrip_gates_only_new_findings(tmp_path):
    from rocm_mpi_tpu.analysis import baseline

    findings = lint_fixture("gl09_pos.py")
    assert gate_exit_code(findings) == 1
    path = tmp_path / "baseline.json"
    baseline.write_baseline(path, findings)
    doc = baseline.load_baseline(path)
    assert doc["schema"] == baseline.BASELINE_SCHEMA

    again = lint_fixture("gl09_pos.py")
    marked = baseline.apply_baseline(again, doc)
    assert marked == len([f for f in again if f.severity == "error"])
    assert gate_exit_code(again) == 0  # accepted findings do not gate

    # a NEW finding (not in the ledger) still fails
    extra = lint_fixture("gl03_pos.py")
    assert baseline.apply_baseline(extra, doc) == 0
    assert gate_exit_code(extra) == 1


def test_baseline_counts_do_not_absorb_duplicates(tmp_path):
    """A baseline accepting one instance of a finding must not absorb a
    second identical one."""
    from rocm_mpi_tpu.analysis import baseline

    src = (
        "import json\n"
        "def w(path, doc):\n"
        "    record = {'schema': 's', 'v': 1}\n"
        "    with open(path, 'w') as fh:\n"
        "        json.dump(record, fh)\n"
    )
    one = lint_source(src, "w.py")
    path = tmp_path / "b.json"
    baseline.write_baseline(path, one)
    doc = baseline.load_baseline(path)

    doubled = (
        src + "\n"
        "def w2(path, doc):\n"
        "    record = {'schema': 's', 'v': 1}\n"
        "    with open(path, 'w') as fh:\n"
        "        json.dump(record, fh)\n"
    )
    two = lint_source(doubled, "w.py")
    assert len([f for f in two if f.rule == "GL09"]) == 2
    assert baseline.apply_baseline(two, doc) == 1
    assert gate_exit_code(two) == 1  # the second instance still gates


def test_malformed_baseline_fails_loudly(tmp_path):
    from rocm_mpi_tpu.analysis import baseline

    bad = tmp_path / "bad.json"
    bad.write_text('{"schema": "wrong"}')
    with pytest.raises(ValueError):
        baseline.load_baseline(bad)
    with pytest.raises(ValueError):
        baseline.load_baseline(tmp_path / "missing.json")


def test_cache_catches_same_size_same_second_edit(tmp_path):
    """The (mtime, size) key this cache used to have misses an edit that
    keeps byte length within the same second; the content hash cannot."""
    import os

    from rocm_mpi_tpu.analysis.core import lint_file

    p = tmp_path / "edit.py"
    p.write_text("from jax.experimental import pallas\n")  # GL03
    st = p.stat()
    first = lint_file(p)
    assert "GL03" in live_rules(first)
    # same byte count, same mtime — only the content differs
    clean = "x = 1111111111111111111111111111111\n"
    assert len(clean) == len("from jax.experimental import pallas\n")
    p.write_text(clean)
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
    second = lint_file(p)
    assert live_rules(second) == set(), [
        (f.rule, f.message) for f in second
    ]


# ---------------------------------------------------------------------------
# --changed: neighborhood expansion + git fallback
# ---------------------------------------------------------------------------


def test_changed_expands_to_import_neighbors(tmp_path):
    from rocm_mpi_tpu.analysis import baseline
    from rocm_mpi_tpu.analysis.core import read_entries

    (tmp_path / "leaf.py").write_text("X = 1\n")
    (tmp_path / "mid.py").write_text("from leaf import X\nY = X\n")
    (tmp_path / "top.py").write_text("from mid import Y\nZ = Y\n")
    (tmp_path / "other.py").write_text("W = 4\n")
    entries = read_entries([str(tmp_path)])
    dirty = {(tmp_path / "mid.py").resolve().as_posix()}
    keep = baseline.expand_neighbors(entries, dirty)
    names = {p.rsplit("/", 1)[-1] for p in keep}
    # dirty + its importer (top) + its import (leaf); not the stranger
    assert names == {"mid.py", "top.py", "leaf.py"}, names


def test_changed_restrict_filters_reported_scope(tmp_path):
    (tmp_path / "dirty.py").write_text(
        "from jax.experimental import pallas\n"  # GL03
    )
    (tmp_path / "clean_but_unselected.py").write_text(
        "from jax.experimental import pallas\n"  # GL03 too
    )
    restrict = {(tmp_path / "dirty.py").resolve().as_posix()}
    findings, scanned = lint_paths([str(tmp_path)], restrict=restrict)
    assert scanned == 1
    assert {f.file.rsplit("/", 1)[-1] for f in findings} == {"dirty.py"}


def test_git_dirty_files_degrades_to_none(tmp_path):
    """Outside a git work tree the fast path must answer None (callers
    then run the full scope), never raise or return a wrong subset."""
    from rocm_mpi_tpu.analysis import baseline

    assert baseline.git_dirty_files(tmp_path) is None


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_inline_and_next_line_suppressions():
    findings = lint_fixture("suppressions.py")
    suppressed = [f for f in findings if f.suppressed]
    live = [f for f in findings if not f.suppressed]
    assert len(suppressed) == 2  # disable= and disable-next=
    assert len(live) == 1  # the undirected violation stays live
    assert all(f.rule == "GL03" for f in findings)


def test_file_wide_suppression():
    findings = lint_fixture("suppress_file.py")
    gl03 = [f for f in findings if f.rule == "GL03"]
    assert gl03 and all(f.suppressed for f in gl03)
    assert gate_exit_code(findings) == 0


def test_suppressed_findings_do_not_gate():
    findings = lint_fixture("suppress_file.py")
    assert gate_exit_code(findings) == 0
    findings = lint_fixture("gl03_pos.py")
    assert gate_exit_code(findings) == 1


def test_docstring_directive_text_does_not_suppress():
    """A docstring that merely DOCUMENTS a suppression must not install
    one (directives are comment tokens, not string content)."""
    src = (
        '"""Docs: silence with `# graftlint: disable-file=GL03`."""\n'
        "from jax.experimental import pallas\n"
    )
    findings = lint_source(src, "doc.py")
    assert [f.rule for f in findings] == ["GL03"]
    assert not findings[0].suppressed
    assert gate_exit_code(findings) == 1


def test_gl03_allowlist_matches_unnormalized_chokepoint_paths():
    """compat.py must stay exempt however the gate spells its path."""
    repo = FIXTURES.parents[1]
    compat = repo / "rocm_mpi_tpu" / "utils" / "compat.py"
    twisted = str(compat.parent / ".." / "utils" / "compat.py")
    findings = lint_source(compat.read_text(), twisted)
    assert [f for f in findings if f.rule == "GL03"] == []


def test_gl04_coverage_ignores_broadcast_in_specs():
    """An input block smaller than out_shape (broadcast/reduction input)
    is legitimate; only out_specs blocks are judged against out_shape."""
    src = (
        "from rocm_mpi_tpu.utils.compat import pallas as pl\n"
        "import jax\n"
        "def _k(a_ref, o_ref):\n"
        "    o_ref[:] = a_ref[:]\n"
        "def launch(a):\n"
        "    return pl.pallas_call(\n"
        "        _k, grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((1,), lambda i: (0,))],\n"
        "        out_specs=pl.BlockSpec((8,), lambda i: (i,)),\n"
        "        out_shape=jax.ShapeDtypeStruct((32,), 'float32'),\n"
        "    )(a)\n"
    )
    assert lint_source(src, "bcast.py") == []


def test_gl04_wire_seam_true_positive():
    """Arithmetic on a received bf16 slab without the f32 upcast at the
    seam fires (PR 12 wire-precision plane, docs/ANALYSIS.md#gl04) —
    both the inline-downcast and named-payload shapes."""
    findings = lint_fixture("gl04_wire_pos.py")
    live = [f for f in findings if not f.suppressed]
    assert live and all(f.rule == "GL04" for f in live)
    assert all("upcast at the seam" in f.message for f in live)
    # Both fixture functions fire.
    lines = {f.line for f in live}
    assert len(lines) >= 2


def test_gl04_wire_seam_true_negative():
    """Decoded-before-use slabs and full-precision ships stay clean."""
    assert lint_fixture("gl04_wire_neg.py") == []


def test_gl04_wire_seam_repo_clean():
    """The shipped exchange itself (halo.py routes every received slab
    through the codec decode before the seam) must not fire."""
    repo = pathlib.Path(__file__).resolve().parent.parent
    halo = repo / "rocm_mpi_tpu" / "parallel" / "halo.py"
    findings = lint_source(halo.read_text(), str(halo))
    assert [f for f in findings if f.rule == "GL04"] == []


def test_gl05_batch_axis_true_positive():
    """A halo/permutation collective over the multi-tenant 'batch' lane
    axis fires even though 'batch' is in the mesh vocabulary — lanes
    are independent tenants (docs/SERVING.md)."""
    findings = lint_fixture("gl05_batch_pos.py")
    live = [f for f in findings if not f.suppressed]
    assert live and all(f.rule == "GL05" for f in live)
    assert all("lane axis" in f.message for f in live)


def test_gl05_batch_axis_true_negative():
    """psum reductions over 'batch' (cross-lane diagnostics) and
    ppermute over a SPACE axis stay clean."""
    assert lint_fixture("gl05_batch_neg.py") == []


def test_gl05_batch_axis_repo_clean():
    """The shipped batched machinery (mesh/halo/serving) never permutes
    over the lane axis — the batch rule stays zero-findings on it."""
    repo = pathlib.Path(__file__).resolve().parent.parent
    for rel in (
        "rocm_mpi_tpu/parallel/mesh.py",
        "rocm_mpi_tpu/parallel/halo.py",
        "rocm_mpi_tpu/parallel/deep_halo.py",
        "rocm_mpi_tpu/serving/service.py",
        "rocm_mpi_tpu/models/diffusion.py",
        "rocm_mpi_tpu/models/wave.py",
        "rocm_mpi_tpu/models/swe.py",
    ):
        path = repo / rel
        findings = lint_source(path.read_text(), str(path))
        assert [f for f in findings if f.rule == "GL05"] == [], rel


def test_lint_file_cache_returns_fresh_copies(tmp_path):
    """Mutating a returned Finding must not poison later cache hits, and
    display_path must not be served from another label's entry."""
    p = tmp_path / "dirty.py"
    p.write_text("from jax.experimental import pallas\n")
    from rocm_mpi_tpu.analysis.core import lint_file

    first = lint_file(p)
    assert first and not first[0].suppressed
    first[0].suppressed = True
    again = lint_file(p)
    assert not again[0].suppressed
    relabeled = lint_file(p, display_path="label.py")
    assert relabeled[0].file == "label.py"


# ---------------------------------------------------------------------------
# Robustness: unparseable input warns, never crashes the gate
# ---------------------------------------------------------------------------


def test_unparseable_source_warns_and_passes_gate():
    findings = lint_source("def broken(:\n", "broken.py")
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == PARSE_RULE
    assert f.severity == "warning"
    assert "skipped" in f.message
    assert gate_exit_code(findings) == 0  # warnings never wedge CI


def test_unparseable_file_in_tree_does_not_crash(tmp_path):
    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "broken.py").write_text("def broken(:\n")
    findings, scanned = lint_paths([str(tmp_path)])
    assert scanned == 2
    assert {f.rule for f in findings} == {PARSE_RULE}
    assert gate_exit_code(findings) == 0


def test_missing_path_fails_loudly():
    with pytest.raises(FileNotFoundError):
        lint_paths(["no/such/dir"])


# ---------------------------------------------------------------------------
# JSON reporter schema (version 3 — pinned; regress --check-schema reads it)
# ---------------------------------------------------------------------------


def test_json_reporter_schema():
    from rocm_mpi_tpu.analysis import catalog_rules, validate_findings_doc
    from rocm_mpi_tpu.analysis.report import (
        FINDINGS_SCHEMA,
        FINDINGS_VERSION,
    )

    findings = lint_fixture("gl03_pos.py") + lint_fixture("suppressions.py")
    doc = json.loads(to_json(findings, files_scanned=2))
    assert doc["schema"] == FINDINGS_SCHEMA
    assert doc["version"] == FINDINGS_VERSION == 3
    assert doc["files_scanned"] == 2
    assert isinstance(doc["suppressed"], int) and doc["suppressed"] == 2
    assert doc["baselined"] == 0
    # counts: every cataloged rule id present (GL08/GL09/GL10 included),
    # GL00 too
    rule_ids = {r.id for r in catalog_rules()} | {PARSE_RULE}
    assert {"GL08", "GL09", "GL10"} <= rule_ids
    assert set(doc["counts"]) == rule_ids
    assert doc["counts"]["GL03"] == len(
        [f for f in findings if not f.suppressed]
    )
    required = {
        "file", "line", "col", "rule", "severity", "message", "hint",
        "suppressed", "baselined",
    }
    for entry in doc["findings"]:
        assert set(entry) == required
        assert entry["severity"] in ("error", "warning")
        assert isinstance(entry["line"], int) and entry["line"] >= 1
    # the document validates against its own schema checker (the one
    # regress --check-schema runs)
    assert validate_findings_doc(doc) == []
    assert validate_findings_doc({"schema": "nope"}) != []


def test_write_findings_is_atomic_and_schema_checked(tmp_path):
    """The banked artifact parses, validates, and is classified by the
    telemetry regress schema gate (the lint.sh wiring)."""
    from rocm_mpi_tpu.analysis import write_findings
    from rocm_mpi_tpu.telemetry.regress import check_schema

    findings = lint_fixture("gl09_pos.py")
    out = tmp_path / "lint" / "findings.json"
    write_findings(out, findings, files_scanned=1)
    assert out.is_file() and not out.with_name("findings.json.tmp").exists()
    doc = json.loads(out.read_text())
    assert doc["schema"] == "rmt-lint-findings"
    assert check_schema([str(out)]) == []
    # a drifted document must FAIL the schema gate
    doc["findings"][0]["line"] = "not-an-int"
    out.write_text(json.dumps(doc))
    assert check_schema([str(out)]) != []


def test_committed_baseline_passes_schema_gate():
    from rocm_mpi_tpu.analysis.baseline import DEFAULT_BASELINE
    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert DEFAULT_BASELINE.is_file(), "committed baseline missing"
    assert check_schema([str(DEFAULT_BASELINE)]) == []


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_exit_codes(capsys):
    assert cli_main([str(FIXTURES / "gl03_neg.py")]) == 0
    assert cli_main([str(FIXTURES / "gl03_pos.py")]) == 1
    assert cli_main(["definitely/not/a/path"]) == 2
    assert cli_main([]) == 2  # no paths = usage error, not a silent pass
    capsys.readouterr()


def test_cli_select_and_json(capsys):
    rc = cli_main([str(FIXTURES / "gl03_pos.py"), "--select", "GL01",
                   "--json"])
    out = capsys.readouterr().out
    assert rc == 0  # GL03 findings filtered out by --select
    doc = json.loads(out)
    assert doc["findings"] == []


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("GL01", "GL02", "GL03", "GL04", "GL05", "GL06",
                    "GL07", "GL08", "GL09", "GL10"):
        assert rule_id in out


def test_cli_baseline_write_then_compare(tmp_path, capsys):
    """The landing flow for a new rule: bank the dirty state, gate only
    what is NOT in the ledger."""
    import shutil

    fixture = tmp_path / "dirty.py"
    shutil.copy(FIXTURES / "gl09_pos.py", fixture)
    ledger = tmp_path / "baseline.json"

    assert cli_main([str(fixture)]) == 1
    assert cli_main([str(fixture), "--baseline-write", str(ledger)]) == 0
    assert cli_main([str(fixture), "--baseline", str(ledger)]) == 0
    out = capsys.readouterr().out
    assert "baselined" in out
    # unreadable ledger = usage error, never a silent pass
    assert cli_main([str(fixture), "--baseline",
                     str(tmp_path / "nope.json")]) == 2
    # --changed would restrict the scan to the dirty neighborhood; a
    # baseline banked from it silently drops every accepted finding
    # outside that set — the combination is a usage error
    assert cli_main([str(fixture), "--changed",
                     "--baseline-write", str(ledger)]) == 2
    capsys.readouterr()


def test_cli_output_artifact(tmp_path, capsys):
    from rocm_mpi_tpu.analysis import validate_findings_doc

    out_path = tmp_path / "out" / "findings.json"
    rc = cli_main([str(FIXTURES / "gl03_pos.py"), "--output",
                   str(out_path)])
    assert rc == 1
    doc = json.loads(out_path.read_text())
    assert validate_findings_doc(doc) == []
    assert doc["counts"]["GL03"] >= 1
    capsys.readouterr()


def test_strict_suppressions_flags_stale_directive(tmp_path, capsys):
    """A directive that covers no finding is itself a GL99 error under
    --strict-suppressions — and invisible without the flag (the default
    lane stays byte-identical for downstream tooling)."""
    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # graftlint: disable=GL03\n")
    assert cli_main([str(stale)]) == 0
    capsys.readouterr()
    assert cli_main([str(stale), "--strict-suppressions"]) == 1
    out = capsys.readouterr().out
    assert "GL99" in out and "stale suppression" in out
    assert "disable=GL03" in out  # names the dead directive verbatim


def test_strict_suppressions_keeps_live_directive(tmp_path, capsys):
    """A directive that still suppresses a finding survives the audit:
    same exit code with and without the flag."""
    live = tmp_path / "live.py"
    live.write_text(
        "from jax import shard_map  # graftlint: disable=GL03\n"
    )
    assert cli_main([str(live)]) == 0
    assert cli_main([str(live), "--strict-suppressions"]) == 0
    # an ALL directive is live if ANY rule fires under it
    blanket = tmp_path / "blanket.py"
    blanket.write_text(
        "from jax import shard_map  # graftlint: disable=ALL\n"
    )
    assert cli_main([str(blanket), "--strict-suppressions"]) == 0
    capsys.readouterr()


def test_audit_suppressions_unit_shapes(tmp_path):
    """disable-next audits against the NEXT line's findings;
    disable-file is live if anything in the file fired under it."""
    from rocm_mpi_tpu.analysis.core import STALE_RULE, audit_suppressions

    nxt = tmp_path / "nxt.py"
    nxt.write_text(
        "# graftlint: disable-next=GL03\n"
        "from jax import shard_map\n"
        "# graftlint: disable-next=GL03\n"
        "x = 1\n"
    )
    findings, _ = lint_paths([str(nxt)])
    stale = audit_suppressions([str(nxt)], findings)
    assert [(f.rule, f.line) for f in stale] == [(STALE_RULE, 3)]
    assert stale[0].severity == "error"

    blanket = tmp_path / "blanket.py"
    blanket.write_text(
        "# graftlint: disable-file=GL03\n"
        "from jax import shard_map\n"
    )
    findings, _ = lint_paths([str(blanket)])
    assert audit_suppressions([str(blanket)], findings) == []
