"""graftlint rule-engine tests: one true-positive and one true-negative
fixture per rule family (tests/analysis_fixtures/), suppression
directives, the JSON reporter schema, and CLI exit codes.

The fixtures are PARSED, never imported — some deliberately contain the
bugs the rules exist to catch.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from rocm_mpi_tpu.analysis import (
    PARSE_RULE,
    all_rules,
    gate_exit_code,
    lint_paths,
    lint_source,
    to_json,
)
from rocm_mpi_tpu.analysis.__main__ import main as cli_main

FIXTURES = pathlib.Path(__file__).parent / "analysis_fixtures"


def lint_fixture(name: str):
    path = FIXTURES / name
    return lint_source(path.read_text(), str(path))


def live_rules(findings) -> set[str]:
    return {f.rule for f in findings if not f.suppressed}


# ---------------------------------------------------------------------------
# Per-rule true positive / true negative
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "rule_id", ["GL01", "GL02", "GL03", "GL04", "GL05", "GL06", "GL07"]
)
def test_rule_true_positive(rule_id):
    findings = lint_fixture(f"{rule_id.lower()}_pos.py")
    assert rule_id in live_rules(findings), (
        f"{rule_id} did not fire on its positive fixture; "
        f"got {[(f.rule, f.line) for f in findings]}"
    )
    # positives are findings of the rule under test, not collateral noise
    assert live_rules(findings) == {rule_id}


@pytest.mark.parametrize(
    "rule_id", ["GL01", "GL02", "GL03", "GL04", "GL05", "GL06", "GL07"]
)
def test_rule_true_negative(rule_id):
    findings = lint_fixture(f"{rule_id.lower()}_neg.py")
    assert rule_id not in live_rules(findings), (
        f"{rule_id} false-positive on its negative fixture: "
        f"{[(f.line, f.message) for f in findings if f.rule == rule_id]}"
    )


def test_gl01_flags_both_patterns():
    """Read-after-donate AND save/advance overlap each produce a finding."""
    findings = [f for f in lint_fixture("gl01_pos.py") if f.rule == "GL01"]
    messages = " | ".join(f.message for f in findings)
    assert "donated" in messages
    assert "async save" in messages


def test_gl01_flags_reshard_gather_after_donate():
    """The elastic-resume hazard (resilience.reshard module docstring):
    the reshard gather READS every leaf, so gathering a state that a
    donating advance already consumed is a read-after-donate — the
    fixture's reshards_after_donate shape must fire, and the safe
    gather-before-donate ordering in the negative fixture must not
    (covered by test_rule_true_negative)."""
    findings = [f for f in lint_fixture("gl01_pos.py") if f.rule == "GL01"]
    assert any(
        "restored" in f.message and f.line > 0 for f in findings
    ), [(f.line, f.message) for f in findings]


def test_gl06_owners_are_exempt():
    """The measurement chokepoints may read the raw clocks; the same
    source is a finding anywhere else."""
    src = "import time\nt0 = time.perf_counter()\n"
    for owner in (
        "repo/rocm_mpi_tpu/utils/metrics.py",
        "repo/rocm_mpi_tpu/telemetry/spans.py",
    ):
        assert "GL06" not in live_rules(lint_source(src, owner)), owner
    assert "GL06" in live_rules(lint_source(src, "repo/apps/foo.py"))


def test_gl06_monotonic_and_sleep_stay_clean():
    src = (
        "import time\n"
        "deadline = time.monotonic() + 5\n"
        "time.sleep(0.1)\n"
    )
    assert lint_source(src, "repo/apps/foo.py") == []


def test_gl07_owners_are_exempt():
    """telemetry/flight.py and resilience/ own signal handlers; the same
    source is a finding anywhere else — including the launcher, which
    may SEND signals but never install handlers."""
    src = (
        "import faulthandler\nimport signal\n"
        "faulthandler.register(signal.SIGUSR2)\n"
        "signal.signal(signal.SIGTERM, None)\n"
    )
    for owner in (
        "repo/rocm_mpi_tpu/telemetry/flight.py",
        "repo/rocm_mpi_tpu/resilience/faults.py",
        "repo/rocm_mpi_tpu/resilience/supervisor.py",
    ):
        assert "GL07" not in live_rules(lint_source(src, owner)), owner
    for elsewhere in (
        "repo/rocm_mpi_tpu/parallel/launcher.py",
        "repo/rocm_mpi_tpu/telemetry/events.py",
        "repo/apps/foo.py",
    ):
        assert "GL07" in live_rules(lint_source(src, elsewhere)), elsewhere


def test_gl07_sending_signals_stays_clean():
    src = (
        "import os\nimport signal\n"
        "def f(p):\n"
        "    p.send_signal(signal.SIGUSR2)\n"
        "    os.kill(1234, signal.SIGTERM)\n"
    )
    assert lint_source(src, "repo/rocm_mpi_tpu/parallel/launcher.py") == []


def test_gl02_flags_cross_module_and_traced_global():
    findings = [f for f in lint_fixture("gl02_pos.py") if f.rule == "GL02"]
    messages = " | ".join(f.message for f in findings)
    assert "mutates module" in messages
    assert "trace time" in messages


def test_gl02_flags_tuning_cache_write_in_traced_body():
    """ISSUE 7's hazard fixture: the tuning cache is READ at trace time
    (resolve — legal); a cache WRITE from a traced body is the
    stale-global class GL02 polices, both as a cross-module mutation of
    the resolve chokepoint and as a winner-recording `global`."""
    findings = [
        f for f in lint_fixture("gl02_tuning_pos.py") if f.rule == "GL02"
    ]
    assert len(findings) >= 2
    messages = " | ".join(f.message for f in findings)
    assert "tuning_resolve._STATE" in messages
    assert "_TUNED" in messages


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_inline_and_next_line_suppressions():
    findings = lint_fixture("suppressions.py")
    suppressed = [f for f in findings if f.suppressed]
    live = [f for f in findings if not f.suppressed]
    assert len(suppressed) == 2  # disable= and disable-next=
    assert len(live) == 1  # the undirected violation stays live
    assert all(f.rule == "GL03" for f in findings)


def test_file_wide_suppression():
    findings = lint_fixture("suppress_file.py")
    gl03 = [f for f in findings if f.rule == "GL03"]
    assert gl03 and all(f.suppressed for f in gl03)
    assert gate_exit_code(findings) == 0


def test_suppressed_findings_do_not_gate():
    findings = lint_fixture("suppress_file.py")
    assert gate_exit_code(findings) == 0
    findings = lint_fixture("gl03_pos.py")
    assert gate_exit_code(findings) == 1


def test_docstring_directive_text_does_not_suppress():
    """A docstring that merely DOCUMENTS a suppression must not install
    one (directives are comment tokens, not string content)."""
    src = (
        '"""Docs: silence with `# graftlint: disable-file=GL03`."""\n'
        "from jax.experimental import pallas\n"
    )
    findings = lint_source(src, "doc.py")
    assert [f.rule for f in findings] == ["GL03"]
    assert not findings[0].suppressed
    assert gate_exit_code(findings) == 1


def test_gl03_allowlist_matches_unnormalized_chokepoint_paths():
    """compat.py must stay exempt however the gate spells its path."""
    repo = FIXTURES.parents[1]
    compat = repo / "rocm_mpi_tpu" / "utils" / "compat.py"
    twisted = str(compat.parent / ".." / "utils" / "compat.py")
    findings = lint_source(compat.read_text(), twisted)
    assert [f for f in findings if f.rule == "GL03"] == []


def test_gl04_coverage_ignores_broadcast_in_specs():
    """An input block smaller than out_shape (broadcast/reduction input)
    is legitimate; only out_specs blocks are judged against out_shape."""
    src = (
        "from rocm_mpi_tpu.utils.compat import pallas as pl\n"
        "import jax\n"
        "def _k(a_ref, o_ref):\n"
        "    o_ref[:] = a_ref[:]\n"
        "def launch(a):\n"
        "    return pl.pallas_call(\n"
        "        _k, grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((1,), lambda i: (0,))],\n"
        "        out_specs=pl.BlockSpec((8,), lambda i: (i,)),\n"
        "        out_shape=jax.ShapeDtypeStruct((32,), 'float32'),\n"
        "    )(a)\n"
    )
    assert lint_source(src, "bcast.py") == []


def test_lint_file_cache_returns_fresh_copies(tmp_path):
    """Mutating a returned Finding must not poison later cache hits, and
    display_path must not be served from another label's entry."""
    p = tmp_path / "dirty.py"
    p.write_text("from jax.experimental import pallas\n")
    from rocm_mpi_tpu.analysis.core import lint_file

    first = lint_file(p)
    assert first and not first[0].suppressed
    first[0].suppressed = True
    again = lint_file(p)
    assert not again[0].suppressed
    relabeled = lint_file(p, display_path="label.py")
    assert relabeled[0].file == "label.py"


# ---------------------------------------------------------------------------
# Robustness: unparseable input warns, never crashes the gate
# ---------------------------------------------------------------------------


def test_unparseable_source_warns_and_passes_gate():
    findings = lint_source("def broken(:\n", "broken.py")
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == PARSE_RULE
    assert f.severity == "warning"
    assert "skipped" in f.message
    assert gate_exit_code(findings) == 0  # warnings never wedge CI


def test_unparseable_file_in_tree_does_not_crash(tmp_path):
    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "broken.py").write_text("def broken(:\n")
    findings, scanned = lint_paths([str(tmp_path)])
    assert scanned == 2
    assert {f.rule for f in findings} == {PARSE_RULE}
    assert gate_exit_code(findings) == 0


def test_missing_path_fails_loudly():
    with pytest.raises(FileNotFoundError):
        lint_paths(["no/such/dir"])


# ---------------------------------------------------------------------------
# JSON reporter schema (version 1 — pinned)
# ---------------------------------------------------------------------------


def test_json_reporter_schema():
    findings = lint_fixture("gl03_pos.py") + lint_fixture("suppressions.py")
    doc = json.loads(to_json(findings, files_scanned=2))
    assert doc["version"] == 1
    assert doc["files_scanned"] == 2
    assert isinstance(doc["suppressed"], int) and doc["suppressed"] == 2
    # counts: every registered rule id present, plus GL00
    rule_ids = {r.id for r in all_rules()} | {PARSE_RULE}
    assert set(doc["counts"]) == rule_ids
    assert doc["counts"]["GL03"] == len(
        [f for f in findings if not f.suppressed]
    )
    required = {
        "file", "line", "col", "rule", "severity", "message", "hint",
        "suppressed",
    }
    for entry in doc["findings"]:
        assert set(entry) == required
        assert entry["severity"] in ("error", "warning")
        assert isinstance(entry["line"], int) and entry["line"] >= 1


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_exit_codes(capsys):
    assert cli_main([str(FIXTURES / "gl03_neg.py")]) == 0
    assert cli_main([str(FIXTURES / "gl03_pos.py")]) == 1
    assert cli_main(["definitely/not/a/path"]) == 2
    assert cli_main([]) == 2  # no paths = usage error, not a silent pass
    capsys.readouterr()


def test_cli_select_and_json(capsys):
    rc = cli_main([str(FIXTURES / "gl03_pos.py"), "--select", "GL01",
                   "--json"])
    out = capsys.readouterr().out
    assert rc == 0  # GL03 findings filtered out by --select
    doc = json.loads(out)
    assert doc["findings"] == []


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("GL01", "GL02", "GL03", "GL04", "GL05"):
        assert rule_id in out
