"""Gloo-real rank worker for the kill-rank-mid-collective drill (slow
lane; tests/test_resilience.py drives it via spawn_ranks).

The real multi-process shape of the failure the launcher supervises:
both ranks join a jax.distributed cluster, then run cross-process
collective steps with a fault point before each — an injected
`kill@step=K,rank=R` takes rank R down mid-run and the survivor's next
collective can never complete. The launcher must record the first
failure and kill the hung survivor within the peer grace window.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

from rocm_mpi_tpu.utils.backend import set_cpu_device_count

jax.config.update("jax_platforms", "cpu")
set_cpu_device_count(2)
jax.config.update("jax_enable_x64", True)


def main() -> int:
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from rocm_mpi_tpu.parallel.distributed import maybe_initialize_distributed
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.utils import metrics

    assert maybe_initialize_distributed(), "launcher env not detected"
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("x",))
    sharding = NamedSharding(mesh, PartitionSpec("x"))
    x = jax.device_put(jnp.arange(8.0), sharding)

    @jax.jit
    def step(v):
        return v + jnp.sum(v)  # global sum: every rank must participate

    for i in range(1, 9):
        faults.fault_point("segment", step=i)
        x = step(x)
        metrics.force(x)
    print("GLOO_WORKER_DONE", flush=True)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
