"""Gloo-real rank worker for the elastic-recovery drills
(tests/test_elastic.py drives it via resilience.elastic.run_elastic).

Each rank joins the jax.distributed cluster the launcher contract
describes, builds the diffusion model on WHATEVER mesh the current
process count yields (one virtual CPU device per rank — the mesh IS the
rank count), resumes from the latest valid checkpoint step using the
manifest's topology metadata alone (`restore_state(like=None)` — the
elastic tentpole path: a checkpoint written on the old mesh lands on the
new one), and runs the segmented checkpointed loop to nt.

Fault drills ride the forwarded RMT_INJECT_FAULT exactly as in the
resilience tier: `kill@…` (nonzero rc), `die@…` (clean-rc vanish), and
`stall@…` (watchdog kill) all strike at the run_segmented "segment"
fault points, after which the surviving rank wedges in the next orbax
save barrier — the state the elastic supervisor must shrink out of.
run_segmented's own flight-recorder step bumps (armed via the
launcher's health_dir → RMT_HEALTH env) give the watchdog its
stalled-vs-median signature.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

from rocm_mpi_tpu.utils.backend import set_cpu_device_count

jax.config.update("jax_platforms", "cpu")
set_cpu_device_count(1)  # one device per rank: the mesh is the rank count
jax.config.update("jax_enable_x64", True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=16)
    p.add_argument("--ny", type=int, default=16)
    p.add_argument("--nt", type=int, default=16)
    p.add_argument("--every", type=int, default=4)
    p.add_argument("--keep", type=int, default=3)
    p.add_argument("--dir", required=True)
    # Grow/preempt drills: stretch each segment so the supervisor's
    # rejoin probe (or the test's SIGTERM thread) reliably lands its
    # preemption while the run is still mid-flight (test_elastic.py).
    p.add_argument("--segment-delay-s", type=float, default=0.0)
    args = p.parse_args()

    import jax.numpy as jnp

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.parallel.distributed import (
        maybe_initialize_distributed,
        process_id,
    )
    from rocm_mpi_tpu.telemetry import flight
    from rocm_mpi_tpu.utils import checkpoint as ckpt

    distributed = maybe_initialize_distributed()
    # Relaunches (and the straight-run twins) re-pay identical XLA:CPU
    # compiles without this; the RMT_CPU_CACHE gate keeps it test-only.
    from rocm_mpi_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache()
    # The launcher's health_dir contract (RMT_HEALTH/RMT_HEALTH_DIR):
    # heartbeat sidecars + the SIGUSR2 post-mortem hook, as
    # apps/_common.setup_health wires it.
    if flight.enable_from_env():
        flight.install_postmortem_handler()
    # The launcher's preemption contract (RMT_PREEMPT_GRACE_S →
    # spawn_ranks preempt_grace_s): arm the SIGTERM grace-deadline
    # handler so a preempted rank exits RC_PREEMPTED from a durable
    # boundary instead of dying handler-less (resilience.preempt).
    from rocm_mpi_tpu.resilience import preempt

    preempt.install_from_env()

    cfg = DiffusionConfig(
        global_shape=(args.nx, args.ny), lengths=(10.0, 10.0),
        nt=args.nt, warmup=0, dtype="f64",
    )
    model = HeatDiffusion(cfg)
    T, Cp = model.init_state()
    advance = model.advance_fn("perf")
    if args.segment_delay_s > 0:
        import time

        def adv(s, n):
            time.sleep(args.segment_delay_s)
            return (advance(s[0], Cp, n),)
    else:
        adv = lambda s, n: (advance(s[0], Cp, n),)  # noqa: E731

    start = ckpt.latest_valid_step(args.dir) or 0
    if start:
        # The elastic restore: template rebuilt from manifest topology
        # metadata alone, mesh planned for THIS launch's devices — which
        # may be fewer than the mesh the checkpoint was written on.
        state = ckpt.restore_state(args.dir, start, like=None)
    else:
        state = (jnp.copy(T),)
    if start < args.nt:
        ckpt.run_segmented(adv, state, args.nt, args.dir, args.every,
                           start_step=start, keep=args.keep)
    print(f"ELASTIC_WORKER_DONE rank={process_id()} start={start}",
          flush=True)
    if distributed:
        jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
