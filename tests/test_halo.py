"""Halo-exchange correctness (D2): ghost values, boundary mask, the
shard-vs-global oracle, and the host-staged transport oracle
(SURVEY.md §4 build implication a/c)."""

import jax
import jax.numpy as jnp
import numpy as np
from rocm_mpi_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.parallel import (
    HostStagedStepper,
    exchange_halo,
    global_boundary_mask,
    init_global_grid,
)


def test_exchange_halo_1d_ghost_values():
    grid = init_global_grid(32, lengths=(1.0,), dims=(8,))
    x = jax.device_put(jnp.arange(32.0), grid.sharding)

    @jax.jit
    def padded(x):
        return shard_map(
            lambda b: exchange_halo(b, grid),
            mesh=grid.mesh,
            in_specs=PartitionSpec("gx"),
            out_specs=PartitionSpec("gx"),
        )(x)

    out = np.asarray(padded(x)).reshape(8, 6)  # local 4 + 2 ghosts
    for i in range(8):
        lo, hi = i * 4, (i + 1) * 4
        np.testing.assert_array_equal(out[i, 1:5], np.arange(lo, hi))
        expect_lo = lo - 1 if i > 0 else 0.0  # zero ghost at domain edge
        expect_hi = hi if i < 7 else 0.0
        assert out[i, 0] == expect_lo
        assert out[i, 5] == expect_hi


def test_exchange_halo_2d_corner_ghosts():
    grid = init_global_grid(8, 8, dims=(2, 2))
    x = jax.device_put(
        jnp.arange(64.0).reshape(8, 8), grid.sharding
    )

    @jax.jit
    def padded(x):
        return shard_map(
            lambda b: exchange_halo(b, grid),
            mesh=grid.mesh,
            in_specs=grid.spec,
            out_specs=grid.spec,
        )(x)

    out = np.asarray(padded(x))  # (12, 12): each 6x6 block is a padded shard
    g = np.arange(64.0).reshape(8, 8)
    blk = out[:6, :6]  # shard (0,0)
    np.testing.assert_array_equal(blk[1:5, 1:5], g[0:4, 0:4])
    np.testing.assert_array_equal(blk[5, 1:5], g[4, 0:4])  # ghost from (1,0)
    np.testing.assert_array_equal(blk[1:5, 5], g[0:4, 4])  # ghost from (0,1)
    # Corner ghost from the diagonal neighbor (two-stage corner trick).
    assert blk[5, 5] == g[4, 4]
    # Domain-edge ghosts are zero.
    np.testing.assert_array_equal(blk[0, :], 0.0)
    np.testing.assert_array_equal(blk[:, 0], 0.0)


def test_global_boundary_mask():
    grid = init_global_grid(8, 8, dims=(2, 2))

    @jax.jit
    def mask():
        return shard_map(
            lambda: global_boundary_mask(grid),
            mesh=grid.mesh,
            in_specs=(),
            out_specs=grid.spec,
        )()

    m = np.asarray(mask())
    expect = np.zeros((8, 8), dtype=bool)
    expect[0, :] = expect[-1, :] = expect[:, 0] = expect[:, -1] = True
    np.testing.assert_array_equal(m, expect)


def test_shard_variant_matches_ap_oracle():
    # Explicit ppermute halo path vs the GSPMD global-array path: the §4c
    # 1-device-vs-n-device equivalence oracle, across a 4x2 mesh.
    cfg = DiffusionConfig(global_shape=(64, 48), nt=50, warmup=0, dims=(4, 2))
    model = HeatDiffusion(cfg)
    res_ap = model.run(variant="ap")
    res_shard = model.run(variant="shard")
    np.testing.assert_allclose(
        np.asarray(res_ap.T), np.asarray(res_shard.T), rtol=1e-13, atol=1e-15
    )


def test_host_staged_oracle_matches_device_path():
    # IGG_ROCMAWARE_MPI=0 analog: host-staged numpy exchange must agree with
    # the ICI (ppermute) path exactly — the reference's transport-bisection
    # affordance (README.md:25-35).
    cfg = DiffusionConfig(
        global_shape=(32, 32), nt=20, warmup=0, dims=(2, 2),
        halo_transport="host",
    )
    model = HeatDiffusion(cfg)
    res_host = model.run(variant="shard")

    cfg_ici = DiffusionConfig(global_shape=(32, 32), nt=20, warmup=0, dims=(2, 2))
    res_ici = HeatDiffusion(cfg_ici).run(variant="shard")
    np.testing.assert_allclose(
        np.asarray(res_host.T), np.asarray(res_ici.T), rtol=1e-13, atol=1e-15
    )


def test_host_stepper_3d_smoke():
    grid = init_global_grid(8, 8, 8, dims=(2, 2, 2))
    rng = np.random.default_rng(0)
    T = rng.random((8, 8, 8))
    Cp = np.ones_like(T) * 1.5
    stepper = HostStagedStepper(grid, lam=1.0, dt=1e-4)
    out = stepper.step(T, Cp)
    # Boundary fixed, interior changed.
    np.testing.assert_array_equal(out[0], T[0])
    assert not np.array_equal(out[1:-1, 1:-1, 1:-1], T[1:-1, 1:-1, 1:-1])
