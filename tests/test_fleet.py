"""The fleet (docs/SERVING.md "The fleet"; ISSUE 16).

Covers the durable ticket journal (rmt-fleet-journal v1: record
validation, segment sealing, torn-tail tolerance, replay idempotence,
the exactly-one-terminal invariant), the merged fleet report
(rmt-fleet-report v1: validator, atomic writer, regress recognition),
the router policy (program-class affinity determinism, session
stickiness, deterministic spillover under a saturated replica, the
merged retry-after fast reject), the autoscaler (whole-replica
grow/retire on aggregate depth), the FLEET badge, and THE acceptance
drill: a 3-replica fleet with replica 1 killed mid-traffic via the
fault grammar — every journaled ticket reaches exactly one terminal
state fleet-wide, surviving tenants bitwise-equal to a standalone
twin. The gloo-real 2-rank edition drives tests/serving_worker.py
--fleet via spawn_ranks.
"""

from __future__ import annotations

import json
import pathlib

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

from rocm_mpi_tpu.serving import journal as fjournal  # noqa: E402
from rocm_mpi_tpu.serving.queue import Request  # noqa: E402


def _req(rid, shape=(16, 16), nt=4, workload="diffusion", **kw):
    return Request(request_id=rid, workload=workload,
                   global_shape=shape, nt=nt, **kw)


def _service(**cfg):
    from rocm_mpi_tpu.serving.service import (
        ServeConfig,
        SimulationService,
    )

    cfg.setdefault("max_width", 2)
    return SimulationService(config=ServeConfig(**cfg))


def _router(tmp_path, n=3, name="fleet-journal.jsonl", **kw):
    from rocm_mpi_tpu.serving.router import FleetRouter

    journal = fjournal.TicketJournal(tmp_path / name)
    return FleetRouter(lambda rid: _service(), n, journal=journal,
                       **kw), journal


def _mixed_trace(tag, n=9):
    """Three bins over two shapes (same mix the soak fleet episode
    paces): i % 3 == 0 is the (24, 24) class, the rest split (16, 16)
    by step count."""
    return [
        _req(
            f"{tag}-{i:02d}",
            shape=(16, 16) if i % 3 else (24, 24),
            nt=3 + (i % 3),
            ic_scale=1.0 + 0.015 * i,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# The ticket journal
# ---------------------------------------------------------------------------


def test_journal_record_validation():
    good = {"schema": fjournal.JOURNAL_SCHEMA,
            "v": fjournal.JOURNAL_VERSION, "kind": "route",
            "seq": 3, "request_id": "r1", "replica": 0}
    assert fjournal.validate_journal_record(good) == []
    assert fjournal.validate_journal_record({}) != []
    bad_kind = dict(good, kind="nope")
    assert any("kind" in p
               for p in fjournal.validate_journal_record(bad_kind))
    bad_state = {"schema": fjournal.JOURNAL_SCHEMA,
                 "v": fjournal.JOURNAL_VERSION, "kind": "terminal",
                 "seq": 4, "request_id": "r1", "state": "vaporized"}
    assert any("state" in p
               for p in fjournal.validate_journal_record(bad_state))
    no_replica = dict(good, replica=None)
    assert any("replica" in p
               for p in fjournal.validate_journal_record(no_replica))


def test_journal_append_replay_and_seq_resume(tmp_path):
    path = tmp_path / "fleet-journal.jsonl"
    j = fjournal.TicketJournal(path)
    j.record_submit("a", bin_key="bin-a")
    j.record_route("a", 0)
    j.record_terminal("a", "done", replica=0)
    j.record_submit("b", session="sess-b", bin_key="bin-b")
    j.record_route("b", 1)
    j.close()

    state = fjournal.replay([path])
    assert state.counts()["tickets"] == 2
    assert state.counts()["terminal"]["done"] == 1
    assert state.open_on(1) == ["b"]
    assert state.open_on(0) == []
    assert state.tickets["b"]["session"] == "sess-b"

    # A reopened journal resumes the seq counter past what's on disk —
    # single-writer monotonicity survives a router restart.
    j2 = fjournal.TicketJournal(path)
    j2.record_terminal("b", "done", replica=1)
    j2.close()
    docs = [json.loads(l) for l in path.read_text().splitlines()]
    seqs = [d["seq"] for d in docs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert fjournal.replay([path]).counts()["open"] == 0


def test_journal_replay_is_idempotent_and_tolerates_torn_tail(tmp_path):
    path = tmp_path / "fleet-journal.jsonl"
    j = fjournal.TicketJournal(path)
    for i in range(4):
        j.record_submit(f"r{i}")
        j.record_route(f"r{i}", i % 2)
        j.record_terminal(f"r{i}", "done", replica=i % 2)
    j.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"torn')  # the mid-write kill artifact

    first = fjournal.replay([path])
    again = fjournal.replay([path])
    # Replaying a complete journal changes no counter: a pure fold.
    assert first.counts() == again.counts()
    assert first.counts()["torn_lines"] == 1
    assert first.counts()["terminal"]["done"] == 4
    assert fjournal.exactly_one_terminal(first) == []


def test_journal_segments_seal_atomically(tmp_path):
    path = tmp_path / "fleet-journal.jsonl"
    j = fjournal.TicketJournal(path)
    j.record_submit("a")
    sealed = j.seal_segment()
    assert sealed is not None and sealed.exists()
    assert not list(tmp_path.glob("*.tmp"))
    j.record_submit("b")
    j.record_route("a", 0)
    segs = j.segments()
    assert segs[-1] == path and sealed in segs
    state = fjournal.replay(segs)
    assert state.counts()["tickets"] == 2
    assert state.open_on(0) == ["a"]
    # Sealing an empty live segment is a no-op.
    j.seal_segment()
    assert j.seal_segment() is None
    j.close()


def test_exactly_one_terminal_names_the_violations():
    state = fjournal.JournalState()
    mk = fjournal.JOURNAL_SCHEMA, fjournal.JOURNAL_VERSION

    def rec(kind, seq, rid, **kw):
        state.apply({"schema": mk[0], "v": mk[1], "kind": kind,
                     "seq": seq, "request_id": rid, **kw})

    rec("submit", 0, "lost")
    rec("route", 1, "lost", replica=0)
    rec("submit", 2, "double")
    rec("route", 3, "double", replica=1)
    rec("terminal", 4, "double", state="done", replica=1)
    rec("terminal", 5, "double", state="expired", replica=1)
    rec("terminal", 6, "ghost", state="done", replica=0)
    problems = fjournal.exactly_one_terminal(state)
    assert any("lost" in p and "no terminal" in p for p in problems)
    assert any("double" in p and "2 terminal" in p for p in problems)
    assert any("ghost" in p for p in problems)


# ---------------------------------------------------------------------------
# The merged fleet report
# ---------------------------------------------------------------------------


def _report_doc(**over):
    slo = {"submitted": 2, "done": 2, "failed": 0, "rejected": 0,
           "expired": 0, "quarantined": 0, "retries": 0}
    counts = {"tickets": 2, "open": 0, "rerouted": 1, "torn_lines": 0,
              "terminal": {"done": 2, "failed": 0, "rejected": 0,
                           "expired": 0, "quarantined": 0}}
    doc = fjournal.fleet_report_doc(
        [{"id": 0, "alive": True, "steady_state": 0},
         {"id": 1, "alive": False, "steady_state": 0}],
        slo, counts, accounting_ok=True,
        autoscale=[{"event": "fleet.grow", "replica": 2}],
    )
    doc.update(over)
    return doc


def test_fleet_report_roundtrip_and_gate(tmp_path):
    doc = _report_doc()
    assert fjournal.validate_fleet_report(doc) == []
    path = tmp_path / "fleet-report.json"
    fjournal.write_fleet_report(path, doc)
    assert path.is_file() and not list(tmp_path.glob("*.tmp"))

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([path]) == []

    # Doctored docs fail the writer AND the regress gate.
    bad = _report_doc(replicas=[])
    assert fjournal.validate_fleet_report(bad) != []
    with pytest.raises(ValueError):
        fjournal.write_fleet_report(tmp_path / "never.json", bad)
    bad2 = _report_doc()
    del bad2["journal"]["terminal"]["expired"]
    bad2_path = tmp_path / "bad-fleet-report.json"
    bad2_path.write_text(json.dumps(bad2))
    assert any("terminal" in p for p in check_schema([bad2_path]))


def test_fleet_schema_spellings_pinned_against_regress():
    """telemetry.regress spells the fleet journal marker locally
    (stdlib read side) — drift from serving.journal must fail loudly;
    the report schema is imported (journal.py is stdlib-at-import)."""
    from rocm_mpi_tpu.telemetry import regress

    assert regress._FLEET_JOURNAL_SCHEMA == fjournal.JOURNAL_SCHEMA
    assert fjournal.FLEET_REPORT_SCHEMA == "rmt-fleet-report"
    from rocm_mpi_tpu.serving.queue import TERMINAL_STATES

    assert fjournal.TERMINAL_STATES == TERMINAL_STATES


def test_fleet_journal_lines_pass_regress_check_schema(tmp_path):
    path = tmp_path / "fleet-journal.jsonl"
    j = fjournal.TicketJournal(path)
    j.record_submit("a", session="s", bin_key="b")
    j.record_route("a", 0)
    j.record_terminal("a", "done", replica=0)
    j.close()

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([path]) == []
    # A doctored line (bad terminal state) is caught per-line.
    doc = json.loads(path.read_text().splitlines()[-1])
    doc["state"] = "vaporized"
    bad = tmp_path / "bad-fleet-journal.jsonl"
    bad.write_text(json.dumps(doc) + "\n")
    assert any("state" in p for p in check_schema([bad]))


# ---------------------------------------------------------------------------
# The FLEET badge
# ---------------------------------------------------------------------------


def test_fleet_badge():
    from rocm_mpi_tpu.telemetry import health

    assert health.fleet_status(None) is None
    assert health.fleet_status({"schema": "rmt-soak-report"}) is None
    doc = _report_doc()
    st = health.fleet_status(doc)
    assert st["live"] == 1 and st["total"] == 2
    assert st["done"] == 2 and st["rerouted"] == 1
    line = health.format_fleet_status(st)
    assert line == "fleet idle (1/2 up — 2 done, 1 rerouted)"
    busy = dict(st, depth=3, accounting_ok=False)
    line2 = health.format_fleet_status(busy)
    assert line2.startswith("[FLEET 1/2 up — depth=3")
    assert "ACCOUNTING BROKEN" in line2


# ---------------------------------------------------------------------------
# Router policy (no draining needed: routing is pre-drain state)
# ---------------------------------------------------------------------------


def test_affinity_determinism_same_trace_same_map(tmp_path):
    ra, ja = _router(tmp_path / "a", n=3)
    rb, jb = _router(tmp_path / "b", n=3)
    trace = _mixed_trace("det", n=9)
    for r in trace:
        ra.submit(r)
        rb.submit(r)
    assert ra.replica_map() == rb.replica_map()
    assert len(set(ra.replica_map().values())) == 3  # bins spread
    # The journal's route trail agrees request-by-request.
    routes_a = {k: v["routes"] for k, v in ra.journal_state().tickets.items()}
    routes_b = {k: v["routes"] for k, v in rb.journal_state().tickets.items()}
    assert routes_a == routes_b
    ja.close(), jb.close()


def test_spillover_ordering_under_saturated_replica(tmp_path):
    router, journal = _router(tmp_path, n=3, max_depth_per_replica=2)
    # Pin one bin to replica 0 and fill it to the bound.
    t0 = router.submit(_req("sat-0", nt=3))
    router.submit(_req("sat-1", nt=3, ic_scale=1.1))
    (bkey, rid0), = router.replica_map().items()
    assert router.replica(rid0).depth() == 2
    # Same-bin overflow spills WITHOUT moving the affinity, in
    # deterministic (depth, id) order over the replicas with room.
    s1 = router.submit(_req("sat-2", nt=3, ic_scale=1.2))
    s2 = router.submit(_req("sat-3", nt=3, ic_scale=1.3))
    assert router.replica_map() == {bkey: rid0}
    spill_rids = [router._tickets[t].replica for t in ("sat-2", "sat-3")]
    others = sorted(r.id for r in router.replicas if r.id != rid0)
    assert spill_rids == others, spill_rids
    assert s1.state == "queued" and s2.state == "queued"
    assert t0.state == "queued"
    journal.close()


def test_fleet_full_fast_reject_carries_merged_hint(tmp_path):
    router, journal = _router(tmp_path, n=2, max_depth_per_replica=1)
    router.submit(_req("full-0", nt=3))
    router.submit(_req("full-1", nt=3, ic_scale=1.1))
    assert all(r.depth() == 1 for r in router.replicas)
    t = router.submit(_req("full-2", nt=3, ic_scale=1.2))
    assert t.state == "rejected"
    assert "fleet-full" in t.error and "retry-after" in t.error
    assert router.router_rejected == 1
    # The reject is journaled terminal — no lost ticket, and the hint
    # is the bounded merged minimum.
    state = router.journal_state()
    assert state.tickets["full-2"]["terminals"] == [("rejected", None)]
    from rocm_mpi_tpu.serving.queue import (
        DEFAULT_RETRY_AFTER_S,
        MAX_RETRY_AFTER_S,
    )

    hint = router.retry_after_hint()
    assert 0.01 <= hint <= MAX_RETRY_AFTER_S
    assert hint == DEFAULT_RETRY_AFTER_S  # no completions yet: default
    journal.close()


def test_session_affinity_sticks_and_survives_kill(tmp_path):
    router, journal = _router(tmp_path, n=3)
    t = router.submit(_req("sess-0", nt=3, session="tenant-a"))
    pinned = router._tickets["sess-0"].replica
    # Later sessioned traffic follows the pin even when other replicas
    # are emptier.
    router.submit(_req("other-0", nt=4, ic_scale=1.2))
    t2 = router.submit(_req("sess-1", nt=3, ic_scale=1.1,
                            session="tenant-a"))
    assert router._tickets["sess-1"].replica == pinned
    # Kill the pinned replica: the session unpins and its OPEN tickets
    # re-route (step manifests make the replay at-most-once).
    router.kill_replica(pinned, verdict="test-kill")
    assert router._sessions["tenant-a"] != pinned
    new_home = router._tickets["sess-0"].replica
    assert new_home != pinned
    assert router._tickets["sess-1"].replica == new_home
    t3 = router.submit(_req("sess-2", nt=3, ic_scale=1.3,
                            session="tenant-a"))
    assert router._tickets["sess-2"].replica == new_home
    assert t.state == t2.state == t3.state == "queued"
    journal.close()


def test_router_reconcile_is_idempotent(tmp_path):
    router, journal = _router(tmp_path, n=3)
    for r in _mixed_trace("rec", n=6):
        router.submit(r)
    before = {k: v.replica for k, v in router._tickets.items()}
    victim = 1
    router.kill_replica(victim, verdict="test")
    moved = {k: v.replica for k, v in router._tickets.items()}
    assert all(rid != victim for rid in moved.values())
    assert any(before[k] == victim for k in before), "nothing to move?"
    rerouted = router.journal_state().counts()["rerouted"]
    assert rerouted >= 1
    # A second reconcile of the same replica finds nothing open on it:
    # the journal already shows every moved ticket's last route
    # elsewhere.
    router._reconcile(victim)
    assert {k: v.replica for k, v in router._tickets.items()} == moved
    assert router.journal_state().counts()["rerouted"] == rerouted
    journal.close()


# ---------------------------------------------------------------------------
# The autoscaler
# ---------------------------------------------------------------------------


def test_autoscaler_grows_and_retires_whole_replicas(tmp_path):
    from rocm_mpi_tpu.resilience.policy import ElasticPolicy

    router, journal = _router(
        tmp_path, n=1,
        policy=ElasticPolicy(min_grow_interval_steps=0),
        max_replicas=2, grow_queue_depth=2, idle_retire_ticks=2,
    )
    for i in range(4):
        router.submit(_req(f"scale-{i}", nt=2, ic_scale=1.0 + 0.1 * i))
    router._tick += 1
    assert router.maybe_scale() is True
    assert len(router.replicas) == 2
    assert router.autoscale_events[0]["event"] == "fleet.grow"
    # At the ceiling: no further grow.
    router._tick += 1
    assert router.maybe_scale() is False
    router.drive()
    # Sustained idleness retires the highest-id replica with the
    # rc-75 drain signal stamped on the event.
    for _ in range(4):
        router.drive_once()
        if len(router.healthy_replicas()) == 1:
            break
    retire = [e for e in router.autoscale_events
              if e["event"] == "fleet.retire"]
    assert retire and retire[0]["replica"] == 1
    assert retire[0]["signal"] == "rc-75"
    assert not router.replica(1).alive
    assert router.check_accounting() == []
    journal.close()


# ---------------------------------------------------------------------------
# THE acceptance drill
# ---------------------------------------------------------------------------


def test_fleet_kill_drill_three_replicas(tmp_path):
    """THE ISSUE-16 acceptance: replica 1 of 3 killed mid-traffic via
    the fault grammar — every journaled ticket reaches exactly one
    terminal state fleet-wide, survivors' results bitwise-equal to a
    standalone twin, merged report schema-valid, steady_state 0 per
    replica."""
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.telemetry import compiles

    compiles.reset()
    router, journal = _router(tmp_path, n=3)
    faults.install("replica-kill@step=2,rank=1")
    try:
        reqs = _mixed_trace("drill", n=9)
        tickets = []
        for i in range(0, len(reqs), 3):
            tickets += [router.submit(r) for r in reqs[i:i + 3]]
            router.drive_once()
        router.drive()
    finally:
        faults.install(None)

    assert [r.id for r in router.replicas if not r.alive] == [1]
    assert router.replica(1).verdict == "injected-kill"
    assert router.check_accounting() == []
    state = router.journal_state()
    assert fjournal.exactly_one_terminal(state) == []
    counts = state.counts()
    assert counts["open"] == 0 and counts["rerouted"] >= 1

    twin = _service()
    twin_tickets = [twin.queue.submit(r) for r in _mixed_trace("drill", n=9)]
    while twin.queue.depth():
        twin.drain_once()
    for t, ref in zip(tickets, twin_tickets):
        assert t.state == "done", (t.request.request_id, t.error)
        for a, b in zip(t.result(timeout=5), ref.result(timeout=5)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    doc = router.report_doc()
    assert fjournal.validate_fleet_report(doc) == []
    assert doc["accounting_ok"] is True
    for row in doc["replicas"]:
        assert row["steady_state"] == 0, row
    journal.close()


def test_fleet_stall_demotion_reroutes(tmp_path):
    """replica-stall demotes (up but untrusted): no new routes, its
    pending tickets re-route, and the fleet still balances."""
    from rocm_mpi_tpu.resilience import faults

    router, journal = _router(tmp_path, n=2)
    faults.install("replica-stall@step=1,rank=0")
    try:
        tickets = [router.submit(r) for r in _mixed_trace("stall", n=6)]
        router.drive()
    finally:
        faults.install(None)
    rep = router.replica(0)
    assert rep.alive and rep.demoted
    assert rep.verdict == "injected-stall"
    assert router.check_accounting() == []
    for t in tickets:
        assert t.state == "done", (t.request.request_id, t.error)
    assert all(
        rec.replica == 1 for rec in router._tickets.values()
    )
    journal.close()


def test_fleet_gloo_two_rank_smoke(tmp_path):
    """Gloo-real 2-rank fleet smoke: every rank mirrors the same
    2-replica router over the SAME trace and must print the identical
    replica map (routing is a pure fold — the GL08 hazard class would
    diverge the batched collectives otherwise)."""
    from rocm_mpi_tpu.parallel.launcher import spawn_ranks

    results = spawn_ranks(
        [REPO / "tests" / "serving_worker.py", "--fleet"],
        nprocs=2, timeout=420,
    )
    lines = []
    for rank, (proc, (out, err)) in enumerate(results):
        assert proc.returncode == 0, (rank, out[-500:], err[-2000:])
        done = [l for l in out.splitlines() if "FLEET_WORKER_DONE" in l]
        assert len(done) == 1, out
        assert f"rank={rank}" in done[0]
        assert "done=6" in done[0], done[0]
        lines.append(done[0].split("map=", 1)[1])
    assert lines[0] == lines[1], lines
