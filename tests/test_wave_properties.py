"""Property-based test of the wave workload's sharded path (hypothesis):
for arbitrary shapes, mesh dims, and step counts, the shard_map + halo +
Pallas 'perf' path must reproduce the transparent numpy leapfrog oracle —
the machine-checked generalization of test_wave.py's hand-picked cases
(the same §5.2-analog strategy as tests/test_halo_properties.py)."""

from __future__ import annotations

import os

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from rocm_mpi_tpu.models.wave import AcousticWave  # noqa: E402

# Sibling test module (tests/ has no __init__; pytest's default
# prepend-import puts this directory on sys.path during collection).
from test_wave import _cfg, _numpy_leapfrog  # noqa: E402


@st.composite
def wave_cases(draw):
    ndim = draw(st.integers(2, 3))
    dims, shape = [], []
    budget = 8  # device budget (conftest provides 8)
    for _ in range(ndim):
        d = draw(st.sampled_from([1, 2, 4]))
        while d > 1 and d * int(np.prod(dims or [1])) > budget:
            d //= 2
        local = draw(st.integers(3, 6))
        dims.append(d)
        shape.append(d * local)
    n_steps = draw(st.integers(1, 12))
    return tuple(shape), tuple(dims), n_steps


@given(wave_cases())
@settings(max_examples=int(os.environ.get("RMT_PROP_EXAMPLES", "20")), deadline=None)
def test_wave_perf_matches_oracle_property(case):
    shape, dims, n_steps = case
    cfg = _cfg(shape=shape, dims=dims, nt=max(n_steps, 2) + 1, warmup=0)
    model = AcousticWave(cfg)
    U, Uprev, C2 = model.init_state()
    ref = _numpy_leapfrog(U, Uprev, C2, cfg.dt, cfg.spacing, n_steps)
    got, _ = model.advance_fn("perf")(U, Uprev, C2, n_steps)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-11, atol=1e-13)
