"""Native host-staging engine (native/halostage.cpp): must be bit-identical
to the pure-numpy oracle. Skipped when the library isn't built
(`make -C native`)."""

import numpy as np
import pytest

from rocm_mpi_tpu.parallel import HostStagedStepper, init_global_grid
from rocm_mpi_tpu.parallel import native_halo

pytestmark = pytest.mark.skipif(
    not native_halo.available(), reason="native library not built"
)


@pytest.mark.parametrize(
    "shape,dims",
    [((64, 48), (4, 2)), ((24, 24, 24), (2, 2, 2)), ((40,), (8,))],
)
def test_native_bit_identical_to_numpy(shape, dims):
    grid = init_global_grid(*shape, dims=dims)
    rng = np.random.default_rng(1)
    T = rng.random(shape)
    Cp = 1.0 + rng.random(shape)
    stepper = HostStagedStepper(grid, lam=1.3, dt=1e-4)
    ref = stepper.step_python(T, Cp)
    got = native_halo.host_staged_step(
        T, Cp, dims, grid.spacing, 1.3, 1e-4
    )
    np.testing.assert_array_equal(ref, got)


def test_stepper_auto_dispatch_matches_python():
    grid = init_global_grid(32, 32, dims=(2, 2))
    rng = np.random.default_rng(2)
    T, Cp = rng.random((32, 32)), np.ones((32, 32))
    s = HostStagedStepper(grid, 1.0, 1e-4)
    assert s.use_native
    np.testing.assert_array_equal(s.step(T, Cp), s.step_python(T, Cp))


def test_native_rejects_bad_geometry():
    with pytest.raises(ValueError, match="code 2"):
        native_halo.host_staged_step(
            np.zeros((10, 10)), np.ones((10, 10)), (3, 3), (0.1, 0.1), 1.0, 1e-4
        )


def test_single_thread_matches_threaded():
    grid = init_global_grid(64, 64, dims=(4, 2))
    rng = np.random.default_rng(3)
    T, Cp = rng.random((64, 64)), 1.0 + rng.random((64, 64))
    a = native_halo.host_staged_step(T, Cp, (4, 2), grid.spacing, 1.0, 1e-4, threads=1)
    b = native_halo.host_staged_step(T, Cp, (4, 2), grid.spacing, 1.0, 1e-4, threads=8)
    np.testing.assert_array_equal(a, b)
