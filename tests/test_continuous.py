"""Continuous batching (PR 19, docs/SERVING.md "Continuous batching").

Covers the step-granular lane swap (segmented batched drain: resolved
lanes swap OUT and queued same-program-class requests swap IN at
segment boundaries of ONE compiled program) and the program-
consolidation shape-padding ladder (serving/bins.ladder_shape), plus
their gates: the bin scheduler's exactly-at-floor boundary, the
ladder's split-instead-of-pad tolerance rule, BinStats' ladder-waste
vs width-padding-waste accounting, the manifest `continuous` block and
budgets-row schema gates, and the two acceptance drills —

* the bitwise pin: a lane swapped in at a segment boundary produces
  results identical to its standalone run, on all three workloads plus
  a resume-session lane, with `compiles.steady_state == 0` across the
  whole swap-heavy trace;
* the utilization win: under the heavy-tailed trace, the continuous
  drain shows strictly higher step-weighted occupancy (above the
  committed `serving.occupancy` floor) and no worse device-bubble than
  batch-synchronous at equal results, and the ladder provably reduces
  program-class count within `padded_flops_tolerance`.
"""

from __future__ import annotations

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

from rocm_mpi_tpu.config import DiffusionConfig  # noqa: E402
from rocm_mpi_tpu.models import HeatDiffusion  # noqa: E402
from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater  # noqa: E402
from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig  # noqa: E402
from rocm_mpi_tpu.serving import bins as sbins  # noqa: E402
from rocm_mpi_tpu.serving.queue import Request  # noqa: E402
from rocm_mpi_tpu.serving.service import (  # noqa: E402
    ServeConfig,
    SimulationService,
)
from rocm_mpi_tpu.telemetry import compiles  # noqa: E402


def _put(arr, sharding):
    return jax.device_put(np.asarray(arr), sharding)


# ---------------------------------------------------------------------------
# The bin scheduler's occupancy-floor boundary and the ladder rules
# ---------------------------------------------------------------------------


def test_plan_batches_exactly_at_occupancy_floor_is_kept():
    """The split rule is STRICTLY below the floor: a tail batch whose
    occupancy lands exactly ON `occupancy_floor` keeps its width — only
    dropping below it forces the narrower split."""
    # 5 lanes at width 8 = 0.625 occupancy: exactly at the floor, kept.
    assert sbins.plan_batches(5, 8, occupancy_floor=0.625) == [8]
    # One epsilon above the same ratio: 0.625 < 0.626 now splits.
    assert sbins.plan_batches(5, 8, occupancy_floor=0.626) == [4, 1]


def test_ladder_rung_values_and_quantum():
    # quantum = max(4, pow2_floor(n) // 4): 30 -> q 4 -> 32; 126 -> q 16
    assert sbins.ladder_rung(30) == 32
    assert sbins.ladder_rung(14) == 16
    assert sbins.ladder_rung(126) == 128
    assert sbins.ladder_rung(62) == 64
    assert sbins.ladder_rung(32) == 32  # already on a rung
    with pytest.raises(ValueError, match=">= 1"):
        sbins.ladder_rung(0)


def test_ladder_shape_split_instead_of_pad():
    """A rung whose padded-FLOPs inflation exceeds the tolerance must
    NOT pad — the shape keeps its exact program class (the shape
    edition of the occupancy floor's split rule)."""
    assert sbins.ladder_shape((30, 14)) == (32, 16)
    infl = sbins.ladder_inflation((30, 30), (32, 32))
    assert infl == pytest.approx(0.1378, abs=1e-3)
    assert sbins.ladder_shape((30, 30)) == (32, 32)
    # (5, 5) -> rung (8, 8) inflates 64/25 - 1 = 1.56 > 0.25: unchanged
    assert sbins.ladder_inflation((5, 5), (8, 8)) > 1.5
    assert sbins.ladder_shape((5, 5)) == (5, 5)
    # tolerance 0 admits only exact-rung shapes
    assert sbins.ladder_shape((30, 30), tolerance=0.0) == (30, 30)
    assert sbins.ladder_shape((32, 32), tolerance=0.0) == (32, 32)
    with pytest.raises(ValueError, match=">= 0"):
        sbins.ladder_shape((16, 16), tolerance=-0.1)


def test_binstats_ladder_waste_distinct_from_width_padding():
    """`ladder_waste` counts padded CELLS, `padding_waste` counts idle
    and frozen lane STEPS — a batch can carry one without the other,
    and the manifest reports them separately."""
    key = sbins.BinKey("diffusion", (32, 32), "f32", (), "shard",
                       "f32", 4)
    # Width padding only: full-width exact-shape lanes, mixed lengths.
    st = sbins.BinStats(key=key)
    st.note_batch(4, [6, 3, 6], 6)
    assert st.padding_waste == pytest.approx(1 - 15 / 24)
    assert st.ladder_waste == 0.0  # no cell accounting banked
    st.note_batch(1, [6], 6, split=True)
    assert st.splits == 1

    # Ladder padding only: every slot live every step, but each lane's
    # 30x30 domain rides the 32x32 rung program.
    st2 = sbins.BinStats(key=key)
    st2.note_continuous(2, [4, 4], 4, swaps_in=0, segments=2,
                        lane_cells=[(900, 1024), (900, 1024)])
    assert st2.padding_waste == 0.0
    assert st2.ladder_waste == pytest.approx(1 - 900 / 1024)
    assert st2.swaps_in == 0 and st2.segments == 2

    # Continuous accounting caps slot occupancy at the compiled width
    # even when swaps seat more tenants than slots.
    st3 = sbins.BinStats(key=key)
    st3.note_continuous(2, [4, 3, 4], 8, swaps_in=1, segments=4,
                        lane_cells=[(1024, 1024)] * 3)
    assert st3.live_lanes == 2 and st3.requests == 3
    assert st3.ladder_waste == 0.0
    assert st3.padding_waste == pytest.approx(1 - 11 / 16)


# ---------------------------------------------------------------------------
# The acceptance bitwise pin: swap-heavy trace, three workloads + resume
# ---------------------------------------------------------------------------


def _swap_trace(tag: str):
    """Three same-bucket groups (bucket 4) on one shape class, more
    lanes than width so every group swaps at segment boundaries."""
    mix = (
        [("diffusion", 4 if i % 2 == 0 else 3) for i in range(6)]
        + [("wave", 4 if i % 2 == 0 else 3) for i in range(4)]
        + [("swe", 4 if i % 2 == 0 else 3) for i in range(4)]
    )
    return [
        Request(request_id=f"{tag}-{wl}-{i:02d}", workload=wl,
                global_shape=(16, 16), dtype="f64", nt=nt,
                ic_scale=1.0 + 0.03 * i)
        for i, (wl, nt) in enumerate(mix)
    ]


def test_segmented_swap_bitwise_all_workloads_and_resume(tmp_path):
    """The tentpole pin: a swap-heavy trace through segments=2 width-2
    programs — every result bitwise-equal to a batch-synchronous
    width-1 twin service AND to direct standalone advance runs, a
    resume-session lane rides the same segmented group, and the whole
    trace recompiles nothing (`compiles.steady_state == 0`)."""
    compiles.install()
    sessions = tmp_path / "sessions"
    svc = SimulationService(config=ServeConfig(
        max_width=2, segments=2, sessions_dir=str(sessions),
    ))
    # Seed the session: its own bucket-2 program, compiled pre-trace.
    seed = Request(request_id="seed", workload="diffusion",
                   global_shape=(16, 16), dtype="f64", nt=2,
                   ic_scale=1.2, session="cont-sess")
    svc.queue.submit(seed)
    svc._drain_all()

    trace = _swap_trace("swap")
    resume = Request(request_id="res", workload="diffusion",
                     global_shape=(16, 16), dtype="f64", nt=4,
                     ic_scale=1.2, session="cont-sess", resume=True)
    tickets = [svc.queue.submit(r) for r in trace]
    t_res = svc.queue.submit(resume)
    report = svc._drain_all()
    assert report.served == len(trace) + 1 and report.failed == 0
    assert report.compiles["steady_state"] == 0
    assert report.continuous["segments"] == 2
    assert report.continuous["swaps_in"] >= 3  # every group re-seats
    assert t_res.start_step == 2 and t_res.steps_run == 2

    # Twin service: batch-synchronous, one lane per program.
    tw_sessions = tmp_path / "tw-sessions"
    twin = SimulationService(config=ServeConfig(
        max_width=1, sessions_dir=str(tw_sessions),
    ))
    twin.queue.submit(Request(
        request_id="seed-tw", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=2, ic_scale=1.2,
        session="cont-sess", ))
    twin._drain_all()
    tw_tickets = [twin.queue.submit(r) for r in _swap_trace("swap")]
    tw_res = twin.queue.submit(Request(
        request_id="res-tw", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=4, ic_scale=1.2,
        session="cont-sess", resume=True,
    ))
    twin._drain_all()
    for i, (a, b) in enumerate(zip(tickets, tw_tickets)):
        for la, lb in zip(a.result(timeout=5), b.result(timeout=5)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), i
    for la, lb in zip(t_res.result(timeout=5), tw_res.result(timeout=5)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))

    # Direct standalone pins for one swapped-in lane per workload (the
    # initial roster holds the first two lanes of each group — index 5
    # in each group arrived via a segment-boundary swap) and the resume
    # lane vs one uninterrupted run.
    dlane = trace[5]
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 1))
    m = HeatDiffusion(cfg, devices=jax.devices()[:1])
    T0, Cp = m.init_state()
    adv = m.advance_fn("shard")
    ref = np.asarray(adv(
        jnp.asarray(np.asarray(T0) * dlane.ic_scale), Cp, dlane.nt))
    assert np.array_equal(tickets[5].result(timeout=5)[0], ref)

    wlane = trace[9]  # 4th wave request: swapped in
    wcfg = WaveConfig(global_shape=(16, 16), nt=8, warmup=0,
                      dtype="f64", dims=(1, 1))
    w = AcousticWave(wcfg, devices=jax.devices()[:1])
    U0, _, _C2 = w.init_state()
    U0s = np.asarray(U0) * wlane.ic_scale
    rU, rUp = w.advance_fn("shard")(
        jnp.asarray(U0s), jnp.asarray(U0s.copy()), _C2, wlane.nt)
    got_w = tickets[9].result(timeout=5)
    assert np.array_equal(got_w[0], np.asarray(rU))
    assert np.array_equal(got_w[1], np.asarray(rUp))

    slane = trace[13]  # 4th swe request: swapped in
    scfg = SWEConfig(global_shape=(16, 16), nt=8, warmup=0,
                     dtype="f64", dims=(1, 1))
    s = ShallowWater(scfg, devices=jax.devices()[:1])
    h0, _ = s.init_state()
    Mus = s.face_masks()
    rh, rus = s.advance_fn("shard")(
        _put(np.asarray(h0) * slane.ic_scale, s.grid.sharding),
        tuple(_put(np.zeros(scfg.global_shape), s.grid.sharding)
              for _ in range(2)),
        Mus, slane.nt,
    )
    got_s = tickets[13].result(timeout=5)
    assert np.array_equal(got_s[0], np.asarray(rh))
    for a in range(2):
        assert np.array_equal(got_s[1 + a], np.asarray(rus[a]))

    # Resume lane vs one uninterrupted 4-step run.
    ref_res = np.asarray(adv(jnp.asarray(np.asarray(T0) * 1.2), Cp, 4))
    assert np.array_equal(t_res.result(timeout=5)[0], ref_res)


# ---------------------------------------------------------------------------
# The ladder consolidates program classes — bitwise, within tolerance
# ---------------------------------------------------------------------------


def _ladder_trace(tag: str):
    mix = [
        ("diffusion", (30, 30)), ("diffusion", (32, 32)),
        ("diffusion", (30, 30)), ("wave", (30, 30)),
        ("wave", (32, 32)), ("swe", (30, 30)),
    ]
    return [
        Request(request_id=f"{tag}-{i}", workload=wl, global_shape=sh,
                dtype="f32", nt=4 if i % 2 == 0 else 3,
                ic_scale=1.0 + 0.04 * i)
        for i, (wl, sh) in enumerate(mix)
    ]


def test_ladder_consolidates_program_classes_bitwise():
    """(30,30) and (32,32) diffusion/wave traffic merges onto the
    32x32 rung (inflation 0.138 <= padded_flops_tolerance 0.25) —
    strictly fewer program classes, every result bitwise-equal to the
    exact-shape service; SWE is ladder-ineligible and keeps its exact
    class."""
    from rocm_mpi_tpu.perf.traffic import load_budgets

    tol = load_budgets()["serving"]["padded_flops_tolerance"]
    assert sbins.ladder_inflation((30, 30), (32, 32)) <= tol
    assert sbins.bin_key(
        _ladder_trace("k")[0], ladder_tolerance=tol
    ).shape == (32, 32)

    exact = SimulationService(config=ServeConfig(max_width=2))
    e_tickets = [exact.queue.submit(r) for r in _ladder_trace("ex")]
    e_report = exact._drain_all()

    lad = SimulationService(config=ServeConfig(
        max_width=2, segments=2, ladder=True,
    ))
    l_tickets = [lad.queue.submit(r) for r in _ladder_trace("la")]
    l_report = lad._drain_all()

    assert l_report.failed == 0 and e_report.failed == 0
    # 6 exact classes (3 shapes x diffusion + 2 x wave + 1 swe by
    # steps-bucket... shapes split them) collapse: diffusion 2 -> 1,
    # wave 2 -> 1; swe keeps its exact (30, 30) class.
    assert l_report.n_bins < e_report.n_bins
    assert l_report.compiles["steady_state"] == 0
    ladder_keys = list(l_report.bins)
    assert any(k.workload == "swe" and k.shape == (30, 30)
               for k in ladder_keys)
    assert not any(k.workload in ("diffusion", "wave")
                   and k.shape == (30, 30) for k in ladder_keys)
    # Ladder cell-padding is visible in the stats, distinctly.
    assert any(st.ladder_waste > 0.0 for st in l_report.bins.values())

    for i, (a, b) in enumerate(zip(e_tickets, l_tickets)):
        for la, lb in zip(a.result(timeout=5), b.result(timeout=5)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), i


# ---------------------------------------------------------------------------
# The utilization acceptance: occupancy up, bubble no worse, results equal
# ---------------------------------------------------------------------------


HEAVY_NTS = [16, 9, 10, 9, 16, 9, 9, 10, 9, 9, 10, 9]


def _heavy_trace(tag: str):
    return [
        Request(request_id=f"{tag}-{i:02d}", workload="diffusion",
                global_shape=(16, 16), dtype="f32", nt=nt,
                ic_scale=1.0 + 0.01 * i)
        for i, nt in enumerate(HEAVY_NTS)
    ]


def test_continuous_occupancy_and_bubble_regress_gate():
    """The regress-gated utilization win, measured warmed: the
    continuous drain's step-weighted occupancy is strictly higher than
    batch-synchronous AND clears the committed `serving.occupancy`
    floor, its device-bubble is no worse, and the two drains return
    bitwise-identical results."""
    from rocm_mpi_tpu.perf.traffic import load_budgets

    floor = load_budgets()["serving"]["occupancy"]
    results = {}
    for mode, segs in (("sync", 1), ("cont", 4)):
        svc = SimulationService(config=ServeConfig(
            max_width=4, segments=segs,
        ))
        svc.run_trace(_heavy_trace(f"warm-{mode}"))  # compile it all
        tickets = [svc.queue.submit(r)
                   for r in _heavy_trace(f"meas-{mode}")]
        p0 = dict(svc._pipe)
        rep = svc._drain_all()
        d_busy = svc._pipe["busy_s"] - p0["busy_s"]
        d_wall = svc._pipe["wall_s"] - p0["wall_s"]
        assert d_wall > 0
        bubble = max(0.0, 1.0 - d_busy / d_wall)
        assert rep.compiles["steady_state"] == 0
        if segs > 1:
            occ = rep.continuous["occupancy"]
            assert rep.continuous["swaps_in"] >= 1
        else:
            # The batch-synchronous comparable: step-weighted useful
            # fraction (1 - padding_waste aggregated over the drain) —
            # NOT the slot-count occupancy, which ignores frozen tails.
            occ = sum(st.useful_steps for st in rep.bins.values()) \
                / sum(st.machine_steps for st in rep.bins.values())
        results[mode] = (
            [t.result(timeout=5) for t in tickets], occ, bubble,
        )

    out_s, occ_s, bub_s = results["sync"]
    out_c, occ_c, bub_c = results["cont"]
    for i, (a, b) in enumerate(zip(out_s, out_c)):
        for la, lb in zip(a, b):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), i
    assert occ_c > occ_s, (occ_c, occ_s)
    assert occ_c >= floor, (occ_c, floor)
    # Wall-clock gauge: allow measurement noise, but the continuous
    # drain must not be structurally worse (measured ~0.14 vs ~0.33 on
    # the CPU lowering — chains keep a flight open across segments).
    assert bub_c <= bub_s + 0.05, (bub_c, bub_s)


# ---------------------------------------------------------------------------
# Schema gates: manifest `continuous` block and the budgets rows
# ---------------------------------------------------------------------------


def test_manifest_continuous_block_schema_gate(tmp_path):
    from rocm_mpi_tpu.telemetry.regress import check_schema

    svc = SimulationService(config=ServeConfig(max_width=2, segments=2))
    svc.run_trace(_swap_trace("man"))
    path = tmp_path / "serve-manifest.json"
    doc = svc.write_manifest(path)
    assert doc["continuous"]["segments"] == 2
    assert doc["continuous"]["swaps_in"] >= 1
    assert 0.0 <= doc["continuous"]["occupancy"] <= 1.0
    assert sbins.validate_manifest_doc(doc) == []
    assert check_schema([path]) == []

    bad = tmp_path / "bad-manifest.json"
    doc1 = json.loads(path.read_text())
    doc1["continuous"]["segments"] = 0
    bad.write_text(json.dumps(doc1))
    assert any("segments" in p for p in check_schema([bad]))

    doc2 = json.loads(path.read_text())
    doc2["continuous"]["occupancy"] = 1.7
    bad.write_text(json.dumps(doc2))
    assert any("occupancy" in p for p in check_schema([bad]))


def test_budgets_continuous_rows_schema_gate(tmp_path):
    from rocm_mpi_tpu.perf.traffic import load_budgets
    from rocm_mpi_tpu.telemetry.regress import check_schema

    doc = load_budgets()
    assert doc["serving"]["padded_flops_tolerance"] == 0.25
    assert 0.0 < doc["serving"]["occupancy"] <= 1.0

    bad = tmp_path / "budgets.json"
    doc["serving"]["padded_flops_tolerance"] = -1
    bad.write_text(json.dumps(doc))
    assert any("padded_flops_tolerance" in p for p in check_schema([bad]))

    doc = load_budgets()
    doc["serving"]["occupancy"] = 1.5
    bad.write_text(json.dumps(doc))
    assert any("occupancy" in p for p in check_schema([bad]))
