"""Failure paths, exercised deterministically (docs/RESILIENCE.md).

The resilience layer's claims, each pinned by injection — never by
waiting for the next real outage:

  * integrity: a truncated/corrupt latest checkpoint is skipped and the
    previous kept step restores instead (manifest validation);
  * supervision: a crash at the segment midpoint recovers through
    run_supervised to a final state BITWISE-equal to the uninterrupted
    run (same compiled program, same segment arithmetic);
  * bounded retries with exponential backoff, every decision recorded as
    a structured utils.metrics event;
  * launcher: an injected rank kill is detected as the first failure and
    hung peers are put down within the grace window (the bare-timeout
    kill this PR replaces).

The 2D heat model on the virtual 8-device CPU mesh keeps every scenario
sharded — orbax saves per-shard, so integrity validation covers the
multi-file checkpoint layout, not a toy single array.
"""

import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.parallel.launcher import spawn_ranks
from rocm_mpi_tpu.resilience import faults, run_supervised
from rocm_mpi_tpu.resilience.faults import RC_INJECTED_KILL, InjectedCrash
from rocm_mpi_tpu.utils import checkpoint as ckpt
from rocm_mpi_tpu.utils import metrics

ROOT = pathlib.Path(__file__).resolve().parent.parent

NT, EVERY = 32, 8


@pytest.fixture(autouse=True)
def _clean_events_and_faults():
    # The unified public reset (telemetry.clear_events): events dropped,
    # annotation dedup preserved; metrics.clear_events is the deprecated
    # alias over the same behavior.
    from rocm_mpi_tpu import telemetry

    telemetry.clear_events()
    yield
    faults.install(None)
    telemetry.clear_events()


def _model(dims=(2, 4)):
    cfg = DiffusionConfig(
        global_shape=(32, 32), lengths=(10.0, 10.0), nt=NT, warmup=0,
        dtype="f64", dims=dims,
    )
    model = HeatDiffusion(cfg)
    T, Cp = model.init_state()
    advance = model.advance_fn("perf")
    # 1-tuple state: orbax wants container structure, and the apps'
    # checkpointed_run wraps the same way.
    adv = lambda s, n: (advance(s[0], Cp, n),)
    return model, adv, (T,)


def _ref(adv, state, nt=NT):
    return adv((jnp.copy(state[0]),), nt)


# ---------------------------------------------------------------------------
# Checkpoint integrity: manifests, validation, fallback
# ---------------------------------------------------------------------------


def test_segmented_run_writes_valid_manifests(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    steps = ckpt.all_steps(tmp_path)
    assert steps, "no checkpoints written"
    for step in steps:
        ok, reason = ckpt.verify_step(tmp_path, step)
        assert ok, f"step {step}: {reason}"
        manifest = ckpt.read_manifest(tmp_path, step)
        assert manifest["step"] == step
        assert manifest["leaves"][0]["dtype"] == "float64"
        assert manifest["files"], "empty file inventory"
    assert ckpt.latest_valid_step(tmp_path) == ckpt.latest_step(tmp_path)


def test_truncated_latest_falls_back_to_previous_step(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    latest = ckpt.latest_step(tmp_path)
    prev = ckpt.all_steps(tmp_path)[-2]
    faults._truncate_latest(tmp_path)
    ok, reason = ckpt.verify_step(tmp_path, latest)
    assert not ok and "mismatch" in reason
    msgs = []
    assert ckpt.latest_valid_step(tmp_path, log=msgs.append) == prev
    assert any("failed validation" in m for m in msgs), msgs


def test_missing_manifest_is_invalid_when_others_exist(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    latest = ckpt.latest_step(tmp_path)
    prev = ckpt.all_steps(tmp_path)[-2]
    # An unmanifested step = a save that never completed (the manifest is
    # written after wait_until_finished): not trustworthy.
    (tmp_path / f"manifest-{latest}.json").unlink()
    assert ckpt.latest_valid_step(tmp_path) == prev


def test_legacy_dir_without_any_manifests_trusts_latest(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    for p in tmp_path.glob("manifest-*.json"):
        p.unlink()
    assert ckpt.latest_valid_step(tmp_path) == ckpt.latest_step(tmp_path)


def test_restore_verify_catches_checksum_mismatch(tmp_path):
    import json

    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    latest = ckpt.latest_step(tmp_path)
    mpath = tmp_path / f"manifest-{latest}.json"
    manifest = json.loads(mpath.read_text())
    manifest["leaves"][0]["crc32"] ^= 0xFFFF  # simulated bit rot
    mpath.write_text(json.dumps(manifest))
    _, _, like = _model()
    with pytest.raises(ckpt.CheckpointCorruptionError, match="crc32"):
        ckpt.restore_state(tmp_path, latest, like)


def test_restored_state_is_donation_safe(tmp_path):
    """The measured 0.4.37 hazard this module defends: restoring then
    immediately donating into the jitted advance must NOT read garbage
    (restore_state returns an XLA-owned copy)."""
    _, adv, state = _model()
    ref = _ref(adv, state)
    ckpt.run_segmented(adv, state, NT // 2, tmp_path, every=EVERY)
    _, _, like = _model()
    restored = ckpt.restore_state(tmp_path, NT // 2, like)
    out = ckpt.run_segmented(adv, restored, NT, tmp_path, every=EVERY,
                             start_step=NT // 2)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))


def test_mid_run_checkpoints_are_uncorrupted(tmp_path):
    """Each save completes before the next segment's donating advance
    reuses the buffer — under the old overlapped design every mid-run
    checkpoint restored as garbage (measured)."""
    _, adv, state = _model()
    mid = _ref(adv, state, 2 * EVERY)
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    _, _, like = _model()
    # 2*EVERY is the oldest KEPT step (max_to_keep=3 prunes the first).
    assert ckpt.all_steps(tmp_path)[0] == 2 * EVERY
    first = ckpt.restore_state(tmp_path, 2 * EVERY, like)
    np.testing.assert_array_equal(np.asarray(first[0]), np.asarray(mid[0]))


# ---------------------------------------------------------------------------
# Supervision: crash recovery, bounded retries, backoff events
# ---------------------------------------------------------------------------


def test_supervised_crash_at_midpoint_bitwise_equals_straight(tmp_path):
    _, adv, state = _model()
    ref = _ref(adv, state)
    faults.install(f"crash@step={NT // 2}")
    waits = []
    out = run_supervised(adv, state, NT, tmp_path, EVERY,
                         sleep=waits.append)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    assert waits == [0.5]
    kinds = [e.kind for e in metrics.events()]
    for k in ("attempt-failed", "backoff", "restored", "recovered"):
        assert k in kinds, kinds
    restored = metrics.events("restored")[0]
    assert restored.step == NT // 2  # latest valid step, not step 0


def test_supervised_recovers_past_truncated_checkpoint(tmp_path):
    """Crash + torn save together: the supervisor must fall back past
    the truncated latest checkpoint to the previous kept step AND still
    land bitwise-equal."""
    _, adv, state = _model()
    ref = _ref(adv, state)
    # Crash at the midpoint AND truncate the just-written midpoint save:
    # exactly what a process dying mid-write leaves behind.
    faults.install(
        f"truncate-latest@step={NT // 2};crash@step={NT // 2}"
    )
    out = run_supervised(adv, state, NT, tmp_path, EVERY, sleep=lambda _: None)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    restored = metrics.events("restored")[0]
    assert restored.step == NT // 2 - EVERY, (
        "should have fallen back past the truncated midpoint save"
    )


def test_supervised_cold_restart_before_first_checkpoint(tmp_path):
    """A crash BEFORE any checkpoint exists must still be retryable: the
    framework's advance donates its state, so the retry cannot reuse the
    buffers attempt 0 consumed — the supervisor hands each cold start a
    fresh copy (a deleted-buffer error here would abort supervision as
    non-retryable exactly when it matters most)."""
    _, adv, state = _model()
    ref = _ref(adv, state)
    flaky = {"fails": 1}

    def adv_flaky_then_ok(s, n):
        out = adv(s, n)  # donate FIRST, then fail: worst-case ordering
        if flaky["fails"]:
            flaky["fails"] -= 1
            raise RuntimeError("transient backend error (simulated)")
        return out

    out = run_supervised(adv_flaky_then_ok, state, NT, tmp_path, EVERY,
                         sleep=lambda _: None)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    assert metrics.events("backoff"), "the crash must have been retried"


def test_supervised_retries_bounded_with_exponential_backoff(tmp_path):
    calls = []

    def always_fails(state, n):
        calls.append(n)
        raise RuntimeError("backend fell over (simulated)")

    waits = []
    with pytest.raises(RuntimeError, match="fell over"):
        run_supervised(always_fails, (jnp.zeros((4,)),), 8, tmp_path, 4,
                       max_retries=3, sleep=waits.append)
    assert len(calls) == 4  # 1 attempt + 3 retries, then give up
    assert waits == [0.5, 1.0, 2.0]  # base * factor**attempt
    assert len(metrics.events("attempt-failed")) == 4
    assert len(metrics.events("backoff")) == 3
    assert len(metrics.events("gave-up")) == 1


def test_supervised_does_not_retry_programming_errors(tmp_path):
    def broken(state, n):
        raise ValueError("bad argument — retrying cannot help")

    with pytest.raises(ValueError):
        run_supervised(broken, (jnp.zeros((4,)),), 8, tmp_path, 4,
                       sleep=lambda _: None)
    assert metrics.events("backoff") == []


def test_injected_crash_fires_exactly_once():
    plan = faults.install("crash@step=5")
    with pytest.raises(InjectedCrash):
        faults.fault_point("segment", step=5)
    # The retry re-runs the same step: the armed clause must NOT re-fire.
    faults.fault_point("segment", step=5)
    assert plan.clauses[0].fires == 1


def test_fault_spec_parsing_errors():
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan.parse("explode@step=3")
    with pytest.raises(ValueError, match="needs a step"):
        faults.FaultPlan.parse("crash")
    with pytest.raises(ValueError, match="unknown fault trigger"):
        faults.FaultPlan.parse("crash@when=now")
    plan = faults.FaultPlan.parse("delay=1.5@step=2,rank=1;kill@step=4")
    assert plan.clauses[0].kind == "delay"
    assert plan.clauses[0].delay_s == 1.5
    assert plan.clauses[0].rank == 1
    assert plan.clauses[1].kind == "kill"


# ---------------------------------------------------------------------------
# Launcher: first-failure reporting, peer grace kill, fault forwarding
# ---------------------------------------------------------------------------


def test_launcher_reports_first_failure_and_kills_hung_peer():
    results = spawn_ranks(
        [str(ROOT / "tests" / "resilience_worker.py"), "--hang-after"],
        nprocs=2,
        timeout=120,
        inject_fault="kill@step=3,rank=1",
        heartbeat_s=1.0,
        peer_grace_s=3.0,
    )
    (p0, (out0, _)), (p1, (out1, _)) = results
    assert p1.returncode == RC_INJECTED_KILL, (p1.returncode, out1)
    assert "WORKER_DONE" not in out1
    report = results.report
    assert report.first_failure is not None
    rank, rc, _ = report.first_failure
    assert (rank, rc) == (1, RC_INJECTED_KILL)
    # Rank 0 survived its own steps, then hung; the launcher must have
    # put it down in the grace window, not at the 120 s timeout.
    assert report.killed_after_failure == [0]
    assert p0.returncode != 0
    assert "WORKER_DONE rank=0" in out0


def test_launcher_clean_run_reports_nothing():
    results = spawn_ranks(
        [str(ROOT / "tests" / "resilience_worker.py")],
        nprocs=2, timeout=120, peer_grace_s=3.0,
    )
    for pid, (p, (out, err)) in enumerate(results):
        assert p.returncode == 0, (pid, err[-500:])
    assert results.report.first_failure is None
    assert results.report.killed_after_failure == []


@pytest.mark.slow
def test_kill_rank_mid_collective_gloo():
    """The gloo-real drill: rank 1 dies between cross-process
    collectives; rank 0's next collective can never complete, and the
    launcher's supervision — not the bare timeout — ends it."""
    results = spawn_ranks(
        [str(ROOT / "tests" / "resilience_gloo_worker.py")],
        nprocs=2,
        timeout=180,
        inject_fault="kill@step=4,rank=1",
        peer_grace_s=10.0,
    )
    (p0, (out0, _)), (p1, (out1, _)) = results
    assert p1.returncode == RC_INJECTED_KILL, (p1.returncode, out1)
    report = results.report
    assert report.first_failure is not None and report.first_failure[0] == 1
    assert p0.returncode != 0, "rank 0 cannot finish without its peer"
    assert "GLOO_WORKER_DONE" not in out0


# ---------------------------------------------------------------------------
# App wiring: the ladder gets supervision through the shared flags
# ---------------------------------------------------------------------------


def test_app_supervised_crash_recovers_bitwise(tmp_path):
    import subprocess
    import sys

    d = tmp_path / "ck"
    straight = tmp_path / "straight.npy"
    recovered = tmp_path / "recovered.npy"
    common = [
        sys.executable, "apps/diffusion_2d_perf.py", "--cpu-devices", "4",
        "--nx", "24", "--ny", "24", "--nt", "24", "--warmup", "0",
    ]

    def run(extra):
        proc = subprocess.run(
            common + extra, capture_output=True, text=True, timeout=600,
            cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    run(["--save-field", str(straight)])
    out = run([
        "--checkpoint", str(d), "--ckpt-every", "6", "--retries", "2",
        "--inject-fault", "crash@step=12",
        "--save-field", str(recovered),
    ])
    assert "supervisor: restored step 12" in out, out
    np.testing.assert_array_equal(np.load(recovered), np.load(straight))
