"""Overlap (perf_hide) correctness: the variant-(3) semantics the reference
never shipped must agree with every other rung (SURVEY.md §3.4, §4b)."""

import dataclasses

import numpy as np
import pytest

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.parallel.overlap import effective_b_width


def _compare(cfg, ref_variant="ap", rtol=1e-13):
    model = HeatDiffusion(cfg)
    res_h = model.run(variant="hide")
    res_r = model.run(variant=ref_variant)
    np.testing.assert_allclose(
        np.asarray(res_h.T), np.asarray(res_r.T), rtol=rtol, atol=1e-15
    )


def test_hide_matches_ap_f64_mesh():
    _compare(
        DiffusionConfig(
            global_shape=(64, 64), nt=40, warmup=0, dims=(4, 2), b_width=(4, 4)
        )
    )


def test_hide_matches_ap_f32_pallas_strips():
    cfg = DiffusionConfig(
        global_shape=(64, 64), nt=30, warmup=0, dims=(2, 2),
        b_width=(8, 8), dtype="f32",
    )
    model = HeatDiffusion(cfg)
    res_h = model.run(variant="hide")
    res_p = model.run(variant="perf")
    np.testing.assert_allclose(
        np.asarray(res_h.T), np.asarray(res_p.T), rtol=1e-6, atol=1e-7
    )


def test_hide_with_reference_b_width_clamped():
    # Reference b_width=(32,4) on shards smaller than the frame: clamp.
    _compare(
        DiffusionConfig(
            global_shape=(32, 32), nt=20, warmup=0, dims=(4, 2), b_width=(32, 4)
        )
    )


def test_hide_strips_cover_whole_shard():
    # b_width == shard/2: interior is empty; strips must tile exactly.
    _compare(
        DiffusionConfig(
            global_shape=(32, 32), nt=10, warmup=0, dims=(2, 2), b_width=(8, 8)
        )
    )


def test_effective_b_width():
    assert effective_b_width((64, 64), (32, 4)) == (32, 4)
    assert effective_b_width((16, 64), (32, 4)) == (8, 4)
    assert effective_b_width((3, 3), (32, 32)) == (1, 1)


def test_hide_single_device():
    _compare(
        DiffusionConfig(
            global_shape=(48, 48), nt=25, warmup=0, dims=(1, 1), b_width=(4, 4)
        )
    )
