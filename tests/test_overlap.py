"""Overlap (perf_hide) correctness: the variant-(3) semantics the reference
never shipped must agree with every other rung (SURVEY.md §3.4, §4b)."""


import numpy as np
import pytest

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.parallel.overlap import effective_b_width


def _compare(cfg, ref_variant="ap", rtol=1e-13):
    model = HeatDiffusion(cfg)
    res_h = model.run(variant="hide")
    res_r = model.run(variant=ref_variant)
    np.testing.assert_allclose(
        np.asarray(res_h.T), np.asarray(res_r.T), rtol=rtol, atol=1e-15
    )


def test_hide_matches_ap_f64_mesh():
    _compare(
        DiffusionConfig(
            global_shape=(64, 64), nt=40, warmup=0, dims=(4, 2), b_width=(4, 4)
        )
    )


def test_hide_matches_ap_f32_pallas_strips():
    cfg = DiffusionConfig(
        global_shape=(64, 64), nt=30, warmup=0, dims=(2, 2),
        b_width=(8, 8), dtype="f32",
    )
    model = HeatDiffusion(cfg)
    res_h = model.run(variant="hide")
    res_p = model.run(variant="perf")
    np.testing.assert_allclose(
        np.asarray(res_h.T), np.asarray(res_p.T), rtol=1e-6, atol=1e-7
    )


def test_hide_with_reference_b_width_clamped():
    # Reference b_width=(32,4) on shards smaller than the frame: clamp.
    _compare(
        DiffusionConfig(
            global_shape=(32, 32), nt=20, warmup=0, dims=(4, 2), b_width=(32, 4)
        )
    )


def test_hide_strips_cover_whole_shard():
    # b_width == shard/2: interior is empty; strips must tile exactly.
    _compare(
        DiffusionConfig(
            global_shape=(32, 32), nt=10, warmup=0, dims=(2, 2), b_width=(8, 8)
        )
    )


def test_effective_b_width():
    assert effective_b_width((64, 64), (32, 4)) == (32, 4)
    assert effective_b_width((16, 64), (32, 4)) == (8, 4)
    assert effective_b_width((3, 3), (32, 32)) == (1, 1)


def test_hide_single_device():
    _compare(
        DiffusionConfig(
            global_shape=(48, 48), nt=25, warmup=0, dims=(1, 1), b_width=(4, 4)
        )
    )


class TestDeepHalo:
    """Deep-halo sweeps (parallel.deep_halo): k steps per width-k exchange."""

    def _model(self, shape=(64, 64), dims=(2, 2), nt=24, warmup=8):
        from rocm_mpi_tpu.config import DiffusionConfig
        from rocm_mpi_tpu.models import HeatDiffusion

        cfg = DiffusionConfig(
            global_shape=shape,
            lengths=(10.0,) * len(shape),
            nt=nt,
            warmup=warmup,
            dtype="f32",
            dims=dims,
        )
        return HeatDiffusion(cfg)

    def test_matches_per_step_path(self):
        import numpy as np

        m = self._model()
        r_deep = m.run_deep(block_steps=8)
        r_ref = m.run(variant="perf")
        np.testing.assert_allclose(
            np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
        )

    def test_k1_matches_per_step_path(self):
        import numpy as np

        m = self._model(nt=6, warmup=2)
        r_deep = m.run_deep(block_steps=1)
        r_ref = m.run(variant="perf")
        np.testing.assert_allclose(
            np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
        )

    def test_3d_mesh(self):
        import numpy as np

        m = self._model(shape=(32, 32, 16), dims=(2, 2, 2), nt=8, warmup=4)
        r_deep = m.run_deep(block_steps=4)
        r_ref = m.run(variant="perf")
        np.testing.assert_allclose(
            np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
        )

    def test_hbm_shard_branch_matches_per_step(self, monkeypatch):
        # Shards too big for the (shrunk) VMEM budget route the local
        # compute to the temporal-blocked HBM sweep (multi_step_cm_hbm);
        # the schedule must still agree with the per-step path.
        import numpy as np

        import rocm_mpi_tpu.ops.pallas_kernels as pk

        monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
        m = self._model(shape=(56, 48), dims=(2, 2), nt=8, warmup=4)
        r_deep = m.run_deep(block_steps=2)  # padded shard (32,28): 32%16==0
        r_ref = m.run(variant="perf")
        np.testing.assert_allclose(
            np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
        )

    def test_bf16_deep_sweep_matches_ap(self):
        # Storage-only bf16 (r4) through the sharded deep sweep: the
        # width-k exchange moves bf16 ghosts, the local kernel computes
        # f32 and rounds once per sweep — must track the bf16 GSPMD ap
        # path to bf16 resolution.
        import numpy as np

        import jax.numpy as jnp

        m = self._model(shape=(32, 32), dims=(2, 2), nt=8, warmup=0)
        import dataclasses

        cfg16 = dataclasses.replace(m.config, dtype="bf16")
        from rocm_mpi_tpu.models import HeatDiffusion

        m16 = HeatDiffusion(cfg16)
        r_deep = m16.run_deep(block_steps=4)
        T0, Cp = m16.init_state()
        ref = m16.advance_fn("ap")(jnp.copy(T0), Cp, 8)
        np.testing.assert_allclose(
            np.asarray(r_deep.T, dtype=np.float32),
            np.asarray(ref, dtype=np.float32),
            rtol=0.02, atol=0.02,  # bf16 resolution, not a numerics bug
        )

    def test_hbm_branch_real_budget_multi_device(self, monkeypatch):
        # VERDICT r3 #7: the HBM routing scored with the PRODUCTION budget
        # (no shrunk threshold) — a genuinely HBM-class f32 shard on a
        # multi-device mesh, spy-asserted so a silent fall-through to the
        # jnp path cannot pass.
        import numpy as np

        import rocm_mpi_tpu.ops.pallas_kernels as pk

        local = pk.hbm_class_edge()  # smallest HBM-routing f32 shard edge
        m = self._model(shape=(2 * local, local), dims=(2, 1), nt=8,
                        warmup=0)
        calls = []
        orig = pk.multi_step_cm_hbm
        monkeypatch.setattr(
            pk, "multi_step_cm_hbm",
            lambda *a, **k: (calls.append(1), orig(*a, **k))[1],
        )
        r_deep = m.run_deep(block_steps=8)
        assert calls, "deep sweep did not route to multi_step_cm_hbm"
        import jax.numpy as jnp

        T0, Cp = m.init_state()  # deterministic: same IC as run_deep's
        ref = m.advance_fn("ap")(jnp.copy(T0), Cp, 8)
        np.testing.assert_allclose(
            np.asarray(r_deep.T), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    def test_hbm_branch_shape_fallback_matches_per_step(self, monkeypatch):
        # k=3 on a (28,24) shard pads to 34 rows — not a multiple of the
        # HBM sweep's stripe height — so the deep sweep must route to the
        # any-shape jnp fallback instead of crashing, and still agree.
        import numpy as np

        import rocm_mpi_tpu.ops.pallas_kernels as pk

        monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
        m = self._model(shape=(56, 48), dims=(2, 2), nt=9, warmup=3)
        r_deep = m.run_deep(block_steps=3)
        r_ref = m.run(variant="perf")
        np.testing.assert_allclose(
            np.asarray(r_deep.T), np.asarray(r_ref.T), rtol=2e-5, atol=1e-6
        )

    def test_default_depth_selection(self):
        from rocm_mpi_tpu.models.diffusion import default_deep_depth

        # Small shard: full default, clamped by shard extent.
        assert default_deep_depth((252, 252), 4) == 32
        assert default_deep_depth((16, 16), 4) == 16
        # Mid-size shard: 672² f32 fits VMEM at k=16 but not k=32 —
        # prefer the shallower VMEM-resident sweep over the HBM route.
        assert default_deep_depth((672, 672), 4) == 16
        # Genuinely HBM-resident shard: capped at the tb sweep's bound.
        assert default_deep_depth((12288, 12288), 4) == 8

    def test_depth_exceeding_shard_raises(self):
        import pytest

        from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

        m = self._model(shape=(16, 16), dims=(4, 2))
        with pytest.raises(ValueError, match="exceeds"):
            make_deep_sweep(m.grid, 8, 1.0, 1e-4, (0.1, 0.1))

    def test_degraded_depth_warns(self):
        import warnings

        m = self._model(nt=24, warmup=9)  # gcd(9, 15, 8) = 1
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            m.run_deep(block_steps=8)
        assert any("degraded" in str(x.message) for x in w)


def test_effective_block_steps_rejects_nonpositive():
    from rocm_mpi_tpu.models.diffusion import effective_block_steps

    with pytest.raises(ValueError, match=">= 1"):
        effective_block_steps(24, 8, 0)
    with pytest.raises(ValueError, match=">= 1"):
        effective_block_steps(24, 8, -4)


def test_hide_single_device_routes_to_whole_block_step():
    # On a 1-device mesh there is nothing to hide: the hide variant must be
    # bit-identical to perf (same whole-block step, no strip bookkeeping).
    cfg = DiffusionConfig(
        global_shape=(48, 48), nt=12, warmup=0, dims=(1, 1),
        b_width=(4, 4), dtype="f32",
    )
    model = HeatDiffusion(cfg)
    r_h = model.run(variant="hide")
    r_p = model.run(variant="perf")
    np.testing.assert_array_equal(np.asarray(r_h.T), np.asarray(r_p.T))


def test_explicit_chunk_cap_warns():
    import warnings

    import jax.numpy as jnp

    from rocm_mpi_tpu.ops.pallas_kernels import fused_multi_step

    T = jnp.zeros((512, 512), jnp.float32)  # > 256 KB: chunk capped to 16
    Cp = jnp.ones_like(T)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fused_multi_step(T, Cp, 1.0, 1e-5, (0.1, 0.1), 64, chunk=64)
    assert any("chunk degraded" in str(x.message) for x in w)
