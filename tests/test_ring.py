"""Ring-exchange smoke test — the analog (and automation) of the reference's
rocmaware_test_selectdevice.jl capability proof (SURVEY.md §3.5, §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
from rocm_mpi_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec

from rocm_mpi_tpu.parallel import init_global_grid
from rocm_mpi_tpu.parallel.ring import ring_exchange, ring_exchange_demo


def test_ring_exchange_demo_values():
    grid = init_global_grid(8 * 4, lengths=(1.0,), dims=(8,))
    sent, received = ring_exchange_demo(grid.mesh, width=4)
    n = 8
    sent = np.asarray(sent).reshape(n, 4)
    received = np.asarray(received).reshape(n, 4)
    for i in range(n):
        assert (sent[i] == i).all()
        # Device i receives from its left neighbor — same assertion the
        # reference makes by printing recv on each rank (…selectdevice.jl:23).
        assert (received[i] == (i - 1) % n).all()


def test_ring_exchange_reverse_shift():
    grid = init_global_grid(16, lengths=(1.0,), dims=(8,))
    mesh = grid.mesh
    x = jax.device_put(
        jnp.repeat(jnp.arange(8.0), 2), grid.sharding
    )
    out = jax.jit(
        shard_map(
            lambda b: ring_exchange(b, "gx", shift=-1),
            mesh=mesh,
            in_specs=PartitionSpec("gx"),
            out_specs=PartitionSpec("gx"),
        )
    )(x)
    out = np.asarray(out).reshape(8, 2)
    for i in range(8):
        assert (out[i] == (i + 1) % 8).all()
