"""The wire-precision plane (docs/PERF.md "Wire precision"): per-mode
codecs and byte accounting, the f32 bitwise pin, the tolerance contract
vs the f64 host-staged oracle on all three workloads, error-feedback
drift, delta round-trips including the first-sweep edge, the wire-bytes
ladder (and its doctored fixture's teeth), and the tuning-axis double
gate."""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import (
    AcousticWave,
    HeatDiffusion,
    ShallowWater,
    SWEConfig,
    WaveConfig,
)
from rocm_mpi_tpu.parallel import (
    HostStagedStepper,
    exchange_halo,
    init_global_grid,
)
from rocm_mpi_tpu.parallel import wire
from rocm_mpi_tpu.parallel.halo import build_for_mesh, exchange_nbytes
from rocm_mpi_tpu.utils.compat import shard_map

REPO = pathlib.Path(__file__).resolve().parent.parent

NON_F32 = [m for m in wire.WIRE_MODES if m != "f32"]
STATEFUL = sorted(wire.STATEFUL_MODES)


def _rel_err(got, ref):
    got = np.asarray(got, np.float64)
    ref = np.asarray(ref, np.float64)
    return float(np.max(np.abs(got - ref)) / np.max(np.abs(ref)))


# ---------------------------------------------------------------------------
# Byte accounting (satellite: annotations report actual on-wire itemsize)
# ---------------------------------------------------------------------------


class TestWireBytes:
    def test_exchange_nbytes_per_mode(self):
        # (64,64) width-1: f32 slabs 2*(64+66)*4; bf16 half; int8 one
        # byte per element + one f32 scale per slab (4 slabs).
        assert exchange_nbytes((64, 64), 4, 1) == 1040
        assert exchange_nbytes((64, 64), 4, 1, wire_mode="bf16") == 520
        assert exchange_nbytes((64, 64), 4, 1, wire_mode="int8") == 276
        assert exchange_nbytes(
            (64, 64), 4, 1, wire_mode="int8_delta"
        ) == 276
        # f32 mode ships the STATE dtype verbatim (f64 oracle -> 8B).
        assert exchange_nbytes((64, 64), 8, 1) == 2080

    def test_ladder_fractions_closed_form(self):
        assert wire.ladder_fraction((64, 64), 1, "f32") == 1.0
        assert wire.ladder_fraction((64, 64), 1, "bf16") == 0.5
        assert wire.ladder_fraction((64, 64), 1, "int8") < 0.5
        assert wire.ladder_fraction((64, 64), 1, "int8_delta") < 0.5

    def test_slab_shapes_corner_growth(self):
        # Axis 1's slabs span axis 0's padding (the corner trick).
        assert wire.slab_shapes((4, 4), 1) == [
            (1, 4), (1, 4), (6, 1), (6, 1)
        ]

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown wire_mode"):
            wire.validate_mode("fp4")
        with pytest.raises(ValueError, match="wire_mode"):
            DiffusionConfig(wire_mode="fp4")

    def test_annotation_reports_mode_bytes(self, tmp_path):
        from rocm_mpi_tpu import telemetry

        grid = init_global_grid(8, 8, dims=(2, 2))
        x = jax.device_put(
            jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8),
            grid.sharding,
        )
        telemetry.configure(enabled=True, directory=str(tmp_path), rank=0)
        try:
            jax.jit(lambda v: shard_map(
                lambda b: exchange_halo(b, grid, wire_mode="bf16"),
                mesh=grid.mesh, in_specs=grid.spec, out_specs=grid.spec,
            )(v))(x)
            recs = telemetry.records(kind="trace", name="halo.exchange")
            assert recs, "no halo.exchange annotation"
            attrs = recs[-1]["attrs"]
            assert attrs["wire"] == "bf16"
            # TRUE on-wire bytes: the bf16 figure, not the f32 one.
            assert attrs["bytes"] == exchange_nbytes(
                (4, 4), 4, 1, wire_mode="bf16"
            )
        finally:
            telemetry.configure(enabled=False)
            telemetry.clear()

    def test_halo_program_carries_wire_mode(self):
        grid = init_global_grid(16, 16, dims=(2, 2))
        prog = build_for_mesh(grid, width=2, wire_mode="bf16")
        assert prog.wire_mode == "bf16"
        assert prog.nbytes(4) == exchange_nbytes(
            (8, 8), 4, 2, wire_mode="bf16"
        )


# ---------------------------------------------------------------------------
# The f32 bitwise pin
# ---------------------------------------------------------------------------


class TestF32Bitwise:
    def test_exchange_jaxpr_identical(self):
        # wire_mode="f32" must trace the EXACT pre-wire-plane program.
        grid = init_global_grid(32, lengths=(1.0,), dims=(8,))

        def padded(wm_kw):
            return jax.make_jaxpr(lambda v: shard_map(
                lambda b: exchange_halo(b, grid, **wm_kw),
                mesh=grid.mesh,
                in_specs=PartitionSpec("gx"),
                out_specs=PartitionSpec("gx"),
            )(v))(jnp.arange(32.0))

        assert str(padded({})) == str(padded({"wire_mode": "f32"}))

    @pytest.mark.parametrize("variant", ["shard", "perf", "hide"])
    def test_run_bitwise_equal_to_default(self, variant):
        base = DiffusionConfig(global_shape=(32, 32), nt=12, warmup=0,
                               dims=(2, 2), dtype="f64")
        pinned = DiffusionConfig(global_shape=(32, 32), nt=12, warmup=0,
                                 dims=(2, 2), dtype="f64",
                                 wire_mode="f32")
        r0 = HeatDiffusion(base).run(variant=variant)
        r1 = HeatDiffusion(pinned).run(variant=variant)
        np.testing.assert_array_equal(np.asarray(r0.T), np.asarray(r1.T))

    def test_wave_and_swe_f32_bitwise_equal_to_default(self):
        w0 = AcousticWave(WaveConfig(
            global_shape=(32, 32), nt=12, warmup=0, dims=(2, 2),
            dtype="f64",
        )).run(variant="perf")
        w1 = AcousticWave(WaveConfig(
            global_shape=(32, 32), nt=12, warmup=0, dims=(2, 2),
            dtype="f64", wire_mode="f32",
        )).run(variant="perf")
        np.testing.assert_array_equal(np.asarray(w0.U), np.asarray(w1.U))
        s0 = ShallowWater(SWEConfig(
            global_shape=(32, 32), nt=12, warmup=0, dims=(2, 2),
            dtype="f64",
        )).run(variant="perf")
        s1 = ShallowWater(SWEConfig(
            global_shape=(32, 32), nt=12, warmup=0, dims=(2, 2),
            dtype="f64", wire_mode="f32",
        )).run(variant="perf")
        np.testing.assert_array_equal(np.asarray(s0.h), np.asarray(s1.h))

    def test_deep_f32_bitwise_equal_to_default(self):
        base = DiffusionConfig(global_shape=(32, 32), nt=16, warmup=0,
                               dims=(2, 2), dtype="f64")
        r0 = HeatDiffusion(base).run_deep(block_steps=4)
        r1 = HeatDiffusion(base).run_deep(block_steps=4, wire_mode="f32")
        np.testing.assert_array_equal(np.asarray(r0.T), np.asarray(r1.T))


# ---------------------------------------------------------------------------
# Tolerance contract: per-mode parity vs the f64 oracle, all 3 workloads
# ---------------------------------------------------------------------------


class TestToleranceContract:
    @pytest.mark.parametrize("mode", wire.WIRE_MODES)
    def test_certification_drill(self, mode):
        res = wire.check_tolerance(mode)
        assert res.ok, (
            f"{mode}: rel err {res.rel_err:.3e} > bound {res.bound:.0e}"
        )

    @pytest.mark.parametrize("mode", ["bf16"])
    def test_diffusion_per_step_vs_host_staged_oracle(self, mode):
        # f64 host-staged oracle vs the f32-state wire-mode device path
        # (per-step shard variant; stateless modes only by design).
        oracle = DiffusionConfig(global_shape=(32, 32), nt=40, warmup=0,
                                 dims=(2, 2), dtype="f64",
                                 halo_transport="host")
        ref = HeatDiffusion(oracle).run(variant="shard")
        cfg = DiffusionConfig(global_shape=(32, 32), nt=40, warmup=0,
                              dims=(2, 2), dtype="f32", wire_mode=mode)
        got = HeatDiffusion(cfg).run(variant="shard")
        assert _rel_err(got.T, ref.T) <= wire.TOLERANCE[mode]

    @pytest.mark.parametrize("mode", NON_F32)
    def test_diffusion_deep_vs_host_staged_oracle(self, mode):
        oracle = DiffusionConfig(global_shape=(32, 32), nt=40, warmup=0,
                                 dims=(2, 2), dtype="f64",
                                 halo_transport="host")
        ref = HeatDiffusion(oracle).run(variant="shard")
        cfg = DiffusionConfig(global_shape=(32, 32), nt=40, warmup=0,
                              dims=(2, 2), dtype="f32")
        got = HeatDiffusion(cfg).run_deep(block_steps=4, wire_mode=mode)
        assert _rel_err(got.T, ref.T) <= wire.TOLERANCE[mode]

    @pytest.mark.parametrize("mode", NON_F32)
    def test_wave_deep_vs_f64_oracle(self, mode):
        ref = AcousticWave(WaveConfig(
            global_shape=(32, 32), nt=24, warmup=0, dims=(2, 2),
            dtype="f64",
        )).run_deep(block_steps=4)
        got = AcousticWave(WaveConfig(
            global_shape=(32, 32), nt=24, warmup=0, dims=(2, 2),
            dtype="f32", wire_mode=mode,
        )).run_deep(block_steps=4)
        assert _rel_err(got.U, ref.U) <= wire.TOLERANCE[mode]

    @pytest.mark.parametrize("mode", NON_F32)
    def test_swe_deep_vs_f64_oracle(self, mode):
        ref = ShallowWater(SWEConfig(
            global_shape=(32, 32), nt=24, warmup=0, dims=(2, 2),
            dtype="f64",
        )).run_deep(block_steps=4)
        got = ShallowWater(SWEConfig(
            global_shape=(32, 32), nt=24, warmup=0, dims=(2, 2),
            dtype="f32", wire_mode=mode,
        )).run_deep(block_steps=4)
        assert _rel_err(got.h, ref.h) <= wire.TOLERANCE[mode]

    def test_host_staged_bf16_matches_device_bf16_wire(self):
        # The oracle twin IS the device path, codec included: f64 state
        # both sides, bf16 wire both sides — transport-bisection holds
        # for reduced-precision exchanges too.
        host = DiffusionConfig(global_shape=(32, 32), nt=20, warmup=0,
                               dims=(2, 2), dtype="f64",
                               halo_transport="host", wire_mode="bf16")
        r_host = HeatDiffusion(host).run(variant="shard")
        ici = DiffusionConfig(global_shape=(32, 32), nt=20, warmup=0,
                              dims=(2, 2), dtype="f64", wire_mode="bf16")
        r_ici = HeatDiffusion(ici).run(variant="shard")
        np.testing.assert_allclose(
            np.asarray(r_host.T), np.asarray(r_ici.T),
            rtol=1e-13, atol=1e-15,
        )

    def test_stateful_mode_refused_on_stateless_path(self):
        cfg = DiffusionConfig(global_shape=(32, 32), nt=8, warmup=0,
                              dims=(2, 2), dtype="f32", wire_mode="int8")
        model = HeatDiffusion(cfg)
        with pytest.raises(Exception, match="error-feedback state"):
            model.run(variant="shard")


# ---------------------------------------------------------------------------
# Error feedback and delta encoding
# ---------------------------------------------------------------------------


class TestErrorFeedbackAndDelta:
    def test_drift_bounded_over_500_steps(self):
        # The long-horizon contract: quantization error is compensated,
        # not accumulated — 500 steps stays within the per-mode bound.
        for mode in STATEFUL:
            res = wire.check_tolerance(mode, steps=500)
            assert res.ok, (
                f"{mode} drifted: {res.rel_err:.3e} > {res.bound:.0e}"
            )

    def test_feedback_compensates_vs_accumulates(self):
        # The same int8 wire WITHOUT the residual drifts measurably
        # worse — what "compensated, not accumulated" means.
        grid = wire._OracleGrid(global_shape=(32, 32), dims=(2, 2),
                                spacing=(10 / 32, 10 / 32))
        dt = (10 / 32) ** 2 / (2 * 2 + 0.1)
        coords = np.meshgrid(
            *[(np.arange(32) + 0.5) * (10 / 32) - 5.0] * 2,
            indexing="ij",
        )
        T0 = np.exp(-sum(c * c for c in coords))
        Cp = np.ones((32, 32))
        ref = HostStagedStepper(grid, 1.0, dt, use_native=False).run(
            T0.copy(), Cp, 300
        )

        def drift(feedback):
            s = HostStagedStepper(grid, 1.0, dt, use_native=False,
                                  wire_mode="int8")
            s._codec = wire.NumpyWireCodec("int8", feedback=feedback)
            return _rel_err(s.run(T0.copy(), Cp, 300), ref)

        with_fb, without_fb = drift(True), drift(False)
        assert with_fb < without_fb, (with_fb, without_fb)

    def test_delta_first_sweep_edge_matches_plain_int8(self):
        # No previous slab (zero state): the delta IS the slab, so the
        # first exchange decodes identically to plain int8.
        grid = init_global_grid(8, 8, dims=(2, 2))
        x = jax.device_put(
            jnp.linspace(0.0, 1.0, 64, dtype=jnp.float32).reshape(8, 8),
            grid.sharding,
        )

        def one(mode):
            ws = wire.init_exchange_state(grid, 1, mode, jnp.float32)

            def local(b, *wsl):
                p, ws2 = exchange_halo(b, grid, wire_mode=mode,
                                       wire_state=tuple(wsl))
                return (p,) + ws2

            outs = jax.jit(lambda v, w: shard_map(
                local, mesh=grid.mesh,
                in_specs=(grid.spec,) * (1 + len(w)),
                out_specs=(grid.spec,) * (1 + len(w)),
                check_vma=False,
            )(v, *w))(x, ws)
            return np.asarray(outs[0]), outs[1:]

        p_int8, _ = one("int8")
        p_delta, ws_delta = one("int8_delta")
        np.testing.assert_array_equal(p_int8, p_delta)
        # And the delta state evolved: the receiver reconstruction is no
        # longer the zero first-sweep state everywhere.
        assert any(float(jnp.abs(w).max()) > 0 for w in ws_delta)

    def test_repeated_exchange_average_converges(self):
        # Error feedback's guarantee is on the STREAM, not one pass:
        # repeatedly exchanging the same field, the residual dithers the
        # quantizer so the time-averaged decode lands far closer to the
        # true slab than any single pass (the compensated-not-
        # accumulated property, measured).
        grid = init_global_grid(8, 8, dims=(2, 2))
        x = jax.device_put(
            jnp.linspace(0.0, 2.0, 64, dtype=jnp.float32).reshape(8, 8),
            grid.sharding,
        )
        ref = np.asarray(jax.jit(lambda v: shard_map(
            lambda b: exchange_halo(b, grid),
            mesh=grid.mesh, in_specs=grid.spec, out_specs=grid.spec,
        )(v))(x))

        def local(b, *wsl):
            p, ws2 = exchange_halo(b, grid, wire_mode="int8",
                                   wire_state=tuple(wsl))
            return (p,) + ws2

        ws = wire.init_exchange_state(grid, 1, "int8", jnp.float32)
        run = jax.jit(lambda v, w: shard_map(
            local, mesh=grid.mesh,
            in_specs=(grid.spec,) * (1 + len(w)),
            out_specs=(grid.spec,) * (1 + len(w)),
            check_vma=False,
        )(v, *w))
        decodes = []
        for _ in range(8):
            outs = run(x, ws)
            decodes.append(np.asarray(outs[0], np.float64))
            ws = tuple(outs[1:])
        err_single = np.abs(decodes[0] - ref).max()
        err_avg = np.abs(np.mean(decodes, axis=0) - ref).max()
        assert err_avg < err_single

    def test_exchange_requires_state_for_stateful_modes(self):
        grid = init_global_grid(8, 8, dims=(2, 2))
        with pytest.raises(ValueError, match="error-feedback state"):
            jax.jit(lambda v: shard_map(
                lambda b: exchange_halo(b, grid, wire_mode="int8"),
                mesh=grid.mesh, in_specs=grid.spec, out_specs=grid.spec,
                check_vma=False,
            )(v))(jnp.zeros((8, 8), jnp.float32))

    def test_numpy_codec_matches_jax_quantizer(self):
        rng = np.random.default_rng(7)
        slab = rng.normal(size=(4, 16)).astype(np.float32)
        q, scale = wire._quantize_int8(jnp.asarray(slab))
        jax_deq = np.asarray(wire._dequantize_int8(q, scale, jnp.float32))
        codec = wire.NumpyWireCodec("int8")
        np_deq = codec.apply(("k",), slab)
        np.testing.assert_allclose(jax_deq, np_deq, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# The wire-bytes ladder
# ---------------------------------------------------------------------------


class TestWireLadder:
    def test_ladder_rows_prove_the_fractions(self):
        from rocm_mpi_tpu.perf.traffic import audit_wire_modes

        rows = {r.mode: r for r in audit_wire_modes(local=16, deep_k=4)}
        assert set(rows) == set(wire.WIRE_MODES)
        assert all(r.ok for r in rows.values()), {
            m: (r.fraction, r.ladder) for m, r in rows.items()
        }
        # THE acceptance numbers: bf16 <= 0.55x the f32 wire ideal,
        # int8 and int8+delta strictly less than bf16's fraction.
        assert rows["f32"].fraction == pytest.approx(1.0)
        assert rows["bf16"].fraction <= 0.55
        assert rows["int8"].fraction < rows["bf16"].fraction
        assert rows["int8_delta"].fraction < rows["bf16"].fraction

    def test_doctored_fixture_fails(self):
        from rocm_mpi_tpu.perf.traffic import audit_wire_modes

        rows = audit_wire_modes(local=16, deep_k=4,
                                include_wire_fixture=True)
        fixture = [r for r in rows if r.fixture]
        assert len(fixture) == 1
        assert not fixture[0].ok
        assert fixture[0].fraction > fixture[0].ladder

    def test_cli_exits_1_on_wire_fixture(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-m", "rocm_mpi_tpu.perf",
             "--include-wire-fixture"],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=600,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "bf16(fixture)" in proc.stdout
        assert "OVER LADDER" in proc.stdout
        # The real modes still pass inside the same run.
        for mode in wire.WIRE_MODES:
            for line in proc.stdout.splitlines():
                if line.startswith(mode + " "):
                    assert line.rstrip().endswith("ok"), line

    def test_budgets_wire_block_schema(self):
        from rocm_mpi_tpu.telemetry import regress

        doc = json.loads(
            (REPO / "rocm_mpi_tpu/perf/budgets.json").read_text()
        )
        assert regress.check_schema(
            [REPO / "rocm_mpi_tpu/perf/budgets.json"]
        ) == []
        assert set(doc["wire"]["ladder"]) == set(wire.WIRE_MODES)

    def test_regress_wire_mode_registry_pinned(self):
        # regress spells the registry locally (stdlib read side);
        # drift against the real one fails here.
        from rocm_mpi_tpu.telemetry import regress

        assert tuple(regress._WIRE_MODES) == tuple(wire.WIRE_MODES)

    def test_doctored_budgets_fail_schema(self, tmp_path):
        from rocm_mpi_tpu.telemetry import regress

        doc = json.loads(
            (REPO / "rocm_mpi_tpu/perf/budgets.json").read_text()
        )
        doc["wire"]["ladder"]["fp4"] = 0.1
        bad = tmp_path / "budgets.json"
        bad.write_text(json.dumps(doc))
        problems = regress.check_schema([bad])
        assert problems and "unknown mode" in problems[0]


# ---------------------------------------------------------------------------
# The tuning axis (space / gate / resolve / search / validate CLI)
# ---------------------------------------------------------------------------


class TestTuningWireAxis:
    def test_deep_space_enumerates_wire_modes(self):
        from rocm_mpi_tpu.tuning import space

        cands = space.enumerate_space("diffusion.deep", (32, 32), "f32")
        modes = {c["wire_mode"] for c in cands}
        assert modes == set(wire.WIRE_MODES)
        # f32 first: the tie-break must prefer full precision.
        assert cands[0]["wire_mode"] == "f32"

    def test_gate_accepts_certified_modes(self):
        from rocm_mpi_tpu.tuning import gate

        for mode in wire.WIRE_MODES:
            g = gate.validate_config(
                "diffusion.deep", (32, 32), "f32",
                {"k": 8, "wire_mode": mode},
            )
            assert g.ok, (mode, g.reason)

    def test_gate_rejects_unknown_and_misfamilied(self):
        from rocm_mpi_tpu.tuning import gate

        g = gate.validate_config("diffusion.deep", (32, 32), "f32",
                                 {"k": 8, "wire_mode": "fp4"})
        assert not g.ok and "fp4" in g.reason
        g = gate.validate_config("diffusion.vmem_loop", (32, 32), "f32",
                                 {"chunk": 16, "wire_mode": "bf16"})
        assert not g.ok and "not a knob" in g.reason
        g = gate.validate_config("diffusion.scan", (32, 32), "f32",
                                 {"chunk": 16, "wire_mode": "int8"})
        assert not g.ok and "stateless" in g.reason

    def test_gate_rejects_out_of_tolerance_winner(self, monkeypatch):
        # THE teeth: a mode failing the f64-oracle contract is rejected
        # no matter what it measured.
        from rocm_mpi_tpu.tuning import gate

        monkeypatch.setitem(wire.TOLERANCE, "int8", 1e-12)
        g = gate.validate_config("diffusion.deep", (32, 32), "f32",
                                 {"k": 8, "wire_mode": "int8"})
        assert not g.ok and "tolerance contract" in g.reason

    def test_gate_rejects_over_ladder_winner(self, monkeypatch):
        from rocm_mpi_tpu.tuning import gate

        monkeypatch.setitem(gate._WIRE_LADDER_CACHE, "ladder",
                            {"bf16": 0.1})
        g = gate.validate_config("diffusion.deep", (32, 32), "f32",
                                 {"k": 8, "wire_mode": "bf16"})
        assert not g.ok and "wire-bytes ladder" in g.reason

    def test_search_refuses_to_measure_uncertified_candidate(
        self, monkeypatch, tmp_path
    ):
        from rocm_mpi_tpu.tuning import search

        monkeypatch.setitem(wire.TOLERANCE, "int8", 1e-12)
        out = search.search_op(
            "diffusion.deep", (16, 16), "f32",
            cache_path=tmp_path / "cache.json",
            candidates=[{"k": 4, "wire_mode": "int8"}],
        )
        assert out["status"] == "all-rejected"
        assert "tolerance contract" in out["rejected"][0][1]

    def test_validate_cli_rejects_doctored_wire_winner(
        self, monkeypatch, tmp_path
    ):
        from rocm_mpi_tpu.tuning import cache as tcache
        from rocm_mpi_tpu.tuning import keys as tkeys
        from rocm_mpi_tpu.tuning.__main__ import main as tuning_main

        key = tkeys.tuning_key("diffusion.deep", (16, 16), "f32",
                               topology=(2, 2))
        path = tmp_path / "cache.json"
        tcache.store(path, key, {
            "config": {"k": 4, "wire_mode": "int8"},
            "median_us": 1.0, "compile_s": 0.0, "gate_ratio": 1.0,
            "fingerprint": tkeys.fingerprint(key.backend),
        })
        assert tuning_main(["validate", str(path)]) == 0
        monkeypatch.setitem(wire.TOLERANCE, "int8", 1e-12)
        assert tuning_main(["validate", str(path)]) == 1

    def test_resolve_sanitizes_wire_field(self):
        from rocm_mpi_tpu.tuning import resolve

        assert resolve._sanitize(
            {"k": 8, "wire_mode": "bf16"}
        ) == {"k": 8, "wire_mode": "bf16"}
        assert resolve._sanitize({"k": 8, "wire_mode": "fp4"}) == {"k": 8}

    def test_auto_resolves_tuned_wire_mode(self, tmp_path):
        from rocm_mpi_tpu.tuning import cache as tcache
        from rocm_mpi_tpu.tuning import keys as tkeys
        from rocm_mpi_tpu.tuning import resolve

        cfg = DiffusionConfig(global_shape=(32, 32), nt=16, warmup=0,
                              dims=(2, 2), dtype="f32")
        model = HeatDiffusion(cfg)
        key = tkeys.tuning_key("diffusion.deep",
                               model.grid.local_shape, "f32",
                               topology=model.grid.dims)
        path = tmp_path / "cache.json"
        tcache.store(path, key, {
            "config": {"k": 4, "wire_mode": "bf16"},
            "median_us": 1.0, "compile_s": 0.0, "gate_ratio": 1.0,
            "fingerprint": tkeys.fingerprint(key.backend),
        })
        resolve.configure(path)
        try:
            # tuned wins under config="auto"; an explicit wire_mode
            # wins over tuned; no config means the cfg field.
            assert model.effective_wire_mode(None, "auto") == "bf16"
            assert model.effective_wire_mode("int8", "auto") == "int8"
            assert model.effective_wire_mode(None, None) == "f32"
        finally:
            resolve.configure(None)


# ---------------------------------------------------------------------------
# Telemetry surfacing (summary badge, gauge fold, monitor)
# ---------------------------------------------------------------------------


class TestTelemetrySurfacing:
    def test_summary_wire_modes_and_gauge_fold(self):
        from rocm_mpi_tpu.telemetry import aggregate

        streams = {0: [
            {"kind": "trace", "name": "halo.exchange",
             "attrs": {"wire": "bf16", "bytes": 520}},
            {"kind": "gauge", "name": "run.gpts", "value": 2.0,
             "attrs": {"devices": 4, "driver": "scan", "wire": "bf16"}},
            {"kind": "gauge", "name": "run.t_eff_gbs", "value": 9.0,
             "attrs": {"wire": "f32"}},
        ]}
        summary = aggregate.summarize(streams)
        assert summary["wire_modes"] == ["bf16"]
        assert "run.gpts@4dev:scan:bf16" in summary["gauges"]
        # f32 keeps the classic key — committed baselines stay live.
        assert "run.t_eff_gbs" in summary["gauges"]
        assert "WIRE MODE: bf16" in aggregate.format_summary(summary)

    def test_f32_summary_has_no_badge(self):
        from rocm_mpi_tpu.telemetry import aggregate

        streams = {0: [
            {"kind": "trace", "name": "halo.exchange",
             "attrs": {"wire": "f32", "bytes": 1040}},
        ]}
        summary = aggregate.summarize(streams)
        assert summary["wire_modes"] == ["f32"]
        assert "WIRE MODE" not in aggregate.format_summary(summary)

    def test_monitor_wire_status(self, tmp_path):
        from rocm_mpi_tpu.telemetry import health

        (tmp_path / "telemetry-rank0.jsonl").write_text(
            json.dumps({"kind": "trace", "name": "deep.sweep", "v": 2,
                        "attrs": {"wire": "int8_delta", "k": 8}}) + "\n"
        )
        modes = health.wire_status(tmp_path)
        assert modes == ["int8_delta"]
        assert health.format_wire_status(modes) == "[WIRE int8_delta]"
        assert health.format_wire_status(["f32"]) is None
        assert health.format_wire_status([]) is None


# ---------------------------------------------------------------------------
# Schedule plumbing (rebuild keeps the mode; state shapes shard cleanly)
# ---------------------------------------------------------------------------


class TestSchedulePlumbing:
    def test_deep_schedule_rebuild_keeps_wire_mode(self):
        from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

        grid = init_global_grid(32, 32, dims=(2, 2))
        sched = make_deep_sweep(grid, 4, 1.0, jnp.float32(0.01),
                                (0.3, 0.3), wire_mode="int8_delta")
        assert sched.wire_mode == "int8_delta"
        assert sched.init_wire is not None
        rebuilt = sched.rebuild(grid)
        assert rebuilt.wire_mode == "int8_delta"
        assert rebuilt.init_wire is not None

    def test_init_exchange_state_shapes(self):
        grid = init_global_grid(8, 8, dims=(2, 2))
        ws = wire.init_exchange_state(grid, 1, "int8", jnp.float32)
        # 2 axes x 2 sides x arity 1; global shapes scale by dims.
        assert [w.shape for w in ws] == [
            (2, 8), (2, 8), (12, 2), (12, 2)
        ]
        wd = wire.init_exchange_state(grid, 1, "int8_delta", jnp.float32)
        assert len(wd) == 12  # arity 3
        assert wire.init_exchange_state(grid, 1, "f32", jnp.float32) == ()
