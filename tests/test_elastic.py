"""Topology-elastic checkpoints and mesh-elastic launches
(docs/RESILIENCE.md "Elastic recovery").

The claims, pinned:

  * v2 manifests are self-describing: mesh dims/axes + per-leaf
    partition specs ride next to the integrity records, validated by
    verify_step (garbage metadata = corrupt checkpoint, fall back);
  * restores are topology-portable: a checkpoint written on one mesh
    restores — template-less (`like=None`, metadata only) or with a
    differently-sharded `like` — onto another, BITWISE; mismatched
    global facts raise TopologyMismatch, v1 manifests keep the legacy
    same-template path with a warning;
  * rebuild_for_mesh re-derives the per-mesh machinery (grid, halo
    programs, deep-halo schedules) for a new decomposition, matching a
    fresh build exactly;
  * the launcher detects VANISHED ranks (clean rc mid-run, fault kind
    `die`) that no nonzero-rc scan can see;
  * run_elastic shrinks to the largest valid sub-mesh and resumes
    instead of aborting — policy unit-tested with an injected launcher,
    then proven gloo-real: kill / die / stall a rank mid-run on 2 ranks,
    shrink to 1, resume from the latest valid step, final checkpoint
    bitwise-equal to an uninterrupted 1-rank continuation of the same
    global state. Clean runs never shrink;
  * the other half (ISSUE 9): `device_budget` arms the rejoin probe and
    run_elastic GROWS back — preempt the reduced-mesh run at a segment
    boundary, relaunch on the largest valid larger mesh — with every
    shrink/grow/give-up decision in the pluggable ElasticPolicy
    (hysteresis table-drilled with fake launches, shrink precedence
    over grow, preempted exits judged resumable and bounded), proven
    gloo-real in test_elastic_drill_shrinks_then_grows_back with the
    final checkpoint bitwise-equal to an uninterrupted 2-rank
    continuation. Clean runs with budget == mesh never change topology.
"""

import json
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.parallel import deep_halo
from rocm_mpi_tpu.parallel import halo as phalo
from rocm_mpi_tpu.parallel import mesh as pmesh
from rocm_mpi_tpu.parallel.launcher import spawn_ranks
from rocm_mpi_tpu.resilience import (
    ElasticExhausted,
    ElasticPolicy,
    faults,
    preempt,
    reshard,
    run_elastic,
)
from rocm_mpi_tpu.telemetry import health
from rocm_mpi_tpu.utils import checkpoint as ckpt

ROOT = pathlib.Path(__file__).resolve().parent.parent

NT, EVERY = 16, 4


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.install(None)
    preempt.reset()


def _model(dims=(2, 4), shape=(32, 32)):
    cfg = DiffusionConfig(
        global_shape=shape, lengths=(10.0, 10.0), nt=NT, warmup=0,
        dtype="f64", dims=dims,
    )
    model = HeatDiffusion(cfg)
    T, Cp = model.init_state()
    advance = model.advance_fn("perf")
    adv = lambda s, n: (advance(s[0], Cp, n),)  # noqa: E731
    return model, adv, (T,)


# ---------------------------------------------------------------------------
# Manifest v2: topology metadata, validation, legacy fallbacks
# ---------------------------------------------------------------------------


def test_manifest_records_topology_metadata(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    for step in ckpt.all_steps(tmp_path):
        man = ckpt.read_manifest(tmp_path, step)
        assert man["v"] == ckpt.MANIFEST_VERSION
        assert man["meta"]["mesh"] == {"dims": [2, 4], "axes": ["gx", "gy"]}
        assert man["meta"]["specs"] == [["gx", "gy"]]
        assert ckpt.validate_manifest_meta(man) == []
        ok, reason = ckpt.verify_step(tmp_path, step)
        assert ok, reason


def test_corrupt_metadata_invalidates_step(tmp_path):
    """latest_valid_step must skip a step whose topology metadata fails
    validation — a template-less resume would plan a mesh from it."""
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    latest = ckpt.latest_step(tmp_path)
    prev = ckpt.all_steps(tmp_path)[-2]
    mpath = tmp_path / f"manifest-{latest}.json"
    man = json.loads(mpath.read_text())
    man["meta"]["specs"] = [["no-such-axis", "gy"]]
    mpath.write_text(json.dumps(man))
    ok, reason = ckpt.verify_step(tmp_path, latest)
    assert not ok and "metadata" in reason
    msgs = []
    assert ckpt.latest_valid_step(tmp_path, log=msgs.append) == prev
    assert any("metadata" in m for m in msgs), msgs


def _strip_to_v1(directory, step):
    mpath = pathlib.Path(directory) / f"manifest-{step}.json"
    man = json.loads(mpath.read_text())
    man.pop("meta", None)
    man.pop("v", None)
    mpath.write_text(json.dumps(man))


def test_v1_manifest_restores_same_mesh_with_warning(tmp_path):
    _, adv, state = _model()
    ref = adv((jnp.copy(state[0]),), NT // 2)
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    _strip_to_v1(tmp_path, NT // 2)
    ok, reason = ckpt.verify_step(tmp_path, NT // 2)
    assert ok, reason  # v1 stays a VALID step (legacy contract)
    _, _, like = _model()
    with pytest.warns(UserWarning, match="v1"):
        out = ckpt.restore_state(tmp_path, NT // 2, like)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))


def test_v1_manifest_refuses_templateless_restore(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    _strip_to_v1(tmp_path, NT)
    with pytest.raises(ckpt.TopologyMismatch, match="pass `like`"):
        ckpt.restore_state(tmp_path, NT, like=None)


def test_mismatched_like_raises_topology_mismatch(tmp_path):
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    # Wrong GLOBAL shape: a clear refusal, not an orbax shape error.
    _, _, small = _model(dims=(2, 2), shape=(16, 16))
    with pytest.raises(ckpt.TopologyMismatch, match="global shape"):
        ckpt.restore_state(tmp_path, NT, small)
    # Wrong dtype, same shape.
    cfg = DiffusionConfig(global_shape=(32, 32), lengths=(10.0, 10.0),
                          nt=NT, warmup=0, dtype="f32", dims=(2, 2))
    T32, _ = HeatDiffusion(cfg).init_state()
    with pytest.raises(ckpt.TopologyMismatch, match="dtype"):
        ckpt.restore_state(tmp_path, NT, (T32,))
    # Wrong leaf count.
    _, _, like = _model()
    with pytest.raises(ckpt.TopologyMismatch, match="leaves"):
        ckpt.restore_state(tmp_path, NT, (like[0], like[0]))


# ---------------------------------------------------------------------------
# Cross-mesh restore: the topology-portable tentpole
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("new_dims", [(2, 2), (1, 1), (4, 2), (1, 2)])
def test_restore_onto_different_mesh_is_bitwise(tmp_path, new_dims):
    """A checkpoint written on (2,4) restores onto other decompositions
    (shrunk, grown, transposed) via a re-sharded `like` with identical
    global content, and the restored state advances on the new mesh
    exactly as a device_put of the same global state does."""
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT // 2, tmp_path, every=EVERY)
    model2, adv2, like2 = _model(dims=new_dims)
    got = ckpt.restore_state(tmp_path, NT // 2, like2)
    assert got[0].sharding.mesh.devices.shape == new_dims
    base = ckpt.restore_state(tmp_path, NT // 2, like=None)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(base[0]))
    # Continue on the new mesh: restored-and-advanced == device_put'd
    # global state advanced (same mesh, same program) — the "straight
    # run on the small mesh from the same global state" contract.
    straight = jax.device_put(np.asarray(got[0]), model2.grid.sharding)
    out_restored = adv2((got[0],), NT // 2)
    out_straight = adv2((straight,), NT // 2)
    np.testing.assert_array_equal(
        np.asarray(out_restored[0]), np.asarray(out_straight[0])
    )


@pytest.mark.parametrize(
    "n_dev,planned",
    [(8, (2, 4)),  # same budget: the saved decomposition is reused
     (4, (2, 2)), (2, (2, 1)), (1, (1, 1)),
     (3, (2, 1))],  # 3 cannot tile 32x32 as (3,1): largest valid is 2
)
def test_templateless_restore_plans_largest_submesh(tmp_path, n_dev,
                                                    planned):
    _, adv, state = _model()
    ref = np.asarray(state[0])
    ckpt.run_segmented(adv, state, NT // 2, tmp_path, every=EVERY)
    got = ckpt.restore_state(
        tmp_path, NT // 2, like=None, devices=jax.devices()[:n_dev]
    )
    assert got[0].sharding.mesh.devices.shape == planned
    assert got[0].shape == ref.shape  # global domain untouched


def test_restored_state_is_donation_safe_after_reshard(tmp_path):
    """The GL01 contract holds on the elastic path too: a cross-mesh
    restored state donates straight into the new mesh's advance."""
    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT // 2, tmp_path, every=EVERY)
    got = ckpt.restore_state(tmp_path, NT // 2, like=None,
                             devices=jax.devices()[:4])
    ref = np.asarray(got[0])
    _, adv2, _ = _model(dims=(2, 2))
    out = adv2(got, EVERY)  # donates got[0]
    again = ckpt.restore_state(tmp_path, NT // 2, like=None,
                               devices=jax.devices()[:4])
    np.testing.assert_array_equal(np.asarray(again[0]), ref)
    assert np.isfinite(np.asarray(out[0])).all()


# ---------------------------------------------------------------------------
# Reshard + rebuild_for_mesh: the slab path and the per-mesh re-derivation
# ---------------------------------------------------------------------------


def test_reshard_state_roundtrip():
    model, _, state = _model()
    new_grid = pmesh.rebuild_for_mesh(model.grid, dims=(4, 2))
    out = reshard.reshard_state(state, new_grid)
    assert out[0].sharding.mesh.devices.shape == (4, 2)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(state[0]))


def test_plan_dims_policy():
    assert pmesh.plan_dims((32, 32), 8) == (4, 2)
    assert pmesh.plan_dims((32, 32), 3) == (2, 1)
    assert pmesh.plan_dims((30, 30), 8) == (3, 2)
    assert pmesh.plan_dims((7, 7), 8) == (7, 1)
    with pytest.raises(ValueError):
        pmesh.plan_dims((8, 8), 0)


def test_mesh_rebuild_validates_divisibility():
    grid = pmesh.init_global_grid(32, 32, dims=(2, 4))
    new = pmesh.rebuild_for_mesh(grid, dims=(2, 2))
    assert new.global_shape == grid.global_shape
    assert new.lengths == grid.lengths
    assert new.dims == (2, 2)
    with pytest.raises(ValueError, match="not divisible"):
        pmesh.rebuild_for_mesh(grid, dims=(3, 1))


def test_halo_rebuild_for_mesh_rederives_geometry():
    grid = pmesh.init_global_grid(32, 32, dims=(2, 4))
    prog = phalo.build_for_mesh(grid, width=2)
    re = phalo.rebuild_for_mesh(prog, dims=(2, 2))
    assert re.grid.dims == (2, 2) and re.width == 2
    assert re.grid.local_shape == (16, 16)
    assert re.nbytes(8) == phalo.exchange_nbytes((16, 16), 8, 2)
    assert re.nbytes(8) != prog.nbytes(8)
    with pytest.raises(ValueError, match="width"):
        phalo.rebuild_for_mesh(grid, dims=(1, 1), width=33)


def test_deep_schedule_rebuild_matches_fresh_build():
    cfg = DiffusionConfig(global_shape=(32, 32), lengths=(10.0, 10.0),
                          nt=NT, warmup=0, dtype="f64", dims=(2, 4))
    model = HeatDiffusion(cfg)
    dt = cfg.jax_dtype(cfg.dt)
    sched = deep_halo.make_deep_sweep(model.grid, 4, cfg.lam, dt,
                                      cfg.spacing, local_form="jnp")
    new_grid = pmesh.rebuild_for_mesh(model.grid, dims=(2, 2))
    rebuilt = deep_halo.rebuild_for_mesh(sched, new_grid)
    fresh = deep_halo.make_deep_sweep(new_grid, 4, cfg.lam, dt,
                                      cfg.spacing, local_form="jnp")
    assert rebuilt.k == fresh.k == 4
    T, Cp = model.init_state()
    Tn = jax.device_put(np.asarray(T), new_grid.sharding)
    Cpn = jax.device_put(np.asarray(Cp), new_grid.sharding)
    np.testing.assert_array_equal(
        np.asarray(rebuilt.sweep(Tn, rebuilt.prepare(Cpn))),
        np.asarray(fresh.sweep(Tn, fresh.prepare(Cpn))),
    )


def test_deep_schedule_without_rebuild_fails_loudly():
    sched = deep_halo.DeepSchedule(lambda x: x, lambda x, c: x, 4)
    grid = pmesh.init_global_grid(32, 32, dims=(2, 2))
    with pytest.raises(ValueError, match="rebuild"):
        deep_halo.rebuild_for_mesh(sched, grid)


# ---------------------------------------------------------------------------
# Fault kind `die` + launcher vanish detection
# ---------------------------------------------------------------------------


def test_die_fault_parses_and_requires_trigger():
    plan = faults.FaultPlan.parse("die@step=4,rank=1")
    assert plan.clauses[0].kind == "die"
    assert plan.clauses[0].step == 4 and plan.clauses[0].rank == 1
    with pytest.raises(ValueError, match="needs a step"):
        faults.FaultPlan.parse("die")


def test_fault_site_scoping_is_opt_in():
    """segment-pre only fires for clauses explicitly scoped there: an
    unscoped legacy spec must keep firing at the post-save site."""
    plan = faults.install("crash@step=8")
    faults.fault_point("segment-pre", step=8)
    assert plan.clauses[0].fires == 0
    with pytest.raises(faults.InjectedCrash):
        faults.fault_point("segment", step=8)
    plan = faults.install("crash@step=8,at=segment-pre")
    faults.fault_point("segment", step=8)  # wrong site: no fire
    assert plan.clauses[0].fires == 0
    with pytest.raises(faults.InjectedCrash):
        faults.fault_point("segment-pre", step=8)
    assert "at=segment-pre" in repr(plan.clauses[0])


def test_launcher_flags_vanished_rank_and_reaps_peers():
    """A rank exiting rc=0 mid-run (fault kind `die`) while its peer
    hangs must be reclassified as a death once the vanish grace passes —
    no nonzero rc ever appears for the legacy first-failure scan."""
    results = spawn_ranks(
        [str(ROOT / "tests" / "resilience_worker.py"), "--hang-after"],
        nprocs=2,
        timeout=60,
        inject_fault="die@step=3,rank=1",
        heartbeat_s=1.0,
        peer_grace_s=3.0,
        vanish_grace_s=3.0,
    )
    (p0, (out0, _)), (p1, (out1, _)) = results
    assert p1.returncode == 0, out1
    assert "WORKER_DONE" not in out1  # it died mid-loop, cleanly
    report = results.report
    assert report.vanished == 1
    assert report.first_failure is not None
    assert report.first_failure[:2] == (1, 0)
    assert report.killed_after_failure == [0]
    assert p0.returncode != 0
    assert any("vanish" in e for e in report.events), report.events


def test_launcher_clean_run_with_vanish_grace_reports_nothing():
    results = spawn_ranks(
        [str(ROOT / "tests" / "resilience_worker.py")],
        nprocs=2, timeout=60, peer_grace_s=3.0, vanish_grace_s=2.0,
    )
    for pid, (p, (out, err)) in enumerate(results):
        assert p.returncode == 0, (pid, err[-500:])
    assert results.report.vanished is None
    assert results.report.first_failure is None
    assert results.report.killed_after_failure == []


# ---------------------------------------------------------------------------
# Elastic policy (injected launcher — no processes)
# ---------------------------------------------------------------------------


class _FakeProc:
    def __init__(self, rc):
        self.returncode = rc


def _fake_results(rcs, first_failure=None, vanished=None, verdicts=()):
    from rocm_mpi_tpu.parallel.launcher import LaunchReport, RankResults

    r = RankResults((_FakeProc(rc), ("", "")) for rc in rcs)
    r.report = LaunchReport()
    r.report.first_failure = first_failure
    r.report.vanished = vanished
    r.report.watchdog_verdicts = list(verdicts)
    return r


def test_elastic_shrinks_once_then_completes(tmp_path):
    calls = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        calls.append((nprocs, inject_fault))
        if len(calls) == 1:
            return _fake_results([0, 43], first_failure=(1, 43, 1.0))
        return _fake_results([0] * nprocs)

    report = run_elastic(
        ["worker.py"], 2, global_shape=(32, 32), sidecar_dir=tmp_path,
        inject_fault="kill@step=8,rank=1", launch=launch,
    )
    assert [c[0] for c in calls] == [2, 1]
    # The fault spec arms the FIRST launch only: it already happened.
    assert calls[0][1] == "kill@step=8,rank=1" and calls[1][1] is None
    assert report.shrinks == 1 and report.final_nprocs == 1
    names = [e["name"] for e in report.events]
    assert names == ["elastic.launch", "elastic.shrink",
                     "elastic.launch", "elastic.complete"]
    shrink = report.events[1]
    assert shrink["old_mesh"] == [2, 1] and shrink["new_mesh"] == [1, 1]
    assert shrink["dead_ranks"] == [1]
    # Sidecar round-trips through the health reader.
    events, skipped = health.load_elastic_events(tmp_path)
    assert skipped == 0 and [e["name"] for e in events] == names


def test_elastic_judges_watchdog_and_vanish(tmp_path):
    seen = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        if len(seen) == 0:
            seen.append("stall")
            return _fake_results(
                [0, -9], verdicts=[{"rank": 1, "step": 8,
                                    "median_step": 10.0,
                                    "stalled_for_s": 6.0,
                                    "last_phase": "checkpoint"}],
                first_failure=(1, -9, 9.0),
            )
        if len(seen) == 1:
            seen.append("vanish")
            return _fake_results([0, 0], vanished=0,
                                 first_failure=(0, 0, 4.0))
        return _fake_results([0] * nprocs)

    report = run_elastic(
        ["worker.py"], 4, global_shape=(32, 32), sidecar_dir=tmp_path,
        launch=launch, min_ranks=1,
    )
    assert report.shrinks == 2
    reasons = [l["reason"] for l in report.launches]
    assert reasons[0] == "watchdog-stall"
    assert "vanished" in reasons[1]
    assert report.launches[0]["dead_ranks"] == [1]
    assert report.launches[1]["dead_ranks"] == [0]


def test_elastic_shrinks_past_every_dead_rank(tmp_path):
    """Two ranks dead in one launch → the next budget excludes BOTH:
    4 ranks with two watchdog verdicts re-plans for 2, not 3."""
    calls = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        calls.append(nprocs)
        if len(calls) == 1:
            return _fake_results(
                [0, -9, -9, 0],
                verdicts=[{"rank": 1, "step": 4, "median_step": 8.0,
                           "stalled_for_s": 6.0, "last_phase": "halo"},
                          {"rank": 2, "step": 4, "median_step": 8.0,
                           "stalled_for_s": 6.0, "last_phase": "halo"}],
                first_failure=(1, -9, 5.0),
            )
        return _fake_results([0] * nprocs)

    report = run_elastic(["worker.py"], 4, global_shape=(32, 32),
                         sidecar_dir=tmp_path, launch=launch)
    assert calls == [4, 2]
    assert report.launches[0]["dead_ranks"] == [1, 2]
    shrink = report.events[1]
    assert shrink["old_nprocs"] == 4 and shrink["new_nprocs"] == 2


def test_elastic_gives_up_at_min_ranks(tmp_path):
    def launch(argv, nprocs, inject_fault=None, **kw):
        return _fake_results([1] * nprocs, first_failure=(0, 1, 0.5))

    with pytest.raises(ElasticExhausted, match="minimum rank count"):
        run_elastic(["worker.py"], 2, sidecar_dir=tmp_path, launch=launch)
    events, _ = health.load_elastic_events(tmp_path)
    assert events[-1]["name"] == "elastic.gave-up"


def test_elastic_clean_run_never_shrinks(tmp_path):
    def launch(argv, nprocs, inject_fault=None, **kw):
        return _fake_results([0] * nprocs)

    report = run_elastic(["worker.py"], 2, global_shape=(32, 32),
                         sidecar_dir=tmp_path, launch=launch)
    assert report.shrinks == 0 and report.final_nprocs == 2
    assert [e["name"] for e in report.events] == ["elastic.launch",
                                                  "elastic.complete"]
    st = health.elastic_status(report.events)
    assert st["shrunk"] is False
    assert "SHRUNK" not in health.format_elastic_status(st)


def test_elastic_callable_argv_gets_rank_count(tmp_path):
    argvs = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        if len(argvs) == 1:
            return _fake_results([0, 1], first_failure=(1, 1, 1.0))
        return _fake_results([0] * nprocs)

    def make_argv(nprocs, attempt):
        argvs.append((nprocs, attempt))
        return ["worker.py", f"--n={nprocs}"]

    run_elastic(make_argv, 2, sidecar_dir=tmp_path, launch=launch)
    assert argvs == [(2, 0), (1, 1)]


# ---------------------------------------------------------------------------
# ElasticPolicy: the pluggable decision table (ISSUE 9)
# ---------------------------------------------------------------------------


def test_policy_wants_grow_table():
    p = ElasticPolicy(min_grow_interval_steps=0)
    assert p.wants_grow(2, 4) is True           # budget exceeds, no interval
    assert p.wants_grow(2, 2) is False          # budget == running: no grow
    assert p.wants_grow(4, 2) is False          # budget below: never
    assert ElasticPolicy(grow=False).wants_grow(2, 4) is False  # master off
    h = ElasticPolicy(min_grow_interval_steps=8)
    # Hysteresis that cannot be evaluated fails CLOSED.
    assert h.wants_grow(2, 4, step=None) is False
    assert h.wants_grow(2, 4, step=12, last_change_step=8) is False  # 4 < 8
    assert h.wants_grow(2, 4, step=16, last_change_step=8) is True   # 8 >= 8
    assert h.wants_grow(2, 4, step=16, last_change_step=None) is True


def test_policy_targets_and_give_up():
    p = ElasticPolicy(min_ranks=2)
    assert p.give_up(2) is True and p.give_up(3) is False
    ident = lambda b: b  # noqa: E731
    # Shrink plans for the SURVIVORS (never n-1 with two dead), floored.
    assert p.shrink_target(4, 1, ident) == 3
    assert p.shrink_target(4, 2, ident) == 2
    assert p.shrink_target(3, 2, ident) == 2  # min_ranks floor
    # Grow may come back equal when no larger mesh tiles the grid.
    assert p.grow_target(2, 8, lambda b: 2) == 2
    assert p.grow_target(2, 8, lambda b: 8) == 8


def test_judge_classifies_preempted_exits():
    from rocm_mpi_tpu.resilience.elastic import _judge

    status, dead, reason = _judge(_fake_results([75, 75]))
    assert status == "preempted" and dead == []
    status, _, _ = _judge(_fake_results([0, 75]))
    assert status == "preempted"
    # A mix of preempted and REAL failure is a failure.
    status, dead, _ = _judge(
        _fake_results([75, 1], first_failure=(1, 1, 2.0))
    )
    assert status == "failed" and dead == [1]
    assert _judge(_fake_results([0, 0]))[0] == "ok"


def test_elastic_grows_after_preempted_launch(tmp_path):
    """The between-launches grow: a preempted launch re-plans against
    the budget and relaunches on the largest valid larger mesh."""
    calls = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        calls.append(nprocs)
        if len(calls) == 1:
            return _fake_results([75, 75])
        return _fake_results([0] * nprocs)

    report = run_elastic(
        ["worker.py"], 2, global_shape=(32, 32), sidecar_dir=tmp_path,
        launch=launch, device_budget=4,
    )
    assert calls == [2, 4]
    assert report.grows == 1 and report.shrinks == 0
    assert report.final_nprocs == 4
    names = [e["name"] for e in report.events]
    assert names == ["elastic.launch", "elastic.grow",
                     "elastic.launch", "elastic.complete"]
    grow = report.events[1]
    assert grow["old_nprocs"] == 2 and grow["new_nprocs"] == 4
    assert grow["old_mesh"] == [2, 1] and grow["new_mesh"] == [2, 2]
    assert grow["reason"] == "device-budget"


def test_elastic_preempted_without_budget_resumes_same_topology(tmp_path):
    calls = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        calls.append(nprocs)
        if len(calls) == 1:
            return _fake_results([75, 75])
        return _fake_results([0] * nprocs)

    report = run_elastic(["worker.py"], 2, global_shape=(32, 32),
                         sidecar_dir=tmp_path, launch=launch)
    assert calls == [2, 2]
    assert report.resumes == 1 and report.grows == 0
    assert [e["name"] for e in report.events] == [
        "elastic.launch", "elastic.resume",
        "elastic.launch", "elastic.complete",
    ]


def test_elastic_hysteresis_refuses_then_allows_grow(tmp_path, monkeypatch):
    """The fake-launch hysteresis table: a preempted relaunch inside the
    min-interval resumes at the same size; once the run has advanced
    past the interval, the same budget signal grows."""
    from rocm_mpi_tpu.utils import checkpoint as uckpt

    steps = {"now": 8}
    monkeypatch.setattr(
        uckpt, "latest_valid_step",
        lambda directory, log=None: steps["now"],
    )
    policy = ElasticPolicy(min_grow_interval_steps=6)
    calls = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        calls.append(nprocs)
        if len(calls) == 1:
            return _fake_results([75, 75])   # step still 8: refused
        if len(calls) == 2:
            steps["now"] = 16                # advanced 8 >= 6: allowed
            return _fake_results([75, 75])
        return _fake_results([0] * nprocs)

    report = run_elastic(
        ["worker.py"], 2, global_shape=(32, 32), sidecar_dir=tmp_path,
        checkpoint_dir=tmp_path / "ck", launch=launch,
        device_budget=4, policy=policy,
    )
    assert calls == [2, 2, 4]
    assert report.resumes == 1 and report.grows == 1
    names = [e["name"] for e in report.events]
    assert names == ["elastic.launch", "elastic.resume", "elastic.launch",
                     "elastic.grow", "elastic.launch", "elastic.complete"]
    grow = next(e for e in report.events if e["name"] == "elastic.grow")
    assert grow["resume_step"] == 16


def test_elastic_shrink_takes_precedence_over_grow(tmp_path):
    """Both signals at once — a dead rank AND an optimistic budget — and
    the supervisor must believe the corpse, not the budget."""
    calls = []

    def launch(argv, nprocs, inject_fault=None, **kw):
        calls.append(nprocs)
        if len(calls) == 1:
            return _fake_results([0, 43, 0, 0], first_failure=(1, 43, 1.0))
        return _fake_results([0] * nprocs)

    report = run_elastic(
        ["worker.py"], 4, global_shape=(32, 32), sidecar_dir=tmp_path,
        launch=launch, device_budget=8,
    )
    assert calls == [4, 2]
    assert report.shrinks == 1 and report.grows == 0
    names = [e["name"] for e in report.events]
    assert "elastic.shrink" in names and "elastic.grow" not in names


def test_elastic_parent_notice_stops_relaunching(tmp_path):
    """When the PARENT itself holds the eviction notice (the launcher's
    forwarder stamped it), a preempted launch is not relaunched: the
    whole job is being taken, and the report says 'resumable'."""

    def launch(argv, nprocs, inject_fault=None, **kw):
        preempt.request(grace_s=30.0)  # the forwarder's stamp
        return _fake_results([75, 75])

    report = run_elastic(["worker.py"], 2, global_shape=(32, 32),
                         sidecar_dir=tmp_path, launch=launch)
    assert report.preempted is True
    assert report.final_nprocs == 2 and report.resumes == 0
    assert report.events[-1]["name"] == "elastic.preempted"
    st = health.elastic_status(report.events)
    assert st["preempted"] is True
    assert "PREEMPTED" in health.format_elastic_status(st)


def test_elastic_preempt_resumes_are_bounded(tmp_path):
    def launch(argv, nprocs, inject_fault=None, **kw):
        return _fake_results([75, 75])

    with pytest.raises(ElasticExhausted, match="preempted"):
        run_elastic(
            ["worker.py"], 2, sidecar_dir=tmp_path, launch=launch,
            policy=ElasticPolicy(max_preempt_resumes=2),
        )
    events, _ = health.load_elastic_events(tmp_path)
    assert events[-1]["name"] == "elastic.gave-up"


# ---------------------------------------------------------------------------
# Schema gate + monitor badge
# ---------------------------------------------------------------------------


def test_check_schema_validates_manifests_and_elastic_records(tmp_path):
    from rocm_mpi_tpu.telemetry import regress

    _, adv, state = _model()
    ckpt.run_segmented(adv, state, NT // 2, tmp_path, every=EVERY)
    step = ckpt.latest_step(tmp_path)
    mpath = tmp_path / f"manifest-{step}.json"
    health.append_elastic_event(tmp_path, "elastic.launch", attempt=0,
                                nprocs=2, mesh=[2, 1], resume_step=None)
    health.append_elastic_event(tmp_path, "elastic.shrink", old_nprocs=2,
                                new_nprocs=1, old_mesh=[2, 1],
                                new_mesh=[1, 1], dead_ranks=[1],
                                reason="drill", resume_step=8)
    assert regress.check_schema(
        [str(mpath), str(tmp_path / health.ELASTIC_FILE)]
    ) == []
    # Corrupt the manifest metadata: the gate must catch it.
    man = json.loads(mpath.read_text())
    man["meta"]["mesh"]["dims"] = [0]
    mpath.write_text(json.dumps(man))
    problems = regress.check_schema([str(mpath)])
    assert problems and "dims" in problems[0]
    # A shrink record missing its rank counts must be caught too.
    bad = tmp_path / "bad-elastic.jsonl"
    bad.write_text(json.dumps({
        "schema": health.ELASTIC_SCHEMA, "v": 1, "kind": "event",
        "name": "elastic.shrink", "t": 1.0,
    }) + "\n")
    problems = regress.check_schema([str(bad)])
    assert any("old_nprocs" in p for p in problems)


def _write_heartbeat(directory, rank, step, **counters):
    from rocm_mpi_tpu.telemetry.flight import (
        HEARTBEAT_SCHEMA,
        HEARTBEAT_VERSION,
    )

    doc = {"schema": HEARTBEAT_SCHEMA, "v": HEARTBEAT_VERSION,
           "rank": rank, "t": 0.0, "t_mono": 0.0, "started_t": 0.0,
           "counters": {"step": step, **counters}, "last_phase": "step",
           "last_phase_name": "step_window", "last_phase_t": 0.0,
           "ring": []}
    (pathlib.Path(directory) / f"heartbeat-rank{rank}.json").write_text(
        json.dumps(doc)
    )


def test_monitor_shows_mesh_and_shrunk_badge(tmp_path, capsys):
    from rocm_mpi_tpu.telemetry.__main__ import main as telemetry_main

    _write_heartbeat(tmp_path, 0, 12)
    health.append_elastic_event(tmp_path, "elastic.launch", attempt=0,
                                nprocs=2, mesh=[2, 1], resume_step=None)
    health.append_elastic_event(tmp_path, "elastic.shrink", old_nprocs=2,
                                new_nprocs=1, old_mesh=[2, 1],
                                new_mesh=[1, 1], dead_ranks=[1],
                                reason="drill", resume_step=8)
    health.append_elastic_event(tmp_path, "elastic.launch", attempt=1,
                                nprocs=1, mesh=[1, 1], resume_step=8)
    rc = telemetry_main(["monitor", str(tmp_path), "--iterations", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "mesh (1, 1)" in out
    assert "SHRUNK from (2, 1)" in out


def test_monitor_without_elastic_sidecar_has_no_badge(tmp_path, capsys):
    from rocm_mpi_tpu.telemetry.__main__ import main as telemetry_main

    _write_heartbeat(tmp_path, 0, 12)
    rc = telemetry_main(["monitor", str(tmp_path), "--iterations", "1"])
    out = capsys.readouterr().out
    assert rc == 0 and "SHRUNK" not in out and "mesh" not in out
    assert "GROWN" not in out and "STORAGE" not in out


def test_elastic_status_tracks_grows():
    events = [
        {"name": "elastic.launch", "nprocs": 2, "mesh": [2, 1]},
        {"name": "elastic.shrink", "old_nprocs": 2, "new_nprocs": 1,
         "old_mesh": [2, 1], "new_mesh": [1, 1]},
        {"name": "elastic.launch", "nprocs": 1, "mesh": [1, 1]},
        {"name": "elastic.grow", "old_nprocs": 1, "new_nprocs": 2,
         "old_mesh": [1, 1], "new_mesh": [2, 1]},
        {"name": "elastic.launch", "nprocs": 2, "mesh": [2, 1]},
    ]
    st = health.elastic_status(events)
    assert st["nprocs"] == 2 and st["mesh"] == [2, 1]
    assert st["shrunk"] and st["grown"]
    assert st["grows"] == 1 and st["grow_mesh"] == [2, 1]
    line = health.format_elastic_status(st)
    assert "SHRUNK from (2, 1)" in line
    assert "GROWN to (2, 1), 1 grow(s)" in line


def test_monitor_shows_grown_badge_and_degraded_storage(tmp_path, capsys):
    from rocm_mpi_tpu.telemetry.__main__ import main as telemetry_main

    _write_heartbeat(tmp_path, 0, 12, ckpt_degraded=1, ckpt_skipped=3)
    _write_heartbeat(tmp_path, 1, 12)
    health.append_elastic_event(tmp_path, "elastic.launch", attempt=0,
                                nprocs=1, mesh=[1, 1], resume_step=None)
    health.append_elastic_event(tmp_path, "elastic.grow", old_nprocs=1,
                                new_nprocs=2, old_mesh=[1, 1],
                                new_mesh=[2, 1], resume_step=8,
                                reason="device-budget")
    health.append_elastic_event(tmp_path, "elastic.launch", attempt=1,
                                nprocs=2, mesh=[2, 1], resume_step=8)
    rc = telemetry_main(["monitor", str(tmp_path), "--iterations", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "mesh (2, 1)" in out
    assert "GROWN to (2, 1), 1 grow(s)" in out
    assert "STORAGE DEGRADED rank(s) 0 — 3 skipped save(s)" in out
    # Recovery clears the badge but keeps the loss window visible.
    _write_heartbeat(tmp_path, 0, 16, ckpt_degraded=1, ckpt_skipped=3,
                     ckpt_recovered=1)
    rc = telemetry_main(["monitor", str(tmp_path), "--iterations", "1"])
    out = capsys.readouterr().out
    assert rc == 0 and "STORAGE DEGRADED" not in out
    assert "storage recovered (3 skipped save(s))" in out


def test_storage_status_table():
    assert health.storage_status({}) is None
    clean = {0: {"counters": {"step": 8}}}
    assert health.storage_status(clean) is None
    live = {0: {"counters": {"ckpt_degraded": 2, "ckpt_recovered": 1,
                             "ckpt_skipped": 4}},
            1: {"counters": {"ckpt_degraded": 1, "ckpt_recovered": 1,
                             "ckpt_skipped": 2}}}
    st = health.storage_status(live)
    assert st["degraded"] and st["degraded_ranks"] == [0]
    assert st["skipped"] == 6
    assert "STORAGE DEGRADED rank(s) 0" in health.format_storage_status(st)


# ---------------------------------------------------------------------------
# The acceptance drills: gloo-real shrink on kill / die / stall
# ---------------------------------------------------------------------------

DRILL = dict(nx=16, ny=16, nt=16, every=4)


def _drill_argv(ck):
    return [
        str(ROOT / "tests" / "elastic_worker.py"),
        "--nx", str(DRILL["nx"]), "--ny", str(DRILL["ny"]),
        "--nt", str(DRILL["nt"]), "--every", str(DRILL["every"]),
        # keep every step: the bitwise reference re-restores the exact
        # step the shrink resumed from AFTER the run finished, which
        # the default keep=3 would have pruned in the stall drill.
        "--keep", "8",
        "--dir", str(ck),
    ]


def _reference_continuation(ck, start):
    """The uninterrupted 1-rank twin: restore the SAME checkpoint at
    `start` (template-less, 1-device plan), advance to nt on a (1,1)
    mesh — what the shrunken run's final state must equal bitwise."""
    devices = jax.devices()[:1]
    state = ckpt.restore_state(ck, start, like=None, devices=devices)
    cfg = DiffusionConfig(
        global_shape=(DRILL["nx"], DRILL["ny"]), lengths=(10.0, 10.0),
        nt=DRILL["nt"], warmup=0, dtype="f64", dims=(1, 1),
    )
    grid = pmesh.init_global_grid(
        DRILL["nx"], DRILL["ny"], dims=(1, 1), devices=devices
    )
    model = HeatDiffusion(cfg, grid=grid)
    _, Cp = model.init_state()
    advance = model.advance_fn("perf")
    return advance(state[0], Cp, DRILL["nt"] - start)


@pytest.mark.parametrize(
    "kind,spec,resume",
    [
        # kill/die strike AFTER the step-8 save completed: resume = 8.
        ("kill", "kill@step=8,rank=1", 8),
        ("die", "die@step=8,rank=1", 8),
        # The stall wedges rank 1 at the opt-in PRE-save site, so its
        # peer bumps past it (the watchdog's stalled-vs-median
        # signature) while the step-8 save itself is torn: resume = 4.
        ("stall", "stall@step=8,rank=1,at=segment-pre", 4),
    ],
)
def test_elastic_drill_shrinks_and_resumes_bitwise(tmp_path, kind, spec,
                                                   resume):
    """THE acceptance drill: 2-rank gloo run, rank 1 killed / vanished /
    stalled mid-run → the supervisor shrinks to 1 rank → resumes from
    the latest valid step → the final checkpoint is bitwise-equal to an
    uninterrupted 1-rank continuation of the same global state."""
    ck = tmp_path / "ck"
    hdir = tmp_path / "health"
    report = run_elastic(
        _drill_argv(ck), 2,
        checkpoint_dir=ck,
        global_shape=(DRILL["nx"], DRILL["ny"]),
        health_dir=hdir,
        inject_fault=spec,
        timeout=100,
        init_timeout_s=60,
        heartbeat_s=2.0,
        peer_grace_s=3.5,
        stall_grace_s=5.0,
        postmortem_grace_s=1.2,
        vanish_grace_s=5.0,
    )
    assert report.shrinks == 1, report.launches
    assert report.final_nprocs == 1
    first, second = report.launches
    assert first["nprocs"] == 2 and not first["ok"]
    assert first["dead_ranks"] == [1], first
    assert second["nprocs"] == 1 and second["ok"]
    if kind == "stall":
        assert first["reason"] == "watchdog-stall"
        assert report.launches[0]["mesh"] == [2, 1]
    if kind == "die":
        assert "vanished" in first["reason"]
    # The shrink resumed from the last step durably saved by BOTH ranks.
    shrink = next(e for e in report.events
                  if e["name"] == "elastic.shrink")
    assert shrink["resume_step"] == resume
    assert shrink["old_mesh"] == [2, 1] and shrink["new_mesh"] == [1, 1]
    # Final state: the run checkpointed through nt on the shrunken mesh.
    assert ckpt.latest_valid_step(ck) == DRILL["nt"]
    final = ckpt.restore_state(ck, DRILL["nt"], like=None,
                               devices=jax.devices()[:1])
    ref = _reference_continuation(ck, resume)
    np.testing.assert_array_equal(np.asarray(final[0]), np.asarray(ref))
    if kind == "stall":
        # The monitor reads the supervisor's record: mesh + SHRUNK
        # badge (subprocess once per drill family — the in-process
        # badge rendering is pinned separately above).
        proc = subprocess.run(
            [sys.executable, "-m", "rocm_mpi_tpu.telemetry", "monitor",
             str(hdir), "--iterations", "1"],
            capture_output=True, text=True, timeout=60, cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        assert "SHRUNK from (2, 1)" in proc.stdout, proc.stdout


def test_elastic_drill_clean_run_never_shrinks(tmp_path):
    """The control: same harness, no fault — one launch, no shrink, no
    SHRUNK badge, and the legacy same-mesh contract intact (the final
    checkpoint equals a straight 2-rank reference restored in-process).
    The device budget and the rejoin probe are ARMED (ISSUE 9): a clean
    run whose budget matches its mesh must never change topology or get
    preempted by its own supervisor."""
    ck = tmp_path / "ck"
    hdir = tmp_path / "health"
    report = run_elastic(
        _drill_argv(ck), 2,
        checkpoint_dir=ck,
        global_shape=(DRILL["nx"], DRILL["ny"]),
        health_dir=hdir,
        device_budget=2,
        timeout=100,
        init_timeout_s=60,
        heartbeat_s=2.0,
        peer_grace_s=3.5,
        vanish_grace_s=6.0,
    )
    assert report.shrinks == 0 and report.final_nprocs == 2
    assert report.grows == 0 and report.resumes == 0
    assert [e["name"] for e in report.events] == ["elastic.launch",
                                                  "elastic.complete"]
    for pid, (p, (out, err)) in enumerate(report.results):
        assert p.returncode == 0, (pid, err[-800:])
    assert ckpt.latest_valid_step(ck) == DRILL["nt"]
    # No watchdog wreckage on a clean elastic run.
    assert not (hdir / "postmortem").exists()
    st = health.elastic_status(
        health.load_elastic_events(hdir)[0]
    )
    assert st is not None and st["shrunk"] is False
    # Legacy bitwise contract: the 2-rank checkpoint restores in-process
    # (different process count, same mesh shape) to the straight result.
    _, adv, state = _model(dims=(2, 1), shape=(DRILL["nx"], DRILL["ny"]))
    ref = adv((jnp.copy(state[0]),), DRILL["nt"])
    final = ckpt.restore_state(ck, DRILL["nt"], like=None,
                               devices=jax.devices()[:2])
    np.testing.assert_array_equal(np.asarray(final[0]),
                                  np.asarray(ref[0]))


# ---------------------------------------------------------------------------
# The growth acceptance drill: shrink on a kill, grow back at a boundary
# ---------------------------------------------------------------------------

GROW_NT = 24


def _grow_argv(ck):
    return [
        str(ROOT / "tests" / "elastic_worker.py"),
        "--nx", str(DRILL["nx"]), "--ny", str(DRILL["ny"]),
        "--nt", str(GROW_NT), "--every", str(DRILL["every"]),
        "--keep", "8",
        "--dir", str(ck),
        # Stretch each segment so the rejoin probe (polling the budget
        # every 0.2 s below) reliably preempts the reduced-mesh launch
        # while it is still mid-flight.
        "--segment-delay-s", "0.4",
    ]


def _grow_reference(ck, start):
    """The uninterrupted 2-rank twin of the grown run: restore the
    drill's own checkpoint at the grow's resume step onto 2 devices and
    advance to GROW_NT on the (2, 1) mesh."""
    devices = jax.devices()[:2]
    state = ckpt.restore_state(ck, start, like=None, devices=devices)
    if start == GROW_NT:
        return state[0]
    cfg = DiffusionConfig(
        global_shape=(DRILL["nx"], DRILL["ny"]), lengths=(10.0, 10.0),
        nt=GROW_NT, warmup=0, dtype="f64", dims=(2, 1),
    )
    grid = pmesh.init_global_grid(
        DRILL["nx"], DRILL["ny"], dims=(2, 1), devices=devices
    )
    model = HeatDiffusion(cfg, grid=grid)
    _, Cp = model.init_state()
    advance = model.advance_fn("perf")
    return advance(state[0], Cp, GROW_NT - start)


def test_elastic_drill_shrinks_then_grows_back(tmp_path):
    """THE growth acceptance drill (ISSUE 9): a 2-rank gloo run loses
    rank 1 to a kill and SHRINKS to 1; the rejoin probe then sees the
    recovered device budget, preempts the reduced-mesh run at a segment
    boundary (SIGTERM → emergency save → RC_PREEMPTED), and GROWS back
    onto 2 ranks — and the final checkpoint is bitwise-equal to an
    uninterrupted 2-rank continuation from the step the grow resumed."""
    ck = tmp_path / "ck"
    hdir = tmp_path / "health"
    report = run_elastic(
        _grow_argv(ck), 2,
        checkpoint_dir=ck,
        global_shape=(DRILL["nx"], DRILL["ny"]),
        health_dir=hdir,
        inject_fault="kill@step=8,rank=1",
        device_budget=2,
        policy=ElasticPolicy(grow_poll_s=0.2),
        timeout=150,
        init_timeout_s=60,
        heartbeat_s=2.0,
        peer_grace_s=6.0,
        stall_grace_s=8.0,
        vanish_grace_s=8.0,
    )
    assert report.shrinks == 1 and report.grows == 1, report.launches
    assert report.final_nprocs == 2
    # Launch ledger: 2 ranks (killed) -> 1 rank (preempted for growth)
    # -> 2 ranks (complete).
    assert [l["nprocs"] for l in report.launches] == [2, 1, 2]
    assert report.launches[0]["status"] == "failed"
    assert report.launches[1]["status"] == "preempted"
    assert report.launches[1]["returncodes"] == [75]
    assert report.launches[2]["ok"]
    shrink = next(e for e in report.events if e["name"] == "elastic.shrink")
    grow = next(e for e in report.events if e["name"] == "elastic.grow")
    assert shrink["resume_step"] == 8
    assert shrink["new_mesh"] == [1, 1] and grow["new_mesh"] == [2, 1]
    assert grow["old_nprocs"] == 1 and grow["new_nprocs"] == 2
    # Growth only ever happens from a boundary durably PAST the shrink's
    # resume point — the hysteresis-by-construction contract.
    assert grow["resume_step"] is not None and grow["resume_step"] >= 12
    assert grow["resume_step"] % DRILL["every"] == 0
    # The run finished on the grown mesh, bitwise equal to the
    # uninterrupted 2-rank continuation of the same global state.
    assert ckpt.latest_valid_step(ck) == GROW_NT
    final = ckpt.restore_state(ck, GROW_NT, like=None,
                               devices=jax.devices()[:2])
    ref = _grow_reference(ck, grow["resume_step"])
    np.testing.assert_array_equal(np.asarray(final[0]), np.asarray(ref))
    # The monitor reads the whole topology history: both badges.
    proc = subprocess.run(
        [sys.executable, "-m", "rocm_mpi_tpu.telemetry", "monitor",
         str(hdir), "--iterations", "1"],
        capture_output=True, text=True, timeout=60, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "SHRUNK from (2, 1)" in proc.stdout, proc.stdout
    assert "GROWN to (2, 1)" in proc.stdout, proc.stdout
    # And the sidecar passes the schema gate with its new grow record.
    from rocm_mpi_tpu.telemetry import regress

    assert regress.check_schema([str(hdir / health.ELASTIC_FILE)]) == []
