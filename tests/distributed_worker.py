"""Worker for the 2-process jax.distributed test (run via subprocess).

The TPU-native analog of one `srun`-launched MPI rank
(/root/reference/README.md:18): the test driver (test_distributed.py) plays
Slurm/PMIx — it spawns N of these with the framework's launcher env contract
(RMT_COORDINATOR / RMT_NUM_PROCS / RMT_PROCESS_ID) — and each worker joins
the cluster through `maybe_initialize_distributed`, runs a sharded diffusion
step over a mesh spanning BOTH processes (ppermute crossing the process
boundary over gloo — the DCN stand-in), gathers to process 0 via the
`process_allgather` branch of gather_to_host0, and process 0 checks the
result against the host-staged oracle.

Exercises every multi-host branch VERDICT r1 flagged as dead code:
distributed.maybe_initialize_distributed, gather.gather_to_host0's
process_count>1 path, and metrics.force's non-addressable branch — plus
the deep-halo sweep (width-k exchange crossing the process boundary, the
flagship multi-chip schedule) against the same oracle, and the wave
workload's perf, hide (overlap), and deep-sweep paths (the state-pair
exchange crossing the same boundary) against the numpy leapfrog oracle.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

from rocm_mpi_tpu.utils.backend import set_cpu_device_count

jax.config.update("jax_platforms", "cpu")
set_cpu_device_count(2)  # 2 local × 2 procs = 4 global
jax.config.update("jax_enable_x64", True)


def main() -> int:
    import numpy as np

    from rocm_mpi_tpu.parallel.distributed import maybe_initialize_distributed

    assert maybe_initialize_distributed(), "launcher env not detected"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.parallel.gather import gather_to_host0
    from rocm_mpi_tpu.utils import metrics

    n_steps = 4
    cfg = DiffusionConfig(
        global_shape=(16, 16),
        lengths=(10.0, 10.0),
        nt=n_steps,
        warmup=0,
        dtype="f64",
        dims=(2, 2),  # 2×2 cartesian grid over the 4 global devices
    )
    model = HeatDiffusion(cfg, devices=jax.devices())
    T, Cp = model.init_state()
    assert not T.is_fully_addressable  # really spans both processes
    # Collective: EVERY process must participate (gathering inside the
    # process-0-only branch below would deadlock its peers).
    T0_full = gather_to_host0(T)

    # 'shard' = explicit shard_map + ppermute halo: the exchange between
    # the two process-local device pairs crosses the process boundary.
    # step_fn does not donate, so T0_dev stays valid for the deep sweep.
    T0_dev = T
    step = model.step_fn("shard")
    for _ in range(n_steps):
        T = step(T, Cp)
    metrics.force(T)  # non-addressable branch: block_until_ready, no fetch

    # Deep-halo sweep over the same mesh: the width-4 ghost exchange (one
    # message per neighbor per 4 steps — the flagship multi-chip schedule)
    # also crosses the process boundary.
    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

    sched = make_deep_sweep(
        model.grid, n_steps, cfg.lam, cfg.jax_dtype(cfg.dt), cfg.spacing
    )
    # DeepSchedule contract: the time-invariant coefficient's width-k
    # exchange runs once (prepare); the sweep carries only the field.
    T_deep = jax.jit(sched.sweep)(T0_dev, jax.jit(sched.prepare)(Cp))
    metrics.force(T_deep)
    full_deep = gather_to_host0(T_deep)

    # Second workload across the same process boundary: the wave model's
    # perf path (state-pair halo exchange) and its deep sweep.
    import jax.numpy as jnp

    from rocm_mpi_tpu.models import AcousticWave, WaveConfig
    from rocm_mpi_tpu.parallel.deep_halo import make_wave_deep_sweep

    wcfg = WaveConfig(
        global_shape=cfg.global_shape, lengths=cfg.lengths, nt=n_steps,
        warmup=0, dtype="f64", dims=cfg.dims,
    )
    wave = AcousticWave(wcfg, devices=jax.devices())
    U, Uprev, C2 = wave.init_state()
    U0_full = gather_to_host0(U)  # collective: both processes participate
    Uw, _ = wave.advance_fn("perf")(jnp.copy(U), jnp.copy(Uprev), C2, n_steps)
    metrics.force(Uw)
    # r4: the wave hide (overlap) variant's strip-decomposed exchange also
    # crosses the process boundary; must land on the same state as perf.
    Uh, _ = wave.advance_fn("hide")(jnp.copy(U), jnp.copy(Uprev), C2, n_steps)
    metrics.force(Uh)
    full_wave_hide = gather_to_host0(Uh)
    wsched = make_wave_deep_sweep(
        wave.grid, n_steps, wcfg.jax_dtype(wcfg.dt), wcfg.spacing
    )
    Uw_deep, _ = jax.jit(wsched.sweep)(
        U, Uprev, jax.jit(wsched.prepare)(C2)
    )
    metrics.force(Uw_deep)
    full_wave = gather_to_host0(Uw)
    full_wave_deep = gather_to_host0(Uw_deep)

    # Third workload across the same process boundary (r4): the SWE
    # model's pytree-state exchange — every coupled field's halo crosses
    # processes in perf, through the overlap decomposition in hide, and as
    # one width-k multi-field exchange in the deep sweep.
    from rocm_mpi_tpu.models import SWEConfig, ShallowWater
    from rocm_mpi_tpu.parallel.deep_halo import make_swe_deep_sweep

    scfg = SWEConfig(
        global_shape=cfg.global_shape, lengths=cfg.lengths, nt=n_steps,
        warmup=0, dtype="f64", dims=cfg.dims,
    )
    swe = ShallowWater(scfg, devices=jax.devices())
    sh0, sus0 = swe.init_state()
    sMus = swe.face_masks()
    sh0_full = gather_to_host0(sh0)
    sh_p, _ = swe.advance_fn("perf")(
        jnp.copy(sh0), tuple(map(jnp.copy, sus0)), sMus, n_steps
    )
    metrics.force(sh_p)
    sh_h, _ = swe.advance_fn("hide")(
        jnp.copy(sh0), tuple(map(jnp.copy, sus0)), sMus, n_steps
    )
    metrics.force(sh_h)
    ssched = make_swe_deep_sweep(
        swe.grid, n_steps, scfg.dt, scfg.spacing, scfg.H0, scfg.g
    )
    sh_d, _ = jax.jit(ssched.sweep)(
        sh0, sus0, jax.jit(ssched.prepare)(sh0)
    )
    metrics.force(sh_d)
    full_swe = gather_to_host0(sh_p)
    full_swe_hide = gather_to_host0(sh_h)
    full_swe_deep = gather_to_host0(sh_d)

    full = gather_to_host0(T)  # process_allgather branch
    if jax.process_index() == 0:
        assert full is not None and full.shape == cfg.global_shape
        # Host-staged oracle over the same decomposition. The stepper only
        # consumes grid *geometry* (dims/local_shape/spacing/global_shape),
        # so a mesh-free namespace stands in for the device-backed grid.
        from types import SimpleNamespace

        from rocm_mpi_tpu.parallel.halo import HostStagedStepper

        oracle_grid = SimpleNamespace(
            dims=cfg.dims,
            ndim=len(cfg.global_shape),
            global_shape=cfg.global_shape,
            local_shape=tuple(
                n // d for n, d in zip(cfg.global_shape, cfg.dims)
            ),
            spacing=tuple(
                l / n for l, n in zip(cfg.lengths, cfg.global_shape)
            ),
        )
        stepper = HostStagedStepper(oracle_grid, cfg.lam, cfg.dt)
        want = stepper.run(
            np.asarray(T0_full), np.full(cfg.global_shape, cfg.cp0), n_steps
        )
        np.testing.assert_allclose(full, want, rtol=1e-12, atol=1e-13)
        np.testing.assert_allclose(full_deep, want, rtol=1e-12, atol=1e-13)

        # Wave oracle: the numpy leapfrog from the gathered initial state
        # (zero initial velocity, uniform c² = c0² = 1).
        from test_wave import _numpy_leapfrog

        want_wave = _numpy_leapfrog(
            U0_full, U0_full, np.full(wcfg.global_shape, wcfg.c0**2),
            wcfg.dt, wcfg.spacing, n_steps,
        )
        np.testing.assert_allclose(
            full_wave, want_wave, rtol=1e-12, atol=1e-13
        )
        np.testing.assert_allclose(
            full_wave_hide, want_wave, rtol=1e-12, atol=1e-13
        )
        np.testing.assert_allclose(
            full_wave_deep, want_wave, rtol=1e-12, atol=1e-13
        )

        # SWE oracle: the numpy forward-backward update from the gathered
        # initial height (velocities start at zero; H0 = g = 1).
        from test_swe import _numpy_fb

        want_swe, _ = _numpy_fb(
            sh0_full,
            [np.zeros(scfg.global_shape)] * len(scfg.global_shape),
            scfg.dt, scfg.spacing, scfg.H0, scfg.g, n_steps,
        )
        np.testing.assert_allclose(full_swe, want_swe, rtol=1e-12,
                                   atol=1e-13)
        np.testing.assert_allclose(full_swe_hide, want_swe, rtol=1e-12,
                                   atol=1e-13)
        np.testing.assert_allclose(full_swe_deep, want_swe, rtol=1e-12,
                                   atol=1e-13)
        print("DISTRIBUTED_OK", flush=True)
    else:
        assert full is None
        assert full_deep is None
        assert full_wave is None and full_wave_deep is None
        assert full_wave_hide is None
        assert full_swe is None and full_swe_hide is None
        assert full_swe_deep is None
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
