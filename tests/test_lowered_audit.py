"""Lowered-program audit tests (analysis/lowered.py): the HLO parser on
canned text (fast, jax-free), the role/donation verdicts on doctored
modules the audit MUST reject, and the real three-workload drill the
lint.sh stage runs.
"""

from __future__ import annotations

from rocm_mpi_tpu.analysis import lowered

# A miniature scheduled-HLO module in the shapes the audit parses:
# collectives inside a while body (the fori/scan drivers), channel ids,
# pair lists, and a donation alias table.
CANNED = """\
HloModule jit_adv, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}, may-alias) }, entry_computation_layout={(f64[16,16]{1,0})->f64[16,16]{1,0}}, num_partitions=2

%body (p: (s64[], f64[16,16])) -> (s64[], f64[16,16]) {
  %p = (s64[], f64[16,16]{1,0}) parameter(0)
  %cp1 = f64[1,16]{1,0} collective-permute(f64[1,16]{1,0} %slice.1), channel_id=1, source_target_pairs={{0,1}}
  %cp2 = f64[1,16]{1,0} collective-permute(f64[1,16]{1,0} %slice.2), channel_id=2, source_target_pairs={{1,0}}
  ROOT %t = (s64[], f64[16,16]{1,0}) tuple(%c, %u)
}

%cond (p: (s64[], f64[16,16])) -> pred[] {
  %p = (s64[], f64[16,16]{1,0}) parameter(0)
  ROOT %lt = pred[] compare(%a, %b), direction=LT
}

ENTRY %main_spmd (param: f64[16,16], param.1: f64[16,16]) -> f64[16,16] {
  %param = f64[16,16]{1,0} parameter(0)
  %w = (s64[], f64[16,16]{1,0}) while((s64[], f64[16,16]{1,0}) %tup), condition=%cond, body=%body
  ROOT %gte = f64[16,16]{1,0} get-tuple-element(%w), index=1
}
"""


class TestHloParsing:
    def test_collective_sequence_enters_while_bodies(self):
        seq = lowered.collective_sequence(CANNED)
        assert [op.kind for op in seq] == [
            "collective-permute", "collective-permute",
        ]
        assert [op.channel for op in seq] == [1, 2]
        assert all(op.loop_depth == 1 for op in seq)
        assert all(not op.in_conditional for op in seq)
        assert seq[0].pairs == ((0, 1),)

    def test_aliased_params(self):
        assert lowered.aliased_params(CANNED) == {0, 1}
        assert lowered.aliased_params(
            "HloModule m, entry_computation_layout={()->()}"
        ) == set()

    def test_roles_identical_on_clean_module(self):
        audit = lowered.audit_roles(CANNED)
        assert audit.ok, audit.problems
        assert audit.num_partitions == 2
        assert audit.role_sequences[0] == audit.role_sequences[1]

    def test_conditional_collective_is_rejected(self):
        """A collective under a conditional branch computation is a
        lowered rank-divergent collective — the exact hazard GL08
        approximates from source; the ground-truth audit must refuse."""
        doctored = CANNED.replace(
            "condition=%cond, body=%body",
            "condition=%cond, body=%body",
        ) + """
%branch_a (p: f64[16,16]) -> f64[16,16] {
  %p = f64[16,16]{1,0} parameter(0)
  ROOT %ar = f64[16,16]{1,0} all-reduce(%p), channel_id=7, to_apply=%sum
}
"""
        doctored = doctored.replace(
            "ENTRY %main_spmd (param: f64[16,16], param.1: f64[16,16]) "
            "-> f64[16,16] {",
            "ENTRY %main_spmd (param: f64[16,16], param.1: f64[16,16]) "
            "-> f64[16,16] {\n"
            "  %c = f64[16,16]{1,0} conditional(%pred, %param, %param), "
            "true_computation=%branch_a, false_computation=%branch_a",
        )
        audit = lowered.audit_roles(doctored)
        assert not audit.ok
        assert any("conditional" in p for p in audit.problems)

    def test_missing_channel_is_rejected(self):
        doctored = CANNED.replace(", channel_id=1", "")
        audit = lowered.audit_roles(doctored)
        assert any("channel_id" in p for p in audit.problems)

    def test_degenerate_permute_pairs_are_rejected(self):
        doctored = CANNED.replace(
            "source_target_pairs={{0,1}}",
            "source_target_pairs={{0,1},{0,0}}",  # 0 sends twice
        )
        audit = lowered.audit_roles(doctored)
        assert any("partial permutation" in p for p in audit.problems)

    def test_donation_audit_names_unaliased_params(self):
        doctored = CANNED.replace(
            "input_output_alias={ {0}: (0, {}, may-alias), "
            "{1}: (1, {}, may-alias) }, ",
            "",
        )
        problems = lowered.audit_donation(doctored, ([1.0], [2.0]), (0, 1))
        assert problems and "not aliased" in problems[0]
        # and the intact module passes the same check
        assert lowered.audit_donation(CANNED, ([1.0], [2.0]), (0, 1)) == []


class TestExpectedDonatedParams:
    def test_pytree_offsets(self):
        h, u, v, m1, m2 = (object(),) * 5
        args = (h, (u, v), (m1, m2), 3)
        # donate h + (u, v): flattened params 0, 1, 2 of 6
        assert lowered.expected_donated_params(args, (0, 1)) == {0, 1, 2}
        assert lowered.expected_donated_params(args, ()) == set()


class TestDriverAudit:
    def test_all_three_workloads_clean(self):
        """The lint.sh acceptance: every workload's steady-state driver
        lowers to identical per-role collective sequences with every
        declared donation aliased."""
        rows = lowered.audit_drivers(local=16)
        assert [r.workload for r in rows] == [
            "diffusion/shard", "wave/perf", "swe/perf",
        ]
        for r in rows:
            assert r.ok, (r.workload, r.problems)
            assert r.num_partitions == 2
            assert r.n_collectives > 0
            assert r.donated_params >= 1
        # SWE donates the full coupled state (h, u, v)
        assert rows[2].donated_params == 3
        table = lowered.render_table(rows)
        assert "ok" in table and "DIVERGENT" not in table
