"""bench.py contract: ONE JSON line with the driver-required keys, rc 0 —
no matter what (the scored artifact must never be empty or malformed).

Run off-TPU these exercise the no-accelerator smoke path end-to-end
through the real parent/child subprocess shielding.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent



def _import_bench():
    """In-process bench import (shared by the unit-level tests)."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def _run_bench(env_extra, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=timeout, cwd=str(REPO),
        env=env,
    )


def _contract_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    obj = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in obj, f"missing contract key {key!r}: {obj}"
    assert obj["unit"] == "Gpts/s"
    assert isinstance(obj["value"], (int, float))
    return obj


def test_bench_contract_fast():
    """The per-commit contract check: one well-formed JSON line, rc 0,
    honestly error-labeled off-TPU — through the REAL parent/child
    subprocess machinery, with the ~30 s interpret smoke stood in by
    fault injection so the default lane pays seconds, not minutes. The
    soak lane's slow-marked siblings cover the genuine smoke run and the
    kill/harvest/fallback timing contracts."""
    proc = _run_bench(
        {"BENCH_BUDGET_S": "120", "BENCH_FAULT_SKIP_SMOKE": "1"},
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    obj = _contract_line(proc.stdout)
    assert "error" in obj and "no accelerator" in obj["error"]
    assert obj["value"] > 0


@pytest.mark.slow
def test_bench_contract_no_accelerator():
    # Generous budget: the smoke child (~30 s here) must finish within the
    # parent's derived child timeout even on a much slower machine, or the
    # parent honestly reports "did not complete" and this test would read
    # as a contract violation instead of a timing flake.
    proc = _run_bench({"BENCH_BUDGET_S": "360"}, timeout=400)
    assert proc.returncode == 0, proc.stderr[-1000:]
    obj = _contract_line(proc.stdout)
    # Off-TPU the honest fallback is the labeled interpret-mode smoke value.
    assert "error" in obj and "no accelerator" in obj["error"]
    assert obj["value"] > 0  # the smoke run really executed the kernel


@pytest.mark.slow
def test_bench_harvests_emitted_line_from_killed_child():
    """The round-3 failure shape (VERDICT r3 #1): a child that produced a
    measurement and then stalled on the transport forever. The parent must
    kill it at the deadline AND still report the flushed measurement —
    emit-as-you-go means a hang can only cost the upgrade, never the number.

    BENCH_FAULT_SKIP_SMOKE stands in for the ~30 s interpret-mode smoke
    run, so the emit happens within seconds on any machine and the budget
    (120 s = 60 s reserve + a ~50 s attempt window) provably kills the
    hanging child (a completed child exits RC_NO_TPU and takes a
    different parent path).
    """
    proc = _run_bench(
        {
            "BENCH_BUDGET_S": "120",
            "BENCH_FAULT_SKIP_SMOKE": "1",
            "BENCH_FAULT_HANG_AFTER_EMIT": "1",
        },
        timeout=190,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "killed after" in proc.stderr  # the child really was killed
    obj = _contract_line(proc.stdout)
    assert obj["value"] > 0  # the harvested pre-hang measurement, not 0.0


@pytest.mark.slow
def test_bench_harvests_real_measurement_over_smoke_fallback():
    """The best_line branch — the actual round-3 fix. Off-TPU every organic
    emit carries an 'error' field (smoke fallback), so this injects a real
    no-error measurement line before the hang: the parent must prefer the
    harvested real measurement over the smoke line when reporting."""
    proc = _run_bench(
        {
            "BENCH_BUDGET_S": "120",
            "BENCH_FAULT_SKIP_SMOKE": "1",
            "BENCH_FAULT_EMIT_REAL_VALUE": "123.4",
            "BENCH_FAULT_HANG_AFTER_EMIT": "1",
        },
        timeout=190,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    obj = _contract_line(proc.stdout)
    assert "error" not in obj  # the real line won, not the smoke fallback
    assert obj["value"] == 123.4


@pytest.mark.slow
def test_bench_survives_slow_backend_init():
    """Injected init delay (the VERDICT r3 #1 'done' criterion, scaled to
    the CPU smoke path): a child that spends a long time before its first
    measurement still lands a nonzero value within the budget."""
    proc = _run_bench(
        {"BENCH_BUDGET_S": "240", "BENCH_FAULT_INIT_DELAY_S": "20"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    obj = _contract_line(proc.stdout)
    assert obj["value"] > 0


@pytest.mark.slow
def test_bench_cpu_fallback_when_all_attempts_hang_pre_emit():
    """The round-end tunnel-down shape: backend init itself hangs, so no
    accelerator attempt ever flushes a line. The parent must spend its
    reserved budget on a forced-CPU fallback child and report its labeled
    smoke value instead of 0.0. (The init-delay fault models the
    accelerator hang, so it exempts the CPU-fallback child; skip-smoke
    keeps the fallback fast.)"""
    proc = _run_bench(
        {
            "BENCH_BUDGET_S": "120",
            "BENCH_FAULT_INIT_DELAY_S": "9999",
            "BENCH_FAULT_SKIP_SMOKE": "1",
        },
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "forced-CPU fallback" in proc.stderr
    obj = _contract_line(proc.stdout)
    assert "error" in obj  # honestly labeled, not passed off as a rate
    assert obj["value"] > 0


def test_prime_cache_no_accelerator_is_clean_noop():
    """startup.sh runs `bench.py --prime-cache` unconditionally; without
    an accelerator it must exit 0 with the explicit skip message (a crash
    here would make bootstrap misreport the chip tunnel as the culprit —
    the startup.sh rc-distinction depends on this)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--prime-cache"],
        capture_output=True, text=True, timeout=120, cwd=str(REPO), env=env,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "nothing to prime" in proc.stderr
    assert proc.stdout.strip() == ""  # no stray contract line


def test_child_kernel_form_ladder_picks_winner(monkeypatch, capsys):
    """Stage 2.5's first real execution is the driver's chip run — pin the
    ladder's CONTROL FLOW in-process so a crash there can never be
    discovered on the scored run: every candidate is timed as an EXPLICIT
    trace-time kwarg pair (never a mutated pk global — ADVICE r5 #1), the
    winner's kwargs are what the long window runs, the long-window emit
    is labeled with the winning form, and the best rate is what lands on
    stdout. Stub model; no accelerator needed."""
    bench = _import_bench()
    import rocm_mpi_tpu.ops.pallas_kernels as pk

    # Rates per (form, pad): conly+pad256 wins.
    rates = {
        ("eqc", False): 100.0,
        ("conly", False): 120.0,
        ("eqc", True): 110.0,
        ("conly", True): 150.0,
    }
    calls = []

    class _Res:
        def __init__(self, gpts):
            self.gpts = gpts
            self.wtime_it = 63504 / (gpts * 1e9)  # 252² points
            self.t_eff = gpts * 12.0

    class _Model:
        def __init__(self, nt, warmup):
            pass

        def run_vmem_resident(self, chunk=None, body_form=None,
                              pad_pow2=None, program_cache=None):
            # None defaults to the module constants, exactly as the real
            # fused_multi_step resolves them.
            form = pk.EQC_BODY_FORM if body_form is None else body_form
            pad = pk.VMEM_PAD_POW2 if pad_pow2 is None else pad_pow2
            calls.append((chunk, form, pad))
            if chunk == 16:  # the floor stage
                return _Res(50.0)
            return _Res(rates[(form, pad)])

    monkeypatch.setattr(bench, "_accelerated", lambda: True)
    monkeypatch.setattr(bench, "_apply_platform_override", lambda: None)
    monkeypatch.setattr(bench, "_setup_compilation_cache", lambda: None)
    monkeypatch.setattr(bench, "_bench_model", lambda nt, wu: _Model(nt, wu))

    rc = bench.child_main(budget_s=300.0)
    out = capsys.readouterr()
    assert rc == bench.RC_OK
    # The ladder passed every candidate explicitly and the module
    # constants were never touched (the measured hardware defaults).
    assert (pk.EQC_BODY_FORM, pk.VMEM_PAD_POW2) == ("eqc", False)
    assert {(f, p) for _, f, p in calls} == set(rates)
    # The long window (the last call) rides the winner's kwargs.
    assert calls[-1][1:] == ("conly", True)
    assert "kernel-form ladder winner: conly+pad256" in out.err
    assert "conly+pad256 x" in out.err  # long-window label carries the form
    # stdout's last emitted line is the best rate (the long window re-runs
    # the winner at the same stub rate, so 150.0 stands).
    last = json.loads(out.out.strip().splitlines()[-1])
    assert last["value"] == 150.0 and "error" not in last


def test_ladder_program_cache_pins_reuse():
    """The kernel-form ladder satellite: identical configs across rungs
    must REUSE the compiled advance, not re-trace it per call — pinned by
    the compiles.total accounting (telemetry/compiles.py). Two same-config
    runs through one program_cache pay strictly fewer backend compiles
    than the same pair without it (the delta IS the re-traced advance;
    init_state's per-instance jits recompile either way, so the pin is a
    strict inequality, not an exact count)."""
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.telemetry import compiles

    def model():
        return HeatDiffusion(DiffusionConfig(
            global_shape=(16, 16), lengths=(10.0, 10.0), nt=8, warmup=4,
            dtype="f32", dims=(1, 1),
        ))

    mode = compiles.install()
    assert mode is not None, "compile listener must install on this jax"
    kw = dict(chunk=4, body_form="eqc", pad_pow2=False)

    def total():
        return compiles.snapshot()["totals"]["backend_compiles"]

    programs: dict = {}
    model().run_vmem_resident(program_cache=programs, **kw)  # warm trace
    t0 = total()
    model().run_vmem_resident(program_cache=programs, **kw)
    cached_delta = total() - t0
    assert len(programs) == 1  # one config -> one cached advance

    t1 = total()
    model().run_vmem_resident(**kw)  # no cache: the advance re-traces
    uncached_delta = total() - t1
    assert cached_delta < uncached_delta, (
        f"cached rerun compiled {cached_delta} programs vs "
        f"{uncached_delta} uncached — the ladder's program cache is not "
        "reusing traces"
    )


def test_env_budget_malformed(monkeypatch, capsys):
    # The malformed-budget fallback is a pure function; unit-test it
    # instead of paying two full smoke-child subprocess runs.
    bench = _import_bench()
    monkeypatch.setenv("BENCH_BUDGET_S", "not-a-number")
    assert bench._env_budget() == bench.DEFAULT_BUDGET_S
    assert "ignoring malformed BENCH_BUDGET_S" in capsys.readouterr().err
    monkeypatch.setenv("BENCH_BUDGET_S", "42.5")
    assert bench._env_budget() == 42.5
    monkeypatch.delenv("BENCH_BUDGET_S")
    assert bench._env_budget() == bench.DEFAULT_BUDGET_S


# ---------------------------------------------------------------------------
# Trajectory compare (ROADMAP item 5: bench.py --compare rN rM)
# ---------------------------------------------------------------------------


def _record(path, rows):
    path.write_text(json.dumps({
        "metrics": {
            k: {"value": v, "direction": "higher"} for k, v in rows.items()
        },
    }))
    return path


def test_compare_resolves_record_specs():
    bench = _import_bench()
    root = str(REPO)
    assert bench._resolve_record("r3") == os.path.join(
        root, "BENCH_r03.json")
    assert bench._resolve_record("r12") == os.path.join(
        root, "BENCH_r12.json")
    assert bench._resolve_record("7") == os.path.join(
        root, "BENCH_r07.json")
    # explicit paths pass through untouched (archived records)
    assert bench._resolve_record("docs/x/BENCH_r01.json") == \
        "docs/x/BENCH_r01.json"
    with pytest.raises(ValueError, match="--compare operand"):
        bench._resolve_record("rX")


def test_compare_reports_deltas_and_gates_regressions(tmp_path, capsys):
    """The trajectory report: per-key delta rows against the regress
    tolerance semantics — exit 0 within tolerance, exit 1 when a rung
    moved the wrong way, and dropped/new rungs named instead of
    silently vanishing from the diff."""
    bench = _import_bench()
    base = _record(tmp_path / "BENCH_r01.json",
                   {"suite.a.gpts": 10.0, "suite.b.gpts": 5.0,
                    "suite.old.gpts": 1.0})
    cur = _record(tmp_path / "BENCH_r02.json",
                  {"suite.a.gpts": 10.5, "suite.b.gpts": 5.2,
                   "suite.new.req_s": 7.0})
    assert bench.compare_records(str(base), str(cur)) == 0
    out = capsys.readouterr().out
    assert "suite.a.gpts" in out and "+5.0%" in out
    assert "dropped (baseline-only rung)" in out  # suite.old
    assert "new (no baseline)" in out  # suite.new
    assert "2 compared, 0 regressed, 1 dropped, 1 new" in out

    # a higher-is-better rung falling past the tolerance gates exit 1
    worse = _record(tmp_path / "BENCH_r03.json",
                    {"suite.a.gpts": 10.0, "suite.b.gpts": 2.0})
    assert bench.compare_records(str(base), str(worse)) == 1
    assert "REGRESSED" in capsys.readouterr().out
    # ... unless the caller widens the tolerance explicitly
    assert bench.compare_records(str(base), str(worse),
                                 tolerance=0.9) == 0
    capsys.readouterr()


def test_compare_rejects_unreadable_and_disjoint_inputs(tmp_path, capsys):
    bench = _import_bench()
    base = _record(tmp_path / "BENCH_r01.json", {"suite.a.gpts": 1.0})
    assert bench.compare_records(
        str(base), str(tmp_path / "missing.json")) == 2
    assert "cannot read" in capsys.readouterr().err
    other = _record(tmp_path / "BENCH_r04.json", {"suite.z.gpts": 1.0})
    assert bench.compare_records(str(base), str(other)) == 2
    assert "no shared metric keys" in capsys.readouterr().err


def test_compare_cli_end_to_end(tmp_path):
    """The CLI spelling the ROADMAP names: `bench.py --compare rN rM`
    (explicit paths here — the repo root's numbered records are the
    chip window's to bank) runs backend-free and fast."""
    base = _record(tmp_path / "BENCH_r01.json", {"suite.a.gpts": 4.0})
    cur = _record(tmp_path / "BENCH_r02.json", {"suite.a.gpts": 1.0})
    ok = subprocess.run(
        [sys.executable, str(REPO / "bench.py"),
         "--compare", str(cur), str(cur)],
        capture_output=True, text=True, timeout=60, cwd=str(REPO),
    )
    assert ok.returncode == 0, ok.stderr
    assert "0 regressed" in ok.stdout
    bad = subprocess.run(
        [sys.executable, str(REPO / "bench.py"),
         "--compare", str(base), str(cur)],
        capture_output=True, text=True, timeout=60, cwd=str(REPO),
    )
    assert bad.returncode == 1, (bad.stdout, bad.stderr)
    assert "REGRESSED" in bad.stdout
    usage = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--compare", str(base)],
        capture_output=True, text=True, timeout=60, cwd=str(REPO),
    )
    assert usage.returncode == 2
    assert "usage" in usage.stderr
