"""bench.py contract: ONE JSON line with the driver-required keys, rc 0 —
no matter what (the scored artifact must never be empty or malformed).

Run off-TPU these exercise the no-accelerator smoke path end-to-end
through the real parent/child subprocess shielding.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_bench(env_extra, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=timeout, cwd=str(REPO),
        env=env,
    )


def _contract_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    obj = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in obj, f"missing contract key {key!r}: {obj}"
    assert obj["unit"] == "Gpts/s"
    assert isinstance(obj["value"], (int, float))
    return obj


def test_bench_contract_no_accelerator():
    # Generous budget: the smoke child (~30 s here) must finish within the
    # parent's derived child timeout even on a much slower machine, or the
    # parent honestly reports "did not complete" and this test would read
    # as a contract violation instead of a timing flake.
    proc = _run_bench({"BENCH_BUDGET_S": "360"}, timeout=400)
    assert proc.returncode == 0, proc.stderr[-1000:]
    obj = _contract_line(proc.stdout)
    # Off-TPU the honest fallback is the labeled interpret-mode smoke value.
    assert "error" in obj and "no accelerator" in obj["error"]
    assert obj["value"] > 0  # the smoke run really executed the kernel


def test_env_budget_malformed(monkeypatch, capsys):
    # The malformed-budget fallback is a pure function; unit-test it
    # instead of paying two full smoke-child subprocess runs.
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("BENCH_BUDGET_S", "not-a-number")
    assert bench._env_budget() == bench.DEFAULT_BUDGET_S
    assert "ignoring malformed BENCH_BUDGET_S" in capsys.readouterr().err
    monkeypatch.setenv("BENCH_BUDGET_S", "42.5")
    assert bench._env_budget() == 42.5
    monkeypatch.delenv("BENCH_BUDGET_S")
    assert bench._env_budget() == bench.DEFAULT_BUDGET_S
