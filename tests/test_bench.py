"""bench.py contract: ONE JSON line with the driver-required keys, rc 0 —
no matter what (the scored artifact must never be empty or malformed).

Run off-TPU these exercise the no-accelerator smoke path end-to-end
through the real parent/child subprocess shielding.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_bench(env_extra, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=timeout, cwd=str(REPO),
        env=env,
    )


def _contract_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    obj = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in obj, f"missing contract key {key!r}: {obj}"
    assert obj["unit"] == "Gpts/s"
    assert isinstance(obj["value"], (int, float))
    return obj


def test_bench_contract_no_accelerator():
    proc = _run_bench({"BENCH_BUDGET_S": "120"})
    assert proc.returncode == 0, proc.stderr[-1000:]
    obj = _contract_line(proc.stdout)
    # Off-TPU the honest fallback is the labeled interpret-mode smoke value.
    assert "error" in obj and "no accelerator" in obj["error"]
    assert obj["value"] > 0  # the smoke run really executed the kernel


def test_bench_contract_malformed_budget():
    # The malformed value falls back to the 300 s default budget, so the
    # subprocess timeout must exceed it (two smoke-child attempts can
    # legitimately run before the parent gives up on a cold machine).
    proc = _run_bench({"BENCH_BUDGET_S": "not-a-number"}, timeout=420)
    assert proc.returncode == 0, proc.stderr[-1000:]
    _contract_line(proc.stdout)
    assert "ignoring malformed BENCH_BUDGET_S" in proc.stderr
