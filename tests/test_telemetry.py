"""Telemetry subsystem tests (docs/TELEMETRY.md): span semantics and
disabled-mode cost, the versioned JSONL schema round-trip, multi-rank
aggregation with a straggler, Chrome-trace validity, the regression CLI's
exit-code contract, and the end-to-end 2-rank weak-scaling acceptance run
(per-rank streams -> merged summary with halo/interior/checkpoint
attribution -> openable trace)."""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

import pytest

from rocm_mpi_tpu.telemetry import aggregate, events, regress, trace
from rocm_mpi_tpu.telemetry.__main__ import main as cli_main
from rocm_mpi_tpu.utils import metrics

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_telemetry(monkeypatch):
    """Every test starts disabled, sink-less, buffer-empty; monkeypatch
    restores whatever the ambient process config was."""
    monkeypatch.setattr(events, "_ENABLED", False)
    monkeypatch.setattr(events, "_DIR", None)
    monkeypatch.setattr(events, "_RANK", None)
    events.clear()
    yield
    events.clear()


# ---------------------------------------------------------------------------
# Spans: nesting, sync, disabled-mode overhead
# ---------------------------------------------------------------------------


def test_span_nesting_and_jsonl_roundtrip(tmp_path):
    from rocm_mpi_tpu.telemetry import span

    events.configure(directory=tmp_path, rank=3)
    with span("outer", phase="step", steps=4) as outer:
        with span("inner.detail") as inner:
            inner.set(bytes=128)
        outer.set(note="done")
    events.counter("halo.bytes", 4096)
    events.gauge("run.gpts", 1.25)

    path = tmp_path / "telemetry-rank3.jsonl"
    assert path.is_file(), "one writer per rank, named by rank"
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert all(r["v"] == events.SCHEMA_VERSION for r in recs)
    assert all(r["rank"] == 3 for r in recs)
    by_name = {r["name"]: r for r in recs}
    assert by_name["inner.detail"]["depth"] == 1
    assert by_name["outer"]["depth"] == 0
    assert by_name["inner.detail"]["attrs"]["bytes"] == 128
    assert by_name["outer"]["attrs"]["note"] == "done"
    # inner closed first and fits inside outer
    assert by_name["inner.detail"]["dur_s"] <= by_name["outer"]["dur_s"]
    assert by_name["halo.bytes"]["kind"] == "counter"
    assert by_name["run.gpts"]["value"] == 1.25
    # the buffer view matches the file view
    assert len(events.records()) == len(recs)


def test_disabled_spans_are_noop_and_cheap(tmp_path):
    from rocm_mpi_tpu.telemetry import span

    assert not events.enabled()
    t0 = time.monotonic()
    for _ in range(20_000):
        with span("hot.loop", steps=1) as sp:
            sp.sync(object())  # must NOT force/fetch when disabled
    elapsed = time.monotonic() - t0
    assert events.records() == [], "disabled spans must record nothing"
    assert not list(tmp_path.iterdir())
    # 20k disabled spans in well under a second — the near-zero-overhead
    # contract (generous cap for slow CI boxes).
    assert elapsed < 2.0, f"20k disabled spans took {elapsed:.2f}s"


def test_span_records_error_flag(tmp_path):
    from rocm_mpi_tpu.telemetry import span

    events.configure(directory=tmp_path, rank=0)
    with pytest.raises(ValueError):
        with span("failing"):
            raise ValueError("boom")
    (rec,) = events.records(kind="span")
    assert rec["error"] == "ValueError"


# ---------------------------------------------------------------------------
# Satellite: versioned + monotonic record_event, Timer context manager
# ---------------------------------------------------------------------------


def test_record_event_is_versioned_and_monotonic():
    a = metrics.record_event("attempt-failed", attempt=0, error="x")
    b = metrics.record_event("backoff", attempt=0, wait_s=0.5)
    assert a.v == events.SCHEMA_VERSION == 2
    assert isinstance(a.t_mono, float)
    assert b.t_mono > a.t_mono, "monotonic stamps order events in-rank"
    assert [e.kind for e in metrics.events()] == ["attempt-failed",
                                                 "backoff"]
    assert metrics.events("backoff")[0].wait_s == 0.5
    doc = json.loads(b.to_json())
    assert doc["v"] == 2 and "t_mono" in doc
    # the unified public reset (telemetry.clear_events); the deprecated
    # metrics.clear_events alias is pinned in tests/test_health.py
    events.clear_events()
    assert metrics.events() == []


def test_events_flow_into_rank_stream_when_enabled(tmp_path):
    events.configure(directory=tmp_path, rank=1)
    metrics.record_event("restored", step=16)
    lines = [
        json.loads(ln) for ln in
        (tmp_path / "telemetry-rank1.jsonl").read_text().splitlines()
    ]
    # configure() leads the stream with the wall<->monotonic clock
    # anchor (the PR-20 cross-replica alignment contract)...
    assert lines[0]["kind"] == "anchor"
    assert lines[0]["name"] == "clock.anchor"
    # ...and the event lands right behind it.
    line = lines[1]
    assert line["kind"] == "event" and line["name"] == "restored"
    assert line["step"] == 16 and line["v"] == 2


def test_timer_context_manager_and_label(tmp_path):
    with metrics.Timer() as t:
        time.sleep(0.01)
    assert t.elapsed and t.elapsed >= 0.008
    # explicit toc inside the block wins over the exit stamp
    with metrics.Timer() as t2:
        time.sleep(0.01)
        t2.toc()
        marked = t2.elapsed
        time.sleep(0.01)
    assert t2.elapsed == marked
    with pytest.raises(RuntimeError):
        metrics.Timer().toc()
    # a labeled timer feeds the telemetry stream
    events.configure(directory=tmp_path, rank=0)
    with metrics.Timer(label="step_window", phase="step", steps=5):
        time.sleep(0.005)
    (rec,) = events.records(kind="span")
    assert rec["name"] == "step_window"
    assert rec["attrs"]["steps"] == 5
    assert rec["dur_s"] >= 0.004


# ---------------------------------------------------------------------------
# Aggregation: merge, phases, percentiles, stragglers
# ---------------------------------------------------------------------------


def _span_rec(name, dur_s, rank, t=1000.0, **attrs):
    rec = {"v": 2, "kind": "span", "name": name, "t": t,
           "t_mono": t, "rank": rank, "dur_s": dur_s, "depth": 0, "tid": 1}
    if attrs:
        rec["attrs"] = attrs
    return rec


def _two_rank_streams():
    fast = [
        _span_rec("step_window", 0.010, 0, t=1000.0 + i, steps=10,
                  phase="step")
        for i in range(4)
    ]
    slow = [
        _span_rec("step_window", 0.040, 1, t=1000.0 + i, steps=10,
                  phase="step")
        for i in range(4)
    ]
    halo = [
        _span_rec("halo.probe", 0.002, r, t=1010.0, phase="halo",
                  bytes=1 << 20)
        for r in (0, 1)
    ]
    interior = [
        _span_rec("interior.probe", 0.004, r, t=1011.0, phase="interior")
        for r in (0, 1)
    ]
    ckpt = [_span_rec("checkpoint.save", 0.05, 0, t=1012.0, step=40)]
    ev = [{"v": 2, "kind": "event", "name": "backoff", "t": 1001.0,
           "t_mono": 1.0, "rank": 1, "attempt": 0, "wait_s": 0.5}]
    gauge = [{"v": 2, "kind": "gauge", "name": "run.gpts", "t": 1013.0,
              "t_mono": 2.0, "rank": 0, "value": 2.5}]
    return {0: fast + [halo[0], interior[0]] + ckpt + gauge,
            1: slow + [halo[1], interior[1]] + ev}


def test_multi_rank_aggregation_detects_straggler():
    streams = _two_rank_streams()
    s = aggregate.summarize(streams)
    assert s["ranks"] == [0, 1]
    for phase in aggregate.CANONICAL_PHASES:
        assert phase in s["phases"], "canonical phases always present"
    assert s["phases"]["halo"]["bytes"] == 2 << 20
    assert s["phases"]["halo"]["bytes_per_s"] > 0
    assert s["phases"]["checkpoint"]["wall_s"] == pytest.approx(0.05)
    assert s["steps"]["count"] == 80 and s["steps"]["windows"] == 8
    # rank 1's windows are 4x slower: p90 reflects the slow rank and the
    # straggler detector names it in the step phase
    assert s["steps"]["per_step_us"]["p90"] >= 4000 * 0.9
    assert any(
        st["rank"] == 1 and st["phase"] == "step" and st["ratio"] > 1.5
        for st in s["stragglers"]
    ), s["stragglers"]
    assert s["events"] == {"backoff": 1}
    assert s["gauges"]["run.gpts"] == 2.5


def test_gauges_reduce_to_cross_rank_median():
    """Per-rank copies of a rung's gauge must merge to the median, not
    whichever rank sorts last — one straggler must not become the gate's
    whole view of the rung."""
    def g(rank, value):
        return {"v": 2, "kind": "gauge", "name": "run.gpts", "t": 1.0,
                "t_mono": 1.0, "rank": rank, "value": value,
                "attrs": {"devices": 4}}

    s = aggregate.summarize({0: [g(0, 1.0)], 1: [g(1, 1.1)],
                             2: [g(2, 1.2)], 3: [g(3, 9.9)]})
    assert s["gauges"]["run.gpts@4dev"] == pytest.approx(1.15)
    assert len(s["gauge_series"]) == 4


def test_timer_cm_records_failed_interval(tmp_path):
    events.configure(directory=tmp_path, rank=0)
    with pytest.raises(RuntimeError):
        with metrics.Timer(label="run.checkpointed", steps=100) as t:
            time.sleep(0.005)
            raise RuntimeError("backend gone")
    assert t.elapsed and t.elapsed >= 0.004
    (rec,) = events.records(kind="span")
    assert rec["name"] == "run.checkpointed"
    assert rec["error"] == "RuntimeError"
    assert rec["dur_s"] >= 0.004


def test_windowed_run_rejects_degenerate_windows(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "weak_scaling_for_test", REPO / "apps" / "weak_scaling.py"
    )
    ws = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ws)

    class _FakeModel:
        def advance_fn(self, variant):  # pragma: no cover - never reached
            raise AssertionError("validation must fire first")

    events.configure(directory=tmp_path, rank=0)
    with pytest.raises(ValueError, match="warmup"):
        ws.telemetry_windowed_run(_FakeModel(), "hide", nt=200,
                                  warmup=200, windows=4)


def test_load_rank_streams_skips_torn_lines(tmp_path):
    good = json.dumps(_span_rec("step_window", 0.01, 0, steps=5))
    (tmp_path / "telemetry-rank0.jsonl").write_text(
        good + "\n" + '{"kind": "span", "name": "torn'  # killed mid-write
    )
    streams, skipped = aggregate.load_rank_streams(tmp_path)
    assert len(streams[0]) == 1 and skipped == 1


# ---------------------------------------------------------------------------
# Chrome trace
# ---------------------------------------------------------------------------


def test_chrome_trace_is_valid_and_complete(tmp_path):
    streams = _two_rank_streams()
    doc = trace.write_chrome_trace(streams, tmp_path / "trace.json")
    parsed = json.loads((tmp_path / "trace.json").read_text())
    assert parsed == doc
    assert isinstance(parsed["traceEvents"], list) and parsed["traceEvents"]
    for ev in parsed["traceEvents"]:
        for key in trace.TRACE_REQUIRED_KEYS:
            assert key in ev, (key, ev)
    pids = {ev["pid"] for ev in parsed["traceEvents"]}
    assert pids == {0, 1}, "one track per rank"
    xs = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
    assert xs and all(e["dur"] >= 0 and e["ts"] >= 0 for e in xs)
    assert any(e["ph"] == "i" for e in parsed["traceEvents"]), \
        "resilience events appear as instants"


# ---------------------------------------------------------------------------
# Regression CLI: exit codes on pass / fail / missing baseline
# ---------------------------------------------------------------------------


def _write_summary(path, scale=1.0):
    streams = {
        0: [_span_rec("step_window", 0.010 * scale, 0, t=1000.0 + i,
                      steps=10, phase="step") for i in range(4)]
        + [{"v": 2, "kind": "gauge", "name": "run.gpts", "t": 1013.0,
            "t_mono": 2.0, "rank": 0, "value": 2.5 / scale}],
    }
    path.write_text(json.dumps(aggregate.summarize(streams)))
    return path


def test_regress_cli_exit_codes(tmp_path, capsys):
    base = _write_summary(tmp_path / "base.json")
    same = _write_summary(tmp_path / "same.json")
    slow = _write_summary(tmp_path / "slow.json", scale=2.0)

    assert cli_main(["regress", str(base), "--baseline", str(base)]) == 0
    assert cli_main(["regress", str(same), "--baseline", str(base)]) == 0
    assert cli_main(["regress", str(slow), "--baseline", str(base)]) == 1
    out = capsys.readouterr()
    assert "REGRESSED" in out.out and "REGRESSION" in out.err
    # a 2x slowdown passes a sufficiently lax gate
    assert cli_main(["regress", str(slow), "--baseline", str(base),
                     "--tolerance", "1.5"]) == 0
    # missing baseline: exit 2, never a silent pass
    assert cli_main(["regress", str(same), "--baseline",
                     str(tmp_path / "nope.json")]) == 2
    assert cli_main(["regress", str(tmp_path / "nope.json"),
                     "--baseline", str(base)]) == 2
    assert cli_main(["regress"]) == 2


def test_regress_direction_higher_is_better(tmp_path):
    """A gpts gauge going UP must not read as a regression, and going
    down must."""
    base = json.loads(_write_summary(tmp_path / "b.json").read_text())
    better = json.loads(json.dumps(base))
    better["gauges"]["run.gpts"] = base["gauges"]["run.gpts"] * 3
    better["steps"] = {"count": 0, "windows": 0, "wall_s": 0,
                       "per_step_us": {}}
    better["phases"] = {}
    deltas = regress.compare(better, base)
    assert not regress.regressions(deltas)
    worse = json.loads(json.dumps(better))
    worse["gauges"]["run.gpts"] = base["gauges"]["run.gpts"] / 3
    assert regress.regressions(regress.compare(worse, base))


def test_check_schema_on_committed_baselines(tmp_path, capsys):
    committed = [str(REPO / "BASELINE.json"),
                 str(REPO / "MULTICHIP_r01.json")]
    jsonl = sorted(
        str(p) for p in (REPO / "docs").glob("weak_scaling_*_r3.jsonl")
    )[:1]
    assert cli_main(["regress", "--check-schema", *committed, *jsonl]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert cli_main(["regress", "--check-schema", str(bad)]) == 1
    assert cli_main(["regress", "--check-schema",
                     str(tmp_path / "missing.json")]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# summarize CLI
# ---------------------------------------------------------------------------


def test_summarize_cli_writes_summary_and_trace(tmp_path, capsys):
    from rocm_mpi_tpu.telemetry import span

    events.configure(directory=tmp_path, rank=0)
    with span("step_window", phase="step", steps=10):
        time.sleep(0.002)
    with span("halo.probe", phase="halo", bytes=2048):
        time.sleep(0.001)
    assert cli_main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "phase" in out and "halo" in out
    summary = json.loads((tmp_path / "telemetry-summary.json").read_text())
    assert summary["schema"] == aggregate.SUMMARY_SCHEMA
    assert summary["phases"]["halo"]["bytes"] == 2048
    parsed = json.loads((tmp_path / "telemetry-trace.json").read_text())
    assert parsed["traceEvents"]
    # an empty dir is exit 2 (nothing to summarize is not success)
    assert cli_main(["summarize", str(tmp_path / "empty")]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Acceptance: 2-rank weak_scaling run -> merged summary + trace
# ---------------------------------------------------------------------------


def test_two_rank_weak_scaling_telemetry_end_to_end(tmp_path, capsys):
    """The ISSUE-3 acceptance drill: a real 2-process gloo weak-scaling
    run with --telemetry via the launcher; the merged summary must
    attribute halo / interior / checkpoint wall time and export a valid
    Chrome trace."""
    from rocm_mpi_tpu.parallel.launcher import spawn_ranks

    tel_dir = tmp_path / "telemetry"
    results = spawn_ranks(
        [
            REPO / "apps" / "weak_scaling.py",
            "--cpu-devices", "1", "--local", "16", "--nt", "24",
            "--warmup", "4", "--counts", "2", "--dtype", "f32",
            "--telemetry-windows", "4",
        ],
        nprocs=2,
        timeout=300,
        telemetry_dir=tel_dir,
    )
    for i, (proc, (out, err)) in enumerate(results):
        assert proc.returncode == 0, f"rank {i} rc={proc.returncode}:" \
                                     f"\n{out}\n{err}"
    assert (tel_dir / "telemetry-rank0.jsonl").is_file()
    assert (tel_dir / "telemetry-rank1.jsonl").is_file()
    # the launcher merged at exit...
    merged = json.loads((tel_dir / "telemetry-summary.json").read_text())
    assert merged["ranks"] == [0, 1]
    assert any("telemetry: merged" in n for n in results.report.events)
    # ...and the CLI reproduces it with per-phase attribution
    assert cli_main(["summarize", str(tel_dir)]) == 0
    capsys.readouterr()
    summary = json.loads((tel_dir / "telemetry-summary.json").read_text())
    phases = summary["phases"]
    for phase in ("halo", "interior", "checkpoint"):
        assert phases[phase]["wall_s"] > 0, (phase, phases)
    assert phases["halo"]["bytes"] > 0
    assert summary["steps"]["windows"] >= 4
    assert summary["steps"]["per_step_us"]["p50"] > 0
    assert summary["traced"].get("halo.exchange", {}).get("bytes", 0) > 0
    trace_doc = json.loads((tel_dir / "telemetry-trace.json").read_text())
    pids = {e["pid"] for e in trace_doc["traceEvents"]}
    assert pids == {0, 1}
    # the banked summary gates cleanly against itself — the regress
    # half of the acceptance criterion
    assert cli_main([
        "regress", str(tel_dir / "telemetry-summary.json"),
        "--baseline", str(tel_dir / "telemetry-summary.json"),
    ]) == 0
    capsys.readouterr()
