"""3D extension: 6-neighbor halo, N-D overlap shell, golden solution
(driver BASELINE.json config diffusion_3D_perf_hide)."""

import jax.numpy as jnp
import numpy as np

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.ops.diffusion import analytic_solution


def _cfg(**kw):
    base = dict(
        global_shape=(24, 24, 24),
        lengths=(10.0, 10.0, 10.0),
        nt=20,
        warmup=0,
        b_width=(4, 4, 4),
    )
    base.update(kw)
    return DiffusionConfig(**base)


def test_3d_shard_matches_ap_2x2x2():
    model = HeatDiffusion(_cfg(dims=(2, 2, 2)))
    res_s = model.run(variant="shard")
    res_a = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_s.T), np.asarray(res_a.T), rtol=1e-13, atol=1e-15
    )


def test_3d_hide_matches_ap_2x2x2():
    model = HeatDiffusion(_cfg(dims=(2, 2, 2)))
    res_h = model.run(variant="hide")
    res_a = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_h.T), np.asarray(res_a.T), rtol=1e-13, atol=1e-15
    )


def test_3d_perf_pallas_matches_ap():
    model = HeatDiffusion(_cfg(dims=(2, 2, 1)))
    res_p = model.run(variant="perf")
    res_a = model.run(variant="ap")
    np.testing.assert_allclose(
        np.asarray(res_p.T), np.asarray(res_a.T), rtol=1e-13, atol=1e-15
    )


def test_3d_dt_uses_cfl_6():
    cfg = _cfg()
    dx = 10.0 / 24
    assert cfg.dt == dx * dx / 6.1  # 2·ndim + 0.1 generalization


def test_3d_golden_analytic():
    cfg = DiffusionConfig(
        global_shape=(48, 48, 48),
        lengths=(10.0, 10.0, 10.0),
        nt=150,
        warmup=0,
        dims=(2, 2, 2),
    )
    model = HeatDiffusion(cfg)
    res = model.run(variant="hide")
    coords = model.grid.coord_mesh(dtype=jnp.float64)
    exact = analytic_solution(
        coords, cfg.lengths, cfg.lam / cfg.cp0, cfg.nt * cfg.dt
    )
    err = np.abs(np.asarray(res.T) - np.asarray(exact)).max() / float(
        jnp.max(exact)
    )
    # Discretization error at 48³ (dx≈0.21): measured 1.1e-2, converging to
    # 2.4e-3 at 64³ — the bound guards against scheme bugs, not truncation.
    assert err < 2e-2, f"3D golden error {err}"
