"""Physics correctness: stencil helpers, step-variant agreement, golden
analytic solution, invariants (SURVEY.md §4 build implication b/d)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.ops import stencil
from rocm_mpi_tpu.ops.diffusion import (
    analytic_solution,
    step_flux_form,
    step_fused,
)


def test_stencil_helpers_shapes_and_values():
    A = jnp.arange(20.0).reshape(4, 5)
    assert stencil.d_xa(A).shape == (3, 5)
    assert stencil.d_ya(A).shape == (4, 4)
    assert stencil.d_xi(A).shape == (3, 3)
    assert stencil.d_yi(A).shape == (2, 4)
    assert stencil.inn(A).shape == (2, 3)
    np.testing.assert_allclose(stencil.d_xa(A), 5.0)  # row stride
    np.testing.assert_allclose(stencil.d_ya(A), 1.0)  # col stride
    np.testing.assert_allclose(stencil.inn(A), A[1:-1, 1:-1])


def _random_state(nx, ny, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    T = jax.random.uniform(k1, (nx, ny), dtype=jnp.float64)
    # Non-constant Cp exercises the 1/cp path the reference's fused kernel
    # gets wrong (multiplies, perf.jl:8); our variants must agree with each
    # other for ANY Cp.
    Cp = 1.0 + jax.random.uniform(k2, (nx, ny), dtype=jnp.float64)
    return T, Cp


def test_flux_form_equals_fused():
    T, Cp = _random_state(33, 47)
    spacing = (0.1, 0.07)
    a = step_flux_form(T, Cp, 1.3, 1e-4, spacing)
    b = step_fused(T, Cp, 1.3, 1e-4, spacing)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-14)


def test_boundary_cells_never_change():
    T, Cp = _random_state(16, 16)
    out = step_fused(T, Cp, 1.0, 1e-4, (0.1, 0.1))
    T, out = np.asarray(T), np.asarray(out)
    np.testing.assert_array_equal(out[0, :], T[0, :])
    np.testing.assert_array_equal(out[-1, :], T[-1, :])
    np.testing.assert_array_equal(out[:, 0], T[:, 0])
    np.testing.assert_array_equal(out[:, -1], T[:, -1])
    assert not np.array_equal(out[1:-1, 1:-1], T[1:-1, 1:-1])


def test_golden_analytic_gaussian():
    # Run the model and compare against the exact free-space solution
    # (quantitative form of the reference's smooth-Gaussian acceptance
    # image, docs/Temp_4_252_252.png).
    cfg = DiffusionConfig(global_shape=(128, 128), nt=400, warmup=0, dims=(1, 1))
    model = HeatDiffusion(cfg)
    res = model.run(variant="ap")
    t_final = cfg.nt * cfg.dt
    coords = model.grid.coord_mesh(dtype=jnp.float64)
    exact = analytic_solution(coords, cfg.lengths, cfg.lam / cfg.cp0, t_final)
    got = np.asarray(res.T)
    exact = np.asarray(exact)
    err = np.abs(got - exact).max() / exact.max()
    assert err < 2e-3, f"relative max error vs analytic solution: {err}"


def test_peak_decays_and_interior_energy_conserved():
    cfg = DiffusionConfig(global_shape=(96, 96), nt=200, warmup=0, dims=(1, 1))
    model = HeatDiffusion(cfg)
    T0, Cp = model.init_state()
    # advance() donates its input (the double-buffer-swap analog), so read
    # invariants before advancing.
    m0, s0 = float(jnp.max(T0)), float(jnp.sum(T0))
    adv = model.advance_fn("ap")
    T30 = adv(T0, Cp, 30)
    m30 = float(jnp.max(T30))
    T60 = adv(T30, Cp, 30)
    m60, s60 = float(jnp.max(T60)), float(jnp.sum(T60))
    assert m0 > m30 > m60  # pure diffusion: monotone peak decay (hide.jl:115)
    # Total heat conserved while the field is still far from the fixed
    # Dirichlet boundary (longer runs legitimately leak heat through it).
    assert s60 == pytest.approx(s0, rel=1e-6)


def test_ic_matches_reference_formula():
    cfg = DiffusionConfig(global_shape=(64, 64), dims=(1, 1))
    model = HeatDiffusion(cfg)
    T, _ = model.init_state()
    # exp(-(xc-lx/2)^2 - (yc-ly/2)^2) with cell centers (ap.jl:28)
    dx = 10.0 / 64
    xc = (np.arange(64) + 0.5) * dx
    expect = np.exp(
        -((xc[:, None] - 5.0) ** 2) - (xc[None, :] - 5.0) ** 2
    )
    np.testing.assert_allclose(np.asarray(T), expect, rtol=1e-12)


def test_3d_steps_agree():
    k = jax.random.PRNGKey(1)
    T = jax.random.uniform(k, (12, 13, 14), dtype=jnp.float64)
    Cp = jnp.full_like(T, 1.5)
    spacing = (0.1, 0.11, 0.12)
    a = step_flux_form(T, Cp, 0.7, 1e-4, spacing)
    b = step_fused(T, Cp, 0.7, 1e-4, spacing)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-12)
