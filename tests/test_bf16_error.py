"""bf16 precision-trade error bound at run length (VERDICT r3 #4).

Round 3 documented the bf16 fast path's error after 4 steps only; the
characterization (scripts/bench_bf16_error.py, chip artifact
docs/bf16_error_r4.txt) shows the error GROWS with run length — per-step
field changes fall below bf16's 8-bit mantissa resolution, so storage
rounding accumulates as systematic drift rather than averaging out. This
test pins the measured bound at ≥100 steps (the VERDICT criterion) in
interpret mode so a numerics regression in the bf16 path (kernel compute
width, coefficient preparation, rounding behavior) cannot silently widen
the documented trade.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from scripts.bench_bf16_error import error_curve  # noqa: E402


def test_bf16_error_bound_at_run_length():
    rows = error_curve(n=84, checkpoints=(4, 128))
    by_steps = {r[0]: r for r in rows}

    # Short-window bound (the regime the r3 BASELINE row was based on).
    _, l2_4, max_4, peak_f32_4, peak_bf16_4 = by_steps[4]
    assert l2_4 < 0.02, f"4-step bf16 rel L2 regressed: {l2_4:.4%}"

    # Run-length bound: measured 6.8% rel L2 at 128 steps (84², interpret
    # mode, this exact protocol); pin with headroom for platform rounding
    # differences. If this trips, the bf16 path got NUMERICALLY worse, not
    # slower.
    _, l2_128, max_128, peak_f32, peak_bf16 = by_steps[128]
    assert l2_128 < 0.10, f"128-step bf16 rel L2 regressed: {l2_128:.4%}"

    # The drift is bounded, finite, and the invariant structure survives:
    # both trajectories keep decaying peaks (max(T) decay, hide.jl:115).
    assert 0 < peak_bf16 < 1.0 and 0 < peak_f32 < 1.0
    assert peak_bf16 < by_steps[4][4], "bf16 peak stopped decaying"


def test_bf16_rounding_is_per_kernel_not_per_step():
    """Mechanical proof of the storage-only contract: the traced multi-step
    kernel contains exactly 3 dtype conversions for bf16 operands — T in,
    Cm in, result out — INDEPENDENT of the step count. A regression to
    per-step rounding (storage-width arithmetic) would scale the count
    with the unroll."""
    import jax
    import jax.numpy as jnp

    import rocm_mpi_tpu.ops.pallas_kernels as pk

    T = jnp.zeros((32, 32), jnp.bfloat16)
    Cm = jnp.zeros((32, 32), jnp.bfloat16)
    counts = {
        n: str(
            jax.make_jaxpr(
                lambda a, b, n=n: pk.multi_step_cm(a, b, (0.1, 0.1), n)
            )(T, Cm)
        ).count("convert_element_type")
        for n in (4, 16)
    }
    assert counts[4] == counts[16] == 3, counts


def test_bf16_storage_only_multi_step_curve_flat():
    """The r4 fix: on the multi-step schedules bf16 is STORAGE-ONLY —
    f32 in-kernel compute, one rounding per chunk — so the error stays at
    quantization level and is damped by the dissipative physics instead of
    compounding (measured: 0.39% rel L2 at 128 steps vs 6.3% for the
    per-step schedule, same geometry/protocol). Pinned so the upcast
    cannot silently regress to storage-width arithmetic."""
    rows = error_curve(n=84, checkpoints=(4, 128), schedule="vmem",
                      vmem_chunk=8)
    by_steps = {r[0]: r for r in rows}
    l2_4 = by_steps[4][1]
    l2_128 = by_steps[128][1]
    assert l2_4 < 0.02, f"4-step storage-only bf16 rel L2: {l2_4:.4%}"
    # Flat-or-shrinking, and far below the per-step schedule's 6.3%: a
    # compounding regression blows straight through 2%.
    assert l2_128 < 0.02, f"128-step storage-only bf16 rel L2: {l2_128:.4%}"
