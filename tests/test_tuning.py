"""The autotuner (rocm_mpi_tpu/tuning/, docs/PERF.md "Autotuning").

Coverage map (ISSUE 7 satellites + acceptance drills):
  * key/cache schema round-trip, atomic writes, torn-file tolerance,
    stale jax/backend fingerprint -> miss (never a crash, never deleted);
  * admission-filtered space + the traffic gate's per-family budgets,
    including THE TEETH: a doctored fastest-but-over-budget "winner" is
    rejected by the gate (search skips it; `validate` exits 1 on it —
    the tuning edition of perf's --include-waste-fixture);
  * the resolve chokepoint: hit/miss/stats, unreadable cache degrades;
  * config="auto" bitwise-equal to the default paths on all three
    workloads — on a cold cache (miss fallback) AND steered by a tuned
    cache whose knobs are the bitwise-safe ones;
  * search: persists a gated winner, second run is a pure hit;
  * CLI verbs end-to-end in-process: search/validate/show exit codes,
    warm-run determinism (identical bytes) and compiles.steady_state=0.
"""

import json
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.tuning import cache as tcache
from rocm_mpi_tpu.tuning import gate as tgate
from rocm_mpi_tpu.tuning import keys as tkeys
from rocm_mpi_tpu.tuning import resolve as tresolve
from rocm_mpi_tpu.tuning import space as tspace


@pytest.fixture(autouse=True)
def _isolated_resolve(tmp_path):
    """Every test gets its own cache path and fresh resolve state; the
    process-default path must never leak between tests (resolve memoizes
    its document snapshot by design)."""
    path = tmp_path / "cache.json"
    tresolve.configure(path)
    tresolve.reset_stats()
    yield path
    tresolve.configure(None)
    tresolve.refresh()
    tresolve.reset_stats()


def _entry(config, fp=None):
    return {
        "config": config, "median_us": 1.0, "compile_s": 0.1,
        "gate_ratio": 1.0,
        "fingerprint": fp or tkeys.fingerprint("cpu"),
    }


def _write_cache(path, entries):
    doc = tcache.empty_doc()
    doc["entries"].update(entries)
    tcache.write_doc(path, doc)
    tresolve.refresh()


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


def test_key_roundtrip():
    k = tkeys.tuning_key("diffusion.vmem_loop", (252, 252), jnp.float32,
                         topology=(2, 1), backend="tpu")
    s = tkeys.key_str(k)
    assert s == "diffusion.vmem_loop|252x252|f32|2x1|tpu"
    assert tkeys.parse_key(s) == k


def test_key_default_topology_matches_rank():
    k2 = tkeys.tuning_key("diffusion.deep", (64, 64), "f32", backend="cpu")
    k3 = tkeys.tuning_key("diffusion.deep", (32, 32, 32), "f32",
                          backend="cpu")
    assert k2.topology == "1x1" and k3.topology == "1x1x1"


@pytest.mark.parametrize("bad", [
    "nope|32x32|f32|1x1|cpu",       # unknown op
    "diffusion.vmem_loop|32x|f32|1x1|cpu",  # malformed shape
    "diffusion.vmem_loop|32x32|f32|1x1",    # missing field
    "diffusion.vmem_loop|32x32|f32|0x1|cpu",  # degenerate topology
])
def test_parse_key_rejects(bad):
    with pytest.raises(ValueError):
        tkeys.parse_key(bad)


def test_unknown_op_rejected_at_key_build():
    with pytest.raises(ValueError, match="unknown tunable op"):
        tkeys.tuning_key("diffusion.bogus", (32, 32), "f32", backend="cpu")


# ---------------------------------------------------------------------------
# Cache document
# ---------------------------------------------------------------------------


def test_store_load_roundtrip_atomic(tmp_path):
    path = tmp_path / "c.json"
    key = tkeys.tuning_key("wave.vmem_loop", (32, 32), "f32", backend="cpu")
    tcache.store(path, key, _entry({"chunk": 16}))
    assert not (tmp_path / "c.json.tmp").exists()  # atomic rename
    doc = tcache.load(path)
    assert tcache.validate_doc(doc, str(path)) == []
    got = tcache.lookup(doc, key, tkeys.fingerprint("cpu"))
    assert got == {"chunk": 16}
    # A second store of another key keeps the first (read-modify-write).
    key2 = tkeys.tuning_key("swe.vmem_loop", (32, 32), "f32", backend="cpu")
    tcache.store(path, key2, _entry({"chunk": 64}))
    doc = tcache.load(path)
    assert len(doc["entries"]) == 2


def test_torn_file_reads_empty_with_warning(tmp_path):
    path = tmp_path / "torn.json"
    path.write_text('{"v": 1, "kind": "rmt-tuning-cache", "entr')  # torn
    with pytest.warns(UserWarning, match="unreadable"):
        doc = tcache.load(path)
    assert doc == tcache.empty_doc()


def test_alien_document_reads_empty(tmp_path):
    path = tmp_path / "alien.json"
    path.write_text(json.dumps({"metrics": {}}))  # a BENCH record, say
    with pytest.warns(UserWarning, match="not a v1"):
        assert tcache.load(path) == tcache.empty_doc()


def test_missing_file_is_silent_empty(tmp_path):
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the cold start must not warn
        assert tcache.load(tmp_path / "never.json") == tcache.empty_doc()


def test_stale_fingerprint_is_miss_not_crash():
    key = tkeys.tuning_key("diffusion.vmem_loop", (32, 32), "f32",
                           backend="cpu")
    doc = tcache.empty_doc()
    doc["entries"][tkeys.key_str(key)] = _entry(
        {"chunk": 16}, fp={"jax": "9.9.99", "backend": "cpu"}
    )
    assert tcache.lookup(doc, key, tkeys.fingerprint("cpu")) is None
    # Backend drift in the fingerprint is equally stale, and the stale
    # entry stays in the document (ignored, never deleted).
    doc["entries"][tkeys.key_str(key)] = _entry(
        {"chunk": 16}, fp={"jax": tkeys.fingerprint("cpu")["jax"],
                           "backend": "tpu"}
    )
    assert tcache.lookup(doc, key, tkeys.fingerprint("cpu")) is None
    assert len(doc["entries"]) == 1


def test_validate_doc_flags_drift(tmp_path):
    doc = tcache.empty_doc()
    doc["entries"]["diffusion.vmem_loop|32x32|f32|1x1|cpu"] = {
        "config": {"chunk": 16},  # missing median_us/compile_s/...
        "fingerprint": {"jax": "0.4.37", "backend": "cpu"},
    }
    problems = tcache.validate_doc(doc, "x.json")
    assert any("median_us" in p for p in problems)
    doc2 = tcache.empty_doc()
    doc2["entries"]["not-a-key"] = _entry({"chunk": 16})
    assert any("malformed tuning key" in p
               for p in tcache.validate_doc(doc2, "y.json"))


# ---------------------------------------------------------------------------
# Space admission
# ---------------------------------------------------------------------------


def test_space_vmem_admission_and_pad():
    # Over the VMEM budget (f32 compute width): nothing to enumerate.
    assert tspace.enumerate_space("diffusion.vmem_loop", (1024, 1024),
                                  "f32") == []
    # pow2 shape: no pad candidates (nothing to pad).
    cands = tspace.enumerate_space("diffusion.vmem_loop", (32, 32), "f32")
    assert cands and all(not c["pad_pow2"] for c in cands)
    # Non-pow2: pad candidates appear alongside.
    cands = tspace.enumerate_space("diffusion.vmem_loop", (20, 24), "f32")
    assert any(c["pad_pow2"] for c in cands)
    # All chunks stay >= 4: 1..3 switch the kernel body form (a
    # different fp expression), which would break the bitwise contract.
    assert all(c["chunk"] >= 4 for c in cands)


def test_space_cpu_backend_caps_chunk():
    cands = tspace.enumerate_space("diffusion.vmem_loop", (32, 32), "f32",
                                   backend="cpu")
    assert cands and all(c["chunk"] <= 16 for c in cands)


def test_space_masked_step_only_for_hbm_class():
    assert tspace.enumerate_space("diffusion.masked_step", (64, 64),
                                  "f32") == []  # VMEM loop serves it
    cands = tspace.enumerate_space("diffusion.masked_step", (4096, 4096),
                                   "f32")
    assert cands and all(4096 % c["tm"] == 0 and c["tm"] % 8 == 0
                         for c in cands)


def test_space_deep_clamps_to_shard():
    ks = [c["k"] for c in
          tspace.enumerate_space("diffusion.deep", (16, 16), "f32")]
    assert ks and max(ks) <= 16


# ---------------------------------------------------------------------------
# Traffic gate
# ---------------------------------------------------------------------------


def test_gate_rejects_overbudget_pad():
    g = tgate.validate_config(
        "diffusion.vmem_loop", (140, 140), "f32",
        {"body_form": "eqc", "pad_pow2": True, "chunk": 16},
    )
    assert not g.ok and g.ratio > 3.0 and "rejected" in g.reason
    ok = tgate.validate_config(
        "diffusion.vmem_loop", (252, 252), "f32",
        {"body_form": "conly", "pad_pow2": True, "chunk": 256},
    )
    assert ok.ok and ok.ratio < 1.1  # 252² -> 256² is a 3% pad


def test_gate_masked_step_stripe_budget():
    assert not tgate.validate_config("diffusion.masked_step",
                                     (4096, 4096), "f32", {"tm": 8}).ok
    assert tgate.validate_config("diffusion.masked_step",
                                 (4096, 4096), "f32", {"tm": 64}).ok


def test_gate_validate_entry_from_key_alone():
    key = tkeys.parse_key("diffusion.vmem_loop|140x140|f32|1x1|cpu")
    g = tgate.validate_entry(key, _entry(
        {"body_form": "eqc", "pad_pow2": True, "chunk": 16}
    ))
    assert not g.ok


def test_gate_scan_is_traffic_neutral():
    assert tgate.validate_config("diffusion.scan", (64, 64), "f32",
                                 {"chunk": 64}).ok


def test_gate_rejects_invalid_vmem_knobs():
    """The loud half of malformed-entry defense: the runtime sanitizer
    silently drops knobs that would crash a kernel; `validate` must
    instead FAIL a committed entry carrying them."""
    for bad in (
        {"chunk": -8}, {"chunk": 9}, {"chunk": 2},  # not pow2 >= 4
        {"body_form": "bogus"},
        {"pad_pow2": "yes"},
    ):
        g = tgate.validate_config("diffusion.vmem_loop", (32, 32), "f32",
                                  bad)
        assert not g.ok, bad


# ---------------------------------------------------------------------------
# The resolve chokepoint
# ---------------------------------------------------------------------------


def test_resolve_hit_miss_and_stats(_isolated_resolve):
    key = tkeys.tuning_key("diffusion.vmem_loop", (20, 24), "f32",
                           backend="cpu")
    _write_cache(_isolated_resolve,
                 {tkeys.key_str(key): _entry({"body_form": "conly"})})
    assert tresolve.resolve("diffusion.vmem_loop", (20, 24), "f32") == {
        "body_form": "conly"
    }
    assert tresolve.resolve("diffusion.vmem_loop", (64, 64), "f32") is None
    assert tresolve.stats() == {"hits": 1, "misses": 1}


def test_resolve_unreadable_cache_is_miss(_isolated_resolve):
    _isolated_resolve.write_text("{{{{")
    tresolve.refresh()
    with pytest.warns(UserWarning):
        assert tresolve.resolve("diffusion.vmem_loop", (20, 24),
                                "f32") is None


def test_resolve_deep_k_revalidates_against_grid(_isolated_resolve):
    from rocm_mpi_tpu.parallel.deep_halo import resolve_deep_k
    from rocm_mpi_tpu.parallel.mesh import init_global_grid

    grid = init_global_grid(16, 16, lengths=(10.0, 10.0), dims=(1, 1))
    key = tkeys.tuning_key("diffusion.deep", grid.local_shape, "f32",
                           topology=grid.dims, backend="cpu")
    _write_cache(_isolated_resolve, {tkeys.key_str(key): _entry({"k": 8})})
    assert resolve_deep_k(grid, jnp.float32, "auto") == 8
    assert resolve_deep_k(grid, jnp.float32, None) is None
    # A cached depth deeper than the shard (a reshard shrank it) falls
    # back silently instead of crashing the auto run.
    _write_cache(_isolated_resolve, {tkeys.key_str(key): _entry({"k": 32})})
    assert resolve_deep_k(grid, jnp.float32, "auto") is None


# ---------------------------------------------------------------------------
# config="auto" — bitwise vs the default paths (acceptance)
# ---------------------------------------------------------------------------


def _models(shape=(16, 16), nt=8, warmup=4):
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import (
        AcousticWave,
        HeatDiffusion,
        ShallowWater,
        SWEConfig,
        WaveConfig,
    )

    common = dict(global_shape=shape, lengths=(10.0,) * len(shape),
                  nt=nt, warmup=warmup, dtype="f32",
                  dims=(1,) * len(shape))
    return (
        HeatDiffusion(DiffusionConfig(**common)),
        AcousticWave(WaveConfig(**common)),
        ShallowWater(SWEConfig(**common)),
    )


def test_auto_equals_default_bitwise_on_cold_cache(_isolated_resolve):
    """Empty cache: every config='auto' lookup misses and the fallback
    must be the hand defaults BITWISE, all three workloads."""
    diff, wave, swe = _models()
    d0 = diff.run_vmem_resident().T
    d1 = _models()[0].run_vmem_resident(config="auto").T
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    w0 = wave.run_vmem_resident().U
    w1 = _models()[1].run_vmem_resident(config="auto").U
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
    s0 = swe.run_vmem_resident().h
    s1 = _models()[2].run_vmem_resident(config="auto").h
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
    assert tresolve.stats()["misses"] >= 3
    assert tresolve.stats()["hits"] == 0


def test_auto_equals_default_bitwise_with_tuned_cache(_isolated_resolve):
    """A warm cache steers config='auto' through the RESOLVED knobs —
    and because the vmem-loop space only contains bitwise-safe knobs
    (pad_pow2 is interior-bitwise-pinned, chunks stay in one body-form
    class), the tuned run stays bitwise-equal to the default run."""
    shape = (20, 24)  # non-pow2: the pad knob actually engages
    entries = {}
    for op, config in (
        ("diffusion.vmem_loop",
         {"body_form": "eqc", "pad_pow2": True, "chunk": 4}),
        ("wave.vmem_loop", {"chunk": 4}),
        ("swe.vmem_loop", {"chunk": 4}),
    ):
        key = tkeys.tuning_key(op, shape, "f32", backend="cpu")
        entries[tkeys.key_str(key)] = _entry(config)
    _write_cache(_isolated_resolve, entries)

    d0 = _models(shape)[0].run_vmem_resident().T
    d1 = _models(shape)[0].run_vmem_resident(config="auto").T
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    w0 = _models(shape)[1].run_vmem_resident().U
    w1 = _models(shape)[1].run_vmem_resident(config="auto").U
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
    s0 = _models(shape)[2].run_vmem_resident().h
    s1 = _models(shape)[2].run_vmem_resident(config="auto").h
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
    assert tresolve.stats()["hits"] >= 3  # the cache really steered


def test_auto_scan_driver_bitwise(_isolated_resolve):
    """The scan drivers' auto chunk: tuned q is bitwise (scan==step is
    pinned at any q); a cold cache falls back to the default window."""
    key = tkeys.tuning_key("diffusion.scan", (16, 16), "f32",
                           backend="cpu")
    _write_cache(_isolated_resolve, {tkeys.key_str(key): _entry(
        {"chunk": 2}
    )})
    r0 = _models()[0].run(variant="fused", driver="scan")
    r1 = _models()[0].run(variant="fused", driver="scan", config="auto")
    np.testing.assert_array_equal(np.asarray(r0.T), np.asarray(r1.T))
    assert tresolve.stats()["hits"] >= 1


def test_masked_step_auto_tm_bitwise(monkeypatch, _isolated_resolve):
    """masked_step's tm knob through the auto path: force the HBM-class
    route with a tiny budget, cache tm=16, and pin bitwise equality with
    the automatic height (the striped kernel computes the same
    expression per element at any tm)."""
    import rocm_mpi_tpu.ops.pallas_kernels as pk

    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    shape = (64, 48)
    key = tkeys.tuning_key("diffusion.masked_step", shape, "f32",
                           backend="cpu")
    _write_cache(_isolated_resolve, {tkeys.key_str(key): _entry(
        {"tm": 16}
    )})
    rng = np.random.default_rng(0)
    T = jnp.asarray(rng.random(shape), jnp.float32)
    Cm = jnp.asarray(rng.random(shape) * 1e-4, jnp.float32)
    ref = pk.masked_step(T, Cm, (0.1, 0.1))
    got = pk.masked_step(T, Cm, (0.1, 0.1), config="auto")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert tresolve.stats()["hits"] == 1
    # A cached tm violating the shape's constraints is ignored silently.
    _write_cache(_isolated_resolve, {tkeys.key_str(key): _entry(
        {"tm": 24}  # 64 % 24 != 0
    )})
    got2 = pk.masked_step(T, Cm, (0.1, 0.1), config="auto")
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(ref))


def test_malformed_cache_entry_degrades_not_crashes(_isolated_resolve):
    """A cache entry is untrusted input: knobs that would crash a kernel
    (chunk=-8, body_form='bogus') are dropped at the resolve chokepoint
    and the run degrades to the defaults BITWISE — 'a cache is an
    accelerator, not a dependency'."""
    key = tkeys.tuning_key("diffusion.vmem_loop", (16, 16), "f32",
                           backend="cpu")
    _write_cache(_isolated_resolve, {tkeys.key_str(key): _entry(
        {"chunk": -8, "body_form": "bogus", "pad_pow2": "yes"}
    )})
    d0 = _models()[0].run_vmem_resident().T
    d1 = _models()[0].run_vmem_resident(config="auto").T  # must not raise
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    # Every field was invalid -> the sanitized config is empty -> a miss.
    assert tresolve.stats()["hits"] == 0


def test_last_pad_applied_deprecated_shim():
    import rocm_mpi_tpu.ops.pallas_kernels as pk

    rng = np.random.default_rng(1)
    T = jnp.asarray(rng.random((20, 24)), jnp.float32)
    Cp = jnp.asarray(1.0 + rng.random((20, 24)), jnp.float32)
    pk.fused_multi_step(T, Cp, 1.0, 1e-5, (0.1, 0.1), n_steps=4,
                        chunk=4, pad_pow2=True)
    with pytest.warns(DeprecationWarning, match="plan_vmem_loop"):
        assert pk.last_pad_applied() is True
    # The replacement answers the same question purely, no run needed.
    assert pk.plan_vmem_loop((20, 24), "float32", 4, chunk=4,
                             pad_pow2=True).pad_applied is True


# ---------------------------------------------------------------------------
# Search (+ THE TEETH)
# ---------------------------------------------------------------------------


def test_search_persists_winner_then_pure_hit(tmp_path):
    from rocm_mpi_tpu.tuning import search as tsearch

    path = tmp_path / "s.json"
    cands = [
        {"body_form": "eqc", "pad_pow2": False, "chunk": 4},
        {"body_form": "conly", "pad_pow2": False, "chunk": 4},
    ]
    r1 = tsearch.search_op("diffusion.vmem_loop", (16, 16), "f32",
                           repeats=1, cache_path=path, candidates=cands)
    assert r1["status"] == "tuned"
    assert r1["entry"]["config"] in cands
    assert tcache.validate_doc(tcache.load(path)) == []
    # Second run: fingerprint-valid entry -> NO measurement at all.
    r2 = tsearch.search_op("diffusion.vmem_loop", (16, 16), "f32",
                           repeats=1, cache_path=path, candidates=cands)
    assert r2["status"] == "hit"
    assert r2["entry"]["config"] == r1["entry"]["config"]


def test_search_gate_rejects_doctored_fast_winner(tmp_path, monkeypatch):
    """THE TEETH (the tuning twin of perf's --include-waste-fixture): a
    config that MEASURES fastest but models over the A_eff budget must
    not win — the gate kicks it and the next-fastest in-budget candidate
    is persisted instead. The runner is stubbed so the doctored pad
    config is deterministically 10x 'faster'."""
    from rocm_mpi_tpu.tuning import search as tsearch

    overbudget = {"body_form": "eqc", "pad_pow2": True, "chunk": 4}
    honest = {"body_form": "eqc", "pad_pow2": False, "chunk": 4}

    def fake_runner(op, shape, dtype):
        return lambda config: 1e-6 if config["pad_pow2"] else 1e-5

    monkeypatch.setattr(tsearch, "_make_runner", fake_runner)
    path = tmp_path / "teeth.json"
    # (140,140) pads to (256,256): 3.3x the ideal bytes, over the 1.5
    # vmem_loop budget.
    r = tsearch.search_op("diffusion.vmem_loop", (140, 140), "f32",
                          repeats=1, cache_path=path,
                          candidates=[overbudget, honest])
    assert r["status"] == "tuned"
    assert r["entry"]["config"] == honest
    assert r["rejected"] and r["rejected"][0][0] == overbudget
    assert "rejected" in r["rejected"][0][1]
    # And when EVERY candidate is over budget, nothing is cached.
    r2 = tsearch.search_op("diffusion.vmem_loop", (140, 140), "f32",
                           repeats=1, cache_path=tmp_path / "none.json",
                           candidates=[overbudget])
    assert r2["status"] == "all-rejected" and r2["entry"] is None
    assert not (tmp_path / "none.json").exists()


def test_search_empty_space_is_clean_noop(tmp_path):
    from rocm_mpi_tpu.tuning import search as tsearch

    r = tsearch.search_op("diffusion.masked_step", (16, 16), "f32",
                          repeats=1, cache_path=tmp_path / "e.json")
    assert r["status"] == "empty"


# ---------------------------------------------------------------------------
# CLI (in-process; the acceptance drill's verbs)
# ---------------------------------------------------------------------------


def _cli(argv):
    from rocm_mpi_tpu.tuning.__main__ import main

    return main(argv)


def test_cli_search_validate_show_and_warm_determinism(
    tmp_path, monkeypatch, capsys
):
    """The acceptance drill, in-process: `search` produces a
    schema-valid cache for diffusion + wave; a second run is a pure
    cache hit — byte-identical file, no re-search, and
    compiles.steady_state == 0 with the cache warm; `validate` passes;
    an injected over-budget config makes `validate` exit 1."""
    from rocm_mpi_tpu.telemetry import compiles

    # Tiny candidate chunks: the CLI honors the module space, and the
    # test must not pay chunk-16 interpret traces per candidate.
    monkeypatch.setattr(tspace, "_CHUNKS", (4,))
    path = tmp_path / "cli.json"
    argv = ["search", "--shape", "16x16", "--repeats", "1",
            "--cache", str(path)]
    assert _cli(argv) == 0
    err1 = capsys.readouterr().err
    assert "tuned" in err1
    blob1 = path.read_bytes()

    compiles.reset()  # model the acceptance's fresh second process
    assert _cli(argv) == 0
    err2 = capsys.readouterr().err
    assert "2 hit(s), 0 tuned" in err2
    assert "compiles.steady_state=0" in err2
    assert path.read_bytes() == blob1  # deterministic: a pure hit

    assert _cli(["validate", str(path)]) == 0
    assert _cli(["show", "--cache", str(path)]) == 0
    out = capsys.readouterr().out
    assert "diffusion.vmem_loop|16x16|f32|1x1|cpu" in out

    # Inject an over-budget entry: the gate must fail validate (exit 1).
    doc = json.loads(blob1)
    doc["entries"]["diffusion.vmem_loop|140x140|f32|1x1|cpu"] = _entry(
        {"body_form": "eqc", "pad_pow2": True, "chunk": 4}
    )
    doctored = tmp_path / "doctored.json"
    doctored.write_text(json.dumps(doc))
    assert _cli(["validate", str(doctored)]) == 1
    assert "A_eff ideal" in capsys.readouterr().err


def test_cli_validate_exit_codes(tmp_path, capsys):
    assert _cli(["validate"]) == 2  # no paths
    assert _cli(["validate", str(tmp_path / "missing.json")]) == 2
    torn = tmp_path / "torn.json"
    torn.write_text('{"v": 1, "kin')
    # A torn COMMITTED file fails strictly (unlike the runtime's
    # tolerant read, which degrades to a miss).
    assert _cli(["validate", str(torn)]) == 1


def test_cli_search_usage_errors(capsys):
    assert _cli(["search", "--shape", "banana"]) == 2
    assert _cli(["search", "--repeats", "0"]) == 2
