"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference assumes a Slurm cluster and has no way to test multi-rank
behavior locally (SURVEY.md §4.5). The TPU-native answer is XLA's virtual
host devices: force 8 CPU devices so every mesh/halo/collective test runs
single-process, no hardware needed. f64 is enabled to match the reference's
Float64 physics (diffusion_2D_ap.jl:22-26).

Note: this environment pre-imports jax at interpreter startup with
JAX_PLATFORMS=axon pinned, so we must override via jax.config (which works
any time before backend initialization), not via os.environ.
"""

import os
import pathlib
import shutil
import subprocess

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn

import jax  # noqa: E402


def pytest_configure(config):
    """Build the native host-staging engine before collection when a
    toolchain is present, so a fresh checkout runs the full 81-test matrix
    instead of silently skipping the native-vs-numpy bit-identity tests
    (the reference's startup.sh likewise builds before first run,
    /root/reference/startup.sh:5-17). Failure is non-fatal: the native
    tests then skip with their usual instructions."""
    if shutil.which("g++") is None or shutil.which("make") is None:
        return
    native = pathlib.Path(__file__).resolve().parent.parent / "native"
    subprocess.run(
        ["make", "-C", str(native)], check=False, capture_output=True
    )

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)

assert len(jax.devices()) == 8, (
    "test harness requires 8 virtual CPU devices, got "
    f"{jax.devices()} — was a backend initialized before conftest ran?"
)
