"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference assumes a Slurm cluster and has no way to test multi-rank
behavior locally (SURVEY.md §4.5). The TPU-native answer is XLA's virtual
host devices: force 8 CPU devices so every mesh/halo/collective test runs
single-process, no hardware needed. f64 is enabled to match the reference's
Float64 physics (diffusion_2D_ap.jl:22-26).

Note: this environment pre-imports jax at interpreter startup with
JAX_PLATFORMS=axon pinned, so we must override via jax.config (which works
any time before backend initialization), not via os.environ.

Two speed levers keep the per-commit gate under the VERDICT r4 #4 bar
(≤ 300 s) without losing coverage:

* **soak lane** — tests marked `slow` (the wall-clock bench-robustness
  contracts, duplicate dryrun sizes, the heaviest subprocess app runs)
  are deselected by default and run with `--soak` (or RMT_SOAK=1). The
  lane is part of the round's acceptance: run it before shipping a round
  and commit the log (docs/ROUND5_NOTES.md records the protocol).
* **machine-local CPU compile cache** — RMT_CPU_CACHE=1 +
  JAX_COMPILATION_CACHE_DIR point this process AND every spawned child
  (apps, bench, dryrun subprocesses) at an untracked per-machine XLA
  cache, so re-runs skip identical XLA:CPU compiles. Safe precisely
  because the dir never leaves the machine that wrote it (the SIGILL
  feature-mismatch hazard needs a foreign cache); see utils.backend.
"""

import os
import pathlib
import shutil
import subprocess

import pytest

_REPO = pathlib.Path(__file__).resolve().parent.parent

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn
os.environ.setdefault("RMT_CPU_CACHE", "1")  # =0 disables (utils.backend)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", str(_REPO / ".jax_cache_cpu")
)


def _env_on(name: str) -> bool:
    """Value-aware env flag: '0'/''/'false'/'no' mean OFF, not presence."""
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "no",
    )

import jax  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--soak", action="store_true", default=False,
        help="also run the slow-marked soak/robustness lane",
    )


def pytest_configure(config):
    """Register the soak marker and build the native host-staging engine
    before collection when a toolchain is present, so a fresh checkout
    runs the full test matrix instead of silently skipping the
    native-vs-numpy bit-identity tests (the reference's startup.sh
    likewise builds before first run, /root/reference/startup.sh:5-17).
    Failure is non-fatal: the native tests then skip with their usual
    instructions."""
    config.addinivalue_line(
        "markers",
        "slow: soak/robustness lane — deselected by default; run with "
        "--soak or RMT_SOAK=1",
    )
    if shutil.which("g++") is None or shutil.which("make") is None:
        return
    subprocess.run(
        ["make", "-C", str(_REPO / "native")],
        check=False, capture_output=True,
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--soak") or _env_on("RMT_SOAK"):
        return
    skip = pytest.mark.skip(
        reason="soak lane: pass --soak (or RMT_SOAK=1) to run"
    )
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


from rocm_mpi_tpu.utils.backend import set_cpu_device_count  # noqa: E402

jax.config.update("jax_platforms", "cpu")
set_cpu_device_count(8)  # compat shim: jax 0.4.37 has no jax_num_cpu_devices
jax.config.update("jax_enable_x64", True)

assert len(jax.devices()) == 8, (
    "test harness requires 8 virtual CPU devices, got "
    f"{jax.devices()} — was a backend initialized before conftest ran?"
)

# In-process compile cache too: the suite's own jit programs (the virtual
# 8-device mesh tests) persist across runs of the per-commit gate.
from rocm_mpi_tpu.utils.backend import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
