"""Mechanical proof of the communication schedules (VERDICT r3 #3).

The framework's schedule claims — argued in docstrings and implied by
timings — are pinned here by inspecting the *compiled program structure*
itself, the strongest proof available in a 1-chip bench environment:

  1. Op counts: lower each schedule to StableHLO on the 8-virtual-device
     mesh and count `stablehlo.collective_permute` ops. The time loop is a
     `lax.fori_loop`, so its body appears exactly once in the lowered text:
     the count IS the per-step (or per-sweep) message count.
       - per-step perf/hide: one exchange_halo per step = 2 ppermutes per
         sharded axis = 2·ndim ops per step;
       - deep-k sweeps: ONLY the state is exchanged per sweep (2·ndim ops
         per k steps for T; the time-invariant Cp is exchanged once per
         compiled advance by DeepSchedule.prepare, outside the loop) —
         the k× message-reduction claim of parallel/deep_halo.py plus its
         hoisted-coefficient refinement, as a regression guard;
       - wave deep-k: the leapfrog state pair = 2·2·ndim per k steps, C2
         once per advance; SWE deep-k: prepare is exchange-free (the face
         masks are geometry).
  2. Dataflow: hide's interior region must not consume collective results
     (the reference's intended variant (3) semantics,
     /root/reference/scripts/diffusion_2D_perf_hide.jl:94-101 — interior
     compute overlaps the exchange precisely because it depends on no ghost
     value). Proven by poisoning: run the hide step with every exchanged
     ghost forced to NaN — if any interior cell consumed a collective
     result, NaN would propagate into it (NaN poisons every arithmetic op);
     the interior must come out bit-identical to the clean run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig
from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep, make_wave_deep_sweep

DIMS = (4, 2)  # both axes really sharded, so every axis exchanges
SHAPE = (32, 16)


def _diffusion(dtype="f32", **kw):
    cfg = DiffusionConfig(
        global_shape=SHAPE, lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype=dtype, dims=DIMS, **kw,
    )
    return HeatDiffusion(cfg)


def _cp_count(lowered) -> int:
    return lowered.as_text().count("stablehlo.collective_permute")


def test_per_step_perf_messages_per_step():
    m = _diffusion()
    T, Cp = m.init_state()
    adv = m.advance_fn("perf")
    # fori_loop body lowers once: 2 ppermutes per axis per step.
    assert _cp_count(adv.lower(T, Cp, 8)) == 2 * len(DIMS)


def test_hide_same_message_count_as_perf():
    m = _diffusion()
    T, Cp = m.init_state()
    n = _cp_count(m.advance_fn("hide").lower(T, Cp, 8))
    assert n == 2 * len(DIMS)  # overlap reorders the schedule, never adds


def test_deep_sweep_messages_per_k_steps():
    m = _diffusion()
    T, Cp = m.init_state()
    k = 4
    sched = make_deep_sweep(
        m.grid, k, m.config.lam, m.config.jax_dtype(m.config.dt),
        m.config.spacing,
    )
    Cm = jax.jit(sched.prepare)(Cp)

    # ONLY the carried field is exchanged per k-step sweep: 2·ndim ops —
    # the k× message-reduction claim, mechanically, plus the hoisted-
    # coefficient refinement (the old schedule re-exchanged Cp inside
    # every sweep, doubling the per-sweep message count).
    per_sweep = _cp_count(jax.jit(sched.sweep).lower(T, Cm))
    assert per_sweep == 2 * len(DIMS)
    # The time-invariant Cp costs one exchange per compiled advance…
    assert _cp_count(jax.jit(sched.prepare).lower(Cp)) == 2 * len(DIMS)

    @jax.jit
    def advance(T, Cp, n_sweeps):
        Cm = sched.prepare(Cp)
        return jax.lax.fori_loop(
            0, n_sweeps, lambda _, x: sched.sweep(x, Cm), T
        )

    # …so the whole advance lowers to prepare + loop body: 2·2·ndim ops
    # regardless of the sweep count.
    assert _cp_count(advance.lower(T, Cp, 2)) == 2 * 2 * len(DIMS)
    per_step_equiv = _cp_count(m.advance_fn("perf").lower(T, Cp, 8))
    assert per_sweep < k * per_step_equiv  # fewer messages for k steps


def test_wave_deep_sweep_messages_three_fields():
    wcfg = WaveConfig(
        global_shape=SHAPE, lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype="f32", dims=DIMS,
    )
    wave = AcousticWave(wcfg)
    U, Uprev, C2 = wave.init_state()
    k = 4
    sched = make_wave_deep_sweep(
        wave.grid, k, wcfg.jax_dtype(wcfg.dt), wcfg.spacing
    )
    P = jax.jit(sched.prepare)(C2)

    # Per sweep: ONLY the leapfrog state pair (2 fields) is exchanged;
    # the time-invariant C2 costs one exchange per compiled advance.
    assert _cp_count(jax.jit(sched.sweep).lower(U, Uprev, P)) \
        == 2 * 2 * len(DIMS)
    assert _cp_count(jax.jit(sched.prepare).lower(C2)) == 2 * len(DIMS)

    @jax.jit
    def advance(U, Uprev, C2, n_sweeps):
        P = sched.prepare(C2)
        return jax.lax.fori_loop(
            0, n_sweeps, lambda _, s: sched.sweep(s[0], s[1], P),
            (U, Uprev),
        )

    # Whole advance: state pair per sweep + C2 once = 3·2·ndim in the
    # lowered text (the loop body appears once).
    assert _cp_count(advance.lower(U, Uprev, C2, 2)) == 3 * 2 * len(DIMS)


def test_wave_per_step_messages():
    wcfg = WaveConfig(
        global_shape=SHAPE, lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype="f32", dims=DIMS,
    )
    wave = AcousticWave(wcfg)
    U, Uprev, C2 = wave.init_state()
    # Per-step leapfrog only exchanges U (Uprev/C2 are read core-only).
    assert _cp_count(
        wave.advance_fn("perf").lower(U, Uprev, C2, 8)
    ) == 2 * len(DIMS)


def test_hide_interior_consumes_no_collective_results(monkeypatch):
    """NaN-poison the exchange: hide's interior must be bit-identical.

    Forces every ghost cell arriving from a ppermute to NaN. Any interior
    cell whose value consumed a collective result would become NaN (NaN
    propagates through every arithmetic op); only the boundary slabs (width
    = effective b_width) may differ. This is the dataflow-independence that
    makes the exchange hideable behind interior compute (overlap.py's
    step (2)) — asserted on the executed program, not the docstring.
    """
    import rocm_mpi_tpu.parallel.overlap as overlap_mod
    from rocm_mpi_tpu.parallel.halo import exchange_halo
    from rocm_mpi_tpu.parallel.overlap import effective_b_width

    b_width = (2, 2)
    m_clean = _diffusion(b_width=b_width)
    T, Cp = m_clean.init_state()
    step_clean = m_clean.step_fn("hide")
    out_clean = np.asarray(jax.block_until_ready(step_clean(T, Cp)))

    def poisoned_exchange(u, grid, width=1, axes=None, **wire_kw):
        padded = exchange_halo(u, grid, width=width, axes=axes, **wire_kw)
        # Everything outside the original core is ghost data that arrived
        # (or would arrive) via collective_permute: poison it all.
        core = tuple(slice(width, width + n) for n in u.shape)
        poison = jnp.full_like(padded, jnp.nan)
        return poison.at[core].set(padded[core])

    monkeypatch.setattr(overlap_mod, "exchange_halo", poisoned_exchange)
    m_poison = _diffusion(b_width=b_width)
    out_poison = np.asarray(
        jax.block_until_ready(m_poison.step_fn("hide")(T, Cp))
    )

    local = m_clean.grid.local_shape
    bw = effective_b_width(local, b_width)
    interior = tuple(slice(b, n - b) for b, n in zip(bw, local))
    poison_seen = clean_boundary_nan = False
    for ci in range(DIMS[0]):
        for cj in range(DIMS[1]):
            blk_p = out_poison[
                ci * local[0]:(ci + 1) * local[0],
                cj * local[1]:(cj + 1) * local[1],
            ]
            blk_c = out_clean[
                ci * local[0]:(ci + 1) * local[0],
                cj * local[1]:(cj + 1) * local[1],
            ]
            np.testing.assert_array_equal(
                blk_p[interior], blk_c[interior],
                err_msg=f"shard ({ci},{cj}): interior consumed a "
                        "collective result (NaN or value drift)",
            )
            poison_seen |= bool(np.isnan(blk_p).any())
            clean_boundary_nan |= bool(np.isnan(blk_c).any())
    # Sanity of the poison itself: it must have reached the boundary slabs
    # of at least one shard (else the test proved nothing), and the clean
    # run must be NaN-free.
    assert poison_seen, "poisoned ghosts never reached any output"
    assert not clean_boundary_nan


def test_per_step_exchange_is_one_per_step_not_per_program():
    """The count scales with sweeps, not steps: lowering a 2-sweep deep
    program and a 16-step per-step program yields the same text-level op
    counts as their 1-unit forms — i.e. the loop body really is the unit
    of communication, so 'messages per step' is well-defined."""
    m = _diffusion()
    T, Cp = m.init_state()
    adv = m.advance_fn("perf")
    assert _cp_count(adv.lower(T, Cp, 1)) == _cp_count(adv.lower(T, Cp, 16))


def test_swe_per_step_messages_all_fields():
    # The coupled SWE update needs neighbors of every field, so the
    # per-step schedule exchanges the whole pytree state: (ndim+1 fields)
    # · 2 ppermutes per sharded axis per step.
    from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater

    scfg = SWEConfig(
        global_shape=SHAPE, lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype="f32", dims=DIMS,
    )
    swe = ShallowWater(scfg)
    h, us = swe.init_state()
    Mus = swe.face_masks()
    ndim = len(DIMS)
    for variant in ("perf", "hide"):
        assert _cp_count(
            swe.advance_fn(variant).lower(h, us, Mus, 8)
        ) == (ndim + 1) * 2 * ndim, variant


def test_swe_deep_sweep_messages_per_k_steps():
    # Deep-k: the same ndim+1 fields exchanged once per k steps — the k×
    # message reduction holds for the coupled workload too.
    from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater
    from rocm_mpi_tpu.parallel.deep_halo import make_swe_deep_sweep

    scfg = SWEConfig(
        global_shape=SHAPE, lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype="f32", dims=DIMS,
    )
    swe = ShallowWater(scfg)
    h, us = swe.init_state()
    k = 4
    sched = make_swe_deep_sweep(
        swe.grid, k, scfg.dt, scfg.spacing, scfg.H0, scfg.g
    )
    ndim = len(DIMS)
    Mp = jax.jit(sched.prepare)(h)

    # The face masks are geometry: prepare needs NO exchange at all.
    assert _cp_count(jax.jit(sched.prepare).lower(h)) == 0
    assert _cp_count(jax.jit(sched.sweep).lower(h, us, Mp)) \
        == (ndim + 1) * 2 * ndim

    @jax.jit
    def advance(h, us, n_sweeps):
        Mp = sched.prepare(h)
        return jax.lax.fori_loop(
            0, n_sweeps, lambda _, s: sched.sweep(s[0], s[1], Mp), (h, us)
        )

    assert _cp_count(advance.lower(h, us, 2)) == (ndim + 1) * 2 * ndim
