"""The storage-fault plane and preemption-aware shutdown
(docs/RESILIENCE.md §7 — utils/checkpoint.py StoragePolicy + degraded
mode, resilience/preempt.py, resilience/faults.py storage kinds).

The claims, pinned:

  * storage faults are deterministic drills: `io-error` / `io-slow` /
    `enospc` clauses parse, pin to the opt-in "save" site, re-fire per
    ATTEMPT up to `times=N`, and raise the real errnos;
  * a transient save failure retries with bounded backoff (`ckpt.retry`
    events) and the run never notices; an outage exhausting the retries
    flips the segmented loop into DEGRADED mode — compute continues,
    boundaries probe-and-skip (`ckpt.degraded`), recovery is announced
    (`ckpt.recovered`) — and the loss window is bounded by the last
    pre-outage valid step (the failed steps simply never exist on disk);
  * ENOSPC prunes the keep-list before giving up; the slow-write
    watchdog degrades without losing the (durable) slow save;
  * a SIGTERM grace deadline lands ONE emergency save at the next
    segment boundary iff the telemetry-measured p90 save wall fits the
    remaining grace — else the save is skipped outright (no torn
    artifact) — and either way the rank exits RC_PREEMPTED, which
    run_supervised never retries and run_elastic classifies as
    resumable, never a failure;
  * all of it holds gloo-real: a 2-rank storage outage spanning two
    consecutive saves keeps the run alive in degraded mode, and a
    preempted 2-rank run resumes under run_elastic to a final state
    bitwise-equal to the uninterrupted twin.
"""

import json
import os
import pathlib
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.resilience import faults
from rocm_mpi_tpu.resilience import preempt
from rocm_mpi_tpu.resilience import run_elastic
from rocm_mpi_tpu.resilience.supervisor import default_retryable
from rocm_mpi_tpu.telemetry import health
from rocm_mpi_tpu.telemetry import regress
from rocm_mpi_tpu.utils import checkpoint as ckpt

ROOT = pathlib.Path(__file__).resolve().parent.parent

NT, EVERY = 16, 4


@pytest.fixture(autouse=True)
def _clean_state():
    """Every test starts with no armed faults, no pending preemption, an
    empty save-wall history, and a fresh event trail — all module-global
    state the storage/preempt planes deliberately keep (the save-wall
    history in particular accretes from every other test file's saves in
    this process)."""
    faults.install(None)
    preempt.reset()
    ckpt._SAVE_WALLS.clear()
    telemetry.clear_events()
    yield
    faults.install(None)
    preempt.uninstall()
    ckpt._SAVE_WALLS.clear()
    telemetry.clear_events()


def _model(nt=NT, shape=(16, 16)):
    cfg = DiffusionConfig(
        global_shape=shape, lengths=(10.0, 10.0), nt=nt, warmup=0,
        dtype="f64", dims=(1, 1),
    )
    model = HeatDiffusion(cfg)
    T, Cp = model.init_state()
    advance = model.advance_fn("perf")
    adv = lambda s, n: (advance(s[0], Cp, n),)  # noqa: E731
    return adv, (T,)


def _policy(**kw):
    kw.setdefault("retries", 2)
    kw.setdefault("backoff_s", 0.01)
    return ckpt.StoragePolicy(**kw)


def _events(name=None):
    return [r for r in telemetry.records(kind="event")
            if name is None or r["name"] == name]


# ---------------------------------------------------------------------------
# Fault grammar: the storage kinds
# ---------------------------------------------------------------------------


def test_storage_fault_kinds_parse_and_default_to_save_site():
    plan = faults.FaultPlan.parse(
        "io-error@step=8;io-slow=0.5@step=4;enospc@step=12,times=3"
    )
    kinds = [(c.kind, c.site, c.times) for c in plan.clauses]
    assert kinds == [("io-error", "save", None),
                     ("io-slow", "save", None),
                     ("enospc", "save", 3)]
    assert plan.clauses[1].delay_s == 0.5
    # Bare io-slow gets the default stall duration.
    bare = faults.FaultPlan.parse("io-slow@step=4")
    assert bare.clauses[0].delay_s == faults.IO_SLOW_DEFAULT_S
    assert "times=3" in repr(plan.clauses[2])
    with pytest.raises(ValueError, match="needs a step"):
        faults.FaultPlan.parse("io-error")
    with pytest.raises(ValueError, match="times"):
        faults.FaultPlan.parse("io-error@step=4,times=0")


def test_storage_faults_fire_with_real_errnos_and_rearm():
    import errno

    plan = faults.install("io-error@step=8,times=2")
    with pytest.raises(OSError) as one:
        faults.fault_point("save", step=8)
    assert one.value.errno == errno.EIO
    with pytest.raises(OSError):
        faults.fault_point("save", step=8)  # times=2: re-fires per attempt
    faults.fault_point("save", step=8)  # exhausted: the retry succeeds
    assert plan.clauses[0].fires == 2
    faults.install("enospc@step=4")
    with pytest.raises(OSError) as two:
        faults.fault_point("save", step=4)
    assert two.value.errno == errno.ENOSPC
    # The save site is opt-in: a legacy segment clause never fires there.
    plan = faults.install("crash@step=8")
    faults.fault_point("save", step=8)
    assert plan.clauses[0].fires == 0


# ---------------------------------------------------------------------------
# Retry / backoff, ENOSPC pruning, the slow-write watchdog, degraded mode
# ---------------------------------------------------------------------------


def test_transient_io_error_retries_and_completes(tmp_path):
    adv, state = _model()
    ref = adv((jnp.copy(state[0]),), NT)
    faults.install("io-error@step=8")
    waits = []
    out = ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY,
                             storage=_policy(sleep=waits.append))
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    assert ckpt.all_steps(tmp_path)[-1] == NT
    retries = _events("ckpt.retry")
    assert len(retries) == 1 and retries[0]["step"] == 8
    assert retries[0]["attempt"] == 0 and retries[0]["wait_s"] == waits[0]
    assert not _events("ckpt.degraded")


def test_io_error_outage_degrades_bounds_loss_and_recovers(tmp_path):
    """An outage spanning two consecutive saves (every attempt at step 8,
    then the degraded probe at step 12): compute continues, the skipped
    steps never exist on disk — a crash during the outage loses exactly
    back to step 4 — and the first healthy probe exits degraded mode."""
    adv, state = _model()
    ref = adv((jnp.copy(state[0]),), NT)
    faults.install("io-error@step=8,times=3;io-error@step=12")
    out = ckpt.run_segmented(
        adv, state, NT, tmp_path, every=EVERY,
        storage=_policy(sleep=lambda _: None), keep=8,
    )
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    # Loss bound: 8 and 12 are simply absent; 4 stayed valid throughout.
    assert ckpt.all_steps(tmp_path) == [4, 16]
    assert ckpt.latest_valid_step(tmp_path) == 16
    degraded = _events("ckpt.degraded")
    assert [d["reason"] for d in degraded] == ["io-error", "probe-failed"]
    assert degraded[0]["step"] == 8 and degraded[0]["last_valid_step"] == 4
    assert degraded[1]["step"] == 12 and degraded[1]["last_valid_step"] == 4
    recovered = _events("ckpt.recovered")
    assert len(recovered) == 1 and recovered[0]["step"] == 16
    assert recovered[0]["skipped"] == 2
    # Two retry events: the two defeated retry attempts at step 8.
    assert len(_events("ckpt.retry")) == 2


def test_degrade_off_raises_after_retries(tmp_path):
    adv, state = _model()
    faults.install("io-error@step=8,times=3")
    with pytest.raises(OSError):
        ckpt.run_segmented(
            adv, state, NT, tmp_path, every=EVERY,
            storage=_policy(degrade=False, sleep=lambda _: None),
        )
    # The failed attempt left no torn artifact behind.
    assert ckpt.all_steps(tmp_path) == [4]
    assert ckpt.latest_valid_step(tmp_path) == 4


def test_enospc_prunes_keep_list_then_save_lands(tmp_path):
    adv, state = _model()
    ckpt.run_segmented(adv, state, 8, tmp_path, every=EVERY, keep=8)
    assert ckpt.all_steps(tmp_path) == [4, 8]
    telemetry.clear_events()
    faults.install("enospc@step=12")
    _, like = _model()
    restored = ckpt.restore_state(tmp_path, 8, like)
    ckpt.run_segmented(adv, restored, NT, tmp_path, every=EVERY,
                       start_step=8, keep=8,
                       storage=_policy(sleep=lambda _: None))
    # Step 4 was sacrificed for space; the newest valid step survived,
    # and the retried save landed.
    assert ckpt.all_steps(tmp_path) == [8, 12, 16]
    prunes = _events("ckpt.enospc-prune")
    assert len(prunes) == 1 and prunes[0]["step"] == 12
    assert prunes[0]["pruned_steps"] == [4]
    assert not _events("ckpt.degraded")


def test_enospc_outage_with_nothing_to_prune_degrades(tmp_path):
    """ENOSPC with only the newest valid step on disk frees nothing —
    the save burns its retries and the run degrades instead of dying."""
    adv, state = _model(nt=20)
    faults.install("enospc@step=8,times=2;enospc@step=12")
    ckpt.run_segmented(adv, state, 20, tmp_path, every=EVERY, keep=8,
                       storage=_policy(retries=1, sleep=lambda _: None))
    # Outage covers saves 8 (both attempts) and the probe at 12; the
    # probe at 16 recovers and 20 saves normally.
    assert ckpt.all_steps(tmp_path) == [4, 16, 20]
    degraded = _events("ckpt.degraded")
    assert [d["reason"] for d in degraded] == ["io-error", "probe-failed"]
    assert _events("ckpt.recovered")[0]["skipped"] == 2
    prunes = _events("ckpt.enospc-prune")
    assert prunes and prunes[0]["pruned_steps"] == []


def test_io_slow_watchdog_degrades_but_keeps_the_saves(tmp_path):
    """A slow save is still a DURABLE save: the watchdog flips degraded
    mode (the operator must see the storage crawling) without losing the
    step; a fast probe exits it."""
    adv, state = _model()
    faults.install("io-slow=1.0@step=8;io-slow=1.0@step=12")
    ckpt.run_segmented(
        adv, state, NT, tmp_path, every=EVERY, keep=8,
        storage=_policy(slow_save_timeout_s=0.5, sleep=lambda _: None),
    )
    assert ckpt.all_steps(tmp_path) == [4, 8, 12, 16]  # nothing lost
    degraded = _events("ckpt.degraded")
    assert [d["reason"] for d in degraded] == ["io-slow", "io-slow"]
    assert degraded[0]["wall_s"] > 0.5
    recovered = _events("ckpt.recovered")
    assert len(recovered) == 1 and recovered[0]["step"] == 16


def test_save_state_stays_loud(tmp_path):
    """The one-shot API keeps the loud contract: retries, then raise —
    degraded skip-save-and-continue belongs to the segmented loop."""
    _, state = _model()
    faults.install("io-error@step=4,times=3")
    with pytest.raises(OSError):
        ckpt.save_state(tmp_path, 4, state,
                        storage=_policy(sleep=lambda _: None))
    faults.install("io-error@step=8")
    ckpt.save_state(tmp_path, 8, state,
                    storage=_policy(sleep=lambda _: None))
    assert ckpt.latest_valid_step(tmp_path) == 8


def test_restore_retries_transient_io_error(tmp_path):
    adv, state = _model()
    ref = np.asarray(state[0])
    ckpt.save_state(tmp_path, 4, state)
    telemetry.clear_events()
    faults.install("io-error@step=4,at=restore")
    out = ckpt.restore_state(tmp_path, 4, like=None)
    np.testing.assert_array_equal(np.asarray(out[0]), ref)
    retries = _events("ckpt.retry")
    assert len(retries) == 1 and retries[0]["op"] == "restore"


def test_storage_policy_from_env(monkeypatch):
    monkeypatch.setenv("RMT_CKPT_RETRIES", "5")
    monkeypatch.setenv("RMT_CKPT_BACKOFF_S", "0.125")
    monkeypatch.setenv("RMT_CKPT_SLOW_S", "2.5")
    monkeypatch.setenv("RMT_CKPT_DEGRADE", "0")
    monkeypatch.setenv("RMT_CKPT_PROBE_EVERY", "3")
    p = ckpt.StoragePolicy.from_env()
    assert (p.retries, p.backoff_s, p.slow_save_timeout_s,
            p.degrade, p.probe_every) == (5, 0.125, 2.5, False, 3)
    monkeypatch.setenv("RMT_CKPT_RETRIES", "garbage")
    monkeypatch.delenv("RMT_CKPT_DEGRADE")
    p = ckpt.StoragePolicy.from_env()
    assert p.retries == ckpt.DEFAULT_SAVE_RETRIES and p.degrade is True


def test_save_wall_p90_interpolates():
    assert ckpt.save_wall_p90() is None
    ckpt._SAVE_WALLS.append(2.0)
    assert ckpt.save_wall_p90() == 2.0
    ckpt._SAVE_WALLS.extend([1.0] * 9)
    walls = sorted(ckpt._SAVE_WALLS)
    pos = 0.9 * (len(walls) - 1)
    lo = int(pos)
    expect = walls[lo] * (1 - (pos - lo)) + walls[lo + 1] * (pos - lo)
    assert ckpt.save_wall_p90() == pytest.approx(expect)


# ---------------------------------------------------------------------------
# Preemption: the grace-deadline machinery
# ---------------------------------------------------------------------------


def test_budget_allows_save_table():
    # No preemption pending: a normal save, always allowed.
    preempt.reset()
    assert preempt.budget_allows_save(0.5) is True
    # Pending with comfortable grace: p90 * safety fits.
    preempt.request(grace_s=60.0)
    assert preempt.budget_allows_save(1.0) is True
    assert preempt.remaining_grace_s() == pytest.approx(60.0, abs=2.0)
    # No history: only a grace above the floor gambles on a save.
    assert preempt.budget_allows_save(None) is True
    preempt.reset()
    preempt.request(grace_s=preempt.NO_HISTORY_FLOOR_S / 2)
    assert preempt.budget_allows_save(None) is False
    # Tight grace vs measured p90: skip.
    preempt.reset()
    preempt.request(grace_s=1.0)
    assert preempt.budget_allows_save(5.0) is False


def test_request_latch_and_notice():
    assert preempt.requested() is False
    assert preempt.note_noticed() is False
    preempt.request(grace_s=30.0)
    first_deadline = preempt.remaining_grace_s()
    preempt.request(grace_s=500.0)  # first request wins
    assert preempt.remaining_grace_s() <= first_deadline
    assert preempt.note_noticed() is True
    assert preempt.note_noticed() is False  # latched
    preempt.reset()
    assert preempt.requested() is False


def test_install_from_env_and_sigterm_handler(monkeypatch):
    monkeypatch.delenv(preempt.ENV_GRACE, raising=False)
    assert preempt.install_from_env() is False
    monkeypatch.setenv(preempt.ENV_GRACE, "not-a-number")
    assert preempt.install_from_env() is False
    monkeypatch.setenv(preempt.ENV_GRACE, "45.5")
    assert preempt.install_from_env() is True
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not preempt.requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert preempt.requested()
        rem = preempt.remaining_grace_s()
        assert rem is not None and 40.0 < rem <= 45.5
    finally:
        preempt.uninstall()
    assert preempt.requested() is False


def test_forwarder_relays_sigterm_to_live_ranks():
    sent = []

    class _Proc:
        def __init__(self, live=True):
            self.live = live

        def poll(self):
            return None if self.live else 0

        def send_signal(self, sig):
            sent.append(sig)

    restore = preempt.install_forwarder([_Proc(), _Proc(live=False),
                                         _Proc()])
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not preempt.requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        # The parent stamped its own notice AND relayed to the live ranks.
        assert preempt.requested()
        assert sent == [signal.SIGTERM, signal.SIGTERM]
    finally:
        restore()
        preempt.reset()


def test_preempted_exit_is_never_retryable():
    exc = preempt.Preempted(step=8, saved=True)
    assert exc.code == preempt.RC_PREEMPTED == 75
    assert isinstance(exc, SystemExit)
    assert default_retryable(exc) is False  # run_supervised resumes, not retries


# ---------------------------------------------------------------------------
# Preemption in the segmented loop
# ---------------------------------------------------------------------------


def test_preempt_with_grace_lands_emergency_save(tmp_path):
    adv, state = _model()
    preempt.request(grace_s=60.0)
    with pytest.raises(preempt.Preempted) as ei:
        ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    assert ei.value.saved is True and ei.value.step == EVERY
    assert ckpt.latest_valid_step(tmp_path) == EVERY
    names = [r["name"] for r in _events()]
    assert "preempt.noticed" in names and "preempt.save" in names
    save = _events("preempt.save")[0]
    assert save["step"] == EVERY and save["remaining_grace_s"] <= 60.0


def test_preempt_without_grace_skips_save_no_torn_artifact(tmp_path):
    adv, state = _model()
    ckpt.run_segmented(adv, state, 8, tmp_path, every=EVERY)
    telemetry.clear_events()
    _, like = _model()
    restored = ckpt.restore_state(tmp_path, 8, like)
    preempt.request(grace_s=0.0)
    with pytest.raises(preempt.Preempted) as ei:
        ckpt.run_segmented(adv, restored, NT, tmp_path, every=EVERY,
                           start_step=8)
    # The save was skipped OUTRIGHT: the resume point is the prior valid
    # step and the boundary that skipped left nothing on disk at all.
    assert ei.value.saved is False and ei.value.step == 8
    assert ckpt.all_steps(tmp_path) == [4, 8]
    assert ckpt.latest_valid_step(tmp_path) == 8
    skip = _events("preempt.skip-save")
    assert len(skip) == 1 and skip[0]["last_valid_step"] == 8
    assert not _events("preempt.save")


def test_preempt_noticed_after_save_exits_from_fresh_boundary(tmp_path,
                                                              monkeypatch):
    """A notice landing DURING the boundary save: the just-published
    step is the resume point — the loop exits instead of betting another
    whole segment against the deadline."""
    adv, state = _model()
    orig = ckpt._guarded_save

    def hooked(*a, **kw):
        durable = orig(*a, **kw)
        if not preempt.requested():
            preempt.request(grace_s=60.0)
        return durable

    monkeypatch.setattr(ckpt, "_guarded_save", hooked)
    with pytest.raises(preempt.Preempted) as ei:
        ckpt.run_segmented(adv, state, NT, tmp_path, every=EVERY)
    assert ei.value.step == EVERY and ei.value.saved is True
    stop = _events("preempt.stop")
    assert len(stop) == 1 and stop[0]["saved"] is True


# ---------------------------------------------------------------------------
# Gloo-real drills: preemption and the storage outage, 2 ranks
# ---------------------------------------------------------------------------

DRILL = dict(nx=16, ny=16, nt=16, every=4)


def _drill_argv(ck, keep=8, delay=0.0):
    argv = [
        str(ROOT / "tests" / "elastic_worker.py"),
        "--nx", str(DRILL["nx"]), "--ny", str(DRILL["ny"]),
        "--nt", str(DRILL["nt"]), "--every", str(DRILL["every"]),
        "--keep", str(keep),
        "--dir", str(ck),
    ]
    if delay:
        argv += ["--segment-delay-s", str(delay)]
    return argv


def _reference_2rank(ck, start):
    """The uninterrupted 2-rank twin: restore the drill's own checkpoint
    at `start` onto 2 devices and advance to nt on the (2, 1) mesh."""
    from rocm_mpi_tpu.parallel import mesh as pmesh

    devices = jax.devices()[:2]
    state = ckpt.restore_state(ck, start, like=None, devices=devices)
    cfg = DiffusionConfig(
        global_shape=(DRILL["nx"], DRILL["ny"]), lengths=(10.0, 10.0),
        nt=DRILL["nt"], warmup=0, dtype="f64", dims=(2, 1),
    )
    grid = pmesh.init_global_grid(
        DRILL["nx"], DRILL["ny"], dims=(2, 1), devices=devices
    )
    model = HeatDiffusion(cfg, grid=grid)
    _, Cp = model.init_state()
    advance = model.advance_fn("perf")
    return advance(state[0], Cp, DRILL["nt"] - start)


def _sigterm_when_step_durable(ck, min_step, procs_box, fired):
    """Drill helper: deliver SIGTERM to every rank once the checkpoint
    dir holds a valid step >= min_step (the preemption must interrupt a
    run that is provably mid-flight, past its first durable boundary).

    The signal is delayed a beat past the durability detection: the
    manifest lands a few ms before the ranks run their post-save
    preemption check, so firing the instant the step verifies races
    that check PER RANK — one rank can exit from the just-saved
    boundary while its peer runs another segment and strands in a
    collective (the skew fallback resilience/preempt.py documents).
    The drill wants the deterministic common case — a notice landing
    mid-segment, every rank deciding at the SAME next boundary — and
    the workers' --segment-delay-s stretch guarantees they are still
    inside the next segment when the delayed signal arrives."""

    def _watch():
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                step = ckpt.latest_valid_step(ck)
            except Exception:  # noqa: BLE001 — sidecar mid-write
                step = None
            if step is not None and step >= min_step:
                time.sleep(0.15)  # into the segment stretch (docstring)
                for p in procs_box[0]:
                    try:
                        if p.poll() is None:
                            p.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
                fired.set()
                return
            time.sleep(0.05)

    t = threading.Thread(target=_watch, daemon=True)
    t.start()
    return t


@pytest.mark.parametrize("grace_s,saved", [(60.0, True), (0.0, False)])
def test_preempt_drill_resumes_under_run_elastic(tmp_path, grace_s, saved):
    """THE preemption acceptance drill: SIGTERM with sufficient grace
    lands an emergency checkpoint; with insufficient grace the save is
    skipped (no torn artifact anywhere on disk). Both exits are
    classified RESUME — run_elastic relaunches on the same topology and
    the final checkpoint is bitwise-equal to the uninterrupted twin."""
    ck = tmp_path / "ck"
    tdir = tmp_path / "telemetry"
    procs_box = [[]]
    fired = threading.Event()
    armed = []

    def on_spawn(procs):
        # Arm the SIGTERM thread for the FIRST launch only: the
        # supervised relaunch must run to completion undisturbed.
        procs_box[0] = procs
        if not armed:
            armed.append(True)
            _sigterm_when_step_durable(ck, 4, procs_box, fired)

    report = run_elastic(
        _drill_argv(ck, delay=0.75), 2,
        checkpoint_dir=ck,
        global_shape=(DRILL["nx"], DRILL["ny"]),
        sidecar_dir=tmp_path,
        telemetry_dir=tdir,
        preempt_grace_s=grace_s,
        on_spawn=on_spawn,
        timeout=120,
        init_timeout_s=60,
        heartbeat_s=2.0,
        peer_grace_s=6.0,
        vanish_grace_s=8.0,
    )
    assert fired.is_set(), "the drill never delivered its SIGTERM"
    # Launch 0 was preempted — rc 75 on every rank, judged a RESUME
    # (not a failure: no shrink, no give-up), then the relaunch finished.
    assert report.resumes == 1, report.launches
    assert report.shrinks == 0 and report.grows == 0
    assert report.launches[0]["status"] == "preempted"
    assert report.launches[0]["returncodes"] == [75, 75]
    assert report.launches[1]["ok"]
    names = [e["name"] for e in report.events]
    assert names == ["elastic.launch", "elastic.resume",
                     "elastic.launch", "elastic.complete"]
    resume_step = report.events[1]["resume_step"]
    assert resume_step is not None and resume_step >= 4
    # No torn artifact: every step dir on disk verifies.
    for step in ckpt.all_steps(ck):
        ok, reason = ckpt.verify_step(ck, step)
        assert ok, (step, reason)
    assert ckpt.latest_valid_step(ck) == DRILL["nt"]
    # The ranks' own decision trail: an emergency save with grace, a
    # skip without — and the archived stream passes the schema gate.
    stream = (tdir / "telemetry-rank0.jsonl").read_text()
    if saved:
        assert '"preempt.save"' in stream
    else:
        assert '"preempt.skip-save"' in stream
        assert '"preempt.save"' not in stream
    assert regress.check_schema([str(tdir / "telemetry-rank0.jsonl")]) == []
    # Bitwise: final state == the uninterrupted 2-rank continuation from
    # the step the resume actually restored.
    final = ckpt.restore_state(ck, DRILL["nt"], like=None,
                               devices=jax.devices()[:2])
    ref = _reference_2rank(ck, resume_step)
    np.testing.assert_array_equal(np.asarray(final[0]), np.asarray(ref))


STORAGE_SPECS = {
    "io-error": "io-error@step=8,times=2;io-error@step=12",
    "io-slow": "io-slow=1.2@step=8;io-slow=1.2@step=12",
    "enospc": "enospc@step=8,times=2;enospc@step=12",
}


@pytest.mark.parametrize("kind", sorted(STORAGE_SPECS))
def test_storage_outage_drill_gloo(tmp_path, monkeypatch, kind):
    """THE storage acceptance drill: a 2-rank gloo run with an injected
    outage spanning two consecutive saves stays ALIVE in degraded mode
    (every rank skips the same saves — no rank enters a save barrier its
    peer refused), recovers at the first healthy boundary, and the loss
    window during the outage was bounded by the last pre-outage step."""
    from rocm_mpi_tpu.parallel.launcher import spawn_ranks

    monkeypatch.setenv("RMT_CKPT_RETRIES", "1")
    monkeypatch.setenv("RMT_CKPT_BACKOFF_S", "0.05")
    if kind == "io-slow":
        # Watchdog threshold well above a natural 2-rank orbax save wall
        # but well below the injected stall: only the drill trips it.
        monkeypatch.setenv("RMT_CKPT_SLOW_S", "0.6")
    ck = tmp_path / "ck"
    tdir = tmp_path / "telemetry"
    hdir = tmp_path / "health"
    results = spawn_ranks(
        _drill_argv(ck), nprocs=2,
        inject_fault=STORAGE_SPECS[kind],
        telemetry_dir=tdir,
        health_dir=hdir,
        timeout=120,
        init_timeout_s=60,
        heartbeat_s=1.0,
        peer_grace_s=6.0,
    )
    for pid, (p, (out, err)) in enumerate(results):
        assert p.returncode == 0, (pid, err[-800:])
        assert "ELASTIC_WORKER_DONE" in out
    steps = ckpt.all_steps(ck)
    if kind == "io-slow":
        # Slow saves are still durable saves: nothing lost.
        assert steps == [4, 8, 12, 16]
    else:
        # The outage steps never existed; the pre-outage step bounds the
        # loss window a crash during the outage would have paid.
        assert steps == [4, 16]
    assert ckpt.latest_valid_step(ck) == DRILL["nt"]
    stream = (tdir / "telemetry-rank0.jsonl").read_text()
    assert '"ckpt.degraded"' in stream and '"ckpt.recovered"' in stream
    if kind == "io-error":
        assert '"ckpt.retry"' in stream
    if kind == "enospc":
        assert '"ckpt.enospc-prune"' in stream
    assert regress.check_schema([str(tdir / "telemetry-rank0.jsonl")]) == []
    # The monitor-side view: the heartbeat counters say the outage came
    # and went — recovered, with the skip count preserved.
    beats, _ = health.load_heartbeats(hdir)
    status = health.storage_status(beats)
    if kind == "io-slow":
        assert status is None or status["degraded"] is False
    else:
        assert status is not None and status["degraded"] is False
        assert status["skipped"] >= 2
        assert "recovered" in health.format_storage_status(status)


def test_storage_and_monitor_schema_fixtures(tmp_path):
    """The new record families, round-tripped through the schema gate:
    a grow record without its rank counts fails, preempt/ckpt event
    records without their anchors fail, well-formed ones pass."""
    good = tmp_path / "elastic.jsonl"
    health.append_elastic_event(tmp_path, "elastic.grow", old_nprocs=1,
                                new_nprocs=2, old_mesh=[1, 1],
                                new_mesh=[2, 1], resume_step=8,
                                reason="device-budget")
    assert regress.check_schema([str(good)]) == []
    bad = tmp_path / "bad-elastic.jsonl"
    bad.write_text(json.dumps({
        "schema": health.ELASTIC_SCHEMA, "v": 1, "kind": "event",
        "name": "elastic.grow", "t": 1.0,
    }) + "\n")
    problems = regress.check_schema([str(bad)])
    assert any("old_nprocs" in p for p in problems)
    events = tmp_path / "events.jsonl"
    events.write_text("\n".join([
        json.dumps({"v": 2, "kind": "event", "name": "preempt.noticed",
                    "t": 1.0, "t_mono": 1.0, "rank": 0, "step": 8,
                    "remaining_grace_s": 20.0}),
        json.dumps({"v": 2, "kind": "event", "name": "ckpt.degraded",
                    "t": 1.0, "t_mono": 1.0, "rank": 0, "step": 8,
                    "reason": "io-error"}),
    ]) + "\n")
    assert regress.check_schema([str(events)]) == []
    torn = tmp_path / "torn-events.jsonl"
    torn.write_text("\n".join([
        json.dumps({"v": 2, "kind": "event", "name": "preempt.save",
                    "t": 1.0}),
        json.dumps({"v": 2, "kind": "event", "name": "ckpt.degraded",
                    "t": 1.0, "step": 8}),
    ]) + "\n")
    problems = regress.check_schema([str(torn)])
    assert any("preempt.save event missing int step" in p
               for p in problems)
    assert any("ckpt.degraded event missing reason" in p
               for p in problems)
