"""Shallow-water workload (models.swe): numpy oracle, EXACT mass
conservation, algebraic time reversal, cross-variant and sharding
equivalence — the correctness strategy of the diffusion/wave suites
applied to the third workload, whose coupled ndim+1-field state is what
exercises the pytree-state paths of parallel.overlap and
parallel.deep_halo."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater
from rocm_mpi_tpu.ops.swe_kernels import swe_coeffs


def _cfg(shape=(24, 20), dims=(1, 1), dtype="f64", nt=40, warmup=8):
    return SWEConfig(
        global_shape=shape,
        lengths=tuple(10.0 for _ in shape),
        nt=nt,
        warmup=warmup,
        dtype=dtype,
        dims=dims,
    )


def _numpy_fb(h, us, dt, spacing, H, g, n):
    """Transparent numpy oracle of the forward-backward C-grid update:
    backward-difference divergence into h, then forward-difference
    gradient of the NEW h into each velocity, with the high wall face
    along each axis held at 0 and zero beyond-domain values."""
    h = np.array(h, np.float64)
    us = [np.array(u, np.float64) for u in us]
    ndim = h.ndim
    for _ in range(n):
        div = np.zeros_like(h)
        for a, u in enumerate(us):
            um = np.zeros_like(u)  # u shifted +1 along a, zero-filled
            lo = tuple(
                slice(1, None) if ax == a else slice(None)
                for ax in range(ndim)
            )
            hi = tuple(
                slice(None, -1) if ax == a else slice(None)
                for ax in range(ndim)
            )
            um[lo] = u[hi]
            div += (u - um) * (dt * H / spacing[a])
        h = h - div
        for a in range(ndim):
            hp = np.zeros_like(h)  # h shifted −1 along a, zero-filled
            lo = tuple(
                slice(None, -1) if ax == a else slice(None)
                for ax in range(ndim)
            )
            hi = tuple(
                slice(1, None) if ax == a else slice(None)
                for ax in range(ndim)
            )
            hp[lo] = h[hi]
            us[a] = us[a] - (dt * g / spacing[a]) * (hp - h)
            # hold the high wall face
            wall = tuple(
                slice(-1, None) if ax == a else slice(None)
                for ax in range(ndim)
            )
            us[a][wall] = 0.0
    return h, us


def _advance(model, variant, n):
    h, us = model.init_state()
    Mus = model.face_masks()
    return model.advance_fn(variant)(h, us, Mus, n)


def test_swe_matches_numpy_oracle():
    cfg = _cfg()
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    h0, us0 = model.init_state()
    ref_h, ref_us = _numpy_fb(
        h0, us0, cfg.dt, cfg.spacing, cfg.H0, cfg.g, 25
    )
    got_h, got_us = model.advance_fn("ap")(h0, us0, model.face_masks(), 25)
    np.testing.assert_allclose(np.asarray(got_h), ref_h, rtol=1e-12)
    for got_u, ref_u in zip(got_us, ref_us):
        np.testing.assert_allclose(
            np.asarray(got_u), ref_u, rtol=1e-12, atol=1e-15
        )


def test_swe_mass_exactly_conserved():
    # The closed-basin divergence telescopes to wall−wall = 0, so sum(h)
    # is invariant to fp rounding — the workload's exact invariant.
    cfg = _cfg(nt=200, warmup=0)
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    h0, us0 = model.init_state()
    mass0 = float(jnp.sum(h0, dtype=jnp.float64))
    got_h, _ = model.advance_fn("ap")(h0, us0, model.face_masks(), 200)
    mass = float(jnp.sum(got_h, dtype=jnp.float64))
    assert abs(mass - mass0) <= 1e-13 * abs(mass0)


def test_swe_mass_conserved_sharded_all_variants():
    for variant in ("ap", "perf", "hide"):
        cfg = _cfg(shape=(32, 32), dims=(2, 4), nt=64, warmup=0)
        model = ShallowWater(cfg)
        h0, us0 = model.init_state()
        mass0 = float(jnp.sum(h0, dtype=jnp.float64))
        got_h, _ = model.advance_fn(variant)(
            h0, us0, model.face_masks(), 64
        )
        mass = float(jnp.sum(got_h, dtype=jnp.float64))
        assert abs(mass - mass0) <= 1e-13 * abs(mass0), variant


def test_swe_wall_faces_stay_zero():
    cfg = _cfg()
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    _, got_us = _advance(model, "ap", 30)
    for a, u in enumerate(got_us):
        wall = tuple(
            slice(-1, None) if ax == a else slice(None)
            for ax in range(cfg.ndim)
        )
        np.testing.assert_array_equal(np.asarray(u)[wall], 0.0)


def test_swe_time_reversal_algebraic():
    # The forward-backward map has a closed-form inverse (inverse
    # sub-steps in reverse order); running it returns the IC at rounding
    # level — the symplectic-structure analog of the wave's leapfrog
    # reversal test.
    cfg = _cfg(nt=60)
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    h0, us0 = model.init_state()
    Mus = model.face_masks()
    n = 40
    h, us = model.advance_fn("ap")(
        jnp.copy(h0), tuple(map(jnp.copy, us0)), Mus, n
    )
    cH, cg = swe_coeffs(cfg.dt, cfg.spacing, cfg.H0, cfg.g)

    def inverse_step(h, us):
        us = tuple(
            u + cg[a] * Mus[a] * (jnp.roll(h, -1, a) - h)
            for a, u in enumerate(us)
        )
        div = sum(
            cH[a] * (u - jnp.roll(u, 1, a)) for a, u in enumerate(us)
        )
        return h + div, us

    for _ in range(n):
        h, us = inverse_step(h, us)
    np.testing.assert_allclose(
        np.asarray(h), np.asarray(h0), rtol=1e-11, atol=1e-13
    )
    for u, u0 in zip(us, us0):
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(u0), atol=1e-13
        )


@pytest.mark.parametrize("dtype", ["f64", "f32"])
def test_swe_perf_matches_ap(dtype):
    tol = 1e-12 if dtype == "f64" else 2e-6
    cfg = _cfg(dtype=dtype)
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    ap_h, ap_us = _advance(model, "ap", 30)
    pf_h, pf_us = _advance(model, "perf", 30)
    np.testing.assert_allclose(
        np.asarray(pf_h), np.asarray(ap_h), rtol=tol, atol=tol
    )
    for pu, au in zip(pf_us, ap_us):
        np.testing.assert_allclose(
            np.asarray(pu), np.asarray(au), rtol=tol, atol=tol
        )


def test_swe_sharded_matches_single_device():
    single = ShallowWater(_cfg(shape=(32, 32)), devices=jax.devices()[:1])
    truth_h, truth_us = _advance(single, "perf", 30)
    for dims in [(2, 2), (4, 2), (1, 8)]:
        model = ShallowWater(_cfg(shape=(32, 32), dims=dims))
        got_h, got_us = _advance(model, "perf", 30)
        np.testing.assert_allclose(
            np.asarray(got_h), np.asarray(truth_h), rtol=1e-12, atol=1e-14
        )
        for gu, tu in zip(got_us, truth_us):
            np.testing.assert_allclose(
                np.asarray(gu), np.asarray(tu), rtol=1e-12, atol=1e-14
            )


def test_swe_hide_matches_perf_sharded():
    for dims in [(2, 2), (2, 4)]:
        cfg = _cfg(shape=(32, 32), dims=dims)
        model = ShallowWater(cfg)
        pf_h, pf_us = _advance(model, "perf", 30)
        hd_h, hd_us = _advance(model, "hide", 30)
        np.testing.assert_allclose(
            np.asarray(hd_h), np.asarray(pf_h), rtol=1e-12, atol=1e-14
        )
        for hu, pu in zip(hd_us, pf_us):
            np.testing.assert_allclose(
                np.asarray(hu), np.asarray(pu), rtol=1e-12, atol=1e-14
            )


def test_swe_hide_3d_matches_perf():
    cfg = _cfg(shape=(12, 12, 12), dims=(2, 2, 2), nt=12, warmup=0)
    model = ShallowWater(cfg)
    pf_h, _ = _advance(model, "perf", 10)
    hd_h, _ = _advance(model, "hide", 10)
    np.testing.assert_allclose(
        np.asarray(hd_h), np.asarray(pf_h), rtol=1e-12, atol=1e-14
    )


def test_swe_deep_sweep_matches_per_step():
    single = ShallowWater(_cfg(shape=(32, 32)), devices=jax.devices()[:1])
    truth_h, truth_us = _advance(single, "ap", 32)
    for dims, k in [((2, 2), 4), ((2, 4), 8), ((1, 1), 4)]:
        model = ShallowWater(_cfg(shape=(32, 32), dims=dims))
        r = model.run_deep(nt=32, warmup=0, block_steps=k)
        np.testing.assert_allclose(
            np.asarray(r.h), np.asarray(truth_h), rtol=1e-12, atol=1e-14
        )
        for gu, tu in zip(r.us, truth_us):
            np.testing.assert_allclose(
                np.asarray(gu), np.asarray(tu), rtol=1e-12, atol=1e-14
            )


def test_swe_run_vmem_resident_matches_per_step():
    single = ShallowWater(_cfg(shape=(32, 32)), devices=jax.devices()[:1])
    truth_h, _ = _advance(single, "ap", 32)
    r = single.run_vmem_resident(nt=32, warmup=0, chunk=8)
    np.testing.assert_allclose(
        np.asarray(r.h), np.asarray(truth_h), rtol=1e-12, atol=1e-14
    )


def test_swe_explicit_oversized_deep_depth_raises():
    model = ShallowWater(_cfg(shape=(32, 32), dims=(2, 4)))
    with pytest.raises(ValueError, match="exceeds a local shard extent"):
        model.run_deep(nt=64, warmup=0, block_steps=64)


def test_swe_hide_single_device_routes_to_perf():
    cfg = _cfg()
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    pf_h, _ = _advance(model, "perf", 20)
    hd_h, _ = _advance(model, "hide", 20)
    # Bit-identical: the single-device hide IS the perf program.
    np.testing.assert_array_equal(np.asarray(hd_h), np.asarray(pf_h))


def test_swe_run_reports_metrics():
    model = ShallowWater(_cfg(nt=16, warmup=4), devices=jax.devices()[:1])
    r = model.run("perf")
    assert r.wtime > 0 and r.t_eff > 0 and r.gpts > 0
    assert r.nt == 16 and r.warmup == 4


def test_swe_app_runs(tmp_path):
    import subprocess
    import sys

    out = tmp_path / "h.npy"
    proc = subprocess.run(
        [
            sys.executable, "apps/swe_2d.py", "--cpu-devices", "4",
            "--nx", "32", "--ny", "32", "--nt", "24", "--warmup", "4",
            "--save-field", str(out),
        ],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr
    assert "mass drift" in proc.stdout
    h = np.load(out)
    assert h.shape == (32, 32)


def test_swe_bf16_rounding_is_per_kernel_not_per_step():
    """Storage-only bf16 holds for the SWE multi-step kernel too: the
    traced kernel contains exactly 2·(ndim+1)+ndim dtype conversions for
    bf16 operands — each state field in and out, each mask in —
    INDEPENDENT of the unroll (the diffusion mechanical pin,
    test_bf16_error.py, applied to the coupled workload)."""
    from rocm_mpi_tpu.ops.swe_kernels import swe_multi_step_masked

    h = jnp.zeros((32, 32), jnp.bfloat16)
    us = (jnp.zeros((32, 32), jnp.bfloat16),) * 2
    Mus = (jnp.ones((32, 32), jnp.bfloat16),) * 2
    cH = cg = (1e-3, 1e-3)
    counts = {
        n: str(
            jax.make_jaxpr(
                lambda h, us, Mus: swe_multi_step_masked(
                    h, us, Mus, cH, cg, n
                )
            )(h, us, Mus)
        ).count("convert_element_type")
        for n in (4, 16)
    }
    # 3 state in + 2 masks in + 3 state out = 8, whatever the unroll.
    assert counts[4] == counts[16] == 8, counts
