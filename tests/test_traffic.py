"""The compiled HBM-traffic gate (rocm_mpi_tpu/perf, docs/PERF.md) and
the parity of the reworked traffic-minimal paths.

Two halves, matching the gate's two failure modes:

* the AUDIT must be right: the splice-free halo/overlap/scan/deep paths
  must still produce the physics — pinned against the HostStagedStepper
  transport oracle (diffusion) and the GSPMD ap oracles (wave, SWE);
* the GATE must have teeth: `python -m rocm_mpi_tpu.perf` exits 0 on the
  shipped drivers and demonstrably exits 1 when the pre-rework
  concatenate splice is measured through it (the known-waste fixture).
"""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_gate(*extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The gate pins its own device count/x64; a test-runner inherited
    # XLA_FLAGS would fight set_cpu_device_count's append.
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "rocm_mpi_tpu.perf", *extra],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600,
    )


class TestGateCLI:
    def test_gate_passes_on_shipped_drivers(self):
        # THE acceptance drill: the traffic gate over the real shard /
        # overlap / deep-k programs on the committed 2-rank CPU geometry.
        proc = _run_gate()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "shard" in proc.stdout and "overlap" in proc.stdout
        assert "OVER BUDGET" not in proc.stdout

    def test_gate_catches_concatenate_splice_waste(self):
        # Regression-test the gate ITSELF: re-introduce the pre-rework
        # concatenate-based splice (as the built-in fixture) and the gate
        # must exit 1 — proof it detects the staging-copy class, not just
        # that budgets are loose.
        proc = _run_gate("--include-waste-fixture")
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "concat-splice(fixture)" in proc.stdout
        assert "OVER BUDGET" in proc.stdout
        # The shipped drivers still pass inside the same run.
        for line in proc.stdout.splitlines():
            if line.strip().startswith(("shard ", "overlap ", "deep")):
                assert line.rstrip().endswith("ok"), line

    def test_gate_json_rows_parse(self):
        proc = _run_gate("--json")
        assert proc.returncode == 0, proc.stderr
        rows = [json.loads(ln) for ln in proc.stdout.splitlines() if ln]
        assert {r["metric"] for r in rows} >= {"traffic shard",
                                               "traffic overlap"}
        traffic_rows = [r for r in rows
                        if r["metric"].startswith("traffic ")]
        for r in traffic_rows:
            assert r["ok"] is True
            assert r["bytes"] > 0 and r["ideal"] > 0
            assert r["wire"] == r["wire_ideal"]  # exact wire accounting
        # The wire-bytes ladder rows ride the same JSON stream
        # (docs/PERF.md "Wire precision").
        wire_rows = {r["metric"]: r for r in rows
                     if r["metric"].startswith("wire ")}
        assert {"wire f32", "wire bf16", "wire int8",
                "wire int8_delta"} <= set(wire_rows)
        for r in wire_rows.values():
            assert r["ok"] is True
            assert r["bytes"] == r["mode_ideal"]  # exact accounting
        assert wire_rows["wire bf16"]["fraction"] <= 0.55


class TestTrafficModel:
    def test_hlo_bytes_accessed_rules(self):
        from rocm_mpi_tpu.perf.traffic import hlo_bytes_accessed

        text = """
HloModule m
ENTRY %main (p0: f64[4,4]) -> f64[4,4] {
  %p0 = f64[4,4]{1,0} parameter(0)
  %c = f64[] constant(1)
  %b = f64[4,4]{1,0} broadcast(f64[] %c), dimensions={}
  %add = f64[4,4]{1,0} add(f64[4,4]{1,0} %p0, f64[4,4]{1,0} %b)
  %s = f64[1,4]{1,0} slice(f64[4,4]{1,0} %add), slice={[0:1], [0:4]}
  ROOT %dus = f64[4,4]{1,0} dynamic-update-slice(f64[4,4]{1,0} %add, f64[1,4]{1,0} %s, s64[] %c, s64[] %c)
}
"""
        got = hlo_bytes_accessed(text)
        # broadcast: 8 + 128; add: 128+128+128; slice: 2*32; dus: 2*32
        assert got == (8 + 128) + 3 * 128 + 64 + 64

    def test_hlo_wire_bytes_counts_collective_sends(self):
        from rocm_mpi_tpu.perf.traffic import hlo_wire_bytes

        text = """
HloModule m
ENTRY %main (p0: f64[2,8]) -> f64[2,8] {
  %p0 = f64[2,8]{1,0} parameter(0)
  %cp = f64[2,8]{1,0} collective-permute(f64[2,8]{1,0} %p0), channel_id=1, source_target_pairs={{0,1}}
  ROOT %cp2 = f64[2,8]{1,0} collective-permute(f64[2,8]{1,0} %cp), channel_id=2, source_target_pairs={{1,0}}
}
"""
        assert hlo_wire_bytes(text) == 2 * 2 * 8 * 8

    def test_budgets_file_is_committed_and_sane(self):
        from rocm_mpi_tpu.perf.traffic import load_budgets

        doc = load_budgets()
        assert doc["budgets"].keys() >= {"shard", "overlap", "deep"}
        # The acceptance pin: the fused shard step's committed budget
        # itself sits within 1.5x of the analytic ideal.
        assert doc["budgets"]["shard"] <= 1.5
        geo = doc["geometry"]
        assert geo["dims"] == [2, 1] and geo["local"] >= 16

    def test_audit_emits_traffic_annotations(self, tmp_path):
        # step.traffic facts land in the telemetry stream when enabled.
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.perf.traffic import audit_variants

        telemetry.configure(enabled=True, directory=str(tmp_path), rank=0)
        try:
            rows = audit_variants(local=16, deep_k=4)
            recs = telemetry.records(kind="trace", name="step.traffic")
            assert {r["attrs"]["variant"] for r in recs} >= {
                "shard", "overlap", "deep4"
            }
        finally:
            telemetry.configure(enabled=False)
            telemetry.clear()
        assert all(r.measured_bytes > 0 for r in rows)


class TestReworkedPathParity:
    """The splice-free paths against the transport-free oracles."""

    def _diffusion(self, **kw):
        from rocm_mpi_tpu.config import DiffusionConfig
        from rocm_mpi_tpu.models import HeatDiffusion

        kw.setdefault("global_shape", (32, 32))
        kw.setdefault("lengths", (10.0, 10.0))
        kw.setdefault("nt", 20)
        kw.setdefault("warmup", 4)
        kw.setdefault("dims", (2, 2))
        kw.setdefault("b_width", (4, 4))
        return HeatDiffusion(DiffusionConfig(**kw))

    def test_diffusion_paths_match_host_staged_oracle(self):
        # The IGG_ROCMAWARE_MPI=0 analog as ground truth: the reworked
        # DUS halo, the DUS-spliced overlap (Cm contract, f64 jnp), the
        # scan driver, and a deep sweep must all land on the pure-numpy
        # HostStagedStepper trajectory on a 2x2 mesh.
        oracle = self._diffusion(halo_transport="host").run(variant="shard")
        ref = np.asarray(oracle.T)

        m = self._diffusion()
        for label, r in (
            ("shard/step", m.run(variant="shard")),
            ("shard/scan", m.run(variant="shard", driver="scan")),
            ("hide/step", m.run(variant="hide")),
            ("hide/scan", m.run(variant="hide", driver="scan")),
            ("deep4", m.run_deep(block_steps=4)),
        ):
            np.testing.assert_allclose(
                np.asarray(r.T), ref, rtol=1e-12, atol=1e-14,
                err_msg=f"{label} diverged from the host-staged oracle",
            )

    def test_scan_driver_bitwise_equals_step_driver(self):
        # Same step program, same order — the drivers must agree BITWISE
        # on every workload (the scan driver changes scheduling and
        # allocation, never values).
        m = self._diffusion()
        r_step = m.run(variant="shard")
        r_scan = m.run(variant="shard", driver="scan")
        np.testing.assert_array_equal(
            np.asarray(r_step.T), np.asarray(r_scan.T)
        )

        from rocm_mpi_tpu.models import (
            AcousticWave,
            ShallowWater,
            SWEConfig,
            WaveConfig,
        )

        w = AcousticWave(WaveConfig(
            global_shape=(32, 32), lengths=(10.0, 10.0), nt=16, warmup=4,
            dims=(2, 2),
        ))
        np.testing.assert_array_equal(
            np.asarray(w.run(variant="hide").U),
            np.asarray(w.run(variant="hide", driver="scan").U),
        )

        s = ShallowWater(SWEConfig(
            global_shape=(32, 32), lengths=(10.0, 10.0), nt=16, warmup=4,
            dims=(2, 2),
        ))
        r1, r2 = s.run(variant="hide"), s.run(variant="hide", driver="scan")
        np.testing.assert_array_equal(np.asarray(r1.h), np.asarray(r2.h))
        for u1, u2 in zip(r1.us, r2.us):
            np.testing.assert_array_equal(np.asarray(u1), np.asarray(u2))

    def test_wave_masked_hide_bitwise_equals_perf(self):
        # The mask-as-data select (wave_step_padded_masked) is built to be
        # fp-IDENTICAL to perf's expression on updating cells and to hold
        # edge cells bitwise — so hide == perf exactly, sharded.
        import jax.numpy as jnp

        from rocm_mpi_tpu.models import AcousticWave, WaveConfig

        w = AcousticWave(WaveConfig(
            global_shape=(32, 32), lengths=(10.0, 10.0), nt=16, warmup=0,
            dims=(2, 2),
        ))
        U, Uprev, C2 = w.init_state()
        p, _ = w.advance_fn("perf")(jnp.copy(U), jnp.copy(Uprev), C2, 12)
        h, _ = w.advance_fn("hide")(jnp.copy(U), jnp.copy(Uprev), C2, 12)
        np.testing.assert_array_equal(np.asarray(h), np.asarray(p))

    def test_swe_scan_matches_ap_oracle(self):
        from rocm_mpi_tpu.models import ShallowWater, SWEConfig

        s = ShallowWater(SWEConfig(
            global_shape=(32, 32), lengths=(10.0, 10.0), nt=20, warmup=4,
            dims=(2, 2),
        ))
        r_ap = s.run(variant="ap")
        r = s.run(variant="hide", driver="scan")
        np.testing.assert_allclose(
            np.asarray(r.h), np.asarray(r_ap.h), rtol=1e-12, atol=1e-14
        )

    def test_scan_chunk_serves_both_windows(self):
        # q = gcd(warmup, timed): one compiled program, windows exact.
        m = self._diffusion(nt=24, warmup=6)
        advance, q = m.scan_advance_fn("shard", nt=24, warmup=6)
        assert q == 6
        with pytest.raises(ValueError, match=">= 1"):
            m.scan_advance_fn("shard", nt=24, warmup=6, chunk=0)


class TestExchangeInto:
    def test_place_core_and_exchange_into_compose_to_exchange_halo(self):
        import jax
        import jax.numpy as jnp

        from rocm_mpi_tpu.parallel import (
            exchange_halo,
            exchange_into,
            init_global_grid,
            place_core,
        )
        from rocm_mpi_tpu.utils.compat import shard_map

        grid = init_global_grid(8, 8, dims=(2, 2))
        x = jax.device_put(
            jnp.arange(64.0).reshape(8, 8), grid.sharding
        )

        @jax.jit
        def both(x):
            def local(b):
                direct = exchange_halo(b, grid)
                composed = exchange_into(place_core(b), grid)
                return direct, composed

            return shard_map(
                local, mesh=grid.mesh, in_specs=grid.spec,
                out_specs=(grid.spec, grid.spec),
            )(x)

        direct, composed = both(x)
        np.testing.assert_array_equal(
            np.asarray(direct), np.asarray(composed)
        )

    def test_wide_halo_corners_3d(self):
        # Width-2 ghosts on a 3D mesh: every corner/edge region of the
        # ghost ring must carry the right diagonal-neighbor values —
        # checked against a numpy reconstruction of the global array.
        import jax
        import jax.numpy as jnp

        from rocm_mpi_tpu.parallel import exchange_halo, init_global_grid
        from rocm_mpi_tpu.utils.compat import shard_map

        grid = init_global_grid(8, 8, 4, dims=(2, 2, 1))
        g = np.arange(8 * 8 * 4, dtype=np.float64).reshape(8, 8, 4)
        x = jax.device_put(jnp.asarray(g), grid.sharding)
        w = 2

        @jax.jit
        def padded(x):
            return shard_map(
                lambda b: exchange_halo(b, grid, width=w),
                mesh=grid.mesh, in_specs=grid.spec, out_specs=grid.spec,
            )(x)

        out = np.asarray(padded(x))
        local = grid.local_shape
        pl_shape = tuple(n + 2 * w for n in local)
        for ci in range(2):
            for cj in range(2):
                blk = out[
                    ci * pl_shape[0]:(ci + 1) * pl_shape[0],
                    cj * pl_shape[1]:(cj + 1) * pl_shape[1],
                ]
                # Expected: the global window around this shard, zero
                # where it falls off the domain.
                want = np.zeros(pl_shape)
                for i in range(pl_shape[0]):
                    for j in range(pl_shape[1]):
                        for kk in range(pl_shape[2]):
                            gi = ci * local[0] + i - w
                            gj = cj * local[1] + j - w
                            gk = kk - w
                            if (0 <= gi < 8 and 0 <= gj < 8
                                    and 0 <= gk < 4):
                                want[i, j, kk] = g[gi, gj, gk]
                np.testing.assert_array_equal(blk, want)
