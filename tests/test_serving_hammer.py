"""Seeded two-thread hammer for the serving control plane (the dynamic
complement to graftlint GL10's static racecheck, ISSUE 17).

The invariants pinned here are exactly the ones the static rules
guard: the queue's terminal accounting identity under concurrent
submit/pop/requeue (the lock-guarded counters GL10a infers),
exactly-one-terminal per journaled ticket under a mid-append replay
(the single-writer ledger GL10f owns), and torn-tail tolerance when a
replay races the appender. All schedules are seeded `random.Random`
draws — a failure replays with the same interleaving pressure.
"""

from __future__ import annotations

import random
import threading
import time

from rocm_mpi_tpu.serving.journal import (
    TicketJournal,
    exactly_one_terminal,
    replay,
)
from rocm_mpi_tpu.serving.queue import Request, RequestQueue

N_REQUESTS = 150
HAMMER_DEADLINE_S = 30.0  # stall guard, not a perf target


def test_queue_two_thread_hammer():
    """Producer submits while the consumer pops, requeues (once per
    ticket, bounded), fails a seeded slice, and resolves the rest.
    At drain: the terminal accounting identity holds, every ticket is
    in exactly one terminal state, and the counters reconstruct the
    per-ticket truth."""
    q = RequestQueue()
    tickets: list = []  # producer-appended, read after join
    requeued_once: set[str] = set()  # consumer-thread-local by design
    barrier = threading.Barrier(2)
    errors: list[BaseException] = []

    def producer():
        barrier.wait()
        for i in range(N_REQUESTS):
            tickets.append(q.submit(Request(request_id=f"r{i:04d}")))
            if i % 17 == 0:
                time.sleep(0)  # hand the GIL over: interleave pops

    def consumer():
        rng = random.Random(0x17)
        barrier.wait()
        done = 0
        deadline = time.monotonic() + HAMMER_DEADLINE_S
        while done < N_REQUESTS:
            if time.monotonic() >= deadline:
                raise AssertionError(
                    f"hammer stalled at {done}/{N_REQUESTS} terminals"
                )
            batch = q.pop_pending(max_n=rng.randint(1, 8))
            if not batch:
                time.sleep(0)  # producer still filling
                continue
            park = [
                t for t in batch
                if t.request.request_id not in requeued_once
                and rng.random() < 0.30
            ]
            requeued_once.update(t.request.request_id for t in park)
            if park:
                q.requeue(park)  # preemption: back to the front
            resolved = failed = 0
            for t in batch:
                if t in park:
                    continue
                if rng.random() < 0.10:
                    t._fail("hammer: injected failure")
                    failed += 1
                else:
                    t._resolve({"ok": t.request.request_id})
                    resolved += 1
            if resolved or failed:
                q.note_completed(resolved, failed=failed)
            done += resolved + failed

    def run(fn):
        def wrapped():
            try:
                fn()
            except BaseException as exc:  # surfaced after join
                errors.append(exc)
        th = threading.Thread(target=wrapped, name=fn.__name__)
        th.start()
        return th

    threads = [run(producer), run(consumer)]
    for th in threads:
        th.join(timeout=HAMMER_DEADLINE_S + 5)
        assert not th.is_alive(), f"{th.name} did not finish"
    assert errors == [], errors

    # THE identity: every submitted ticket terminally accounted.
    assert q.check_accounting(in_flight=0) == []
    assert len(tickets) == N_REQUESTS
    states = [t.state for t in tickets]
    assert all(s in ("done", "failed") for s in states), (
        sorted(set(states))
    )
    c = q.counters()
    assert c["submitted"] == N_REQUESTS
    assert c["completed"] == states.count("done")
    assert c["failed"] == states.count("failed")
    assert c["depth"] == 0
    assert c["requeued"] == len(requeued_once)
    assert c["rejected"] == c["expired"] == c["quarantined"] == 0


def test_journal_concurrent_append_and_replay(tmp_path):
    """One writer appends submit/route/terminal triples while a reader
    replays the live segment mid-append. Replay must never raise, the
    observed ticket count is monotone (the ledger only grows), and the
    drained journal balances to exactly one terminal per ticket."""
    path = tmp_path / "ticket-journal.jsonl"
    journal = TicketJournal(path)
    n = 200
    stop = threading.Event()
    barrier = threading.Barrier(2)
    errors: list[BaseException] = []
    observed: list[int] = []

    def writer():
        try:
            barrier.wait()
            for i in range(n):
                rid = f"t{i:04d}"
                journal.record_submit(rid, bin_key="hammer")
                journal.record_route(rid, replica=i % 3)
                journal.record_terminal(
                    rid, "done" if i % 7 else "failed", replica=i % 3
                )
        except BaseException as exc:
            errors.append(exc)
        finally:
            stop.set()

    def reader():
        try:
            barrier.wait()
            while not stop.is_set():
                state = replay([path])  # mid-append: must not raise
                observed.append(len(state.tickets))
                time.sleep(0.001)
        except BaseException as exc:
            errors.append(exc)

    threads = [threading.Thread(target=writer),
               threading.Thread(target=reader)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=HAMMER_DEADLINE_S)
        assert not th.is_alive(), "journal hammer stalled"
    assert errors == [], errors
    journal.close()

    assert observed == sorted(observed), (
        "replay went backwards against an append-only ledger"
    )
    state = replay([path])
    assert len(state.tickets) == n
    assert state.torn_lines == 0  # writer finished: no torn tail left
    assert exactly_one_terminal(state) == []
    counts = state.terminal_counts()
    assert counts.get("failed", 0) == sum(1 for i in range(n) if i % 7 == 0)


def append_torn_tail(path) -> None:
    # The owning append helper for this test's sidecar (GL10f shape):
    # half a record, no newline — a writer killed mid-append.
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"kind": "terminal", "seq": 9')


def test_journal_torn_tail_replay(tmp_path):
    """A half-written tail line (writer killed mid-append) is counted,
    never parsed, and never poisons the completed records around it —
    and a restarted journal resumes over it without raising."""
    path = tmp_path / "ticket-journal.jsonl"
    journal = TicketJournal(path)
    for i in range(5):
        rid = f"t{i}"
        journal.record_submit(rid)
        journal.record_terminal(rid, "done")
    journal.close()
    append_torn_tail(path)

    state = replay([path])
    assert state.torn_lines == 1
    assert len(state.tickets) == 5
    assert exactly_one_terminal(state) == []

    # restart over the torn tail: seq resume replays the same segment
    resumed = TicketJournal(path)
    assert resumed._seq == state.seq_max + 1
    resumed.close()
